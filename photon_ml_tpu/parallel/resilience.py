"""Coordinated failure propagation for the multi-controller runtime.

The reference inherits fault tolerance from Spark (task retry, lineage
recompute — SURVEY/PAPER.md §5.8); the JAX multi-controller runtime has
none of that, and its failure mode is worse than a crash: a process that
raises locally (a bad input block, an OOM, an assertion) simply stops
calling collectives, and every OTHER process blocks inside its next
``psum``/``allgather`` until the transport times out — minutes to forever,
with no indication of which peer died or why. The distributed-training
literature treats hierarchical execution as viable only with explicit
failure handling at the communication boundary (Snap ML, arXiv:1803.06333;
distributed CD, arXiv:1611.02101); this module is that boundary.

Fault model: **fail-stop** — a process either follows the SPMD program or
stops participating (crash, hang, injected fault). No Byzantine behavior:
a live process's status report is trusted. Three mechanisms:

1. **Health barrier** (:func:`health_barrier`): a cheap status-code
   allgather run at phase boundaries (feature summarization, CD sweep
   boundaries, streamed-pass boundaries). Every process reports OK or a
   coarse failure class; any non-OK status converts into a
   :class:`PeerFailure` raised on *every* process, so the job dies
   together — loudly, promptly, resumably — instead of deadlocking.
2. **Guarded phases** (:class:`CollectiveGuard` / :func:`guarded`): the
   with-block form — a local exception inside the guard is reported
   through the barrier (then re-raised wrapped, preserving the cause);
   a peer's failure raises :class:`PeerFailure` before this process can
   enter the next collective. The barrier itself runs under a watchdog:
   a peer that stopped responding entirely (fail-stop without a report)
   surfaces as :class:`WatchdogTimeout` within ``timeout`` seconds.
3. **Bounded retry** (:func:`retry_transient`): coordinator/rendezvous
   setup in ``initialize_multihost`` retries transient failures with
   exponential backoff instead of failing a pod job on one slow peer.

Single-process runs pay nothing: every barrier is a no-op passthrough and
local exceptions propagate unchanged.

The transport is pluggable (thread-local override) so the deterministic
fault-injection harness (``parallel/fault_injection.py`` +
``testing.run_simulated_processes``) can exercise every path above with
simulated processes on one CPU host; production uses the jax
multihost runtime transport.

This module also hosts the unified :class:`ResumeManager` — the
resume-marker lifecycle (atomic write, kept until success, fingerprinted
against inputs) shared by the CLI drivers' device-loss recovery.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Callable, Dict, List, Optional

__all__ = [
    "PeerFailure", "WatchdogTimeout", "ResumeMismatch",
    "health_barrier", "CollectiveGuard", "guarded", "retry_transient",
    "Backoff", "ResumeManager", "current_transport", "use_transport",
    "set_transport", "current_process_index", "default_timeout",
    "collective_site", "current_collective_site",
    "CODE_OK", "CODE_ERROR", "CODE_DEVICE_LOSS", "CODE_DATA",
]

# -- status codes ----------------------------------------------------------
# Coarse failure classes exchanged through the barrier (one int32 per
# process). Classes, not messages: the payload must stay O(bytes) so the
# barrier is cheap enough to run at every phase boundary; the failing
# process logs its own full traceback locally.
CODE_OK = 0
CODE_ERROR = 1        # any local exception
CODE_DEVICE_LOSS = 2  # accelerator backend died (utils.is_device_loss)
CODE_DATA = 3         # data/input error (ValueError family)

_CODE_NAMES = {CODE_OK: "ok", CODE_ERROR: "error",
               CODE_DEVICE_LOSS: "device_loss", CODE_DATA: "data_error"}


def code_for(exc: BaseException) -> int:
    """Map a local exception onto its barrier status class."""
    from photon_ml_tpu.utils import is_device_loss

    if is_device_loss(exc):
        return CODE_DEVICE_LOSS
    if isinstance(exc, ValueError):
        return CODE_DATA
    return CODE_ERROR


class PeerFailure(RuntimeError):
    """Raised on EVERY process when any process of the multi-controller
    job reports failure at a health barrier (or, for the reporting process
    itself, wraps its local exception as ``__cause__``). ``failed`` maps
    process index -> status code of each non-OK peer."""

    def __init__(self, message: str, *, tag: str = "",
                 failed: Optional[Dict[int, int]] = None):
        super().__init__(message)
        self.tag = tag
        self.failed = dict(failed or {})

    @property
    def device_loss(self) -> bool:
        """True when the coordinated abort was caused by an accelerator
        loss somewhere in the job — every process should take the
        resume-marker exit path, not just the one whose device died."""
        return CODE_DEVICE_LOSS in self.failed.values()


class WatchdogTimeout(PeerFailure):
    """A health barrier did not complete within the watchdog timeout: some
    peer stopped participating entirely (fail-stop without a report)."""


class ResumeMismatch(ValueError):
    """A resume marker's input fingerprint does not match the current run's
    inputs; resuming would silently mix datasets/settings."""


def default_timeout() -> float:
    """Watchdog timeout (seconds) for health barriers; generous by default
    (it only bounds how long peers wait on a DEAD process — live peers
    answer in milliseconds). Override with PHOTON_ML_TPU_BARRIER_TIMEOUT_S."""
    return float(os.environ.get("PHOTON_ML_TPU_BARRIER_TIMEOUT_S", 600.0))


# -- transport -------------------------------------------------------------
class JaxTransport:
    """Production transport: the jax multi-controller runtime. The status
    allgather runs in a worker thread so the caller can enforce the
    watchdog timeout even when a dead peer would block the collective
    forever (the thread is abandoned on timeout — under fail-stop the
    whole process exits right after, which is the point)."""

    def process_index(self) -> int:
        import jax

        return jax.process_index()

    def process_count(self) -> int:
        import jax

        return jax.process_count()

    def allgather_status(self, code: int, timeout: float) -> List[int]:
        import numpy as np
        from jax.experimental import multihost_utils

        box: dict = {}

        def run():
            try:
                got = multihost_utils.process_allgather(
                    np.asarray([code], np.int32))
                box["codes"] = [int(c) for c in np.asarray(got).reshape(-1)]
            except BaseException as e:  # surfaced to the caller below
                box["error"] = e

        t = threading.Thread(target=run, daemon=True,
                             name="photon-health-barrier")
        t.start()
        t.join(timeout)
        if t.is_alive():
            raise WatchdogTimeout(
                f"health barrier timed out after {timeout:.0f}s: a peer "
                "process stopped participating (fail-stop without a "
                "report); aborting so this process does not hang in the "
                "next collective")
        if "error" in box:
            raise box["error"]
        return box["codes"]


_default_transport = JaxTransport()
_tls = threading.local()


def current_transport():
    return getattr(_tls, "transport", None) or _default_transport


def current_process_index() -> int:
    """Process index through the ambient transport WITHOUT forcing jax
    backend initialization when no distributed runtime is configured."""
    tp = getattr(_tls, "transport", None)
    if tp is not None:
        return tp.process_index()
    import jax

    return jax.process_index()


def current_collective_site() -> str:
    """The ambient label of the collective being issued on this thread.

    Purely observational: the collective-trace sanitizer
    (``analysis/sanitizers.py``) records it per simulated process so a
    sequence mismatch can name the SITE that diverged, not just a step
    number. Empty when no labeled collective is in flight."""
    return getattr(_tls, "collective_site", "")


@contextlib.contextmanager
def collective_site(tag: str):
    """Thread-locally label the collective(s) issued inside the block
    (the trace hook the barrier and the entity-shard exchange use)."""
    prev = getattr(_tls, "collective_site", "")
    _tls.collective_site = tag
    try:
        yield
    finally:
        _tls.collective_site = prev


@contextlib.contextmanager
def use_transport(transport):
    """Thread-locally override the transport (simulated processes install
    their per-thread endpoint here; production never calls this)."""
    prev = getattr(_tls, "transport", None)
    _tls.transport = transport
    try:
        yield transport
    finally:
        _tls.transport = prev


def set_transport(transport) -> None:
    """Replace this thread's transport IN PLACE — the elastic-recovery
    hook: after a surviving-set rendezvous shrinks the process group,
    the survivor installs its new (remapped-rank) endpoint here so every
    subsequent collective runs over the surviving set. An enclosing
    :func:`use_transport` context still restores its own previous value
    on exit, so the swap never leaks past the simulated process."""
    _tls.transport = transport


# -- health barrier / guarded phases ---------------------------------------
def health_barrier(tag: str, failure: Optional[BaseException] = None,
                   *, timeout: Optional[float] = None) -> None:
    """Exchange health status with every peer before the next collective
    phase. Raises :class:`PeerFailure` on every process when any process
    reports non-OK (the local reporter gets its exception chained as
    ``__cause__``); no-op passthrough in single-process mode (a local
    ``failure`` is re-raised unchanged there)."""
    tp = current_transport()
    if tp.process_count() == 1:
        if failure is not None:
            raise failure
        return
    code = CODE_OK if failure is None else code_for(failure)
    with collective_site(tag):
        codes = tp.allgather_status(code, timeout or default_timeout())
    failed = {i: c for i, c in enumerate(codes) if c != CODE_OK}
    if not failed:
        return
    who = ", ".join(f"process {i} ({_CODE_NAMES.get(c, c)})"
                    for i, c in sorted(failed.items()))
    msg = (f"coordinated abort at '{tag}': {who} failed; every process "
           "raises instead of deadlocking in the next collective")
    if failure is not None:
        raise PeerFailure(msg, tag=tag, failed=failed) from failure
    raise PeerFailure(msg, tag=tag, failed=failed)


class CollectiveGuard:
    """Guard one phase that ends at a collective: convert any process's
    local exception into a :class:`PeerFailure` on every process.

    ::

        with CollectiveGuard("stream.fg"):
            ...local per-process work...
        # all processes healthy here -> safe to enter the collective

    Single-process: zero-cost passthrough (local exceptions propagate
    unchanged). ``PeerFailure`` raised inside the block (a nested guard
    already coordinated) passes through without a second barrier."""

    def __init__(self, tag: str, *, timeout: Optional[float] = None):
        self.tag = tag
        self.timeout = timeout

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        from photon_ml_tpu.parallel.fault_injection import DroppedProcess

        if exc is not None and isinstance(exc, (PeerFailure, DroppedProcess)):
            return False  # already coordinated / simulated silent death
        tp = current_transport()
        if tp.process_count() == 1:
            return False
        health_barrier(self.tag, failure=exc, timeout=self.timeout)
        return False


def guarded(fn: Callable, tag: Optional[str] = None,
            *, timeout: Optional[float] = None) -> Callable:
    """Wrap ``fn`` so every call runs inside a :class:`CollectiveGuard`."""
    import functools

    label = tag or getattr(fn, "__name__", "guarded")

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        with CollectiveGuard(label, timeout=timeout):
            return fn(*args, **kwargs)

    return wrapper


# -- bounded retry ---------------------------------------------------------
class Backoff:
    """Jittered exponential backoff schedule with an optional total
    deadline — the ONE delay policy shared by :func:`retry_transient`,
    the registry watcher's error backoff, the front door's circuit
    breaker, and the chaos harness's respawn supervision (so every
    retry loop in the tree jitters the same way instead of four fixed
    waits stampeding in sync).

    ``next_delay()`` returns ``base_s * factor^k``, clamped to ``max_s``,
    plus a uniform jitter of up to ``jitter`` (a FRACTION of the delay).
    ``reset()`` restarts the schedule (call it on success).
    ``expired()`` reports whether ``deadline_s`` of wall time has passed
    since construction or the last reset — callers stop retrying then."""

    def __init__(self, base_s: float = 0.5, factor: float = 2.0,
                 max_s: float = 60.0, jitter: float = 0.1,
                 deadline_s: Optional[float] = None,
                 rng=None, clock: Callable = time.monotonic):
        if base_s < 0 or factor < 1.0 or jitter < 0:
            raise ValueError(
                f"need base_s >= 0, factor >= 1, jitter >= 0; got "
                f"base_s={base_s}, factor={factor}, jitter={jitter}")
        import random as _random

        self.base_s = float(base_s)
        self.factor = float(factor)
        self.max_s = float(max_s)
        self.jitter = float(jitter)
        self.deadline_s = deadline_s
        self._rng = rng if rng is not None else _random.Random()
        self._clock = clock
        self.attempts = 0
        self._start = clock()

    def next_delay(self) -> float:
        delay = min(self.base_s * (self.factor ** self.attempts), self.max_s)
        self.attempts += 1
        if self.jitter > 0.0:
            delay += self._rng.uniform(0.0, self.jitter * delay)
        return delay

    def reset(self) -> None:
        self.attempts = 0
        self._start = self._clock()

    def expired(self) -> bool:
        if self.deadline_s is None:
            return False
        return (self._clock() - self._start) >= self.deadline_s

    def remaining(self) -> Optional[float]:
        """Seconds left under the deadline (None when unbounded)."""
        if self.deadline_s is None:
            return None
        return max(0.0, self.deadline_s - (self._clock() - self._start))


def retry_transient(fn: Callable, *, attempts: int = 3,
                    backoff_s: float = 0.5, backoff_factor: float = 2.0,
                    jitter: float = 0.0,
                    deadline_s: Optional[float] = None,
                    retriable=(RuntimeError, ConnectionError, OSError),
                    on_retry: Optional[Callable] = None,
                    sleep: Callable = time.sleep,
                    rng=None, clock: Callable = time.monotonic):
    """Call ``fn`` with bounded retry-with-backoff on transient failures
    (coordinator rendezvous races, slow peers). Non-``retriable``
    exceptions propagate immediately; the last attempt's exception
    propagates unchanged so callers see the real error.

    ``jitter`` adds up to that FRACTION of each delay as uniform random
    extra sleep (multi-process rendezvous retries must not re-collide in
    lockstep); ``deadline_s`` caps TOTAL wall time across attempts — the
    next retry is abandoned (and the last error raised) once the deadline
    passes or the upcoming sleep would overrun it. ``jitter=0`` and
    ``deadline_s=None`` reproduce the original fixed schedule exactly."""
    if attempts < 1:
        raise ValueError(f"attempts must be >= 1, got {attempts}")
    backoff = Backoff(base_s=backoff_s, factor=backoff_factor,
                      max_s=float("inf"), jitter=jitter,
                      deadline_s=deadline_s, rng=rng, clock=clock)
    for attempt in range(attempts):
        try:
            return fn()
        except retriable as e:
            if attempt + 1 >= attempts:
                raise
            delay = backoff.next_delay()
            remaining = backoff.remaining()
            if remaining is not None and delay >= remaining:
                raise  # the deadline would pass mid-sleep: escalate now
            if on_retry is not None:
                on_retry(attempt, e)
            sleep(delay)


# -- unified resume/checkpoint marker lifecycle ----------------------------
class ResumeManager:
    """One resume-marker contract for every driver (subsumes the GAME
    driver's ``RESUME.json`` and the GLM driver's ``RESUME_GLM.npz``):

    * **written atomically and durably** — temp file + fsync +
      ``os.replace`` + parent-dir fsync (``io/durable.py``), so a crash
      mid-write can never leave a half-marker that hijacks a rerun, and
      a power loss after "committed" cannot un-commit it;
    * **kept until success** — the marker is consumed only when the
      protected work COMPLETES (``consume()``), so a second failure of
      any kind (OOM, SIGKILL, another device loss) does not silently
      discard resume state;
    * **fingerprinted against inputs** — ``save`` embeds the constructor's
      fingerprint (e.g. training/validation paths + row counts) and
      ``load`` refuses with :class:`ResumeMismatch` when the rerun's
      inputs differ, so restored state never silently mixes datasets.

    Codec by extension: ``.json`` for string payloads, ``.npz`` (numpy,
    pickled payload dict) when the payload carries arrays. Multi-process:
    construct with ``is_lead=False`` on non-lead processes — their
    ``save``/``consume`` become no-ops (every process may ``load``)."""

    _FP_KEY = "__fingerprint__"

    def __init__(self, path: str, fingerprint: Optional[dict] = None,
                 *, is_lead: bool = True):
        self.path = path
        self.fingerprint = fingerprint
        self.is_lead = bool(is_lead)
        self._npz = path.endswith(".npz")

    def exists(self) -> bool:
        return os.path.exists(self.path)

    def save(self, payload: dict) -> None:
        if not self.is_lead:
            return
        record = dict(payload)
        if self.fingerprint is not None:
            record[self._FP_KEY] = self.fingerprint
        tmp = f"{self.path}.tmp-{os.getpid()}"
        if self._npz:
            import numpy as np

            np.savez(tmp, payload=record)
            # np.savez appends .npz to names without it
            tmp = tmp if tmp.endswith(".npz") else tmp + ".npz"
        else:
            with open(tmp, "w") as f:
                json.dump(record, f)
        # durable commit: fsync content + parent dir around the rename — a
        # marker that claims "committed" must survive power loss, not just
        # concurrent readers (io/durable.py)
        from photon_ml_tpu.io.durable import durable_replace

        durable_replace(tmp, self.path)

    def load(self, verify: bool = True) -> Optional[dict]:
        """Marker payload, or None when absent. ``verify=False`` skips the
        fingerprint check (callers that run their own ordering of
        driver-specific checks first call :meth:`verify` afterwards)."""
        if not self.exists():
            return None
        if self._npz:
            import numpy as np

            record = np.load(self.path,
                             allow_pickle=True)["payload"].item()
        else:
            with open(self.path) as f:
                record = json.load(f)
        if verify:
            self.verify(record)
        return record

    def verify(self, record: dict) -> None:
        """Refuse resume when the marker was written against different
        inputs. Markers from before fingerprinting (no embedded
        fingerprint) are accepted."""
        stored = record.get(self._FP_KEY)
        if stored is None or self.fingerprint is None:
            return
        if stored != self.fingerprint:
            diffs = sorted(set(stored) | set(self.fingerprint))
            detail = "; ".join(
                f"{k}: marker={stored.get(k)!r} run={self.fingerprint.get(k)!r}"
                for k in diffs if stored.get(k) != self.fingerprint.get(k))
            raise ResumeMismatch(
                f"{os.path.basename(self.path)} was written for different "
                f"inputs ({detail}); refusing to resume — restored state "
                "would mix datasets. Rerun with the original inputs or "
                f"delete the marker ({self.path})")

    def consume(self) -> None:
        """Remove the marker — call ONLY after the protected work
        completed and its outputs are published."""
        if not self.is_lead:
            return
        with contextlib.suppress(FileNotFoundError):
            os.remove(self.path)
