"""Entity-sharded random-effect tables: owner map + delta-only exchange.

Single-controller GAME training replicates every random-effect
coordinate's full entity table on every process, so entity count — the
dimension the paper's mixed-effect models exist to scale — is bounded by
one host's RAM. This module makes the table a *partitioned* structure:

* **Owner map** (:class:`EntityShardSpec`): every entity id hashes to
  exactly one shard through a process-stable hash (splitmix64 for integer
  ids, FNV-1a 64 over the utf-8 string form otherwise — the same
  stability rationale as ``io.hashing``: Python's ``hash`` is
  per-process randomized and unusable for a cross-process partition).
  Process ``i`` of an ``N``-process job owns shard ``i``: it builds only
  its owned entities' buckets (``game/data.build_random_effect_data``)
  and solves them purely locally (the PR-5 active-set path).

* **Delta-only exchange** (:func:`exchange_score_updates`): the ONLY
  thing the shared fixed-effect residual needs from a random-effect
  coordinate is its per-row score vector, and each row belongs to
  exactly one entity, hence exactly one shard. After a local solve each
  shard publishes just the rows whose score *bitwise changed* since its
  last publish; the allgathered union scatter-overwrites every process's
  copy of the coordinate's global score vector. Coefficients and entity
  tables never cross the wire during training — this is the
  communication-efficient structure of distributed block CD
  (arXiv:1611.02101) with the changed-row set bounding the payload the
  way one-shot/surrogate aggregation bounds it (arXiv:2001.06194). The
  one full-table gather happens at *save points* only
  (:func:`allgather_objects`, used by ``descent._build_model``) so
  checkpoints and the saved model keep the single-file ``io/model_io``
  layout and serving/registry are unchanged.

* **Failure semantics**: every exchange is a collective boundary, so it
  follows the PR-1 contract — a health barrier runs *before* the payload
  gather (a peer that failed since the last barrier surfaces as
  ``PeerFailure`` instead of wedging the gather), the
  ``entity_shard.exchange`` fault-injection site makes the path
  exercisable in tier-1, and the surrounding ``CollectiveGuard`` in
  ``game/descent.py`` coordinates aborts at the sweep boundary.

* **Transport**: the simulated multi-controller harness
  (``testing.run_simulated_processes``) exchanges payload objects
  directly through its rendezvous; the real runtime allgathers the
  pickled payload as uint8 (bit-preserving — no f64→f32 surprise) in
  bounded chunks so one giant message can never monopolize the
  interconnect (the streamed-pass batching convention of
  ``parallel/streaming.py`` applied to the control plane).
"""

from __future__ import annotations

import dataclasses
import os
import pickle
import struct
import time
from typing import List, Optional, Sequence

import numpy as np

from photon_ml_tpu.analysis.sanitizers import deterministic_replay
from photon_ml_tpu.obs import metrics as obs_metrics
from photon_ml_tpu.obs import trace as obs_trace
from photon_ml_tpu.parallel import fault_injection
from photon_ml_tpu.parallel.resilience import (
    collective_site,
    current_transport,
    default_timeout,
    health_barrier,
)

__all__ = [
    "EntityShardSpec", "EntityTableBudgetError", "ShardCommStats",
    "stable_entity_hash", "serving_owner_of", "check_table_budget",
    "exchange_score_updates", "allgather_objects", "allgather_blobs",
]

_U64 = (1 << 64) - 1

# One payload-allgather message is at most this many bytes on the real
# multi-controller transport; longer payloads gather in multiple rounds
# (every process computes the same round count from the gathered lengths,
# so the rounds stay SPMD-aligned). Env-overridable for tuning.
_EXCHANGE_CHUNK_BYTES = int(os.environ.get(
    "PHOTON_SHARD_EXCHANGE_CHUNK_BYTES", 4 << 20))


def stable_entity_hash(entity_ids) -> np.ndarray:
    """uint64 hash per entity id, identical on every process.

    Integer ids mix through a vectorized splitmix64 finalizer (the same
    family as ``game.data.SketchProjection``); any other dtype hashes
    FNV-1a 64 over the utf-8 of ``str(id)`` (``io.hashing.fnv1a_64``).
    The owner map is defined over the *training data's* id dtype — a
    dataset must present each entity column with one consistent dtype
    across processes (it does: every process reads the same files)."""
    ids = np.asarray(entity_ids)
    if ids.dtype.kind in "iu":
        x = ids.astype(np.uint64)
        x = (x + np.uint64(0x9E3779B97F4A7C15)) & np.uint64(_U64)
        x = ((x ^ (x >> np.uint64(30)))
             * np.uint64(0xBF58476D1CE4E5B9)) & np.uint64(_U64)
        x = ((x ^ (x >> np.uint64(27)))
             * np.uint64(0x94D049BB133111EB)) & np.uint64(_U64)
        return x ^ (x >> np.uint64(31))
    from photon_ml_tpu.io.hashing import fnv1a_64

    return np.fromiter(
        (fnv1a_64(str(e).encode("utf-8")) for e in ids.ravel()),
        np.uint64, ids.size).reshape(ids.shape)


def _int_like(entity_id) -> bool:
    """True when a SERVING-side id (JSON string or number) would have
    presented as an integer dtype to the training reader: a python int
    (bools excluded — they are a different training dtype story and a
    malformed id anyway) or a base-10 integer string, within int64 range
    (a wider value cannot live in an int64 training column, so the
    training side would have carried it as a string and hashed FNV)."""
    if isinstance(entity_id, bool):
        return False
    if isinstance(entity_id, (int, np.integer)):
        return -(1 << 63) <= int(entity_id) < (1 << 63)
    if isinstance(entity_id, str):
        s = entity_id
        if s.startswith("-"):
            s = s[1:]
        if not s or not s.isdigit() or len(s) > 19:
            return False
        return -(1 << 63) <= int(entity_id) < (1 << 63)
    return False


def serving_owner_of(entity_ids, num_shards: int,
                     id_kind: str = "auto") -> np.ndarray:
    """int64 owning-shard index per SERVING-side entity id — the same
    map :meth:`EntityShardSpec.owner_of` computes over the training
    data's arrays, re-derived from the wire form (JSON strings/numbers)
    a scoring request carries.

    The dtype edge this guards: :func:`stable_entity_hash` mixes integer
    dtypes through splitmix64 and everything else through FNV-1a 64 over
    ``str(id)``, so ``123`` and ``"123"`` hash DIFFERENTLY. ``id_kind``
    says which dtype the training data presented:

    * ``"int"`` — the id column trained as an integer dtype; string ids
      parse base-10 (a non-numeric id raises, surfacing the config
      error instead of silently forking the owner map);
    * ``"str"`` — the column trained as strings, so ``"123"`` hashes
      FNV even though it looks numeric;
    * ``"auto"`` — decide PER ID: integer-looking ids (see
      :func:`_int_like`) hash as int64, the rest as strings. Per-id,
      not per-batch, so one odd id in a request cannot move every other
      row's owner.
    """
    if id_kind not in ("auto", "int", "str"):
        raise ValueError(f"unknown id_kind {id_kind!r} "
                         "(expected auto|int|str)")
    ids = list(entity_ids)
    n = np.uint64(num_shards)
    out = np.empty(len(ids), np.int64)
    if not ids:
        return out
    if id_kind == "int":
        arr = np.asarray([int(e) for e in ids], np.int64)
        return (stable_entity_hash(arr) % n).astype(np.int64)
    if id_kind == "str":
        arr = np.asarray([str(e) for e in ids])
        return (stable_entity_hash(arr) % n).astype(np.int64)
    mask = np.asarray([_int_like(e) for e in ids], bool)
    if mask.any():
        arr = np.asarray([int(e) for e, m in zip(ids, mask) if m],
                         np.int64)
        out[mask] = (stable_entity_hash(arr) % n).astype(np.int64)
    if not mask.all():
        arr = np.asarray([str(e) for e, m in zip(ids, mask) if not m])
        out[~mask] = (stable_entity_hash(arr) % n).astype(np.int64)
    return out


@dataclasses.dataclass(frozen=True)
class EntityShardSpec:
    """This process's slice of the entity partition: shard
    ``shard_index`` of ``num_shards``. ``num_shards == 1`` is the
    degenerate single-owner map (no exchange runs)."""

    num_shards: int
    shard_index: int

    def __post_init__(self):
        if self.num_shards < 1:
            raise ValueError(f"num_shards must be >= 1, got "
                             f"{self.num_shards}")
        if not 0 <= self.shard_index < self.num_shards:
            raise ValueError(
                f"shard_index must be in [0, {self.num_shards}), got "
                f"{self.shard_index}")

    @property
    def active(self) -> bool:
        return self.num_shards > 1

    def owner_of(self, entity_ids) -> np.ndarray:
        """int64 owning-shard index per entity id."""
        return (stable_entity_hash(entity_ids)
                % np.uint64(self.num_shards)).astype(np.int64)

    def owned_mask(self, entity_ids) -> np.ndarray:
        """Boolean mask of the entities THIS shard owns. The masks across
        all ``num_shards`` shard indices partition any id set exactly."""
        return self.owner_of(entity_ids) == self.shard_index


class EntityTableBudgetError(RuntimeError):
    """A random-effect coordinate's local entity table exceeds the
    configured per-process memory budget."""


def check_table_budget(table_bytes: int, budget_bytes: Optional[int], *,
                       coordinate: str, num_shards: int = 1) -> None:
    """Fail fast (before any sweep runs) when a coordinate's local entity
    table is over the per-process budget, pointing at the fix: shard the
    entities across more processes instead of silently exhausting RAM."""
    if budget_bytes is None or table_bytes <= budget_bytes:
        return
    raise EntityTableBudgetError(
        f"random-effect coordinate '{coordinate}': local entity table is "
        f"{table_bytes} bytes, over the {budget_bytes}-byte per-process "
        f"budget (currently {num_shards} entity shard"
        f"{'s' if num_shards != 1 else ''}); raise --entity-shards / run "
        "more controller processes so each owns a smaller slice")


@dataclasses.dataclass
class ShardCommStats:
    """Cross-shard communication accounting for one training run.

    ``bytes_sent`` is this process's published payload bytes;
    ``bytes_gathered`` sums every shard's payloads per exchange (what
    actually crossed the wire, fleet-wide); ``seconds`` is wall time in
    the exchange (barrier + gather + scatter) — surfaced per sweep as
    ``comm_seconds``/``comm_bytes`` in the CD history, next to the PR-4
    ``solve_seconds``/``eval_seconds`` split."""

    bytes_sent: int = 0
    bytes_gathered: int = 0
    exchanges: int = 0
    seconds: float = 0.0

    def as_dict(self) -> dict:
        return {"bytes_sent": self.bytes_sent,
                "bytes_gathered": self.bytes_gathered,
                "exchanges": self.exchanges,
                "seconds": round(self.seconds, 6)}


# -- transport: bounded blob allgather --------------------------------------
def allgather_blobs(blob: bytes, *, timeout: Optional[float] = None
                    ) -> List[bytes]:
    """Allgather one bytes payload per process, in rank order.

    Single-process: identity. Simulated transport (a thread endpoint with
    ``allgather_payload``): direct object rendezvous. Real runtime:
    uint8 ``process_allgather`` rounds — lengths first, then the padded
    payload in ``_EXCHANGE_CHUNK_BYTES`` batches (uint8 is bit-preserving
    through the gather, unlike f64 without x64)."""
    tp = current_transport()
    p = tp.process_count()
    if p == 1:
        return [bytes(blob)]
    timeout = timeout if timeout is not None else default_timeout()
    gather = getattr(tp, "allgather_payload", None)
    if gather is not None:
        return [bytes(b) for b in gather(bytes(blob), timeout)]
    from jax.experimental import multihost_utils

    local = np.frombuffer(bytes(blob), np.uint8)
    lens = np.asarray(multihost_utils.process_allgather(
        np.asarray([len(local)], np.int64))).reshape(-1)
    max_len = int(lens.max())
    parts: List[List[np.ndarray]] = [[] for _ in range(p)]
    for start in range(0, max_len, _EXCHANGE_CHUNK_BYTES):
        stop = min(start + _EXCHANGE_CHUNK_BYTES, max_len)
        seg = np.zeros(stop - start, np.uint8)
        have = local[start:stop]
        seg[: len(have)] = have
        got = np.asarray(multihost_utils.process_allgather(seg))
        for i in range(p):
            parts[i].append(got[i])
    return [
        (np.concatenate(parts[i])[: int(lens[i])].tobytes()
         if parts[i] else b"")
        for i in range(p)
    ]


def _pack_arrays(arrays: Sequence[np.ndarray]) -> bytes:
    """Length-prefixed header (dtypes + shapes) followed by the raw
    buffers — a fixed, version-free wire form for the score exchange."""
    head = []
    bufs = []
    for a in arrays:
        a = np.ascontiguousarray(a)
        head.append((a.dtype.str, a.shape))
        bufs.append(a.tobytes())
    hdr = pickle.dumps(head, protocol=pickle.HIGHEST_PROTOCOL)
    return struct.pack("<I", len(hdr)) + hdr + b"".join(bufs)


def _unpack_arrays(blob: bytes) -> List[np.ndarray]:
    (hlen,) = struct.unpack_from("<I", blob, 0)
    head = pickle.loads(blob[4:4 + hlen])
    out = []
    off = 4 + hlen
    for dtype_str, shape in head:
        dt = np.dtype(dtype_str)
        n = int(np.prod(shape, dtype=np.int64)) if shape else 1
        out.append(np.frombuffer(blob, dt, count=n, offset=off)
                   .reshape(shape))
        off += n * dt.itemsize
    return out


def _guarded_gather(blob: bytes, *, tag: str,
                    stats: Optional[ShardCommStats],
                    timeout: Optional[float]) -> List[bytes]:
    """The shared collective body: fault site, pre-gather health barrier
    (a peer that failed before this boundary aborts everyone instead of
    wedging the payload gather), then the blob allgather — with the
    bytes/seconds accounting."""
    t0 = time.perf_counter()
    fault_injection.check("entity_shard.exchange")
    tp = current_transport()
    if tp.process_count() > 1:
        with obs_trace.span("exchange.barrier", cat="collective",
                            site=f"barrier:{tag}"):
            health_barrier(f"entity_shard.exchange:{tag}", timeout=timeout)
    with obs_trace.span("exchange.allgather", cat="collective",
                        site=tag, bytes_sent=len(blob)):
        with collective_site(tag):  # trace label for the sanitizer
            blobs = allgather_blobs(blob, timeout=timeout)
    if stats is not None:
        stats.exchanges += 1
        stats.bytes_sent += len(blob)
        stats.bytes_gathered += sum(len(b) for b in blobs)
        stats.seconds += time.perf_counter() - t0
        obs_metrics.training_metrics().record_exchange(
            len(blob), sum(len(b) for b in blobs),
            time.perf_counter() - t0)
    return blobs


def exchange_score_updates(arrays: Sequence[np.ndarray], *, tag: str,
                           stats: Optional[ShardCommStats] = None,
                           timeout: Optional[float] = None
                           ) -> List[List[np.ndarray]]:
    """Allgather one batch of changed-row score updates (any fixed tuple
    of numpy arrays — the CD loop sends ``(rows, vals, val_rows,
    val_vals)``). Returns every shard's arrays, rank-ordered. Row sets
    are disjoint across shards (one owner per entity), so callers can
    scatter them in any order and land on the bit-identical global
    vector the single-host loop would have computed."""
    # pack and reassembly are pure and parity-bearing, so they carry
    # replay hooks (no-ops outside an armed DeterminismSanitizer); the
    # gather between them must NOT be replayed — a re-issued collective
    # would corrupt the trace alignment
    blob = deterministic_replay(
        f"entity_shard.pack:{tag}", _pack_arrays, arrays)
    blobs = _guarded_gather(blob, tag=tag, stats=stats, timeout=timeout)
    return deterministic_replay(
        f"entity_shard.unpack:{tag}",
        lambda: [_unpack_arrays(b) for b in blobs])


def allgather_objects(obj, *, tag: str,
                      stats: Optional[ShardCommStats] = None,
                      timeout: Optional[float] = None) -> list:
    """Allgather one picklable object per process, rank-ordered — the
    save-point full-table gather (``descent._build_model`` merges every
    shard's buckets through this so the saved model keeps the
    single-file layout). This is deliberately NOT used per sweep; the
    whole point of the delta exchange is that coefficients cross the
    wire only here."""
    blobs = _guarded_gather(
        pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL),
        tag=tag, stats=stats, timeout=timeout)
    return [pickle.loads(b) for b in blobs]
