"""Device mesh + batch sharding helpers.

The communication layer of the rebuild (SURVEY.md §5.8; reference mount
empty): where the reference uses Spark primitives — ``treeAggregate`` for
gradient reductions, torrent ``broadcast`` for coefficients, shuffles for
entity grouping — this framework uses a ``jax.sharding.Mesh`` with XLA
collectives over ICI/DCN: ``psum`` replaces ``treeAggregate``, replicated
shardings replace broadcast, and device_put with entity-sharded layouts
replaces the shuffle.

Mesh axes used across the framework:
  * ``data``   — examples (fixed-effect data parallelism)
  * ``entity`` — random-effect entities (the reference's entity partitioning)
Both can coexist in one mesh for a full GAME step.
"""

from __future__ import annotations

import math
from typing import Mapping

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from photon_ml_tpu.types import LabeledBatch, SparseFeatures


def make_mesh(axis_sizes: Mapping[str, int] | None = None, devices=None) -> Mesh:
    """Build a mesh, e.g. make_mesh({"data": 8}) or {"data": 4, "entity": 2}.

    With no arguments, uses all local devices on a single "data" axis.
    """
    if devices is None:
        devices = jax.devices()
    if axis_sizes is None:
        axis_sizes = {"data": len(devices)}
    names = tuple(axis_sizes)
    sizes = tuple(axis_sizes[n] for n in names)
    for name, size in zip(names, sizes):
        if size < 1:
            raise ValueError(f"mesh axis '{name}' must be >= 1, got {size}")
    total = math.prod(sizes)
    if total > len(devices):
        raise ValueError(
            f"mesh axes {dict(zip(names, sizes))} need {total} devices, "
            f"have {len(devices)} — shrink an axis or pass more devices")
    dev_array = np.asarray(devices[:total]).reshape(sizes)
    return Mesh(dev_array, names)


def pad_batch(batch: LabeledBatch, multiple: int) -> LabeledBatch:
    """Pad rows to a multiple of ``multiple`` with weight-0 rows, which are
    exact no-ops under the sum-semantics objective."""
    n = batch.num_examples
    target = -(-n // multiple) * multiple
    pad = target - n
    if pad == 0:
        return batch
    pad0 = lambda a: jnp.concatenate([a, jnp.zeros((pad,) + a.shape[1:], a.dtype)], 0)
    if isinstance(batch.features, SparseFeatures):
        # implicit-ones (values=None) rows stay value-free: padding rows'
        # implicit 1.0 slots are neutralized by their weight-0 rows (every
        # loss/gradient term is weight- or d1-multiplied)
        feats = SparseFeatures(
            indices=pad0(batch.features.indices),
            values=(None if batch.features.values is None
                    else pad0(batch.features.values)),
            dim=batch.features.dim,
        )
    else:
        feats = pad0(batch.features)
    # padded labels of 1.0 keep poisson/logistic losses finite at any margin
    labels = jnp.concatenate([batch.labels, jnp.ones((pad,), batch.labels.dtype)], 0)
    return LabeledBatch(feats, labels, pad0(batch.offsets), pad0(batch.weights))


def shard_batch(batch: LabeledBatch, mesh: Mesh, axis: str = "data") -> LabeledBatch:
    """Pad rows to the axis size and lay the batch out shard-by-row on the
    mesh (the device boundary the reference crosses by partitioning RDDs —
    SURVEY.md §4.1)."""
    batch = pad_batch(batch, mesh.shape[axis])
    sharding = NamedSharding(mesh, P(axis))
    return jax.tree.map(lambda a: jax.device_put(a, sharding), batch)


def replicate(x, mesh: Mesh):
    return jax.device_put(x, NamedSharding(mesh, P()))
