"""Deterministic fault injection for the multi-controller runtime.

Every failure path the resilience layer promises to handle must be
EXERCISABLE in tier-1 tests — otherwise the coordinated-abort machinery is
dead code until the first real pod outage. This module plants named
injection sites in the hot paths (stream-source block decode, streamed
pass boundaries, CD steps, multihost init) and lets a test arm a
:class:`FaultPlan` that fires per-process, per-occurrence faults:

* ``kind="raise"`` — a local exception (:class:`InjectedFault`) at the
  site, exactly like a data/compute error in that process;
* ``kind="device_loss"`` — an exception ``utils.is_device_loss``
  recognizes, driving the drivers' resume-marker/exit-75 path without a
  real TPU crash;
* ``kind="truncate"`` — corrupt the bytes at a decode site
  (:func:`mangle_payload`), driving the REAL truncated-block error path;
* ``kind="drop"`` — simulated fail-stop-silent: raises
  :class:`DroppedProcess` (a ``BaseException``), which the simulated
  runner (``testing.run_simulated_processes``) treats as the process
  going dark — it never reaches another health barrier, so peers must
  surface :class:`~.resilience.WatchdogTimeout` within the watchdog;
* ``kind="delay"`` — a latency fault: the site sleeps ``delay_s``
  before continuing, driving the serving tier's deadline/degrade
  machinery (a slow coefficient store or a wedged backend) without
  raising. Sites on an event loop use :func:`async_check`, which awaits
  the delay instead of blocking the loop.

Determinism: faults address a (site, process, occurrence) triple.
``at=-1`` fires at EVERY occurrence (the chaos-storm form: "100% of
store loads are slow/failing").
Occurrence counters are per-thread (each simulated process counts its own
visits) and reset when a new plan is installed. Real multi-process runs
can arm a plan through the ``PHOTON_ML_TPU_FAULTS`` env var (JSON list of
fault dicts) so spawned worker processes inject without code changes.

Zero overhead when disarmed: every site is a single truthiness check of a
module global.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
from typing import List, Optional, Sequence

__all__ = ["Fault", "InjectedFault", "DroppedProcess", "install", "clear",
           "installed", "check", "async_check", "mangle_payload",
           "process_context", "crash_schedule"]


class InjectedFault(RuntimeError):
    """The generic injected local failure."""


class DroppedProcess(BaseException):
    """Simulated silent process death (fail-stop without a report). A
    ``BaseException`` so generic ``except Exception`` recovery — including
    :class:`~.resilience.CollectiveGuard` — cannot convert it into a
    reported failure: the whole point is that this process never reports."""


@dataclasses.dataclass(frozen=True)
class Fault:
    """One armed fault: fire at the ``at``-th visit (0-based, per process)
    of ``site`` by process ``process`` (None = every process). ``at=-1``
    fires at every visit. ``delay_s`` is the sleep for ``kind="delay"``."""

    site: str
    kind: str = "raise"  # raise | device_loss | truncate | drop | delay
    process: Optional[int] = None
    at: int = 0
    message: str = "injected fault"
    delay_s: float = 0.0

    def __post_init__(self):
        if self.kind not in ("raise", "device_loss", "truncate", "drop",
                             "delay"):
            raise ValueError(f"unknown fault kind {self.kind!r}")


_lock = threading.Lock()
_plan: List[Fault] = []
_armed = False  # fast-path gate: sites check this single global
_tls = threading.local()


def _counters() -> dict:
    c = getattr(_tls, "counters", None)
    if c is None or getattr(_tls, "generation", -1) != _generation:
        c = {}
        _tls.counters = c
        _tls.generation = _generation
    return c


_generation = 0


def install(faults: Sequence[Fault]) -> None:
    """Arm a plan (replacing any previous one; all occurrence counters
    reset). Tests normally use this through a fixture/finally with
    :func:`clear`."""
    global _plan, _armed, _generation
    with _lock:
        _plan = [f if isinstance(f, Fault) else Fault(**f) for f in faults]
        _generation += 1
        _armed = bool(_plan)


def clear() -> None:
    install(())


def installed() -> List[Fault]:
    return list(_plan)


def _env_plan_loaded() -> None:
    """One-shot: arm from PHOTON_ML_TPU_FAULTS (JSON list of fault dicts)
    so real spawned worker processes can inject."""
    global _env_checked
    if _env_checked:
        return
    _env_checked = True
    raw = os.environ.get("PHOTON_ML_TPU_FAULTS")
    if raw:
        install([Fault(**d) for d in json.loads(raw)])


_env_checked = False


def process_context(index: int):
    """Thread-local process-index override for fault matching — simulated
    processes (threads) and worker threads acting on behalf of a process
    (the stream source's producer) set this; real runs resolve the index
    through the resilience transport."""
    import contextlib

    @contextlib.contextmanager
    def cm():
        prev = getattr(_tls, "process_index", None)
        _tls.process_index = index
        try:
            yield
        finally:
            _tls.process_index = prev

    return cm()


def _current_process() -> int:
    idx = getattr(_tls, "process_index", None)
    if idx is not None:
        return idx
    from photon_ml_tpu.parallel.resilience import current_process_index

    try:
        return current_process_index()
    except Exception:
        return 0


def _match(site: str, kinds: Sequence[str]) -> Optional[Fault]:
    n = _counters().setdefault(site, 0)
    _counters()[site] = n + 1
    proc = _current_process()
    for f in _plan:
        if (f.site == site and f.kind in kinds and (f.at == n or f.at < 0)
                and (f.process is None or f.process == proc)):
            return f
    return None


def _fire(site: str, f: Fault) -> None:
    """Raise the exception a matched control-flow fault calls for (shared
    by the sync and async injection points)."""
    if f.kind == "drop":
        raise DroppedProcess(f"{site}: {f.message}")
    if f.kind == "device_loss":
        import jax

        raise jax.errors.JaxRuntimeError(
            f"UNAVAILABLE: {f.message} (injected device loss at {site})")
    raise InjectedFault(f"{site}: {f.message}")


def check(site: str) -> None:
    """Injection point for control-flow faults. No-op unless a plan is
    armed; otherwise fires any (site, process, occurrence)-matching fault.
    A matched ``kind="delay"`` fault sleeps ``delay_s`` and returns —
    callers on an event loop must use :func:`async_check` instead."""
    _env_plan_loaded()
    if not _armed:
        return
    f = _match(site, ("raise", "device_loss", "drop", "delay"))
    if f is None:
        return
    if f.kind == "delay":
        import time

        time.sleep(f.delay_s)
        return
    _fire(site, f)


async def async_check(site: str) -> None:
    """Event-loop-safe injection point: identical matching to
    :func:`check`, but a ``kind="delay"`` fault is awaited via
    ``asyncio.sleep`` so an armed latency fault never blocks the loop
    (the front door's proxy path runs here)."""
    _env_plan_loaded()
    if not _armed:
        return
    f = _match(site, ("raise", "device_loss", "drop", "delay"))
    if f is None:
        return
    if f.kind == "delay":
        import asyncio

        await asyncio.sleep(f.delay_s)
        return
    _fire(site, f)


def crash_schedule(*kills, kind: str = "drop") -> List[Fault]:
    """Build a crash schedule: each ``(rank, site, occurrence)`` triple
    kills process ``rank`` at its ``occurrence``-th visit (0-based) of
    fault site ``site``. ``kind`` selects how it dies: ``"drop"`` (silent
    fail-stop — the recovery harness's shrink path), ``"raise"`` (a
    reported local failure — the rollback path) or ``"device_loss"``
    (the drivers' resume-marker/exit-75 path). Sites include the
    mid-collective ``transport.allgather`` point inside the simulated
    transport itself, so a rank can die INSIDE a rendezvous, not only
    between collectives. Feed the result to :func:`install` (or merge
    with other faults first)::

        fault_injection.install(fault_injection.crash_schedule(
            (2, "cd.step", 5),                   # rank 2, 6th CD step
            (1, "transport.allgather", 3),       # rank 1, mid-collective
        ))
    """
    plan = []
    for rank, site, occurrence in kills:
        plan.append(Fault(site=site, kind=kind, process=int(rank),
                          at=int(occurrence),
                          message=f"scheduled crash of rank {rank}"))
    return plan


def mangle_payload(site: str, payload: bytes) -> bytes:
    """Injection point for data-corruption faults: a matching
    ``kind="truncate"`` fault halves the payload, driving the caller's
    genuine truncated-read error path. Identity unless armed."""
    _env_plan_loaded()
    if not _armed:
        return payload
    f = _match(site, ("truncate",))
    if f is None:
        return payload
    return payload[: len(payload) // 2]
