"""In-job rollback-recovery: coordinated failure becomes coordinated recovery.

PR 1 made failure *coordinated*: every process of a multi-controller job
raises :class:`~photon_ml_tpu.parallel.resilience.PeerFailure` together at
the same collective round. This module adds the other half — coordinated
RECOVERY — so a transient fault or a lost rank costs one rolled-back sweep
instead of the whole multi-hour fit (the explicit failure handling that
distributed block-CD solvers assume — arXiv:1611.02101, Snap ML
arXiv:1803.06333 — and that Spark gave the reference for free).

Three layers:

* **Classification** (:func:`classify_failure`): a coordinated abort is
  ``ROLLBACK`` (some rank reported a generic local error — under
  fail-stop, every rank is still alive and can retry together),
  ``RANK_LOSS`` (a watchdog fired: some rank stopped participating and
  will never return), or ``FATAL`` (device loss — the drivers' existing
  resume-marker/exit-75 whole-job restart path — or a deterministic data
  error that would recur on every retry).

* **Commit protocol** (:meth:`RecoveryManager.commit`): each rank writes
  a sweep-stamped shard snapshot through
  :class:`~photon_ml_tpu.parallel.resilience.ResumeManager` (fingerprint
  discipline + durable rename), *then* passes a health barrier, *then*
  advances its local committed pointer and prunes older files. Barrier
  passage is all-or-nothing among live ranks, so every survivor of a
  later failure agrees on the last committed sweep — and because each
  rank's write *precedes* its barrier deposit, every member's file for
  that sweep (including a rank that died later) exists on disk. Each
  snapshot records the membership it was committed under, so a recovery
  knows exactly whose files compose the full table.

* **Recovery** (:meth:`RecoveryManager.on_failure`): ``ROLLBACK`` sleeps
  a jittered backoff, re-aligns on a recovery barrier, and agrees on the
  rollback sweep via a payload gather — this works on ANY transport,
  including the production jax runtime. ``RANK_LOSS`` additionally needs
  the transport to *shrink*: survivors rendezvous through
  ``transport.recover`` (only the simulated thread transport supports
  this — a production jax job cannot resize; there the loss escalates to
  the existing whole-job restart), install the shrunk endpoint via
  :func:`~photon_ml_tpu.parallel.resilience.set_transport`, and the
  caller (``game/descent.py``) recomputes the
  :class:`~photon_ml_tpu.parallel.entity_shard.EntityShardSpec` owner map
  over the survivors and redistributes the dead rank's entities from the
  agreed snapshot. Budgets (``max_rank_failures`` / ``max_rollbacks``)
  bound the loop; every decision is a deterministic function of state
  that advances identically on every rank, so ranks never split-brain on
  whether to recover.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import pickle
import time
from typing import Callable, Dict, List, Optional

import numpy as np

from photon_ml_tpu.parallel import fault_injection, resilience

__all__ = [
    "FATAL", "ROLLBACK", "RANK_LOSS", "classify_failure",
    "recovery_supported", "RecoveryPlan", "RecoveryManager",
    "retry_collective",
]

_log = logging.getLogger(__name__)

# failure classes (strings so they read well in logs and BENCH json)
ROLLBACK = "rollback"    # all ranks alive: back off, roll back, retry
RANK_LOSS = "rank_loss"  # some rank is gone: shrink + redistribute
FATAL = "fatal"          # device loss / deterministic data error: abort


def classify_failure(exc: BaseException) -> str:
    """Map a coordinated-abort exception onto its recovery class.

    * :class:`~.resilience.WatchdogTimeout` — a peer stopped
      participating entirely; under fail-stop it will never return:
      ``RANK_LOSS``.
    * Any other :class:`~.resilience.PeerFailure` came through a
      COMPLETED status round, so every rank is alive and aligned:
      ``FATAL`` when the cause was a device loss (the whole job must
      take the resume-marker path) or a data error (deterministic — a
      retry re-reads the same bad input), else ``ROLLBACK``.
    * Anything else is not a coordinated abort: ``FATAL``.
    """
    if isinstance(exc, resilience.WatchdogTimeout):
        return RANK_LOSS
    if isinstance(exc, resilience.PeerFailure):
        if exc.device_loss:
            return FATAL
        if resilience.CODE_DATA in exc.failed.values():
            return FATAL
        return ROLLBACK
    return FATAL


def recovery_supported(transport=None) -> bool:
    """Whether ELASTIC (surviving-set) recovery is available on the
    ambient transport: trivially true single-process (no peer can fail),
    true on transports exposing ``recover`` (the simulated thread
    transport), false on the production jax runtime — which still gets
    ROLLBACK-class in-job retry, but escalates rank loss to the
    whole-job resume path."""
    tp = transport if transport is not None else resilience.current_transport()
    try:
        if tp.process_count() <= 1:
            return True
    except Exception:
        return True
    return hasattr(tp, "recover")


@dataclasses.dataclass(frozen=True)
class RecoveryPlan:
    """What the caller needs to roll back and resume: the agreed sweep,
    every committed member's snapshot at that sweep, the membership the
    snapshot was committed under (``old_members``, ordered — old shard
    index ``i`` belonged to original rank ``old_members[i]``), and the
    surviving membership (``members``, same ordering rule for the new
    owner map)."""

    sweep: int
    snapshots: Dict[int, dict]
    old_members: List[int]
    members: List[int]
    own_rank: int
    failure_class: str

    @property
    def remapped(self) -> bool:
        return self.members != self.old_members

    @property
    def new_shard_index(self) -> int:
        return self.members.index(self.own_rank)

    @property
    def new_num_shards(self) -> int:
        return len(self.members)


class RecoveryManager:
    """Per-rank recovery state machine for one training run.

    Constructed once per driver invocation (``--max-rank-failures`` > 0
    enables it) and handed to :class:`~photon_ml_tpu.game.descent.
    CoordinateDescent`; the descent loop calls :meth:`commit` at each
    snapshot sweep and :meth:`on_failure` from its ``PeerFailure``
    handler. All counters advance identically on every rank (commit and
    recovery are collective), so budget decisions can never split-brain.

    ``snapshot_every`` trades snapshot cost against replay: a failure
    rolls back to the last committed sweep, re-running at most
    ``snapshot_every`` sweeps. ``max_rank_failures`` bounds cumulative
    lost ranks; ``max_rollbacks`` (default ``2 * max_rank_failures + 2``)
    bounds ROLLBACK-class retries; ``deadline_s`` caps total wall time
    spent backing off across recoveries."""

    def __init__(self, directory: str, fingerprint: Optional[dict] = None,
                 *, max_rank_failures: int = 1, snapshot_every: int = 1,
                 max_rollbacks: Optional[int] = None,
                 backoff_s: float = 0.05, backoff_factor: float = 2.0,
                 jitter: float = 0.1, deadline_s: Optional[float] = None,
                 barrier_timeout: Optional[float] = None,
                 sleep: Callable = time.sleep):
        if max_rank_failures < 0:
            raise ValueError(f"max_rank_failures must be >= 0, got "
                             f"{max_rank_failures}")
        if snapshot_every < 1:
            raise ValueError(f"snapshot_every must be >= 1, got "
                             f"{snapshot_every}")
        self.directory = directory
        self.fingerprint = fingerprint
        self.max_rank_failures = int(max_rank_failures)
        self.snapshot_every = int(snapshot_every)
        self.max_rollbacks = (2 * self.max_rank_failures + 2
                              if max_rollbacks is None else int(max_rollbacks))
        self.barrier_timeout = barrier_timeout
        self._sleep = sleep
        self._backoff = resilience.Backoff(
            base_s=backoff_s, factor=backoff_factor, jitter=jitter,
            deadline_s=deadline_s)
        # bound lazily to the transport of the thread that runs the fit
        # (simulated processes construct one manager per thread)
        self._bound = False
        self.rank: Optional[int] = None
        self._members: List[int] = []
        self._last_committed: Optional[int] = None
        self.epoch = 0
        self.rank_failures = 0
        self.rollbacks = 0
        self._recovery_t0: Optional[float] = None
        self.stats: Dict[str, float] = {
            "recoveries": 0, "rank_failures": 0, "rollbacks": 0,
            "snapshots": 0, "snapshot_seconds": 0.0,
            "recovery_seconds": 0.0,
        }

    # -- wiring ----------------------------------------------------------
    def _bind(self, tp) -> None:
        if self._bound:
            return
        self._bound = True
        self.rank = tp.process_index()
        self._members = list(range(tp.process_count()))

    def enabled(self) -> bool:
        """Recovery only has work to do in multi-process runs (a single
        process never sees PeerFailure)."""
        tp = resilience.current_transport()
        return tp.process_count() > 1

    def reset_for_run(self) -> None:
        """Start a fresh fit (a new grid point): stale snapshots from a
        previous run must never be rolled back into. Cumulative budgets
        and stats survive — they bound the whole job, not one fit."""
        self._last_committed = None
        if self.rank is not None:
            self._prune(keep_sweep=None)

    def _path(self, rank: int, sweep: int) -> str:
        return os.path.join(self.directory,
                            f"shard-r{rank}-s{sweep}.snap.npz")

    def _manager(self, rank: int, sweep: int) -> resilience.ResumeManager:
        return resilience.ResumeManager(self._path(rank, sweep),
                                        fingerprint=self.fingerprint,
                                        is_lead=True)

    def _prune(self, keep_sweep: Optional[int]) -> None:
        """Delete this rank's OWN snapshot files other than ``keep_sweep``
        (each rank prunes only its own files, so a dead rank's last
        committed snapshot stays on disk for the survivors to merge)."""
        if not os.path.isdir(self.directory):
            return
        prefix = f"shard-r{self.rank}-s"
        keep = (None if keep_sweep is None
                else os.path.basename(self._path(self.rank, keep_sweep)))
        for name in sorted(os.listdir(self.directory)):
            if name.startswith(prefix) and name != keep:
                try:
                    os.remove(os.path.join(self.directory, name))
                except OSError:
                    pass

    # -- commit protocol -------------------------------------------------
    def commit(self, sweep: int, build_payload: Callable[[], dict],
               *, force: bool = False) -> bool:
        """Commit a sweep-start snapshot: write this rank's file (durable
        rename through ResumeManager), pass the commit barrier, advance
        the committed pointer, prune older own files. ``build_payload``
        is only called when this sweep actually commits (it copies
        arrays). Returns True when a commit happened."""
        tp = resilience.current_transport()
        if tp.process_count() == 1:
            return False
        self._bind(tp)
        if not force and sweep % self.snapshot_every != 0:
            return False
        if not force and self._last_committed == sweep:
            return False
        t0 = time.perf_counter()
        fault_injection.check("recovery.commit")
        record = dict(build_payload())
        record["sweep"] = int(sweep)
        record["members"] = list(self._members)
        os.makedirs(self.directory, exist_ok=True)
        self._manager(self.rank, sweep).save(record)
        resilience.health_barrier(f"recovery.commit:{sweep}",
                                  timeout=self.barrier_timeout)
        self._last_committed = int(sweep)
        self._prune(keep_sweep=sweep)
        self.stats["snapshots"] += 1
        self.stats["snapshot_seconds"] += time.perf_counter() - t0
        if force and self._recovery_t0 is not None:
            # the post-restore commit closes the recovery window
            self.stats["recovery_seconds"] += (time.perf_counter()
                                               - self._recovery_t0)
            self._recovery_t0 = None
        return True

    # -- recovery --------------------------------------------------------
    def on_failure(self, exc: BaseException) -> RecoveryPlan:
        """Decide and run the collective half of recovery. Returns a
        :class:`RecoveryPlan` for the caller to restore from, or
        re-raises ``exc`` when the failure is fatal, budgets are
        exhausted, nothing was ever committed, or the transport cannot
        shrink. Every branch below depends only on state that advances
        identically on every rank."""
        cls = classify_failure(exc)
        tp = resilience.current_transport()
        if cls == FATAL or tp.process_count() == 1:
            raise exc
        self._bind(tp)
        if self._last_committed is None:
            raise exc
        if self._backoff.expired():
            _log.error("recovery: backoff deadline exhausted; escalating")
            raise exc
        self._recovery_t0 = time.perf_counter()
        self.epoch += 1
        if cls == ROLLBACK:
            if self.rollbacks >= self.max_rollbacks:
                _log.error("recovery: rollback budget (%d) exhausted; "
                           "escalating", self.max_rollbacks)
                raise exc
            self.rollbacks += 1
            self.stats["rollbacks"] += 1
            self._sleep(self._backoff.next_delay())
            payloads = self._gather(f"recovery.rollback:{self.epoch}")
            survivors = list(self._members)
        else:  # RANK_LOSS
            recover = getattr(tp, "recover", None)
            if recover is None:
                _log.error(
                    "recovery: transport cannot shrink (production jax "
                    "runtime); escalating rank loss to the whole-job "
                    "resume path")
                raise exc
            if self.rank_failures >= self.max_rank_failures:
                _log.error("recovery: rank-failure budget (%d) exhausted; "
                           "escalating", self.max_rank_failures)
                raise exc
            timeout = (self.barrier_timeout
                       if self.barrier_timeout is not None
                       else resilience.default_timeout())
            self._sleep(self._backoff.next_delay())
            cur_ranks, payloads, new_tp = recover(
                {"rank": self.rank, "committed": self._last_committed},
                timeout)
            # recover() speaks CURRENT-transport ranks; membership is
            # tracked in ORIGINAL ranks across successive shrinks
            survivors = [self._members[i] for i in cur_ranks]
            lost = len(self._members) - len(survivors)
            self.rank_failures += lost
            self.stats["rank_failures"] += lost
            if lost == 0:
                # every "lost" rank turned out alive (a stalled peer hit
                # the watchdog): same membership on a fresh group —
                # account it against the rollback budget instead
                self.rollbacks += 1
                self.stats["rollbacks"] += 1
                if self.rollbacks > self.max_rollbacks:
                    raise exc
            elif self.rank_failures > self.max_rank_failures:
                _log.error(
                    "recovery: lost %d rank(s), cumulative %d > budget %d; "
                    "escalating", lost, self.rank_failures,
                    self.max_rank_failures)
                raise exc
            resilience.set_transport(new_tp)
            self._members = survivors
        agreed = min(int(p["committed"]) for p in payloads)
        own = self._manager(self.rank, agreed).load()
        if own is None:
            raise exc
        old_members = [int(m) for m in own["members"]]
        snapshots = {r: (own if r == self.rank
                         else self._manager(r, agreed).load())
                     for r in old_members}
        self.stats["recoveries"] += 1
        _log.warning(
            "recovery: %s at sweep pointer %d — %d survivor(s) of %s, "
            "rolling back to committed sweep %d",
            cls, self._last_committed, len(survivors), old_members, agreed)
        self._last_committed = agreed
        return RecoveryPlan(sweep=agreed, snapshots=snapshots,
                            old_members=old_members, members=survivors,
                            own_rank=self.rank, failure_class=cls)

    def _gather(self, tag: str) -> List[dict]:
        """Align every (live) member on a recovery barrier and exchange
        committed pointers — works on any transport (the production
        runtime gathers pickled blobs)."""
        from photon_ml_tpu.parallel.entity_shard import allgather_blobs

        resilience.health_barrier(tag, timeout=self.barrier_timeout)
        with resilience.collective_site(tag):
            blobs = allgather_blobs(
                pickle.dumps({"rank": self.rank,
                              "committed": self._last_committed}),
                timeout=self.barrier_timeout)
        return [pickle.loads(b) for b in blobs]

    def as_dict(self) -> dict:
        out = dict(self.stats)
        out["last_committed"] = self._last_committed
        out["members"] = list(self._members)
        out["max_rank_failures"] = self.max_rank_failures
        out["snapshot_every"] = self.snapshot_every
        return out


def retry_collective(fn: Callable, *, max_retries: int = 1,
                     backoff_s: float = 0.05, backoff_factor: float = 2.0,
                     jitter: float = 0.1, deadline_s: Optional[float] = None,
                     tag: str = "recovery.retry",
                     sleep: Callable = time.sleep):
    """Collectively-aligned bounded retry of a guarded collective phase
    (the GLM driver wraps each lambda's distributed fit in this): a
    ROLLBACK-class :class:`~.resilience.PeerFailure` sleeps a jittered
    backoff, re-aligns every rank on a health barrier, and re-runs
    ``fn``. Rank loss, device loss, data errors, budget exhaustion and
    single-process runs all propagate unchanged. Every rank takes the
    same branch (the exception and counters are identical everywhere),
    so the retry barrier can never mismatch."""
    backoff = resilience.Backoff(base_s=backoff_s, factor=backoff_factor,
                                 jitter=jitter, deadline_s=deadline_s)
    retries = 0
    while True:
        try:
            return fn()
        except resilience.PeerFailure as e:
            if (classify_failure(e) != ROLLBACK or retries >= max_retries
                    or backoff.expired()):
                raise
            retries += 1
            _log.warning("retry_collective[%s]: transient coordinated "
                         "abort (%s); retry %d/%d", tag, e, retries,
                         max_retries)
            sleep(backoff.next_delay())
            resilience.health_barrier(f"{tag}:{retries}")
