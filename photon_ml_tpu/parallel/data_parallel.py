"""Data-parallel objective evaluation: the ``DistributedGLMLossFunction``
equivalent (SURVEY.md §3.2/§4.2; reference mount empty).

The reference broadcasts coefficients to executors and tree-aggregates
per-partition (loss, gradient) partials back to the driver each optimizer
iteration. Here the batch lives sharded over the mesh's ``data`` axis, the
coefficient vector is replicated, and a ``shard_map`` computes per-shard
partial sums joined by ``lax.psum`` over ICI — one XLA program, no host in
the loop. The entire optimizer (L-BFGS/TRON/OWL-QN ``while_loop``) jits
*around* this, so a whole fit is a single device computation.
"""

from __future__ import annotations

import functools
from collections import OrderedDict
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from photon_ml_tpu.compat import VMA_TRANSPOSE, shard_map
from photon_ml_tpu.ops.losses import apply_weights, mask_margins
from photon_ml_tpu.ops.objective import GLMObjective
from photon_ml_tpu.optimize import OptimizerConfig, get_optimizer
from photon_ml_tpu.optimize.common import OptimizationResult
from photon_ml_tpu.parallel.mesh import shard_batch
from photon_ml_tpu.types import (
    LabeledBatch,
    SparseFeatures,
    build_csc_transpose,
    csc_transpose_apply,
    margins as ell_margins,
    transpose_apply,
)


def distributed_value_and_grad(
    objective: GLMObjective, mesh: Mesh, axis: str = "data"
) -> Callable:
    """Returns fg(w, batch, l2) -> (value, grad) with batch rows sharded over
    ``axis``. The L2 term is added once globally (outside the psum), matching
    the single-device objective exactly."""

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(), P(axis), P()),
        out_specs=(P(), P()),
    )
    def shard_fg(w, batch, l2):
        # Per-shard data term only; L2 added globally afterwards. Only the
        # value needs an explicit psum: under shard_map's varying-axis
        # tracking (check_vma), the AD transpose of "replicated w touches
        # sharded batch" inserts the gradient's all-reduce automatically —
        # psumming g again would multiply it by the axis size. Legacy
        # check_rep shard_map inserts nothing, so psum explicitly there.
        f, g = objective.value_and_grad(w, batch, 0.0)
        if not VMA_TRANSPOSE:
            g = lax.psum(g, axis)
        return lax.psum(f, axis), g

    def fg(w, batch, l2=0.0):
        l2 = jnp.asarray(l2, w.dtype)
        f, g = shard_fg(w, batch, l2)
        wr = objective._reg_mask(w)
        return f + 0.5 * l2 * jnp.sum(wr * wr), g + l2 * wr

    return fg


def distributed_hvp(objective: GLMObjective, mesh: Mesh, axis: str = "data") -> Callable:
    """Returns hvp(w, v, batch, l2) sharded like distributed_value_and_grad.
    This is what the reference's HessianVectorAggregator treeAggregate does
    per CG step (SURVEY.md §4.2), as one on-device collective."""

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(), P(), P(axis)),
        out_specs=P(),
    )
    def shard_hvp(w, v, batch):
        # Like the gradient, the HVP's all-reduce is inserted by the AD
        # transpose (w and v are replicated, batch varies over `axis`) —
        # except on legacy check_rep shard_map, where it must be explicit.
        grad_data = lambda x: objective.grad(x, batch, 0.0)
        hv = jax.jvp(grad_data, (w,), (v,))[1]
        return hv if VMA_TRANSPOSE else lax.psum(hv, axis)

    def hvp(w, v, batch, l2=0.0):
        l2 = jnp.asarray(l2, w.dtype)
        hv = shard_hvp(w, v, batch)
        vr = objective._reg_mask(v)
        return hv + l2 * vr

    return hvp


def distributed_diagonal_hessian(objective: GLMObjective, mesh: Mesh,
                                 axis: str = "data") -> Callable:
    """Returns diag(w, batch, l2) -> exact Hessian diagonal, rows sharded
    over ``axis`` — one data pass; feeds TRON's Jacobi preconditioner."""

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(), P(axis)),
        out_specs=P(),
    )
    def shard_diag(w, batch):
        return lax.psum(objective.diagonal_hessian(w, batch, 0.0), axis)

    def diag(w, batch, l2=0.0):
        l2 = jnp.asarray(l2, w.dtype)
        d = shard_diag(w, batch)
        reg = jnp.full_like(d, l2)
        if not objective.regularize_intercept and objective.intercept_index >= 0:
            reg = reg.at[objective.intercept_index].set(0.0)
        return d + reg

    return diag


# Jitted-runner cache: one jit wrapper per (objective, fit configuration),
# so repeated fits — regularization grids, bench warm-up + timed runs,
# calibration sweeps — reuse one compiled executable instead of re-tracing
# and RECOMPILING per call (a fresh ``jax.jit(lambda ...)`` every call made
# the round-2 bench time compile, not compute, and silently broke the
# "l2 is traced so a grid reuses one compilation" contract). Keyed by
# objective identity (objectives hold unhashable arrays) then by the
# hashable fit configuration; jit's own per-wrapper cache handles argument
# shapes/dtypes. The runners' closures strongly reference the objective,
# so entries hold it strongly too (identity stays valid) and growth is
# bounded by LRU eviction — evicting an entry drops its executables and
# its objective together.
_RUNNER_CACHE: "OrderedDict[int, tuple]" = OrderedDict()
_RUNNER_CACHE_MAX = 16


def _runner_cache_for(objective) -> dict:
    oid = id(objective)
    entry = _RUNNER_CACHE.get(oid)
    if entry is not None and entry[0] is objective:
        _RUNNER_CACHE.move_to_end(oid)
        return entry[1]
    runners: dict = {}
    _RUNNER_CACHE[oid] = (objective, runners)
    _RUNNER_CACHE.move_to_end(oid)
    while len(_RUNNER_CACHE) > _RUNNER_CACHE_MAX:
        _RUNNER_CACHE.popitem(last=False)
    return runners


def cached_jit(objective, key, make_fn, **jit_kwargs):
    """Get-or-create a jitted kernel in the objective's runner cache (the
    streaming chunk kernels share the fit runners' cache policy).
    ``jit_kwargs`` (e.g. ``donate_argnums``) apply only when the kernel is
    first built, so every caller of one key must pass the same ones."""
    cache = _runner_cache_for(objective)
    fn = cache.get(key)
    if fn is None:
        fn = jax.jit(make_fn(), **jit_kwargs)
        cache[key] = fn
    return fn


def compiled_kernel_count(objective) -> int:
    """Total compiled-executable count across the objective's cached
    kernels (bench/test instrumentation: a count that stays flat across
    streamed passes proves the fixed-shape chunk contract held — no chunk
    retraced a kernel)."""
    total = 0
    for entry in _runner_cache_for(objective).values():
        for fn in (entry if isinstance(entry, (tuple, list)) else (entry,)):
            size = getattr(fn, "_cache_size", None)
            if callable(size):
                total += int(size())
    return total


def _eff_coeffs(norm, w):
    """Optimizer-space w -> (raw-space effective w, scalar margin adj)."""
    if norm is None:
        return w, jnp.zeros((), w.dtype)
    return norm.model_coefficients(w)


def _norm_fixed_fs(norm, dtype):
    """Normalization (factors, shifts) with the intercept slot pinned 1/0."""
    f = s = None
    if norm is not None and norm.factors is not None:
        f = norm.factors.astype(dtype)
        if norm.intercept_index >= 0:
            f = f.at[norm.intercept_index].set(1.0)
    if norm is not None and norm.shifts is not None:
        s = norm.shifts.astype(dtype)
        if norm.intercept_index >= 0:
            s = s.at[norm.intercept_index].set(0.0)
    return f, s


def _norm_chain_t(norm, gx, d_sum):
    """Raw-space Xᵀd (plus Σd) -> optimizer-space gradient."""
    if norm is None:
        return gx
    f, s = _norm_fixed_fs(norm, gx.dtype)
    if f is not None:
        gx = gx * f
    if s is not None:
        fs = s if f is None else f * s
        gx = gx - fs * d_sum
    return gx


def make_csc_path(objective: GLMObjective, mesh: Mesh, axis: str = "data",
                  use_pallas: bool = False, precise: bool = False,
                  segment: bool = False, with_cols: Optional[bool] = None):
    """Scatter-free sparse gradient path (see ``types.CSCTranspose``).

    Returns (build, fg, hvp): ``build(batch)`` sorts each shard's nonzeros by
    column under ``shard_map`` (runs on device, once per jitted fit);
    ``fg(w, batch, csc, l2)`` / ``hvp(w, v, batch, csc, l2)`` evaluate the
    objective with explicit margin-space derivatives — forward is the ELL
    gather, backward is the CSC prefix-sum, reductions are explicit psums.
    Requires SparseFeatures.

    Normalization composes with the coefficient-space trick: margins use
    ``w_eff = f̃·w`` plus the scalar shift adjustment, and the transposed
    chain rule maps the raw-space contraction back to optimizer space as
    ``g = f̃ ⊙ (Xᵀd) − f̃ s̃ Σd`` (f̃/s̃ have the intercept slot pinned to
    1/0) — both linear, so they commute with the per-shard psum."""
    norm = objective.normalization
    if with_cols is None:
        with_cols = segment

    def _eff(w):
        return _eff_coeffs(norm, w)

    def _chain_t(gx, d_sum):
        return _norm_chain_t(norm, gx, d_sum)

    if use_pallas:
        from photon_ml_tpu.ops.pallas_kernels import csc_transpose_apply_pallas

        if precise:
            raise ValueError("precise (f64 prefix) accumulation is not "
                             "available in the Pallas kernel; use "
                             "sparse_grad='csc_precise'")
        apply_t = csc_transpose_apply_pallas
    elif segment:
        from photon_ml_tpu.types import csc_segment_apply

        apply_t = csc_segment_apply
    elif precise:
        # full-f64 global prefix: meaningful only under jax_enable_x64
        # (x64-off runs, i.e. all TPU runs, silently degrade it to the
        # global-f32 scheme that cancels at scale) — the blocked default
        # is the accurate choice there (types.csc_transpose_apply)
        apply_t = functools.partial(csc_transpose_apply, precise=True)
    else:
        apply_t = csc_transpose_apply
    def build(batch: LabeledBatch):
        feats = batch.features
        if not isinstance(feats, SparseFeatures):
            raise ValueError("CSC path needs SparseFeatures")
        dim = feats.dim

        @functools.partial(
            shard_map, mesh=mesh, in_specs=(P(axis), P(axis)),
            out_specs=P(axis),
        )
        def _build(indices, values):
            # cols only when the segment apply will read them (the rest of
            # a precomputed view shouldn't carry +4 B/nnz of dead weight;
            # build_csc passes with_cols=True so one artifact serves every
            # calibration mode)
            csc = build_csc_transpose(indices, values, dim,
                                      with_cols=with_cols)
            # lead with a shard axis so P(axis) concatenation keeps each
            # shard's arrays intact ([n_shards, ...] leaves overall); the
            # whole CSCTranspose travels as one pytree so new fields (cols)
            # flow through every consumer
            return jax.tree.map(lambda a: a[None], csc)

        return _build(feats.indices, feats.values)

    def _margin_value_and_d(w, batch):
        w_eff, adjust = _eff(w)
        m = ell_margins(batch.features, w_eff) + batch.offsets + adjust
        per_ex = lambda m: jnp.sum(apply_weights(
            batch.weights,
            objective.loss.loss(mask_margins(batch.weights, m),
                                batch.labels)))
        f, d = jax.value_and_grad(per_ex)(m)
        return f, d

    # check_vma is disabled on the pallas variant: the interpret-mode kernel
    # body can't thread varying-axis types through pallas_call (reductions
    # here are explicit psums, so nothing relies on vma-driven transposes)
    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(), P(axis), P(axis)),
        out_specs=(P(), P()),
        check_vma=not use_pallas,
    )
    def shard_fg(w, batch, csc_sh):
        f, d = _margin_value_and_d(w, batch)
        csc = jax.tree.map(lambda a: a[0], csc_sh)
        g = _chain_t(apply_t(csc, d), jnp.sum(d))
        return lax.psum(f, axis), lax.psum(g, axis)

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(), P(), P(axis), P(axis)),
        out_specs=P(),
        check_vma=not use_pallas,
    )
    def shard_hvp(w, v, batch, csc_sh):
        w_eff, adjust = _eff(w)
        m = ell_margins(batch.features, w_eff) + batch.offsets + adjust
        # directional margin: the margin is linear in w, so the same
        # effective-coefficient map applies to v (no offset term)
        v_eff, v_adjust = _eff(v)
        mv = ell_margins(batch.features, v_eff) + v_adjust
        d2 = apply_weights(batch.weights,
                           objective.loss.d2(
                               mask_margins(batch.weights, m),
                               batch.labels))
        csc = jax.tree.map(lambda a: a[0], csc_sh)
        dv = d2 * mv
        return lax.psum(_chain_t(apply_t(csc, dv), jnp.sum(dv)), axis)

    def fg(w, batch, csc, l2=0.0):
        l2 = jnp.asarray(l2, w.dtype)
        f, g = shard_fg(w, batch, csc)
        wr = objective._reg_mask(w)
        return f + 0.5 * l2 * jnp.sum(wr * wr), g + l2 * wr

    def hvp(w, v, batch, csc, l2=0.0):
        l2 = jnp.asarray(l2, w.dtype)
        hv = shard_hvp(w, v, batch, csc)
        return hv + l2 * objective._reg_mask(v)

    return build, fg, hvp


# Measured per-platform sparse-gradient defaults for "auto" (both
# platforms calibrated — docs/PERF.md): the v5e r05 calibration at the
# bench shape ran {scatter 17.9s, csc 12.6s, csc_segment 27.2s,
# csc_pallas 12.5s}/20 iters — the fused Mosaic kernel wins on TPU,
# while on CPU the XLA scatter-add is ~10x faster than the csc paths.
_SPARSE_GRAD_DEFAULT = {"cpu": "scatter", "tpu": "csc_pallas"}
_sparse_grad_warned: set = set()


def resolve_sparse_grad(sparse_grad: str, features=None) -> str:
    """Resolve ``"auto"`` to the measured per-platform default. Dense
    features always resolve to "scatter" (the csc paths are sparse-only;
    dense X^T d is a plain MXU matmul). Unmeasured platforms fall back
    to "scatter" with a one-line log, mirroring
    ``game.random_effect.resolve_re_optimizer`` — no silent
    cross-platform fallback."""
    if sparse_grad != "auto":
        return sparse_grad
    if features is not None and not isinstance(features, SparseFeatures):
        return "scatter"
    platform = jax.devices()[0].platform
    choice = _SPARSE_GRAD_DEFAULT.get(platform, "scatter")
    if platform not in _SPARSE_GRAD_DEFAULT and platform not in _sparse_grad_warned:
        _sparse_grad_warned.add(platform)
        import logging

        logging.getLogger("photon_ml_tpu").info(
            "sparse_grad='auto' on platform %r -> %r (unmeasured default; "
            "run python bench.py on this platform to calibrate)",
            platform, choice)
    return choice


def build_csc(objective: GLMObjective, batch: LabeledBatch, mesh: Mesh,
              axis: str = "data", with_cols: bool = True):
    """Precompute the column-sorted (CSC) view of a sharded batch ONCE for
    reuse across fits (``fit_distributed(..., precomputed_csc=...)``) —
    regularization grids, hyperparameter calibration, and repeated bench
    fits all share one dataset, so the O(nnz log nnz) device sort should be
    paid per dataset, not per fit. The batch is padded/sharded exactly as
    ``fit_distributed`` will pad it, so the views line up."""
    batch = shard_batch(batch, mesh, axis)
    build = make_csc_path(objective, mesh, axis, with_cols=with_cols)[0]
    return jax.jit(build)(batch)


def make_margin_path(objective: GLMObjective, mesh: Mesh, axis: str = "data",
                     transpose: str = "scatter", precise: bool = False):
    """Margin-space primitives for :func:`optimize.lbfgs_margin.lbfgs_margin`.

    Returns ``(init_margin, dir_margin, loss_and_dir, make_data_grad)``:

    * ``init_margin(w, batch)`` — margins of the starting point, offsets and
      normalization adjust included (sharded [n]).
    * ``dir_margin(batch)(p)`` — the linear margin of a direction, no
      offsets (the per-iteration gather pass).
    * ``loss_and_dir(batch)(m, mp)`` — ``(Σ wᵢ l(mᵢ), Σ wᵢ l'(mᵢ) mpᵢ)``
      psummed to global scalars: the O(n) line-search trial evaluation.
    * ``make_data_grad(batch, csc)(m)`` — the data-term gradient from
      margins (the per-iteration transpose pass): XLA scatter-add when
      ``transpose='scatter'``/dense, or the column-sorted scatter-free
      apply when a prebuilt ``csc`` is given; normalization chain rule and
      the psum are applied inside.

    All reductions are explicit psums over ``axis`` so the optimizer runs
    entirely outside ``shard_map`` on replicated [d]-vectors.
    """
    norm = objective.normalization
    loss = objective.loss

    if transpose == "csc_pallas":
        from photon_ml_tpu.ops.pallas_kernels import csc_transpose_apply_pallas

        apply_t = csc_transpose_apply_pallas
    elif transpose == "csc_segment":
        from photon_ml_tpu.types import csc_segment_apply

        apply_t = csc_segment_apply
    elif precise:
        apply_t = functools.partial(csc_transpose_apply, precise=True)
    else:
        apply_t = csc_transpose_apply
    check_vma = transpose != "csc_pallas"

    @functools.partial(
        shard_map, mesh=mesh, in_specs=(P(), P(axis)), out_specs=P(axis),
    )
    def s_margin(v_eff, feats):
        return ell_margins(feats, v_eff)

    def init_margin(w, batch):
        w_eff, adjust = _eff_coeffs(norm, w)
        return s_margin(w_eff, batch.features) + batch.offsets + adjust

    def dir_margin(batch):
        def f(p):
            p_eff, p_adjust = _eff_coeffs(norm, p)
            return s_margin(p_eff, batch.features) + p_adjust

        return f

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(axis), P(axis), P(axis), P(axis)),
        out_specs=(P(), P()),
    )
    def s_loss_and_dir(m, mp, labels, weights):
        per_ex = lambda mm: jnp.sum(apply_weights(
            weights, loss.loss(mask_margins(weights, mm), labels)))
        f, d1 = jax.value_and_grad(per_ex)(m)
        return lax.psum(f, axis), lax.psum(jnp.sum(d1 * mp), axis)

    def loss_and_dir(batch):
        return lambda m, mp: s_loss_and_dir(m, mp, batch.labels, batch.weights)

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(axis), P(axis), P(), P(axis), P(axis)),
        out_specs=(P(), P()),
    )
    def s_delta_and_dir(m, mp, alpha, labels, weights):
        """Line-search evaluation in DELTA space: sums the per-row loss
        DIFFERENCES l(m + a*mp) - l(m), which keeps relative accuracy in
        the delta itself. In f32 the total loss's resolution is eps*|f|
        (~5e-3 at the bench scale) — far coarser than the per-iteration
        improvements near convergence, so Wolfe tests on totals become
        coin flips and the fit stalls (observed: hard stop at 16/20 on
        TPU). The derivative is evaluated at the trial point as usual."""
        mm0 = mask_margins(weights, m)
        per_ex = lambda mm: jnp.sum(apply_weights(
            weights, loss.loss(mask_margins(weights, mm), labels)))
        m1 = m + alpha * mp
        d1 = jax.grad(per_ex)(m1)
        diffs = apply_weights(
            weights,
            loss.loss(mask_margins(weights, m1), labels)
            - loss.loss(mm0, labels))
        return (lax.psum(jnp.sum(diffs), axis),
                lax.psum(jnp.sum(d1 * mp), axis))

    def delta_and_dir(batch):
        return lambda m, mp, alpha: s_delta_and_dir(
            m, mp, alpha, batch.labels, batch.weights)

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(axis), P(axis), P(axis), P(axis)),
        out_specs=P(),
    )
    def s_grad_scatter(m, feats, labels, weights):
        per_ex = lambda mm: jnp.sum(apply_weights(
            weights, loss.loss(mask_margins(weights, mm), labels)))
        d1 = jax.grad(per_ex)(m)
        g = _norm_chain_t(norm, transpose_apply(feats, d1), jnp.sum(d1))
        return lax.psum(g, axis)

    @functools.partial(
        shard_map, mesh=mesh,
        in_specs=(P(axis), P(axis), P(axis), P(axis)),
        out_specs=P(),
        check_vma=check_vma,
    )
    def s_grad_csc(m, labels, weights, csc_sh):
        per_ex = lambda mm: jnp.sum(apply_weights(
            weights, loss.loss(mask_margins(weights, mm), labels)))
        d1 = jax.grad(per_ex)(m)
        csc = jax.tree.map(lambda a: a[0], csc_sh)
        g = _norm_chain_t(norm, apply_t(csc, d1), jnp.sum(d1))
        return lax.psum(g, axis)

    def make_data_grad(batch, csc=None):
        if csc is None:
            return lambda m: s_grad_scatter(
                m, batch.features, batch.labels, batch.weights)
        return lambda m: s_grad_csc(
            m, batch.labels, batch.weights, csc)

    return (init_margin, dir_margin, loss_and_dir, make_data_grad,
            delta_and_dir)


def _fit_distributed_margin(
    objective, batch, mesh, w0, l2, config, axis,
    transpose: str = "scatter", precomputed_csc=None,
) -> OptimizationResult:
    """L-BFGS fit with the margin-space line search: 2 data passes per
    iteration (one gather, one transpose) regardless of line-search effort.
    ``transpose`` in {"scatter", "csc", "csc_pallas", "csc_precise",
    "csc_segment"}; the
    csc variants sort the nonzeros once (inside the jit but OUTSIDE the
    optimizer loop), or reuse ``precomputed_csc`` across fits."""
    from photon_ml_tpu.optimize.lbfgs_margin import lbfgs_margin

    batch = shard_batch(batch, mesh, axis)
    use_csc = transpose in ("csc", "csc_pallas", "csc_precise",
                            "csc_segment")
    if precomputed_csc is not None and not use_csc:
        raise ValueError(
            f"precomputed_csc given but sparse_grad={transpose!r} does not "
            "use it; pass sparse_grad='csc' (or a csc variant)")

    cache = _runner_cache_for(objective)
    key = ("margin", mesh, axis, transpose, config,
           precomputed_csc is not None)
    run = cache.get(key)
    if run is None:
        (init_margin, dir_margin, loss_and_dir, make_data_grad,
         delta_and_dir) = \
            make_margin_path(objective, mesh, axis, transpose=transpose,
                             precise=(transpose == "csc_precise"))
        reg_mask = objective._reg_mask
        build = None
        if use_csc and precomputed_csc is None:
            build = make_csc_path(
                objective, mesh, axis,
                use_pallas=(transpose == "csc_pallas"),
                precise=(transpose == "csc_precise"),
                segment=(transpose == "csc_segment"),
            )[0]

        @jax.jit
        def run(w0, b, l2v, csc):
            if use_csc and csc is None:
                csc = build(b)
            m0 = init_margin(w0, b)
            return lbfgs_margin(
                dir_margin(b), loss_and_dir(b), make_data_grad(b, csc),
                reg_mask, w0, m0, l2v, config,
                loss_delta_and_dir=delta_and_dir(b),
            )

        cache[key] = run
    return run(w0, batch, l2, precomputed_csc)


def fit_distributed(
    objective: GLMObjective,
    batch: LabeledBatch,
    mesh: Mesh,
    w0: jax.Array,
    l2=0.0,
    l1=0.0,
    optimizer: str = "lbfgs",
    config: OptimizerConfig = OptimizerConfig(),
    axis: str = "data",
    sparse_grad: str = "auto",
    line_search: str = "margin",
    precomputed_csc=None,
) -> OptimizationResult:
    """Shard the batch over the mesh and run a full jitted fit — the
    ``DistributedOptimizationProblem.run`` equivalent (SURVEY.md §3.2).

    ``sparse_grad``: "auto" (default: the measured per-platform choice —
    ``resolve_sparse_grad``), "scatter" (XLA scatter-add via autodiff transpose),
    "csc" (scatter-free column-sorted gradients — see ``make_csc_path``;
    sorts once per fit on device, best for many-iteration sparse fits on
    TPU), "csc_pallas" (fused Pallas kernel), "csc_precise" (CSC with
    f64 global prefix — only meaningful under jax_enable_x64), or "csc_segment" (sorted
    segment-sum: a scatter with indices_are_sorted=True, which XLA can
    lower without collision ordering).

    ``line_search``: "margin" (default, L-BFGS only) runs the strong-Wolfe
    search on cached margin vectors — O(n) per trial, two O(nnz) passes per
    iteration total (see ``optimize.lbfgs_margin``); "full" evaluates the
    black-box objective at every trial (the round-2 behavior, kept for
    parity testing and as the TRON/OWL-QN path).

    ``precomputed_csc``: reuse a ``build_csc(batch, mesh)`` result across
    fits on the same dataset (regularization grids, calibration) so the
    per-dataset column sort is paid once, not per fit."""
    sparse_grad = resolve_sparse_grad(sparse_grad, batch.features)
    if optimizer == "lbfgs" and line_search == "margin":
        return _fit_distributed_margin(
            objective, batch, mesh, w0, l2, config, axis,
            transpose=sparse_grad, precomputed_csc=precomputed_csc,
        )
    if sparse_grad in ("csc", "csc_pallas", "csc_precise", "csc_segment"):
        return _fit_distributed_csc(
            objective, batch, mesh, w0, l2, l1, optimizer, config, axis,
            use_pallas=(sparse_grad == "csc_pallas"),
            precise=(sparse_grad == "csc_precise"),
            segment=(sparse_grad == "csc_segment"),
            precomputed_csc=precomputed_csc,
        )
    if precomputed_csc is not None:
        raise ValueError(
            f"precomputed_csc given but sparse_grad={sparse_grad!r} does "
            "not use it; pass sparse_grad='csc' (or a csc variant)")
    batch = shard_batch(batch, mesh, axis)
    cache = _runner_cache_for(objective)
    key = ("full", mesh, axis, optimizer, config)
    run = cache.get(key)
    if run is None:
        fg = distributed_value_and_grad(objective, mesh, axis)
        opt = get_optimizer(optimizer)
        if optimizer == "owlqn":
            # L1 intercept mask (consistent with the L2 mask) is
            # shape-dependent: derive from the traced w0 so the cached
            # runner serves any dimension
            mask_int = (objective.intercept_index
                        if (objective.intercept_index >= 0
                            and not objective.regularize_intercept) else -1)

            def _owlqn_run(w0, b, l2v, l1v):
                l1_mask = (None if mask_int < 0
                           else jnp.ones_like(w0).at[mask_int].set(0.0))
                return opt(lambda w: fg(w, b, l2v), w0, l1v, config,
                           l1_mask=l1_mask)

            run = jax.jit(_owlqn_run)
        elif optimizer == "tron":
            hvp = distributed_hvp(objective, mesh, axis)
            diag = distributed_diagonal_hessian(objective, mesh, axis)
            # Jacobi preconditioner: one extra data pass per OUTER
            # iteration buys fewer CG passes (each CG step is a full pass)
            run = jax.jit(
                lambda w0, b, l2v: opt(
                    lambda w: fg(w, b, l2v), w0, config,
                    hvp=lambda w, v: hvp(w, v, b, l2v),
                    precond=lambda w: diag(w, b, l2v),
                )
            )
        else:
            run = jax.jit(
                lambda w0, b, l2v: opt(lambda w: fg(w, b, l2v), w0, config))
        cache[key] = run
    if optimizer == "owlqn":
        return run(w0, batch, l2, l1)
    return run(w0, batch, l2)


def _fit_distributed_csc(
    objective, batch, mesh, w0, l2, l1, optimizer, config, axis,
    use_pallas: bool = False, precise: bool = False, segment: bool = False,
    precomputed_csc=None,
) -> OptimizationResult:
    """CSC-path fit: ONE jitted program that sorts the shard nonzeros by
    column (or reuses ``precomputed_csc`` from :func:`build_csc`), then runs
    the whole optimizer loop against the sorted view — sort cost amortizes
    over every iteration (and over every fit when precomputed)."""
    batch = shard_batch(batch, mesh, axis)
    cache = _runner_cache_for(objective)
    key = ("csc", mesh, axis, optimizer, config, use_pallas, precise,
           segment, precomputed_csc is not None)
    run = cache.get(key)
    if run is None:
        build, fg, hvp = make_csc_path(objective, mesh, axis,
                                       use_pallas=use_pallas,
                                       precise=precise, segment=segment)
        opt = get_optimizer(optimizer)
        if optimizer == "owlqn":
            # the mask is shape-dependent: derive it from the traced w0 so
            # the cached runner serves any dimension
            mask_int = (objective.intercept_index
                        if (objective.intercept_index >= 0
                            and not objective.regularize_intercept) else -1)

            @jax.jit
            def run(w0, b, l2v, l1v, csc):
                if csc is None:
                    csc = build(b)
                l1_mask = (None if mask_int < 0
                           else jnp.ones_like(w0).at[mask_int].set(0.0))
                return opt(lambda w: fg(w, b, csc, l2v), w0, l1v, config,
                           l1_mask=l1_mask)

        elif optimizer == "tron":
            diag = distributed_diagonal_hessian(objective, mesh, axis)

            @jax.jit
            def run(w0, b, l2v, csc):
                if csc is None:
                    csc = build(b)
                return opt(lambda w: fg(w, b, csc, l2v), w0, config,
                           hvp=lambda w, v: hvp(w, v, b, csc, l2v),
                           precond=lambda w: diag(w, b, l2v))

        else:

            @jax.jit
            def run(w0, b, l2v, csc):
                if csc is None:
                    csc = build(b)
                return opt(lambda w: fg(w, b, csc, l2v), w0, config)

        cache[key] = run
    if optimizer == "owlqn":
        return run(w0, batch, l2, l1, precomputed_csc)
    return run(w0, batch, l2, precomputed_csc)
