from photon_ml_tpu.parallel.mesh import make_mesh, pad_batch, shard_batch
from photon_ml_tpu.parallel.data_parallel import (
    distributed_value_and_grad,
    distributed_hvp,
    fit_distributed,
)
