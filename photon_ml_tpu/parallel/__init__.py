from photon_ml_tpu.parallel import fault_injection, resilience
from photon_ml_tpu.parallel.mesh import make_mesh, pad_batch, shard_batch
from photon_ml_tpu.parallel.data_parallel import (
    distributed_value_and_grad,
    distributed_hvp,
    fit_distributed,
)
from photon_ml_tpu.parallel.resilience import (
    CollectiveGuard,
    PeerFailure,
    ResumeManager,
    ResumeMismatch,
    WatchdogTimeout,
    guarded,
    health_barrier,
    retry_transient,
)
from photon_ml_tpu.parallel.entity_shard import (
    EntityShardSpec,
    EntityTableBudgetError,
    ShardCommStats,
)
