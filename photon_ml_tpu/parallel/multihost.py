"""Multi-host (multi-process) runtime initialization.

The reference scales past one machine through Spark's cluster manager and
netty shuffle service (SURVEY.md §5.8). The TPU-native equivalent is the JAX
multi-controller runtime: every host runs the same program, calls
``jax.distributed.initialize`` (coordinator rendezvous), and afterwards
``jax.devices()`` spans every chip in the slice — the same ``shard_map`` /
``psum`` programs used single-host then reduce over ICI within a host and
DCN across hosts, with XLA picking the collective implementation. No
NCCL/MPI port is needed or wanted.

Drivers expose this via ``--coordinator-address`` (plus optional
``--num-processes`` / ``--process-id``; on TPU pods those are inferred from
the environment). Data loading composes with it: each process reads its own
row range (``process_span``) and the global batch is formed by sharding over
the full mesh.
"""

from __future__ import annotations

from typing import Optional, Tuple


def initialize_multihost(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    max_attempts: Optional[int] = None,
    backoff_s: float = 1.0,
) -> bool:
    """Rendezvous this process into the global runtime. Returns True if
    distributed mode was initialized, False for the single-process no-op
    (no coordinator given and no TPU pod environment to infer one from).

    Coordinator rendezvous is the flakiest moment of a pod job (the
    coordinator may not be listening yet, a peer may be slow to restart
    after preemption), so transient failures retry with exponential
    backoff — bounded by ``max_attempts`` (default 3; env override
    PHOTON_ML_TPU_INIT_ATTEMPTS) so a genuinely wrong address still fails
    fast with the real error.

    Must run before the first use of the jax backend."""
    import os

    import jax

    from photon_ml_tpu.parallel import fault_injection, resilience

    if coordinator_address is None and num_processes is None:
        return False
    if (num_processes is None) != (process_id is None):
        raise ValueError("--num-processes and --process-id go together")
    if max_attempts is None:
        max_attempts = int(os.environ.get("PHOTON_ML_TPU_INIT_ATTEMPTS", 3))

    def _rendezvous():
        fault_injection.check("multihost.init")
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )

    resilience.retry_transient(_rendezvous, attempts=max_attempts,
                               backoff_s=backoff_s)
    return True


def span_of(total_rows: int, index: int, count: int) -> Tuple[int, int]:
    """Process ``index``'s [start, stop) slice of a globally-ordered
    dataset under near-equal contiguous assignment (the reference's
    input-split assignment)."""
    base, extra = divmod(total_rows, count)
    start = index * base + min(index, extra)
    return start, start + base + (1 if index < extra else 0)


def process_span(total_rows: int) -> Tuple[int, int]:
    """This process's [start, stop) slice of a globally-ordered dataset."""
    import jax

    return span_of(total_rows, jax.process_index(), jax.process_count())


def allgather_spans(local: "np.ndarray", total_rows: int) -> "np.ndarray":
    """Reassemble a globally-ordered [total_rows] vector from per-process
    ``process_span`` slices (each process passes its own slice): the
    ``span_of``-sliced special case of :func:`allgather_varspans`."""
    import jax

    p = jax.process_count()
    return allgather_varspans(local,
                              [span_of(total_rows, i, p) for i in range(p)])


def allreduce_summary_moments(s1, s2, nnz, mx, mn):
    """All-reduce the raw per-feature moment accumulators of a streamed
    feature summarization across processes (sum for the power sums and
    nonzero counts, max/min for the extrema). Passed as ``part_reduce`` to
    ``ops.statistics.summarize_features_streamed`` by multi-controller
    drivers so every process finalizes the same GLOBAL summary."""
    import jax
    import numpy as np

    if jax.process_count() == 1:
        return s1, s2, nnz, mx, mn
    from jax.experimental import multihost_utils

    def gather_f64(a):
        # process_allgather round-trips through jax arrays, which silently
        # downcast f64 to f32 without jax_enable_x64 — destroying exactly
        # the accumulator precision the streamed summarization guarantees.
        # An int32 view is bit-preserving through the gather.
        a = np.ascontiguousarray(np.asarray(a, np.float64))
        bits = multihost_utils.process_allgather(a.view(np.int32))
        return np.ascontiguousarray(np.asarray(bits)).view(np.float64)

    g1, g2, gn, gx, gm = (gather_f64(a) for a in (s1, s2, nnz, mx, mn))
    return (g1.sum(axis=0), g2.sum(axis=0), gn.sum(axis=0),
            gx.max(axis=0), gm.min(axis=0))


def runtime_info() -> dict:
    """Host/device topology for logs (PhotonLogger-friendly)."""
    import jax

    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
        "platform": jax.devices()[0].platform,
    }


def allgather_varspans(local: "np.ndarray", spans) -> "np.ndarray":
    """Reassemble a globally-ordered vector from per-process CONTIGUOUS
    row spans of ARBITRARY sizes (``spans``: one (start, stop) per
    process, identical on every process — e.g. block-aligned out-of-core
    input splits, which are contiguous but not ``process_span``-aligned).
    Generalizes :func:`allgather_spans` (which assumes ``span_of``
    slicing)."""
    import jax
    import numpy as np

    local = np.asarray(local)
    p = jax.process_count()
    if p == 1:
        return local
    assert len(spans) == p, (len(spans), p)
    from jax.experimental import multihost_utils

    max_len = max(stop - start for start, stop in spans)
    padded = np.zeros((max_len,) + local.shape[1:], local.dtype)
    padded[: len(local)] = local
    gathered = np.asarray(multihost_utils.process_allgather(padded))
    return np.concatenate([gathered[i, : stop - start]
                           for i, (start, stop) in enumerate(spans)])
