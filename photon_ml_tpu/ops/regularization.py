"""L1 / L2 / elastic-net regularization contexts.

Equivalent of the reference's ``optimization.{RegularizationContext,
RegularizationType}`` (SURVEY.md §3.1; reference mount empty). Semantics match
the reference: the L2 part is folded analytically into the smooth objective
(value/gradient/Hessian); the L1 part is NOT part of the smooth objective and
is handled by the OWL-QN optimizer. Elastic net splits the regularization
weight by ``alpha``: L1 gets ``alpha * lambda``, L2 gets ``(1-alpha) * lambda``.
"""

from __future__ import annotations

import dataclasses
import enum


class RegularizationType(str, enum.Enum):
    NONE = "none"
    L1 = "l1"
    L2 = "l2"
    ELASTIC_NET = "elastic_net"


@dataclasses.dataclass(frozen=True)
class RegularizationContext:
    reg_type: RegularizationType = RegularizationType.NONE
    # elastic-net mixing in [0,1]: fraction of the weight that is L1.
    alpha: float = 0.5

    def __post_init__(self):
        object.__setattr__(self, "reg_type", RegularizationType(self.reg_type))
        if not (0.0 <= self.alpha <= 1.0):
            raise ValueError(f"elastic-net alpha must be in [0,1], got {self.alpha}")

    def l1_weight(self, reg_weight: float) -> float:
        if self.reg_type == RegularizationType.L1:
            return reg_weight
        if self.reg_type == RegularizationType.ELASTIC_NET:
            return self.alpha * reg_weight
        return 0.0

    def l2_weight(self, reg_weight: float) -> float:
        if self.reg_type == RegularizationType.L2:
            return reg_weight
        if self.reg_type == RegularizationType.ELASTIC_NET:
            return (1.0 - self.alpha) * reg_weight
        return 0.0

    @property
    def needs_owlqn(self) -> bool:
        return self.reg_type in (RegularizationType.L1, RegularizationType.ELASTIC_NET)
