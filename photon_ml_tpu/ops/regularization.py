"""L1 / L2 / elastic-net regularization contexts.

Equivalent of the reference's ``optimization.{RegularizationContext,
RegularizationType}`` (SURVEY.md §3.1; reference mount empty). Semantics match
the reference: the L2 part is folded analytically into the smooth objective
(value/gradient/Hessian); the L1 part is NOT part of the smooth objective and
is handled by the OWL-QN optimizer. Elastic net splits the regularization
weight by ``alpha``: L1 gets ``alpha * lambda``, L2 gets ``(1-alpha) * lambda``.
"""

from __future__ import annotations

import dataclasses
import enum


class RegularizationType(str, enum.Enum):
    NONE = "none"
    L1 = "l1"
    L2 = "l2"
    ELASTIC_NET = "elastic_net"


@dataclasses.dataclass(frozen=True)
class RegularizationContext:
    reg_type: RegularizationType = RegularizationType.NONE
    # elastic-net mixing in [0,1]: fraction of the weight that is L1.
    alpha: float = 0.5

    def __post_init__(self):
        object.__setattr__(self, "reg_type", RegularizationType(self.reg_type))
        if not (0.0 <= self.alpha <= 1.0):
            raise ValueError(f"elastic-net alpha must be in [0,1], got {self.alpha}")

    def l1_weight(self, reg_weight: float) -> float:
        if self.reg_type == RegularizationType.L1:
            return reg_weight
        if self.reg_type == RegularizationType.ELASTIC_NET:
            return self.alpha * reg_weight
        return 0.0

    def l2_weight(self, reg_weight: float) -> float:
        if self.reg_type == RegularizationType.L2:
            return reg_weight
        if self.reg_type == RegularizationType.ELASTIC_NET:
            return (1.0 - self.alpha) * reg_weight
        return 0.0

    @property
    def needs_owlqn(self) -> bool:
        return self.reg_type in (RegularizationType.L1, RegularizationType.ELASTIC_NET)


def screening_threshold(rule: str, lam_l1: float, lam_l1_prev: float,
                        slack: float = 0.0) -> float:
    """Sequential screening threshold for the pathwise fixed-effect solver
    (``optimize.path``): a feature whose data-gradient magnitude at the
    previous lambda's solution falls BELOW the returned value is frozen at
    zero for the restricted solve at ``lam_l1``.

    * ``"strong"`` — the sequential strong rule of Tibshirani et al.
      (the screen in distributed CD for GLMs, arxiv 1611.02101 and Snap
      ML's hierarchy, arxiv 1803.06333): ``2*l1 - l1_prev``, i.e. the
      unit-slope bound on how fast ``|g_j|`` can grow along the path.
      Aggressive; can over-screen on strongly correlated designs.
    * ``"safe"`` — double the strong rule's guard band:
      ``l1 - 2*(l1_prev-l1)``, i.e. a slope-2 growth allowance. Keeps
      marginal features in the candidate set, trading a larger
      restricted problem for fewer KKT repair rounds.

    Both are certified downstream: the post-solve full-gradient KKT check
    re-admits anything either rule wrongly froze, so the rule choice only
    moves the work split between restricted-solve size and repair rounds —
    never the solution. ``slack`` inflates the threshold by
    ``slack * (l1_prev - l1)`` to deliberately over-screen (adversarial
    repair tests; 0 = the published rules). A non-positive return means
    nothing can be screened at this step (e.g. a large lambda drop)."""
    gap = lam_l1_prev - lam_l1
    if rule == "strong":
        base = lam_l1 - gap
    elif rule == "safe":
        base = lam_l1 - 2.0 * gap
    else:
        raise ValueError(f"unknown screening rule {rule!r}; "
                         "known: strong, safe")
    return base + slack * gap


def kkt_slack(lam_l1: float, kkt_tol: float) -> float:
    """Absolute slack for the screened-coordinate KKT test: a frozen
    coordinate with ``|g_j| > lam_l1 + kkt_slack`` is a violator and
    re-enters the candidate set. Relative in the L1 weight with a unit
    floor so small-lambda grid tails don't demand sub-solver-tolerance
    gradient precision."""
    return kkt_tol * max(lam_l1, 1.0)
