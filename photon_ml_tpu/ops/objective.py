"""GLM objective: value / gradient / Hessian-vector product over a batch.

TPU-native equivalent of the reference's objective-function hierarchy
(``function.{ObjectiveFunction, DiffFunction, TwiceDiffFunction}``,
``SingleNodeGLMLossFunction`` and ``DistributedGLMLossFunction`` — SURVEY.md
§3.1/§3.2; reference mount empty). Differences by design:

* One pure-function objective serves both the "single node" and "distributed"
  roles: distribution is a *sharding* concern (see ``photon_ml_tpu.parallel``),
  not a class hierarchy. Under ``jit`` with batch rows sharded over a mesh
  axis, the sums below lower to per-shard partial sums + an ICI all-reduce —
  exactly the reference's ``treeAggregate`` role.
* Hessian-vector products come from forward-over-reverse autodiff
  (``jax.jvp`` of ``jax.grad``) instead of a hand-written aggregator; on TPU
  an HVP costs ~2 gradient passes and no extra cluster round-trip (the
  reference pays one full ``treeAggregate`` per CG step — SURVEY.md §4.2).
* Sum semantics (not mean), weights multiply per-example losses, offsets add
  to margins, the L2 term is ``0.5 * l2 * ||w_masked||^2`` — matching the
  reference so loss values line up.

``l2`` is a traced argument so a regularization grid reuses one compilation.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from flax import struct

from photon_ml_tpu.ops.losses import PointwiseLoss, get_loss
from photon_ml_tpu.ops.normalization import NormalizationContext
from photon_ml_tpu.types import (
    LabeledBatch,
    margins as _margins,
    row_squares_apply,
    transpose_apply,
)


@struct.dataclass
class GLMObjective:
    """A GLM training objective.

    Attributes:
      loss: the pointwise loss (static).
      normalization: optional NormalizationContext folded into margins.
      regularize_intercept: whether L2 touches the intercept coordinate
        (default False, i.e. the intercept is unpenalized).
      intercept_index: column of the constant-1 intercept feature, -1 if none.
    """

    loss: PointwiseLoss = struct.field(pytree_node=False)
    normalization: Optional[NormalizationContext] = None
    regularize_intercept: bool = struct.field(pytree_node=False, default=False)
    intercept_index: int = struct.field(pytree_node=False, default=-1)

    # -- margins ------------------------------------------------------------
    def margins(self, w: jax.Array, batch: LabeledBatch) -> jax.Array:
        if self.normalization is not None:
            w_eff, adjust = self.normalization.model_coefficients(w)
        else:
            w_eff, adjust = w, 0.0
        return _margins(batch.features, w_eff) + batch.offsets + adjust

    def predict(self, w: jax.Array, batch: LabeledBatch) -> jax.Array:
        """Mean response (inverse link of the margin)."""
        return self.loss.mean(self.margins(w, batch))

    # -- objective ----------------------------------------------------------
    def _reg_mask(self, w: jax.Array) -> jax.Array:
        if self.regularize_intercept or self.intercept_index < 0:
            return w
        return w.at[self.intercept_index].set(0.0)

    def value(self, w: jax.Array, batch: LabeledBatch, l2=0.0) -> jax.Array:
        m = self.margins(w, batch)
        data_term = jnp.sum(batch.weights * self.loss.loss(m, batch.labels))
        wr = self._reg_mask(w)
        return data_term + 0.5 * l2 * jnp.sum(wr * wr)

    def value_and_grad(self, w, batch, l2=0.0):
        return jax.value_and_grad(self.value)(w, batch, l2)

    def grad(self, w, batch, l2=0.0):
        return jax.grad(self.value)(w, batch, l2)

    def hvp(self, w, v, batch, l2=0.0):
        """Hessian-vector product via forward-over-reverse autodiff."""
        g = lambda x: jax.grad(self.value)(x, batch, l2)
        return jax.jvp(g, (w,), (v,))[1]

    def diagonal_hessian(self, w, batch, l2=0.0):
        """Exact diagonal of the Hessian: sum_i w_i l''(m_i) x'_ij^2 + l2
        where x' is the (virtually) normalized feature x'_j = (x_j - s_j) f_j.

        Used for coefficient-variance computation (the reference's
        diagonal-Hessian aggregator, VarianceComputationType.SIMPLE —
        SURVEY.md §3.2). Expanded so the shifted square never materializes:
        sum d2 (x - s)^2 f^2 = f^2 (sum d2 x^2 - 2 s sum d2 x + s^2 sum d2)."""
        m = self.margins(w, batch)
        d2 = batch.weights * self.loss.d2(m, batch.labels)
        diag = row_squares_apply(batch.features, d2)
        if self.normalization is not None:
            norm = self.normalization
            if norm.shifts is not None:
                s = norm.shifts
                if norm.intercept_index >= 0:
                    s = s.at[norm.intercept_index].set(0.0)
                diag = diag - 2.0 * s * transpose_apply(batch.features, d2) + s * s * jnp.sum(d2)
            if norm.factors is not None:
                f = norm.factors
                if norm.intercept_index >= 0:
                    f = f.at[norm.intercept_index].set(1.0)
                diag = diag * f * f
        reg = jnp.full_like(diag, l2)
        if not self.regularize_intercept and self.intercept_index >= 0:
            reg = reg.at[self.intercept_index].set(0.0)
        return diag + reg

    def coefficient_variances(self, w, batch, l2=0.0):
        """Diagonal-inverse-Hessian coefficient variances (SURVEY.md §4.2)."""
        diag = self.diagonal_hessian(w, batch, l2)
        return 1.0 / jnp.maximum(diag, jnp.finfo(diag.dtype).tiny)


def make_objective(
    loss: str | PointwiseLoss,
    normalization: Optional[NormalizationContext] = None,
    regularize_intercept: bool = False,
    intercept_index: int = -1,
) -> GLMObjective:
    if isinstance(loss, str):
        loss = get_loss(loss)
    return GLMObjective(
        loss=loss,
        normalization=normalization,
        regularize_intercept=regularize_intercept,
        intercept_index=intercept_index,
    )
