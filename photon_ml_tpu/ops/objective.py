"""GLM objective: value / gradient / Hessian-vector product over a batch.

TPU-native equivalent of the reference's objective-function hierarchy
(``function.{ObjectiveFunction, DiffFunction, TwiceDiffFunction}``,
``SingleNodeGLMLossFunction`` and ``DistributedGLMLossFunction`` — SURVEY.md
§3.1/§3.2; reference mount empty). Differences by design:

* One pure-function objective serves both the "single node" and "distributed"
  roles: distribution is a *sharding* concern (see ``photon_ml_tpu.parallel``),
  not a class hierarchy. Under ``jit`` with batch rows sharded over a mesh
  axis, the sums below lower to per-shard partial sums + an ICI all-reduce —
  exactly the reference's ``treeAggregate`` role.
* Hessian-vector products come from forward-over-reverse autodiff
  (``jax.jvp`` of ``jax.grad``) instead of a hand-written aggregator; on TPU
  an HVP costs ~2 gradient passes and no extra cluster round-trip (the
  reference pays one full ``treeAggregate`` per CG step — SURVEY.md §4.2).
* Sum semantics (not mean), weights multiply per-example losses, offsets add
  to margins, the L2 term is ``0.5 * l2 * ||w_masked||^2`` — matching the
  reference so loss values line up.

``l2`` is a traced argument so a regularization grid reuses one compilation.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from flax import struct
from jax import lax

from photon_ml_tpu.ops.losses import (
    PointwiseLoss, apply_weights, get_loss, mask_margins,
)
from photon_ml_tpu.ops.normalization import NormalizationContext
from photon_ml_tpu.types import (
    LabeledBatch,
    SparseFeatures,
    margins as _margins,
    row_squares_apply,
    transpose_apply,
)


@struct.dataclass
class GLMObjective:
    """A GLM training objective.

    Attributes:
      loss: the pointwise loss (static).
      normalization: optional NormalizationContext folded into margins.
      regularize_intercept: whether L2 touches the intercept coordinate
        (default False, i.e. the intercept is unpenalized).
      intercept_index: column of the constant-1 intercept feature, -1 if none.
    """

    loss: PointwiseLoss = struct.field(pytree_node=False)
    normalization: Optional[NormalizationContext] = None
    regularize_intercept: bool = struct.field(pytree_node=False, default=False)
    intercept_index: int = struct.field(pytree_node=False, default=-1)

    # -- margins ------------------------------------------------------------
    def margins(self, w: jax.Array, batch: LabeledBatch) -> jax.Array:
        if self.normalization is not None:
            w_eff, adjust = self.normalization.model_coefficients(w)
        else:
            w_eff, adjust = w, 0.0
        return _margins(batch.features, w_eff) + batch.offsets + adjust

    def predict(self, w: jax.Array, batch: LabeledBatch) -> jax.Array:
        """Mean response (inverse link of the margin)."""
        return self.loss.mean(self.margins(w, batch))

    # -- objective ----------------------------------------------------------
    def _reg_mask(self, w: jax.Array) -> jax.Array:
        if self.regularize_intercept or self.intercept_index < 0:
            return w
        return w.at[self.intercept_index].set(0.0)

    def value(self, w: jax.Array, batch: LabeledBatch, l2=0.0) -> jax.Array:
        m = mask_margins(batch.weights, self.margins(w, batch))
        data_term = jnp.sum(apply_weights(batch.weights,
                                          self.loss.loss(m, batch.labels)))
        wr = self._reg_mask(w)
        return data_term + 0.5 * l2 * jnp.sum(wr * wr)

    def value_and_grad(self, w, batch, l2=0.0):
        return jax.value_and_grad(self.value)(w, batch, l2)

    def grad(self, w, batch, l2=0.0):
        return jax.grad(self.value)(w, batch, l2)

    def hvp(self, w, v, batch, l2=0.0):
        """Hessian-vector product via forward-over-reverse autodiff."""
        g = lambda x: jax.grad(self.value)(x, batch, l2)
        return jax.jvp(g, (w,), (v,))[1]

    def diagonal_hessian(self, w, batch, l2=0.0):
        """Exact diagonal of the Hessian: sum_i w_i l''(m_i) x'_ij^2 + l2
        where x' is the (virtually) normalized feature x'_j = (x_j - s_j) f_j.

        Used for coefficient-variance computation (the reference's
        diagonal-Hessian aggregator, VarianceComputationType.SIMPLE —
        SURVEY.md §3.2). Expanded so the shifted square never materializes:
        sum d2 (x - s)^2 f^2 = f^2 (sum d2 x^2 - 2 s sum d2 x + s^2 sum d2)."""
        m = mask_margins(batch.weights, self.margins(w, batch))
        d2 = apply_weights(batch.weights, self.loss.d2(m, batch.labels))
        diag = row_squares_apply(batch.features, d2)
        if self.normalization is not None:
            norm = self.normalization
            if norm.shifts is not None:
                s = norm.shifts
                if norm.intercept_index >= 0:
                    s = s.at[norm.intercept_index].set(0.0)
                diag = diag - 2.0 * s * transpose_apply(batch.features, d2) + s * s * jnp.sum(d2)
            if norm.factors is not None:
                f = norm.factors
                if norm.intercept_index >= 0:
                    f = f.at[norm.intercept_index].set(1.0)
                diag = diag * f * f
        reg = jnp.full_like(diag, l2)
        if not self.regularize_intercept and self.intercept_index >= 0:
            reg = reg.at[self.intercept_index].set(0.0)
        return diag + reg

    def full_hessian(self, w, batch, l2=0.0, chunk_rows: int = 4096):
        """Explicit d x d Hessian  X'^T diag(w_i l''(m_i)) X' + l2*mask  —
        the matrix behind the reference's FULL VarianceComputationType
        (SURVEY.md §3.2 optimization-problems row). Only sensible for small
        dims (d up to a few thousand: O(d^2) memory, O(n d^2) FLOPs — dense
        chunks ride the MXU). Rows stream in fixed-size chunks so the dense
        [n, d] view never materializes."""
        m = mask_margins(batch.weights, self.margins(w, batch))
        d2 = apply_weights(batch.weights, self.loss.d2(m, batch.labels))
        dim = batch.dim
        n = batch.num_examples
        c = min(chunk_rows, n)
        n_chunks = -(-n // c)

        norm = self.normalization
        f_pin = s_pin = None
        if norm is not None and norm.factors is not None:
            f_pin = norm.factors
            if norm.intercept_index >= 0:
                f_pin = f_pin.at[norm.intercept_index].set(1.0)
        if norm is not None and norm.shifts is not None:
            s_pin = norm.shifts
            if norm.intercept_index >= 0:
                s_pin = s_pin.at[norm.intercept_index].set(0.0)

        def chunk_h(i, acc):
            # clamp the last chunk's start so the slice stays in bounds,
            # and zero the d2 of rows the previous chunk already covered
            s0 = jnp.minimum(i * c, n - c)
            sl = batch.slice_rows(s0, c)
            dc = lax.dynamic_slice_in_dim(d2, s0, c)
            dc = dc * (s0 + jnp.arange(c) >= i * c)
            X = (sl.features.todense()
                 if isinstance(sl.features, SparseFeatures)
                 else sl.features)
            if s_pin is not None:
                X = X - s_pin[None, :]
            if f_pin is not None:
                X = X * f_pin[None, :]
            return acc + X.T @ (dc[:, None] * X)

        H = lax.fori_loop(
            0, n_chunks, chunk_h, jnp.zeros((dim, dim), d2.dtype))
        reg = jnp.full((dim,), l2, H.dtype)
        if not self.regularize_intercept and self.intercept_index >= 0:
            reg = reg.at[self.intercept_index].set(0.0)
        return H + jnp.diag(reg)

    def coefficient_variances(self, w, batch, l2=0.0, mode: str = "diagonal"):
        """Coefficient variances (SURVEY.md §4.2):

        * ``"diagonal"`` — 1 / diag(H), the reference's SIMPLE type: exact
          diagonal, cheap at any dim.
        * ``"full"`` — diag(H^{-1}), the reference's FULL type: accounts
          for feature correlations; O(d^3) solve, small dims only.
        """
        if mode == "full":
            H = self.full_hessian(w, batch, l2)
            # diag of the inverse via a full solve against I (d is small)
            Hinv = jnp.linalg.solve(H, jnp.eye(H.shape[0], dtype=H.dtype))
            return jnp.diagonal(Hinv)
        if mode != "diagonal":
            raise ValueError(f"unknown variance mode {mode!r}")
        diag = self.diagonal_hessian(w, batch, l2)
        return 1.0 / jnp.maximum(diag, jnp.finfo(diag.dtype).tiny)


def kkt_residuals(w: jax.Array, g: jax.Array, lam_l1,
                  l1_mask: Optional[jax.Array] = None) -> jax.Array:
    """Per-coordinate KKT stationarity residual of
    ``min f(w) + lam_l1 * ||w * mask||_1`` given the smooth-part gradient
    ``g`` = grad f(w):

    * unpenalized coordinates (mask 0): ``|g_j|`` — plain stationarity;
    * zero coordinates: ``max(|g_j| - lam_l1, 0)`` — the subgradient
      condition ``|g_j| <= lam_l1``;
    * nonzero coordinates: ``|g_j + lam_l1 * sign(w_j)|``.

    The pathwise screening certificate (``optimize.path``) and its tests
    are phrased in this residual: a solve is KKT-certified when every
    screened-out coordinate's residual is within the certification slack
    (``ops.regularization.kkt_slack``) and the solver's own coordinates
    are within solver tolerance."""
    lam = jnp.asarray(lam_l1, g.dtype)
    mask = (jnp.ones_like(g) if l1_mask is None
            else jnp.asarray(l1_mask, g.dtype))
    lam_eff = lam * mask
    at_zero = jnp.maximum(jnp.abs(g) - lam_eff, 0.0)
    away = jnp.abs(g + lam_eff * jnp.sign(w))
    return jnp.where(w == 0, at_zero, away)


def make_objective(
    loss: str | PointwiseLoss,
    normalization: Optional[NormalizationContext] = None,
    regularize_intercept: bool = False,
    intercept_index: int = -1,
) -> GLMObjective:
    if isinstance(loss, str):
        loss = get_loss(loss)
    return GLMObjective(
        loss=loss,
        normalization=normalization,
        regularize_intercept=regularize_intercept,
        intercept_index=intercept_index,
    )
