"""Pallas TPU kernels for the sparse-gradient hot path.

The scatter-free CSC gradient (``types.CSCTranspose``) is bottlenecked by a
length-nnz prefix sum: XLA lowers ``jnp.cumsum`` over tens of millions of
elements to several log-tree passes over HBM. The kernel here streams the
array once: a sequential 1-D grid over row tiles with a running carry in
SMEM, computing each tile's inclusive scan as two small lower-triangular
**matmuls on the MXU** (cumsum-as-matmul — the TPU-native scan idiom; no
unsupported vector shifts or gathers), and fusing the
``contrib = values * d_gathered`` multiply into the same pass so the
contribution vector is never materialized in HBM.

Why matmul: a [T, 128] tile's per-lane inclusive prefix is ``x @ L`` with
``L[a, b] = 1 if a <= b``; the running offset across the tile's rows is a
strict-lower-triangular matmul of the per-row totals. Both hit the MXU with
static shapes.

Falls back to interpret mode off-TPU (CPU tests run the same kernel code).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from photon_ml_tpu.compat import VMA_TRANSPOSE, typeof
from jax.experimental import pallas as pl

_LANES = 128


def _mps_kernel(v_ref, d_ref, out_ref):
    """One [T, 128] tile: fused multiply + TILE-LOCAL inclusive prefix sum,
    plus the tile's total. No cross-tile carry: a global running prefix
    would reintroduce the f32 boundary-difference cancellation the blocked
    scheme exists to avoid (types.blocked_boundary_combine), and dropping
    the sequential carry removes the only cross-tile dependency."""
    x = v_ref[:] * d_ref[:]  # fused contribution product
    rows = x.shape[0]
    dtype = x.dtype

    # match_vma: in interpret mode (CPU tests) the kernel body runs under
    # shard_map's varying-axis tracking, where fresh iota constants are
    # unvarying and may not meet varying data in a dot; on the compiled TPU
    # path the kernel traces standalone and this is a no-op.
    from photon_ml_tpu.optimize.common import match_vma

    # inclusive prefix along lanes: x @ L, L[a, b] = (a <= b)
    a = jax.lax.broadcasted_iota(jnp.int32, (_LANES, _LANES), 0)
    b = jax.lax.broadcasted_iota(jnp.int32, (_LANES, _LANES), 1)
    lane_cum = jnp.dot(x, match_vma((a <= b).astype(dtype), x),
                       preferred_element_type=dtype)

    # running offset across rows: strict lower-triangular matmul of row sums
    row_tot = lane_cum[:, _LANES - 1:_LANES]  # [rows, 1]
    ra = jax.lax.broadcasted_iota(jnp.int32, (rows, rows), 0)
    rb = jax.lax.broadcasted_iota(jnp.int32, (rows, rows), 1)
    row_excl = jnp.dot(match_vma((rb < ra).astype(dtype), x), row_tot,
                       preferred_element_type=dtype)  # [rows, 1]

    # the tile total is the local prefix's last element; the wrapper slices
    # it out of this output, so the kernel has no second (scalar-shaped)
    # output — the r05 chip session showed Mosaic pads an [n_tiles, 1]
    # SMEM output window to 512 B/element, overflowing SMEM at bench-shape
    # tile counts (docs/tpu_r05_logs/bench.log: u8[1277952] > 1 MB)
    out_ref[:] = lane_cum + row_excl


def _mps_call(v, d, n_tiles, block_rows, interpret):
    # under shard_map (manual mode) the output varies over the same mesh
    # axes as the inputs; plumb the vma through or check_vma rejects the call
    vma = frozenset(getattr(typeof(v), "vma", frozenset()))
    def _shape(sh):
        return (jax.ShapeDtypeStruct(sh, v.dtype, vma=vma) if vma
                else jax.ShapeDtypeStruct(sh, v.dtype))
    return pl.pallas_call(
        _mps_kernel,
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((block_rows, _LANES), lambda i: (i, 0)),
            pl.BlockSpec((block_rows, _LANES), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((block_rows, _LANES), lambda i: (i, 0)),
        out_shape=_shape(v.shape),
        interpret=interpret,
    )(v, d)


@functools.partial(jax.jit, static_argnames=("block_rows", "interpret"))
def multiply_prefix_sum(
    values: jax.Array,
    d_sorted: jax.Array,
    block_rows: int = 256,
    interpret: bool | None = None,
) -> tuple[jax.Array, jax.Array, int]:
    """TILE-LOCAL inclusive prefix sums of ``values * d_sorted`` (both
    [nnz]) in one streamed pass, plus per-tile totals.

    Returns ``(local, totals, tile)``: ``local`` is [padded] with the
    prefix restarting every ``tile = block_rows * 128`` elements, exactly
    the pair ``types.blocked_boundary_combine`` consumes.

    ``interpret=None`` selects per LOWERING platform via
    ``lax.platform_dependent`` — the compiled Mosaic kernel for TPU,
    interpret mode elsewhere. The old device-probe auto-detect picked
    interpret mode whenever the CURRENT backend was CPU, which silently
    exported interpreter HLO (not the kernel) when lowering for TPU from
    a CPU host (jax.export / AOT)."""
    nnz = values.shape[0]
    tile = block_rows * _LANES
    n_tiles = max(pl.cdiv(nnz, tile), 1)
    padded = n_tiles * tile
    pad = padded - nnz
    v = jnp.pad(values, (0, pad)).reshape(-1, _LANES)
    d = jnp.pad(d_sorted, (0, pad)).reshape(-1, _LANES)

    if interpret is None:
        if not VMA_TRANSPOSE:
            # legacy jax lowers BOTH platform_dependent branches for the
            # current platform, and the compiled-kernel branch hard-fails
            # CPU lowering; fall back to the trace-time backend probe there
            # (losing only the lower-for-TPU-from-CPU-host export case)
            local = _mps_call(v, d, n_tiles, block_rows,
                              interpret=jax.default_backend() != "tpu")
        else:
            local = jax.lax.platform_dependent(
                v, d,
                tpu=functools.partial(_mps_call, n_tiles=n_tiles,
                                      block_rows=block_rows, interpret=False),
                default=functools.partial(_mps_call, n_tiles=n_tiles,
                                          block_rows=block_rows,
                                          interpret=True),
            )
    else:
        local = _mps_call(v, d, n_tiles, block_rows, interpret)
    totals = local.reshape(n_tiles, -1)[:, -1]
    return local.reshape(-1), totals, tile


def paged_gather_score(table: jax.Array, slots: jax.Array,
                       indices: jax.Array, values: jax.Array) -> jax.Array:
    """Per-row margin of a batch against a device-resident paged entity
    table: ``out[i] = sum_j table[slots[i], indices[i, j]] * values[i, j]``
    with ``slots[i] < 0`` (no resident entity model) scoring exactly 0.

    ``table`` is the paged coefficient buffer flattened to ``[S, D]``
    (``S = pages * page_rows`` slots, ``D`` dense global-feature dims);
    ``slots`` int32 ``[B]``; ``indices`` int32 / ``values`` ``[B, k]``
    are the batch's resolved sparse features for the table's shard.

    Lowering: ONE flat ``table_gather`` over ``slot * D + index`` — the
    same gather idiom as the margin kernels (``types.table_gather``), so
    the whole random-effect score is a single [B*k] gather + row-sum with
    no ``[B, D]`` dense intermediate and no host round-trip. Serving's
    fused executable calls this once per random coordinate per batch."""
    from photon_ml_tpu.types import table_gather

    dim = table.shape[-1]
    safe = jnp.maximum(slots, 0).astype(jnp.int32)
    flat_idx = safe[:, None] * dim + indices
    picked = table_gather(table.reshape(-1), flat_idx)  # [B, k]
    score = jnp.sum(picked * values, axis=-1)
    return jnp.where(slots >= 0, score, jnp.zeros((), table.dtype))


def csc_transpose_apply_pallas(csc, d: jax.Array) -> jax.Array:
    """``X^T d`` from the column-sorted view with the fused Pallas per-tile
    scan + the shared blocked boundary combine (drop-in for
    ``types.csc_transpose_apply``, same accuracy guarantee: error does not
    grow with nnz). The implicit-ones layout materializes a ones vector
    here (the kernel is a two-operand scan); prefer sparse_grad='csc' for
    binary data."""
    from photon_ml_tpu.types import blocked_boundary_combine, table_gather

    dg = table_gather(d, csc.rows)
    values = jnp.ones_like(dg) if csc.values is None else csc.values
    local, totals, tile = multiply_prefix_sum(values, dg)
    out = blocked_boundary_combine(local, totals, csc.col_starts, tile)
    return out.astype(d.dtype)
