"""Pallas TPU kernels for the sparse-gradient hot path.

The scatter-free CSC gradient (``types.CSCTranspose``) is bottlenecked by a
length-nnz prefix sum: XLA lowers ``jnp.cumsum`` over tens of millions of
elements to several log-tree passes over HBM. The kernel here streams the
array once: a sequential 1-D grid over row tiles with a running carry in
SMEM, computing each tile's inclusive scan as two small lower-triangular
**matmuls on the MXU** (cumsum-as-matmul — the TPU-native scan idiom; no
unsupported vector shifts or gathers), and fusing the
``contrib = values * d_gathered`` multiply into the same pass so the
contribution vector is never materialized in HBM.

Why matmul: a [T, 128] tile's per-lane inclusive prefix is ``x @ L`` with
``L[a, b] = 1 if a <= b``; the running offset across the tile's rows is a
strict-lower-triangular matmul of the per-row totals. Both hit the MXU with
static shapes.

Falls back to interpret mode off-TPU (CPU tests run the same kernel code).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANES = 128


def _mps_kernel(v_ref, d_ref, out_ref, carry_ref):
    """One [T, 128] tile of the fused multiply + inclusive prefix sum."""
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        carry_ref[0, 0] = jnp.zeros((), v_ref.dtype)

    x = v_ref[:] * d_ref[:]  # fused contribution product
    rows = x.shape[0]
    dtype = x.dtype

    # match_vma: in interpret mode (CPU tests) the kernel body runs under
    # shard_map's varying-axis tracking, where fresh iota constants are
    # unvarying and may not meet varying data in a dot; on the compiled TPU
    # path the kernel traces standalone and this is a no-op.
    from photon_ml_tpu.optimize.common import match_vma

    # inclusive prefix along lanes: x @ L, L[a, b] = (a <= b)
    a = jax.lax.broadcasted_iota(jnp.int32, (_LANES, _LANES), 0)
    b = jax.lax.broadcasted_iota(jnp.int32, (_LANES, _LANES), 1)
    lane_cum = jnp.dot(x, match_vma((a <= b).astype(dtype), x),
                       preferred_element_type=dtype)

    # running offset across rows: strict lower-triangular matmul of row sums
    row_tot = lane_cum[:, _LANES - 1:_LANES]  # [rows, 1]
    ra = jax.lax.broadcasted_iota(jnp.int32, (rows, rows), 0)
    rb = jax.lax.broadcasted_iota(jnp.int32, (rows, rows), 1)
    row_excl = jnp.dot(match_vma((rb < ra).astype(dtype), x), row_tot,
                       preferred_element_type=dtype)  # [rows, 1]

    carry = carry_ref[0, 0]
    out_ref[:] = lane_cum + row_excl + carry
    carry_ref[0, 0] = carry + row_excl[rows - 1, 0] + row_tot[rows - 1, 0]


@functools.partial(jax.jit, static_argnames=("block_rows", "interpret"))
def multiply_prefix_sum(
    values: jax.Array,
    d_sorted: jax.Array,
    block_rows: int = 256,
    interpret: bool | None = None,
) -> jax.Array:
    """Inclusive prefix sum of ``values * d_sorted`` (both [nnz]) in one
    streamed pass. ``interpret=None`` auto-selects interpret mode off-TPU."""
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    nnz = values.shape[0]
    tile = block_rows * _LANES
    padded = max(pl.cdiv(nnz, tile), 1) * tile
    pad = padded - nnz
    v = jnp.pad(values, (0, pad)).reshape(-1, _LANES)
    d = jnp.pad(d_sorted, (0, pad)).reshape(-1, _LANES)

    # under shard_map (manual mode) the output varies over the same mesh
    # axes as the inputs; plumb the vma through or check_vma rejects the call
    vma = frozenset(getattr(jax.typeof(v), "vma", frozenset()))
    out_shape = (jax.ShapeDtypeStruct(v.shape, v.dtype, vma=vma) if vma
                 else jax.ShapeDtypeStruct(v.shape, v.dtype))
    out = pl.pallas_call(
        _mps_kernel,
        grid=(padded // tile,),
        in_specs=[
            pl.BlockSpec((block_rows, _LANES), lambda i: (i, 0)),
            pl.BlockSpec((block_rows, _LANES), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((block_rows, _LANES), lambda i: (i, 0)),
        out_shape=out_shape,
        scratch_shapes=[pltpu.SMEM((1, 1), v.dtype)],
        interpret=interpret,
    )(v, d)
    return out.reshape(-1)[:nnz]


def csc_transpose_apply_pallas(csc, d: jax.Array) -> jax.Array:
    """``X^T d`` from the column-sorted view with the fused Pallas scan
    (drop-in for ``types.csc_transpose_apply``). The implicit-ones layout
    materializes a ones vector here (the kernel is a two-operand scan);
    prefer sparse_grad='csc' for binary data."""
    values = (jnp.ones_like(d[csc.rows]) if csc.values is None
              else csc.values)
    prefix_incl = multiply_prefix_sum(values, d[csc.rows])
    prefix = jnp.concatenate([jnp.zeros((1,), prefix_incl.dtype), prefix_incl])
    out = prefix[csc.col_starts[1:]] - prefix[csc.col_starts[:-1]]
    return out.astype(d.dtype)
