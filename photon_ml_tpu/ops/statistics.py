"""Per-feature summary statistics.

Equivalent of the reference's ``stat.BasicStatisticalSummary`` (SURVEY.md
§3.1; reference mount empty): per-feature mean, variance, min/max, nonzero
count — feeding normalization contexts and the feature-summarization output
(``FeatureSummarizationResultAvro``). Computed on device with weighted sums;
sparse features are handled without densifying (zeros counted analytically).
"""

from __future__ import annotations

import dataclasses

import numpy as np

from photon_ml_tpu.types import LabeledBatch


@dataclasses.dataclass(frozen=True)
class FeatureSummary:
    mean: np.ndarray
    variance: np.ndarray
    std: np.ndarray
    min: np.ndarray
    max: np.ndarray
    num_nonzeros: np.ndarray
    count: int

    @property
    def dim(self) -> int:
        return self.mean.shape[0]


def summarize_features(batch: LabeledBatch) -> FeatureSummary:
    """Unweighted per-feature moments (matching the reference's summary used
    for normalization; weights affect training, not summarization).

    Accumulates on host in float64 regardless of the device dtype: with f32
    accumulation the E[x^2]-E[x]^2 subtraction loses the variance entirely
    for large-mean features, which would silently corrupt standardization.
    Summarization is a one-shot preprocessing stage (a dedicated job in the
    reference — SURVEY.md §4.1), so host-side f64 is the right trade."""
    feats = batch.features
    n = batch.num_examples
    # duck-typed so host-resident HostSparse (the streaming path, which never
    # moves the training set to device) summarizes identically
    if hasattr(feats, "indices"):
        d = feats.dim
        flat_idx = np.asarray(feats.indices).reshape(-1)
        if feats.values is None:
            # implicit-ones layout: every slot is a real 1.0 feature, so
            # s1 == s2 == nnz == bincount and max == min == 1 where present
            # (no n*k float materialization — the layout exists to avoid it)
            nnz = np.bincount(flat_idx, minlength=d).astype(np.float64)
            s1 = nnz.copy()
            s2 = nnz.copy()
            mx = np.where(nnz > 0, 1.0, -np.inf)
            mn = np.where(nnz > 0, 1.0, np.inf)
        else:
            flat_val = np.asarray(feats.values, np.float64).reshape(-1)
            present = flat_val != 0.0
            idx, val = flat_idx[present], flat_val[present]
            s1 = np.zeros(d)
            s2 = np.zeros(d)
            nnz = np.zeros(d)
            np.add.at(s1, idx, val)
            np.add.at(s2, idx, val**2)
            np.add.at(nnz, idx, 1.0)
            mx = np.full(d, -np.inf)
            mn = np.full(d, np.inf)
            np.maximum.at(mx, idx, val)
            np.minimum.at(mn, idx, val)
        # features absent from a row are implicit zeros
        has_zero = nnz < n
        mx = np.where(has_zero, np.maximum(mx, 0.0), mx)
        mn = np.where(has_zero, np.minimum(mn, 0.0), mn)
        mx = np.where(np.isfinite(mx), mx, 0.0)
        mn = np.where(np.isfinite(mn), mn, 0.0)
    else:
        X = np.asarray(feats, np.float64)
        d = X.shape[1]
        s1 = X.sum(axis=0)
        s2 = (X**2).sum(axis=0)
        nnz = (X != 0.0).sum(axis=0).astype(np.float64)
        mx = X.max(axis=0) if n else np.zeros(d)
        mn = X.min(axis=0) if n else np.zeros(d)
    mean = s1 / max(n, 1)
    var = np.maximum(s2 / max(n, 1) - mean**2, 0.0)
    return FeatureSummary(
        mean=mean,
        variance=var,
        std=np.sqrt(var),
        min=mn,
        max=mx,
        num_nonzeros=nnz,
        count=n,
    )


def summarize_features_streamed(chunks, dim: int, num_rows: int,
                                total_rows: int = None,
                                part_reduce=None) -> FeatureSummary:
    """``summarize_features`` over ONE streamed pass of a chunk source
    (``parallel.streaming.HostChunk`` iterable — in-RAM lists or the
    disk-backed ``io.stream_source.AvroChunkSource``): per-feature f64
    moments accumulate across chunks, so out-of-core shards can feed
    normalization contexts without a resident copy.

    ``num_rows`` is the REAL dataset row count: chunks are fixed-shape
    with trailing padding rows in the final chunk, and padding must not
    count as rows of implicit zeros (it would bias means/variances). A
    genuine weight-0 row, by contrast, still counts — summarization is
    unweighted, matching the in-RAM function.

    Multi-controller runs stream only the local process part: pass the
    GLOBAL row count as ``total_rows`` (``num_rows`` stays the LOCAL count
    that caps final-chunk padding) and a ``part_reduce(s1, s2, nnz, mx,
    mn)`` that all-reduces the raw moments across processes
    (``multihost.allreduce_summary_moments``) — otherwise each process
    would finalize a summary of only its own rows and normalization
    contexts would silently diverge between processes."""
    s1 = np.zeros(dim)
    s2 = np.zeros(dim)
    nnz = np.zeros(dim)
    mx = np.full(dim, -np.inf)
    mn = np.full(dim, np.inf)
    at = 0
    for c in chunks:
        rows = c.indices.shape[0]
        live = max(0, min(rows, num_rows - at))
        at += rows
        if live == 0:
            continue
        idxs = np.asarray(c.indices[:live]).reshape(-1)
        if c.values is None:
            # implicit-ones: every slot is a real 1.0 feature
            cnt = np.bincount(idxs, minlength=dim).astype(np.float64)
            s1 += cnt
            s2 += cnt
            nnz += cnt
            mx = np.where(cnt > 0, np.maximum(mx, 1.0), mx)
            mn = np.where(cnt > 0, np.minimum(mn, 1.0), mn)
        else:
            vals = np.asarray(c.values[:live], np.float64).reshape(-1)
            present = vals != 0.0
            idx, val = idxs[present], vals[present]
            np.add.at(s1, idx, val)
            np.add.at(s2, idx, val ** 2)
            np.add.at(nnz, idx, 1.0)
            np.maximum.at(mx, idx, val)
            np.minimum.at(mn, idx, val)
    if part_reduce is not None:
        s1, s2, nnz, mx, mn = part_reduce(s1, s2, nnz, mx, mn)
    n = num_rows if total_rows is None else total_rows
    has_zero = nnz < n
    mx = np.where(has_zero, np.maximum(mx, 0.0), mx)
    mn = np.where(has_zero, np.minimum(mn, 0.0), mn)
    mx = np.where(np.isfinite(mx), mx, 0.0)
    mn = np.where(np.isfinite(mn), mn, 0.0)
    mean = s1 / max(n, 1)
    var = np.maximum(s2 / max(n, 1) - mean ** 2, 0.0)
    return FeatureSummary(mean=mean, variance=var, std=np.sqrt(var),
                          min=mn, max=mx, num_nonzeros=nnz, count=n)
