"""Feature normalization applied inside the objective, never materialized.

Equivalent of the reference's ``normalization.{NormalizationContext,
NormalizationType}`` (SURVEY.md §3.1; reference mount empty). The key trick is
identical in spirit: for normalized features ``x'_j = (x_j - s_j) * f_j`` the
margin factors as

    x' . w = x . (f * w) - sum_j s_j f_j w_j

so instead of transforming the (huge, sparse) data we transform the (small,
dense) coefficient vector once per optimizer iteration and fold the shift term
into the intercept. ``to_model_space`` converts optimizer-space coefficients to
raw-feature-space coefficients for saving; ``to_training_space`` is the inverse
(warm start).
"""

from __future__ import annotations

import enum
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct


class NormalizationType(str, enum.Enum):
    NONE = "none"
    SCALE_WITH_STANDARD_DEVIATION = "scale_with_standard_deviation"
    SCALE_WITH_MAX_MAGNITUDE = "scale_with_max_magnitude"
    STANDARDIZATION = "standardization"


@struct.dataclass
class NormalizationContext:
    """factors/shifts over the feature axis; ``intercept_index`` is the column
    holding the constant-1 intercept feature (-1 if none). STANDARDIZATION
    requires an intercept (the shift term must land somewhere)."""

    factors: Optional[jax.Array]  # [d] or None
    shifts: Optional[jax.Array]  # [d] or None
    intercept_index: int = struct.field(pytree_node=False, default=-1)

    def model_coefficients(self, w: jax.Array) -> Tuple[jax.Array, jax.Array]:
        """Map optimizer-space w to (w_eff, margin_adjust) applied to RAW x:
        margin_normalized(x) = x . w_eff + margin_adjust."""
        w_eff = w
        adjust = jnp.zeros((), w.dtype)
        if self.factors is not None:
            f = self.factors
            if self.intercept_index >= 0:
                f = f.at[self.intercept_index].set(1.0)
            w_eff = w_eff * f
        if self.shifts is not None:
            s = self.shifts
            if self.intercept_index >= 0:
                s = s.at[self.intercept_index].set(0.0)
            adjust = -jnp.sum(s * w_eff)
        return w_eff, adjust

    def to_model_space(self, w: jax.Array) -> jax.Array:
        """Optimizer-space coefficients -> raw-feature-space model."""
        if self.shifts is not None and self.intercept_index < 0:
            # with no intercept to absorb it, the shift adjustment would be
            # silently dropped and every saved-model prediction off by it
            raise ValueError("shift normalization requires an intercept feature")
        w_eff, adjust = self.model_coefficients(w)
        if self.intercept_index >= 0:
            w_eff = w_eff.at[self.intercept_index].add(adjust)
        return w_eff

    def to_training_space(self, w_model: jax.Array) -> jax.Array:
        """Inverse of to_model_space (for warm starts)."""
        w = w_model
        if self.shifts is not None:
            s = self.shifts
            if self.intercept_index >= 0:
                s = s.at[self.intercept_index].set(0.0)
            # undo the intercept fold: adjust was -sum(s * w_eff_nonint)
            if self.intercept_index >= 0:
                w_no_int = w.at[self.intercept_index].set(0.0)
                w = w.at[self.intercept_index].add(jnp.sum(s * w_no_int))
        if self.factors is not None:
            f = self.factors
            if self.intercept_index >= 0:
                f = f.at[self.intercept_index].set(1.0)
            w = w / f
        return w


def no_normalization() -> Optional[NormalizationContext]:
    return None


def build_normalization_context(
    norm_type: NormalizationType | str,
    summary,
    intercept_index: int = -1,
) -> Optional[NormalizationContext]:
    """Build from a per-feature :class:`~photon_ml_tpu.ops.statistics.FeatureSummary`
    (mirrors the reference's NormalizationContext factory — SURVEY.md §3.1)."""
    norm_type = NormalizationType(norm_type)
    if norm_type == NormalizationType.NONE:
        return None
    std = np.asarray(summary.std)
    safe_std = np.where(std > 0, std, 1.0)
    if norm_type == NormalizationType.SCALE_WITH_STANDARD_DEVIATION:
        return NormalizationContext(jnp.asarray(1.0 / safe_std), None, intercept_index)
    if norm_type == NormalizationType.SCALE_WITH_MAX_MAGNITUDE:
        mx = np.maximum(np.abs(np.asarray(summary.max)), np.abs(np.asarray(summary.min)))
        mx = np.where(mx > 0, mx, 1.0)
        return NormalizationContext(jnp.asarray(1.0 / mx), None, intercept_index)
    if norm_type == NormalizationType.STANDARDIZATION:
        if intercept_index < 0:
            raise ValueError("STANDARDIZATION requires an intercept feature")
        return NormalizationContext(
            jnp.asarray(1.0 / safe_std), jnp.asarray(np.asarray(summary.mean)), intercept_index
        )
    raise ValueError(f"unhandled normalization type {norm_type}")
