"""Pointwise GLM losses as pure functions of (margin, label).

TPU-native equivalent of the reference's ``PointwiseLossFunction`` family
(``function.glm.{LogisticLossFunction, SquaredLossFunction,
PoissonLossFunction, SmoothedHingeLossFunction}`` — SURVEY.md §3.1; reference
mount empty, paths unverified). The reference hand-codes first/second
derivatives w.r.t. the margin (``lossAndDzLoss`` / ``DzzLoss``); here autodiff
supplies them, and we additionally expose closed-form ``d2`` for the diagonal
Hessian / variance path where the second derivative is cheap and stable.

Labels follow the reference's conventions: binary tasks use {0, 1} labels
(internally mapped to ±1 where needed), regression uses real labels, Poisson
uses non-negative counts.
"""

from __future__ import annotations

import dataclasses
from typing import Callable

import jax
import jax.numpy as jnp


def apply_weights(weights, per_example):
    """``w * l`` per example with exact-zero weights annihilating
    non-finite losses. Zero-weight rows are the framework's padding
    mechanism (mesh.pad_batch, streaming chunks, CD fixed states); under
    the implicit-ones layout padding rows carry arbitrary margins (k
    copies of feature 0), so e.g. a Poisson ``exp(margin)`` overflow would
    turn ``0 * inf`` into NaN and poison the whole sum.

    VALUE protection only: reverse-mode AD through this ``where`` still
    multiplies the pad-branch cotangent (0) by the upstream loss
    derivative, and ``0 * inf = NaN`` (the classic double-where pitfall).
    Every differentiated path must therefore ALSO run its margins through
    :func:`mask_margins` before the loss touches them."""
    return jnp.where(weights != 0, weights * per_example, 0.0)


def mask_margins(weights, margins):
    """Zero the margin on exactly-zero-weight (padding) rows BEFORE the
    loss is evaluated. ``loss(0, label)`` is finite for every loss family,
    so with masked margins no pad-row intermediate is ever non-finite and
    gradients/HVPs through :func:`apply_weights` stay finite (masking only
    the loss value is not enough — see the double-where note there).
    Differentiating through this ``where`` hard-zeroes pad-row cotangents,
    which is exactly the weight-0 semantics."""
    return jnp.where(weights != 0, margins, 0.0)


@dataclasses.dataclass(frozen=True)
class PointwiseLoss:
    """A pointwise loss: per-example ``loss(margin, label)`` plus the inverse
    link ``mean(margin)`` used for scoring, and the margin second derivative
    ``d2`` used by diagonal-Hessian variance computation."""

    name: str
    loss: Callable[[jax.Array, jax.Array], jax.Array]
    mean: Callable[[jax.Array], jax.Array]
    d2: Callable[[jax.Array, jax.Array], jax.Array]


def _logistic_loss(margin, label):
    # -log p(y|m) for y in {0,1}, p = sigmoid(m); stable via logaddexp.
    return jnp.logaddexp(0.0, margin) - label * margin


def _logistic_d2(margin, label):
    p = jax.nn.sigmoid(margin)
    return p * (1.0 - p)


def _squared_loss(margin, label):
    return 0.5 * (margin - label) ** 2


def _poisson_loss(margin, label):
    # NLL of Poisson with rate exp(m), dropping the label-only term log(y!).
    return jnp.exp(margin) - label * margin


def _smoothed_hinge_loss(margin, label):
    # Rennie's smoothed hinge on z = (2y-1)*m:
    #   1/2 - z      z <= 0
    #   (1-z)^2 / 2  0 < z < 1
    #   0            z >= 1
    z = (2.0 * label - 1.0) * margin
    return jnp.where(z <= 0.0, 0.5 - z, jnp.where(z < 1.0, 0.5 * (1.0 - z) ** 2, 0.0))


def _smoothed_hinge_d2(margin, label):
    z = (2.0 * label - 1.0) * margin
    return jnp.where((z > 0.0) & (z < 1.0), 1.0, 0.0)


LOGISTIC = PointwiseLoss("logistic", _logistic_loss, jax.nn.sigmoid, _logistic_d2)
SQUARED = PointwiseLoss("squared", _squared_loss, lambda m: m, lambda m, y: jnp.ones_like(m))
POISSON = PointwiseLoss("poisson", _poisson_loss, jnp.exp, lambda m, y: jnp.exp(m))
SMOOTHED_HINGE = PointwiseLoss(
    "smoothed_hinge",
    _smoothed_hinge_loss,
    lambda m: (m + 1.0) * 0.5,  # affine score->[~0,1] mapping for ranking metrics
    _smoothed_hinge_d2,
)

_REGISTRY = {
    "logistic": LOGISTIC,
    "squared": SQUARED,
    "linear": SQUARED,
    "poisson": POISSON,
    "smoothed_hinge": SMOOTHED_HINGE,
    "hinge": SMOOTHED_HINGE,
}

# The reference's TaskType enum (LOGISTIC_REGRESSION, LINEAR_REGRESSION,
# POISSON_REGRESSION, SMOOTHED_HINGE_LOSS_LINEAR_SVM — SURVEY.md §1).
TASK_TO_LOSS = {
    "logistic_regression": "logistic",
    "linear_regression": "squared",
    "poisson_regression": "poisson",
    "smoothed_hinge_loss_linear_svm": "smoothed_hinge",
}


def get_loss(name: str) -> PointwiseLoss:
    key = name.lower()
    if key in TASK_TO_LOSS:
        key = TASK_TO_LOSS[key]
    if key not in _REGISTRY:
        raise ValueError(f"unknown loss '{name}'; known: {sorted(_REGISTRY)}")
    return _REGISTRY[key]
