"""GAME model save/load as Avro (the model persistence contract).

Equivalent of the reference's ``data.avro.ModelProcessingUtils``
(SURVEY.md §3.3/§4.1; reference mount empty): a GAME model is saved as one
``BayesianLinearModelAvro`` per fixed effect plus one per entity in each
random effect, with coefficients as name/term/value records resolved through
the feature index maps; loading reverses the mapping. Layout:

    <dir>/metadata.json                    (task, coordinate order/types)
    <dir>/fixed-effect/<name>/coefficients.avro
    <dir>/random-effect/<name>/coefficients.avro

Coefficient name/term resolution uses the shard's index map; saving also
persists the index maps so a model directory is self-contained.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List

import numpy as np

from photon_ml_tpu.io.avro import read_avro_file, write_avro_file
from photon_ml_tpu.io.index_map import IndexMap
from photon_ml_tpu.io.schemas import (
    BAYESIAN_LINEAR_MODEL_SCHEMA,
    split_feature_key,
)
from photon_ml_tpu.models import (
    Coefficients,
    FixedEffectModel,
    GameModel,
    GeneralizedLinearModel,
    RandomEffectBucket,
    RandomEffectModel,
)

import jax.numpy as jnp


def _sketch_records(w: np.ndarray):
    w = np.asarray(w)
    return [{"name": f"(SKETCH {j})", "term": "", "value": float(w[j])}
            for j in np.nonzero(w)[0]]


def _model_index_of(imap, name: str, term: str):
    """Model-load index resolution: lets backends recognize synthetic
    coefficient names they wrote (e.g. HashingIndexMap's ``(HASH n)``)
    without exposing that aliasing to data ingestion."""
    fn = getattr(imap, "model_index_of", None)
    return fn(name, term) if fn is not None else imap.index_of(name, term)


def _coef_records(w: np.ndarray, inverse: Dict[int, str]):
    out = []
    for idx in np.nonzero(w)[0]:
        name, term = split_feature_key(inverse[int(idx)])
        out.append({"name": name, "term": term, "value": float(w[idx])})
    return out


def save_game_model(
    model: GameModel,
    directory: str,
    index_maps: IndexMap | Dict[str, IndexMap],
) -> None:
    """Atomic for fresh paths: the tree is written into a sibling tmp dir
    and renamed into place, so a crash mid-save (device loss during the
    d2h reads, SIGKILL) can never leave a half-written model where
    resume/scoring would find it. Overwrites swap via two renames; a
    crash in that window leaves the previous COMPLETE tree at
    '{path}.old-{pid}', which checkpoint discovery counts as its base
    name (game_training_driver._latest_checkpoint).

    Entity-sharded training (docs/sharding.md) keeps this single-file
    layout unchanged: ``descent._build_model`` gathers every shard's
    random-effect buckets into the full table at each save point, so the
    ``model`` every process hands here is already complete — only the
    lead process should actually call this (shared output path), which
    the drivers enforce."""
    import shutil

    tmp = f"{directory}.tmp-{os.getpid()}"
    if os.path.isdir(tmp):
        shutil.rmtree(tmp)
    try:
        _save_game_model_tree(model, tmp, index_maps)
    except BaseException:
        # an interrupted save must leave NOTHING a loader, the registry,
        # or checkpoint discovery could ingest — not even the tmp tree
        # (a crash that skips this unwind leaves only a '.tmp-' name,
        # which every consumer already ignores)
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    if os.path.isdir(directory):  # overwrite: swap out the old tree
        old = f"{directory}.old-{os.getpid()}"
        os.rename(directory, old)
        os.rename(tmp, directory)
        shutil.rmtree(old)
    else:
        os.rename(tmp, directory)


def _save_game_model_tree(
    model: GameModel,
    directory: str,
    index_maps: IndexMap | Dict[str, IndexMap],
) -> None:
    from photon_ml_tpu.parallel import fault_injection

    if not isinstance(index_maps, dict):  # any IndexMap-like backend
        index_maps = {"global": index_maps}
    os.makedirs(directory, exist_ok=True)
    meta = {"task": model.task, "coordinates": []}
    for name, coord in model.coordinates.items():
        # injection site: a crash mid-save (device loss during the d2h
        # reads, SIGKILL) — the tier-1 crash-safety test arms this and
        # asserts no half-written tree is ever visible at the final path
        fault_injection.check("model_io.save_coordinate")
        imap = index_maps[coord.feature_shard]
        inverse = imap.inverse()
        if isinstance(coord, FixedEffectModel):
            sub = os.path.join(directory, "fixed-effect", name)
            os.makedirs(sub, exist_ok=True)
            w = np.asarray(coord.model.coefficients.means)
            var = coord.model.coefficients.variances
            rec = {
                "modelId": name,
                "modelClass": "FixedEffectModel",
                "means": _coef_records(w, inverse),
                "variances": None if var is None else _coef_records(
                    np.asarray(var), inverse
                ),
                "lossFunction": model.task,
            }
            write_avro_file(os.path.join(sub, "coefficients.avro"), [rec],
                            BAYESIAN_LINEAR_MODEL_SCHEMA)
            meta["coordinates"].append(
                {"name": name, "type": "fixed", "feature_shard": coord.feature_shard}
            )
        else:
            sub = os.path.join(directory, "random-effect", name)
            os.makedirs(sub, exist_ok=True)

            def records():
                for bucket in coord.buckets:
                    proj = np.asarray(bucket.projection)
                    coefs = np.asarray(bucket.coefficients)
                    variances = (
                        None if bucket.variances is None else np.asarray(bucket.variances)
                    )
                    for r, eid in enumerate(bucket.entity_ids):
                        if bucket.sketch is not None:
                            # sketched space is non-invertible: save per-slot
                            # coefficients under synthetic (SKETCH j) names
                            rec = {
                                "modelId": str(eid),
                                "modelClass": "RandomEffectModel",
                                "means": _sketch_records(coefs[r]),
                                "variances": None if variances is None
                                else _sketch_records(variances[r]),
                                "lossFunction": model.task,
                            }
                            yield rec
                            continue
                        valid = proj[r] >= 0
                        w = np.zeros(imap.size)
                        w[proj[r][valid]] = coefs[r][valid]
                        rec = {
                            "modelId": str(eid),
                            "modelClass": "RandomEffectModel",
                            "means": _coef_records(w, inverse),
                            "variances": None,
                            "lossFunction": model.task,
                        }
                        if variances is not None:
                            v = np.zeros(imap.size)
                            v[proj[r][valid]] = variances[r][valid]
                            rec["variances"] = _coef_records(v, inverse)
                        yield rec

            write_avro_file(os.path.join(sub, "coefficients.avro"), records(),
                            BAYESIAN_LINEAR_MODEL_SCHEMA)
            entry = {"name": name, "type": "random",
                     "feature_shard": coord.feature_shard,
                     "entity_column": coord.entity_column}
            sketches = [b.sketch for b in coord.buckets if b.sketch is not None]
            if sketches:
                entry["projection"] = {"type": "random",
                                       "dim": sketches[0].dim,
                                       "seed": sketches[0].seed}
            meta["coordinates"].append(entry)
        # persist the shard's index map alongside the model
        imap.save(os.path.join(directory, f"index-map.{coord.feature_shard}.json"))
    # last write wins: metadata.json is the completeness marker loaders
    # look for, so it lands only after every coefficient file
    fault_injection.check("model_io.save_metadata")
    with open(os.path.join(directory, "metadata.json"), "w") as f:
        json.dump(meta, f, indent=2)


def load_model_metadata(directory: str) -> dict:
    """The model directory's ``metadata.json`` payload (task + coordinate
    order/types) — shared by :func:`load_game_model` and the serving
    session, which loads coordinates selectively."""
    with open(os.path.join(directory, "metadata.json")) as f:
        return json.load(f)


def load_model_index_map(directory: str, shard: str):
    """Open one shard's persisted index map (either backend — JSON or the
    native paldb-style store) from a saved model directory."""
    from photon_ml_tpu.io.paldb import load_index_map

    return load_index_map(os.path.join(directory, f"index-map.{shard}.json"))


def read_random_effect_records(directory: str, name: str):
    """All BayesianLinearModelAvro records of one random-effect coordinate
    (one record per entity). The serving coefficient cache reads through
    this so its decode can never diverge from :func:`load_game_model`."""
    path = os.path.join(directory, "random-effect", name,
                        "coefficients.avro")
    records, _ = read_avro_file(path)
    return records


def entity_support_from_record(rec, imap: IndexMap):
    """Parse ONE RandomEffectModel record into its (sorted global feature
    ids, matching coefficient values) support — the per-entity payload the
    bulk rebuild and the serving entity-coefficient cache share. Sorting
    ascending fixes the local-slot order, so a cache entry's slot map is
    identical to the loaded model's projection row."""
    ids, vals = [], []
    for coef in rec["means"]:
        idx = _model_index_of(imap, coef["name"], coef.get("term", ""))
        if idx is not None:
            ids.append(idx)
            vals.append(coef["value"])
    order = np.argsort(ids)
    return (np.asarray(ids, np.int64)[order],
            np.asarray(vals, np.float64)[order])


def sketch_coefficients_from_record(rec, dim: int) -> np.ndarray:
    """Dense sketched-space coefficient vector of one RandomEffectModel
    record saved under synthetic ``(SKETCH j)`` slot names."""
    w = np.zeros(dim)
    for coef in rec["means"]:
        nm = coef["name"]
        if nm.startswith("(SKETCH ") and nm.endswith(")"):
            w[int(nm[len("(SKETCH "):-1])] = coef["value"]
    return w


def load_fixed_effect_coordinate(directory: str, name: str, imap: IndexMap,
                                 task: str, shard: str) -> FixedEffectModel:
    """Rebuild one fixed-effect coordinate from its saved record (shared
    by the bulk load and the serving session, which loads fixed effects
    eagerly but random effects through its coefficient cache)."""
    path = os.path.join(directory, "fixed-effect", name, "coefficients.avro")
    records, _ = read_avro_file(path)
    rec = records[0]
    w = np.zeros(imap.size)
    for coef in rec["means"]:
        idx = _model_index_of(imap, coef["name"], coef.get("term", ""))
        if idx is not None:
            w[idx] = coef["value"]
    var = None
    if rec.get("variances"):
        var = np.zeros(imap.size)
        for coef in rec["variances"]:
            idx = _model_index_of(imap, coef["name"], coef.get("term", ""))
            if idx is not None:
                var[idx] = coef["value"]
    return FixedEffectModel(
        GeneralizedLinearModel(
            Coefficients(jnp.asarray(w),
                         None if var is None else jnp.asarray(var)),
            task,
        ),
        shard,
    )


def load_game_model(directory: str) -> GameModel:
    meta = load_model_metadata(directory)
    index_maps: Dict[str, IndexMap] = {}
    coords = {}
    for c in meta["coordinates"]:
        shard = c["feature_shard"]
        if shard not in index_maps:
            index_maps[shard] = load_model_index_map(directory, shard)
        imap = index_maps[shard]
        if c["type"] == "fixed":
            coords[c["name"]] = load_fixed_effect_coordinate(
                directory, c["name"], imap, meta["task"], shard)
        else:
            records = read_random_effect_records(directory, c["name"])
            coords[c["name"]] = _rebuild_random_effect(
                c["name"], records, imap, meta["task"], shard,
                c.get("entity_column", ""), c.get("projection"),
            )
    return GameModel(coords, meta["task"])


def _rebuild_random_effect(name, records, imap: IndexMap, task, shard,
                           entity_column="", projection_meta=None) -> RandomEffectModel:
    """Rebuild bucketed per-entity coefficients from per-entity records,
    grouping entities with equal support size into buckets."""
    if projection_meta and projection_meta.get("type") == "random":
        return _rebuild_sketched_random_effect(
            name, records, task, shard, entity_column, projection_meta
        )
    entities: List[tuple] = []
    for rec in records:
        ids, vals = entity_support_from_record(rec, imap)
        variances = {}
        if rec.get("variances"):
            for coef in rec["variances"]:
                idx = _model_index_of(imap, coef["name"], coef.get("term", ""))
                if idx is not None:
                    variances[idx] = coef["value"]
        entities.append((rec["modelId"], ids, vals, variances))
    # bucket by support size
    by_size: Dict[int, List[tuple]] = {}
    for ent in entities:
        by_size.setdefault(len(ent[1]), []).append(ent)
    buckets = []
    for size, members in sorted(by_size.items()):
        E, D = len(members), max(size, 1)
        proj = np.full((E, D), -1, np.int32)
        coefs = np.zeros((E, D))
        eids = [m[0] for m in members]
        if size:
            # every member of a bucket has exactly `size` support ids, so
            # the fill is two stacks, not a per-entity Python loop
            # (VERDICT r4 #7 — model load at 100k+ entities)
            proj[:, :size] = np.stack([m[1] for m in members])
            coefs[:, :size] = np.stack([m[2] for m in members])
        has_var = any(m[3] for m in members)
        variances = None
        if has_var:
            variances = np.zeros((E, D))
            if size:
                variances[:, :size] = np.stack([
                    [m[3].get(int(g), 0.0) for g in m[1]] for m in members
                ])
        buckets.append(RandomEffectBucket(eids, coefs, proj, variances))
    return RandomEffectModel(name, buckets, task, shard, entity_column=entity_column)


def _rebuild_sketched_random_effect(name, records, task, shard, entity_column,
                                    projection_meta) -> RandomEffectModel:
    """Rebuild a random-projection effect: coefficients live in the sketched
    space, addressed by (SKETCH j) slot names; one bucket, constant width."""
    from photon_ml_tpu.game.data import SketchProjection

    dim = int(projection_meta["dim"])
    sketch = SketchProjection(dim, int(projection_meta.get("seed", 0)))
    eids, coefs_list, var_list = [], [], []
    has_var = False
    for rec in records:
        w = sketch_coefficients_from_record(rec, dim)
        v = np.zeros(dim)
        if rec.get("variances"):
            has_var = True
            for coef in rec["variances"]:
                nm = coef["name"]
                if nm.startswith("(SKETCH ") and nm.endswith(")"):
                    v[int(nm[len("(SKETCH "):-1])] = coef["value"]
        eids.append(rec["modelId"])
        coefs_list.append(w)
        var_list.append(v)
    E = len(eids)
    bucket = RandomEffectBucket(
        eids,
        np.stack(coefs_list) if E else np.zeros((0, dim)),
        np.full((E, dim), -1, np.int32),
        np.stack(var_list) if has_var else None,
        sketch=sketch,
    )
    return RandomEffectModel(name, [bucket], task, shard,
                             entity_column=entity_column)
