"""Pure-Python Avro binary codec + object container file format.

The reference's external data contract is Avro-on-HDFS (SURVEY.md §3.4:
``TrainingExampleAvro``, ``BayesianLinearModelAvro``, ...); no Avro library
is available in this image, so this module implements the needed subset of
the Avro 1.x specification from scratch: zig-zag varint primitives, the
binary encoding of records/arrays/maps/unions/enums/fixed, and the object
container format (magic ``Obj\\x01``, metadata map with schema + codec,
sync-marker-delimited blocks, null and deflate codecs).

Supports the complete type surface our schemas use and round-trips files
that standard Avro tooling can read (spec-conformant encoding; deflate is
raw zlib per the spec).
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import struct
import zlib
from typing import Any, BinaryIO, Iterable, Iterator, List

MAGIC = b"Obj\x01"

PRIMITIVES = {"null", "boolean", "int", "long", "float", "double", "bytes", "string"}


# -- schema ----------------------------------------------------------------
def parse_schema(schema) -> Any:
    """Normalize a schema (JSON string or dict/list) to dict/list/str form,
    resolving named-type references within the document."""
    if isinstance(schema, str) and schema not in PRIMITIVES:
        schema = json.loads(schema)
    named: dict = {}
    return _resolve(schema, named)


def _resolve(schema, named):
    if isinstance(schema, str):
        if schema in PRIMITIVES:
            return schema
        if schema in named:
            return named[schema]
        raise ValueError(f"unknown type reference '{schema}'")
    if isinstance(schema, list):
        return [_resolve(s, named) for s in schema]
    if isinstance(schema, dict):
        t = schema.get("type")
        if t in ("record", "enum", "fixed"):
            name = schema.get("name")
            if name:
                named[name] = schema
                ns = schema.get("namespace")
                if ns:
                    named[f"{ns}.{name}"] = schema
        if t == "record":
            for f in schema["fields"]:
                f["type"] = _resolve(f["type"], named)
        elif t in ("array",):
            schema["items"] = _resolve(schema["items"], named)
        elif t in ("map",):
            schema["values"] = _resolve(schema["values"], named)
        elif isinstance(t, (dict, list)):
            schema["type"] = _resolve(t, named)
        return schema
    raise ValueError(f"bad schema: {schema!r}")


def _schema_type(schema) -> str:
    if isinstance(schema, str):
        return schema
    if isinstance(schema, list):
        return "union"
    return schema["type"]


def dump_schema(schema) -> str:
    """Serialize a resolved schema to JSON, emitting a *name reference* for
    the second and later occurrences of each named type. ``parse_schema``
    aliases repeated references to one shared dict; naively json.dumps-ing
    that re-defines the named type, which the Avro spec forbids and standard
    tooling rejects ("Can't redefine")."""
    seen: set = set()

    def conv(s):
        if isinstance(s, str):
            return s
        if isinstance(s, list):
            return [conv(b) for b in s]
        t = s.get("type")
        if t in ("record", "enum", "fixed"):
            name = s["name"]
            full = f"{s['namespace']}.{name}" if s.get("namespace") else name
            if full in seen:
                return full
            seen.add(full)
            out = dict(s)
            if t == "record":
                out["fields"] = [dict(f, type=conv(f["type"])) for f in s["fields"]]
            return out
        out = dict(s)
        if t == "array":
            out["items"] = conv(s["items"])
        elif t == "map":
            out["values"] = conv(s["values"])
        elif isinstance(t, (dict, list)):
            out["type"] = conv(t)
        return out

    return json.dumps(conv(schema))


# -- binary primitives -----------------------------------------------------
def _write_long(out: BinaryIO, n: int) -> None:
    n = (n << 1) ^ (n >> 63)  # zig-zag
    while (n & ~0x7F) != 0:
        out.write(bytes([(n & 0x7F) | 0x80]))
        n >>= 7
    out.write(bytes([n & 0x7F]))


def _read_long_or_eof(f: BinaryIO):
    """Read a zig-zag varint; None at clean EOF (zero bytes available).
    A partial varint still raises (truncation is corruption, not EOF)."""
    b = f.read(1)
    if not b:
        return None
    shift = 0
    acc = 0
    while True:
        byte = b[0]
        acc |= (byte & 0x7F) << shift
        if not byte & 0x80:
            break
        shift += 7
        b = f.read(1)
        if not b:
            raise EOFError("truncated varint")
    return (acc >> 1) ^ -(acc & 1)  # un-zig-zag


def _read_long(buf: io.BytesIO) -> int:
    v = _read_long_or_eof(buf)
    if v is None:
        raise EOFError("truncated varint")
    return v


# -- datum encode/decode ---------------------------------------------------
def write_datum(out: BinaryIO, datum, schema) -> None:
    t = _schema_type(schema)
    if t == "null":
        return
    if t == "boolean":
        out.write(b"\x01" if datum else b"\x00")
    elif t in ("int", "long"):
        _write_long(out, int(datum))
    elif t == "float":
        out.write(struct.pack("<f", float(datum)))
    elif t == "double":
        out.write(struct.pack("<d", float(datum)))
    elif t == "bytes":
        raw = bytes(datum)
        _write_long(out, len(raw))
        out.write(raw)
    elif t == "string":
        raw = str(datum).encode("utf-8")
        _write_long(out, len(raw))
        out.write(raw)
    elif t == "record":
        for f in schema["fields"]:
            name = f["name"]
            if isinstance(datum, dict):
                if name in datum:
                    value = datum[name]
                elif "default" in f:
                    value = f["default"]
                else:
                    raise ValueError(f"record field '{name}' missing and no default")
            else:
                value = getattr(datum, name)
            write_datum(out, value, f["type"])
    elif t == "array":
        items = list(datum)
        if items:
            _write_long(out, len(items))
            for item in items:
                write_datum(out, item, schema["items"])
        _write_long(out, 0)
    elif t == "map":
        entries = dict(datum)
        if entries:
            _write_long(out, len(entries))
            for k, v in entries.items():
                write_datum(out, k, "string")
                write_datum(out, v, schema["values"])
        _write_long(out, 0)
    elif t == "union":
        idx = _union_branch(datum, schema)
        _write_long(out, idx)
        write_datum(out, datum, schema[idx])
    elif t == "enum":
        _write_long(out, schema["symbols"].index(datum))
    elif t == "fixed":
        raw = bytes(datum)
        if len(raw) != schema["size"]:
            raise ValueError(f"fixed size mismatch: {len(raw)} != {schema['size']}")
        out.write(raw)
    else:
        raise ValueError(f"unsupported schema type {t!r}")


def _union_branch(datum, union) -> int:
    """Pick the first matching branch (sufficient for our null|X unions)."""
    for i, branch in enumerate(union):
        bt = _schema_type(branch)
        if datum is None and bt == "null":
            return i
        if datum is None:
            continue
        if bt in ("int", "long") and isinstance(datum, int) and not isinstance(datum, bool):
            return i
        if bt in ("float", "double") and isinstance(datum, (int, float)) and not isinstance(datum, bool):
            return i
        if bt == "string" and isinstance(datum, str):
            return i
        if bt == "boolean" and isinstance(datum, bool):
            return i
        if bt == "bytes" and isinstance(datum, (bytes, bytearray)):
            return i
        if bt in ("record", "map") and isinstance(datum, dict):
            return i
        if bt == "array" and isinstance(datum, (list, tuple)):
            return i
        if bt == "enum" and isinstance(datum, str):
            return i
    raise ValueError(f"no union branch for {type(datum)} in {union}")


def read_datum(buf: io.BytesIO, schema):
    t = _schema_type(schema)
    if t == "null":
        return None
    if t == "boolean":
        return buf.read(1) == b"\x01"
    if t in ("int", "long"):
        return _read_long(buf)
    if t == "float":
        return struct.unpack("<f", buf.read(4))[0]
    if t == "double":
        return struct.unpack("<d", buf.read(8))[0]
    if t == "bytes":
        return buf.read(_read_long(buf))
    if t == "string":
        return buf.read(_read_long(buf)).decode("utf-8")
    if t == "record":
        return {f["name"]: read_datum(buf, f["type"]) for f in schema["fields"]}
    if t == "array":
        out: List = []
        while True:
            count = _read_long(buf)
            if count == 0:
                break
            if count < 0:  # block with byte-size prefix
                count = -count
                _read_long(buf)
            for _ in range(count):
                out.append(read_datum(buf, schema["items"]))
        return out
    if t == "map":
        entries = {}
        while True:
            count = _read_long(buf)
            if count == 0:
                break
            if count < 0:
                count = -count
                _read_long(buf)
            for _ in range(count):
                k = read_datum(buf, "string")
                entries[k] = read_datum(buf, schema["values"])
        return entries
    if t == "union":
        return read_datum(buf, schema[_read_long(buf)])
    if t == "enum":
        return schema["symbols"][_read_long(buf)]
    if t == "fixed":
        return buf.read(schema["size"])
    raise ValueError(f"unsupported schema type {t!r}")


# -- object container files ------------------------------------------------
_META_SCHEMA = parse_schema({"type": "map", "values": "bytes"})


def write_avro_file(
    path: str,
    records: Iterable,
    schema,
    codec: str = "deflate",
    block_size: int = 4096,
) -> None:
    """Write an Avro object container file (records per schema)."""
    schema = parse_schema(schema)
    if codec not in ("null", "deflate"):
        raise ValueError(f"unsupported codec '{codec}' (null|deflate)")
    # Deterministic sync marker (schema digest) instead of os.urandom:
    # readers never SCAN for the marker (blocks are length-prefixed; the
    # 16 bytes after each block are compared, not searched), so the only
    # property that matters is stability — and determinism makes two
    # saves of the same model byte-identical, which the registry's
    # per-artifact content fingerprints and delta diffing rely on.
    sync = hashlib.md5(dump_schema(schema).encode()).digest()
    with open(path, "wb") as f:
        f.write(MAGIC)
        meta = {
            "avro.schema": dump_schema(schema).encode(),
            "avro.codec": codec.encode(),
        }
        write_datum(f, meta, _META_SCHEMA)
        f.write(sync)
        block: List[bytes] = []

        def flush():
            if not block:
                return
            payload = b"".join(block)
            if codec == "deflate":
                comp = zlib.compressobj(9, zlib.DEFLATED, -15)
                payload = comp.compress(payload) + comp.flush()
            _write_long(f, len(block))
            _write_long(f, len(payload))
            f.write(payload)
            f.write(sync)
            block.clear()

        for rec in records:
            buf = io.BytesIO()
            write_datum(buf, rec, schema)
            block.append(buf.getvalue())
            if len(block) >= block_size:
                flush()
        flush()


def _read_header(f: BinaryIO, path: str):
    """Read container-file magic + metadata -> (schema, codec, sync)."""
    if f.read(4) != MAGIC:
        raise ValueError(f"{path}: not an Avro object container file")
    meta = read_datum(f, _META_SCHEMA)
    schema = parse_schema(json.loads(meta["avro.schema"].decode()))
    codec = meta.get("avro.codec", b"null").decode()
    if codec not in ("null", "deflate"):
        raise ValueError(f"{path}: unsupported codec '{codec}'")
    sync = f.read(16)
    return schema, codec, sync


def _iter_blocks(f: BinaryIO, path: str, schema, codec: str, sync: bytes) -> Iterator:
    """Yield records from a positioned container file, one block at a time."""
    while True:
        count = _read_long_or_eof(f)
        if count is None:
            return
        size = _read_long(f)
        payload = f.read(size)
        if codec == "deflate":
            payload = zlib.decompress(payload, -15)
        block = io.BytesIO(payload)
        for _ in range(count):
            yield read_datum(block, schema)
        if f.read(16) != sync:
            raise ValueError(f"{path}: sync marker mismatch (corrupt file)")


def stream_avro_file(path: str) -> Iterator:
    """Yield records one sync-delimited block at a time — constant memory in
    the file size (one decompressed block resident at once)."""
    with open(path, "rb") as f:
        schema, codec, sync = _read_header(f, path)
        yield from _iter_blocks(f, path, schema, codec, sync)


def read_avro_schema(path: str):
    """Read just the schema from a container file's header."""
    with open(path, "rb") as f:
        return _read_header(f, path)[0]


def read_avro_file(path: str):
    """Read an Avro object container file -> (records, schema)."""
    with open(path, "rb") as f:
        schema, codec, sync = _read_header(f, path)
        records = list(_iter_blocks(f, path, schema, codec, sync))
    return records, schema


def iter_avro_records(paths: Iterable[str]) -> Iterator:
    """Stream records from one or more Avro files (directory ok),
    block-at-a-time — never materializes a whole file."""
    for path in _expand(paths):
        yield from stream_avro_file(path)


def _expand(paths) -> List[str]:
    if isinstance(paths, (str, os.PathLike)):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        p = str(p)
        if os.path.isdir(p):
            out.extend(
                os.path.join(p, f) for f in sorted(os.listdir(p)) if f.endswith(".avro")
            )
        else:
            out.append(p)
    return out
