"""Pre-training data validation.

Equivalent of the reference's ``DataValidators`` (SURVEY.md §3.3, legacy
classic driver row; reference mount empty, path unverified): sanity checks on
labels / features / offsets / weights run before any compute is spent, with
task-specific label rules (binary labels for logistic and smoothed-hinge,
non-negative counts for Poisson). Checks run on host over the already-decoded
arrays — validation is a one-shot preprocessing stage, not a jit concern.
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np

from photon_ml_tpu.game.data import HostSparse


class DataValidationError(ValueError):
    """Raised when a dataset fails validation; message lists every failure."""


def validate_training_data(
    features,
    labels: np.ndarray,
    offsets: np.ndarray | None = None,
    weights: np.ndarray | None = None,
    task: str = "logistic",
) -> None:
    """Validate one dataset; raises DataValidationError listing all problems.

    ``features`` is a HostSparse, a dense [n, d] array, or a dict of either
    (per-shard). Rules mirror the reference's validator set:
      * labels finite; binary tasks need labels in {0, 1}; poisson needs >= 0
      * feature values finite
      * offsets finite
      * weights finite and strictly positive
    """
    problems: List[str] = []
    labels = np.asarray(labels)

    if labels.size and not np.all(np.isfinite(labels)):
        problems.append(f"{np.sum(~np.isfinite(labels))} non-finite labels")
    if task in ("logistic", "smoothed_hinge"):
        bad = labels[np.isfinite(labels)]
        bad = bad[(bad != 0.0) & (bad != 1.0)]
        if bad.size:
            problems.append(
                f"{bad.size} labels outside {{0,1}} for binary task "
                f"'{task}' (first: {bad[:3].tolist()})"
            )
    elif task == "poisson":
        neg = np.sum(labels[np.isfinite(labels)] < 0)
        if neg:
            problems.append(f"{neg} negative labels for poisson task")

    shards: Dict[str, object] = (
        features if isinstance(features, dict) else {"global": features}
    )
    for shard, feats in shards.items():
        vals = feats.values if isinstance(feats, HostSparse) else np.asarray(feats)
        if vals.size and not np.all(np.isfinite(vals)):
            problems.append(
                f"{np.sum(~np.isfinite(vals))} non-finite feature values "
                f"in shard '{shard}'"
            )

    if offsets is not None:
        offsets = np.asarray(offsets)
        if offsets.size and not np.all(np.isfinite(offsets)):
            problems.append(f"{np.sum(~np.isfinite(offsets))} non-finite offsets")
    if weights is not None:
        weights = np.asarray(weights)
        if weights.size and not np.all(np.isfinite(weights)):
            problems.append(f"{np.sum(~np.isfinite(weights))} non-finite weights")
        nonpos = np.sum(weights[np.isfinite(weights)] <= 0)
        if nonpos:
            problems.append(f"{nonpos} non-positive weights")

    if problems:
        raise DataValidationError(
            "training data failed validation: " + "; ".join(problems)
        )
