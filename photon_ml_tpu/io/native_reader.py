"""Native (C++) Avro training-example ingestion.

SURVEY.md §7 flags the host-side decode/index pipeline as the likely real
bottleneck at TB scale — the reference leans on the JVM + Spark for decode
throughput (``AvroDataReader``, SURVEY.md §3.3); the TPU-native equivalent
is ``native/avro_decoder.cpp``. This module is the Python half:

1. parse the container header and validate the writer schema shape;
2. compile a compact per-record *field program* (capture opcodes for
   response/offset/weight/uid/features/metadataMap, structural skip opcodes
   for everything else);
3. stream raw block payloads to the decoder via ctypes (the decoder
   inflates and decodes entirely in C++, resolving feature name/term
   against the mmap'd feature index store or by FNV-1a hashing);
4. assemble the columnar outputs into the same values
   ``read_training_examples`` produces.

Any schema shape or index-map backend the native path cannot serve raises
``NativeUnsupported``; ``data_reader`` then silently falls back to the
pure-Python codec (``io/avro.py``), so the native path is a transparent
accelerator, never a new failure mode.
"""

from __future__ import annotations

import ctypes
import os
import tempfile
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from photon_ml_tpu.io.avro import _read_header, _read_long_or_eof, _expand

# capture opcodes (must match avro_decoder.cpp)
_CAP_LABEL_D, _CAP_LABEL_ND = 0x01, 0x02
_CAP_OFFSET_D, _CAP_OFFSET_ND = 0x03, 0x04
_CAP_WEIGHT_D, _CAP_WEIGHT_ND = 0x05, 0x06
_CAP_FEATURES, _CAP_METADATA, _CAP_UID = 0x07, 0x08, 0x09
# skip opcodes
_SKIP = {"null": 0x10, "boolean": 0x11, "int": 0x12, "long": 0x12,
         "float": 0x13, "double": 0x14, "bytes": 0x15, "string": 0x15,
         "enum": 0x12}
_SKIP_UNION, _SKIP_ARRAY, _SKIP_MAP, _SKIP_RECORD = 0x16, 0x17, 0x18, 0x19


class NativeUnsupported(Exception):
    """Schema/backend shape the native decoder does not cover."""


def _stype(schema) -> str:
    if isinstance(schema, str):
        return schema
    if isinstance(schema, list):
        return "union"
    return schema["type"]


def _compile_skip(schema, out: bytearray) -> None:
    t = _stype(schema)
    if t in _SKIP:
        out.append(_SKIP[t])
    elif t == "union":
        if len(schema) > 255:
            raise NativeUnsupported("union too wide")
        out.append(_SKIP_UNION)
        out.append(len(schema))
        for branch in schema:
            _compile_skip(branch, out)
    elif t == "array":
        out.append(_SKIP_ARRAY)
        _compile_skip(schema["items"], out)
    elif t == "map":
        out.append(_SKIP_MAP)
        _compile_skip(schema["values"], out)
    elif t == "record":
        fields = schema["fields"]
        if len(fields) > 255:
            raise NativeUnsupported("record too wide")
        out.append(_SKIP_RECORD)
        out.append(len(fields))
        for f in fields:
            _compile_skip(f["type"], out)
    else:  # fixed (needs a size operand the program lacks), logical exotics
        raise NativeUnsupported(f"cannot skip schema type {t!r}")


def _nullable_double(schema) -> Optional[int]:
    """For union [null,double]-shaped fields: the null branch index."""
    if _stype(schema) == "double":
        return None  # plain double, not nullable
    if (isinstance(schema, list) and len(schema) == 2
            and "null" in schema and "double" in schema):
        return schema.index("null")
    raise NativeUnsupported(f"field is not double / [null,double]: {schema}")


def _is_feature_array(schema) -> bool:
    if _stype(schema) != "array":
        return False
    item = schema["items"]
    if _stype(item) != "record":
        return False
    fields = item["fields"]
    return ([f["name"] for f in fields] == ["name", "term", "value"]
            and [_stype(f["type"]) for f in fields]
            == ["string", "string", "double"])


def compile_field_program(schema, columns, capture_metadata: bool) -> bytes:
    """Compile the writer schema's top-level record into the decoder's field
    program. Raises NativeUnsupported for shapes the decoder cannot walk —
    including a missing features field, so the Python fallback raises the
    same KeyError it always did instead of this path silently yielding
    intercept-only rows."""
    if _stype(schema) != "record":
        raise NativeUnsupported("top-level schema is not a record")
    if not any(f["name"] == columns.features for f in schema["fields"]):
        raise NativeUnsupported(f"no '{columns.features}' field in schema")
    prog = bytearray()
    for f in schema["fields"]:
        name, ftype = f["name"], f["type"]
        if name == columns.response:
            nb = _nullable_double(ftype)
            prog += (bytes([_CAP_LABEL_D]) if nb is None
                     else bytes([_CAP_LABEL_ND, nb]))
        elif name == columns.offset:
            nb = _nullable_double(ftype)
            prog += (bytes([_CAP_OFFSET_D]) if nb is None
                     else bytes([_CAP_OFFSET_ND, nb]))
        elif name == columns.weight:
            nb = _nullable_double(ftype)
            prog += (bytes([_CAP_WEIGHT_D]) if nb is None
                     else bytes([_CAP_WEIGHT_ND, nb]))
        elif name == columns.features:
            if not _is_feature_array(ftype):
                raise NativeUnsupported(
                    f"features field shape unsupported: {ftype}")
            prog.append(_CAP_FEATURES)
        elif name == columns.metadata_map and capture_metadata:
            if (_stype(ftype) != "map"
                    or _stype(ftype["values"]) != "string"):
                raise NativeUnsupported("metadataMap is not map<string>")
            prog.append(_CAP_METADATA)
        elif name == columns.uid:
            is_union = isinstance(ftype, list)
            branches = ftype if is_union else [ftype]
            kinds = []
            for b in branches:
                bt = _stype(b)
                if bt == "null":
                    kinds.append(0)
                elif bt == "string":
                    kinds.append(1)
                elif bt in ("int", "long"):
                    kinds.append(2)
                else:
                    raise NativeUnsupported(f"uid branch {bt!r}")
            # Avro writes a branch index for every union, even 1-branch ones
            prog += bytes([_CAP_UID, int(is_union), len(kinds), *kinds])
        else:
            _compile_skip(ftype, prog)
    return bytes(prog)


# -- ctypes surface ---------------------------------------------------------
def _lib() -> ctypes.CDLL:
    from photon_ml_tpu.native import load_library

    lib = load_library("avro_decoder")
    if not getattr(lib, "_avd_configured", False):
        u8p = ctypes.POINTER(ctypes.c_uint8)
        u64p = ctypes.POINTER(ctypes.c_uint64)
        lib.avd_create.restype = ctypes.c_void_p
        lib.avd_create.argtypes = [ctypes.c_char_p,
                                   ctypes.POINTER(ctypes.c_uint32),
                                   ctypes.c_uint32, ctypes.c_uint32]
        lib.avd_decode_block.restype = ctypes.c_int
        lib.avd_decode_block.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64, ctypes.c_int,
            ctypes.c_int64, ctypes.c_char_p, ctypes.c_uint32,
            ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_int64), ctypes.c_uint32,
        ]
        lib.avd_decode_blocks_mt.restype = ctypes.c_int
        lib.avd_decode_blocks_mt.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_int64),
            ctypes.c_uint64, ctypes.c_int, ctypes.c_char_p, ctypes.c_uint32,
            ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_int64), ctypes.c_uint32, ctypes.c_uint32,
        ]
        for fn, res in [("avd_rows", ctypes.c_uint64),
                        ("avd_nnz", ctypes.c_uint64),
                        ("avd_labels", ctypes.POINTER(ctypes.c_double)),
                        ("avd_has_label", u8p),
                        ("avd_offsets", ctypes.POINTER(ctypes.c_double)),
                        ("avd_weights", ctypes.POINTER(ctypes.c_double)),
                        ("avd_feat_counts", ctypes.POINTER(ctypes.c_int32)),
                        ("avd_feat_values", ctypes.POINTER(ctypes.c_double)),
                        ("avd_error", ctypes.c_char_p)]:
            getattr(lib, fn).restype = res
            getattr(lib, fn).argtypes = [ctypes.c_void_p]
        lib.avd_feat_indices.restype = ctypes.POINTER(ctypes.c_int32)
        lib.avd_feat_indices.argtypes = [ctypes.c_void_p, ctypes.c_uint32]
        lib.avd_uid.restype = ctypes.c_int
        lib.avd_uid.argtypes = [ctypes.c_void_p, ctypes.POINTER(u8p),
                                ctypes.POINTER(u64p), ctypes.POINTER(u8p),
                                u64p]
        lib.avd_entity_col.restype = ctypes.c_int
        lib.avd_entity_col.argtypes = [ctypes.c_void_p, ctypes.c_uint32,
                                       ctypes.POINTER(u8p),
                                       ctypes.POINTER(u64p),
                                       ctypes.POINTER(u8p), u64p]
        lib.avd_free.argtypes = [ctypes.c_void_p]
        lib._avd_configured = True
    return lib


class _Resolver:
    """Native feature resolution backing for one index map: either the
    mmap'd feature index store (handle + lookup fn pointer) or FNV hashing.
    Plain in-memory IndexMaps are converted into a temporary native store —
    a one-time O(#features) build that keeps per-feature lookups in C++."""

    def __init__(self, imap):
        from photon_ml_tpu.io.hashing import HashingIndexMap
        from photon_ml_tpu.io.paldb import PersistentIndexMap, build_store

        self._tmp = None
        self._store = None
        self.hash_dim = 0
        if isinstance(imap, HashingIndexMap):
            self.hash_dim = imap._hash_dim
        elif isinstance(imap, PersistentIndexMap):
            self._store = imap
        else:  # in-memory IndexMap (or any duck-type exposing .forward)
            forward = getattr(imap, "forward", None)
            if forward is None:
                raise NativeUnsupported(
                    f"no native resolution for {type(imap).__name__}")
            self._tmp = tempfile.NamedTemporaryFile(
                suffix=".fis", delete=False)
            self._tmp.close()
            build_store(dict(forward), self._tmp.name)
            self._store = PersistentIndexMap(self._tmp.name)

    @property
    def fis_handle(self):
        return self._store._handle if self._store is not None else None

    @property
    def fis_lookup_ptr(self):
        if self._store is None:
            return None
        return ctypes.cast(self._store._lib.fis_lookup, ctypes.c_void_p)

    def close(self):
        if self._tmp is not None:
            self._store.close()
            os.unlink(self._tmp.name)
            self._tmp = None


# Parallel decode knobs: thread count (0 = all cores) and the per-wave byte
# budget that bounds how much raw payload is staged in memory at once.
_DECODE_THREADS_ENV = "PHOTON_ML_DECODE_THREADS"
_WAVE_BYTES = 256 << 20


def _decode_threads() -> int:
    env = os.environ.get(_DECODE_THREADS_ENV)
    if env:
        n = int(env)  # loud on bad values
        if n > 0:
            return n
    return max(os.cpu_count() or 1, 1)


def _decode_file(path: str, columns, entity_columns: Sequence[str],
                 resolvers: Sequence[_Resolver], lib) -> ctypes.c_void_p:
    """Decode one container file (once, for all shards) into a fresh native
    Output handle. Blocks are staged in bounded waves and decoded by
    ``avd_decode_blocks_mt`` — container blocks are independent, so decode
    parallelizes across cores while this loop keeps at most ``_WAVE_BYTES``
    of raw payload in memory (TB-scale files never fully stage)."""
    keys = [c.encode() for c in entity_columns]
    blob = b"".join(keys)
    lens = (ctypes.c_uint32 * max(len(keys), 1))(*[len(k) for k in keys])
    n_shards = len(resolvers)
    handle = lib.avd_create(blob, lens, len(keys), n_shards)
    fis_handles = (ctypes.c_void_p * n_shards)(
        *[r.fis_handle for r in resolvers])
    lookup_ptrs = (ctypes.c_void_p * n_shards)(
        *[r.fis_lookup_ptr for r in resolvers])
    hash_dims = (ctypes.c_int64 * n_shards)(
        *[r.hash_dim for r in resolvers])
    n_threads = _decode_threads()

    def flush(wave: List[Tuple[bytes, int]], deflate: int, prog: bytes):
        if not wave:
            return
        n = len(wave)
        datas = (ctypes.c_char_p * n)(*[p for p, _ in wave])
        blens = (ctypes.c_uint64 * n)(*[len(p) for p, _ in wave])
        counts = (ctypes.c_int64 * n)(*[c for _, c in wave])
        rc = lib.avd_decode_blocks_mt(
            handle, datas, blens, counts, n, deflate, prog, len(prog),
            fis_handles, lookup_ptrs, hash_dims, n_shards, n_threads,
        )
        if rc != 0:
            err = lib.avd_error(handle)
            raise ValueError(f"{path}: native decode failed: "
                             f"{err.decode() if err else rc}")

    try:
        with open(path, "rb") as f:
            schema, codec, sync = _read_header(f, path)
            prog = compile_field_program(schema, columns,
                                         bool(entity_columns))
            deflate = 1 if codec == "deflate" else 0
            wave: List[Tuple[bytes, int]] = []
            wave_bytes = 0
            while True:
                count = _read_long_or_eof(f)
                if count is None:
                    break
                size = _read_long_or_eof(f)
                if size is None or size < 0:
                    raise ValueError(f"{path}: truncated block header")
                payload = f.read(size)
                if len(payload) != size:
                    raise ValueError(f"{path}: truncated block")
                if f.read(16) != sync:
                    raise ValueError(f"{path}: sync marker mismatch "
                                     "(corrupt file)")
                wave.append((payload, count))
                wave_bytes += size
                if wave_bytes >= _WAVE_BYTES:
                    flush(wave, deflate, prog)
                    wave, wave_bytes = [], 0
            flush(wave, deflate, prog)
    except Exception:
        lib.avd_free(handle)
        raise
    return handle


def _np_from(ptr, n, dtype):
    if n == 0:
        return np.empty(0, dtype)
    return np.ctypeslib.as_array(ptr, shape=(n,)).astype(dtype, copy=True)


def _ragged_strings(blob_p, off_p, n) -> List[bytes]:
    if n == 0:
        return []
    offs = np.ctypeslib.as_array(off_p, shape=(n + 1,))
    raw = (ctypes.string_at(ctypes.cast(blob_p, ctypes.c_void_p),
                            int(offs[n])) if offs[n] else b"")
    return [raw[offs[i]:offs[i + 1]] for i in range(n)]


def _pad_features(counts: np.ndarray, flat_idx: np.ndarray,
                  flat_val: np.ndarray, intercept: int
                  ) -> Tuple[np.ndarray, np.ndarray]:
    """Ragged (counts, indices, values) -> padded (n,k) arrays, dropping
    unresolved (-1) entries and appending the intercept column. Matches
    ``_rows_to_host_sparse`` + the per-row intercept append."""
    n = len(counts)
    row_ids = np.repeat(np.arange(n), counts)
    keep = flat_idx >= 0
    row_ids, idx, val = row_ids[keep], flat_idx[keep], flat_val[keep]
    valid = np.bincount(row_ids, minlength=n).astype(np.int64)
    extra = 1 if intercept >= 0 else 0
    k = max(int(valid.max(initial=0)) + extra, 1)
    starts = np.zeros(n, np.int64)
    np.cumsum(valid[:-1], out=starts[1:])
    pos = np.arange(len(row_ids)) - np.repeat(starts, valid)
    indices = np.zeros((n, k), np.int32)
    values = np.zeros((n, k))
    indices[row_ids, pos] = idx
    values[row_ids, pos] = val
    if intercept >= 0:
        rows = np.arange(n)
        indices[rows, valid] = intercept
        values[rows, valid] = 1.0
    return indices, values


def read_training_examples_native(
    paths,
    index_maps: Dict[str, object],
    entity_columns: Sequence[str],
    columns,
    require_response: bool,
):
    """Native-path equivalent of ``data_reader.read_training_examples``.
    Raises NativeUnsupported when this path cannot serve the request (the
    caller falls back to the Python codec)."""
    from photon_ml_tpu.game.data import HostSparse
    from photon_ml_tpu.native import NativeBuildError

    try:
        lib = _lib()
    except NativeBuildError as e:
        raise NativeUnsupported(str(e)) from e

    shards = sorted(index_maps)
    if not shards:
        # scalars/entity-columns-only read (every feature shard is
        # disk-backed out of core): the decoder requires >=1 shard, and
        # the python codec handles the no-features case directly
        raise NativeUnsupported("no feature shards requested")
    resolvers: List[_Resolver] = []
    try:
        for s in shards:
            resolvers.append(_Resolver(index_maps[s]))
        file_list = _expand(paths)
        if not file_list:
            raise NativeUnsupported("no input files")
        # one decode pass per file resolves features for every shard
        per_file: List[dict] = []
        scalars: List[tuple] = []
        for path in file_list:
            handle = _decode_file(path, columns, entity_columns,
                                  resolvers, lib)
            try:
                rows = int(lib.avd_rows(handle))
                nnz = int(lib.avd_nnz(handle))
                per_file.append({
                    "counts": _np_from(lib.avd_feat_counts(handle), rows,
                                       np.int64),
                    "values": _np_from(lib.avd_feat_values(handle), nnz,
                                       np.float64),
                    "indices": [
                        _np_from(lib.avd_feat_indices(handle, si), nnz,
                                 np.int32)
                        for si in range(len(shards))
                    ],
                })
                scalars.append(_extract_scalars(
                    lib, handle, rows, entity_columns))
            finally:
                lib.avd_free(handle)
        counts = np.concatenate([p["counts"] for p in per_file])
        flat_val = np.concatenate([p["values"] for p in per_file])
        features: Dict[str, HostSparse] = {}
        for si, shard in enumerate(shards):
            imap = index_maps[shard]
            flat_idx = np.concatenate([p["indices"][si] for p in per_file])
            indices, values = _pad_features(counts, flat_idx, flat_val,
                                            imap.intercept_index)
            features[shard] = HostSparse(indices, values, imap.size)
        labels = np.concatenate([s[0] for s in scalars])
        has_label = np.concatenate([s[1] for s in scalars])
        offsets = np.concatenate([s[2] for s in scalars])
        weights = np.concatenate([s[3] for s in scalars])
        uids = [u for s in scalars for u in s[4]]
        entity_vals = {
            c: np.concatenate([s[5][c] for s in scalars])
            for c in entity_columns
        }
    finally:
        for r in resolvers:
            r.close()

    missing = ~has_label.astype(bool)
    if require_response:
        if missing.any():
            i = int(np.argmax(missing))
            raise ValueError(
                f"record uid={uids[i]} has no '{columns.response}' — "
                "training data must be labeled")
    else:
        labels = labels.copy()
        labels[missing] = np.nan
    return features, labels, offsets, weights, entity_vals, uids


def _extract_scalars(lib, handle, rows: int, entity_columns: Sequence[str]):
    labels = _np_from(lib.avd_labels(handle), rows, np.float64)
    has_label = _np_from(lib.avd_has_label(handle), rows, np.uint8)
    offs = _np_from(lib.avd_offsets(handle), rows, np.float64)
    weights = _np_from(lib.avd_weights(handle), rows, np.float64)

    u8p = ctypes.POINTER(ctypes.c_uint8)
    u64p = ctypes.POINTER(ctypes.c_uint64)
    blob_p, off_p, kind_p = u8p(), u64p(), u8p()
    n_uid = ctypes.c_uint64()
    lib.avd_uid(handle, ctypes.byref(blob_p), ctypes.byref(off_p),
                ctypes.byref(kind_p), ctypes.byref(n_uid))
    n_uid = int(n_uid.value)
    if n_uid == 0:  # schema has no uid field
        uids = [None] * rows
    else:
        raw = _ragged_strings(blob_p, off_p, n_uid)
        kinds = np.ctypeslib.as_array(kind_p, shape=(n_uid,))
        uids = [None if k == 0 else
                (int(r) if k == 2 else r.decode("utf-8"))
                for k, r in zip(kinds, raw)]

    entity_vals: Dict[str, np.ndarray] = {}
    for ci, col in enumerate(entity_columns):
        blob_p, off_p, pres_p = u8p(), u64p(), u8p()
        n = ctypes.c_uint64()
        lib.avd_entity_col(handle, ci, ctypes.byref(blob_p),
                           ctypes.byref(off_p), ctypes.byref(pres_p),
                           ctypes.byref(n))
        n_rows = int(n.value)
        vals = _ragged_strings(blob_p, off_p, n_rows)
        present = (np.ctypeslib.as_array(pres_p, shape=(n_rows,))
                   if n_rows else np.zeros(0, np.uint8))
        if not present.all():
            i = int(np.argmin(present))
            raise ValueError(f"record uid={uids[i]} missing entity column "
                             f"'{col}' in metadataMap")
        entity_vals[col] = np.asarray([v.decode("utf-8") for v in vals])
    return labels, has_label, offs, weights, uids, entity_vals
