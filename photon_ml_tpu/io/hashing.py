"""Feature hashing (the hashing trick) as an index-map backend.

The reference materializes name/term→index maps (in-memory or PalDB) built
by a dedicated indexing job (SURVEY.md §3.3). At Criteo-TB scale a
materialized map is itself a bottleneck; the standard alternative is a
stable hash of the feature key into a fixed-width space — no build pass, no
storage, identical across processes/hosts. This backend duck-types
``IndexMap`` so every driver accepts ``--hash-dim`` in place of a built map.

Collisions are the accepted trade (two features sharing an index add their
contributions); width should be chosen ~4x the live feature count. Hashing
is FNV-1a 64 over the utf-8 feature key — the same function the native
store uses, and stable by construction (Python's ``hash`` is per-process
randomized and unusable here).

Saved models name hashed coefficients ``(HASH <index>)``; the model-load
path calls ``model_index_of`` which recognizes that form, so model
save/load round-trips without the original feature names (which a hashing
map never sees). Plain ``index_of`` always hashes — a real data feature
that happens to be literally named ``(HASH n)`` is treated like any other
feature, never routed directly to slot ``n``.
"""

from __future__ import annotations

from typing import Dict, Optional

from photon_ml_tpu.io.schemas import INTERCEPT_KEY, feature_key

_FNV_OFFSET = 0xCBF29CE484222325
_FNV_PRIME = 0x100000001B3
_MASK64 = (1 << 64) - 1
_HASH_NAME_PREFIX = "(HASH "


def fnv1a_64(data: bytes) -> int:
    h = _FNV_OFFSET
    for byte in data:
        h = ((h ^ byte) * _FNV_PRIME) & _MASK64
    return h


class HashingIndexMap:
    """Fixed-width hashed feature space; duck-types ``IndexMap``."""

    def __init__(self, dim: int, add_intercept: bool = True):
        if dim <= 0:
            raise ValueError(f"hash dim must be positive, got {dim}")
        # the intercept gets a reserved slot past the hashed range so no
        # feature can collide with it
        self._hash_dim = dim
        self._intercept = dim if add_intercept else -1

    @property
    def size(self) -> int:
        return self._hash_dim + (1 if self._intercept >= 0 else 0)

    @property
    def intercept_index(self) -> int:
        return self._intercept

    def index_of(self, name: str, term: str = "") -> Optional[int]:
        if name == INTERCEPT_KEY:
            return self._intercept if self._intercept >= 0 else None
        key = feature_key(name, term)
        return fnv1a_64(key.encode("utf-8")) % self._hash_dim

    def model_index_of(self, name: str, term: str = "") -> Optional[int]:
        """``index_of`` plus recognition of the synthetic ``(HASH n)`` names
        this map writes into saved models. Only the model-load path calls
        this, so user data named ``(HASH n)`` cannot alias slot ``n``."""
        if name.startswith(_HASH_NAME_PREFIX) and name.endswith(")") and not term:
            try:
                idx = int(name[len(_HASH_NAME_PREFIX):-1])
            except ValueError:
                idx = -1
            if 0 <= idx < self.size:
                return idx
        return self.index_of(name, term)

    def inverse(self) -> Dict[int, str]:
        """Synthetic names — hashing is not invertible."""
        out = {i: f"{_HASH_NAME_PREFIX}{i})" for i in range(self._hash_dim)}
        if self._intercept >= 0:
            out[self._intercept] = INTERCEPT_KEY
        return out

    def digest(self) -> str:
        """Feature-space fingerprint (chunk-cache invalidation key). The
        hash function is fixed, so (dim, intercept slot) determines every
        resolution."""
        return f"fnv1a64:{self._hash_dim}:{self._intercept}"

    def save(self, path: str) -> None:
        import json

        with open(path, "w") as f:
            json.dump({"hashing": {"dim": self._hash_dim,
                                   "add_intercept": self._intercept >= 0}}, f)

    @classmethod
    def load(cls, path: str) -> "HashingIndexMap":
        import json

        with open(path) as f:
            cfg = json.load(f)["hashing"]
        return cls(cfg["dim"], add_intercept=cfg["add_intercept"])
