"""Out-of-core chunk source: stream on-disk Avro through ``fit_streaming``.

VERDICT r4 missing #1 / SURVEY.md §7 hard-part #3 ("host↔device data
pipeline at 1TB"): the reference streams Avro partitions through Spark
executors so no single host ever materializes the dataset. The in-RAM
``make_host_chunks`` path cannot reach that scale — it needs the whole
dataset as numpy in one host's RAM, re-iterated every optimizer pass.

:class:`AvroChunkSource` is the TPU-native equivalent, a drop-in
replacement for the chunk LIST that ``fit_streaming`` consumes (it only
needs ``len()`` + repeated ``iter()``):

1. **Scan once, cheaply.** Avro container block headers carry the record
   count and payload size, so total rows — and hence the fixed chunk
   count — come from a header walk that never decodes a payload.
2. **Decode per pass, bounded.** Each ``iter()`` starts a background
   producer thread that decodes consecutive block waves through the native
   C++ decoder (``native/avro_decoder.cpp`` — inflate + decode + feature
   resolution all outside the GIL) into a ``queue.Queue(maxsize=prefetch)``
   of fixed-shape :class:`~photon_ml_tpu.parallel.streaming.HostChunk`.
   Host RAM holds at most ``prefetch + 2`` chunks regardless of dataset
   size; decode of chunk i+1 overlaps device compute of chunk i.
3. **Fixed shapes.** Every chunk is exactly ``(chunk_rows, pad_nnz)`` —
   the per-chunk XLA program compiles once — with trailing zero-weight
   padding rows, mirroring ``make_host_chunks``.

Without the native library (no compiler) the producer falls back to the
pure-Python codec's block-at-a-time record stream — same bounded-memory
contract, slower decode — so the source is a transparent accelerator,
never a new failure mode (same policy as ``io/data_reader.py``).
"""

from __future__ import annotations

import contextlib
import ctypes
import dataclasses
import logging
import os
import queue
import threading
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

_log = logging.getLogger("photon_ml_tpu")

from photon_ml_tpu.io.avro import (
    _expand,
    _read_header,
    _read_long_or_eof,
)
from photon_ml_tpu.parallel import fault_injection
from photon_ml_tpu.parallel.streaming import HostChunk

__all__ = ["AvroChunkSource", "ScalarOverlaySource", "scan_blocks",
           "iter_block_records", "BlockRef"]


@dataclasses.dataclass(frozen=True)
class BlockRef:
    """One container block located during the header scan (no decode)."""

    path: str
    payload_offset: int
    payload_size: int
    count: int  # records in the block
    codec: str  # "null" | "deflate", per owning file


def scan_blocks(paths) -> Tuple[List[BlockRef], object]:
    """Walk container block headers (seek past payloads): returns
    (blocks, writer_schema). O(#blocks) reads of ~20 bytes each — the
    row count of a TB-scale dataset costs a few MB of header IO."""
    blocks: List[BlockRef] = []
    schema = None
    for path in _expand(paths):
        with open(path, "rb") as f:
            file_schema, codec, sync = _read_header(f, path)
            if schema is None:
                schema = file_schema
            while True:
                count = _read_long_or_eof(f)
                if count is None:
                    break
                size = _read_long_or_eof(f)
                if count < 0 or size is None or size < 0:
                    raise ValueError(f"{path}: truncated block header")
                off = f.tell()
                f.seek(size, 1)
                if f.read(16) != sync:
                    raise ValueError(
                        f"{path}: sync marker mismatch (corrupt file)")
                blocks.append(BlockRef(path, off, size, count, codec))
    if schema is None:
        raise ValueError(f"no Avro input files under {paths!r}")
    return blocks, schema


def iter_block_records(blocks: Sequence[BlockRef]) -> Iterator[dict]:
    """Decode an explicit block list with the pure-Python codec, one block
    payload resident at a time — shared by the chunk source's python
    fallback and the chunked scoring reader (io/data_reader.py), so the
    block-walk contract has one definition."""
    import io as _io
    import zlib

    from photon_ml_tpu.io.avro import read_datum

    open_path, f, schema = None, None, None
    try:
        for blk in blocks:
            if blk.path != open_path:
                if f is not None:
                    f.close()
                f = open(blk.path, "rb")
                schema, _, _ = _read_header(f, blk.path)
                open_path = blk.path
            f.seek(blk.payload_offset)
            payload = fault_injection.mangle_payload(
                "stream.block_payload", f.read(blk.payload_size))
            if len(payload) != blk.payload_size:
                raise ValueError(f"{blk.path}: truncated block")
            if blk.codec == "deflate":
                payload = zlib.decompress(payload, -15)
            buf = _io.BytesIO(payload)
            for _ in range(blk.count):
                yield read_datum(buf, schema)
    finally:
        if f is not None:
            f.close()


class _Ragged:
    """Pending decoded rows in ragged layout, FIFO across wave appends."""

    def __init__(self):
        self.counts: List[np.ndarray] = []
        self.flat_idx: List[np.ndarray] = []
        self.flat_val: List[np.ndarray] = []
        self.labels: List[np.ndarray] = []
        self.offsets: List[np.ndarray] = []
        self.weights: List[np.ndarray] = []

    def rows(self) -> int:
        return sum(len(c) for c in self.counts)

    def append(self, counts, fi, fv, lab, off, wt):
        self.counts.append(counts)
        self.flat_idx.append(fi)
        self.flat_val.append(fv)
        self.labels.append(lab)
        self.offsets.append(off)
        self.weights.append(wt)

    def take(self, n: int):
        """Split off the first ``n`` rows (ragged concatenate + slice)."""
        counts = np.concatenate(self.counts)
        fi = np.concatenate(self.flat_idx)
        fv = np.concatenate(self.flat_val)
        lab = np.concatenate(self.labels)
        off = np.concatenate(self.offsets)
        wt = np.concatenate(self.weights)
        nnz_head = int(counts[:n].sum())
        head = (counts[:n], fi[:nnz_head], fv[:nnz_head],
                lab[:n], off[:n], wt[:n])
        self.__init__()
        if len(counts) > n:
            self.append(counts[n:], fi[nnz_head:], fv[nnz_head:],
                        lab[n:], off[n:], wt[n:])
        return head


def _pad_fixed(counts, flat_idx, flat_val, intercept: int, k: int,
               dtype) -> Tuple[np.ndarray, np.ndarray]:
    """Ragged rows -> fixed (n, k) padded arrays, dropping unresolved (-1)
    entries and appending the intercept column. Vectorized like
    ``native_reader._pad_features`` but with a CALLER-FIXED width so every
    chunk shares one XLA program; overflow is a loud error."""
    n = len(counts)
    row_ids = np.repeat(np.arange(n), counts)
    keep = flat_idx >= 0
    row_ids, idx, val = row_ids[keep], flat_idx[keep], flat_val[keep]
    valid = np.bincount(row_ids, minlength=n).astype(np.int64)
    extra = 1 if intercept >= 0 else 0
    need = int(valid.max(initial=0)) + extra
    if need > k:
        raise ValueError(
            f"row with {need} features exceeds pad_nnz={k} — raise pad_nnz "
            "(or let AvroChunkSource measure it with pad_nnz=None)")
    starts = np.zeros(n, np.int64)
    np.cumsum(valid[:-1], out=starts[1:])
    pos = np.arange(len(row_ids)) - np.repeat(starts, valid)
    indices = np.zeros((n, k), np.int32)
    values = np.zeros((n, k), dtype)
    indices[row_ids, pos] = idx
    values[row_ids, pos] = val
    if intercept >= 0:
        rows = np.arange(n)
        indices[rows, valid] = intercept
        values[rows, valid] = 1.0
    return indices, values


class ScalarOverlaySource:
    """Wrap a chunk source, substituting the scalar columns
    (labels/offsets/weights) from dataset-level host arrays addressed by
    running row index — feature columns stream from the wrapped source
    untouched.

    This is what lets a GAME coordinate-descent step run its fixed effect
    OUT OF CORE: the residual offsets (base + other coordinates' scores)
    change every CD step and live in host RAM (O(12B/row)), while the
    fixed shard's features re-decode from disk per pass. Trailing padding
    rows of the last chunk keep zeroed scalars (weight 0 = inert)."""

    def __init__(self, src, labels=None, offsets=None, weights=None):
        self._src = src
        self._labels = labels
        self._offsets = offsets
        self._weights = weights

    def __len__(self) -> int:
        return len(self._src)

    def __iter__(self) -> Iterator[HostChunk]:
        at = 0
        for c in self._src:
            rows = c.indices.shape[0]

            def take(arr, cur):
                if arr is None:
                    return cur
                seg = np.asarray(arr[at:at + rows], dtype=cur.dtype)
                if len(seg) < rows:  # final-chunk padding rows stay inert
                    seg = np.pad(seg, (0, rows - len(seg)))
                return seg

            yield dataclasses.replace(
                c,
                labels=take(self._labels, c.labels),
                offsets=take(self._offsets, c.offsets),
                weights=take(self._weights, c.weights),
            )
            at += rows


class AvroChunkSource:
    """Re-iterable, disk-backed, bounded-memory chunk source.

    Parameters
    ----------
    paths: Avro file / directory / list (``io.avro._expand`` semantics).
    index_map: feature index map (in-memory ``IndexMap``, mmap'd
        ``PersistentIndexMap``, or ``HashingIndexMap``) resolving
        name/term -> column, exactly as the in-RAM reader does.
    chunk_rows: rows per emitted chunk (fixed; last chunk zero-weight
        padded).
    pad_nnz: fixed per-row feature width including the intercept. ``None``
        measures it with one extra decode pass at construction — pass the
        known value at TB scale to skip that pass.
    columns: ``InputColumnsNames`` overrides (default names).
    implicit_ones: emit the value-free layout (``values=None``, half the
        per-chunk transfer) after verifying every resolved value is 1.0.
    prefetch: producer queue depth; host RAM holds at most
        ``prefetch + 2`` chunks at any moment.
    require_response: unlabeled records raise (training contract).
    process_part: ``(part, n_parts)`` — keep only this process's
        contiguous share of the container blocks (balanced by row count).
        The multi-controller streamed fit gives each process its own
        part; the per-process partials reduce across processes
        (``streaming._cross_process_sum``), which is row-partition
        agnostic, so block-granular splits need no padding coordination.
    """

    def __init__(self, paths, index_map, *, chunk_rows: int,
                 pad_nnz: Optional[int] = None, columns=None,
                 implicit_ones: bool = False, dtype=np.float32,
                 prefetch: int = 2, require_response: bool = True,
                 process_part: Optional[Tuple[int, int]] = None):
        from photon_ml_tpu.io.data_reader import InputColumnsNames

        # first, so close()/__del__ stay safe on a half-built instance
        self._resolver_lock = threading.Lock()
        self._resolver_cached = None  # built once, reused across passes
        self._paths = paths
        self._imap = index_map
        self.chunk_rows = int(chunk_rows)
        self._columns = columns or InputColumnsNames()
        self._implicit_ones = bool(implicit_ones)
        self._dtype = np.dtype(dtype)
        self._prefetch = max(int(prefetch), 0)
        self._require_response = bool(require_response)
        self._blocks, self._schema = scan_blocks(paths)
        self.total_rows = sum(b.count for b in self._blocks)
        # absolute-row span of the kept blocks (block parts are CONTIGUOUS
        # row ranges); with process_part, every part's span is recorded so
        # multi-controller consumers can reassemble globally-ordered
        # vectors (multihost.allgather_varspans)
        self.row_span = (0, self.total_rows)
        self.part_spans = None
        if process_part is not None:
            part, n_parts = process_part
            if not 0 <= part < n_parts:
                raise ValueError(f"process_part {process_part} out of range")
            counts = np.asarray([b.count for b in self._blocks])
            starts = np.cumsum(counts) - counts
            total = int(counts.sum())
            # one vectorized boundary pass: part i owns the blocks whose
            # start row falls in [i*total//n_parts, (i+1)*total//n_parts)
            lows = np.asarray([i * total // n_parts
                               for i in range(n_parts + 1)])
            edges = np.searchsorted(starts, lows, side="left")
            self.part_spans = []
            for i in range(n_parts):
                e0, e1 = int(edges[i]), int(edges[i + 1])
                if e0 < e1:
                    s0 = int(starts[e0])
                    s1 = int(starts[e1 - 1]) + self._blocks[e1 - 1].count
                else:
                    s0 = s1 = 0
                self.part_spans.append((s0, s1))
            # Coordinated abort without communication: the spans are
            # computed from the GLOBAL block layout, identically on every
            # process, so a starved part is detected — and raised — on ALL
            # processes, not only the one that owns it. (Raising on one
            # process alone would leave its peers deadlocked inside the
            # next collective until the watchdog; see
            # parallel/resilience.py for the runtime-failure analogue.)
            starved = [i for i, (s0, s1) in enumerate(self.part_spans)
                       if s0 == s1]
            if starved:
                raise ValueError(
                    f"process_part {starved[0]}/{n_parts} owns no container "
                    f"blocks ({len(counts)} blocks for {n_parts} parts; "
                    f"starved parts {starved}, detected on every process): "
                    "rewrite the dataset with a smaller block_size so "
                    "every process gets >= one block")
            e0, e1 = int(edges[part]), int(edges[part + 1])
            self._blocks = self._blocks[e0:e1]
            self.row_span = self.part_spans[part]
        self.rows = sum(b.count for b in self._blocks)
        if self.rows == 0:
            raise ValueError(f"no records under {paths!r}")
        self.dim = index_map.size
        self._use_native = self._native_usable()
        self._prog_cache: Dict[str, bytes] = {}
        # producer-side instrumentation (tests assert boundedness)
        self.chunks_produced = 0
        self.passes = 0
        # producer threads that outlived the end-of-pass join (a wedged
        # decoder); each increment comes with a logged warning so leaked
        # threads are visible instead of silently accumulating
        self.producer_join_timeouts = 0
        if pad_nnz is None:
            pad_nnz = self._measure_pad_nnz()
        self.pad_nnz = int(pad_nnz)

    # -- sizing ------------------------------------------------------------
    def __len__(self) -> int:
        return -(-self.rows // self.chunk_rows)

    def _measure_pad_nnz(self) -> int:
        """One bounded decode pass recording the widest row (+intercept)."""
        widest = 0
        for counts, fi, _fv, *_ in self._ragged_waves():
            if len(counts) == 0:
                continue
            n = len(counts)
            row_ids = np.repeat(np.arange(n), counts)
            valid = np.bincount(row_ids[fi >= 0], minlength=n)
            widest = max(widest, int(valid.max(initial=0)))
        extra = 1 if self._imap.intercept_index >= 0 else 0
        return max(widest + extra, 1)

    # -- decode backends ---------------------------------------------------
    def _native_usable(self) -> bool:
        if os.environ.get("PHOTON_ML_TPU_NO_NATIVE"):
            return False
        from photon_ml_tpu.native import NativeBuildError
        from photon_ml_tpu.io.native_reader import (
            NativeUnsupported,
            _lib,
            compile_field_program,
        )

        try:
            _lib()
            compile_field_program(self._schema, self._columns, False)
            return True
        except (NativeBuildError, NativeUnsupported):
            return False

    def _ragged_waves(self) -> Iterator[tuple]:
        """Yield ragged decoded waves (counts, flat_idx, flat_val, labels,
        offsets, weights), each roughly chunk-sized, bounded memory."""
        if self._use_native:
            yield from self._native_waves()
        else:
            yield from self._python_waves()

    def _resolver(self):
        """The native feature resolver, built ONCE and reused across every
        decode pass — for a plain in-memory IndexMap the build serializes
        the whole map into a temp mmap store (O(#features)), and a margin
        fit makes several full passes per optimizer iteration. Built
        lazily on the producer THREAD but torn down by ``close()`` on
        the caller's, so the cache slot is lock-owned."""
        with self._resolver_lock:
            if self._resolver_cached is None:
                from photon_ml_tpu.io.native_reader import _Resolver

                self._resolver_cached = _Resolver(self._imap)
            return self._resolver_cached

    def close(self) -> None:
        """Release the native resolver's temp store (idempotent)."""
        with self._resolver_lock:
            r = self._resolver_cached
            self._resolver_cached = None
        if r is not None:
            r.close()

    def __del__(self):  # best-effort; close() is the real API
        try:
            self.close()
        except Exception:
            pass

    def _native_waves(self) -> Iterator[tuple]:
        from photon_ml_tpu.io.native_reader import (
            _decode_threads,
            _lib,
            _np_from,
            compile_field_program,
        )

        lib = _lib()
        resolver = self._resolver()
        prog_cache = self._prog_cache
        fis_handles = (ctypes.c_void_p * 1)(resolver.fis_handle)
        lookup_ptrs = (ctypes.c_void_p * 1)(resolver.fis_lookup_ptr)
        hash_dims = (ctypes.c_int64 * 1)(resolver.hash_dim)
        lens = (ctypes.c_uint32 * 1)()
        n_threads = _decode_threads()
        wave: List[Tuple[bytes, BlockRef]] = []
        wave_rows = 0
        open_path, f = None, None

        def decode(wave):
            b0 = wave[0][1]
            prog = prog_cache.get(b0.path)
            if prog is None:
                with open(b0.path, "rb") as fh:
                    schema, _, _ = _read_header(fh, b0.path)
                prog = compile_field_program(schema, self._columns, False)
                prog_cache[b0.path] = prog
            n = len(wave)
            datas = (ctypes.c_char_p * n)(*[p for p, _ in wave])
            blens = (ctypes.c_uint64 * n)(*[len(p) for p, _ in wave])
            counts = (ctypes.c_int64 * n)(*[b.count for _, b in wave])
            deflate = 1 if b0.codec == "deflate" else 0
            handle = lib.avd_create(b"", lens, 0, 1)
            try:
                rc = lib.avd_decode_blocks_mt(
                    handle, datas, blens, counts, n, deflate, prog,
                    len(prog), fis_handles, lookup_ptrs, hash_dims, 1,
                    n_threads)
                if rc != 0:
                    err = lib.avd_error(handle)
                    raise ValueError(
                        f"{b0.path}: native decode failed: "
                        f"{err.decode() if err else rc}")
                rows = int(lib.avd_rows(handle))
                nnz = int(lib.avd_nnz(handle))
                out = (
                    _np_from(lib.avd_feat_counts(handle), rows, np.int64),
                    _np_from(lib.avd_feat_indices(handle, 0), nnz,
                             np.int32),
                    _np_from(lib.avd_feat_values(handle), nnz,
                             np.float64),
                    _np_from(lib.avd_labels(handle), rows, np.float64),
                    _np_from(lib.avd_has_label(handle), rows, np.uint8),
                    _np_from(lib.avd_offsets(handle), rows, np.float64),
                    _np_from(lib.avd_weights(handle), rows, np.float64),
                )
            finally:
                lib.avd_free(handle)
            counts_a, fi, fv, lab, has, off, wt = out
            if self._require_response and not has.all():
                raise ValueError(
                    f"{b0.path}: unlabeled record — training data must "
                    f"carry '{self._columns.response}'")
            return counts_a, fi, fv, lab, off, wt

        try:
            for blk in self._blocks:
                if blk.path != open_path:
                    # flush across file boundaries: one wave, one codec
                    if wave:
                        yield decode(wave)
                        wave, wave_rows = [], 0
                    if f is not None:
                        f.close()
                    f = open(blk.path, "rb")
                    open_path = blk.path
                f.seek(blk.payload_offset)
                payload = fault_injection.mangle_payload(
                    "stream.block_payload", f.read(blk.payload_size))
                if len(payload) != blk.payload_size:
                    raise ValueError(f"{blk.path}: truncated block")
                wave.append((payload, blk))
                wave_rows += blk.count
                if wave_rows >= self.chunk_rows:
                    yield decode(wave)
                    wave, wave_rows = [], 0
            if wave:
                yield decode(wave)
        finally:
            if f is not None:
                f.close()

    def _python_waves(self) -> Iterator[tuple]:
        """Pure-Python fallback: block-at-a-time record streaming through
        the codec, mapped through the index map — bounded memory, no
        native library needed."""
        cols, imap = self._columns, self._imap
        counts: List[int] = []
        fi: List[int] = []
        fv: List[float] = []
        lab: List[float] = []
        off: List[float] = []
        wt: List[float] = []

        def flush():
            return (np.asarray(counts, np.int64),
                    np.asarray(fi, np.int32), np.asarray(fv, np.float64),
                    np.asarray(lab, np.float64), np.asarray(off, np.float64),
                    np.asarray(wt, np.float64))

        for rec in iter_block_records(self._blocks):
            val = rec.get(cols.response)
            if val is None:
                if self._require_response:
                    raise ValueError(
                        f"record uid={rec.get(cols.uid)} has no "
                        f"'{cols.response}' — training data must be labeled")
                val = float("nan")
            lab.append(float(val))
            off.append(float(rec[cols.offset])
                       if rec.get(cols.offset) is not None else 0.0)
            wt.append(float(rec[cols.weight])
                      if rec.get(cols.weight) is not None else 1.0)
            c = 0
            for feat in rec[cols.features]:
                idx = imap.index_of(feat["name"], feat.get("term", ""))
                if idx is not None:
                    fi.append(idx)
                    fv.append(float(feat["value"]))
                    c += 1
            counts.append(c)
            if len(counts) >= self.chunk_rows:
                yield flush()
                counts, fi, fv, lab, off, wt = [], [], [], [], [], []
        if counts:
            yield flush()

    # -- chunk assembly ----------------------------------------------------
    def _emit(self, counts, fi, fv, lab, off, wt) -> HostChunk:
        rows = len(counts)
        indices, values = _pad_fixed(counts, fi, fv,
                                     self._imap.intercept_index,
                                     self.pad_nnz, self._dtype)
        pad = self.chunk_rows - rows
        if pad:
            indices = np.pad(indices, ((0, pad), (0, 0)))
            values = np.pad(values, ((0, pad), (0, 0)))
            lab = np.pad(lab, (0, pad))
            off = np.pad(off, (0, pad))
            wt = np.pad(wt, (0, pad))  # pad weight = 0: inert rows
        if self._implicit_ones:
            # the value-free layout is only correct when every slot inside
            # the valid prefix is exactly 1.0 AND the padded tail slots all
            # alias a real column with value 1.0 — instead, padding slots
            # carry value 0, so implicit-ones requires every row to fill
            # pad_nnz exactly (one-hot datasets with uniform arity, like
            # Criteo). Verify both, loudly.
            full = counts + (1 if self._imap.intercept_index >= 0 else 0)
            if not (np.all(values[:rows] == 1.0)
                    and np.all(full == self.pad_nnz) and pad == 0):
                raise ValueError(
                    "implicit_ones=True needs uniform-arity all-ones rows "
                    "filling pad_nnz exactly with no padded chunk tail "
                    "(chunk_rows must divide the row count)")
            values = None
        return HostChunk(indices=indices, values=values,
                         labels=lab.astype(self._dtype),
                         offsets=off.astype(self._dtype),
                         weights=wt.astype(self._dtype))

    # end-of-pass producer join timeout (seconds); a class attribute so
    # tests can shrink it without monkeypatching the iterator internals
    _join_timeout = 30.0
    # consumer-side queue poll (seconds): each expiry rechecks producer
    # liveness, so a decoder that dies without relaying its sentinel
    # fails the pass instead of hanging the consumer forever
    _consumer_poll_s = 0.5

    @staticmethod
    def _put_or_stop(q: queue.Queue, stop: threading.Event, item) -> bool:
        """Stop-aware bounded put — used for chunks, the end-of-pass
        sentinel AND error propagation alike, so an abandoned consumer can
        never wedge the producer thread in a blocking ``put`` (the queue
        may be full at any of the three)."""
        while not stop.is_set():
            try:
                q.put(item, timeout=0.2)
                return True
            except queue.Full:
                continue
        return False

    def _produce(self, q: queue.Queue, stop: threading.Event,
                 fault_proc: Optional[int] = None):
        # the producer thread acts on behalf of the CONSUMER's process:
        # propagate its process identity so per-process fault plans (and
        # the simulated multi-controller harness) address decode faults
        # deterministically
        ctx = (fault_injection.process_context(fault_proc)
               if fault_proc is not None else contextlib.nullcontext())
        with ctx:
            self._produce_inner(q, stop)

    def _produce_inner(self, q: queue.Queue, stop: threading.Event):
        try:
            pending = _Ragged()
            for wave in self._ragged_waves():
                if stop.is_set():
                    return
                pending.append(*wave)
                while pending.rows() >= self.chunk_rows:
                    chunk = self._emit(*pending.take(self.chunk_rows))
                    self.chunks_produced += 1
                    if not self._put_or_stop(q, stop, chunk):
                        return
            n_left = pending.rows()
            if n_left:
                chunk = self._emit(*pending.take(n_left))
                self.chunks_produced += 1
                if not self._put_or_stop(q, stop, chunk):
                    return
            self._put_or_stop(q, stop, None)  # end-of-pass sentinel
        except BaseException as e:  # surfaced in the consumer
            self._put_or_stop(q, stop, e)

    def __iter__(self) -> Iterator[HostChunk]:
        self.passes += 1
        q: queue.Queue = queue.Queue(maxsize=max(self._prefetch, 1))
        stop = threading.Event()
        try:
            from photon_ml_tpu.parallel.resilience import (
                current_process_index,
            )

            fault_proc = current_process_index()
        except Exception:
            fault_proc = None
        t = threading.Thread(target=self._produce,
                             args=(q, stop, fault_proc),
                             daemon=True, name="avro-chunk-producer")
        t.start()
        emitted = 0
        try:
            while True:
                # consumer-side injection point: raise-at-chunk-N faults
                # fire in the consuming (process-context-bearing) thread
                fault_injection.check("stream.chunk")
                try:
                    item = q.get(timeout=self._consumer_poll_s)
                except queue.Empty:
                    if t.is_alive():
                        continue
                    try:
                        # the producer may have parked its last item /
                        # sentinel between our timeout and its exit
                        item = q.get_nowait()
                    except queue.Empty:
                        raise RuntimeError(
                            "avro-chunk-producer thread died without "
                            "delivering its end-of-pass sentinel "
                            "(decoder crash hard enough to skip the "
                            "BaseException relay?)") from None
                if item is None:
                    break
                if isinstance(item, BaseException):
                    raise item
                emitted += 1
                yield item
        finally:
            stop.set()
            t.join(timeout=self._join_timeout)
            if t.is_alive():
                # a wedged decoder (native call stuck outside the GIL, NFS
                # read hung, ...) cannot be killed from here — count and
                # name it loudly rather than leaking the thread invisibly
                self.producer_join_timeouts += 1
                _log.warning(
                    "AvroChunkSource: producer thread %r still alive %.0fs "
                    "after the pass ended (wedged decoder?); leaking it as "
                    "a daemon (join timeouts so far: %d)",
                    t.name, self._join_timeout, self.producer_join_timeouts)
        if emitted != len(self):
            raise RuntimeError(
                f"chunk source produced {emitted} chunks, expected "
                f"{len(self)} — dataset changed under a running fit?")
