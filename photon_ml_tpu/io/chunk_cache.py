"""Decode-once packed chunk cache for out-of-core streamed training.

The out-of-core fixed-effect path (``game/descent._init_out_of_core``,
``glm_driver --out-of-core``) re-decodes the Avro shard from disk on EVERY
optimizer pass: the margin L-BFGS pays two full decode passes per
iteration, the black-box loops one per evaluation. Snap ML
(arXiv:1803.06333) and large-scale GPU SGD (arXiv:1702.07005) both
locate the end-to-end gap in data staging, not kernels — and the r05
bench notes put the per-chunk kernel at the chip's gather issue rate
already, so decode is the remaining streamed-throughput headroom.

:class:`ChunkCacheSource` wraps any re-iterable chunk source and makes
the job pay Avro decode exactly ONCE:

* **Cold pass (first iteration)**: chunks are served from the wrapped
  source unchanged while being teed into one packed ``np.memmap`` file
  per field under a ``.tmp-`` staging dir. When the pass completes, the
  staging dir is renamed into place in one ``os.rename`` — the same
  crash-safety contract as the model registry (``registry/store.py``): a
  cache directory is COMPLETE the instant it exists, and an interrupted
  write leaves only an invisible staging dir (swept on the next
  construction).
* **Warm passes**: chunks are zero-copy views into the read-only memmaps
  — no decode, no feature-resolution, just page-cache reads. CD residual
  offsets still update per pass because ``ScalarOverlaySource`` overlays
  the per-pass scalars ON TOP of whatever source it wraps, cached or not.
* **Invalidation**: the cache is keyed by a fingerprint over the source
  files (path, size, mtime_ns), chunk geometry (chunk_rows, pad_nnz,
  dim, dtype, implicit_ones, row_span) and the feature index map's
  content digest. Touching a source file, changing chunk_rows, or
  swapping the index map changes the fingerprint, so the stale cache is
  never opened (and is swept as garbage).
* **Disk budget**: ``max_bytes`` bounds the packed size; a dataset that
  does not fit falls through to plain re-decode with a logged warning —
  the cache is a transparent accelerator, never a new failure mode.

One cache directory serves ONE source: multi-controller processes (each
holding its own ``process_part`` block share, hence its own fingerprint)
must point at per-process directories — stale-fingerprint sweeping would
otherwise collect a peer's cache on shared storage.
"""

from __future__ import annotations

import itertools
import json
import logging
import os
import shutil
from typing import Iterator, Optional

import numpy as np

from photon_ml_tpu.parallel import fault_injection
from photon_ml_tpu.parallel.streaming import HostChunk

__all__ = ["ChunkCacheSource", "source_fingerprint"]

_log = logging.getLogger("photon_ml_tpu")

_FIELDS = ("indices", "values", "labels", "offsets", "weights")
_META = "META.json"
_FORMAT = 1
_tmp_seq = itertools.count()


def _index_map_digest(imap) -> str:
    dig = getattr(imap, "digest", None)
    if callable(dig):
        return str(dig())
    # fallback for duck-typed maps without a content digest: coarse, but
    # any size/intercept change still invalidates
    return f"{type(imap).__name__}:{imap.size}:{imap.intercept_index}"


def source_fingerprint(source) -> dict:
    """Invalidation fingerprint of a disk-backed chunk source (the
    ``AvroChunkSource`` attribute surface): source files with size+mtime,
    chunk geometry, and the index-map content digest. Raises for sources
    it cannot introspect — pass ``fingerprint=`` explicitly then."""
    from photon_ml_tpu.io.avro import _expand

    paths = getattr(source, "_paths", None)
    imap = getattr(source, "_imap", None)
    if paths is None or imap is None:
        raise ValueError(
            f"cannot fingerprint a {type(source).__name__} (no _paths/_imap "
            "surface); pass ChunkCacheSource(..., fingerprint=...) with a "
            "caller-provided invalidation key")
    files = []
    for p in sorted(_expand(paths)):
        st = os.stat(p)
        files.append([p, st.st_size, st.st_mtime_ns])
    return {
        "format": _FORMAT,
        "files": files,
        "chunk_rows": int(source.chunk_rows),
        "pad_nnz": int(source.pad_nnz),
        "dim": int(source.dim),
        "dtype": str(np.dtype(getattr(source, "_dtype", np.float32))),
        "implicit_ones": bool(getattr(source, "_implicit_ones", False)),
        "row_span": list(getattr(source, "row_span", (0, source.rows))),
        "index_map": _index_map_digest(imap),
    }


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    return True


class _PackedWriter:
    """Spill fixed-shape chunks into one packed memmap file per field."""

    def __init__(self, staging: str, n_chunks: int, first_chunk: HostChunk):
        self.maps = {}
        self.meta_fields = {}
        for name in _FIELDS:
            arr = getattr(first_chunk, name)
            if arr is None:
                continue
            arr = np.asarray(arr)
            shape = (n_chunks,) + arr.shape
            self.maps[name] = np.memmap(os.path.join(staging, name + ".bin"),
                                        dtype=arr.dtype, mode="w+",
                                        shape=shape)
            self.meta_fields[name] = {"dtype": str(arr.dtype),
                                      "shape": list(shape)}

    @property
    def nbytes(self) -> int:
        return sum(mm.nbytes for mm in self.maps.values())

    def write(self, i: int, chunk: HostChunk) -> None:
        for name, mm in self.maps.items():
            mm[i] = getattr(chunk, name)

    def finalize(self) -> None:
        for mm in self.maps.values():
            mm.flush()
        self.maps = {}


class ChunkCacheSource:
    """Re-iterable wrapper that decodes the wrapped source once, then
    serves memmap-backed chunks. Drop-in for ``fit_streaming``'s chunk
    list (``len()`` + repeated ``iter()``); every other attribute
    (``dim``, ``rows``, ``row_span``, ``part_spans``, ...) delegates to
    the wrapped source, so out-of-core validation in ``game/descent``
    sees the source it expects.

    Parameters
    ----------
    source: the chunk source to cache (typically ``AvroChunkSource``).
    cache_dir: directory owned by this source's cache (created lazily).
    max_bytes: disk budget; a packed size above it disables the cache
        with a warning and every pass falls through to ``source``.
    fingerprint: explicit invalidation key for sources
        :func:`source_fingerprint` cannot introspect (e.g. in-RAM chunk
        lists in tests).
    """

    def __init__(self, source, cache_dir: str,
                 max_bytes: Optional[int] = None, *,
                 fingerprint: Optional[dict] = None):
        self._src = source
        self.cache_dir = str(cache_dir)
        self.max_bytes = None if max_bytes is None else int(max_bytes)
        fp = fingerprint if fingerprint is not None \
            else source_fingerprint(source)
        import hashlib

        self._fingerprint = fp
        self._fp_hex = hashlib.sha256(
            json.dumps(fp, sort_keys=True).encode()).hexdigest()
        self.cache_path = os.path.join(self.cache_dir,
                                       f"chunks-{self._fp_hex[:16]}")
        self.enabled = True
        self.cold_passes = 0
        self.warm_passes = 0
        self.fallthrough_passes = 0
        self.bytes_written = 0
        self._maps = None
        self._meta = None
        self._sweep()

    # -- sizing / delegation ------------------------------------------------
    def __len__(self) -> int:
        return len(self._src)

    @property
    def passes(self) -> int:
        return self.cold_passes + self.warm_passes + self.fallthrough_passes

    def __getattr__(self, name):
        if name.startswith("__"):
            raise AttributeError(name)
        try:
            src = self.__dict__["_src"]
        except KeyError:
            raise AttributeError(name) from None
        return getattr(src, name)

    def close(self) -> None:
        """Release the memmaps (idempotent); delegates to the source."""
        self._maps = None
        close = getattr(self._src, "close", None)
        if close is not None:
            close()

    # -- housekeeping -------------------------------------------------------
    def _sweep(self) -> None:
        """Remove invisible garbage: staging dirs whose writer process is
        dead, and committed caches with a stale fingerprint (their source
        changed — they can never be opened again)."""
        if not os.path.isdir(self.cache_dir):
            return
        for name in sorted(os.listdir(self.cache_dir)):
            full = os.path.join(self.cache_dir, name)
            if name.startswith(".tmp-"):
                try:
                    pid = int(name.split("-")[1])
                except (IndexError, ValueError):
                    pid = 0
                if not pid or not _pid_alive(pid):
                    shutil.rmtree(full, ignore_errors=True)
            elif (name.startswith("chunks-")
                    and full != self.cache_path and os.path.isdir(full)):
                _log.info("chunk cache: sweeping stale %s (fingerprint "
                          "changed)", full)
                shutil.rmtree(full, ignore_errors=True)

    # -- warm side ----------------------------------------------------------
    def _try_open_warm(self) -> bool:
        """Open the committed cache read-only; a corrupt or mismatched one
        is removed and reported as absent (forcing a clean re-decode)."""
        if self._maps is not None:
            return True
        meta_path = os.path.join(self.cache_path, _META)
        if not os.path.exists(meta_path):
            return False
        try:
            with open(meta_path) as f:
                meta = json.load(f)
            if meta.get("fingerprint") != self._fp_hex:
                raise ValueError("fingerprint mismatch")
            if meta.get("n_chunks") != len(self._src):
                raise ValueError("chunk count mismatch")
            maps = {}
            for name, spec in meta["fields"].items():
                path = os.path.join(self.cache_path, name + ".bin")
                dtype = np.dtype(spec["dtype"])
                shape = tuple(spec["shape"])
                want = int(np.prod(shape)) * dtype.itemsize
                if os.path.getsize(path) != want:
                    raise ValueError(f"{name}.bin truncated")
                maps[name] = np.memmap(path, dtype=dtype, mode="r",
                                       shape=shape)
        except Exception as e:
            _log.warning("chunk cache: %s unreadable (%s); removing and "
                         "re-decoding", self.cache_path, e)
            self._maps = None
            shutil.rmtree(self.cache_path, ignore_errors=True)
            return False
        self._maps = maps
        self._meta = meta
        return True

    def _iter_warm(self) -> Iterator[HostChunk]:
        maps = self._maps
        values = maps.get("values")
        for i in range(self._meta["n_chunks"]):
            yield HostChunk(indices=maps["indices"][i],
                            values=None if values is None else values[i],
                            labels=maps["labels"][i],
                            offsets=maps["offsets"][i],
                            weights=maps["weights"][i])

    # -- cold side ----------------------------------------------------------
    def _iter_cold(self) -> Iterator[HostChunk]:
        n_chunks = len(self._src)
        os.makedirs(self.cache_dir, exist_ok=True)
        staging = os.path.join(
            self.cache_dir, f".tmp-{os.getpid()}-{next(_tmp_seq)}")
        os.makedirs(staging)
        writer = None
        done = 0
        committed = False
        try:
            for i, chunk in enumerate(self._src):
                if self.enabled and writer is None:
                    writer = _PackedWriter(staging, n_chunks, chunk)
                    if (self.max_bytes is not None
                            and writer.nbytes > self.max_bytes):
                        _log.warning(
                            "chunk cache: packed size %.1f MB exceeds the "
                            "%.1f MB disk budget; disabling the cache — "
                            "every pass will re-decode from source",
                            writer.nbytes / 1e6, self.max_bytes / 1e6)
                        writer.maps = {}
                        self.enabled = False
                        writer = None
                if writer is not None:
                    fault_injection.check("chunk_cache.spill")
                    writer.write(i, chunk)
                done += 1
                yield chunk
            if writer is not None and done == n_chunks:
                total = writer.nbytes
                writer.finalize()
                with open(os.path.join(staging, _META), "w") as f:
                    json.dump({
                        "format": _FORMAT,
                        "fingerprint": self._fp_hex,
                        "source": self._fingerprint,
                        "n_chunks": n_chunks,
                        "bytes": total,
                        "fields": writer.meta_fields,
                    }, f, indent=2)
                fault_injection.check("chunk_cache.commit")
                try:
                    os.rename(staging, self.cache_path)
                    committed = True
                    self.bytes_written = total
                except OSError:
                    # a concurrent iterator committed first; theirs is
                    # identical (same fingerprint) — discard ours
                    pass
        finally:
            if not committed:
                shutil.rmtree(staging, ignore_errors=True)

    def __iter__(self) -> Iterator[HostChunk]:
        from photon_ml_tpu.obs.metrics import training_metrics

        if self.enabled and self._try_open_warm():
            self.warm_passes += 1
            training_metrics().record_chunk_cache_pass("warm")
            return self._iter_warm()
        if not self.enabled:
            self.fallthrough_passes += 1
            training_metrics().record_chunk_cache_pass("fallthrough")
            return iter(self._src)
        self.cold_passes += 1
        training_metrics().record_chunk_cache_pass("cold")
        return self._iter_cold()
