"""LIBSVM format reader (for the a1a baseline config — BASELINE.md #1)."""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from photon_ml_tpu.game.data import HostSparse


def read_libsvm(
    path: str,
    dim: Optional[int] = None,
    zero_based: bool = False,
    add_intercept: bool = False,
) -> Tuple[HostSparse, np.ndarray, int]:
    """Parse a LIBSVM file -> (HostSparse features, labels in {0,1} for
    binary or raw values, intercept_index or -1). Labels -1/+1 map to 0/1."""
    rows, labels = [], []
    max_idx = -1
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            parts = line.split()
            labels.append(float(parts[0]))
            row = []
            for tok in parts[1:]:
                idx_s, val_s = tok.split(":")
                idx = int(idx_s) - (0 if zero_based else 1)
                if idx < 0:
                    raise ValueError(f"feature index {idx_s} < 1 in 1-based file")
                row.append((idx, float(val_s)))
                max_idx = max(max_idx, idx)
            rows.append(row)
    d = dim if dim is not None else max_idx + 1
    intercept_index = -1
    if add_intercept:
        intercept_index = d
        d += 1
        for row in rows:
            row.append((intercept_index, 1.0))
    n = len(rows)
    k = max(max((len(r) for r in rows), default=0), 1)
    indices = np.zeros((n, k), np.int32)
    values = np.zeros((n, k))
    for i, row in enumerate(rows):
        for j, (idx, val) in enumerate(row):
            indices[i, j] = idx
            values[i, j] = val
    labels = np.asarray(labels)
    if set(np.unique(labels)) <= {-1.0, 1.0}:
        labels = (labels + 1.0) / 2.0  # -1/+1 -> 0/1
    return HostSparse(indices, values, d), labels, intercept_index
