"""Durable atomic-rename commits: fsync the file AND its directory.

Every commit point in the tree (resume markers, registry ``LATEST``,
trace files, scoring outputs) uses the temp-file + ``os.replace`` idiom,
which is atomic against CONCURRENT readers but not durable against power
loss: POSIX only guarantees the rename reaches disk after the parent
directory is fsynced, and the renamed file's CONTENT only after the file
itself is fsynced. A rename-only commit can therefore surface after a
crash as a present-but-empty (or half-written) "committed" file — the
exact state the atomic idiom exists to rule out.

:func:`durable_replace` closes the hole: fsync the temp file, then
``os.replace``, then fsync the destination's parent directory. The
``durable.commit`` fault-injection site fires between the content fsync
and the rename — the crash window where the commit must be invisible —
so tier-1 tests can assert the destination is untouched when the commit
dies mid-flight.

Directory fsync is best-effort on platforms that refuse it (Windows has
no ``O_DIRECTORY``; some filesystems return EINVAL): the rename itself
already happened, so degrading to the pre-fix guarantee there is strictly
no worse than before.
"""

from __future__ import annotations

import os

from photon_ml_tpu.parallel import fault_injection

__all__ = ["durable_replace", "fsync_file", "fsync_dir",
           "durable_dir_rename"]


def fsync_file(path: str) -> None:
    """fsync one file's content (open read-only, fsync, close)."""
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def fsync_dir(path: str) -> None:
    """Best-effort fsync of a DIRECTORY so a rename inside it is durable.
    Platforms/filesystems that cannot fsync directories degrade to a
    no-op (the rename still happened; durability falls back to the
    filesystem's own ordering)."""
    flags = os.O_RDONLY | getattr(os, "O_DIRECTORY", 0)
    try:
        fd = os.open(path, flags)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def durable_replace(tmp: str, dst: str) -> None:
    """Atomically AND durably commit ``tmp`` over ``dst``: fsync the temp
    file's content, rename, fsync the destination's parent directory.
    The fault site fires inside the crash window (content synced, rename
    not yet issued) so tests can prove a mid-commit crash leaves ``dst``
    untouched."""
    fsync_file(tmp)
    fault_injection.check("durable.commit")
    os.replace(tmp, dst)
    fsync_dir(os.path.dirname(os.path.abspath(dst)))


def durable_dir_rename(src_dir: str, dst_dir: str) -> None:
    """Durably commit a staged DIRECTORY (the registry's version-publish
    rename): fsync the staging directory itself (its entries' names),
    rename, fsync the destination's parent. Callers are responsible for
    having fsynced the individual files inside (the registry's manifest
    goes through :class:`~photon_ml_tpu.parallel.resilience.ResumeManager`,
    which commits via :func:`durable_replace`)."""
    fsync_dir(src_dir)
    os.rename(src_dir, dst_dir)
    fsync_dir(os.path.dirname(os.path.abspath(dst_dir)))
