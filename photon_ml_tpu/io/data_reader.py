"""Avro training-data reader/writer honoring the TrainingExampleAvro contract.

Equivalent of the reference's ``data.avro.AvroDataReader`` +
``NameAndTermFeatureMapUtils`` (SURVEY.md §3.3; reference mount empty):
reads records with name/term/value feature arrays, maps them through
per-shard feature index maps into padded sparse matrices, and carries
response/offset/weight/uid plus entity-id columns (from ``metadataMap``)
for GAME random effects.
"""

from __future__ import annotations

import os
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from photon_ml_tpu.game.data import HostSparse
from photon_ml_tpu.io.avro import iter_avro_records, write_avro_file
from photon_ml_tpu.io.index_map import IndexMap
from photon_ml_tpu.io.schemas import TRAINING_EXAMPLE_SCHEMA
import dataclasses


@dataclasses.dataclass(frozen=True)
class InputColumnsNames:
    """Record-field name overrides — the reference's ``InputColumnsNames``
    (SURVEY.md §3.2 GAME data layer row): datasets whose response / offset /
    weight / uid / features / metadata fields use different names are read
    without rewriting."""

    response: str = "response"
    offset: str = "offset"
    weight: str = "weight"
    uid: str = "uid"
    features: str = "features"
    metadata_map: str = "metadataMap"

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "InputColumnsNames":
        if not d:
            return cls()
        unknown = set(d) - {f.name for f in dataclasses.fields(cls)}
        if unknown:
            raise ValueError(f"unknown input column keys: {sorted(unknown)}")
        return cls(**d)


def read_training_examples(
    paths,
    index_maps: IndexMap | Dict[str, IndexMap],
    entity_columns: Sequence[str] = (),
    columns: Optional[InputColumnsNames] = None,
    require_response: bool = True,
):
    """Read Avro training examples into per-shard sparse features.

    Returns (features: dict shard->HostSparse, labels, offsets, weights,
    entity_ids: dict column->np.ndarray, uids: list). Features absent from a
    shard's index map are dropped for that shard (per-shard feature
    selection, as in the reference's feature bags).

    Decoding runs through the native C++ decoder (io/native_reader.py —
    the host-ingestion hot path, SURVEY.md §7) whenever the writer schema
    and index-map backend support it, falling back to the pure-Python codec
    otherwise. Set PHOTON_ML_TPU_NO_NATIVE=1 to force the Python path."""
    if not isinstance(index_maps, dict):  # any IndexMap-like backend
        index_maps = {"global": index_maps}
    cols = columns or InputColumnsNames()
    if not index_maps:
        # scalars/entity-columns-only read (every feature shard is
        # disk-backed out of core): keep the fast native decode by
        # resolving against a 1-wide dummy hash shard and dropping it
        from photon_ml_tpu.io.hashing import HashingIndexMap

        out = read_training_examples(
            paths, {"__scalars__": HashingIndexMap(1, add_intercept=False)},
            entity_columns, cols, require_response)
        return ({},) + out[1:]
    if not os.environ.get("PHOTON_ML_TPU_NO_NATIVE"):
        from photon_ml_tpu.io.native_reader import (
            NativeUnsupported,
            read_training_examples_native,
        )

        try:
            return read_training_examples_native(
                paths, index_maps, entity_columns, cols, require_response
            )
        except NativeUnsupported:
            pass
    rows_per_shard: Dict[str, List[List[Tuple[int, float]]]] = {
        s: [] for s in index_maps
    }
    labels: List[float] = []
    offsets: List[float] = []
    weights: List[float] = []
    uids: List = []
    entity_vals: Dict[str, List] = {c: [] for c in entity_columns}

    for rec in iter_avro_records(paths):
        label, offset, weight, uid, evals, shard_rows = _parse_record(
            rec, cols, index_maps, entity_columns, require_response)
        labels.append(label)
        offsets.append(offset)
        weights.append(weight)
        uids.append(uid)
        for c, v in zip(entity_columns, evals):
            entity_vals[c].append(v)
        for shard, row in shard_rows.items():
            rows_per_shard[shard].append(row)

    features = {
        shard: _rows_to_host_sparse(rows, index_maps[shard].size)
        for shard, rows in rows_per_shard.items()
    }
    return (
        features,
        np.asarray(labels),
        np.asarray(offsets),
        np.asarray(weights),
        {c: np.asarray(v) for c, v in entity_vals.items()},
        uids,
    )


def _parse_record(rec, cols: InputColumnsNames, index_maps, entity_columns,
                  require_response: bool):
    """Parse ONE TrainingExampleAvro record — the single definition of the
    record contract, shared by the bulk python fallback and the chunked
    (out-of-core scoring) reader so the two can never desynchronize.
    Returns (label, offset, weight, uid, entity_values, per-shard rows)."""
    val = rec.get(cols.response)
    if val is None:
        if require_response:
            raise ValueError(
                f"record uid={rec.get(cols.uid)} has no "
                f"'{cols.response}' — training data must be labeled")
        label = float("nan")
    else:
        label = float(val)
    offset = (float(rec[cols.offset])
              if rec.get(cols.offset) is not None else 0.0)
    weight = (float(rec[cols.weight])
              if rec.get(cols.weight) is not None else 1.0)
    uid = rec.get(cols.uid)
    meta = rec.get(cols.metadata_map) or {}
    evals = []
    for c in entity_columns:
        if c not in meta:
            raise ValueError(f"record uid={uid} missing entity column "
                             f"'{c}' in {cols.metadata_map}")
        evals.append(meta[c])
    shard_rows = {}
    for shard, imap in index_maps.items():
        row: List[Tuple[int, float]] = []
        for feat in rec[cols.features]:
            idx = imap.index_of(feat["name"], feat.get("term", ""))
            if idx is not None:
                row.append((idx, float(feat["value"])))
        if imap.intercept_index >= 0:
            row.append((imap.intercept_index, 1.0))
        shard_rows[shard] = row
    return label, offset, weight, uid, evals, shard_rows


def _rows_to_host_sparse(rows: List[List[Tuple[int, float]]], dim: int) -> HostSparse:
    n = len(rows)
    k = max(max((len(r) for r in rows), default=0), 1)
    indices = np.zeros((n, k), np.int32)
    values = np.zeros((n, k))
    for i, row in enumerate(rows):
        for j, (idx, val) in enumerate(row):
            indices[i, j] = idx
            values[i, j] = val
    return HostSparse(indices, values, dim)


def write_training_examples(
    path: str,
    features: Iterable[Iterable[Tuple[str, str, float]]],
    labels: Optional[Sequence[float]] = None,
    offsets: Optional[Sequence[float]] = None,
    weights: Optional[Sequence[float]] = None,
    entity_ids: Optional[Dict[str, Sequence]] = None,
    uids: Optional[Sequence] = None,
    codec: str = "deflate",
    block_size: int = 4096,
) -> None:
    """Write TrainingExampleAvro records; ``features`` yields per-row lists
    of (name, term, value). ``labels=None`` writes unlabeled scoring data.
    ``block_size`` (records per container block) controls the granularity
    available to block-level consumers (AvroChunkSource process_part)."""
    entity_ids = entity_ids or {}

    def records():
        for i, row in enumerate(features):
            label = None if labels is None else labels[i]
            yield {
                "uid": str(uids[i]) if uids is not None else str(i),
                "response": None if label is None else float(label),
                "offset": float(offsets[i]) if offsets is not None else None,
                "weight": float(weights[i]) if weights is not None else None,
                "features": [
                    {"name": name, "term": term, "value": float(v)}
                    for name, term, v in row
                ],
                "metadataMap": {c: str(vals[i]) for c, vals in entity_ids.items()},
            }

    write_avro_file(path, records(), TRAINING_EXAMPLE_SCHEMA, codec=codec,
                    block_size=block_size)


def feature_tuples_from_dense(X: np.ndarray, prefix: str = "f"):
    """Helper for fixtures: dense matrix -> per-row (name, term, value)."""
    for row in np.asarray(X):
        yield [(f"{prefix}{j}", "", float(v)) for j, v in enumerate(row) if v != 0]


def read_training_examples_chunked(
    paths,
    index_maps: IndexMap | Dict[str, IndexMap],
    entity_columns: Sequence[str] = (),
    columns: Optional[InputColumnsNames] = None,
    chunk_rows: int = 1 << 16,
    require_response: bool = True,
):
    """Generator form of :func:`read_training_examples` for out-of-core
    BULK SCORING: yields windows of ~``chunk_rows`` rows as the same
    tuple shape (features-per-shard, labels, offsets, weights,
    entity_vals, uids), decoding container block ranges one window at a
    time — host RAM holds one window, never the dataset. Windows follow
    block boundaries (Avro blocks are the atomic decode unit), so a
    window's actual row count is the smallest block-aligned count
    >= ``chunk_rows`` (the final window is whatever remains).

    Unlike the training-path :class:`~photon_ml_tpu.io.stream_source.
    AvroChunkSource` (single shard, fixed shapes, re-iterable for
    multi-pass optimizers), this reader serves the SCORING driver: all
    feature shards resolve in one decode, uid and entity columns are
    captured, and one forward pass per window is the whole consumption
    pattern."""
    from photon_ml_tpu.io.stream_source import scan_blocks

    if not isinstance(index_maps, dict):
        index_maps = {"global": index_maps}
    cols = columns or InputColumnsNames()
    blocks, _schema = scan_blocks(paths)

    windows: List[List] = []
    cur: List = []
    rows = 0
    for b in blocks:
        cur.append(b)
        rows += b.count
        if rows >= chunk_rows:
            windows.append(cur)
            cur, rows = [], 0
    if cur:
        windows.append(cur)

    native = not os.environ.get("PHOTON_ML_TPU_NO_NATIVE")
    if native:
        try:
            yield from _chunked_native(windows, index_maps, entity_columns,
                                       cols, require_response)
            return
        except Exception as e:
            from photon_ml_tpu.io.native_reader import NativeUnsupported

            if not isinstance(e, NativeUnsupported):
                raise
    yield from _chunked_python(windows, index_maps, entity_columns, cols,
                               require_response)


def _chunked_native(windows, index_maps, entity_columns, cols,
                    require_response):
    import ctypes

    from photon_ml_tpu.game.data import HostSparse
    from photon_ml_tpu.io.avro import _read_header
    from photon_ml_tpu.io.native_reader import (
        NativeUnsupported,
        _Resolver,
        _decode_threads,
        _extract_scalars,
        _lib,
        _np_from,
        _pad_features,
        compile_field_program,
    )
    from photon_ml_tpu.native import NativeBuildError

    try:
        lib = _lib()
    except NativeBuildError as e:
        raise NativeUnsupported(str(e)) from e
    shards = sorted(index_maps)
    if not shards:
        raise NativeUnsupported("no feature shards requested")
    resolvers = [_Resolver(index_maps[s]) for s in shards]
    try:
        keys = [c.encode() for c in entity_columns]
        blob = b"".join(keys)
        lens = (ctypes.c_uint32 * max(len(keys), 1))(
            *[len(k) for k in keys])
        n_shards = len(resolvers)
        fis = (ctypes.c_void_p * n_shards)(
            *[r.fis_handle for r in resolvers])
        ptrs = (ctypes.c_void_p * n_shards)(
            *[r.fis_lookup_ptr for r in resolvers])
        hdims = (ctypes.c_int64 * n_shards)(
            *[r.hash_dim for r in resolvers])
        threads = _decode_threads()
        # compile every file's field program UP FRONT: NativeUnsupported
        # must fire before the first yield (the caller's python fallback
        # would otherwise replay already-yielded windows)
        prog_cache: Dict[str, bytes] = {}
        for w_ in windows:
            for b_ in w_:
                if b_.path not in prog_cache:
                    with open(b_.path, "rb") as fh:
                        schema, _, _ = _read_header(fh, b_.path)
                    prog_cache[b_.path] = compile_field_program(
                        schema, cols, bool(entity_columns))

        for window in windows:
            handle = lib.avd_create(blob, lens, len(keys), n_shards)
            try:
                # one native decode per window; a window may span files
                at = 0
                while at < len(window):
                    path = window[at].path
                    prog = prog_cache[path]  # precompiled before any yield
                    part = []
                    with open(path, "rb") as f:
                        while at < len(window) and window[at].path == path:
                            b = window[at]
                            f.seek(b.payload_offset)
                            payload = f.read(b.payload_size)
                            if len(payload) != b.payload_size:
                                raise ValueError(f"{path}: truncated block")
                            part.append((payload, b))
                            at += 1
                    datas = (ctypes.c_char_p * len(part))(
                        *[p for p, _ in part])
                    blens = (ctypes.c_uint64 * len(part))(
                        *[len(p) for p, _ in part])
                    counts = (ctypes.c_int64 * len(part))(
                        *[b.count for _, b in part])
                    deflate = 1 if part[0][1].codec == "deflate" else 0
                    rc = lib.avd_decode_blocks_mt(
                        handle, datas, blens, counts, len(part), deflate,
                        prog, len(prog), fis, ptrs, hdims, n_shards,
                        threads)
                    if rc != 0:
                        err = lib.avd_error(handle)
                        raise ValueError(
                            f"{path}: native decode failed: "
                            f"{err.decode() if err else rc}")
                rows = int(lib.avd_rows(handle))
                nnz = int(lib.avd_nnz(handle))
                counts_a = _np_from(lib.avd_feat_counts(handle), rows,
                                    np.int64)
                flat_val = _np_from(lib.avd_feat_values(handle), nnz,
                                    np.float64)
                features = {}
                for si, shard in enumerate(shards):
                    imap = index_maps[shard]
                    flat_idx = _np_from(lib.avd_feat_indices(handle, si),
                                        nnz, np.int32)
                    idx, val = _pad_features(counts_a, flat_idx, flat_val,
                                             imap.intercept_index)
                    features[shard] = HostSparse(idx, val, imap.size)
                (labels, has_label, offsets, weights, uids,
                 entity_vals) = _extract_scalars(lib, handle, rows,
                                                 entity_columns)
            finally:
                lib.avd_free(handle)
            labels = labels.copy()
            missing = ~has_label.astype(bool)
            if require_response and missing.any():
                i = int(np.argmax(missing))
                raise ValueError(
                    f"record uid={uids[i]} has no '{cols.response}' — "
                    "training data must be labeled")
            labels[missing] = np.nan
            yield features, labels, offsets, weights, entity_vals, uids
    finally:
        for r in resolvers:
            r.close()


def _chunked_python(windows, index_maps, entity_columns, cols,
                    require_response):
    from photon_ml_tpu.io.stream_source import iter_block_records

    for window in windows:
        rows_per_shard = {s: [] for s in index_maps}
        labels, offsets, weights, uids = [], [], [], []
        entity_vals = {c: [] for c in entity_columns}
        for rec in iter_block_records(window):
            label, offset, weight, uid, evals, shard_rows = _parse_record(
                rec, cols, index_maps, entity_columns, require_response)
            labels.append(label)
            offsets.append(offset)
            weights.append(weight)
            uids.append(uid)
            for c, v in zip(entity_columns, evals):
                entity_vals[c].append(v)
            for shard, row in shard_rows.items():
                rows_per_shard[shard].append(row)
        features = {
            shard: _rows_to_host_sparse(rows, index_maps[shard].size)
            for shard, rows in rows_per_shard.items()
        }
        yield (features, np.asarray(labels), np.asarray(offsets),
               np.asarray(weights),
               {c: np.asarray(v) for c, v in entity_vals.items()}, uids)
