"""Avro training-data reader/writer honoring the TrainingExampleAvro contract.

Equivalent of the reference's ``data.avro.AvroDataReader`` +
``NameAndTermFeatureMapUtils`` (SURVEY.md §3.3; reference mount empty):
reads records with name/term/value feature arrays, maps them through
per-shard feature index maps into padded sparse matrices, and carries
response/offset/weight/uid plus entity-id columns (from ``metadataMap``)
for GAME random effects.
"""

from __future__ import annotations

import os
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from photon_ml_tpu.game.data import HostSparse
from photon_ml_tpu.io.avro import iter_avro_records, write_avro_file
from photon_ml_tpu.io.index_map import IndexMap
from photon_ml_tpu.io.schemas import (
    INTERCEPT_KEY,
    TRAINING_EXAMPLE_SCHEMA,
    feature_key,
)
import dataclasses


@dataclasses.dataclass(frozen=True)
class InputColumnsNames:
    """Record-field name overrides — the reference's ``InputColumnsNames``
    (SURVEY.md §3.2 GAME data layer row): datasets whose response / offset /
    weight / uid / features / metadata fields use different names are read
    without rewriting."""

    response: str = "response"
    offset: str = "offset"
    weight: str = "weight"
    uid: str = "uid"
    features: str = "features"
    metadata_map: str = "metadataMap"

    @classmethod
    def from_dict(cls, d: Optional[dict]) -> "InputColumnsNames":
        if not d:
            return cls()
        unknown = set(d) - {f.name for f in dataclasses.fields(cls)}
        if unknown:
            raise ValueError(f"unknown input column keys: {sorted(unknown)}")
        return cls(**d)


def read_training_examples(
    paths,
    index_maps: IndexMap | Dict[str, IndexMap],
    entity_columns: Sequence[str] = (),
    columns: Optional[InputColumnsNames] = None,
    require_response: bool = True,
):
    """Read Avro training examples into per-shard sparse features.

    Returns (features: dict shard->HostSparse, labels, offsets, weights,
    entity_ids: dict column->np.ndarray, uids: list). Features absent from a
    shard's index map are dropped for that shard (per-shard feature
    selection, as in the reference's feature bags).

    Decoding runs through the native C++ decoder (io/native_reader.py —
    the host-ingestion hot path, SURVEY.md §7) whenever the writer schema
    and index-map backend support it, falling back to the pure-Python codec
    otherwise. Set PHOTON_ML_TPU_NO_NATIVE=1 to force the Python path."""
    if not isinstance(index_maps, dict):  # any IndexMap-like backend
        index_maps = {"global": index_maps}
    cols = columns or InputColumnsNames()
    if not os.environ.get("PHOTON_ML_TPU_NO_NATIVE"):
        from photon_ml_tpu.io.native_reader import (
            NativeUnsupported,
            read_training_examples_native,
        )

        try:
            return read_training_examples_native(
                paths, index_maps, entity_columns, cols, require_response
            )
        except NativeUnsupported:
            pass
    rows_per_shard: Dict[str, List[List[Tuple[int, float]]]] = {
        s: [] for s in index_maps
    }
    labels: List[float] = []
    offsets: List[float] = []
    weights: List[float] = []
    uids: List = []
    entity_vals: Dict[str, List] = {c: [] for c in entity_columns}

    for rec in iter_avro_records(paths):
        if require_response:
            val = rec.get(cols.response)
            if val is None:
                raise ValueError(
                    f"record uid={rec.get(cols.uid)} has no "
                    f"'{cols.response}' — training data must be labeled"
                )
            labels.append(float(val))
        else:
            # scoring data may be unlabeled (the reference scores label-less
            # rows); NaN marks "no label" downstream
            val = rec.get(cols.response)
            labels.append(float("nan") if val is None else float(val))
        offsets.append(float(rec[cols.offset])
                       if rec.get(cols.offset) is not None else 0.0)
        weights.append(float(rec[cols.weight])
                       if rec.get(cols.weight) is not None else 1.0)
        uids.append(rec.get(cols.uid))
        meta = rec.get(cols.metadata_map) or {}
        for c in entity_columns:
            if c not in meta:
                raise ValueError(f"record uid={rec.get(cols.uid)} missing "
                                 f"entity column '{c}' in "
                                 f"{cols.metadata_map}")
            entity_vals[c].append(meta[c])
        for shard, imap in index_maps.items():
            row: List[Tuple[int, float]] = []
            for feat in rec[cols.features]:
                idx = imap.index_of(feat["name"], feat.get("term", ""))
                if idx is not None:
                    row.append((idx, float(feat["value"])))
            if imap.intercept_index >= 0:
                row.append((imap.intercept_index, 1.0))
            rows_per_shard[shard].append(row)

    features = {
        shard: _rows_to_host_sparse(rows, index_maps[shard].size)
        for shard, rows in rows_per_shard.items()
    }
    return (
        features,
        np.asarray(labels),
        np.asarray(offsets),
        np.asarray(weights),
        {c: np.asarray(v) for c, v in entity_vals.items()},
        uids,
    )


def _rows_to_host_sparse(rows: List[List[Tuple[int, float]]], dim: int) -> HostSparse:
    n = len(rows)
    k = max(max((len(r) for r in rows), default=0), 1)
    indices = np.zeros((n, k), np.int32)
    values = np.zeros((n, k))
    for i, row in enumerate(rows):
        for j, (idx, val) in enumerate(row):
            indices[i, j] = idx
            values[i, j] = val
    return HostSparse(indices, values, dim)


def write_training_examples(
    path: str,
    features: Iterable[Iterable[Tuple[str, str, float]]],
    labels: Optional[Sequence[float]] = None,
    offsets: Optional[Sequence[float]] = None,
    weights: Optional[Sequence[float]] = None,
    entity_ids: Optional[Dict[str, Sequence]] = None,
    uids: Optional[Sequence] = None,
    codec: str = "deflate",
    block_size: int = 4096,
) -> None:
    """Write TrainingExampleAvro records; ``features`` yields per-row lists
    of (name, term, value). ``labels=None`` writes unlabeled scoring data.
    ``block_size`` (records per container block) controls the granularity
    available to block-level consumers (AvroChunkSource process_part)."""
    entity_ids = entity_ids or {}

    def records():
        for i, row in enumerate(features):
            label = None if labels is None else labels[i]
            yield {
                "uid": str(uids[i]) if uids is not None else str(i),
                "response": None if label is None else float(label),
                "offset": float(offsets[i]) if offsets is not None else None,
                "weight": float(weights[i]) if weights is not None else None,
                "features": [
                    {"name": name, "term": term, "value": float(v)}
                    for name, term, v in row
                ],
                "metadataMap": {c: str(vals[i]) for c, vals in entity_ids.items()},
            }

    write_avro_file(path, records(), TRAINING_EXAMPLE_SCHEMA, codec=codec,
                    block_size=block_size)


def feature_tuples_from_dense(X: np.ndarray, prefix: str = "f"):
    """Helper for fixtures: dense matrix -> per-row (name, term, value)."""
    for row in np.asarray(X):
        yield [(f"{prefix}{j}", "", float(v)) for j, v in enumerate(row) if v != 0]
