from photon_ml_tpu.io.avro import read_avro_file, write_avro_file, parse_schema
from photon_ml_tpu.io.schemas import (
    TRAINING_EXAMPLE_SCHEMA,
    BAYESIAN_LINEAR_MODEL_SCHEMA,
    SCORING_RESULT_SCHEMA,
    FEATURE_SUMMARIZATION_SCHEMA,
)
from photon_ml_tpu.io.index_map import IndexMap, build_index_map
from photon_ml_tpu.io.data_reader import read_training_examples, write_training_examples
from photon_ml_tpu.io.model_io import save_game_model, load_game_model
from photon_ml_tpu.io.libsvm import read_libsvm
