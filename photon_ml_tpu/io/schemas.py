"""Avro schemas: the external data contract.

Reconstructions of the reference's ``photon-avro-schemas`` module
(SURVEY.md §3.4; reference mount empty, so field surfaces follow the
documented upstream contract): training examples carry a response, optional
offset/weight/uid and a list of name/term/value feature records (name+term
is the feature key); models are saved as Bayesian linear models with
per-coefficient name/term/value means and optional variances; scoring
results carry uid + score.
"""

FEATURE_SCHEMA = {
    "type": "record",
    "name": "FeatureAvro",
    "fields": [
        {"name": "name", "type": "string"},
        {"name": "term", "type": "string", "default": ""},
        {"name": "value", "type": "double"},
    ],
}

TRAINING_EXAMPLE_SCHEMA = {
    "type": "record",
    "name": "TrainingExampleAvro",
    "fields": [
        {"name": "uid", "type": ["null", "string", "long"], "default": None},
        # nullable: scoring inputs may be unlabeled; training requires it
        {"name": "response", "type": ["null", "double"], "default": None},
        {"name": "offset", "type": ["null", "double"], "default": None},
        {"name": "weight", "type": ["null", "double"], "default": None},
        {"name": "features", "type": {"type": "array", "items": FEATURE_SCHEMA}},
        # entity-id columns for GAME random effects (e.g. userId, itemId)
        {"name": "metadataMap", "type": {"type": "map", "values": "string"},
         "default": {}},
    ],
}

COEFFICIENT_SCHEMA = {
    "type": "record",
    "name": "NameTermValueAvro",
    "fields": [
        {"name": "name", "type": "string"},
        {"name": "term", "type": "string", "default": ""},
        {"name": "value", "type": "double"},
    ],
}

BAYESIAN_LINEAR_MODEL_SCHEMA = {
    "type": "record",
    "name": "BayesianLinearModelAvro",
    "fields": [
        {"name": "modelId", "type": "string"},
        {"name": "modelClass", "type": ["null", "string"], "default": None},
        {"name": "means", "type": {"type": "array", "items": COEFFICIENT_SCHEMA}},
        {"name": "variances",
         "type": ["null", {"type": "array", "items": "NameTermValueAvro"}],
         "default": None},
        {"name": "lossFunction", "type": ["null", "string"], "default": None},
    ],
}

SCORING_RESULT_SCHEMA = {
    "type": "record",
    "name": "ScoringResultAvro",
    "fields": [
        {"name": "uid", "type": ["null", "string", "long"], "default": None},
        {"name": "predictionScore", "type": "double"},
        {"name": "label", "type": ["null", "double"], "default": None},
        # optional per-coordinate score breakdown
        {"name": "scoreComponents", "type": {"type": "map", "values": "double"},
         "default": {}},
    ],
}

FEATURE_SUMMARIZATION_SCHEMA = {
    "type": "record",
    "name": "FeatureSummarizationResultAvro",
    "fields": [
        {"name": "name", "type": "string"},
        {"name": "term", "type": "string", "default": ""},
        {"name": "mean", "type": "double"},
        {"name": "variance", "type": "double"},
        {"name": "min", "type": "double"},
        {"name": "max", "type": "double"},
        {"name": "numNonzeros", "type": "double"},
        {"name": "count", "type": "long"},
    ],
}

# separator between feature name and term when forming the flat key, as in
# the reference's NameAndTerm utilities (SURVEY.md §3.3)
NAME_TERM_SEPARATOR = "\x01"


def feature_key(name: str, term: str = "") -> str:
    return f"{name}{NAME_TERM_SEPARATOR}{term}" if term else name


def split_feature_key(key: str):
    if NAME_TERM_SEPARATOR in key:
        name, term = key.split(NAME_TERM_SEPARATOR, 1)
        return name, term
    return key, ""


INTERCEPT_KEY = "(INTERCEPT)"
