"""Persistent (off-heap) feature index map backed by the native store.

Equivalent of the reference's ``index.{PalDBIndexMap, PalDBIndexMapBuilder}``
(SURVEY.md §3.3; reference mount empty, paths unverified): feature
name/term → index maps too large for a per-process Python dict are built
once into an mmap-backed file (``photon_ml_tpu/native/feature_index_store
.cpp``) and opened with zero parse time. Duck-types ``IndexMap`` (size,
intercept_index, index_of, inverse, save/load) so every driver accepts
either backend.
"""

from __future__ import annotations

import ctypes
import os
from typing import Dict, Iterator, Optional, Tuple

import numpy as np

from photon_ml_tpu.io.schemas import INTERCEPT_KEY, feature_key
from photon_ml_tpu.native import load_library

_ENC = "utf-8"


def _lib() -> ctypes.CDLL:
    lib = load_library("feature_index_store")
    if not getattr(lib, "_fis_configured", False):
        lib.fis_build.restype = ctypes.c_int
        lib.fis_build.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint32), ctypes.POINTER(ctypes.c_int32),
            ctypes.c_uint64, ctypes.c_char_p,
        ]
        lib.fis_open.restype = ctypes.c_void_p
        lib.fis_open.argtypes = [ctypes.c_char_p]
        lib.fis_close.argtypes = [ctypes.c_void_p]
        lib.fis_size.restype = ctypes.c_uint64
        lib.fis_size.argtypes = [ctypes.c_void_p]
        lib.fis_num_slots.restype = ctypes.c_uint64
        lib.fis_num_slots.argtypes = [ctypes.c_void_p]
        lib.fis_lookup.restype = ctypes.c_int32
        lib.fis_lookup.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                   ctypes.c_uint32]
        lib.fis_lookup_batch.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint32), ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_int32),
        ]
        lib.fis_entry.restype = ctypes.c_int
        lib.fis_entry.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint32), ctypes.POINTER(ctypes.c_int32),
        ]
        lib.fis_keys_blob.restype = ctypes.c_void_p
        lib.fis_keys_blob.argtypes = [ctypes.c_void_p]
        lib._fis_configured = True
    return lib


def build_store(forward: Dict[str, int], path: str) -> None:
    """Write a persistent store from a key→index dict (the
    PalDBIndexMapBuilder role)."""
    lib = _lib()
    keys = [k.encode(_ENC) for k in forward]
    n = len(keys)
    lens = np.array([len(k) for k in keys], np.uint32)
    offsets = np.zeros(n, np.uint64)
    if n:
        np.cumsum(lens[:-1], out=offsets[1:])
    blob = b"".join(keys)
    indices = np.fromiter(forward.values(), np.int32, count=n)
    rc = lib.fis_build(
        blob,
        offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        lens.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
        indices.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        ctypes.c_uint64(n),
        path.encode(),
    )
    if rc != 0:
        raise OSError(-rc, f"fis_build failed for {path} (rc={rc})")


class PersistentIndexMap:
    """Read-only mmap-backed feature index map (the PalDBIndexMap role)."""

    def __init__(self, path: str):
        self.path = path
        self._lib = _lib()
        self._handle = self._lib.fis_open(path.encode())
        if not self._handle:
            raise OSError(f"cannot open feature index store: {path}")
        self._intercept = self._lookup_key(INTERCEPT_KEY.encode(_ENC))

    # -- IndexMap duck-type surface ------------------------------------------
    @property
    def size(self) -> int:
        return int(self._lib.fis_size(self._handle))

    @property
    def intercept_index(self) -> int:
        return self._intercept

    def index_of(self, name: str, term: str = "") -> Optional[int]:
        idx = self._lookup_key(feature_key(name, term).encode(_ENC))
        return None if idx < 0 else idx

    def inverse(self) -> Dict[int, str]:
        return {idx: key for key, idx in self.items()}

    @property
    def forward(self) -> Dict[str, int]:
        """Materialized key→index dict. Only for small-map interop paths
        (e.g. per-shard filtering); bulk lookups should use lookup_batch."""
        return dict(self.items())

    def digest(self) -> str:
        """Feature-space fingerprint (chunk-cache invalidation key): the
        store file's content hash. O(file) once per job — the store is
        immutable after build, so callers may cache the result."""
        import hashlib

        h = hashlib.sha256()
        with open(self.path, "rb") as f:
            for block in iter(lambda: f.read(1 << 20), b""):
                h.update(block)
        return h.hexdigest()

    def save(self, path: str) -> None:
        """Copy the store file (saving alongside models, as drivers do)."""
        if os.path.abspath(path) != os.path.abspath(self.path):
            import shutil

            shutil.copyfile(self.path, path)

    @classmethod
    def load(cls, path: str) -> "PersistentIndexMap":
        return cls(path)

    @classmethod
    def build(cls, forward: Dict[str, int], path: str) -> "PersistentIndexMap":
        build_store(forward, path)
        return cls(path)

    # -- extras ---------------------------------------------------------------
    def _lookup_key(self, key: bytes) -> int:
        return int(self._lib.fis_lookup(self._handle, key,
                                        ctypes.c_uint32(len(key))))

    def items(self) -> Iterator[Tuple[str, int]]:
        keys_ptr = self._lib.fis_keys_blob(self._handle)
        key_off = ctypes.c_uint64()
        key_len = ctypes.c_uint32()
        index = ctypes.c_int32()
        for slot in range(int(self._lib.fis_num_slots(self._handle))):
            if self._lib.fis_entry(self._handle, ctypes.c_uint64(slot),
                                   ctypes.byref(key_off), ctypes.byref(key_len),
                                   ctypes.byref(index)):
                key = ctypes.string_at(keys_ptr + key_off.value, key_len.value)
                yield key.decode(_ENC), int(index.value)

    def lookup_batch(self, keys) -> np.ndarray:
        """Vectorized lookup: list of key strings -> int32 indices (-1 if
        absent). One C call for the whole batch — the bulk ingestion path."""
        enc = [k.encode(_ENC) for k in keys]
        n = len(enc)
        lens = np.array([len(k) for k in enc], np.uint32)
        offsets = np.zeros(n, np.uint64)
        if n:
            np.cumsum(lens[:-1], out=offsets[1:])
        blob = b"".join(enc)
        out = np.empty(n, np.int32)
        self._lib.fis_lookup_batch(
            self._handle, blob,
            offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            lens.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            ctypes.c_uint64(n),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        )
        return out

    def close(self) -> None:
        if self._handle:
            self._lib.fis_close(self._handle)
            self._handle = None

    def __del__(self):  # pragma: no cover - GC timing
        try:
            self.close()
        except Exception:
            pass


def load_index_map(path: str):
    """Open either backend by sniffing the file: native store (binary magic)
    or JSON, dispatched on the parsed top-level key — never on raw-byte
    substrings, which key order/whitespace or feature names could fool.
    Drivers use this so --index-map takes any format."""
    with open(path, "rb") as f:
        head = f.read(1)
    if head != b"{":  # native store starts with its binary magic
        return PersistentIndexMap(path)
    import json

    with open(path) as f:
        doc = json.load(f)
    if "hashing" in doc:
        from photon_ml_tpu.io.hashing import HashingIndexMap

        cfg = doc["hashing"]
        return HashingIndexMap(cfg["dim"], add_intercept=cfg["add_intercept"])
    from photon_ml_tpu.io.index_map import IndexMap

    return IndexMap(doc["features"])
