"""Feature index maps: name/term string -> dense column index.

Equivalent of the reference's ``index.{IndexMap, DefaultIndexMap,
PalDBIndexMap, PalDBIndexMapBuilder}`` (SURVEY.md §3.3; reference mount
empty). The reference offers an in-memory map or an off-heap PalDB store
built by a dedicated Spark job (``FeatureIndexingDriver``); here a plain
dict plus a compact binary file replaces PalDB (SURVEY.md §3.7: no native
store needed), and ``build_index_map`` plays the indexing-driver role.
Supports one map per feature shard.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, Iterable, Optional

from photon_ml_tpu.io.schemas import INTERCEPT_KEY, feature_key


@dataclasses.dataclass
class IndexMap:
    forward: Dict[str, int]  # feature key -> index
    add_intercept: bool = False

    def __post_init__(self):
        if self.add_intercept and INTERCEPT_KEY not in self.forward:
            self.forward[INTERCEPT_KEY] = len(self.forward)

    @property
    def size(self) -> int:
        return len(self.forward)

    @property
    def intercept_index(self) -> int:
        return self.forward.get(INTERCEPT_KEY, -1)

    def index_of(self, name: str, term: str = "") -> Optional[int]:
        return self.forward.get(feature_key(name, term))

    def inverse(self) -> Dict[int, str]:
        return {v: k for k, v in self.forward.items()}

    def digest(self) -> str:
        """Content fingerprint of the feature space (key -> index mapping
        and intercept placement). Cache layers key decoded artifacts on
        this: two maps with the same digest resolve every feature
        identically, so a cached decode is reusable; any remap must miss."""
        import hashlib

        h = hashlib.sha256()
        for key, idx in sorted(self.forward.items()):
            h.update(f"{key}\x00{idx}\x01".encode())
        return h.hexdigest()

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump({"features": self.forward}, f)

    @classmethod
    def load(cls, path: str) -> "IndexMap":
        with open(path) as f:
            payload = json.load(f)
        return cls(payload["features"])


def build_index_map(
    records: Iterable,
    add_intercept: bool = True,
    min_count: int = 1,
    features_field: str = "features",
) -> IndexMap:
    """Scan training example records (dicts with a ``features`` list of
    name/term/value) and assign dense indices — the FeatureIndexingDriver
    role. ``min_count`` drops rare features."""
    counts: Dict[str, int] = {}
    for rec in records:
        for feat in rec[features_field]:
            key = feature_key(feat["name"], feat.get("term", ""))
            counts[key] = counts.get(key, 0) + 1
    keys = sorted(k for k, c in counts.items() if c >= min_count)
    forward = {k: i for i, k in enumerate(keys)}
    return IndexMap(forward, add_intercept=add_intercept)


def filter_index_map(
    imap: IndexMap, prefixes: Iterable[str], add_intercept: bool = True
) -> IndexMap:
    """Restrict an index map to feature names starting with any prefix and
    re-densify indices — per-shard feature selection (the reference's
    feature bags / shard configs, SURVEY.md §4.1). Empty prefix matches all."""
    prefixes = list(prefixes)
    keys = sorted(
        k for k in imap.forward
        if k != INTERCEPT_KEY and any(k.startswith(p) for p in prefixes)
    )
    forward = {k: i for i, k in enumerate(keys)}
    return IndexMap(forward, add_intercept=add_intercept)
