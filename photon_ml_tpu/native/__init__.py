"""Native (C++) runtime components, built on demand with the system
toolchain and loaded via ctypes (pybind11 is not available in this image).

Currently: the persistent feature index store (``feature_index_store.cpp``)
— the PalDB replacement (SURVEY.md §3.3/§3.7).
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading

_BUILD_LOCK = threading.Lock()
_NATIVE_DIR = os.path.dirname(os.path.abspath(__file__))


class NativeBuildError(RuntimeError):
    pass


def _source_digest(src_path: str, extra: tuple = ()) -> str:
    """Digest of source + build flags — flag changes must rebuild too."""
    h = hashlib.sha256()
    with open(src_path, "rb") as f:
        h.update(f.read())
    for item in extra:
        h.update(item.encode())
    return h.hexdigest()[:16]


def _build_dir() -> str:
    """Writable cache dir for compiled libraries: the package tree when
    writable (repo checkouts), else a per-user cache (pip installs into
    root-owned site-packages must not be written to)."""
    in_tree = os.path.join(_NATIVE_DIR, "_build")
    probe_dir = in_tree if os.path.isdir(in_tree) else _NATIVE_DIR
    if os.access(probe_dir, os.W_OK):
        return in_tree
    base = os.environ.get("XDG_CACHE_HOME",
                          os.path.join(os.path.expanduser("~"), ".cache"))
    return os.path.join(base, "photon_ml_tpu", "native")


# extra link flags per native library
_LINK_FLAGS = {"avro_decoder": ("-pthread", "-lz")}


def build_library(name: str, *, cxx: str | None = None) -> str:
    """Compile ``<name>.cpp`` into a cached ``.so`` and return its path.
    The cache key includes a source digest, so editing the .cpp rebuilds."""
    src = os.path.join(_NATIVE_DIR, f"{name}.cpp")
    if not os.path.exists(src):
        raise NativeBuildError(f"no such native source: {src}")
    out_dir = _build_dir()
    flags = _LINK_FLAGS.get(name, ())
    lib = os.path.join(out_dir, f"lib{name}-{_source_digest(src, flags)}.so")
    with _BUILD_LOCK:
        if os.path.exists(lib):
            return lib
        os.makedirs(out_dir, exist_ok=True)
        cxx = cxx or os.environ.get("CXX", "g++")
        cmd = [cxx, "-O3", "-std=c++17", "-shared", "-fPIC", src, "-o",
               lib + ".tmp", *flags]
        try:
            proc = subprocess.run(cmd, capture_output=True, text=True)
        except FileNotFoundError as e:
            raise NativeBuildError(f"compiler not found: {cxx}") from e
        if proc.returncode != 0:
            raise NativeBuildError(
                f"native build failed ({' '.join(cmd)}):\n{proc.stderr}"
            )
        os.replace(lib + ".tmp", lib)
    return lib


_LOADED: dict[str, ctypes.CDLL] = {}


def load_library(name: str) -> ctypes.CDLL:
    if name not in _LOADED:
        _LOADED[name] = ctypes.CDLL(build_library(name))
    return _LOADED[name]
