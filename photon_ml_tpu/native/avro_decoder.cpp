// Native Avro training-example decoder: the host-side ingestion hot path.
//
// SURVEY.md 7 flags the host<->device data pipeline as the likely real
// bottleneck at TB scale ("overlap Avro decode/index with device compute").
// The reference leans on the JVM + Spark for decode throughput; the
// TPU-native equivalent is this C++ decoder: it walks Avro object-container
// blocks (null/deflate codecs), executes a compact field program compiled by
// Python from the writer schema, and materializes columnar buffers (labels /
// offsets / weights, ragged feature index+value arrays, selected metadata
// columns) with feature name/term resolution done in-process — against the
// mmap'd feature index store (feature_index_store.cpp) or by FNV-1a hashing
// — so per-feature work never touches the Python interpreter.
//
// Field program: one opcode per top-level record field, executed in order
// per record.
//   0x01 CAP_LABEL_D        double
//   0x02 CAP_LABEL_ND u8    union, followed by the null-branch index
//   0x03 CAP_OFFSET_D       (same pattern for offset / weight)
//   0x04 CAP_OFFSET_ND u8
//   0x05 CAP_WEIGHT_D
//   0x06 CAP_WEIGHT_ND u8
//   0x07 CAP_FEATURES       array<record{name:string, term:string, value:double}>
//   0x08 CAP_METADATA       map<string,string>; keys matched against the
//                           requested entity columns
//   0x09 CAP_UID u8 is_union, u8 n, then n branch kinds (0=null 1=string
//                           2=long); a union uid reads a branch index from
//                           the stream (Avro writes one even for 1-branch
//                           unions), a plain uid does not
//   0x10 SKIP_NULL  0x11 SKIP_BOOL  0x12 SKIP_VARINT  0x13 SKIP_FLOAT
//   0x14 SKIP_DOUBLE  0x15 SKIP_BYTES (string/bytes)
//   0x16 SKIP_UNION u8 n, then n sub-opcodes (branch dispatch)
//   0x17 SKIP_ARRAY, sub-opcode          0x18 SKIP_MAP, value sub-opcode
//   0x19 SKIP_RECORD u8 n, then n sub-opcodes
//
// Python (io/native_reader.py) validates the writer schema shape before
// choosing this path and falls back to the pure-Python reader otherwise.

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <vector>
#include <zlib.h>

// Feature-index-store lookup, passed in as a function pointer by Python
// (ctypes address of fis_lookup from the separately-loaded
// feature_index_store library) so this library has no undefined externs
// and dlopens standalone.
using fis_lookup_fn = int32_t (*)(void*, const char*, uint32_t);

namespace {

constexpr uint8_t CAP_LABEL_D = 0x01, CAP_LABEL_ND = 0x02, CAP_OFFSET_D = 0x03,
                  CAP_OFFSET_ND = 0x04, CAP_WEIGHT_D = 0x05,
                  CAP_WEIGHT_ND = 0x06, CAP_FEATURES = 0x07,
                  CAP_METADATA = 0x08, CAP_UID = 0x09;
constexpr uint8_t SKIP_NULL = 0x10, SKIP_BOOL = 0x11, SKIP_VARINT = 0x12,
                  SKIP_FLOAT = 0x13, SKIP_DOUBLE = 0x14, SKIP_BYTES = 0x15,
                  SKIP_UNION = 0x16, SKIP_ARRAY = 0x17, SKIP_MAP = 0x18,
                  SKIP_RECORD = 0x19;

constexpr uint64_t kFnvOffset = 0xCBF29CE484222325ULL;
constexpr uint64_t kFnvPrime = 0x100000001B3ULL;

struct Cursor {
  const uint8_t* p;
  const uint8_t* end;
  bool fail = false;

  bool need(size_t n) {
    if (static_cast<size_t>(end - p) < n) {
      fail = true;
      return false;
    }
    return true;
  }
  int64_t read_long() {  // zigzag varint
    uint64_t acc = 0;
    int shift = 0;
    while (true) {
      if (!need(1)) return 0;
      uint8_t b = *p++;
      acc |= static_cast<uint64_t>(b & 0x7F) << shift;
      if (!(b & 0x80)) break;
      shift += 7;
      if (shift > 63) {
        fail = true;
        return 0;
      }
    }
    return static_cast<int64_t>(acc >> 1) ^ -static_cast<int64_t>(acc & 1);
  }
  double read_double() {
    if (!need(8)) return 0.0;
    double v;
    std::memcpy(&v, p, 8);
    p += 8;
    return v;
  }
  void skip(size_t n) {
    if (need(n)) p += n;
  }
};

struct EntityCol {
  std::string key;
  // per-row value bytes (concatenated) + offsets
  std::vector<uint8_t> blob;
  std::vector<uint64_t> offsets;  // size rows+1
  std::vector<uint8_t> present;   // per row: key present in metadataMap
};

struct Output {
  std::vector<double> labels, offsets, weights;
  std::vector<uint8_t> has_label;
  std::vector<int32_t> feat_counts;  // per row
  // per shard: one resolved index per feature occurrence (-1 = dropped);
  // the name/term/value walk happens once, resolution fans out per shard
  std::vector<std::vector<int32_t>> feat_indices;
  std::vector<double> feat_values;
  std::vector<EntityCol> entities;
  EntityCol uid;                      // per-row uid bytes (string/long text)
  std::vector<uint8_t> uid_kind;      // 0=null, 1=string, 2=long
  uint64_t rows = 0;
  std::string error;
};

uint64_t fnv1a(const uint8_t* s, size_t len, uint64_t h = kFnvOffset) {
  for (size_t i = 0; i < len; ++i) {
    h ^= s[i];
    h *= kFnvPrime;
  }
  return h;
}

// Skip one value described by the sub-opcode program at *prog (advances it).
void skip_value(Cursor& c, const uint8_t*& prog, const uint8_t* prog_end);

void skip_blocks(Cursor& c, const uint8_t* item_prog,
                 const uint8_t* prog_end, bool is_map) {
  while (!c.fail) {
    int64_t count = c.read_long();
    if (count == 0) break;
    if (count < 0) {  // block with byte size: skip wholesale
      int64_t size = c.read_long();
      if (size < 0) {
        c.fail = true;
        return;
      }
      c.skip(static_cast<size_t>(size));
      continue;
    }
    for (int64_t i = 0; i < count && !c.fail; ++i) {
      if (is_map) {
        int64_t klen = c.read_long();
        if (klen < 0) {
          c.fail = true;
          return;
        }
        c.skip(static_cast<size_t>(klen));
      }
      const uint8_t* p = item_prog;
      skip_value(c, p, prog_end);
    }
  }
}

void skip_value(Cursor& c, const uint8_t*& prog, const uint8_t* prog_end) {
  if (prog >= prog_end) {
    c.fail = true;
    return;
  }
  uint8_t op = *prog++;
  switch (op) {
    case SKIP_NULL:
      break;
    case SKIP_BOOL:
      c.skip(1);
      break;
    case SKIP_VARINT:
      c.read_long();
      break;
    case SKIP_FLOAT:
      c.skip(4);
      break;
    case SKIP_DOUBLE:
      c.skip(8);
      break;
    case SKIP_BYTES: {
      int64_t len = c.read_long();
      if (len < 0) {
        c.fail = true;
        return;
      }
      c.skip(static_cast<size_t>(len));
      break;
    }
    case SKIP_UNION: {
      if (prog >= prog_end) {
        c.fail = true;
        return;
      }
      uint8_t n = *prog++;
      // locate branch sub-programs (they are laid out back to back)
      int64_t branch = c.read_long();
      const uint8_t* p = prog;
      for (uint8_t i = 0; i < n; ++i) {
        if (i == branch) {
          const uint8_t* bp = p;
          skip_value(c, bp, prog_end);
        } else {
          // advance p past this branch without consuming input
          Cursor dummy{nullptr, nullptr};
          dummy.fail = true;  // never reads
          const uint8_t* bp = p;
          // structural walk: reuse skip_value's program advance by walking
          // with a cursor that can't read; we only need prog advancement
          skip_value(dummy, bp, prog_end);
          p = bp;
          continue;
        }
        // advance p past consumed branch program
        {
          Cursor dummy{nullptr, nullptr};
          dummy.fail = true;
          const uint8_t* bp = p;
          skip_value(dummy, bp, prog_end);
          p = bp;
        }
      }
      if (branch < 0 || branch >= n) c.fail = true;
      prog = p;
      break;
    }
    case SKIP_ARRAY: {
      const uint8_t* item = prog;
      // advance prog past the item program
      Cursor dummy{nullptr, nullptr};
      dummy.fail = true;
      const uint8_t* bp = prog;
      skip_value(dummy, bp, prog_end);
      skip_blocks(c, item, prog_end, /*is_map=*/false);
      prog = bp;
      break;
    }
    case SKIP_MAP: {
      const uint8_t* item = prog;
      Cursor dummy{nullptr, nullptr};
      dummy.fail = true;
      const uint8_t* bp = prog;
      skip_value(dummy, bp, prog_end);
      skip_blocks(c, item, prog_end, /*is_map=*/true);
      prog = bp;
      break;
    }
    case SKIP_RECORD: {
      if (prog >= prog_end) {
        c.fail = true;
        return;
      }
      uint8_t n = *prog++;
      for (uint8_t i = 0; i < n; ++i) skip_value(c, prog, prog_end);
      break;
    }
    default:
      c.fail = true;
  }
}

double read_nullable_double(Cursor& c, uint8_t null_branch, bool* present) {
  int64_t branch = c.read_long();
  if (branch == null_branch) {
    *present = false;
    return 0.0;
  }
  *present = true;
  return c.read_double();
}

struct FeatureResolver {
  void* fis;             // feature_index_store handle, may be null
  fis_lookup_fn lookup;  // its lookup entry point (ctypes-provided)
  int64_t hash_dim;      // >0: FNV hash % dim when no store
  char sep;              // name/term separator (\x01)

  int32_t resolve(const uint8_t* name, size_t nlen, const uint8_t* term,
                  size_t tlen) const {
    if (fis && lookup) {
      // key = name [sep term]
      char stack_buf[256];
      std::vector<char> heap_buf;
      size_t klen = nlen + (tlen ? 1 + tlen : 0);
      char* key = stack_buf;
      if (klen > sizeof(stack_buf)) {
        heap_buf.resize(klen);
        key = heap_buf.data();
      }
      std::memcpy(key, name, nlen);
      if (tlen) {
        key[nlen] = sep;
        std::memcpy(key + nlen + 1, term, tlen);
      }
      return lookup(fis, key, static_cast<uint32_t>(klen));
    }
    if (hash_dim > 0) {
      uint64_t h = fnv1a(name, nlen);
      if (tlen) {
        uint8_t s = static_cast<uint8_t>(sep);
        h = fnv1a(&s, 1, h);
        h = fnv1a(term, tlen, h);
      }
      return static_cast<int32_t>(h % static_cast<uint64_t>(hash_dim));
    }
    return -1;
  }
};

// Decode the features array: record{name, term, value} items, resolving
// each feature against every shard's resolver in one walk.
void decode_features(Cursor& c, const std::vector<FeatureResolver>& frs,
                     Output& out) {
  int32_t count = 0;
  while (!c.fail) {
    int64_t n = c.read_long();
    if (n == 0) break;
    if (n < 0) {
      c.read_long();  // byte size (unused; we still decode items)
      n = -n;
    }
    for (int64_t i = 0; i < n && !c.fail; ++i) {
      int64_t nlen = c.read_long();
      if (nlen < 0 || !c.need(static_cast<size_t>(nlen))) {
        c.fail = true;
        return;
      }
      const uint8_t* name = c.p;
      c.p += nlen;
      int64_t tlen = c.read_long();
      if (tlen < 0 || !c.need(static_cast<size_t>(tlen))) {
        c.fail = true;
        return;
      }
      const uint8_t* term = c.p;
      c.p += tlen;
      double value = c.read_double();
      for (size_t s = 0; s < frs.size(); ++s) {
        out.feat_indices[s].push_back(
            frs[s].resolve(name, static_cast<size_t>(nlen), term,
                           static_cast<size_t>(tlen)));
      }
      out.feat_values.push_back(value);
      ++count;
    }
  }
  out.feat_counts.push_back(count);
}

void decode_metadata(Cursor& c, Output& out, uint64_t row) {
  // mark all entity columns absent for this row, fill when seen
  while (!c.fail) {
    int64_t n = c.read_long();
    if (n == 0) break;
    if (n < 0) {
      c.read_long();
      n = -n;
    }
    for (int64_t i = 0; i < n && !c.fail; ++i) {
      int64_t klen = c.read_long();
      if (klen < 0 || !c.need(static_cast<size_t>(klen))) {
        c.fail = true;
        return;
      }
      const uint8_t* key = c.p;
      c.p += klen;
      int64_t vlen = c.read_long();
      if (vlen < 0 || !c.need(static_cast<size_t>(vlen))) {
        c.fail = true;
        return;
      }
      const uint8_t* val = c.p;
      c.p += vlen;
      for (auto& col : out.entities) {
        if (col.offsets.size() == row + 2) continue;  // already set
        if (col.key.size() == static_cast<size_t>(klen) &&
            std::memcmp(col.key.data(), key, klen) == 0) {
          col.blob.insert(col.blob.end(), val, val + vlen);
          col.offsets.push_back(col.blob.size());
          col.present.push_back(1);
        }
      }
    }
  }
}

bool decode_record(Cursor& c, const uint8_t* prog, const uint8_t* prog_end,
                   const std::vector<FeatureResolver>& frs, Output& out) {
  uint64_t row = out.rows;
  bool saw_features = false, saw_meta = false;
  double label = 0.0, offset = 0.0, weight = 1.0;
  bool has_label = false;
  const uint8_t* p = prog;
  while (p < prog_end && !c.fail) {
    uint8_t op = *p++;
    bool present;
    switch (op) {
      case CAP_LABEL_D:
        label = c.read_double();
        has_label = true;
        break;
      case CAP_LABEL_ND:
        label = read_nullable_double(c, *p++, &present);
        has_label = present;
        break;
      case CAP_OFFSET_D:
        offset = c.read_double();
        break;
      case CAP_OFFSET_ND:
        offset = read_nullable_double(c, *p++, &present);
        if (!present) offset = 0.0;
        break;
      case CAP_WEIGHT_D:
        weight = c.read_double();
        break;
      case CAP_WEIGHT_ND:
        weight = read_nullable_double(c, *p++, &present);
        if (!present) weight = 1.0;
        break;
      case CAP_FEATURES:
        decode_features(c, frs, out);
        saw_features = true;
        break;
      case CAP_METADATA:
        decode_metadata(c, out, row);
        saw_meta = true;
        break;
      case CAP_UID: {
        // program: u8 is_union, u8 n, then n branch kinds (0=null 1=string
        // 2=long); unions carry a branch index in the stream even when they
        // have a single branch
        uint8_t is_union = *p++;
        uint8_t n = *p++;
        int64_t branch = is_union ? c.read_long() : 0;
        if (branch < 0 || branch >= n) {
          c.fail = true;
          break;
        }
        uint8_t kind = p[branch];
        p += n;
        if (kind == 1) {  // string
          int64_t len = c.read_long();
          if (len < 0 || !c.need(static_cast<size_t>(len))) {
            c.fail = true;
            break;
          }
          out.uid.blob.insert(out.uid.blob.end(), c.p, c.p + len);
          c.p += len;
        } else if (kind == 2) {  // long -> decimal text
          char buf[24];
          int len = std::snprintf(buf, sizeof(buf), "%lld",
                                  static_cast<long long>(c.read_long()));
          out.uid.blob.insert(out.uid.blob.end(), buf, buf + len);
        }
        out.uid.offsets.push_back(out.uid.blob.size());
        out.uid_kind.push_back(kind);
        break;
      }
      default:
        --p;
        skip_value(c, p, prog_end);
    }
  }
  if (c.fail) return false;
  if (!saw_features) out.feat_counts.push_back(0);
  for (auto& col : out.entities) {
    if (col.offsets.size() == row + 1) {  // column absent for this row
      col.offsets.push_back(col.blob.size());
      col.present.push_back(0);
    }
  }
  (void)saw_meta;
  out.labels.push_back(label);
  out.has_label.push_back(has_label ? 1 : 0);
  out.offsets.push_back(offset);
  out.weights.push_back(weight);
  out.rows += 1;
  return true;
}

bool inflate_block(const uint8_t* src, size_t src_len,
                   std::vector<uint8_t>& dst) {
  // Avro deflate = raw DEFLATE (windowBits = -15)
  z_stream zs;
  std::memset(&zs, 0, sizeof(zs));
  if (inflateInit2(&zs, -15) != Z_OK) return false;
  zs.next_in = const_cast<Bytef*>(src);
  zs.avail_in = static_cast<uInt>(src_len);
  dst.clear();
  dst.resize(src_len * 4 + 64);
  size_t written = 0;
  int rc;
  do {
    if (written == dst.size()) dst.resize(dst.size() * 2);
    zs.next_out = dst.data() + written;
    zs.avail_out = static_cast<uInt>(dst.size() - written);
    rc = inflate(&zs, Z_NO_FLUSH);
    written = dst.size() - zs.avail_out;
    if (rc == Z_BUF_ERROR && zs.avail_in == 0) break;
  } while (rc == Z_OK);
  inflateEnd(&zs);
  if (rc != Z_STREAM_END) return false;
  dst.resize(written);
  return true;
}

// Decode one raw block payload (inflating if needed) into `out`. The
// serial entry point and every worker thread of the parallel one both land
// here.
bool decode_one_block(Output& out, const uint8_t* data, size_t len,
                      bool codec_deflate, int64_t n_records,
                      const uint8_t* prog, uint32_t prog_len,
                      const std::vector<FeatureResolver>& frs) {
  std::vector<uint8_t> scratch;
  const uint8_t* payload = data;
  size_t payload_len = len;
  if (codec_deflate) {
    if (!inflate_block(data, payload_len, scratch)) {
      out.error = "deflate decode failed";
      return false;
    }
    payload = scratch.data();
    payload_len = scratch.size();
  }
  Cursor c{payload, payload + payload_len};
  for (int64_t i = 0; i < n_records; ++i) {
    if (!decode_record(c, prog, prog + prog_len, frs, out)) {
      out.error = "record decode failed at row " + std::to_string(out.rows);
      return false;
    }
  }
  return true;
}

// Append `src` onto `dst` preserving row order (per-row ragged offsets are
// rebased). `src` is left in a moved-from state.
void merge_output(Output& dst, Output& src) {
  auto app = [](auto& a, auto& b) {
    a.insert(a.end(), b.begin(), b.end());
    b.clear();
    b.shrink_to_fit();
  };
  app(dst.labels, src.labels);
  app(dst.has_label, src.has_label);
  app(dst.offsets, src.offsets);
  app(dst.weights, src.weights);
  app(dst.feat_counts, src.feat_counts);
  for (size_t s = 0; s < dst.feat_indices.size(); ++s)
    app(dst.feat_indices[s], src.feat_indices[s]);
  app(dst.feat_values, src.feat_values);
  auto app_col = [&](EntityCol& d, EntityCol& s) {
    uint64_t base = d.blob.size();
    app(d.blob, s.blob);
    for (size_t i = 1; i < s.offsets.size(); ++i)
      d.offsets.push_back(base + s.offsets[i]);
    app(d.present, s.present);
  };
  for (size_t e = 0; e < dst.entities.size(); ++e)
    app_col(dst.entities[e], src.entities[e]);
  app_col(dst.uid, src.uid);
  app(dst.uid_kind, src.uid_kind);
  dst.rows += src.rows;
}

// A worker-local Output mirroring the main handle's column structure.
Output make_like(const Output& main_out) {
  Output out;
  out.uid.offsets.push_back(0);
  out.feat_indices.resize(main_out.feat_indices.size());
  for (const auto& col : main_out.entities) {
    EntityCol c;
    c.key = col.key;
    c.offsets.push_back(0);
    out.entities.push_back(std::move(c));
  }
  return out;
}

}  // namespace

extern "C" {

// Decode one Avro container file. `block_payloads` are handed in by Python
// (which parses the container header/sync framing and the schema — framing
// is cheap; per-record decode is the hot part):
//   avd_create(entity_keys_blob, key_lens, n_keys) -> Output*
//   avd_decode_block(out, data, len, codec, n_records, prog, prog_len,
//                    fis_handle, hash_dim) -> 0 on success
//   getters + avd_free
void* avd_create(const char* keys_blob, const uint32_t* key_lens,
                 uint32_t n_keys, uint32_t n_shards) {
  Output* out = new Output();
  out->uid.offsets.push_back(0);
  out->feat_indices.resize(n_shards ? n_shards : 1);
  size_t at = 0;
  for (uint32_t i = 0; i < n_keys; ++i) {
    EntityCol col;
    col.key.assign(keys_blob + at, key_lens[i]);
    col.offsets.push_back(0);
    at += key_lens[i];
    out->entities.push_back(std::move(col));
  }
  return out;
}

// One resolver triple (fis handle, lookup fn, hash_dim) per feature shard;
// the record walk happens once, feature resolution fans out to all shards.
int avd_decode_block(void* handle, const uint8_t* data, uint64_t len,
                     int codec_deflate, int64_t n_records, const uint8_t* prog,
                     uint32_t prog_len, void* const* fis_handles,
                     void* const* fis_lookup_ptrs, const int64_t* hash_dims,
                     uint32_t n_shards) {
  Output* out = static_cast<Output*>(handle);
  if (n_shards != out->feat_indices.size()) {
    out->error = "shard count mismatch vs avd_create";
    return -3;
  }
  std::vector<FeatureResolver> frs;
  for (uint32_t s = 0; s < n_shards; ++s) {
    frs.push_back(FeatureResolver{
        fis_handles[s],
        reinterpret_cast<fis_lookup_fn>(fis_lookup_ptrs[s]),
        hash_dims[s], '\x01'});
  }
  return decode_one_block(*out, data, static_cast<size_t>(len),
                          codec_deflate != 0, n_records, prog, prog_len, frs)
             ? 0
             : -2;
}

// Parallel variant: container blocks are independent by construction (each
// carries its own record count and compressed payload), so N threads decode
// disjoint blocks into per-block staging Outputs which are then concatenated
// in block order — byte-identical results to the serial path, ~cores x the
// throughput (the round-2 decoder measured ~30 MB/s single-thread; SURVEY.md
// §3.3: the reference amortizes decode across 256 Spark executors).
// Resolver state is shared read-only (the feature index store is an mmap'd
// hash table; FNV hashing is stateless), so no locks are needed.
int avd_decode_blocks_mt(void* handle, const uint8_t* const* datas,
                         const uint64_t* lens, const int64_t* counts,
                         uint64_t n_blocks, int codec_deflate,
                         const uint8_t* prog, uint32_t prog_len,
                         void* const* fis_handles,
                         void* const* fis_lookup_ptrs,
                         const int64_t* hash_dims, uint32_t n_shards,
                         uint32_t n_threads) {
  Output* out = static_cast<Output*>(handle);
  if (n_shards != out->feat_indices.size()) {
    out->error = "shard count mismatch vs avd_create";
    return -3;
  }
  std::vector<FeatureResolver> frs;
  for (uint32_t s = 0; s < n_shards; ++s) {
    frs.push_back(FeatureResolver{
        fis_handles[s],
        reinterpret_cast<fis_lookup_fn>(fis_lookup_ptrs[s]),
        hash_dims[s], '\x01'});
  }
  if (n_threads <= 1 || n_blocks <= 1) {
    for (uint64_t b = 0; b < n_blocks; ++b) {
      if (!decode_one_block(*out, datas[b], static_cast<size_t>(lens[b]),
                            codec_deflate != 0, counts[b], prog, prog_len,
                            frs))
        return -2;
    }
    return 0;
  }

  std::vector<Output> staging;
  staging.reserve(n_blocks);
  for (uint64_t b = 0; b < n_blocks; ++b) staging.push_back(make_like(*out));
  std::atomic<uint64_t> next{0};
  std::atomic<bool> failed{false};
  uint32_t workers = static_cast<uint32_t>(
      n_threads < n_blocks ? n_threads : n_blocks);
  std::vector<std::thread> threads;
  threads.reserve(workers);
  for (uint32_t t = 0; t < workers; ++t) {
    threads.emplace_back([&]() {
      while (!failed.load(std::memory_order_relaxed)) {
        uint64_t b = next.fetch_add(1, std::memory_order_relaxed);
        if (b >= n_blocks) break;
        if (!decode_one_block(staging[b], datas[b],
                              static_cast<size_t>(lens[b]),
                              codec_deflate != 0, counts[b], prog, prog_len,
                              frs))
          failed.store(true, std::memory_order_relaxed);
      }
    });
  }
  for (auto& th : threads) th.join();
  if (failed.load()) {
    for (uint64_t b = 0; b < n_blocks; ++b) {
      if (!staging[b].error.empty()) {
        out->error = "block " + std::to_string(b) + ": " + staging[b].error;
        break;
      }
    }
    return -2;
  }
  for (uint64_t b = 0; b < n_blocks; ++b) merge_output(*out, staging[b]);
  return 0;
}

uint64_t avd_rows(void* handle) { return static_cast<Output*>(handle)->rows; }
uint64_t avd_nnz(void* handle) {
  return static_cast<Output*>(handle)->feat_values.size();
}
const double* avd_labels(void* handle) {
  return static_cast<Output*>(handle)->labels.data();
}
const uint8_t* avd_has_label(void* handle) {
  return static_cast<Output*>(handle)->has_label.data();
}
const double* avd_offsets(void* handle) {
  return static_cast<Output*>(handle)->offsets.data();
}
const double* avd_weights(void* handle) {
  return static_cast<Output*>(handle)->weights.data();
}
const int32_t* avd_feat_counts(void* handle) {
  return static_cast<Output*>(handle)->feat_counts.data();
}
const int32_t* avd_feat_indices(void* handle, uint32_t shard) {
  Output* out = static_cast<Output*>(handle);
  if (shard >= out->feat_indices.size()) return nullptr;
  return out->feat_indices[shard].data();
}
const double* avd_feat_values(void* handle) {
  return static_cast<Output*>(handle)->feat_values.data();
}
const char* avd_error(void* handle) {
  return static_cast<Output*>(handle)->error.c_str();
}
int avd_uid(void* handle, const uint8_t** blob, const uint64_t** offsets,
            const uint8_t** kinds, uint64_t* n) {
  Output* out = static_cast<Output*>(handle);
  *blob = out->uid.blob.data();
  *offsets = out->uid.offsets.data();
  *kinds = out->uid_kind.data();
  *n = out->uid_kind.size();
  return 0;
}
int avd_entity_col(void* handle, uint32_t col, const uint8_t** blob,
                   const uint64_t** offsets, const uint8_t** present,
                   uint64_t* n) {
  Output* out = static_cast<Output*>(handle);
  if (col >= out->entities.size()) return -1;
  EntityCol& e = out->entities[col];
  *blob = e.blob.data();
  *offsets = e.offsets.data();
  *present = e.present.data();
  *n = e.offsets.size() - 1;
  return 0;
}
void avd_free(void* handle) { delete static_cast<Output*>(handle); }

}  // extern "C"
