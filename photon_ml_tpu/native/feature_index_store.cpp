// Persistent, mmap-backed feature index store.
//
// TPU-native equivalent of the reference's PalDB-based feature index maps
// (index.PalDBIndexMap / PalDBIndexMapBuilder -- SURVEY.md 3.3; reference
// mount empty, paths unverified): a read-only key->index store built once by
// the feature-indexing driver and then opened by every training / scoring
// process with zero parse time (mmap) and no Python-heap cost per entry.
//
// File layout (little-endian, 8-byte aligned):
//   Header | Slot[num_slots] | keys blob
// Open-addressed hash table with linear probing; FNV-1a 64 hashing; hash
// value 0 marks an empty slot (occupied hashes are forced odd).
//
// C API (ctypes-friendly), exported below:
//   fis_build(blob, offsets, lens, indices, n, path) -> 0/-errno
//   fis_open(path) -> handle|NULL, fis_close(handle)
//   fis_size(handle), fis_lookup(handle, key, len) -> index|-1
//   fis_lookup_batch(handle, blob, offsets, lens, n, out_indices)
//   fis_entry(handle, slot, &key_off, &key_len, &index) -> 1 if occupied

#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x5048304649445831ULL;  // "PH0FIDX1"

struct Header {
  uint64_t magic;
  uint64_t num_entries;
  uint64_t num_slots;  // power of two
  uint64_t keys_offset;
  uint64_t keys_size;
};

struct Slot {
  uint64_t hash;      // 0 = empty
  uint64_t key_off;   // offset into keys blob
  uint32_t key_len;
  int32_t index;
};

struct Store {
  void* map;
  size_t map_size;
  const Header* header;
  const Slot* slots;
  const char* keys;
};

uint64_t fnv1a(const char* s, uint32_t len) {
  uint64_t h = 1469598103934665603ULL;
  for (uint32_t i = 0; i < len; ++i) {
    h ^= static_cast<unsigned char>(s[i]);
    h *= 1099511628211ULL;
  }
  return h | 1ULL;  // never 0 so 0 can mark empty slots
}

uint64_t next_pow2(uint64_t x) {
  uint64_t p = 1;
  while (p < x) p <<= 1;
  return p;
}

}  // namespace

extern "C" {

// Build the store file. Keys arrive as one concatenated blob with per-key
// (offset, len); duplicate keys are rejected (-EEXIST).
int fis_build(const char* blob, const uint64_t* offsets, const uint32_t* lens,
              const int32_t* indices, uint64_t n, const char* path) {
  // load factor <= 0.5 keeps linear-probe chains short
  uint64_t num_slots = next_pow2(n == 0 ? 1 : n * 2);
  uint64_t keys_size = 0;
  for (uint64_t i = 0; i < n; ++i) keys_size += lens[i];

  Header header;
  std::memset(&header, 0, sizeof(header));
  header.magic = kMagic;
  header.num_entries = n;
  header.num_slots = num_slots;
  header.keys_offset = sizeof(Header) + num_slots * sizeof(Slot);
  header.keys_size = keys_size;

  Slot* slots = static_cast<Slot*>(std::calloc(num_slots, sizeof(Slot)));
  if (!slots) return -ENOMEM;

  uint64_t mask = num_slots - 1;
  for (uint64_t i = 0; i < n; ++i) {
    uint64_t h = fnv1a(blob + offsets[i], lens[i]);
    uint64_t s = h & mask;
    while (slots[s].hash != 0) {
      if (slots[s].hash == h && slots[s].key_len == lens[i] &&
          std::memcmp(blob + slots[s].key_off, blob + offsets[i], lens[i]) == 0) {
        std::free(slots);
        return -EEXIST;
      }
      s = (s + 1) & mask;
    }
    slots[s].hash = h;
    slots[s].key_off = offsets[i];
    slots[s].key_len = lens[i];
    slots[s].index = indices[i];
  }

  FILE* f = std::fopen(path, "wb");
  if (!f) {
    std::free(slots);
    return -errno;
  }
  int rc = 0;
  if (std::fwrite(&header, sizeof(header), 1, f) != 1) rc = -EIO;
  if (rc == 0 && num_slots &&
      std::fwrite(slots, sizeof(Slot), num_slots, f) != num_slots)
    rc = -EIO;
  if (rc == 0 && keys_size && std::fwrite(blob, 1, keys_size, f) != keys_size)
    rc = -EIO;
  // NOTE: assumes each key's bytes live at blob[offsets[i]..+lens[i]) within
  // one contiguous blob of exactly keys_size bytes (the Python builder
  // guarantees this); key_off indexes that same blob after mmap.
  if (std::fclose(f) != 0 && rc == 0) rc = -EIO;
  std::free(slots);
  return rc;
}

void* fis_open(const char* path) {
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || static_cast<size_t>(st.st_size) < sizeof(Header)) {
    ::close(fd);
    return nullptr;
  }
  void* map = mmap(nullptr, st.st_size, PROT_READ, MAP_SHARED, fd, 0);
  ::close(fd);
  if (map == MAP_FAILED) return nullptr;

  const Header* header = static_cast<const Header*>(map);
  if (header->magic != kMagic ||
      header->keys_offset + header->keys_size !=
          static_cast<uint64_t>(st.st_size)) {
    munmap(map, st.st_size);
    return nullptr;
  }
  Store* store = new Store;
  store->map = map;
  store->map_size = st.st_size;
  store->header = header;
  store->slots = reinterpret_cast<const Slot*>(static_cast<const char*>(map) +
                                               sizeof(Header));
  store->keys = static_cast<const char*>(map) + header->keys_offset;
  return store;
}

void fis_close(void* handle) {
  Store* store = static_cast<Store*>(handle);
  if (!store) return;
  munmap(store->map, store->map_size);
  delete store;
}

uint64_t fis_size(void* handle) {
  return static_cast<Store*>(handle)->header->num_entries;
}

uint64_t fis_num_slots(void* handle) {
  return static_cast<Store*>(handle)->header->num_slots;
}

int32_t fis_lookup(void* handle, const char* key, uint32_t len) {
  const Store* store = static_cast<Store*>(handle);
  uint64_t mask = store->header->num_slots - 1;
  uint64_t h = fnv1a(key, len);
  uint64_t s = h & mask;
  while (store->slots[s].hash != 0) {
    const Slot& slot = store->slots[s];
    if (slot.hash == h && slot.key_len == len &&
        std::memcmp(store->keys + slot.key_off, key, len) == 0)
      return slot.index;
    s = (s + 1) & mask;
  }
  return -1;
}

void fis_lookup_batch(void* handle, const char* blob, const uint64_t* offsets,
                      const uint32_t* lens, uint64_t n, int32_t* out) {
  for (uint64_t i = 0; i < n; ++i)
    out[i] = fis_lookup(handle, blob + offsets[i], lens[i]);
}

// Iterate hash slots (0..num_slots): returns 1 and fills outputs if the slot
// is occupied. Iteration order is slot order, not insertion order.
int fis_entry(void* handle, uint64_t slot, uint64_t* key_off,
              uint32_t* key_len, int32_t* index) {
  const Store* store = static_cast<Store*>(handle);
  if (slot >= store->header->num_slots) return 0;
  const Slot& s = store->slots[slot];
  if (s.hash == 0) return 0;
  *key_off = s.key_off;
  *key_len = s.key_len;
  *index = s.index;
  return 1;
}

const char* fis_keys_blob(void* handle) {
  return static_cast<Store*>(handle)->keys;
}

}  // extern "C"
