"""Version compatibility shims for the jax API surface this repo targets.

The code is written against the current jax API (``jax.shard_map`` with
``check_vma``, ``jax.typeof``); older runtimes (0.4.x, where ``shard_map``
still lives in ``jax.experimental`` and replication checking is spelled
``check_rep``) are common in pinned TPU images, and every entry point in
this package must keep working there. One shim module, imported as
``from photon_ml_tpu.compat import shard_map, typeof``, so the
per-call-site hasattr probing never spreads through the codebase.
"""

from __future__ import annotations

import jax

__all__ = ["shard_map", "typeof", "random_multinomial", "VMA_TRANSPOSE"]

# True on the jax.shard_map era: varying-manual-axes (vma) tracking makes
# the AD transpose of "replicated operand touches sharded data" insert the
# gradient's psum automatically inside a shard_map body. The legacy
# check_rep shard_map leaves inside-body AD collective-free, so call sites
# that rely on the auto-inserted all-reduce must psum their partial
# gradients explicitly when this is False (a static trace-time branch).
VMA_TRANSPOSE = hasattr(jax, "shard_map")


if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:
    from jax.experimental.shard_map import shard_map as _shard_map_legacy

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
        """Legacy spelling: ``check_vma`` was ``check_rep`` before shard_map
        graduated out of jax.experimental; semantics (skip the replication/
        varying-axes type check and its AD-transpose collective insertion)
        are the same for every use in this repo."""
        return _shard_map_legacy(f, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_rep=check_vma)


if hasattr(jax.random, "multinomial"):
    random_multinomial = jax.random.multinomial
else:
    def random_multinomial(key, n, p, *, shape):
        """Legacy fallback: ``n`` iid categorical draws per output row,
        histogrammed — exactly a Multinomial(n, p) sample. ``n`` and
        ``shape`` must be static (they are, at the bootstrap call site)."""
        import jax.numpy as jnp

        k = p.shape[-1]
        assert shape[-1] == k, (shape, k)
        rows = 1
        for s in shape[:-1]:
            rows *= s
        draws = jax.random.categorical(key, jnp.log(p), axis=-1,
                                       shape=(rows, int(n)))
        counts = jax.vmap(
            lambda d: jnp.zeros((k,), jnp.int32).at[d].add(1))(draws)
        return counts.reshape(shape)


if hasattr(jax, "typeof"):
    typeof = jax.typeof
else:
    def typeof(x):
        """Pre-``jax.typeof`` fallback: the abstract value. Callers in this
        repo only read optional attributes off the result (``.vma`` with a
        frozenset default), and legacy avals simply don't carry them."""
        aval = getattr(x, "aval", None)
        if aval is not None:
            return aval
        return jax.core.get_aval(x)
