"""Small runtime-config helpers shared by the CLI drivers."""

from __future__ import annotations


def resolve_dtype(name: str):
    """Map a ``--dtype`` flag to a jnp dtype, enabling x64 first when needed
    (jax truncates f64 arrays silently otherwise)."""
    import jax
    import jax.numpy as jnp

    if name == "float64":
        jax.config.update("jax_enable_x64", True)
        return jnp.float64
    return jnp.float32
