"""Small runtime-config helpers shared by the CLI drivers."""

from __future__ import annotations

import os


def apply_env_platforms() -> None:
    """Re-apply an explicit ``JAX_PLATFORMS`` env var over whatever a
    sitecustomize pinned at interpreter startup.

    This container's axon sitecustomize force-sets
    ``jax_platforms=axon,cpu`` before any user code runs, which silently
    overrides the env var; a harness told ``JAX_PLATFORMS=cpu`` (CI smoke,
    the session dry-run) would otherwise hang in the axon plugin's
    connect-retry loop when the tunnel is wedged. Call right after
    ``import jax``, before any device use. No-op when the env var is
    unset or the backend is already initialized."""
    import jax

    if os.environ.get("JAX_PLATFORMS"):
        try:
            jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
        except RuntimeError:
            pass


def resolve_dtype(name: str):
    """Map a ``--dtype`` flag to a jnp dtype, enabling x64 first when needed
    (jax truncates f64 arrays silently otherwise)."""
    import jax
    import jax.numpy as jnp

    if name == "float64":
        jax.config.update("jax_enable_x64", True)
        return jnp.float64
    return jnp.float32


def is_device_loss(exc: BaseException) -> bool:
    """True when an exception means the accelerator backend died under us
    (TPU worker crash / tunnel loss). The dead backend cannot be
    reinitialized in-process (measured, docs/RUNBOOK.md §5), so every
    driver converts this into an exit-75 process-boundary retry. One
    predicate, shared by all drivers — refine detection here only.

    A coordinated abort (``resilience.PeerFailure``) counts when ANY
    process of the job reported device loss: every process must take the
    resume-marker exit path together, not only the one whose device died."""
    import jax

    from photon_ml_tpu.parallel.resilience import PeerFailure

    if isinstance(exc, PeerFailure):
        return exc.device_loss or (exc.__cause__ is not None
                                   and is_device_loss(exc.__cause__))
    return (isinstance(exc, jax.errors.JaxRuntimeError)
            and "UNAVAILABLE" in str(exc))
