"""Host->device transfer budget for hardware measurement sessions.

The axon TPU tunnel in this environment wedges — and has twice crashed the
TPU worker — on bulk host->device transfers (r03: one ~800MB upload at
04:57 cost the round its chip; see docs/PERF.md "Measuring through the
axon tunnel"). The protection is structural, not procedural: every
sanctioned upload in the measurement harnesses is routed through
:func:`charge` / :func:`device_put`, and a session-configured budget makes
an oversized transfer raise *on the host, before any bytes move*, instead
of killing the worker.

Two limits, both in bytes:

- ``single``: the per-transfer cap (default 64 MB). This is the actual
  wedge vector — one huge contiguous upload. Chunked uploads of the same
  total are fine (~10MB pieces demonstrably safe on the tunnel).
- ``total``: the per-process cap (default 256 MB). Streaming benches that
  legitimately move more declare it via :func:`waive` / a larger env
  budget, so the waiver is visible in the harness source.

Activation: explicitly via :func:`set_budget`, or ambiently via the
``PHOTON_TRANSFER_BUDGET_MB`` / ``PHOTON_TRANSFER_SINGLE_MB`` env vars
(read at first use — the session runner sets them per experiment). With
no budget configured every charge is a no-op, so library users outside
measurement sessions never see this module.

Design note: JAX's own ``jax_transfer_guard`` is not used — on the CPU
backend host->device "transfers" are zero-copy and never fire the guard,
which would make the mandated CPU dry-run of the session vacuous, and on
any backend it cannot distinguish a sanctioned chunked upload from the
800MB mistake. Byte accounting at the call sites is deterministic and
dry-testable.
"""

from __future__ import annotations

import os
import threading
from typing import Optional

__all__ = [
    "TransferBudgetExceeded", "set_budget", "get_budget", "charge",
    "device_put", "waive", "set_activity_hook",
]

# Optional per-charge callback (no args). Measurement harnesses use it as a
# liveness signal: every sanctioned upload — including the margin-ladder
# streams that fire no optimizer-progress callback — proves the run is not
# wedged, so a stall watchdog fed from here cannot falsely kill a live fit
# that is mid-line-search (ADVICE r4).
_activity_hook = None


def set_activity_hook(fn) -> None:
    """Install (or with ``None`` clear) a zero-arg callback fired on every
    budget charge, regardless of whether a budget is configured."""
    global _activity_hook
    _activity_hook = fn


class TransferBudgetExceeded(RuntimeError):
    """A sanctioned upload would exceed the session's transfer budget."""


class _Budget:
    def __init__(self, total: float, single: float, label: str = ""):
        self.total = float(total)
        self.single = float(single)
        self.label = label
        self.spent = 0.0
        self._lock = threading.Lock()

    def charge(self, nbytes: int, what: str = "") -> None:
        nbytes = int(nbytes)
        if nbytes > self.single:
            raise TransferBudgetExceeded(
                f"single host->device transfer of {nbytes/1e6:.1f} MB "
                f"exceeds the per-transfer cap {self.single/1e6:.1f} MB"
                f"{' [' + what + ']' if what else ''} — chunk it (~10MB "
                "pieces are tunnel-safe); bulk uploads have crashed the "
                "TPU worker (docs/PERF.md)")
        with self._lock:
            if self.spent + nbytes > self.total:
                raise TransferBudgetExceeded(
                    f"transfer of {nbytes/1e6:.1f} MB would take this "
                    f"process to {(self.spent + nbytes)/1e6:.1f} MB, over "
                    f"the {self.total/1e6:.1f} MB budget"
                    f"{' [' + what + ']' if what else ''} — synthesize on "
                    "device, or waive explicitly (transfer_budget.waive / "
                    "PHOTON_TRANSFER_BUDGET_MB) if this experiment is "
                    "meant to move bulk data")
            self.spent += nbytes


_budget: Optional[_Budget] = None
_initialized = False


def _ambient() -> Optional[_Budget]:
    """Budget from the environment, if the session runner set one."""
    mb = os.environ.get("PHOTON_TRANSFER_BUDGET_MB")
    if not mb:
        return None
    single = float(os.environ.get("PHOTON_TRANSFER_SINGLE_MB", "64"))
    return _Budget(float(mb) * 1e6, single * 1e6, label="env")


def set_budget(total_mb: Optional[float], single_mb: float = 64.0,
               label: str = "") -> None:
    """Install (or with ``None`` clear) the process transfer budget."""
    global _budget, _initialized
    _initialized = True
    _budget = (None if total_mb is None
               else _Budget(total_mb * 1e6, single_mb * 1e6, label))


def get_budget() -> Optional[_Budget]:
    global _budget, _initialized
    if not _initialized:
        _initialized = True
        _budget = _ambient()
    return _budget


def waive(extra_total_mb: float, reason: str) -> None:
    """Raise the total cap for an experiment that legitimately moves bulk
    data (e.g. a streaming bench). The reason is mandatory so the waiver
    is auditable at the call site; the per-transfer cap stays."""
    b = get_budget()
    if b is not None:
        assert reason, "a transfer-budget waiver needs a reason"
        with b._lock:
            b.total += extra_total_mb * 1e6


def charge(nbytes: int, what: str = "") -> None:
    """Account ``nbytes`` of imminent host->device transfer against the
    budget (no-op when none is configured). Call BEFORE the upload."""
    if _activity_hook is not None:
        _activity_hook()
    b = get_budget()
    if b is not None and nbytes:
        b.charge(nbytes, what)


def device_put(x, sharding=None, what: str = ""):
    """Budget-accounted ``jax.device_put`` for host-resident arrays.

    Charges anything exposing ``nbytes`` that is not already a ``jax.Array``
    — not just ``np.ndarray`` — so chunks built from array-protocol objects
    (memoryviews, mmap-backed arrays, torch CPU tensors) cannot silently
    bypass the budget (ADVICE r4). A committed ``jax.Array`` input is a
    no-op transfer and charges nothing."""
    import jax

    if not isinstance(x, jax.Array):
        nbytes = getattr(x, "nbytes", 0)
        if nbytes:
            charge(int(nbytes), what or "device_put")
    return jax.device_put(x, sharding)
