"""Structured run logging + stage timers.

Equivalent of the reference's ``PhotonLogger`` (a structured log file
written next to outputs — SURVEY.md §5.5) and its ``Timed`` stage wrappers
(SURVEY.md §5.1). Events are JSON lines so downstream tooling can parse
them; optimizer-level convergence traces live in OptimizationResult's
loss/grad-norm histories and are logged per coordinate by the drivers.
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Optional


class PhotonLogger:
    """JSONL event logger writing to a file and (optionally) stderr."""

    def __init__(self, path: Optional[str] = None, echo: bool = True):
        self.path = path
        self.echo = echo
        self._fh = None
        if path:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            self._fh = open(path, "a")

    def log(self, event: str, **fields) -> None:
        record = {"ts": time.time(), "event": event, **fields}
        line = json.dumps(record, default=str)
        if self._fh:
            self._fh.write(line + "\n")
            self._fh.flush()
        if self.echo:
            print(line, file=sys.stderr)

    def close(self) -> None:
        if self._fh:
            self._fh.close()
            self._fh = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class Timed:
    """Context manager timing a stage and logging wall-clock seconds."""

    def __init__(self, logger: Optional[PhotonLogger], stage: str):
        self.logger = logger
        self.stage = stage
        self.seconds = 0.0

    def __enter__(self):
        self._t0 = time.time()
        return self

    def __exit__(self, *exc):
        self.seconds = time.time() - self._t0
        if self.logger is not None:
            self.logger.log("stage_timing", stage=self.stage, seconds=self.seconds)
