"""Profiling / tracing hooks.

The reference has no real tracer — driver stages are wall-clock timed and
``OptimizationStatesTracker`` records per-iteration optimizer state, with
Spark's UI covering task-level profiling (SURVEY.md §5.1). The TPU-native
rebuild keeps the stage timers (``utils.logging.Timed``) and optimizer
histories (``OptimizationResult.loss_history``), and adds the JAX profiler
for device-level traces: pass ``--profile-dir`` to a driver (or use
``profile_trace``) and load the result in TensorBoard/Perfetto.
"""

from __future__ import annotations

import contextlib
from typing import Iterator, Optional


@contextlib.contextmanager
def profile_trace(trace_dir: Optional[str]) -> Iterator[None]:
    """Capture a JAX profiler trace into ``trace_dir`` (no-op when None)."""
    if not trace_dir:
        yield
        return
    import jax

    jax.profiler.start_trace(trace_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named sub-span inside an active trace (jax.profiler.TraceAnnotation);
    usable as a context manager."""
    import jax

    return jax.profiler.TraceAnnotation(name)
