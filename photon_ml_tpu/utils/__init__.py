from photon_ml_tpu.utils.config import resolve_dtype
from photon_ml_tpu.utils.logging import PhotonLogger, Timed
