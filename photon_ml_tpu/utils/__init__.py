from photon_ml_tpu.utils.config import (apply_env_platforms, is_device_loss,
                                         resolve_dtype)
from photon_ml_tpu.utils.logging import PhotonLogger, Timed
from photon_ml_tpu.utils.tracing import annotate, profile_trace
