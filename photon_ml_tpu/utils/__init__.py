from photon_ml_tpu.utils.logging import PhotonLogger, Timed
