from photon_ml_tpu.models.glm import (
    Coefficients,
    GeneralizedLinearModel,
    FixedEffectModel,
    RandomEffectBucket,
    RandomEffectModel,
    GameModel,
)
