"""Model types: coefficients, GLMs, fixed/random-effect and GAME composites.

Equivalents of the reference's ``model.{Coefficients, GeneralizedLinearModel,
LogisticRegressionModel, ...}`` and the distributed ``model.{FixedEffectModel,
RandomEffectModel, GameModel}`` (SURVEY.md §3.1/§3.2; reference mount empty).
TPU-native differences:

* A random-effect model is not an RDD of per-entity model objects but a set
  of dense coefficient *matrices* — one ``[num_entities, local_dim]`` array
  per size bucket — plus host-side entity-id indexes and per-entity
  projections into the global feature space (the ``LinearSubspaceProjector``
  role). This keeps per-entity scoring a gather + batched dot, not a join.
* Task type is carried as the loss name; the inverse link for scoring comes
  from the loss definition (``PointwiseLoss.mean``).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Mapping, Optional, Sequence

import jax
import numpy as np
from flax import struct

from photon_ml_tpu.ops.losses import get_loss
from photon_ml_tpu.types import Features, margins as _margins


@struct.dataclass
class Coefficients:
    """Means + optional variances (the Bayesian-linear-model payload the
    reference saves as BayesianLinearModelAvro — SURVEY.md §3.4)."""

    means: jax.Array
    variances: Optional[jax.Array] = None

    @property
    def dim(self) -> int:
        return self.means.shape[-1]


@dataclasses.dataclass(frozen=True)
class GeneralizedLinearModel:
    """A single GLM: score = margin = x.w (+ offset); mean = inv_link(margin)."""

    coefficients: Coefficients
    task: str = "logistic"

    @property
    def loss(self):
        return get_loss(self.task)

    def score(self, features: Features, offsets=0.0) -> jax.Array:
        return _margins(features, self.coefficients.means) + offsets

    def predict_mean(self, features: Features, offsets=0.0) -> jax.Array:
        return self.loss.mean(self.score(features, offsets))


@dataclasses.dataclass(frozen=True)
class FixedEffectModel:
    """Global coefficients over one feature shard (replicated across the
    mesh at train/score time — the broadcast replacement)."""

    model: GeneralizedLinearModel
    feature_shard: str = "global"

    def score(self, features: Features, offsets=0.0) -> jax.Array:
        return self.model.score(features, offsets)


@dataclasses.dataclass(frozen=True)
class RandomEffectBucket:
    """Per-entity coefficients for one size bucket.

    Attributes:
      entity_ids: host-side sequence of entity keys, length E.
      coefficients: [E, D_local] per-entity coefficients in local subspace.
      variances: optional [E, D_local].
      projection: int32 [E, D_local] — global feature id of each local slot,
        -1 for padding slots.
    """

    entity_ids: Sequence
    coefficients: np.ndarray | jax.Array
    projection: np.ndarray | jax.Array
    variances: Optional[np.ndarray] = None
    # set when the bucket's local space is a count-sketch (random
    # projection) instead of an exact subspace; projection is then all -1
    sketch: Optional[object] = None


@dataclasses.dataclass(frozen=True)
class RandomEffectModel:
    """All per-entity GLMs for one random effect (e.g. per-user).

    The reference holds RDD[(REId, GeneralizedLinearModel)]; here the models
    live in bucketed dense matrices plus an entity->-(bucket, row) index.
    """

    effect_name: str
    buckets: Sequence[RandomEffectBucket]
    task: str = "logistic"
    feature_shard: str = "global"
    # which dataset entity-id column keys this effect (e.g. "userId")
    entity_column: str = ""

    def entity_index(self) -> Dict:
        """entity id -> (bucket_idx, row) mapping (host side)."""
        out = {}
        for b, bucket in enumerate(self.buckets):
            for r, eid in enumerate(bucket.entity_ids):
                out[eid] = (b, r)
        return out

    @property
    def num_entities(self) -> int:
        return sum(len(b.entity_ids) for b in self.buckets)

    def coefficients_for(self, entity_id) -> Optional[np.ndarray]:
        """Dense global-space coefficient vector for one entity (host-side
        convenience; bulk scoring uses the bucketed arrays directly)."""
        for bucket in self.buckets:
            try:
                row = list(bucket.entity_ids).index(entity_id)
            except ValueError:
                continue
            proj = np.asarray(bucket.projection[row])
            coef = np.asarray(bucket.coefficients[row])
            dim = int(proj.max()) + 1 if (proj >= 0).any() else 0
            out = np.zeros(max(dim, 0))
            valid = proj >= 0
            out[proj[valid]] = coef[valid]
            return out
        return None


@dataclasses.dataclass(frozen=True)
class GameModel:
    """Composite additive model: total score = sum of coordinate scores
    (SURVEY.md §4.4). Keys are coordinate names in training order."""

    coordinates: Mapping[str, FixedEffectModel | RandomEffectModel]
    task: str = "logistic"

    def __getitem__(self, name):
        return self.coordinates[name]

    @property
    def loss(self):
        return get_loss(self.task)
