"""GAME training driver: the end-to-end training entry point.

Equivalent of the reference's ``cli.game.training.GameTrainingDriver``
(SURVEY.md §4.1; reference mount empty): parse params, build/load feature
index maps, read Avro training data, optionally normalize, train a GAME
model per optimization-config grid point with validation tracking, select
the best by the primary evaluator, save best + all models (Avro), and
write a structured log. Warm start, locked coordinates (partial retrain),
and per-iteration checkpoints are supported.

Usage:
    python -m photon_ml_tpu.cli.game_training_driver \
        --train-data data/train.avro --validation-data data/val.avro \
        --output-dir out/ --task logistic_regression \
        --coordinates configs/coordinates.json --evaluators auc \
        --n-iterations 3

The coordinate config JSON is a list of dicts matching CoordinateConfig
fields; ``reg_weight`` may be a list to define a grid (cross-product over
coordinates is expanded).
"""

from __future__ import annotations

import argparse
import itertools
import json
import os
import sys
from typing import Dict, List, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.estimators import GameEstimator
from photon_ml_tpu.evaluation.evaluators import TASK_DEFAULT_EVALUATOR
from photon_ml_tpu.game.descent import CoordinateConfig, GameDataset
from photon_ml_tpu.io.avro import iter_avro_records
from photon_ml_tpu.io.data_reader import read_training_examples
from photon_ml_tpu.io.index_map import IndexMap, build_index_map, filter_index_map
from photon_ml_tpu.io.model_io import load_game_model, save_game_model
from photon_ml_tpu.io.schemas import FEATURE_SUMMARIZATION_SCHEMA
from photon_ml_tpu.ops.losses import TASK_TO_LOSS
from photon_ml_tpu.ops.normalization import NormalizationType, build_normalization_context
from photon_ml_tpu.ops.statistics import summarize_features
from photon_ml_tpu.types import make_batch
from photon_ml_tpu.utils import PhotonLogger, Timed, resolve_dtype


def _positive_int(value: str) -> int:
    n = int(value)
    if n <= 0:
        raise argparse.ArgumentTypeError(
            f"expected a positive integer, got {value!r}")
    return n


def _finite_nonneg_float(value: str) -> float:
    x = float(value)
    if not np.isfinite(x) or x < 0:
        raise argparse.ArgumentTypeError(
            f"expected a finite float >= 0, got {value!r}")
    return x


def _tol_schedule(value: str):
    from photon_ml_tpu.optimize import parse_tolerance_schedule

    try:
        return parse_tolerance_schedule(value)
    except ValueError as e:
        raise argparse.ArgumentTypeError(str(e))


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="GAME training driver (TPU-native)")
    p.add_argument("--train-data", required=True, nargs="+",
                   help="Avro file(s)/dir(s) of TrainingExampleAvro records")
    p.add_argument("--validation-data", nargs="+", default=None)
    p.add_argument("--output-dir", required=True)
    p.add_argument("--task", default="logistic_regression",
                   choices=sorted(TASK_TO_LOSS) + sorted(set(TASK_TO_LOSS.values())))
    p.add_argument("--coordinates", required=True,
                   help="path to coordinate-config JSON, or inline JSON")
    p.add_argument("--evaluators", nargs="*", default=None)
    p.add_argument("--n-iterations", type=int, default=1)
    p.add_argument("--cd-tolerance", type=_finite_nonneg_float, default=0.0,
                   help="sweep-level early exit: stop once every "
                        "coordinate's score vector moved by at most this "
                        "(max-abs) over a whole sweep; 0 disables (exactly "
                        "--n-iterations sweeps run). Must be finite — "
                        "nan/inf would silently disable or always trigger "
                        "the test")
    p.add_argument("--re-active-set", action="store_true", default=None,
                   help="active-set coordinate descent for random effects "
                        "(the CoordinateConfig default): converged "
                        "entities whose coefficients stopped moving are "
                        "frozen and later sweeps solve only the "
                        "unconverged frontier")
    p.add_argument("--no-re-active-set", dest="re_active_set",
                   action="store_false",
                   help="re-solve every entity every sweep (the exact "
                        "fixed-sweep schedule)")
    p.add_argument("--re-refresh-every", type=_positive_int, default=None,
                   help="with the active set: every K-th sweep is a full "
                        "refresh that re-solves frozen entities too, "
                        "re-activating any that drifted because other "
                        "coordinates moved (must be positive)")
    p.add_argument("--solver-tol-schedule", type=_tol_schedule, default=None,
                   metavar="START:DECAY",
                   help="inexact-CD inner-solve tolerance schedule: sweep "
                        "k solves to max(coordinate tolerance, START * "
                        "DECAY^k) — loose early sweeps, geometrically "
                        "tightening to the configured tolerance (e.g. "
                        "1e-3:0.1; 'off' disables)")
    p.add_argument("--index-map", default=None,
                   help="prebuilt index map (JSON, native store, or hashing "
                        "config; else built from data)")
    p.add_argument("--hash-dim", type=int, default=None,
                   help="feature-hash into this width instead of building an "
                        "index map (TB-scale path; collisions accepted)")
    p.add_argument("--feature-shards", default=None,
                   help="JSON (inline or path): shard name -> list of feature-"
                        "name prefixes (per-shard feature bags); shards not "
                        "listed get all features")
    p.add_argument("--min-feature-count", type=int, default=1)
    p.add_argument("--input-columns", default=None,
                   help="JSON (inline or path) remapping record field names "
                        "(response/offset/weight/uid/features/metadata_map)")
    p.add_argument("--add-intercept", action="store_true", default=True)
    p.add_argument("--no-intercept", dest="add_intercept", action="store_false")
    p.add_argument("--normalization", default="none",
                   choices=[t.value for t in NormalizationType])
    p.add_argument("--warm-start-model", default=None,
                   help="model dir to warm start from")
    p.add_argument("--locked-coordinates", nargs="*", default=(),
                   help="coordinates kept fixed (partial retrain)")
    p.add_argument("--checkpoint", action="store_true",
                   help="save the model after each outer CD iteration")
    p.add_argument("--auto-resume", action="store_true",
                   help="with --checkpoint: adopt the latest checkpoint as "
                        "the warm start when a prior run died on device "
                        "loss (see the RESUME marker / exit code 75)")
    p.add_argument("--save-all-models", action="store_true")
    p.add_argument("--publish-to", default=None,
                   help="model-registry root (registry/): publish the "
                        "best model there as an immutable version after "
                        "saving. The FIRST publish into an empty "
                        "registry also sets LATEST (bootstrap); later "
                        "versions are promoted through the gate "
                        "(photon-model-publish --gate-data ...)")
    p.add_argument("--summarize-features", action="store_true",
                   help="write FeatureSummarizationResultAvro output")
    p.add_argument("--dtype", default="float32", choices=["float32", "float64"])
    p.add_argument("--streaming", action="store_true",
                   help="larger-than-HBM mode for fixed-effect coordinates: "
                        "features stay in host RAM, each optimizer pass "
                        "streams fixed-shape chunks through the device")
    p.add_argument("--pad-nnz", type=int, default=None,
                   help="fixed per-row feature width incl. intercept for "
                        "--out-of-core-shards sources (default: one "
                        "measuring decode pass per shard — pass the known "
                        "value at scale to skip it)")
    p.add_argument("--out-of-core-shards", nargs="*", default=(),
                   help="feature shards that must NEVER materialize in "
                        "host RAM: their coordinates (streaming fixed "
                        "effects) re-decode Avro block waves from disk "
                        "every optimizer pass (io/stream_source.py); "
                        "multi-process runs give each process its own "
                        "contiguous block share. Requires a pinned "
                        "feature space (--hash-dim or --index-map); "
                        "normalization works via a streamed "
                        "summarization pass")
    p.add_argument("--chunk-rows", type=int, default=1 << 16,
                   help="rows per streamed chunk (--streaming)")
    p.add_argument("--chunk-cache-dir", default=None,
                   help="with --out-of-core-shards: decode-once packed "
                        "chunk cache root (io/chunk_cache.py; one subdir "
                        "per shard) — the first streamed pass spills "
                        "decoded chunks into packed memmaps, every later "
                        "pass (and every CD iteration) streams them back "
                        "decode-free; CD residual offsets still update "
                        "through the scalar overlay. Invalidated when "
                        "source files / chunk geometry / index map "
                        "change; multi-process runs need per-process dirs")
    p.add_argument("--chunk-cache-gb", type=float, default=None,
                   help="per-shard disk budget for --chunk-cache-dir; a "
                        "shard that doesn't fit falls through to "
                        "re-decode with a logged warning")
    p.add_argument("--prefetch-depth", type=int, default=None,
                   help="streamed transfer-ring depth: chunks staged on "
                        "device ahead of compute (default 2 / "
                        "PHOTON_PREFETCH_DEPTH; 0 = synchronous)")
    p.add_argument("--tuning-mode", default="none",
                   choices=["none", "random", "bayesian"],
                   help="auto-tune reg weights after the grid (SURVEY.md §4.5)")
    p.add_argument("--tuning-iters", type=int, default=10)
    p.add_argument("--tuning-range", type=float, nargs=2, default=(1e-4, 1e4),
                   metavar=("LOW", "HIGH"),
                   help="log-scale search range for regularization weights")
    p.add_argument("--tuning-coordinates", nargs="*", default=None,
                   help="coordinates whose reg weights are tuned (default: all "
                        "unlocked)")
    p.add_argument("--tuning-seed", type=int, default=0)
    p.add_argument("--coordinator-address", default=None,
                   help="multi-host: coordinator host:port for "
                        "jax.distributed.initialize (every process runs this "
                        "driver with the same args)")
    p.add_argument("--num-processes", type=int, default=None)
    p.add_argument("--process-id", type=int, default=None)
    p.add_argument("--entity-shards", type=_positive_int, default=None,
                   help="entity-sharded random-effect training: partition "
                        "every random coordinate's entity table across this "
                        "many processes by a stable hash of the entity id "
                        "(must equal the controller process count — shard i "
                        "lives on process i). Each process builds and "
                        "solves only its owned entities; sweeps exchange "
                        "only changed rows' scores, never coefficients "
                        "(parallel/entity_shard.py, docs/sharding.md)")
    p.add_argument("--re-table-budget-mb", type=float, default=None,
                   help="per-process random-effect entity-table budget in "
                        "MB: a coordinate whose LOCAL table exceeds it "
                        "fails fast with a pointer at --entity-shards "
                        "instead of silently exhausting host RAM")
    p.add_argument("--max-rank-failures", type=int, default=0,
                   help="in-job elastic recovery: tolerate up to this many "
                        "cumulative rank losses by shrinking onto the "
                        "surviving process set and redistributing the dead "
                        "ranks' entities from the last committed per-sweep "
                        "snapshot (transports that cannot resize — the "
                        "production jax runtime — still get transient "
                        "rollback-retry and escalate rank loss to the "
                        "--auto-resume whole-job path). 0 (default) keeps "
                        "the plain fail-stop behavior "
                        "(parallel/recovery.py, docs/resilience.md)")
    p.add_argument("--recovery-snapshot-every", type=_positive_int,
                   default=1,
                   help="commit a recovery snapshot every N CD sweeps "
                        "(with --max-rank-failures > 0): a failure rolls "
                        "back at most N sweeps; larger N trades snapshot "
                        "time for replay time")
    p.add_argument("--profile-dir", default=None,
                   help="capture a JAX profiler trace of training here "
                        "(view in TensorBoard/Perfetto)")
    p.add_argument("--trace-dir", default=None,
                   help="write photon-trace span files here (one "
                        "trace-rankN.json per process, Chrome-trace "
                        "format; merge with `photon-trace merge`). "
                        "Also honors PHOTON_TRACE / PHOTON_TRACE_SAMPLE "
                        "(obs/trace.py, docs/observability.md)")
    p.add_argument("--trace-sample", type=float, default=1.0,
                   help="fraction of traces recorded under --trace-dir")
    return p


def _load_input_columns(spec):
    from photon_ml_tpu.io.data_reader import InputColumnsNames

    if not spec:
        return InputColumnsNames()
    if os.path.exists(spec):
        with open(spec) as f:
            return InputColumnsNames.from_dict(json.load(f))
    return InputColumnsNames.from_dict(json.loads(spec))


def _load_coordinate_grid(spec: str) -> List[List[CoordinateConfig]]:
    if os.path.exists(spec):
        with open(spec) as f:
            raw = json.load(f)
    else:
        raw = json.loads(spec)
    if not isinstance(raw, list) or not raw:
        raise ValueError("coordinate config must be a non-empty JSON list")
    # expand list-valued reg_weight into a grid (the reference's grid of
    # GameOptimizationConfigurations — SURVEY.md §4.1)
    per_coord_options: List[List[dict]] = []
    for c in raw:
        weights = c.get("reg_weight", 0.0)
        if isinstance(weights, list):
            per_coord_options.append([{**c, "reg_weight": w} for w in weights])
        else:
            per_coord_options.append([c])
    grid = []
    for combo in itertools.product(*per_coord_options):
        grid.append([CoordinateConfig(**c) for c in combo])
    return grid


def _entity_columns(grid) -> List[str]:
    cols = []
    for cfg in grid[0]:
        if cfg.coordinate_type == "random" and cfg.entity_column not in cols:
            cols.append(cfg.entity_column)
    return cols


def _read_dataset(paths, index_maps, entity_columns, columns=None) -> GameDataset:
    feats, labels, offsets, weights, ents, uids = read_training_examples(
        paths, index_maps, entity_columns=entity_columns, columns=columns
    )
    return GameDataset(feats, labels, weights, offsets, ents, None)


def main(argv: Sequence[str] | None = None) -> int:
    args = build_arg_parser().parse_args(argv)
    from photon_ml_tpu.obs import logging as obs_logging
    from photon_ml_tpu.obs import trace as obs_trace

    obs_logging.configure()
    if args.trace_dir:
        started = obs_trace.start(args.trace_dir, sample=args.trace_sample)
    else:
        started = obs_trace.maybe_start_from_env()
    try:
        return _run(args)
    finally:
        # every exit path (incl. the device-loss return 75) exports the
        # trace files so a crashed run still leaves its spans behind.
        # Only stop a tracer THIS invocation started: in the simulated
        # harness several ranks run main() in one process and only one
        # of them owns the process-wide tracer.
        if started is not None:
            obs_trace.stop()


def _run(args) -> int:
    from photon_ml_tpu.parallel import resilience
    from photon_ml_tpu.parallel.multihost import initialize_multihost, runtime_info

    distributed = initialize_multihost(args.coordinator_address,
                                       args.num_processes, args.process_id)
    # lead election through the ambient transport, not jax: identical in
    # a real multi-controller run, and under the simulated harness every
    # thread shares jax.process_index()==0 while the transport reports
    # the true per-rank index — without this, all simulated ranks think
    # they lead and race their saves to the shared output dir
    is_lead = ((not distributed) or jax.process_index() == 0) \
        and resilience.current_process_index() == 0
    # entity sharding is argv-validated HERE, before any data read: the
    # owner map assigns shard i to process i, so the shard count must be
    # the controller process count
    entity_spec = None
    if args.entity_shards is not None:
        # the transport's view, not jax's: identical in a real
        # multi-controller run, and the simulated harness's per-thread
        # transports report their group size here
        tp = resilience.current_transport()
        pc = tp.process_count()
        if args.entity_shards != pc:
            raise SystemExit(
                f"--entity-shards {args.entity_shards} must equal the "
                f"controller process count ({pc}): the owner map assigns "
                "entity shard i to process i (run one process per shard "
                "via --coordinator-address/--num-processes)")
        from photon_ml_tpu.parallel.entity_shard import EntityShardSpec

        entity_spec = EntityShardSpec(
            args.entity_shards, resilience.current_process_index())
    re_table_budget = (None if args.re_table_budget_mb is None
                       else int(args.re_table_budget_mb * 1e6))
    dtype = resolve_dtype(args.dtype)
    task = TASK_TO_LOSS.get(args.task, args.task)
    os.makedirs(args.output_dir, exist_ok=True)
    logger = PhotonLogger(os.path.join(args.output_dir, "photon.log.jsonl"))
    logger.log("driver_start", driver="game_training", args=vars(args),
               distributed=distributed, **runtime_info())

    columns = _load_input_columns(args.input_columns)
    grid = _load_coordinate_grid(args.coordinates)
    if args.streaming:
        import dataclasses as _dc

        grid = [
            [_dc.replace(cfg, streaming=True, chunk_rows=args.chunk_rows)
             if cfg.coordinate_type == "fixed" else cfg
             for cfg in configs]
            for configs in grid
        ]
    if args.prefetch_depth is not None:
        import dataclasses as _dc

        grid = [
            [_dc.replace(cfg, prefetch_depth=args.prefetch_depth)
             if cfg.coordinate_type == "fixed" else cfg
             for cfg in configs]
            for configs in grid
        ]
    re_overrides = {
        k: v for k, v in (("active_set", args.re_active_set),
                          ("refresh_every", args.re_refresh_every))
        if v is not None
    }
    if re_overrides:  # apply to every random coordinate across the grid
        import dataclasses as _dc

        grid = [
            [_dc.replace(cfg, **re_overrides)
             if cfg.coordinate_type == "random" else cfg
             for cfg in configs]
            for configs in grid
        ]
    shards = sorted({cfg.feature_shard for cfg in grid[0]})
    entity_columns = _entity_columns(grid)

    # fail fast on bad tuning flags — tuning runs AFTER the (possibly long)
    # grid training, so catching these there would waste the whole run
    tuned_coords = None
    if args.tuning_mode != "none":
        if not args.validation_data:
            raise SystemExit("--tuning-mode requires --validation-data")
        lo, hi = args.tuning_range
        if not (0 < lo < hi):
            raise SystemExit(f"--tuning-range needs 0 < LOW < HIGH, got "
                             f"{lo} {hi}")
        if args.evaluators is not None and not args.evaluators:
            raise SystemExit("--tuning-mode needs at least one evaluator "
                             "(drop the bare --evaluators flag to use the "
                             "task default)")
        from photon_ml_tpu.tuning import resolve_tuned_coordinates

        try:
            tuned_coords = resolve_tuned_coordinates(
                grid[0], args.tuning_coordinates, args.locked_coordinates
            )
        except ValueError as e:
            raise SystemExit(f"--tuning-coordinates: {e}")

    with Timed(logger, "feature_indexing"):
        if args.hash_dim:
            from photon_ml_tpu.io.hashing import HashingIndexMap

            base_map = HashingIndexMap(args.hash_dim,
                                       add_intercept=args.add_intercept)
        elif args.index_map:
            from photon_ml_tpu.io.paldb import load_index_map

            base_map = load_index_map(args.index_map)
        else:
            base_map = build_index_map(
                iter_avro_records(args.train_data),
                add_intercept=args.add_intercept,
                min_count=args.min_feature_count,
                features_field=columns.features,
            )
        shard_defs = {}
        if args.feature_shards:
            if os.path.exists(args.feature_shards):
                shard_defs = json.load(open(args.feature_shards))
            else:
                shard_defs = json.loads(args.feature_shards)
            if args.hash_dim and any(s in shard_defs for s in shards):
                raise SystemExit(
                    "--hash-dim cannot be combined with feature-shard prefix "
                    "filtering (a hashing map has no enumerable features); "
                    "give each shard its own driver run or drop --hash-dim"
                )
        index_maps: Dict[str, IndexMap] = {}
        for s in shards:
            if s in shard_defs:
                index_maps[s] = filter_index_map(
                    base_map, shard_defs[s], add_intercept=args.add_intercept
                )
            else:
                index_maps[s] = base_map

    ooc_shards = set(args.out_of_core_shards or ())
    if args.chunk_cache_dir and not ooc_shards:
        raise SystemExit("--chunk-cache-dir requires --out-of-core-shards "
                         "(only disk-backed shards re-decode per pass)")
    if args.chunk_cache_gb is not None and not args.chunk_cache_dir:
        raise SystemExit("--chunk-cache-gb requires --chunk-cache-dir")
    if ooc_shards:
        # every check here is argv-only: fail BEFORE the (potentially
        # hours-long at the scale this feature targets) dataset reads
        unknown = ooc_shards - set(shards)
        if unknown:
            raise SystemExit(f"--out-of-core-shards: {sorted(unknown)} not "
                             f"used by any coordinate (shards: {sorted(shards)})")
        if not (args.hash_dim or args.index_map):
            raise SystemExit("--out-of-core-shards needs a pinned feature "
                             "space (--hash-dim or --index-map): building "
                             "an index map scans the full dataset")
        # only streaming FIXED coordinates can consume a disk-backed
        # shard; a random coordinate's data layer needs resident features
        ooc_chunk_rows: Dict[str, int] = {}
        for cfg in grid[0]:
            if cfg.feature_shard not in ooc_shards:
                continue
            if cfg.coordinate_type != "fixed" or not cfg.streaming:
                raise SystemExit(
                    f"--out-of-core-shards: shard '{cfg.feature_shard}' is "
                    f"used by coordinate '{cfg.name}' "
                    f"({cfg.coordinate_type}"
                    f"{'' if cfg.streaming else ', streaming=false'}) — "
                    "only streaming fixed-effect coordinates can train "
                    "from a disk-backed shard")
            ooc_chunk_rows[cfg.feature_shard] = min(
                cfg.chunk_rows,
                ooc_chunk_rows.get(cfg.feature_shard, cfg.chunk_rows))

    with Timed(logger, "read_train_data"):
        train = _read_dataset(
            args.train_data,
            {s_: m for s_, m in index_maps.items() if s_ not in ooc_shards},
            entity_columns, columns)
        if ooc_shards:
            from photon_ml_tpu.io.stream_source import AvroChunkSource

            n_local = max(len(jax.local_devices()), 1)

            def _cr(shard):
                # the consuming coordinate's chunk_rows (min across
                # coordinates sharing the shard), device-rounded
                base = ooc_chunk_rows.get(shard, args.chunk_rows)
                return -(-base // n_local) * n_local

            # multi-process: each process keeps its own contiguous block
            # share; per-pass partials reduce across processes and scoring
            # reassembles via the recorded part spans
            part = ((jax.process_index(), jax.process_count())
                    if distributed else None)
            train.feature_sources = {
                s_: AvroChunkSource(args.train_data, index_maps[s_],
                                    chunk_rows=_cr(s_), columns=columns,
                                    pad_nnz=args.pad_nnz, dtype=dtype,
                                    process_part=part)
                for s_ in ooc_shards
            }
            if args.chunk_cache_dir:
                # decode-once: the first streamed pass over each shard
                # (summarization or the first fit pass) pays the Avro
                # decode; every later pass — including every CD
                # iteration's 2 sparse passes — streams packed memmaps
                from photon_ml_tpu.io.chunk_cache import ChunkCacheSource

                cache_bytes = (None if args.chunk_cache_gb is None
                               else int(args.chunk_cache_gb * 1e9))
                train.feature_sources = {
                    s_: ChunkCacheSource(
                        src_, os.path.join(args.chunk_cache_dir, s_),
                        max_bytes=cache_bytes)
                    for s_, src_ in train.feature_sources.items()
                }
    validation = None
    if args.validation_data:
        with Timed(logger, "read_validation_data"):
            validation = _read_dataset(args.validation_data, index_maps,
                                       entity_columns, columns)
    logger.log("data_read", num_train=train.num_samples,
               num_validation=0 if validation is None else validation.num_samples,
               num_features={s: m.size for s, m in index_maps.items()})

    norm_type = NormalizationType(args.normalization)
    if norm_type != NormalizationType.NONE or args.summarize_features:
        contexts = {}
        # feature summarization is the first collective phase of a
        # multi-controller run (the streamed-moment all-reduce): run it
        # under the health guard so one process's read/decode failure
        # aborts every process instead of wedging the reduce
        with Timed(logger, "feature_summarization"), \
                resilience.CollectiveGuard("feature_summarization"):
            for shard in shards:
                if shard in ooc_shards:
                    # one extra streamed pass over the disk-backed shard:
                    # per-feature moments without a resident copy. A
                    # multi-controller run streams only the local block
                    # part, so the raw moments are all-reduced and
                    # finalized against the GLOBAL row count — otherwise
                    # each process would build a normalization context
                    # from its own data half and the summed gradients
                    # would mix feature spaces.
                    from photon_ml_tpu.ops.statistics import (
                        summarize_features_streamed,
                    )
                    from photon_ml_tpu.parallel.multihost import (
                        allreduce_summary_moments,
                    )

                    src = train.feature_sources[shard]
                    summary = summarize_features_streamed(
                        src, src.dim, src.rows,
                        total_rows=src.total_rows,
                        part_reduce=(allreduce_summary_moments
                                     if distributed else None))
                else:
                    sp = train.features[shard]
                    batch = make_batch(_to_sparse_features(sp), train.labels)
                    summary = summarize_features(batch)
                if args.summarize_features and is_lead:
                    _write_summary(args.output_dir, summary, index_maps[shard],
                                   suffix=shard)
                if norm_type != NormalizationType.NONE:
                    contexts[shard] = build_normalization_context(
                        norm_type, summary,
                        intercept_index=index_maps[shard].intercept_index,
                    )
        if norm_type != NormalizationType.NONE:
            grid = [
                [_with_normalization(cfg, contexts[cfg.feature_shard],
                                     index_maps[cfg.feature_shard])
                 for cfg in configs]
                for configs in grid
            ]

    warm = load_game_model(args.warm_start_model) if args.warm_start_model else None
    # Unified resume-marker lifecycle (parallel/resilience.ResumeManager):
    # written atomically on device loss, KEPT until this run completes (a
    # second failure of any kind — OOM, SIGKILL, another device loss —
    # must not discard resume state; same semantics as the GLM driver's
    # RESUME_GLM.npz), and fingerprinted against the inputs so a rerun
    # pointed at different data refuses to resume instead of silently
    # mixing datasets.
    resume = resilience.ResumeManager(
        os.path.join(args.output_dir, "RESUME.json"),
        fingerprint={
            "train_data": sorted(args.train_data),
            "validation_data": (sorted(args.validation_data)
                                if args.validation_data else None),
            "validation_rows": (None if validation is None
                                else int(validation.num_samples)),
        },
        is_lead=is_lead)
    if args.auto_resume and resume.exists():
        # marker-gated ONLY: without it --auto-resume is a no-op, so a
        # supervisor can pass the flag unconditionally without a cleanly
        # finished run's leftover checkpoints hijacking later reruns
        resume_from = resume.load().get("checkpoint")
        if resume_from:
            warm = load_game_model(resume_from)
            logger.log("auto_resume", checkpoint=resume_from)
    if args.auto_resume and distributed:
        # every process must have adopted the checkpoint (or observed
        # its absence) before any enters training's first collective;
        # the health barrier doubles as the ordering sync and surfaces
        # a peer whose marker load failed. It runs UNCONDITIONALLY of
        # resume.exists(): that is a process-LOCAL filesystem probe, and
        # a marker visible on only some hosts (eventual-consistency
        # shared FS mid-write) would otherwise send part of the job to
        # this barrier while the rest proceeds to training — diverging
        # the collective sequences (photon-check PC102).
        resilience.health_barrier("auto_resume_loaded")

    evaluators = args.evaluators
    if evaluators is None:
        evaluators = [TASK_DEFAULT_EVALUATOR[task]] if validation is not None else []

    recovery_mgr = None
    if args.max_rank_failures > 0:
        from photon_ml_tpu.parallel.recovery import RecoveryManager

        # same fingerprint discipline as the resume marker: a recovery
        # snapshot from a run over different inputs must refuse to load
        recovery_mgr = RecoveryManager(
            os.path.join(args.output_dir, "recovery"),
            fingerprint=resume.fingerprint,
            max_rank_failures=args.max_rank_failures,
            snapshot_every=args.recovery_snapshot_every)

    estimator = GameEstimator(
        task=task, n_iterations=args.n_iterations, evaluators=evaluators,
        dtype=dtype, cd_tolerance=args.cd_tolerance,
        solver_tol_schedule=args.solver_tol_schedule,
        entity_shard=entity_spec,
        entity_table_budget_bytes=re_table_budget,
        recovery=recovery_mgr,
    )
    ckpt = None
    if args.checkpoint and is_lead:
        # lead-only: every process reaches the same model and output_dir
        # is shared, so concurrent saves to one checkpoint path would
        # race the atomic rename-into-place
        def ckpt(gi, it, model):
            path = os.path.join(args.output_dir, "checkpoints",
                                f"config-{gi}-iter-{it}")
            save_game_model(model, path, index_maps)
            logger.log("checkpoint", config=gi, iteration=it, path=path)
    elif args.checkpoint and entity_spec is not None and entity_spec.active:
        # entity-sharded checkpoints are a collective (the per-iteration
        # model build gathers every shard's buckets): non-lead processes
        # must still participate in the gather, they just don't write
        def ckpt(gi, it, model):
            del gi, it, model  # gathered; the lead wrote it

    def log_fit(gi, result):
        for rec in result.history:
            logger.log("cd_iteration", config=gi, **rec)

    from photon_ml_tpu.utils import profile_trace

    # Device-loss recovery (SURVEY §5.3): a TPU worker crash surfaces as
    # JaxRuntimeError("UNAVAILABLE ...") and the dead backend cannot be
    # reinitialized IN-PROCESS (measured: the r05 axon worker crash—
    # docs/tpu_r05_logs/bench_game.log—required a fresh process even
    # though the worker itself recovered in ~90 s). So recovery is a
    # process boundary: persist a RESUME marker pointing at the newest
    # checkpoint and exit 75 (EX_TEMPFAIL); a supervisor reruns the same
    # command with --auto-resume, which adopts that checkpoint as the
    # warm start. --auto-resume consumed the marker above.
    try:
        with Timed(logger, "training"), profile_trace(args.profile_dir):
            results = estimator.fit(
                train, validation, config_grid=grid, warm_start=warm,
                locked=args.locked_coordinates, checkpoint_callback=ckpt,
                fit_callback=log_fit,
            )
    except Exception as e:
        from photon_ml_tpu.utils import is_device_loss

        if not is_device_loss(e) or not args.checkpoint:
            raise
        latest = _latest_checkpoint(args.output_dir)
        resume.save({"error": str(e).split("\n")[0], "checkpoint": latest})
        logger.log("device_lost", error=str(e).split("\n")[0],
                   resume_checkpoint=latest)
        logger.close()
        print(f"device lost; resume marker written to {resume.path} "
              "(rerun with --auto-resume)", file=sys.stderr)
        return 75

    if recovery_mgr is not None and recovery_mgr.stats["recoveries"]:
        # the run survived at least one in-job recovery: record it in the
        # run log (the supervisor never saw a restart, so this is the
        # only durable trace of the event)
        logger.log("in_job_recovery", **recovery_mgr.as_dict())

    if args.tuning_mode != "none":
        from photon_ml_tpu.tuning import tune_game

        def log_tune(ri, result):
            logger.log("tuning_round", round=ri,
                       reg_weights={c.name: c.reg_weight for c in result.configs},
                       metrics=result.evaluation.metrics)

        with Timed(logger, "hyperparameter_tuning"):
            tuned = tune_game(
                estimator, train, validation, list(grid[0]),
                n_iterations=args.tuning_iters, mode=args.tuning_mode,
                reg_range=tuple(args.tuning_range), prior_results=results,
                seed=args.tuning_seed, tuned_coordinates=tuned_coords,
                fit_callback=log_tune, warm_start=warm,
                locked=args.locked_coordinates,
            )
        results = results + tuned

    best = estimator.select_best(results)
    with Timed(logger, "save_models"):
        # every process reaches the same model; only the lead writes, so
        # co-located multi-controller processes never interleave writes
        # to one output path
        if is_lead:
            save_game_model(best.model, os.path.join(args.output_dir, "best"),
                            index_maps)
            if args.save_all_models:
                for gi, r in enumerate(results):
                    save_game_model(
                        r.model,
                        os.path.join(args.output_dir, "all", f"config-{gi}"),
                        index_maps)
    if args.publish_to and is_lead:
        from photon_ml_tpu.registry import ModelRegistry

        registry = ModelRegistry(args.publish_to)
        best_metrics = ({} if best.evaluation is None
                        else dict(best.evaluation.metrics))
        bootstrap = registry.read_latest(retries=1) is None
        version = registry.publish(
            os.path.join(args.output_dir, "best"),
            metrics=best_metrics, set_latest=bootstrap)
        logger.log("model_published", registry=args.publish_to,
                   version=version, set_latest=bootstrap,
                   metrics=best_metrics)
    # outputs are published: ANY completed run consumes the marker (not
    # only --auto-resume ones) so a later auto-resume cannot warm-start
    # from a checkpoint that predates these outputs
    resume.consume()
    logger.log("driver_done",
               best_config=[dataclasses_asdict(c) for c in best.configs],
               best_metrics=None if best.evaluation is None else best.evaluation.metrics)
    logger.close()
    return 0


def _latest_checkpoint(output_dir: str):
    """Newest checkpoint dir, or None. mtime first; ties (coarse-mtime
    filesystems) break on the PARSED config/iteration numbers — a
    lexicographic tiebreak would order iter-9 above iter-10."""
    import re

    root = os.path.join(output_dir, "checkpoints")
    if not os.path.isdir(root):
        return None

    def nums(name):
        return tuple(int(x) for x in re.findall(r"\d+", name)) or (-1,)

    entries = [d for d in sorted(os.listdir(root))
               if os.path.isdir(os.path.join(root, d))]
    live = [d for d in entries if ".tmp-" not in d and ".old-" not in d]
    # crash-window recovery: save_game_model's overwrite swap can die
    # between its two renames, leaving only a complete '{name}.old-{pid}'
    # copy; count it as its base name when the base is missing
    for d in entries:
        if ".old-" in d:
            base = d.split(".old-")[0]
            if base not in live:
                live.append(d)
    if not live:
        return None
    best = max(live, key=lambda d: (os.path.getmtime(os.path.join(root, d)),
                                    nums(d)))
    return os.path.join(root, best)


def _to_sparse_features(sp):
    from photon_ml_tpu.types import SparseFeatures

    return SparseFeatures(jnp.asarray(sp.indices), jnp.asarray(sp.values),
                          dim=sp.dim)


def _with_normalization(cfg: CoordinateConfig, ctx, imap: IndexMap):
    import dataclasses as _dc

    return _dc.replace(cfg, normalization=ctx,
                       intercept_index=imap.intercept_index)


def dataclasses_asdict(cfg: CoordinateConfig) -> dict:
    import dataclasses as _dc

    d = _dc.asdict(cfg)
    d.pop("normalization", None)  # device arrays aren't JSON
    return d


def _write_summary(output_dir, summary, imap: IndexMap, suffix: str = "global"):
    from photon_ml_tpu.io.avro import write_avro_file
    from photon_ml_tpu.io.schemas import split_feature_key

    inverse = imap.inverse()

    def records():
        for i in range(summary.dim):
            name, term = split_feature_key(inverse[i])
            yield {
                "name": name, "term": term,
                "mean": float(summary.mean[i]),
                "variance": float(summary.variance[i]),
                "min": float(summary.min[i]), "max": float(summary.max[i]),
                "numNonzeros": float(summary.num_nonzeros[i]),
                "count": summary.count,
            }

    name = ("feature-summary.avro" if suffix == "global"
            else f"feature-summary.{suffix}.avro")
    write_avro_file(os.path.join(output_dir, name),
                    records(), FEATURE_SUMMARIZATION_SCHEMA)


if __name__ == "__main__":
    raise SystemExit(main())
