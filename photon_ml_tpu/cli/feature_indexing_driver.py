"""Feature indexing driver: build a feature index map from Avro data.

Equivalent of the reference's ``index.FeatureIndexingDriver`` (the dedicated
Spark job that builds PalDB index maps — SURVEY.md §3.3; reference mount
empty). Output is a JSON index map loadable by the training/scoring drivers.
"""

from __future__ import annotations

import argparse
from typing import Sequence

from photon_ml_tpu.io.avro import iter_avro_records
from photon_ml_tpu.io.index_map import build_index_map
from photon_ml_tpu.utils import PhotonLogger


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="Feature indexing driver (TPU-native)")
    p.add_argument("--data", required=True, nargs="+")
    p.add_argument("--output", required=True, help="index map output path")
    p.add_argument("--store-format", default="json", choices=["json", "paldb"],
                   help="json: human-readable; paldb: native mmap store "
                        "(the reference's PalDB role, zero load time)")
    p.add_argument("--min-feature-count", type=int, default=1)
    p.add_argument("--add-intercept", action="store_true", default=True)
    p.add_argument("--no-intercept", dest="add_intercept", action="store_false")
    return p


def main(argv: Sequence[str] | None = None) -> int:
    args = build_arg_parser().parse_args(argv)
    logger = PhotonLogger(None)
    imap = build_index_map(
        iter_avro_records(args.data),
        add_intercept=args.add_intercept,
        min_count=args.min_feature_count,
    )
    if args.store_format == "paldb":
        from photon_ml_tpu.io.paldb import build_store

        build_store(imap.forward, args.output)
    else:
        imap.save(args.output)
    logger.log("index_map_built", num_features=imap.size, output=args.output,
               store_format=args.store_format)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
