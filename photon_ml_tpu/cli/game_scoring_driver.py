"""GAME scoring driver: batch inference with a saved model.

Equivalent of the reference's ``cli.game.scoring.GameScoringDriver``
(SURVEY.md §4.4; reference mount empty): load a saved GAME model + Avro
data, score every row (fixed-effect margins + per-entity random-effect
margins + offsets), write ``ScoringResultAvro`` records and optionally
evaluate against labels.
"""

from __future__ import annotations

import argparse
import os
from typing import Sequence

import numpy as np

from photon_ml_tpu.game.scoring import score_game_model
from photon_ml_tpu.io.avro import write_avro_file
from photon_ml_tpu.io.data_reader import read_training_examples
from photon_ml_tpu.io.durable import durable_replace
from photon_ml_tpu.io.model_io import load_game_model
from photon_ml_tpu.io.schemas import SCORING_RESULT_SCHEMA
from photon_ml_tpu.evaluation import get_evaluator
from photon_ml_tpu.models import RandomEffectModel
from photon_ml_tpu.utils import PhotonLogger, Timed, resolve_dtype


def _positive_int(value: str) -> int:
    n = int(value)
    if n <= 0:
        raise argparse.ArgumentTypeError(
            f"expected a positive integer, got {value!r}")
    return n


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="GAME scoring driver (TPU-native)")
    p.add_argument("--data", required=True, nargs="+")
    p.add_argument("--model-dir", required=True)
    p.add_argument("--output-dir", required=True)
    p.add_argument("--evaluators", nargs="*", default=())
    p.add_argument("--group-column", default=None,
                   help="metadataMap column keying grouped (Multi-) "
                        "evaluators, e.g. a query id for per_group_auc")
    p.add_argument("--per-coordinate-scores", action="store_true",
                   help="include a per-coordinate score breakdown")
    p.add_argument("--input-columns", default=None,
                   help="JSON (inline or path) remapping record field names")
    p.add_argument("--batch-rows", type=_positive_int, default=None,
                   help="score in row batches of this size (bounds device "
                        "memory for large scoring sets; must be positive "
                        "— 0/negative used to silently produce no output "
                        "rows mid-write)")
    p.add_argument("--out-of-core", action="store_true",
                   help="larger-than-host-RAM scoring: decode block "
                        "windows of ~--batch-rows rows one at a time "
                        "(io/data_reader.read_training_examples_chunked), "
                        "score each, and append its ScoringResult records "
                        "before the next window decodes — host RAM holds "
                        "one window plus O(16B/row) evaluator state")
    p.add_argument("--dtype", default="float32", choices=["float32", "float64"])
    return p


def _scoring_record(uid, score: float, label: float, parts, i: int) -> dict:
    """One ScoringResultAvro record (shared by the resident and
    out-of-core writers)."""
    return {
        "uid": uid,
        "predictionScore": float(score),
        "label": None if np.isnan(label) else float(label),
        "scoreComponents": {k: float(v[i]) for k, v in parts.items()},
    }


def _slice_host_sparse(sp, row_slice):
    from photon_ml_tpu.game.data import HostSparse

    return HostSparse(sp.indices[row_slice], sp.values[row_slice], sp.dim)


def main(argv: Sequence[str] | None = None) -> int:
    try:
        return _main(argv)
    except Exception as e:
        # scoring is stateless and its output write is atomic, so device
        # loss needs no marker: exit 75 (EX_TEMPFAIL) and a supervisor
        # rerun is a clean, idempotent retry (same contract as the
        # training drivers)
        from photon_ml_tpu.utils import is_device_loss

        if is_device_loss(e):
            import sys

            print("device lost; rerun this command (scoring is "
                  "idempotent, no partial output was published)",
                  file=sys.stderr)
            return 75
        raise


def _main(argv: Sequence[str] | None = None) -> int:
    args = build_arg_parser().parse_args(argv)
    dtype = resolve_dtype(args.dtype)
    os.makedirs(args.output_dir, exist_ok=True)
    logger = PhotonLogger(os.path.join(args.output_dir, "photon.log.jsonl"))
    logger.log("driver_start", driver="game_scoring", args=vars(args))

    with Timed(logger, "load_model"):
        model = load_game_model(args.model_dir)
    from photon_ml_tpu.io.paldb import load_index_map

    shards = sorted({c.feature_shard for c in model.coordinates.values()})
    index_maps = {
        s: load_index_map(os.path.join(args.model_dir, f"index-map.{s}.json"))
        for s in shards
    }
    entity_columns = [
        c.entity_column for c in model.coordinates.values()
        if isinstance(c, RandomEffectModel) and c.entity_column
    ]
    if args.group_column and args.group_column not in entity_columns:
        entity_columns = entity_columns + [args.group_column]

    from photon_ml_tpu.cli.game_training_driver import _load_input_columns

    if args.out_of_core:
        return _score_out_of_core(args, model, index_maps, entity_columns,
                                  logger, dtype)

    with Timed(logger, "read_data"):
        feats, labels, offsets, weights, ents, uids = read_training_examples(
            args.data, index_maps, entity_columns=entity_columns,
            columns=_load_input_columns(args.input_columns),
            require_response=False,
        )
    logger.log("data_read", num_rows=len(labels))

    def score_rows(row_slice):
        f = {s: _slice_host_sparse(sp, row_slice) for s, sp in feats.items()}
        e = {c: v[row_slice] for c, v in ents.items()}
        result = score_game_model(
            model, f, e, offsets=offsets[row_slice], dtype=dtype,
            per_coordinate=args.per_coordinate_scores,
        )
        if args.per_coordinate_scores:
            s, parts = result
            return np.asarray(s), {k: np.asarray(v) for k, v in parts.items()}
        return np.asarray(result), {}

    with Timed(logger, "score"):
        n = len(labels)
        if n == 0:
            # empty scoring set: a valid, COMPLETE empty output (the
            # atomic write below still runs), not a device no-op that
            # happens to work — downstream consumers see scores.avro
            # with zero records and evaluation is skipped
            chunks = []
        else:
            step = args.batch_rows or n
            chunks = [score_rows(slice(i, min(i + step, n)))
                      for i in range(0, n, step)]
        scores = np.concatenate([c[0] for c in chunks]) if chunks else np.zeros(0)
        parts = {}
        if chunks and chunks[0][1]:
            parts = {k: np.concatenate([c[1][k] for c in chunks])
                     for k in chunks[0][1]}

    with Timed(logger, "write_scores"):
        if len(scores) != len(uids):
            # belt-and-braces: never start streaming records whose score
            # lookups will IndexError halfway through the Avro write
            raise RuntimeError(
                f"scored {len(scores)} rows but read {len(uids)} — "
                "refusing to write a partial scoring set")

        def records():
            for i, uid in enumerate(uids):
                yield _scoring_record(uid, scores[i], labels[i], parts, i)

        _write_scores_atomic(args.output_dir, records())

    labeled = ~np.isnan(labels)
    metrics = {}
    if args.evaluators and not labeled.any():
        logger.log("evaluation_skipped", reason="no labeled rows")
    else:
        group_ids = (ents[args.group_column][labeled]
                     if args.group_column else None)
        for name in args.evaluators:
            ev = get_evaluator(name)
            metrics[name] = ev.evaluate(scores[labeled], labels[labeled],
                                        weights[labeled], group_ids)
    if metrics:
        logger.log("evaluation", **metrics)
    logger.log("driver_done", num_scored=len(scores))
    logger.close()
    return 0



def _write_scores_atomic(output_dir: str, records) -> None:
    """scores.avro appears only when COMPLETE: the writer streams into a
    sibling tmp file that is renamed into place at the end, so a crash
    mid-scoring (device loss) can never leave a partial output a consumer
    would mistake for the full scoring set."""
    final = os.path.join(output_dir, "scores.avro")
    tmp = f"{final}.tmp-{os.getpid()}"
    try:
        write_avro_file(tmp, records, SCORING_RESULT_SCHEMA)
    except BaseException:
        import contextlib

        with contextlib.suppress(OSError):
            os.remove(tmp)
        raise
    durable_replace(tmp, final)

def _score_out_of_core(args, model, index_maps, entity_columns, logger,
                       dtype) -> int:
    """Stream decode -> score -> write, one block window at a time. The
    Avro writer consumes a generator, so output records append as each
    window finishes; only evaluator inputs (scores/labels/weights/groups,
    16B/row) accumulate in host RAM."""
    from photon_ml_tpu.game.scoring import score_game_model
    from photon_ml_tpu.io.data_reader import read_training_examples_chunked

    from photon_ml_tpu.cli.game_training_driver import _load_input_columns

    cols = _load_input_columns(args.input_columns)
    chunk_rows = args.batch_rows or (1 << 16)
    acc_scores, acc_labels, acc_weights, acc_groups = [], [], [], []
    n_scored = [0]

    def scored_records():
        windows = read_training_examples_chunked(
            args.data, index_maps, entity_columns=entity_columns,
            columns=cols, chunk_rows=chunk_rows, require_response=False)
        for feats, labels, offsets, weights, ents, uids in windows:
            result = score_game_model(
                model, feats, ents, offsets=offsets, dtype=dtype,
                per_coordinate=args.per_coordinate_scores)
            if args.per_coordinate_scores:
                scores, parts = result
                parts = {k: np.asarray(v) for k, v in parts.items()}
            else:
                scores, parts = result, {}
            scores = np.asarray(scores)
            if args.evaluators:
                # evaluator state is the ONLY per-row accumulation
                # (16B/row); without evaluators nothing accumulates at all
                acc_scores.append(scores)
                acc_labels.append(labels)
                acc_weights.append(weights)
                if args.group_column:
                    acc_groups.append(ents[args.group_column])
            n_scored[0] += len(scores)
            for i, uid in enumerate(uids):
                yield _scoring_record(uid, scores[i], labels[i], parts, i)

    with Timed(logger, "score_and_write"):
        _write_scores_atomic(args.output_dir, scored_records())

    metrics = {}
    if args.evaluators:
        scores = (np.concatenate(acc_scores) if acc_scores
                  else np.zeros(0))
        labels = (np.concatenate(acc_labels) if acc_labels
                  else np.zeros(0))
        weights = (np.concatenate(acc_weights) if acc_weights
                   else np.zeros(0))
        labeled = ~np.isnan(labels)
        if labeled.any():
            groups = (np.concatenate(acc_groups)[labeled]
                      if acc_groups else None)
            for name in args.evaluators:
                ev = get_evaluator(name)
                metrics[name] = ev.evaluate(scores[labeled],
                                            labels[labeled],
                                            weights[labeled], groups)
        else:
            logger.log("evaluation_skipped", reason="no labeled rows")
    if metrics:
        logger.log("evaluation", **metrics)
    logger.log("driver_done", num_scored=n_scored[0])
    logger.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
