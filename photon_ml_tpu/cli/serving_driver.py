"""Online scoring driver: serve a saved GAME model over HTTP.

The fourth driver next to train/score/index: load a model ONCE, keep it
resident (``serve/session.py``), and answer JSON scoring requests with
micro-batching, shape-bucketed pre-compiled executables, and an
entity-coefficient LRU. See docs/serving.md for the endpoint and
operational contract, docs/lifecycle.md for the registry integration.

    photon-game-serve --model-dir out/model --port 8471 \
        --max-batch 64 --max-delay-ms 5

    # registry mode: serve LATEST, follow promotions, hot-swap in place
    photon-game-serve --registry /models/registry --watch-interval-s 10

Shutdown contract: SIGTERM/SIGINT stop the listener (no new requests),
DRAIN the micro-batcher (in-flight and queued batches finish and their
responses go out), then exit 0 — a rolling restart never kills requests
mid-batch.
"""

from __future__ import annotations

import argparse
import os
import signal
import threading
from typing import Sequence

from photon_ml_tpu.utils import PhotonLogger, Timed


def positive_int(value: str) -> int:
    n = int(value)
    if n <= 0:
        raise argparse.ArgumentTypeError(
            f"expected a positive integer, got {value!r}")
    return n


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        description="GAME online scoring server (TPU-native)")
    src = p.add_mutually_exclusive_group(required=True)
    src.add_argument("--model-dir",
                     help="serve one fixed saved-model directory")
    src.add_argument("--registry",
                     help="model-registry root (registry/): serve the "
                          "LATEST version and hot-swap on promotion")
    p.add_argument("--model-version", default=None,
                   help="with --registry: pin a specific version instead "
                        "of LATEST (also disables the watcher)")
    p.add_argument("--watch-interval-s", type=float, default=10.0,
                   help="with --registry: poll LATEST this often and "
                        "hot-swap on change; <= 0 disables the watcher")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8471,
                   help="0 binds an ephemeral port (printed at startup)")
    p.add_argument("--max-batch", type=positive_int, default=64,
                   help="rows per scoring execution; also the top of the "
                        "pre-compiled shape ladder")
    p.add_argument("--max-delay-ms", type=float, default=5.0,
                   help="longest a request waits for batch companions")
    p.add_argument("--max-queue", type=positive_int, default=256,
                   help="admission-queue bound; beyond it requests are "
                        "shed with HTTP 429")
    p.add_argument("--pad-nnz", type=positive_int, default=64,
                   help="padded nonzeros per row in the compiled shapes")
    p.add_argument("--coeff-cache-entries", type=positive_int, default=4096,
                   help="resident entities per random effect (LRU)")
    p.add_argument("--watchdog-s", type=float, default=60.0,
                   help="stuck-batch watchdog; <= 0 disables")
    p.add_argument("--request-timeout-s", type=float, default=30.0)
    p.add_argument("--drain-timeout-s", type=float, default=30.0,
                   help="longest a SIGTERM/SIGINT shutdown waits for the "
                        "micro-batcher to flush in-flight batches")
    p.add_argument("--log-dir", default=None,
                   help="photon.log.jsonl location (default: model dir "
                        "or registry root)")
    p.add_argument("--dtype", default="float32",
                   choices=["float32", "float64"])
    return p


def build_server(args):
    """Session + batcher + HTTP server (+ registry) from parsed args
    (shared with the serving bench, which drives the service without
    the process exec). Returns (server, registry_or_None)."""
    from photon_ml_tpu.serve import (
        MicroBatcher,
        ScoringServer,
        ScoringService,
        ScoringSession,
    )

    registry = None
    if args.registry:
        from photon_ml_tpu.registry import ModelRegistry, RegistryError

        registry = ModelRegistry(args.registry)
        version = args.model_version or registry.read_latest()
        if version is None:
            raise RegistryError(
                f"registry {args.registry} has no live version; publish "
                "and promote one (photon-model-publish) or pass "
                "--model-version")
        source = registry.open_version(version)
    else:
        source = args.model_dir
    session = ScoringSession(
        source, dtype=args.dtype, max_batch=args.max_batch,
        pad_nnz=args.pad_nnz, coeff_cache_entries=args.coeff_cache_entries)
    batcher = MicroBatcher(
        session.score_rows, max_batch=args.max_batch,
        max_delay_ms=args.max_delay_ms, max_queue=args.max_queue,
        watchdog_s=(None if args.watchdog_s <= 0 else args.watchdog_s),
        metrics=session.metrics)
    service = ScoringService(session, batcher,
                             request_timeout_s=args.request_timeout_s,
                             registry=registry)
    return ScoringServer(service, host=args.host, port=args.port), registry


def install_signal_handlers(server, signals=(signal.SIGTERM, signal.SIGINT)):
    """Arm graceful drain: the first SIGTERM/SIGINT stops the HTTP
    accept loop FROM A HELPER THREAD (``shutdown()`` handshakes with the
    running ``serve_forever`` loop and would deadlock if called inside
    the signal handler on the same thread), letting ``main`` fall
    through to ``server.close()`` — which drains the micro-batcher —
    and return 0. A second signal is ignored (drain is already
    running); must be called from the main thread (CPython restriction
    on ``signal.signal``). Returns the handler's state dict
    (``state["signal"]`` is the signum that fired, for logging)."""
    state = {"signal": None}

    def handler(signum, frame):
        if state["signal"] is not None:
            return
        state["signal"] = signum
        threading.Thread(target=server._httpd.shutdown, daemon=True,
                         name="photon-serve-shutdown").start()

    for sig in signals:
        signal.signal(sig, handler)
    state["handler"] = handler
    return state


def main(argv: Sequence[str] | None = None) -> int:
    args = build_arg_parser().parse_args(argv)
    log_dir = args.log_dir or args.model_dir or args.registry
    os.makedirs(log_dir, exist_ok=True)
    logger = PhotonLogger(os.path.join(log_dir, "photon.log.jsonl"))
    logger.log("driver_start", driver="serving", args=vars(args))
    with Timed(logger, "load_and_warmup"):
        server, registry = build_server(args)
    session = server.service.session
    compiled = session.compile_count
    watcher = None
    if (registry is not None and args.watch_interval_s > 0
            and not args.model_version):
        from photon_ml_tpu.serve import RegistryWatcher

        watcher = RegistryWatcher(
            registry, session, interval_s=args.watch_interval_s,
            on_swap=lambda v: logger.log("hot_swap", version=v,
                                         source="watcher"),
            on_error=lambda e: logger.log("watch_error", error=str(e)),
        ).start()
    logger.log("serving_ready", host=server.host, port=server.port,
               active_version=session.active_version,
               precompiled_executables=compiled)
    print(f"serving {session.active_version} on "
          f"http://{server.host}:{server.port} "
          f"({compiled} pre-compiled executables; POST /score, "
          "POST /admin/reload, GET /healthz, GET /metrics)", flush=True)
    stop = install_signal_handlers(server)
    try:
        server.serve_forever()
    except KeyboardInterrupt:  # pre-handler window / non-main-thread use
        pass
    finally:
        if watcher is not None:
            watcher.stop()
        if stop["signal"] is not None:
            logger.log("draining", signal=int(stop["signal"]),
                       queue_depth=server.service.batcher.queue_depth)
        server.close(drain_timeout_s=args.drain_timeout_s)
        logger.log("driver_done", drained=True,
                   **server.service.metrics.snapshot())
        logger.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
