"""Online scoring driver: serve a saved GAME model over HTTP.

The fourth driver next to train/score/index: load a model ONCE, keep it
resident (``serve/session.py``), and answer JSON scoring requests with
micro-batching, shape-bucketed pre-compiled executables, a
device-resident paged coefficient table, and an entity-coefficient LRU.
See docs/serving.md for the endpoint and operational contract,
docs/lifecycle.md for the registry integration.

    photon-game-serve --model-dir out/model --port 8471 \
        --max-batch 64 --max-delay-ms 5

    # registry mode: serve LATEST, follow promotions, hot-swap in place
    photon-game-serve --registry /models/registry --watch-interval-s 10

    # multi-replica: N serving processes behind an asyncio front door,
    # every replica watching the same registry for consistent hot swap
    photon-game-serve --registry /models/registry --replicas 4 \
        --port 8471

The front end defaults to the asyncio server (``--server async``,
``serve/aserver.py``); ``--server thread`` keeps the PR-2
``ThreadingHTTPServer`` stack.

Shutdown contract: SIGTERM/SIGINT stop the listener (no new requests),
DRAIN the micro-batcher (in-flight and queued batches finish and their
responses go out), then exit 0 — a rolling restart never kills requests
mid-batch. In multi-replica mode the parent forwards the signal to
every replica and waits for their drains.
"""

from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys
import threading
import time
from typing import Sequence

from photon_ml_tpu.utils import PhotonLogger, Timed


def positive_int(value: str) -> int:
    n = int(value)
    if n <= 0:
        raise argparse.ArgumentTypeError(
            f"expected a positive integer, got {value!r}")
    return n


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        description="GAME online scoring server (TPU-native)")
    src = p.add_mutually_exclusive_group(required=True)
    src.add_argument("--model-dir",
                     help="serve one fixed saved-model directory")
    src.add_argument("--registry",
                     help="model-registry root (registry/): serve the "
                          "LATEST version and hot-swap on promotion")
    p.add_argument("--model-version", default=None,
                   help="with --registry: pin a specific version instead "
                        "of LATEST (also disables the watcher)")
    p.add_argument("--watch-interval-s", type=float, default=10.0,
                   help="with --registry: poll LATEST this often and "
                        "hot-swap on change; <= 0 disables the watcher")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8471,
                   help="0 binds an ephemeral port (printed at startup)")
    p.add_argument("--max-batch", type=positive_int, default=64,
                   help="rows per scoring execution; also the top of the "
                        "pre-compiled shape ladder")
    p.add_argument("--max-delay-ms", type=float, default=5.0,
                   help="longest a request waits for batch companions")
    p.add_argument("--max-queue", type=positive_int, default=256,
                   help="admission-queue bound; beyond it requests are "
                        "shed with HTTP 429")
    p.add_argument("--pad-nnz", type=positive_int, default=64,
                   help="padded nonzeros per row in the compiled shapes")
    p.add_argument("--coeff-cache-entries", type=positive_int, default=4096,
                   help="resident entities per random effect (LRU)")
    p.add_argument("--server", choices=["async", "thread"], default="async",
                   help="front end: asyncio event loop (default) or the "
                        "thread-per-request http.server stack")
    p.add_argument("--replicas", type=positive_int, default=1,
                   help="N > 1 spawns N serving processes on successive "
                        "ports behind an asyncio front door on --port")
    p.add_argument("--front-door-policy", default="least_loaded",
                   choices=["least_loaded", "round_robin"],
                   help="replica selection at the front door")
    p.add_argument("--no-paged-table", action="store_true",
                   help="disable the device-resident paged coefficient "
                        "table (host-LRU scoring path only)")
    p.add_argument("--re-pages", type=positive_int, default=4,
                   help="paged-table pages per random effect")
    p.add_argument("--re-page-rows", type=positive_int, default=256,
                   help="entities per paged-table page (page = unit of "
                        "device install/evict transfer)")
    p.add_argument("--re-dense-dim-max", type=positive_int, default=4096,
                   help="widest random-effect feature space to densify "
                        "into pages; wider coordinates use the LRU path")
    p.add_argument("--queue-deadline-s", type=float, default=0.0,
                   help="> 0 sheds requests still queued after this long "
                        "(429 cause=deadline) instead of scoring them")
    p.add_argument("--default-deadline-ms", type=float, default=0.0,
                   help="> 0 gives requests WITHOUT an X-Deadline-Ms "
                        "header this budget; expired requests drop at "
                        "the cheapest stage (429 cause=deadline)")
    p.add_argument("--brownout", action="store_true",
                   help="enable the brownout controller: sustained "
                        "queue-wait overload raises the default "
                        "degraded-scoring level (resident-only, then "
                        "fixed-effect-only) before any 429 shedding")
    p.add_argument("--brownout-l1-ms", type=float, default=50.0,
                   help="queue-wait EWMA (ms) at which brownout level 1 "
                        "(resident-coefficients-only) engages")
    p.add_argument("--brownout-l2-ms", type=float, default=200.0,
                   help="queue-wait EWMA (ms) at which brownout level 2 "
                        "(fixed-effect-only) engages")
    p.add_argument("--hedge", action="store_true",
                   help="multi-replica front door: duplicate a request "
                        "onto a second replica when the first exceeds "
                        "its observed p99 (first answer wins)")
    p.add_argument("--hedge-min-ms", type=float, default=50.0,
                   help="floor on the hedge trigger delay")
    p.add_argument("--affinity", action="store_true",
                   help="multi-replica front door: route each row to "
                        "the replica OWNING its entity (stable-hash "
                        "membership epochs; join/leave/breaker churn "
                        "re-owns the moved slice with prefetch before "
                        "the epoch commits; docs/serving.md)")
    p.add_argument("--affinity-id-kind", default="auto",
                   choices=["auto", "int", "str"],
                   help="entity-id hashing domain for the owner map; "
                        "auto decides per id (digits hash as int64, "
                        "anything else as a string) to match the "
                        "training shard map")
    p.add_argument("--watchdog-s", type=float, default=60.0,
                   help="stuck-batch watchdog; <= 0 disables")
    p.add_argument("--request-timeout-s", type=float, default=30.0)
    p.add_argument("--drain-timeout-s", type=float, default=30.0,
                   help="longest a SIGTERM/SIGINT shutdown waits for the "
                        "micro-batcher to flush in-flight batches")
    p.add_argument("--log-dir", default=None,
                   help="photon.log.jsonl location (default: model dir "
                        "or registry root)")
    p.add_argument("--dtype", default="float32",
                   choices=["float32", "float64"])
    p.add_argument("--trace-dir", default=None,
                   help="write photon-trace span files here (replicas "
                        "get per-replica subdirectories; merge with "
                        "`photon-trace merge`; docs/observability.md)")
    p.add_argument("--trace-sample", type=float, default=1.0,
                   help="fraction of requests traced under --trace-dir")
    return p


def build_service(args):
    """Session + batcher + service (+ registry) from parsed args
    (shared by both transports and the serving bench, which drives the
    service without the process exec). Returns (service, registry)."""
    from photon_ml_tpu.serve import (
        MicroBatcher,
        ScoringService,
        ScoringSession,
    )

    registry = None
    if args.registry:
        from photon_ml_tpu.registry import ModelRegistry, RegistryError

        registry = ModelRegistry(args.registry)
        version = args.model_version or registry.read_latest()
        if version is None:
            raise RegistryError(
                f"registry {args.registry} has no live version; publish "
                "and promote one (photon-model-publish) or pass "
                "--model-version")
        source = registry.open_version(version)
    else:
        source = args.model_dir
    session = ScoringSession(
        source, dtype=args.dtype, max_batch=args.max_batch,
        pad_nnz=args.pad_nnz, coeff_cache_entries=args.coeff_cache_entries,
        paged_table=not getattr(args, "no_paged_table", False),
        re_pages=getattr(args, "re_pages", 4),
        re_page_rows=getattr(args, "re_page_rows", 256),
        re_dense_dim_max=getattr(args, "re_dense_dim_max", 4096))
    deadline = getattr(args, "queue_deadline_s", 0.0)
    brownout = None
    if getattr(args, "brownout", False):
        from photon_ml_tpu.serve import BrownoutController

        brownout = BrownoutController(
            enter_ms={1: getattr(args, "brownout_l1_ms", 50.0),
                      2: getattr(args, "brownout_l2_ms", 200.0)},
            metrics=session.metrics)
    batcher = MicroBatcher(
        session.score_rows, max_batch=args.max_batch,
        max_delay_ms=args.max_delay_ms, max_queue=args.max_queue,
        watchdog_s=(None if args.watchdog_s <= 0 else args.watchdog_s),
        request_deadline_s=(deadline if deadline > 0 else None),
        metrics=session.metrics, brownout=brownout)
    default_ms = getattr(args, "default_deadline_ms", 0.0)
    service = ScoringService(session, batcher,
                             request_timeout_s=args.request_timeout_s,
                             registry=registry,
                             default_deadline_ms=(
                                 default_ms if default_ms > 0 else None),
                             brownout=brownout)
    return service, registry


def build_server(args):
    """Threaded-transport convenience over :func:`build_service` (kept
    for the PR-2 entry shape: returns (server, registry))."""
    from photon_ml_tpu.serve import ScoringServer

    service, registry = build_service(args)
    return ScoringServer(service, host=args.host, port=args.port), registry


def install_signal_handlers(server, signals=(signal.SIGTERM, signal.SIGINT)):
    """Arm graceful drain: the first SIGTERM/SIGINT stops the HTTP
    accept loop FROM A HELPER THREAD (``shutdown()`` handshakes with the
    running ``serve_forever`` loop and would deadlock if called inside
    the signal handler on the same thread), letting ``main`` fall
    through to ``server.close()`` — which drains the micro-batcher —
    and return 0. A second signal is ignored (drain is already
    running); must be called from the main thread (CPython restriction
    on ``signal.signal``). Returns the handler's state dict
    (``state["signal"]`` is the signum that fired, for logging)."""
    state = {"signal": None, "thread": None}

    def handler(signum, frame):
        if state["signal"] is not None:
            return
        state["signal"] = signum
        # the helper's bounded join lives in join_shutdown_helper (run
        # by main's finally) — it cannot happen here: a signal handler
        # joining its own helper would stall the very drain it triggers
        t = threading.Thread(target=server._httpd.shutdown, daemon=True,
                             name="photon-serve-shutdown")
        state["thread"] = t
        t.start()

    for sig in signals:
        signal.signal(sig, handler)
    state["handler"] = handler
    return state


def join_shutdown_helper(state, timeout_s: float = 5.0,
                         logger=None) -> None:
    """Bounded join of the signal handler's shutdown helper thread (the
    PT403 discipline: no thread leaks without a counter and a log line).
    By the time main's finally runs, ``serve_forever`` has returned, so
    the ``shutdown()`` handshake has completed and the join is instant
    in the healthy case."""
    t = state.get("thread")
    if t is None:
        return
    t.join(timeout_s)
    if t.is_alive():
        state["join_timeouts"] = state.get("join_timeouts", 0) + 1
        if logger is not None:
            logger.log("shutdown_helper_join_timeout",
                       timeout_s=timeout_s,
                       join_timeouts=state["join_timeouts"])


def _maybe_watcher(args, registry, session, logger):
    if (registry is None or args.watch_interval_s <= 0
            or args.model_version):
        return None
    from photon_ml_tpu.serve import RegistryWatcher

    return RegistryWatcher(
        registry, session, interval_s=args.watch_interval_s,
        jitter_s=min(1.0, args.watch_interval_s / 10.0),
        on_swap=lambda v: logger.log("hot_swap", version=v,
                                     source="watcher"),
        on_error=lambda e: logger.log("watch_error", error=str(e)),
    ).start()


def _announce(logger, session, host, port, compiled, transport):
    logger.log("serving_ready", host=host, port=port,
               active_version=session.active_version,
               precompiled_executables=compiled, transport=transport)
    paged = "paged" if session.paged_active else "host-LRU"
    print(f"serving {session.active_version} on http://{host}:{port} "
          f"({transport}, {paged} coefficients, {compiled} pre-compiled "
          "executables; POST /score, POST /admin/reload, GET /healthz, "
          "GET /metrics)", flush=True)


def _run_async(args, logger) -> int:
    from photon_ml_tpu.serve import AsyncScoringServer

    with Timed(logger, "load_and_warmup"):
        service, registry = build_service(args)
    session = service.session
    compiled = session.compile_count
    watcher = _maybe_watcher(args, registry, session, logger)
    server = AsyncScoringServer(service, host=args.host, port=args.port)
    try:
        server.run_forever(
            drain_timeout_s=args.drain_timeout_s,
            ready_callback=lambda srv: _announce(
                logger, session, srv.host, srv.port, compiled, "asyncio"))
    except KeyboardInterrupt:
        pass
    finally:
        if watcher is not None:
            watcher.stop()
        logger.log("driver_done", drained=True,
                   **service.metrics.snapshot())
        logger.close()
    return 0


def _free_port(host: str) -> int:
    s = socket.socket()
    s.bind((host, 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _replica_argv(args, port: int, log_dir: str) -> list:
    argv = [sys.executable, "-m", "photon_ml_tpu.cli.serving_driver",
            "--replicas", "1", "--server", "async",
            "--host", args.host, "--port", str(port),
            "--max-batch", str(args.max_batch),
            "--max-delay-ms", str(args.max_delay_ms),
            "--max-queue", str(args.max_queue),
            "--pad-nnz", str(args.pad_nnz),
            "--coeff-cache-entries", str(args.coeff_cache_entries),
            "--re-pages", str(args.re_pages),
            "--re-page-rows", str(args.re_page_rows),
            "--re-dense-dim-max", str(args.re_dense_dim_max),
            "--queue-deadline-s", str(args.queue_deadline_s),
            "--default-deadline-ms", str(args.default_deadline_ms),
            "--brownout-l1-ms", str(args.brownout_l1_ms),
            "--brownout-l2-ms", str(args.brownout_l2_ms),
            "--watchdog-s", str(args.watchdog_s),
            "--request-timeout-s", str(args.request_timeout_s),
            "--drain-timeout-s", str(args.drain_timeout_s),
            "--watch-interval-s", str(args.watch_interval_s),
            "--dtype", args.dtype, "--log-dir", log_dir]
    if args.trace_dir:
        # each replica process writes its own trace subdir; merge with
        # `photon-trace merge` across replica-*/ afterwards
        argv += ["--trace-dir",
                 os.path.join(args.trace_dir, os.path.basename(log_dir)),
                 "--trace-sample", str(args.trace_sample)]
    if args.no_paged_table:
        argv.append("--no-paged-table")
    if args.brownout:
        argv.append("--brownout")
    if args.registry:
        argv += ["--registry", args.registry]
        if args.model_version:
            argv += ["--model-version", args.model_version]
    else:
        argv += ["--model-dir", args.model_dir]
    return argv


def _wait_healthy(host: str, port: int, timeout_s: float,
                  proc=None) -> bool:
    import urllib.request

    deadline = time.monotonic() + timeout_s
    url = f"http://{host}:{port}/healthz"
    while time.monotonic() < deadline:
        if proc is not None and proc.poll() is not None:
            return False  # replica died during warmup
        try:
            with urllib.request.urlopen(url, timeout=1.0) as resp:
                if resp.status == 200:
                    return True
        except Exception:
            time.sleep(0.2)
    return False


def _run_multi_replica(args, logger) -> int:
    """N replica processes + asyncio front door. Every replica loads the
    same source; in registry mode each runs its own watcher (with
    jitter), so a promotion reaches all replicas within one poll
    interval — the front door needs no model awareness at all."""
    from photon_ml_tpu.serve import AsyncFrontDoor

    log_root = args.log_dir or args.model_dir or args.registry
    ports = [_free_port(args.host) for _ in range(args.replicas)]
    procs = []
    for i, port in enumerate(ports):
        rep_log = os.path.join(log_root, f"replica-{i}")
        os.makedirs(rep_log, exist_ok=True)
        procs.append(subprocess.Popen(
            _replica_argv(args, port, rep_log),
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL))
    logger.log("replicas_spawned", ports=ports,
               pids=[p.pid for p in procs])
    ok = all(_wait_healthy(args.host, port, timeout_s=180.0, proc=p)
             for port, p in zip(ports, procs))
    if not ok:
        for p in procs:
            p.terminate()
        logger.log("replica_startup_failed", ports=ports)
        logger.close()
        print("replica startup failed (see replica logs)", flush=True)
        return 1
    door = AsyncFrontDoor([f"{args.host}:{p}" for p in ports],
                          host=args.host, port=args.port,
                          policy=args.front_door_policy,
                          hedge_enabled=args.hedge,
                          hedge_min_s=args.hedge_min_ms / 1e3,
                          affinity=args.affinity,
                          affinity_id_kind=args.affinity_id_kind)

    def ready(d):
        epoch = d.membership_epoch
        logger.log("front_door_ready", host=d.host, port=d.port,
                   backends=[f"{args.host}:{p}" for p in ports],
                   affinity=bool(args.affinity),
                   membership_epoch=(None if epoch is None
                                     else epoch.epoch))
        routing = (f", entity-affinity epoch {epoch.epoch}"
                   if epoch is not None else "")
        print(f"front door on http://{d.host}:{d.port} -> "
              f"{len(ports)} replicas on {ports} "
              f"({args.front_door_policy}{routing})", flush=True)

    try:
        door.run_forever(ready_callback=ready)
    except KeyboardInterrupt:
        pass
    finally:
        for p in procs:
            p.terminate()  # SIGTERM -> each replica drains
        deadline = time.monotonic() + args.drain_timeout_s + 10.0
        for p in procs:
            try:
                p.wait(max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                p.kill()
        logger.log("driver_done", replicas=len(procs))
        logger.close()
    return 0


def main(argv: Sequence[str] | None = None) -> int:
    args = build_arg_parser().parse_args(argv)
    from photon_ml_tpu.obs import logging as obs_logging
    from photon_ml_tpu.obs import trace as obs_trace

    obs_logging.configure()
    started = None
    if args.trace_dir and args.replicas == 1:
        # single-replica: trace in-process; multi-replica runs trace in
        # the replica processes (the front door stays untraced here)
        started = obs_trace.start(args.trace_dir, sample=args.trace_sample)
    elif args.replicas == 1:
        started = obs_trace.maybe_start_from_env()
    try:
        return _serve(args)
    finally:
        if started is not None:  # only stop a tracer this call started
            obs_trace.stop()


def _serve(args) -> int:
    log_dir = args.log_dir or args.model_dir or args.registry
    os.makedirs(log_dir, exist_ok=True)
    logger = PhotonLogger(os.path.join(log_dir, "photon.log.jsonl"))
    logger.log("driver_start", driver="serving", args=vars(args))
    if args.replicas > 1:
        return _run_multi_replica(args, logger)
    if args.server == "async":
        return _run_async(args, logger)
    with Timed(logger, "load_and_warmup"):
        server, registry = build_server(args)
    session = server.service.session
    compiled = session.compile_count
    watcher = _maybe_watcher(args, registry, session, logger)
    _announce(logger, session, server.host, server.port, compiled,
              "threaded")
    stop = install_signal_handlers(server)
    try:
        server.serve_forever()
    except KeyboardInterrupt:  # pre-handler window / non-main-thread use
        pass
    finally:
        if watcher is not None:
            watcher.stop()
        if stop["signal"] is not None:
            logger.log("draining", signal=int(stop["signal"]),
                       queue_depth=server.service.batcher.queue_depth)
        server.close(drain_timeout_s=args.drain_timeout_s)
        join_shutdown_helper(stop, logger=logger)
        logger.log("driver_done", drained=True,
                   **server.service.metrics.snapshot())
        logger.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
