"""Online scoring driver: serve a saved GAME model over HTTP.

The fourth driver next to train/score/index: load a model ONCE, keep it
resident (``serve/session.py``), and answer JSON scoring requests with
micro-batching, shape-bucketed pre-compiled executables, and an
entity-coefficient LRU. See docs/serving.md for the endpoint and
operational contract.

    photon-game-serve --model-dir out/model --port 8471 \
        --max-batch 64 --max-delay-ms 5
"""

from __future__ import annotations

import argparse
import os
from typing import Sequence

from photon_ml_tpu.utils import PhotonLogger, Timed


def positive_int(value: str) -> int:
    n = int(value)
    if n <= 0:
        raise argparse.ArgumentTypeError(
            f"expected a positive integer, got {value!r}")
    return n


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        description="GAME online scoring server (TPU-native)")
    p.add_argument("--model-dir", required=True)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8471,
                   help="0 binds an ephemeral port (printed at startup)")
    p.add_argument("--max-batch", type=positive_int, default=64,
                   help="rows per scoring execution; also the top of the "
                        "pre-compiled shape ladder")
    p.add_argument("--max-delay-ms", type=float, default=5.0,
                   help="longest a request waits for batch companions")
    p.add_argument("--max-queue", type=positive_int, default=256,
                   help="admission-queue bound; beyond it requests are "
                        "shed with HTTP 429")
    p.add_argument("--pad-nnz", type=positive_int, default=64,
                   help="padded nonzeros per row in the compiled shapes")
    p.add_argument("--coeff-cache-entries", type=positive_int, default=4096,
                   help="resident entities per random effect (LRU)")
    p.add_argument("--watchdog-s", type=float, default=60.0,
                   help="stuck-batch watchdog; <= 0 disables")
    p.add_argument("--request-timeout-s", type=float, default=30.0)
    p.add_argument("--log-dir", default=None,
                   help="photon.log.jsonl location (default: model dir)")
    p.add_argument("--dtype", default="float32",
                   choices=["float32", "float64"])
    return p


def build_server(args):
    """Session + batcher + HTTP server from parsed args (shared with the
    serving bench, which drives the service without the process exec)."""
    from photon_ml_tpu.serve import (
        MicroBatcher,
        ScoringServer,
        ScoringService,
        ScoringSession,
    )

    session = ScoringSession(
        args.model_dir, dtype=args.dtype, max_batch=args.max_batch,
        pad_nnz=args.pad_nnz, coeff_cache_entries=args.coeff_cache_entries)
    batcher = MicroBatcher(
        session.score_rows, max_batch=args.max_batch,
        max_delay_ms=args.max_delay_ms, max_queue=args.max_queue,
        watchdog_s=(None if args.watchdog_s <= 0 else args.watchdog_s),
        metrics=session.metrics)
    service = ScoringService(session, batcher,
                             request_timeout_s=args.request_timeout_s)
    return ScoringServer(service, host=args.host, port=args.port)


def main(argv: Sequence[str] | None = None) -> int:
    args = build_arg_parser().parse_args(argv)
    log_dir = args.log_dir or args.model_dir
    os.makedirs(log_dir, exist_ok=True)
    logger = PhotonLogger(os.path.join(log_dir, "photon.log.jsonl"))
    logger.log("driver_start", driver="serving", args=vars(args))
    with Timed(logger, "load_and_warmup"):
        server = build_server(args)
    compiled = server.service.session.compile_count
    logger.log("serving_ready", host=server.host, port=server.port,
               precompiled_executables=compiled)
    print(f"serving {args.model_dir} on http://{server.host}:{server.port} "
          f"({compiled} pre-compiled executables; POST /score, "
          "GET /healthz, GET /metrics)", flush=True)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        server.close()
        logger.log("driver_done",
                   **server.service.metrics.snapshot())
        logger.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
