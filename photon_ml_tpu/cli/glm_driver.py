"""Classic (non-GAME) GLM training driver — the staged pipeline.

Equivalent of the reference's legacy ``com.linkedin.photon.ml.Driver``
(SURVEY.md §3.3, marked ``(?)``; reference mount empty): a fixed sequence of
stages — validate → summarize/normalize → train one model per regularization
weight with **warm start** across the lambda grid → validate + select best →
diagnostics — for a single fixed-effect GLM, no random effects. The GAME
driver supersedes this for mixed-effect models; this driver remains the
shortest path for plain sparse GLMs (the a1a / Criteo baseline configs,
BASELINE.md #1–#3).

TPU-native shape: each lambda's fit is one jitted device computation
(`fit_distributed`: sharded batch + psum — SURVEY.md §4.2); the lambda loop
reuses the same compiled program because the regularization weight is a
traced argument.

Usage:
    python -m photon_ml_tpu.cli.glm_driver \
        --train-data a1a --input-format libsvm --task logistic_regression \
        --reg-weights 0.1 1.0 10.0 --optimizer lbfgs --output-dir out/
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.evaluation import get_evaluator
from photon_ml_tpu.evaluation.evaluators import TASK_DEFAULT_EVALUATOR
from photon_ml_tpu.game.data import HostSparse
from photon_ml_tpu.io.avro import iter_avro_records
from photon_ml_tpu.io.data_reader import read_training_examples
from photon_ml_tpu.io.index_map import IndexMap, build_index_map
from photon_ml_tpu.io.libsvm import read_libsvm
from photon_ml_tpu.io.model_io import save_game_model
from photon_ml_tpu.io.validators import validate_training_data
from photon_ml_tpu.models import (
    Coefficients,
    FixedEffectModel,
    GameModel,
    GeneralizedLinearModel,
)
from photon_ml_tpu.ops.losses import TASK_TO_LOSS
from photon_ml_tpu.ops.normalization import (
    NormalizationType,
    build_normalization_context,
)
from photon_ml_tpu.ops.objective import make_objective
from photon_ml_tpu.ops.regularization import RegularizationContext
from photon_ml_tpu.ops.statistics import summarize_features
from photon_ml_tpu.optimize import OptimizerConfig
from photon_ml_tpu.parallel.data_parallel import fit_distributed
from photon_ml_tpu.parallel.mesh import make_mesh
from photon_ml_tpu.types import LabeledBatch, SparseFeatures, make_batch
from photon_ml_tpu.utils import (PhotonLogger, Timed, is_device_loss,
                                 resolve_dtype)


def _tol_schedule(value: str):
    from photon_ml_tpu.optimize import parse_tolerance_schedule

    try:
        return parse_tolerance_schedule(value)
    except ValueError as e:
        raise argparse.ArgumentTypeError(str(e))


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="Classic GLM training driver "
                                            "(staged pipeline, TPU-native)")
    p.add_argument("--train-data", required=True, nargs="+")
    p.add_argument("--validation-data", nargs="+", default=None)
    p.add_argument("--input-format", default="avro", choices=["avro", "libsvm"])
    p.add_argument("--output-dir", required=True)
    p.add_argument("--task", default="logistic_regression",
                   choices=sorted(TASK_TO_LOSS) + sorted(set(TASK_TO_LOSS.values())))
    p.add_argument("--optimizer", default="lbfgs",
                   choices=["lbfgs", "owlqn", "tron"])
    p.add_argument("--reg-type", default="l2",
                   choices=["none", "l1", "l2", "elastic_net"])
    p.add_argument("--reg-weights", type=float, nargs="+", default=[0.0],
                   help="lambda grid; trained in order with warm start")
    p.add_argument("--elastic-net-alpha", type=float, default=0.5)
    p.add_argument("--max-iters", type=int, default=100)
    p.add_argument("--tolerance", type=float, default=1e-7)
    p.add_argument("--solver-tol-schedule", type=_tol_schedule, default=None,
                   metavar="START:DECAY",
                   help="inexact path-following over the lambda grid: the "
                        "i-th lambda solves to max(--tolerance, START * "
                        "DECAY^i) — early grid points only warm-start the "
                        "chain, so a loose solve there buys wall-clock "
                        "without moving the tight final fits (e.g. "
                        "1e-3:0.1; 'off' disables)")
    p.add_argument("--path-screen", default="off",
                   choices=["off", "strong", "safe"],
                   help="pathwise screening over the lambda grid "
                        "(optimize/path.py, docs/path.md): walk "
                        "--reg-weights in decreasing order, freeze "
                        "features the sequential strong/safe rule screens "
                        "out, solve the restricted problem, and KKT-"
                        "certify against the full gradient (violators "
                        "re-enter and the solve repeats). Composes with "
                        "warm start, --solver-tol-schedule and "
                        "--auto-resume; requires an L1 component "
                        "(l1/elastic_net) to bite and refuses "
                        "--normalization")
    p.add_argument("--path-kkt-tol", type=float, default=1e-6,
                   help="relative slack of the screened-coordinate KKT "
                        "certification test (ops.regularization."
                        "kkt_slack)")
    p.add_argument("--path-max-kkt-rounds", type=int, default=5,
                   help="restricted-solve repair rounds per lambda before "
                        "falling back to a full-width solve")
    p.add_argument("--path-min-bucket", type=int, default=64,
                   help="floor of the power-of-two restricted-width "
                        "bucket ladder")
    p.add_argument("--normalization", default="none",
                   choices=[t.value for t in NormalizationType])
    p.add_argument("--add-intercept", action="store_true", default=True)
    p.add_argument("--no-intercept", dest="add_intercept", action="store_false")
    p.add_argument("--index-map", default=None,
                   help="prebuilt index map (avro input only)")
    p.add_argument("--hash-dim", type=int, default=None,
                   help="feature-hash into this width instead of building an "
                        "index map (avro input only)")
    p.add_argument("--min-feature-count", type=int, default=1)
    p.add_argument("--evaluators", nargs="*", default=None)
    p.add_argument("--validate-data", action="store_true", default=True,
                   help="run DataValidators-style checks before training")
    p.add_argument("--no-validate-data", dest="validate_data",
                   action="store_false")
    p.add_argument("--auto-resume", action="store_true",
                   help="resume a lambda grid that died on device loss "
                        "(RESUME_GLM.npz marker / exit code 75)")
    p.add_argument("--max-rank-failures", type=int, default=0,
                   help="in-job recovery: retry a lambda fit that died in "
                        "a TRANSIENT coordinated abort (every rank alive, "
                        "generic local error) up to this many times, with "
                        "jittered backoff and a re-aligning barrier. GLM "
                        "coefficients are replicated, so there is nothing "
                        "to redistribute: rank loss, device loss and data "
                        "errors still escalate to the --auto-resume "
                        "whole-job path (parallel/recovery.py)")
    p.add_argument("--recovery-snapshot-every", type=int, default=1,
                   help="accepted for CLI parity with photon-game-train; "
                        "the GLM grid's recovery unit is one LAMBDA (every "
                        "finished lambda is already persisted to the "
                        "resume marker), so this knob has no finer "
                        "granularity to select here")
    p.add_argument("--compute-variances", action="store_true",
                   help="diagonal-inverse-Hessian coefficient variances")
    p.add_argument("--summarize-features", action="store_true")
    p.add_argument("--diagnostics", action="store_true",
                   help="write diagnostics.json for the best model: Hosmer-"
                        "Lemeshow fit test (binary), feature importance, "
                        "optional bootstrap CIs")
    p.add_argument("--bootstrap-replicates", type=int, default=0,
                   help="bootstrap refits for coefficient CIs (vmapped into "
                        "one batched fit; 0 disables)")
    p.add_argument("--streaming", action="store_true",
                   help="larger-than-HBM mode: keep the training set in host "
                        "RAM and stream fixed-shape chunks through the "
                        "device each optimizer pass")
    p.add_argument("--out-of-core", action="store_true",
                   help="larger-than-host-RAM mode (implies --streaming): "
                        "never materialize the training set — each optimizer "
                        "pass re-decodes Avro block waves from disk on a "
                        "background thread (io/stream_source.py). Requires "
                        "--input-format avro and a pinned feature space "
                        "(--hash-dim or --index-map); full-data validation/"
                        "summarization/normalization are unavailable (no "
                        "resident data to scan)")
    p.add_argument("--pad-nnz", type=int, default=None,
                   help="fixed per-row feature width incl. intercept for "
                        "--out-of-core (default: one measuring decode pass)")
    p.add_argument("--chunk-rows", type=int, default=1 << 16,
                   help="rows per streamed chunk (--streaming)")
    p.add_argument("--chunk-cache-dir", default=None,
                   help="with --out-of-core: decode-once packed chunk "
                        "cache directory (io/chunk_cache.py) — the first "
                        "optimizer pass spills decoded chunks into packed "
                        "memmaps there, every later pass streams them "
                        "back decode-free. Invalidated automatically when "
                        "the source files, chunk geometry, or index map "
                        "change; multi-process runs need per-process dirs")
    p.add_argument("--chunk-cache-gb", type=float, default=None,
                   help="disk budget for --chunk-cache-dir; a dataset "
                        "that doesn't fit falls through to re-decode "
                        "with a logged warning (default: unbounded)")
    p.add_argument("--prefetch-depth", type=int, default=None,
                   help="streamed transfer-ring depth: how many chunks "
                        "the transfer thread stages on device ahead of "
                        "compute (default 2 / PHOTON_PREFETCH_DEPTH; 0 = "
                        "synchronous)")
    p.add_argument("--dtype", default="float32", choices=["float32", "float64"])
    p.add_argument("--coordinator-address", default=None,
                   help="multi-host: coordinator host:port for "
                        "jax.distributed.initialize (every process runs this "
                        "driver with the same args)")
    p.add_argument("--num-processes", type=int, default=None)
    p.add_argument("--process-id", type=int, default=None)
    p.add_argument("--profile-dir", default=None,
                   help="capture a JAX profiler trace of training here")
    p.add_argument("--trace-dir", default=None,
                   help="write photon-trace span files here (one "
                        "trace-rankN.json per process; merge with "
                        "`photon-trace merge`; docs/observability.md)")
    p.add_argument("--trace-sample", type=float, default=1.0,
                   help="fraction of traces recorded under --trace-dir")
    return p


def _read(paths, fmt, index_map: Optional[IndexMap], add_intercept):
    """-> (HostSparse, labels, offsets, weights, index_map, intercept_index).
    Host-side only; device conversion happens after validation."""
    if fmt == "libsvm":
        # read raw (no intercept) so multiple files share one feature space,
        # then append the intercept column at the common dim
        parts = [read_libsvm(p) for p in paths]
        # an index_map (from the training pass) pins the feature space, so
        # validation files line up with the trained model: features beyond it
        # are dropped, missing ones stay implicit zeros
        if index_map is not None:
            base_dim = index_map.size - (
                1 if index_map.intercept_index >= 0 else 0
            )
            for sp, _, _ in parts:
                drop = sp.indices >= base_dim
                sp.indices[drop] = 0
                sp.values[drop] = 0.0
        else:
            base_dim = max(sp.dim for sp, _, _ in parts)
        intercept = base_dim if add_intercept else -1
        dim = base_dim + (1 if add_intercept else 0)
        k = max(sp.values.shape[1] for sp, _, _ in parts) + (
            1 if add_intercept else 0
        )
        n = sum(sp.num_rows for sp, _, _ in parts)
        indices = np.zeros((n, k), np.int32)
        values = np.zeros((n, k))
        at = 0
        for sp, _, _ in parts:
            m, kk = sp.values.shape
            indices[at:at + m, :kk] = sp.indices
            values[at:at + m, :kk] = sp.values
            if add_intercept:
                indices[at:at + m, kk] = intercept
                values[at:at + m, kk] = 1.0
            at += m
        labels = np.concatenate([lab for _, lab, _ in parts])
        feats = HostSparse(indices, values, dim)
        if index_map is None:
            entries = {f"f{i}": i for i in range(base_dim)}
            if intercept >= 0:
                entries["(INTERCEPT)"] = intercept
            index_map = IndexMap(entries)
        return feats, labels, np.zeros(n), np.ones(n), index_map, intercept
    feats, labels, offsets, weights, _, _ = read_training_examples(
        paths, index_map
    )
    return (feats["global"], labels, offsets, weights, index_map,
            index_map.intercept_index)


def main(argv: Sequence[str] | None = None) -> int:
    args = build_arg_parser().parse_args(argv)
    from photon_ml_tpu.obs import logging as obs_logging
    from photon_ml_tpu.obs import trace as obs_trace

    obs_logging.configure()
    if args.trace_dir:
        started = obs_trace.start(args.trace_dir, sample=args.trace_sample)
    else:
        started = obs_trace.maybe_start_from_env()
    try:
        return _run(args)
    finally:
        # every exit path exports the trace files; only stop a tracer
        # this invocation started (simulated-harness ranks share one)
        if started is not None:
            obs_trace.stop()


def _run(args) -> int:
    from photon_ml_tpu.parallel import fault_injection, resilience
    from photon_ml_tpu.parallel.multihost import initialize_multihost, runtime_info

    distributed = initialize_multihost(args.coordinator_address,
                                       args.num_processes, args.process_id)
    dtype = resolve_dtype(args.dtype)
    task = TASK_TO_LOSS.get(args.task, args.task)
    os.makedirs(args.output_dir, exist_ok=True)
    logger = PhotonLogger(os.path.join(args.output_dir, "photon.log.jsonl"))
    logger.log("driver_start", driver="glm", args=vars(args),
               distributed=distributed, **runtime_info())

    reg = RegularizationContext(args.reg_type, alpha=args.elastic_net_alpha)
    optimizer = args.optimizer
    if reg.needs_owlqn and optimizer != "owlqn":
        logger.log("optimizer_override", requested=optimizer, used="owlqn",
                   reason=f"reg_type={args.reg_type} needs OWL-QN")
        optimizer = "owlqn"

    if args.path_screen != "off" \
            and NormalizationType(args.normalization) != NormalizationType.NONE:
        raise SystemExit(
            "--path-screen does not compose with --normalization: the "
            "virtual shift couples every column through the margin "
            "adjustment, so a frozen column would still move the margins "
            "(optimize/path.py). Normalize the data on disk or drop one "
            "of the flags")

    out_of_core = args.out_of_core
    if args.chunk_cache_dir and not out_of_core:
        raise SystemExit("--chunk-cache-dir requires --out-of-core (the "
                         "in-RAM streaming path never re-decodes)")
    if args.chunk_cache_gb is not None and not args.chunk_cache_dir:
        raise SystemExit("--chunk-cache-gb requires --chunk-cache-dir")
    if out_of_core:
        if args.input_format != "avro":
            raise SystemExit("--out-of-core requires --input-format avro")
        if not (args.hash_dim or args.index_map):
            raise SystemExit(
                "--out-of-core needs a pinned feature space (--hash-dim or "
                "--index-map): building an index map would scan the full "
                "dataset — run the feature indexing driver first")
        if args.summarize_features or NormalizationType(args.normalization) != NormalizationType.NONE:
            raise SystemExit("--out-of-core cannot summarize/normalize: "
                             "no resident data to scan")

    # -- stage: read + index -------------------------------------------------
    with Timed(logger, "read_train_data"):
        index_map = None
        if args.input_format == "avro":
            if args.hash_dim:
                from photon_ml_tpu.io.hashing import HashingIndexMap

                index_map = HashingIndexMap(args.hash_dim,
                                            add_intercept=args.add_intercept)
            elif args.index_map:
                from photon_ml_tpu.io.paldb import load_index_map

                index_map = load_index_map(args.index_map)
            else:
                index_map = build_index_map(
                    iter_avro_records(args.train_data),
                    add_intercept=args.add_intercept,
                    min_count=args.min_feature_count,
                )
        if out_of_core:
            from photon_ml_tpu.io.stream_source import AvroChunkSource

            n_local_dev = max(len(jax.local_devices()), 1)
            ooc_chunk_rows = -(-args.chunk_rows // n_local_dev) * n_local_dev
            src = AvroChunkSource(
                args.train_data, index_map, chunk_rows=ooc_chunk_rows,
                pad_nnz=args.pad_nnz, dtype=resolve_dtype(args.dtype),
                process_part=((jax.process_index(), jax.process_count())
                              if distributed else None))
            if args.chunk_cache_dir:
                from photon_ml_tpu.io.chunk_cache import ChunkCacheSource

                src = ChunkCacheSource(
                    src, args.chunk_cache_dir,
                    max_bytes=(None if args.chunk_cache_gb is None
                               else int(args.chunk_cache_gb * 1e9)))
            host_feats = labels = offsets = weights = None
            intercept_index = index_map.intercept_index
        else:
            (host_feats, labels, offsets, weights, index_map,
             intercept_index) = _read(
                args.train_data, args.input_format, index_map,
                args.add_intercept
            )
    validation = None
    if args.validation_data:
        with Timed(logger, "read_validation_data"):
            vhost, vlabels, voffsets, vweights, _, _ = _read(
                args.validation_data, args.input_format, index_map,
                args.add_intercept,
            )
            validation = (vhost, vlabels, voffsets, vweights)
    logger.log("data_read",
               num_train=(src.rows if out_of_core else int(labels.shape[0])),
               num_validation=0 if validation is None else int(vlabels.shape[0]),
               num_features=(index_map.size if out_of_core
                             else host_feats.dim))

    # -- stage: validate (on host, before any device transfer) ---------------
    if args.validate_data:
        with Timed(logger, "validate_data"):
            if out_of_core:
                # no resident training data to scan: structural validation
                # happens per decoded chunk (the source raises on unlabeled
                # / malformed records); only validation data is checked here
                logger.log("validate_skipped_out_of_core")
            else:
                validate_training_data(host_feats, labels, offsets, weights,
                                       task=task)
            if validation is not None:
                validate_training_data(vhost, vlabels, voffsets, vweights,
                                       task=task)

    # -- stage: summarize + normalization ------------------------------------
    streaming = args.streaming or out_of_core
    dim = index_map.size if out_of_core else host_feats.dim
    if out_of_core:
        chunks = src
        batch = None
    elif streaming:
        from photon_ml_tpu.parallel.multihost import process_span
        from photon_ml_tpu.parallel.streaming import make_host_chunks

        # training set stays in host RAM; only fixed-shape chunks ever
        # touch the device. Distributed: each process streams only its own
        # contiguous row span (the reference's input-split assignment); the
        # per-chunk partials then psum over the full mesh.
        span = process_span(len(labels)) if distributed else (0, len(labels))
        sl = slice(*span)
        from photon_ml_tpu.game.data import HostSparse

        local_feats = HostSparse(np.asarray(host_feats.indices)[sl],
                                 np.asarray(host_feats.values)[sl],
                                 host_feats.dim)
        n_local_dev = max(len(jax.local_devices()), 1)
        chunk_rows = -(-args.chunk_rows // n_local_dev) * n_local_dev
        chunks, _ = make_host_chunks(
            local_feats, np.asarray(labels)[sl], np.asarray(offsets)[sl],
            np.asarray(weights)[sl], chunk_rows=chunk_rows)
        batch = LabeledBatch(host_feats, labels, offsets, weights)
        feats = None
    else:
        feats = SparseFeatures(jnp.asarray(host_feats.indices),
                               jnp.asarray(host_feats.values, dtype),
                               dim=dim)
        batch = make_batch(feats, labels, offsets, weights, dtype=dtype)
    validation_batch = None
    if validation is not None:
        vfeats = SparseFeatures(jnp.asarray(vhost.indices),
                                jnp.asarray(vhost.values, dtype),
                                dim=vhost.dim)
        validation_batch = make_batch(vfeats, vlabels, voffsets, vweights,
                                      dtype=dtype)
    norm_type = NormalizationType(args.normalization)
    normalization = None
    if norm_type != NormalizationType.NONE or args.summarize_features:
        with Timed(logger, "feature_summarization"):
            summary = summarize_features(batch)
            if args.summarize_features:
                from photon_ml_tpu.cli.game_training_driver import _write_summary

                _write_summary(args.output_dir, summary, index_map)
            if norm_type != NormalizationType.NONE:
                normalization = build_normalization_context(
                    norm_type, summary, intercept_index=intercept_index
                )

    objective = make_objective(task, normalization=normalization,
                               intercept_index=intercept_index)
    mesh = make_mesh()
    # streamed chunks shard over THIS process's devices only; the global
    # mesh is for the in-memory fit_distributed path
    stream_mesh = (mesh if not distributed
                   else make_mesh({"data": len(jax.local_devices())},
                                  devices=jax.local_devices()))
    opt_config = OptimizerConfig(max_iters=args.max_iters,
                                 tolerance=args.tolerance)

    evaluators = args.evaluators
    if evaluators is None:
        evaluators = [TASK_DEFAULT_EVALUATOR[task]] if validation is not None else []

    # -- stage: train over the lambda grid with warm start -------------------
    results = []
    w = jnp.zeros((dim,), dtype)
    from photon_ml_tpu.utils import profile_trace

    # Device-loss recovery over the lambda grid (same contract as the
    # GAME driver's RESUME marker, but lambda-granular: every finished
    # lambda's host-side result is persisted, so the rerun replays them
    # and resumes the warm-start chain at the first unfinished lambda).
    is_lead = (not distributed) or jax.process_index() == 0
    # Unified marker lifecycle (parallel/resilience.ResumeManager): atomic
    # writes, kept until the grid completes, and a validation-input
    # fingerprint — restored per-lambda metrics were computed on the
    # crashed run's validation dataset, so a rerun pointed at different
    # --validation-data must refuse resume instead of mixing metrics from
    # two datasets when selecting the best lambda.
    resume = resilience.ResumeManager(
        os.path.join(args.output_dir, "RESUME_GLM.npz"),
        fingerprint={
            "train_data": sorted(args.train_data),
            "validation_data": (sorted(args.validation_data)
                                if args.validation_data else None),
            "validation_rows": (None if validation is None
                                else int(vlabels.shape[0])),
            # a resumed path must re-screen the tail exactly as the
            # crashed run would have: refuse to resume across a change
            # of screening rule
            "path_screen": args.path_screen,
        },
        is_lead=is_lead)
    resume_path = resume.path
    if args.auto_resume and resume.exists():
        from types import SimpleNamespace

        # driver-specific compatibility checks run FIRST (their error
        # messages name the actual mismatch); the input fingerprint is
        # verified after, below
        saved = resume.load(verify=False)
        saved_lams = [e["lam"] for e in saved["entries"]]
        if saved_lams != list(args.reg_weights[: len(saved_lams)]):
            raise ValueError(
                f"RESUME_GLM.npz holds lambdas {saved_lams} which are not a "
                f"prefix of --reg-weights {list(args.reg_weights)}; refusing "
                "to mix grids — rerun with the original grid or delete the "
                "marker")
        if validation is not None and evaluators and any(
                evaluators[0] not in (e["metrics"] or {})
                for e in saved["entries"]):
            raise ValueError(
                "RESUME_GLM.npz entries lack the current evaluator "
                f"{evaluators[0]!r} (the crashed run had different "
                "validation settings); rerun with the original settings or "
                "delete the marker")
        resume.verify(saved)  # refuse changed train/validation inputs
        for e in saved["entries"]:
            res_like = SimpleNamespace(**e["res"])
            res_like.w = jnp.asarray(res_like.w, dtype)
            results.append((e["lam"], res_like, e["metrics"], e["variances"]))
        w = jnp.asarray(saved["last_w"], dtype)
        # the marker is consumed only after the grid COMPLETES (below): a
        # second failure of any kind must not discard the progress
        logger.log("auto_resume", completed_lambdas=len(results))

    def _persist_resume(err):
        entries = [{
            "lam": lam,
            "res": {"w": np.asarray(res.w),  # native dtype: a resumed
                    # f64 run must reproduce the uninterrupted one
                    "value": float(res.value),
                    "grad_norm": float(res.grad_norm),
                    "iterations": int(res.iterations),
                    "converged": bool(res.converged),
                    "solver_tolerance": getattr(res, "solver_tolerance",
                                                None),
                    "screened_dim": getattr(res, "screened_dim", None),
                    "loss_history": np.asarray(res.loss_history)},
            "metrics": metrics_,
            "variances": (None if variances_ is None
                          else np.asarray(variances_)),
        } for lam, res, metrics_, variances_ in results]
        resume.save({
            "entries": entries,
            "last_w": (np.asarray(results[-1][1].w)
                       if results else np.zeros((dim,))),
            "error": str(err).split("\n")[0],
        })

    # the per-dataset column sort behind the csc gradient paths is paid
    # once for the whole lambda grid, not per fit
    grid_csc = None
    if not streaming:
        from photon_ml_tpu.parallel.data_parallel import (
            build_csc, resolve_sparse_grad,
        )

        if resolve_sparse_grad("auto",
                               batch.features).startswith("csc"):
            grid_csc = build_csc(objective, batch, mesh)

    path_solver = None
    if args.path_screen != "off":
        from photon_ml_tpu.optimize import PathConfig, PathSolver

        pcfg = PathConfig(screen=args.path_screen,
                          kkt_tol=args.path_kkt_tol,
                          max_kkt_rounds=args.path_max_kkt_rounds,
                          min_bucket=args.path_min_bucket)
        if streaming:
            # out-of-core: the restricted passes stream the SAME chunk
            # sequence (the PR-4 chunk cache underneath makes the whole
            # path one decode of the data)
            path_solver = PathSolver(
                objective, reg, chunks=chunks, dim=dim, mesh=stream_mesh,
                optimizer=optimizer, config=opt_config, path_config=pcfg,
                dtype=dtype, prefetch_depth=args.prefetch_depth)
        else:
            path_solver = PathSolver(
                objective, reg, batch=batch, mesh=mesh,
                optimizer=optimizer, config=opt_config, path_config=pcfg,
                dtype=dtype, precomputed_csc=grid_csc)
        # lambda-granular resume: replayed solutions seed warm/screening
        # states (gradients recomputed lazily), so the resumed tail's
        # candidate sets match the uninterrupted run's
        for lam_done, res_done, _m, _v in results:
            path_solver.seed_state(lam_done, np.asarray(res_done.w))

    try:
        with Timed(logger, "training"), profile_trace(args.profile_dir):
            start_idx = len(results)
            for li, lam in enumerate(args.reg_weights[start_idx:],
                                     start=start_idx):
                # per-lambda injection point: kill-and-rerun tests drive
                # the device-loss resume path through here without
                # monkeypatching the fit internals
                fault_injection.check("glm.lambda")
                run_config = opt_config
                if args.solver_tol_schedule is not None:
                    import dataclasses as _dc

                    run_config = _dc.replace(
                        opt_config,
                        tolerance=args.solver_tol_schedule.at(
                            li, args.tolerance))
                path_stats_box = [None]

                def _fit_lambda(lam=lam, run_config=run_config):
                    if path_solver is not None:
                        res_, pstats = path_solver.solve(
                            lam, tolerance=run_config.tolerance)
                        path_stats_box[0] = pstats
                        return res_
                    if streaming:
                        from photon_ml_tpu.parallel.streaming import (
                            fit_streaming,
                        )

                        # distributed: chunks hold this process's span only
                        # and the partials allgather-reduce across
                        # processes; chunk sharding uses the process-LOCAL
                        # mesh so per-process partials stay local sums
                        # while all local chips work each pass
                        return fit_streaming(
                            objective, chunks, dim, w0=w,
                            l2=reg.l2_weight(lam), l1=reg.l1_weight(lam),
                            optimizer=optimizer, config=run_config,
                            dtype=dtype, mesh=stream_mesh,
                            prefetch_depth=args.prefetch_depth,
                        )
                    return fit_distributed(
                        objective, batch, mesh, w,
                        l2=reg.l2_weight(lam), l1=reg.l1_weight(lam),
                        optimizer=optimizer, config=run_config,
                        precomputed_csc=grid_csc,
                    )

                if args.max_rank_failures > 0:
                    # bounded collective rollback-retry: a transient
                    # coordinated abort (every rank alive) re-runs this
                    # lambda from the same warm start instead of killing
                    # the whole grid; anything else propagates to the
                    # device-loss/resume handling below
                    from photon_ml_tpu.parallel.recovery import (
                        retry_collective,
                    )

                    res = retry_collective(
                        _fit_lambda, max_retries=args.max_rank_failures,
                        tag=f"glm.lambda_retry:{li}")
                else:
                    res = _fit_lambda()
                # every fit records the tolerance it solved to and the
                # width it solved over (full dim when unscreened), so the
                # lambda log and resume marker always carry both
                if res.solver_tolerance is None:
                    res = res._replace(
                        solver_tolerance=float(run_config.tolerance))
                if res.screened_dim is None:
                    res = res._replace(screened_dim=int(dim))
                w = res.w  # warm start the next lambda
                diag = {
                    "reg_weight": lam,
                    "solver_tolerance": float(res.solver_tolerance),
                    "screened_dim": int(res.screened_dim),
                    "loss": float(res.value),
                    "grad_norm": float(res.grad_norm),
                    "iterations": int(res.iterations),
                    "converged": bool(res.converged),
                    "loss_history": [
                        float(v) for v in np.asarray(res.loss_history)
                        if np.isfinite(v)
                    ],
                }
                if res.stream_stats is not None:
                    # streamed fits: decode-wait / transfer / compute-stall
                    # seconds for this lambda's whole pass sequence
                    diag["stream"] = res.stream_stats
                if path_stats_box[0] is not None:
                    diag["path"] = path_stats_box[0].as_dict()
                metrics = {}
                if validation_batch is not None and evaluators:
                    scores = np.asarray(objective.margins(res.w, validation_batch))
                    for name in evaluators:
                        metrics[name] = get_evaluator(name).evaluate(
                            scores, vlabels, vweights
                        )
                    diag["metrics"] = metrics
                variances = None
                if args.compute_variances:
                    if streaming:
                        from photon_ml_tpu.parallel.streaming import (
                            streaming_coefficient_variances,
                        )

                        variances = streaming_coefficient_variances(
                            objective, chunks, dim, res.w,
                            l2=reg.l2_weight(lam), dtype=dtype, mesh=stream_mesh,
                            prefetch_depth=args.prefetch_depth,
                        )
                    else:
                        variances = objective.coefficient_variances(
                            res.w, batch, reg.l2_weight(lam)
                        )
                results.append((lam, res, metrics, variances))
                logger.log("lambda_trained", **diag)

    except Exception as e:
        if not is_device_loss(e):
            raise
        _persist_resume(e)
        logger.log("device_lost", error=str(e).split("\n")[0],
                   completed_lambdas=len(results))
        logger.close()
        print(f"device lost; {len(results)} finished lambdas persisted to "
              f"{resume_path} (rerun with --auto-resume)", file=sys.stderr)
        return 75

    try:
        # -- stage: validate + select best ---------------------------------------
        best_i = 0
        if validation is not None and evaluators:
            ev = get_evaluator(evaluators[0])
            for i in range(1, len(results)):
                if ev.better(results[i][2][evaluators[0]],
                             results[best_i][2][evaluators[0]]):
                    best_i = i

        if args.diagnostics:
            from photon_ml_tpu import diagnostics as diag

            lam_best, res_best, _, _ = results[best_i]
            report = {"reg_weight": lam_best}
            inverse = index_map.inverse()
            summary_std = None
            if norm_type != NormalizationType.NONE or args.summarize_features:
                summary_std = np.zeros(dim)
                summary_std[:summary.dim] = summary.std
            imp = diag.feature_importance(np.asarray(res_best.w), summary_std,
                                          top_k=50)
            report["feature_importance"] = [
                {"feature": inverse.get(int(i), str(int(i))),
                 "score": float(s)}
                for i, s in zip(imp["index"], imp["score"])
            ]
            if validation_batch is not None and task in ("logistic",
                                                         "smoothed_hinge"):
                probs = np.asarray(
                    objective.loss.mean(
                        objective.margins(res_best.w, validation_batch)
                    )
                )
                report["hosmer_lemeshow"] = diag.hosmer_lemeshow(probs, vlabels)
            if args.bootstrap_replicates > 0 and not streaming:
                with Timed(logger, "bootstrap"):
                    boot = diag.bootstrap_coefficients(
                        objective, batch, res_best.w,
                        l2=reg.l2_weight(lam_best),
                        n_replicates=args.bootstrap_replicates,
                    )
                report["bootstrap"] = {
                    "replicates": args.bootstrap_replicates,
                    "std": boot["std"].tolist(),
                    "lower": boot["lower"].tolist(),
                    "upper": boot["upper"].tolist(),
                }
            with open(os.path.join(args.output_dir, "diagnostics.json"), "w") as f:
                json.dump(report, f, indent=2)
            logger.log("diagnostics_written",
                       hosmer_lemeshow=report.get("hosmer_lemeshow"))

        # -- stage: diagnostics + model output ------------------------------------
        with Timed(logger, "save_models"):
            for i, (lam, res, metrics, variances) in enumerate(results):
                model = GameModel(
                    {"global": FixedEffectModel(
                        GeneralizedLinearModel(
                            Coefficients(res.w, variances), task=task
                        )
                    )},
                    task=task,
                )
                out = os.path.join(
                    args.output_dir,
                    "best" if i == best_i else os.path.join("all", f"lambda-{lam:g}"),
                )
                save_game_model(model, out, index_map)
                if i == best_i and len(results) > 1:
                    save_game_model(
                        model, os.path.join(args.output_dir, "all", f"lambda-{lam:g}"),
                        index_map,
                    )
    except Exception as e:
        if not is_device_loss(e):
            raise
        _persist_resume(e)
        logger.log("device_lost", error=str(e).split("\n")[0],
                   completed_lambdas=len(results), stage="post_grid")
        logger.close()
        print(f"device lost after the grid; progress persisted to "
              f"{resume_path} (rerun with --auto-resume)", file=sys.stderr)
        return 75

    # outputs are published: ANY completed grid consumes a marker so a
    # later --auto-resume cannot replay stale results
    resume.consume()
    logger.log("driver_done", best_reg_weight=results[best_i][0],
               best_metrics=results[best_i][2] or None)
    logger.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
