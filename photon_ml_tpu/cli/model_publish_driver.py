"""Model lifecycle driver: publish / gate / promote / roll back / GC.

``photon-model-publish`` is the operator's seam between training output
directories and the serving registry (docs/lifecycle.md):

    # bootstrap: first full publish, promoted immediately
    photon-model-publish --registry /models/r --model-dir out/best --set-latest

    # incremental retrain: publish only the changed bytes, then earn
    # LATEST on a held-out shard (exit 3 when the gate refuses)
    photon-model-publish --registry /models/r --model-dir out2/best \
        --delta --gate-data data/holdout.avro --evaluators auc \
        --tolerance 0.005

    # operations
    photon-model-publish --registry /models/r --list
    photon-model-publish --registry /models/r --rollback-to v000002
    photon-model-publish --registry /models/r --gc-keep 5

Exit codes: 0 ok; 2 usage/validation error; 3 the gate REFUSED the
candidate (published but not promoted — LATEST unchanged).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Sequence

__all__ = ["build_arg_parser", "main"]


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        description="GAME model registry: publish / gate / promote")
    p.add_argument("--registry", required=True,
                   help="registry root directory (created on first publish)")
    p.add_argument("--model-dir", default=None,
                   help="saved model directory to publish")
    p.add_argument("--delta", action="store_true",
                   help="publish only the records that changed against "
                        "the parent (default parent: the live version)")
    p.add_argument("--parent", default=None,
                   help="explicit parent version for --delta")
    p.add_argument("--metrics", default=None,
                   help="JSON (inline or path) of training metrics to "
                        "record in the manifest")
    p.add_argument("--gate-data", nargs="+", default=None,
                   help="held-out labeled Avro shard(s): run the "
                        "promotion gate after publishing (or against "
                        "--candidate) and promote only on pass")
    p.add_argument("--candidate", default=None,
                   help="gate an ALREADY-published version instead of "
                        "publishing --model-dir")
    p.add_argument("--evaluators", nargs="*", default=None,
                   help="gate metrics (default: the task's default)")
    p.add_argument("--group-column", default=None)
    p.add_argument("--tolerance", type=float, default=0.0,
                   help="largest acceptable per-metric regression "
                        "(metric units)")
    p.add_argument("--set-latest", action="store_true",
                   help="promote without a gate (bootstrap / operator "
                        "override)")
    p.add_argument("--rollback-to", default=None,
                   help="repoint LATEST at a retained version")
    p.add_argument("--gc-keep", type=int, default=None,
                   help="after everything else: GC all but the newest N "
                        "versions (the live chain is always kept)")
    p.add_argument("--list", action="store_true", dest="list_versions",
                   help="print every version's manifest summary")
    return p


def _load_metrics(spec):
    if not spec:
        return {}
    if os.path.exists(spec):
        with open(spec) as f:
            return json.load(f)
    return json.loads(spec)


def _say(**fields) -> None:
    print(json.dumps(fields), flush=True)


def main(argv: Sequence[str] | None = None) -> int:
    args = build_arg_parser().parse_args(argv)
    from photon_ml_tpu.registry import (
        ModelRegistry,
        RegistryError,
        publish_delta,
        run_gate,
    )

    registry = ModelRegistry(args.registry)
    try:
        if args.list_versions:
            live = registry.read_latest(retries=1)
            for v in registry.list_versions():
                m = registry.manifest(v)
                gate = m.get("gate") or {}
                _say(version=v, live=(v == live), parent=m.get("parent"),
                     delta=bool(m.get("delta")), metrics=m.get("metrics"),
                     gate_passed=gate.get("passed"),
                     promoted=gate.get("promoted"))
            if not registry.list_versions():
                _say(registry=args.registry, versions=0)
            return 0

        if args.rollback_to:
            registry.set_latest(args.rollback_to)
            _say(rolled_back_to=args.rollback_to)
            if args.gc_keep is not None:
                _say(gc_removed=registry.gc(keep=args.gc_keep))
            return 0

        candidate = args.candidate
        if args.model_dir:
            metrics = _load_metrics(args.metrics)
            if args.delta:
                candidate = publish_delta(
                    registry, args.model_dir, parent=args.parent,
                    metrics=metrics)
                summary = registry.manifest(candidate).get("delta_summary")
                _say(published=candidate, delta=True,
                     delta_summary=summary)
            else:
                candidate = registry.publish(
                    args.model_dir, metrics=metrics, parent=args.parent)
                _say(published=candidate, delta=False)
        elif candidate is None and not args.gc_keep and not args.gate_data:
            print("nothing to do: pass --model-dir, --candidate, "
                  "--list, --rollback-to, or --gc-keep", file=sys.stderr)
            return 2

        refused = False
        if args.gate_data:
            if candidate is None:
                print("--gate-data needs --model-dir or --candidate",
                      file=sys.stderr)
                return 2
            verdict = run_gate(
                registry, candidate, args.gate_data,
                evaluators=args.evaluators, tolerance=args.tolerance,
                group_column=args.group_column)
            _say(gate_candidate=candidate, gate_passed=verdict.passed,
                 promoted=verdict.promoted,
                 candidate_metrics=verdict.candidate_metrics,
                 live_metrics=verdict.live_metrics,
                 regressions=verdict.regressions)
            refused = not verdict.passed
        elif candidate is not None and args.set_latest:
            registry.set_latest(candidate)
            _say(promoted=candidate, gate="skipped (--set-latest)")

        if args.gc_keep is not None:
            _say(gc_removed=registry.gc(keep=args.gc_keep))
        live = registry.read_latest(retries=1)
        _say(latest=live)
        return 3 if refused else 0
    except (RegistryError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    raise SystemExit(main())
