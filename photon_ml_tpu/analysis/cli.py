"""``photon-check`` CLI.

Exit codes (distinct so CI can tell failure classes apart):
  0  clean (no unsuppressed findings / all fault sites covered)
  1  lint findings not covered by baseline or pragma
  2  fault-site audit failure (--fault-sites)
  3  baseline problems: malformed, unjustified, or stale entries

Usage:
  photon-check [paths...]              lint (default: photon_ml_tpu/)
  photon-check --fault-sites           fault-injection coverage audit
  photon-check --write-baseline        accept current findings (each
                                       entry still needs a justification
                                       filled in before CI accepts it)
  photon-check --json                  machine-readable report
  photon-check --numerics              PN5xx bit-determinism passes only
  photon-check --list-passes           finding-code catalogue
  photon-check --lock-graph            dump the inferred lock
                                       acquisition-order graph as DOT
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional, Sequence

from photon_ml_tpu.analysis import __version__
from photon_ml_tpu.analysis.core import (
    PASS_CATALOG,
    BaselineError,
    load_baseline,
    run_check,
)
from photon_ml_tpu.analysis.fault_sites import audit_fault_sites

__all__ = ["main", "build_arg_parser"]


def _default_repo_root() -> str:
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="photon-check",
        description="SPMD collective-alignment, recompile-hazard and "
                    "event-loop-blocking lint for photon_ml_tpu")
    p.add_argument("paths", nargs="*",
                   help="files/dirs to lint (default: the photon_ml_tpu "
                        "package next to this install)")
    p.add_argument("--repo-root", default=None,
                   help="root for repo-relative paths (default: inferred)")
    p.add_argument("--baseline", default=None,
                   help="baseline JSON (default: "
                        "<repo-root>/photon-check-baseline.json when "
                        "present)")
    p.add_argument("--write-baseline", action="store_true",
                   help="write every current finding to the baseline "
                        "file with an empty justification to fill in")
    p.add_argument("--fault-sites", action="store_true",
                   help="audit fault-injection site coverage against "
                        "the tests/ tree instead of linting")
    p.add_argument("--tests-dir", default=None,
                   help="tests root for --fault-sites (default: "
                        "<repo-root>/tests)")
    p.add_argument("--passes", default=None,
                   help="comma list: collectives,recompile,blocking,"
                        "concurrency,numerics")
    p.add_argument("--numerics", action="store_true", dest="numerics",
                   help="run only the PN5xx bit-determinism passes "
                        "(shorthand for --passes numerics)")
    p.add_argument("--lock-graph", action="store_true", dest="lock_graph",
                   help="print the static lock acquisition-order graph "
                        "(PT402's model) as DOT instead of linting")
    p.add_argument("--json", action="store_true", dest="as_json")
    p.add_argument("--list-passes", action="store_true")
    p.add_argument("--version", action="version",
                   version=f"photon-check {__version__}")
    return p


def _lint(args, repo_root: str) -> int:
    paths = args.paths or [os.path.join(repo_root, "photon_ml_tpu")]
    baseline_path = args.baseline or os.path.join(
        repo_root, "photon-check-baseline.json")
    baseline = []
    if not args.write_baseline and os.path.exists(baseline_path):
        try:
            baseline = load_baseline(baseline_path)
        except BaselineError as e:
            print(f"photon-check: {e}", file=sys.stderr)
            return 3
    passes = (args.passes.split(",") if args.passes else None)
    if args.numerics:
        passes = sorted(set(passes or []) | {"numerics"})
    report = run_check(paths, baseline=baseline, repo_root=repo_root,
                       passes=passes)
    findings = report["findings"]

    if args.write_baseline:
        entries = [{
            "code": f.code, "path": f.path, "snippet": f.snippet,
            "justification": "",
        } for f in findings]
        with open(baseline_path, "w") as fh:
            json.dump({"entries": entries}, fh, indent=2)
            fh.write("\n")
        print(f"wrote {len(entries)} entries to {baseline_path} — fill "
              "in every justification before CI will accept it")
        return 0

    if args.as_json:
        print(json.dumps({
            "version": __version__,
            "files_checked": report["files_checked"],
            "findings": [f.as_dict() for f in findings],
            "suppressed": [
                {"via": via, **f.as_dict()}
                for f, via in report["suppressed"]],
            "stale_baseline": [
                {"code": e.code, "path": e.path, "snippet": e.snippet}
                for e in report["stale_baseline"]],
        }, indent=2))
    else:
        for f in findings:
            print(f.render())
        for e in report["stale_baseline"]:
            print(f"stale baseline entry (matches nothing): {e.code} "
                  f"{e.path} :: {e.snippet!r}")
        print(f"photon-check {__version__}: {report['files_checked']} "
              f"files, {len(findings)} finding"
              f"{'s' if len(findings) != 1 else ''}, "
              f"{len(report['suppressed'])} suppressed")
    if findings:
        return 1
    if report["stale_baseline"]:
        return 3
    return 0


def _lock_graph(args, repo_root: str) -> int:
    from photon_ml_tpu.analysis.concurrency import lock_graph_dot
    from photon_ml_tpu.analysis.core import iter_python_files, parse_module

    paths = args.paths or [os.path.join(repo_root, "photon_ml_tpu")]
    modules = []
    for path in iter_python_files(paths):
        tree, lines = parse_module(path)
        if tree is None:
            continue
        rel = os.path.relpath(path, repo_root).replace(os.sep, "/")
        modules.append((path, rel, tree, lines))
    print(lock_graph_dot(modules))
    return 0


def _fault_audit(args, repo_root: str) -> int:
    pkg = (args.paths[0] if args.paths
           else os.path.join(repo_root, "photon_ml_tpu"))
    tests = args.tests_dir or os.path.join(repo_root, "tests")
    audit = audit_fault_sites(pkg, tests)
    if args.as_json:
        print(json.dumps({
            "registered": {s: list(loc)
                           for s, loc in audit.registered.items()},
            "exercised": sorted(audit.exercised),
            "uncovered": audit.uncovered,
        }, indent=2))
    else:
        print(audit.render())
    return 0 if audit.ok else 2


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = build_arg_parser().parse_args(argv)
    if args.list_passes:
        for code in sorted(PASS_CATALOG):
            desc, hint = PASS_CATALOG[code]
            print(f"{code}  {desc}\n       fix: {hint}")
        return 0
    repo_root = args.repo_root or _default_repo_root()
    if args.lock_graph:
        return _lock_graph(args, repo_root)
    if args.fault_sites:
        return _fault_audit(args, repo_root)
    return _lint(args, repo_root)


if __name__ == "__main__":
    raise SystemExit(main())
