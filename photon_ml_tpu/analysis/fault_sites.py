"""Fault-site coverage audit (``photon-check --fault-sites``).

``parallel/fault_injection.py`` plants named sites in the hot paths so
every failure path the resilience layer promises to handle is
EXERCISABLE. That promise decays silently: a new site with no test is
dead code until the first real outage. The audit closes the loop:

* **registered sites** — every string literal passed to
  ``fault_injection.check("...")`` / ``fault_injection.async_check``
  / ``fault_injection.mangle_payload("...", ...)`` in the package
  (AST scan, so dynamically-composed site names do not count — keep
  site names literal);
* **exercised sites** — every registered site name appearing as a
  string literal anywhere under ``tests/`` (covers direct
  ``Fault(site=...)`` construction, parametrize tables, and env-plan
  JSON alike);
* any registered-but-never-exercised site fails the audit, listing the
  site and where it is planted.

Sites that appear only in tests (test-local harness sites like
``work.step``) are ignored — the audit covers the production surface.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Set, Tuple

from photon_ml_tpu.analysis.core import iter_python_files, parse_module

__all__ = ["FaultSiteAudit", "audit_fault_sites", "registered_sites",
           "exercised_sites"]

_INJECTION_FUNCS = {"check", "async_check", "mangle_payload"}


def registered_sites(package_root: str) -> Dict[str, Tuple[str, int]]:
    """site name -> (path, line) of its first injection point."""
    out: Dict[str, Tuple[str, int]] = {}
    for path in iter_python_files([package_root]):
        tree, _lines = parse_module(path)
        if tree is None:
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            func = node.func
            if not (isinstance(func, ast.Attribute)
                    and func.attr in _INJECTION_FUNCS
                    and isinstance(func.value, ast.Name)
                    and func.value.id == "fault_injection"):
                continue
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                out.setdefault(arg.value, (path, node.lineno))
    return out


def exercised_sites(tests_root: str, known: Set[str]) -> Set[str]:
    """Registered site names referenced as string literals in tests."""
    seen: Set[str] = set()
    for path in iter_python_files([tests_root]):
        tree, _lines = parse_module(path)
        if tree is None:
            continue
        for node in ast.walk(tree):
            if (isinstance(node, ast.Constant)
                    and isinstance(node.value, str)
                    and node.value in known):
                seen.add(node.value)
    return seen


@dataclasses.dataclass(frozen=True)
class FaultSiteAudit:
    registered: Dict[str, Tuple[str, int]]
    exercised: Set[str]

    @property
    def uncovered(self) -> List[str]:
        return sorted(set(self.registered) - self.exercised)

    @property
    def ok(self) -> bool:
        return not self.uncovered

    def render(self) -> str:
        lines = [f"fault-injection sites: {len(self.registered)} "
                 f"registered, {len(self.exercised)} exercised by tests"]
        for site in sorted(self.registered):
            path, lineno = self.registered[site]
            mark = "ok " if site in self.exercised else "MISSING"
            lines.append(f"  [{mark}] {site}  ({path}:{lineno})")
        if self.uncovered:
            lines.append(
                "uncovered sites have NO tier-1 test arming a Fault at "
                "them — the failure path they guard is unexercised")
        return "\n".join(lines)


def audit_fault_sites(package_root: str, tests_root: str) -> FaultSiteAudit:
    reg = registered_sites(package_root)
    return FaultSiteAudit(registered=reg,
                          exercised=exercised_sites(tests_root, set(reg)))
