"""Collective-alignment lint (PC101 / PC102).

The PR-1 contract: every cross-process collective is a guarded boundary
— a peer that failed since the last barrier must surface as
:class:`~photon_ml_tpu.parallel.resilience.PeerFailure` *before* this
process can wedge inside the next gather. Two ways to break it:

* **PC101** — a collective call site with no dominating guard: not
  inside a ``with CollectiveGuard(...)`` block, not in a
  ``guarded(...)``-wrapped function, and with no ``health_barrier``
  earlier in the same function. A peer that died since the last
  boundary wedges this gather for the full transport timeout.
* **PC102** — a collective (including a health barrier: a
  rank-conditioned barrier is the classic SPMD hang) inside control
  flow conditioned on process-local state — rank/shard index, a
  filesystem probe, queue depth, local frontier size. Processes take
  different branches, collective sequences diverge, and the runtime
  pairs up mismatched collectives (silent corruption) or deadlocks.
  Branches are accepted when both arms issue the same collective (the
  shape-aligned-branches escape hatch).

Domination is checked lexically per function: a barrier in a *caller*
does not clear a raw gather in a *callee* — transport primitives whose
guards genuinely live one frame up are exactly what the baseline file
(with per-entry justification) is for.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from photon_ml_tpu.analysis.core import (
    PASS_CATALOG,
    Finding,
    ancestors,
    call_name,
    enclosing_function,
    snippet_at,
)

__all__ = ["check_modules", "RAW_COLLECTIVES", "GUARDED_HELPERS",
           "SELF_GUARDED"]

# Transport-level primitives: one un-aligned call deadlocks the fleet.
RAW_COLLECTIVES = {
    "process_allgather",   # jax.experimental.multihost_utils
    "allgather_status",    # resilience transport leg
    "allgather_payload",   # simulated-transport data leg
    "sync_global_devices", "broadcast_one_to_all",  # multihost_utils kin
}

# Repo helpers that wrap a raw gather but do NOT barrier internally:
# call sites need a dominating guard just like the raw primitives.
GUARDED_HELPERS = {
    "allgather_blobs",            # parallel/entity_shard.py
    "allgather_spans",            # parallel/multihost.py
    "allgather_varspans",
    "allreduce_summary_moments",
    "_cross_process_sum",         # parallel/streaming.py
}

# Helpers that run their own pre-gather health barrier (the
# entity_shard._guarded_gather family): exempt from PC101, still
# checked for divergent branches (PC102).
SELF_GUARDED = {
    "exchange_score_updates",
    "allgather_objects",
    "_guarded_gather",
}

BARRIERS = {"health_barrier"}
GUARD_CONSTRUCTORS = {"CollectiveGuard", "guarded"}

# Names/attributes that read process-LOCAL state. process_count() is
# deliberately absent: it is uniform across the job, and `if
# process_count() > 1` is the standard single-process fast path.
PROCESS_LOCAL_NAMES = {
    "process_index", "process_id", "rank", "shard_index", "is_lead",
    "owned_mask", "local_rank", "frontier", "queue_depth",
}
PROCESS_LOCAL_CALLS = {
    "exists",     # filesystem probes diverge across hosts / in time
    "qsize", "is_alive", "poll", "owned_mask", "process_index",
}


def _collective_category(node: ast.Call) -> Optional[str]:
    name = call_name(node)
    if name in RAW_COLLECTIVES or name in GUARDED_HELPERS:
        return "gather"
    if name in SELF_GUARDED:
        return "self_guarded"
    if name in BARRIERS:
        return "barrier"
    return None


def _is_guard_with(node: ast.With) -> bool:
    return any(isinstance(item.context_expr, ast.Call)
               and call_name(item.context_expr) in GUARD_CONSTRUCTORS
               for item in node.items)


def _function_is_guarded(fn) -> bool:
    return any(isinstance(dec, ast.Call)
               and call_name(dec) in GUARD_CONSTRUCTORS
               for dec in fn.decorator_list)


def _barrier_lines(fn) -> List[int]:
    """Lines inside ``fn`` (excluding nested defs) where a health
    barrier runs or a CollectiveGuard block opens."""
    out: List[int] = []

    def visit(node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            if isinstance(child, ast.Call) and call_name(child) in BARRIERS:
                out.append(child.lineno)
            if isinstance(child, ast.With) and _is_guard_with(child):
                out.append(child.lineno)
            visit(child)

    visit(fn)
    return out


def _divergence_marker(test: ast.AST) -> Optional[str]:
    """The first process-local marker inside a branch condition, or
    None when the condition looks process-uniform."""
    for node in ast.walk(test):
        if isinstance(node, ast.Call):
            name = call_name(node)
            if name in PROCESS_LOCAL_CALLS:
                return f"{name}()"
        elif isinstance(node, ast.Attribute):
            if node.attr in PROCESS_LOCAL_NAMES:
                return node.attr
        elif isinstance(node, ast.Name):
            if node.id in PROCESS_LOCAL_NAMES:
                return node.id
    return None


def _branch_has_collective(body, name: str) -> bool:
    for stmt in body:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call) and call_name(node) == name:
                return True
    return False


def _finding(code: str, rel: str, lines, node: ast.Call, message: str
             ) -> Finding:
    return Finding(code=code, path=rel, line=node.lineno, message=message,
                   hint=PASS_CATALOG[code][1],
                   snippet=snippet_at(lines, node.lineno))


def check_modules(modules) -> List[Finding]:
    findings: List[Finding] = []
    for _path, rel, tree, lines in modules:
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            category = _collective_category(node)
            if category is None:
                continue
            name = call_name(node)
            if category == "gather":
                findings.extend(_check_pc101(rel, lines, node, name))
            findings.extend(_check_pc102(rel, lines, node, name))
    return findings


def _check_pc101(rel, lines, node: ast.Call, name: str) -> List[Finding]:
    fn = enclosing_function(node)
    dominated = False
    for anc in ancestors(node):
        if isinstance(anc, ast.With) and _is_guard_with(anc):
            dominated = True
            break
        if anc is fn:
            break
    if not dominated and fn is not None:
        if _function_is_guarded(fn):
            dominated = True
        elif any(line < node.lineno for line in _barrier_lines(fn)):
            # approximate dominance: a barrier earlier in this function.
            dominated = True
    if dominated:
        return []
    return [_finding(
        "PC101", rel, lines, node,
        f"collective '{name}' is not dominated by a health-barrier "
        "guard: a peer that failed since the last boundary wedges this "
        "gather instead of raising PeerFailure")]


def _check_pc102(rel, lines, node: ast.Call, name: str) -> List[Finding]:
    fn = enclosing_function(node)
    for anc in ancestors(node):
        if fn is not None and anc is fn:
            break
        if isinstance(anc, (ast.If, ast.While)):
            marker = _divergence_marker(anc.test)
            if marker is None:
                continue
            if (isinstance(anc, ast.If) and anc.orelse
                    and _branch_has_collective(anc.body, name)
                    and _branch_has_collective(anc.orelse, name)):
                continue  # both arms issue the collective: shape-aligned
            return [_finding(
                "PC102", rel, lines, node,
                f"collective '{name}' runs inside a branch conditioned "
                f"on process-local state ('{marker}'): processes that "
                "take the other branch never reach it and the job's "
                "collective sequences diverge")]
        elif isinstance(anc, ast.IfExp):
            marker = _divergence_marker(anc.test)
            if marker is not None:
                return [_finding(
                    "PC102", rel, lines, node,
                    f"collective '{name}' inside a conditional "
                    f"expression on process-local state ('{marker}')")]
    return []
