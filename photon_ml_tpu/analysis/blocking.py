"""Blocking-call-on-event-loop lint (PB301-PB303).

The asyncio serving front end (``serve/aserver.py``) parses requests ON
the loop and resolves scores through batcher callbacks — one blocked
coroutine stalls every connection. Three ways the loop gets blocked:

* **PB301** — a known-blocking primitive called directly in an ``async
  def``: file IO (``open``/``json.load``/``np.load``), ``time.sleep``,
  ``os``/``shutil``/``subprocess``, synchronous HTTP, device syncs
  (``.block_until_ready()``), registry reads (``read_latest`` /
  ``open_version`` / ``materialize``) — anything that parks the loop on
  a syscall or a device fence.
* **PB302** — the same primitives one hop away: an ``async def`` calls
  a *sync* function (resolved by name within the scanned serving
  modules) whose body transitively blocks. Depth-limited propagation —
  the point is catching ``handler -> service method -> disk read``.
* **PB303** — an opaque callable *parameter* invoked synchronously in
  async context. The lint cannot see the implementations, but the repo
  precedent is exactly why it flags them: the serving driver's ready
  callbacks write JSONL logs.

Calls dispatched through ``loop.run_in_executor(...)`` /
``asyncio.to_thread(...)`` are exempt — that is the fix the hints
prescribe.

Scope: ``serve/`` plus the serving driver (``cli/serving_driver.py``) —
the "aserver-adjacent" set. Thread-based code (the watcher, the
threaded server) blocks legitimately and is only scanned for the
*async* entry points it exposes.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from photon_ml_tpu.analysis.core import (
    PASS_CATALOG,
    Finding,
    ancestors,
    call_name,
    dotted_name,
    snippet_at,
)

__all__ = ["check_modules", "DEFAULT_SCOPE"]

DEFAULT_SCOPE = (
    "photon_ml_tpu/serve/",
    "photon_ml_tpu/cli/serving_driver.py",
)

# (base module, attr) pairs; attr "*" = every attribute of that module.
_BLOCKING_QUALIFIED = {
    ("time", "sleep"),
    ("os", "replace"), ("os", "remove"), ("os", "rename"),
    ("os", "listdir"), ("os", "stat"), ("os", "makedirs"),
    ("os", "rmdir"), ("os", "unlink"), ("os", "fsync"), ("os", "open"),
    ("path", "exists"), ("path", "getsize"), ("path", "getmtime"),
    ("shutil", "*"), ("subprocess", "*"),
    ("json", "load"),  # json.loads is CPU-only and fine
    ("np", "load"), ("np", "save"), ("np", "savez"),
    ("numpy", "load"), ("numpy", "save"), ("numpy", "savez"),
    ("request", "urlopen"), ("urllib", "urlopen"),
    ("socket", "create_connection"),
}

# Attribute names that block regardless of the receiver.
_BLOCKING_ATTRS = {
    "block_until_ready",          # device fence
    "read_latest", "open_version", "materialize",  # registry disk reads
    "read_avro_file", "write_avro_file",
    "serve_forever", "shutdown",  # http.server handshakes
}

_BLOCKING_BARE = {"open", "urlopen", "sleep"}

_EXECUTOR_DISPATCH = {"run_in_executor", "to_thread"}

_PROPAGATION_DEPTH = 3


def _is_blocking_primitive(node: ast.Call) -> Optional[str]:
    func = node.func
    if isinstance(func, ast.Name):
        if func.id in _BLOCKING_BARE:
            return func.id
        return None
    if isinstance(func, ast.Attribute):
        attr = func.attr
        if attr in _BLOCKING_ATTRS:
            return dotted_name(node) or attr
        base = func.value
        base_name = ""
        if isinstance(base, ast.Name):
            base_name = base.id
        elif isinstance(base, ast.Attribute):
            base_name = base.attr
        if (base_name, attr) in _BLOCKING_QUALIFIED:
            return f"{base_name}.{attr}"
        if (base_name, "*") in _BLOCKING_QUALIFIED:
            return f"{base_name}.{attr}"
    return None


def _inside_executor_dispatch(node: ast.AST) -> bool:
    """True when the node sits inside the ARGUMENTS of a
    run_in_executor/to_thread call (being shipped off the loop), either
    as the callable or inside a lambda passed there."""
    for anc in ancestors(node):
        if isinstance(anc, ast.Call) \
                and call_name(anc) in _EXECUTOR_DISPATCH:
            return True
    return False


def _enclosing_async(node: ast.AST):
    """The nearest enclosing function if it is async, else None. A sync
    def nested inside an async def is NOT on the loop (it may be a
    worker callback), so the nearest function decides."""
    for anc in ancestors(node):
        if isinstance(anc, ast.AsyncFunctionDef):
            return anc
        if isinstance(anc, (ast.FunctionDef, ast.Lambda)):
            return None
    return None


def _param_names(node: ast.AST) -> Set[str]:
    """Parameter names visible at ``node`` from every enclosing function
    (closures included: a callback param of a sync wrapper invoked
    inside its nested async main() is the repo's actual shape)."""
    out: Set[str] = set()
    for anc in ancestors(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            a = anc.args
            out.update(p.arg for p in a.posonlyargs + a.args + a.kwonlyargs)
            if a.vararg:
                out.add(a.vararg.arg)
            if a.kwarg:
                out.add(a.kwarg.arg)
    return out


def _finding(code, rel, lines, lineno, message) -> Finding:
    return Finding(code=code, path=rel, line=lineno, message=message,
                   hint=PASS_CATALOG[code][1],
                   snippet=snippet_at(lines, lineno))


def _collect_sync_defs(modules) -> Dict[str, ast.FunctionDef]:
    """name -> def across the scanned set (methods keyed by bare name;
    collisions keep the first — good enough for a lint hop)."""
    out: Dict[str, ast.FunctionDef] = {}
    for _path, _rel, tree, _lines in modules:
        for node in ast.walk(tree):
            if isinstance(node, ast.FunctionDef):
                out.setdefault(node.name, node)
    return out


def _blocking_reason(fn: ast.FunctionDef, defs, depth: int,
                     seen: Set[str]) -> Optional[str]:
    """Why ``fn`` blocks (a primitive name or a call chain), or None."""
    if depth <= 0 or fn.name in seen:
        return None
    seen = seen | {fn.name}
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        prim = _is_blocking_primitive(node)
        if prim is not None and not _inside_executor_dispatch(node):
            return prim
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        callee = defs.get(call_name(node))
        if callee is None or callee is fn:
            continue
        reason = _blocking_reason(callee, defs, depth - 1, seen)
        if reason is not None:
            return f"{callee.name}() -> {reason}"
    return None


def check_modules(modules, *, scope: Optional[Sequence[str]] = None
                  ) -> List[Finding]:
    scopes = tuple(DEFAULT_SCOPE if scope is None else scope)
    scan_all = "*" in scopes
    in_scope = [m for m in modules
                if scan_all or any(s in m[1] for s in scopes)]
    if not in_scope:
        return []
    defs = _collect_sync_defs(in_scope)
    findings: List[Finding] = []
    for _path, rel, tree, lines in in_scope:
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            if _enclosing_async(node) is None:
                continue
            if _inside_executor_dispatch(node):
                continue
            prim = _is_blocking_primitive(node)
            if prim is not None:
                findings.append(_finding(
                    "PB301", rel, lines, node.lineno,
                    f"blocking call '{prim}' runs on the asyncio event "
                    "loop: every connection stalls behind it"))
                continue
            name = call_name(node)
            callee = defs.get(name)
            if callee is not None:
                reason = _blocking_reason(callee, defs,
                                          _PROPAGATION_DEPTH, set())
                if reason is not None:
                    findings.append(_finding(
                        "PB302", rel, lines, node.lineno,
                        f"'{name}()' called on the event loop blocks "
                        f"via {reason}"))
                    continue
            if (isinstance(node.func, ast.Name)
                    and node.func.id in _param_names(node)
                    and not isinstance(getattr(node, "_pcheck_parent",
                                               None), ast.Await)):
                findings.append(_finding(
                    "PB303", rel, lines, node.lineno,
                    f"opaque callable parameter '{node.func.id}' invoked "
                    "synchronously on the event loop: implementations "
                    "may do file IO (the serving driver's ready "
                    "callbacks write JSONL logs)"))
    return findings
