"""Runtime sanitizers: collective-trace alignment + flat-compile checks.

Static analysis catches the lexical shapes of SPMD divergence; these
two sanitizers catch the *dynamic* ones, in tier-1, with zero
dependence on jax (pure stdlib — importable from the lint CLI).

**CollectiveTraceSanitizer** — a race detector for multi-controller
code. The simulated harness (``testing.run_simulated_processes``)
records every collective each simulated process issues through its
``ThreadTransport`` — ``(op, site, payload descriptor)`` in program
order — and verifies the sequences at join: under fail-stop SPMD,
every process's trace must be a *prefix* of the longest trace (a
process that died early stops participating; it must never have issued
a DIFFERENT collective). A rank-conditioned extra allgather, a
reordered barrier, or a payload-kind mismatch surfaces as
:class:`CollectiveTraceMismatch` naming the step, the site(s), and the
diverging ranks — instead of a silent generation-pairing corruption or
a watchdog timeout with no attribution.

Payload *values* are deliberately not compared: shards legitimately
publish different row sets, and a health barrier's whole purpose is
letting ranks report different status codes. Alignment is about the
SEQUENCE — op kind + site label + payload kind.

**CompileSanitizer** — one implementation of the "compile counter must
stay flat" assertion that serving/CD tests previously each hand-rolled.
Wrap the steady-state block; any counter movement beyond ``max_new``
raises :class:`CompileSanitizerError` with the counter label and the
moment it moved (``check()`` gives mid-block anchors, e.g. per sweep).
"""

from __future__ import annotations

from typing import Callable, List, Mapping, Optional, Sequence, Tuple

__all__ = [
    "CollectiveTraceMismatch", "CollectiveTraceSanitizer",
    "CompileSanitizer", "CompileSanitizerError", "describe_payload",
]

# One trace event: (op, site, payload descriptor), e.g.
# ("status", "entity_shard.exchange:cd:0:per-user", "i32") or
# ("payload", "cd:0:per-user", "bytes").
TraceEvent = Tuple[str, str, str]


class CollectiveTraceMismatch(AssertionError):
    """Simulated processes issued diverging collective sequences."""


class CompileSanitizerError(AssertionError):
    """A compile counter moved inside a block that must stay flat."""


def describe_payload(payload) -> str:
    """Stable payload-kind descriptor for trace events: enough to catch
    a payload/status mix-up or a dtype drift, without comparing values
    (which legitimately differ per rank)."""
    if payload is None:
        return "none"
    if isinstance(payload, (bytes, bytearray, memoryview)):
        return "bytes"
    if isinstance(payload, int):
        return "i32"
    dtype = getattr(payload, "dtype", None)
    if dtype is not None:
        return f"{dtype}[{getattr(payload, 'ndim', '?')}d]"
    return type(payload).__name__


class CollectiveTraceSanitizer:
    """Verifier over per-rank collective event sequences.

    Normally driven by ``run_simulated_processes`` (on by default);
    usable standalone against any ``{rank: [TraceEvent, ...]}``."""

    @staticmethod
    def verify(traces: Mapping[int, Sequence[TraceEvent]],
               *, context: str = "", strict_sites: bool = True) -> None:
        """Raise :class:`CollectiveTraceMismatch` unless every rank's
        trace is a prefix of the longest trace.

        ``strict_sites=False`` relaxes SITE comparison for *status*
        (barrier) events, comparing only op + payload kind. This is the
        failure-path mode: a CollectiveGuard that catches a local
        exception reports it through a barrier that deliberately pairs
        with whatever barrier the healthy peers reach next — the tags
        differ by design, and only the op/kind stream must still align.
        Clean runs keep ``strict_sites=True`` so two processes sitting
        in different *phases* (same op shape, different site) are
        caught."""
        if not traces:
            return
        ranks = sorted(traces)
        ref_rank = max(ranks, key=lambda r: (len(traces[r]), -r))
        ref = list(traces[ref_rank])

        def mismatch(a: TraceEvent, b: TraceEvent) -> bool:
            if a == b:
                return False
            op_a, site_a, desc_a = a
            op_b, site_b, desc_b = b
            if op_a != op_b or desc_a != desc_b:
                return True
            return strict_sites and site_a != site_b

        for rank in ranks:
            if rank == ref_rank:
                continue
            seq = list(traces[rank])
            for step, event in enumerate(seq):
                if mismatch(event, ref[step]):
                    raise CollectiveTraceMismatch(
                        CollectiveTraceSanitizer._explain(
                            step, ref_rank, ref[step], rank, event,
                            context))

    @staticmethod
    def _explain(step: int, ref_rank: int, ref_event: TraceEvent,
                 rank: int, event: TraceEvent, context: str) -> str:
        op_a, site_a, desc_a = event
        op_b, site_b, desc_b = ref_event
        where = f" [{context}]" if context else ""
        return (
            f"collective sequence mismatch at step {step}{where}: "
            f"process {rank} issued {op_a} at site '{site_a}' "
            f"(payload {desc_a}) while process {ref_rank} issued "
            f"{op_b} at site '{site_b}' (payload {desc_b}); the ranks' "
            "collective streams have diverged — under the real "
            "multi-controller runtime these calls would pair up and "
            "exchange garbage or deadlock")


class CompileSanitizer:
    """Assert compile counters stay flat across a block.

    Counters are callables returning an int, or objects exposing a
    ``compile_count`` attribute (``ScoringSession``)::

        with CompileSanitizer(session, label="serving ladder") as san:
            for _ in range(110):
                session.score_rows(...)
            san.check("steady state")      # optional mid-block anchor

        with CompileSanitizer(re_solver_compile_count, max_new=0):
            cd.run(dataset)

    ``max_new`` admits a known number of fresh executables (e.g. a lazy
    first-touch) while still bounding the block.
    """

    def __init__(self, *counters, max_new: int = 0, label: str = ""):
        if not counters:
            raise ValueError("CompileSanitizer needs at least one counter")
        self._fns: List[Tuple[str, Callable[[], int]]] = []
        for c in counters:
            self._fns.append(self._resolve(c))
        self.max_new = int(max_new)
        self.label = label
        self._start: Optional[List[int]] = None

    @staticmethod
    def _resolve(c) -> Tuple[str, Callable[[], int]]:
        if callable(c):
            name = getattr(c, "__name__", type(c).__name__)
            return (name, lambda: int(c()))
        if hasattr(c, "compile_count"):
            name = f"{type(c).__name__}.compile_count"
            return (name, lambda: int(c.compile_count))
        raise TypeError(
            f"counter must be callable or expose .compile_count, got "
            f"{type(c).__name__}")

    def __enter__(self) -> "CompileSanitizer":
        self._start = [fn() for _name, fn in self._fns]
        return self

    @property
    def new_compiles(self) -> int:
        if self._start is None:
            raise RuntimeError("CompileSanitizer used outside its block")
        return sum(fn() - s
                   for (_n, fn), s in zip(self._fns, self._start))

    def check(self, moment: str = "") -> None:
        """Assert flatness right now (mid-block anchor: per sweep, per
        swap, per request wave)."""
        assert self._start is not None, "check() before __enter__"
        for (name, fn), start in zip(self._fns, self._start):
            now = fn()
            if now - start > self.max_new:
                at = f" at {moment}" if moment else ""
                tag = f" [{self.label}]" if self.label else ""
                raise CompileSanitizerError(
                    f"compile counter '{name}'{tag} moved "
                    f"{start} -> {now}{at} (allowed new: "
                    f"{self.max_new}): the hot path recompiled")

    def __exit__(self, exc_type, exc, tb) -> bool:
        if exc_type is None:
            self.check("block exit")
        return False
