"""Runtime sanitizers: collective traces, compiles, locks, threads.

Static analysis catches the lexical shapes of SPMD divergence; these
two sanitizers catch the *dynamic* ones, in tier-1, with zero
dependence on jax (pure stdlib — importable from the lint CLI).

**CollectiveTraceSanitizer** — a race detector for multi-controller
code. The simulated harness (``testing.run_simulated_processes``)
records every collective each simulated process issues through its
``ThreadTransport`` — ``(op, site, payload descriptor)`` in program
order — and verifies the sequences at join: under fail-stop SPMD,
every process's trace must be a *prefix* of the longest trace (a
process that died early stops participating; it must never have issued
a DIFFERENT collective). A rank-conditioned extra allgather, a
reordered barrier, or a payload-kind mismatch surfaces as
:class:`CollectiveTraceMismatch` naming the step, the site(s), and the
diverging ranks — instead of a silent generation-pairing corruption or
a watchdog timeout with no attribution.

Payload *values* are deliberately not compared: shards legitimately
publish different row sets, and a health barrier's whole purpose is
letting ranks report different status codes. Alignment is about the
SEQUENCE — op kind + site label + payload kind.

**CompileSanitizer** — one implementation of the "compile counter must
stay flat" assertion that serving/CD tests previously each hand-rolled.
Wrap the steady-state block; any counter movement beyond ``max_new``
raises :class:`CompileSanitizerError` with the counter label and the
moment it moved (``check()`` gives mid-block anchors, e.g. per sweep).

**LockOrderSanitizer** — deadlock detection without deadlocking. While
active, ``threading.Lock``/``threading.RLock`` construction from
photon code (stdlib- and site-packages-created locks — ``queue.Queue``
internals, ``Condition`` inner locks, jax — stay raw) returns an
instrumented wrapper that maintains each thread's held-set and a global
acquisition-order graph. A blocking acquire that would close a cycle in
that graph — thread A holds X wanting Y while the graph already records
Y held wanting X — raises :class:`LockOrderViolation` carrying BOTH
acquisition stacks (the current one and the recorded opposing edge's),
at the moment the inversion is *attempted*, whether or not the schedule
would have deadlocked this run. Edges are recorded at blocking-acquire
*intent* only; nonblocking probes (``acquire(False)``, Condition's
``_is_owned``) are check-free so they can never fabricate an ordering.

**DeterminismSanitizer** — the runtime twin of the PN5xx numerics
lint. Code marks pure, parity-bearing blocks (payload packing, delta
computation, gather reassembly, sweep resyncs) with
``deterministic_replay(label, fn, *args)`` — a zero-cost passthrough
normally. While a sanitizer is armed (the simulated harness arms one
by default, ``verify_determinism=``), each marked block runs twice
and a bitwise difference raises :class:`DeterminismViolation` naming
the label and the first differing array index / byte offset —
iteration-order and hidden-state bugs caught at the block that leaks
them, not as a cryptic end-to-end parity failure.

**NaNGuard** — an opt-in NaN/Inf trap at solver-kernel host
boundaries. The jitted kernels are single fused ``lax.while_loop``s,
so the guard scans concrete outputs where they land on the host
(``guard.wrap(fn, site=...)`` or the ``nan_guard_check`` hook inside
an armed ``with NaNGuard():`` block) and raises
:class:`NaNGuardError` naming the producing site and the first
non-finite index.

**ThreadLeakSanitizer** — a context manager asserting no NEW live
photon-named thread (``photon-*``, ``avro-chunk-producer``,
``stream-transfer``, ``sim-process-*``) outlives the block, after a
bounded grace poll. The runtime companion to the PT403 lint: a
shutdown path that forgets a bounded join fails the test that drove
it, with the leaked threads named.

Both are wired into ``run_simulated_processes`` (opt-out, like
``verify_collectives``); the serving/streaming suites use them
directly.
"""

from __future__ import annotations

import os
import sys
import threading
import time
import traceback
from typing import (
    Callable,
    Dict,
    List,
    Mapping,
    Optional,
    Sequence,
    Tuple,
)

__all__ = [
    "CollectiveTraceMismatch", "CollectiveTraceSanitizer",
    "CompileSanitizer", "CompileSanitizerError", "describe_payload",
    "DeterminismSanitizer", "DeterminismViolation",
    "deterministic_replay", "LockOrderSanitizer", "LockOrderViolation",
    "NaNGuard", "NaNGuardError", "nan_guard_check",
    "ThreadLeakSanitizer", "ThreadLeakError", "PHOTON_THREAD_PREFIXES",
]

# One trace event: (op, site, payload descriptor), e.g.
# ("status", "entity_shard.exchange:cd:0:per-user", "i32") or
# ("payload", "cd:0:per-user", "bytes").
TraceEvent = Tuple[str, str, str]


class CollectiveTraceMismatch(AssertionError):
    """Simulated processes issued diverging collective sequences."""


class CompileSanitizerError(AssertionError):
    """A compile counter moved inside a block that must stay flat."""


def describe_payload(payload) -> str:
    """Stable payload-kind descriptor for trace events: enough to catch
    a payload/status mix-up or a dtype drift, without comparing values
    (which legitimately differ per rank)."""
    if payload is None:
        return "none"
    if isinstance(payload, (bytes, bytearray, memoryview)):
        return "bytes"
    if isinstance(payload, int):
        return "i32"
    dtype = getattr(payload, "dtype", None)
    if dtype is not None:
        return f"{dtype}[{getattr(payload, 'ndim', '?')}d]"
    return type(payload).__name__


class CollectiveTraceSanitizer:
    """Verifier over per-rank collective event sequences.

    Normally driven by ``run_simulated_processes`` (on by default);
    usable standalone against any ``{rank: [TraceEvent, ...]}``."""

    @staticmethod
    def verify(traces: Mapping[int, Sequence[TraceEvent]],
               *, context: str = "", strict_sites: bool = True) -> None:
        """Raise :class:`CollectiveTraceMismatch` unless every rank's
        trace is a prefix of the longest trace.

        ``strict_sites=False`` relaxes SITE comparison for *status*
        (barrier) events, comparing only op + payload kind. This is the
        failure-path mode: a CollectiveGuard that catches a local
        exception reports it through a barrier that deliberately pairs
        with whatever barrier the healthy peers reach next — the tags
        differ by design, and only the op/kind stream must still align.
        Clean runs keep ``strict_sites=True`` so two processes sitting
        in different *phases* (same op shape, different site) are
        caught."""
        if not traces:
            return
        ranks = sorted(traces)
        ref_rank = max(ranks, key=lambda r: (len(traces[r]), -r))
        ref = list(traces[ref_rank])

        def mismatch(a: TraceEvent, b: TraceEvent) -> bool:
            if a == b:
                return False
            op_a, site_a, desc_a = a
            op_b, site_b, desc_b = b
            if op_a != op_b or desc_a != desc_b:
                return True
            return strict_sites and site_a != site_b

        for rank in ranks:
            if rank == ref_rank:
                continue
            seq = list(traces[rank])
            for step, event in enumerate(seq):
                if mismatch(event, ref[step]):
                    raise CollectiveTraceMismatch(
                        CollectiveTraceSanitizer._explain(
                            step, ref_rank, ref[step], rank, event,
                            context))

    @staticmethod
    def _explain(step: int, ref_rank: int, ref_event: TraceEvent,
                 rank: int, event: TraceEvent, context: str) -> str:
        op_a, site_a, desc_a = event
        op_b, site_b, desc_b = ref_event
        where = f" [{context}]" if context else ""
        return (
            f"collective sequence mismatch at step {step}{where}: "
            f"process {rank} issued {op_a} at site '{site_a}' "
            f"(payload {desc_a}) while process {ref_rank} issued "
            f"{op_b} at site '{site_b}' (payload {desc_b}); the ranks' "
            "collective streams have diverged — under the real "
            "multi-controller runtime these calls would pair up and "
            "exchange garbage or deadlock")


class CompileSanitizer:
    """Assert compile counters stay flat across a block.

    Counters are callables returning an int, or objects exposing a
    ``compile_count`` attribute (``ScoringSession``)::

        with CompileSanitizer(session, label="serving ladder") as san:
            for _ in range(110):
                session.score_rows(...)
            san.check("steady state")      # optional mid-block anchor

        with CompileSanitizer(re_solver_compile_count, max_new=0):
            cd.run(dataset)

    ``max_new`` admits a known number of fresh executables (e.g. a lazy
    first-touch) while still bounding the block.
    """

    def __init__(self, *counters, max_new: int = 0, label: str = ""):
        if not counters:
            raise ValueError("CompileSanitizer needs at least one counter")
        self._fns: List[Tuple[str, Callable[[], int]]] = []
        for c in counters:
            self._fns.append(self._resolve(c))
        self.max_new = int(max_new)
        self.label = label
        self._start: Optional[List[int]] = None

    @staticmethod
    def _resolve(c) -> Tuple[str, Callable[[], int]]:
        if callable(c):
            name = getattr(c, "__name__", type(c).__name__)
            return (name, lambda: int(c()))
        if hasattr(c, "compile_count"):
            name = f"{type(c).__name__}.compile_count"
            return (name, lambda: int(c.compile_count))
        raise TypeError(
            f"counter must be callable or expose .compile_count, got "
            f"{type(c).__name__}")

    def __enter__(self) -> "CompileSanitizer":
        self._start = [fn() for _name, fn in self._fns]
        return self

    @property
    def new_compiles(self) -> int:
        if self._start is None:
            raise RuntimeError("CompileSanitizer used outside its block")
        return sum(fn() - s
                   for (_n, fn), s in zip(self._fns, self._start))

    def check(self, moment: str = "") -> None:
        """Assert flatness right now (mid-block anchor: per sweep, per
        swap, per request wave)."""
        assert self._start is not None, "check() before __enter__"
        for (name, fn), start in zip(self._fns, self._start):
            now = fn()
            if now - start > self.max_new:
                at = f" at {moment}" if moment else ""
                tag = f" [{self.label}]" if self.label else ""
                raise CompileSanitizerError(
                    f"compile counter '{name}'{tag} moved "
                    f"{start} -> {now}{at} (allowed new: "
                    f"{self.max_new}): the hot path recompiled")

    def __exit__(self, exc_type, exc, tb) -> bool:
        if exc_type is None:
            self.check("block exit")
        return False


# -- lock-order sanitizer ---------------------------------------------------
class LockOrderViolation(AssertionError):
    """A blocking acquire attempted a lock order whose reverse is
    already recorded: a deadlock window, caught without deadlocking."""


_STDLIB_DIR = os.path.dirname(threading.__file__)


def _foreign_frame(filename: str) -> bool:
    """Creation frames whose locks stay raw: the stdlib (queue.Queue
    mutexes, Condition inner locks) and installed packages (jax)."""
    return (filename.startswith(_STDLIB_DIR)
            or "site-packages" in filename
            or "dist-packages" in filename
            or filename.startswith("<"))


class _InstrumentedLock:
    """``threading.Lock`` stand-in that reports acquisition intent to
    the owning :class:`LockOrderSanitizer`."""

    _reentrant = False

    def __init__(self, inner, san: "LockOrderSanitizer", name: str):
        self._inner = inner
        self._san = san
        self._name = name

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if blocking:
            # intent BEFORE the (possibly deadlocking) wait: the cycle
            # is reported even on schedules where the wait would hang
            self._san._on_intent(self)
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._san._on_acquired(self)
        return got

    def release(self) -> None:
        self._san._on_release(self)
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.release()
        return False

    def __repr__(self) -> str:
        return f"<sanitized {self._name}>"


class _InstrumentedRLock(_InstrumentedLock):
    """RLock stand-in: reacquisition by the owner records nothing (no
    new ordering), and the ``Condition`` protocol hooks
    (``_is_owned``/``_release_save``/``_acquire_restore``) are
    implemented so a Condition built over an instrumented RLock keeps
    working — with its wait/notify reacquisition instrumented too."""

    _reentrant = True

    def __init__(self, inner, san: "LockOrderSanitizer", name: str):
        super().__init__(inner, san, name)
        self._owner: Optional[int] = None
        self._count = 0

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        me = threading.get_ident()
        if self._owner == me:
            got = self._inner.acquire(blocking, timeout)
            if got:
                self._count += 1
            return got
        if blocking:
            self._san._on_intent(self)
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._owner, self._count = me, 1
            self._san._on_acquired(self)
        return got

    def release(self) -> None:
        if self._owner != threading.get_ident():
            raise RuntimeError("cannot release un-acquired lock")
        self._count -= 1
        if self._count == 0:
            self._owner = None
            self._san._on_release(self)
        self._inner.release()

    # Condition protocol (threading.Condition defers to these when the
    # underlying lock provides them)
    def _is_owned(self) -> bool:
        return self._owner == threading.get_ident()

    def _release_save(self):
        count, self._count = self._count, 0
        self._owner = None
        self._san._on_release(self)
        return (count, self._inner._release_save())

    def _acquire_restore(self, saved) -> None:
        count, inner_state = saved
        self._san._on_intent(self)
        self._inner._acquire_restore(inner_state)
        self._owner, self._count = threading.get_ident(), count
        self._san._on_acquired(self)


class LockOrderSanitizer:
    """Instrument photon-created locks and flag acquisition-order
    cycles with both stacks::

        with LockOrderSanitizer() as san:
            run_threaded_code()        # locks CREATED here are watched
        san.check()                    # deferred mode (the default)

    ``immediate=True`` raises :class:`LockOrderViolation` inside the
    acquiring thread at the moment of the inversion — right for direct
    use; the simulated-process harness uses the deferred default so a
    violation in a worker cannot corrupt the harness's own outcome
    collection, and calls ``check()`` after the join.

    Only locks *constructed* while the sanitizer is active are
    instrumented, so a long-lived singleton lock from before the block
    is invisible — create the objects under test inside the block.
    Patching ``threading.Lock``/``threading.RLock`` is process-global:
    one active sanitizer at a time (enforced)."""

    _active: Optional["LockOrderSanitizer"] = None

    def __init__(self, *, immediate: bool = False):
        self.immediate = immediate
        self.violations: List[str] = []
        # (src_name, dst_name) -> formatted stack at first observation
        self.graph: Dict[Tuple[str, str], str] = {}
        self._meta = threading.Lock()  # raw: guards the graph itself
        self._held = threading.local()
        self._counts: Dict[str, int] = {}
        self._orig_lock = None
        self._orig_rlock = None

    # -- factory patching --------------------------------------------------
    def _name_for(self, site: str) -> str:
        n = self._counts.get(site, 0)
        self._counts[site] = n + 1
        return site if n == 0 else f"{site}#{n + 1}"

    def _make(self, cls, orig_factory):
        san = self

        def factory():
            frame = sys._getframe(1)
            filename = frame.f_code.co_filename
            if _foreign_frame(filename):
                return orig_factory()
            site = f"{os.path.basename(filename)}:{frame.f_lineno}"
            with san._meta:
                name = san._name_for(site)
            return cls(orig_factory(), san, name)

        return factory

    def __enter__(self) -> "LockOrderSanitizer":
        if LockOrderSanitizer._active is not None:
            raise RuntimeError("a LockOrderSanitizer is already active "
                               "(the threading patch is process-global)")
        LockOrderSanitizer._active = self
        self._orig_lock = threading.Lock
        self._orig_rlock = threading.RLock
        threading.Lock = self._make(_InstrumentedLock, self._orig_lock)
        threading.RLock = self._make(_InstrumentedRLock,
                                     self._orig_rlock)
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        threading.Lock = self._orig_lock
        threading.RLock = self._orig_rlock
        LockOrderSanitizer._active = None
        return False

    # -- acquisition bookkeeping -------------------------------------------
    def _held_stack(self) -> List[_InstrumentedLock]:
        stack = getattr(self._held, "stack", None)
        if stack is None:
            stack = self._held.stack = []
        return stack

    def _on_intent(self, lock: _InstrumentedLock) -> None:
        held = self._held_stack()
        if not held:
            return
        here = "".join(traceback.format_stack(sys._getframe(2)))
        with self._meta:
            for h in held:
                if h is lock:
                    continue
                edge = (h._name, lock._name)
                path = self._path(lock._name, h._name)
                if path is not None:
                    self._violate(edge, path, here)
                self.graph.setdefault(edge, here)

    def _on_acquired(self, lock: _InstrumentedLock) -> None:
        self._held_stack().append(lock)

    def _on_release(self, lock: _InstrumentedLock) -> None:
        held = self._held_stack()
        for i in range(len(held) - 1, -1, -1):
            if held[i] is lock:
                del held[i]
                return

    def _path(self, src: str, dst: str) -> Optional[List[str]]:
        """Lock names from ``src`` to ``dst`` through recorded edges
        (caller holds ``_meta``), or None when unreachable."""
        prev: Dict[str, str] = {}
        stack = [src]
        seen = {src}
        while stack:
            cur = stack.pop()
            if cur == dst:
                names = [dst]
                while names[-1] != src:
                    names.append(prev[names[-1]])
                return list(reversed(names))
            for (a, b) in self.graph:
                if a == cur and b not in seen:
                    seen.add(b)
                    prev[b] = a
                    stack.append(b)
        return None

    def _violate(self, edge: Tuple[str, str], path: List[str],
                 here: str) -> None:
        opposing = self.graph.get((path[0], path[1]), "<unrecorded>")
        chain = " -> ".join(path)
        msg = (
            f"lock-order inversion: acquiring '{edge[1]}' while holding "
            f"'{edge[0]}', but the opposite order {chain} is already "
            "recorded — two threads interleaving these paths deadlock."
            f"\n--- this acquisition ({edge[0]} -> {edge[1]}) ---\n"
            f"{here}"
            f"--- recorded opposing acquisition "
            f"({path[0]} -> {path[1]}) ---\n{opposing}")
        self.violations.append(msg)
        if self.immediate:
            raise LockOrderViolation(msg)

    def check(self) -> None:
        """Raise the first deferred violation (after threads joined)."""
        if self.violations:
            raise LockOrderViolation(self.violations[0])


# -- thread-leak sanitizer --------------------------------------------------
# The stack's thread-name vocabulary (see PT403 in docs/analysis.md):
# every photon-owned thread carries one of these prefixes, so a leak
# check can ignore pytest/jax housekeeping threads.
PHOTON_THREAD_PREFIXES: Tuple[str, ...] = (
    "photon-", "avro-chunk-producer", "stream-transfer", "sim-process-",
)


class ThreadLeakError(AssertionError):
    """Photon-named threads started inside the block outlived it."""


class ThreadLeakSanitizer:
    """Assert no NEW live photon-named thread survives the block::

        with ThreadLeakSanitizer():
            server = build()...
            server.close()

    Exit polls up to ``grace_s`` (threads legitimately take a moment to
    unwind after a bounded join returns) and then raises
    :class:`ThreadLeakError` naming the survivors. An exception already
    propagating out of the block takes precedence — the leak check only
    runs on clean exits."""

    def __init__(self, prefixes: Sequence[str] = PHOTON_THREAD_PREFIXES,
                 grace_s: float = 2.0, poll_s: float = 0.02):
        self.prefixes = tuple(prefixes)
        self.grace_s = float(grace_s)
        self.poll_s = float(poll_s)
        self._before: set = set()

    def _leaked(self) -> List[threading.Thread]:
        # membership by Thread OBJECT, not ident: idents are recycled,
        # and a recycled ident would hide a genuine leak
        return [t for t in threading.enumerate()
                if t.is_alive() and t not in self._before
                and t.name.startswith(self.prefixes)]

    def __enter__(self) -> "ThreadLeakSanitizer":
        self._before = set(threading.enumerate())
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if exc_type is not None:
            return False
        self.check()
        return False

    def check(self) -> None:
        deadline = time.monotonic() + self.grace_s
        leaked = self._leaked()
        while leaked and time.monotonic() < deadline:
            time.sleep(self.poll_s)
            leaked = self._leaked()
        if leaked:
            names = ", ".join(sorted(t.name for t in leaked))
            raise ThreadLeakError(
                f"{len(leaked)} photon thread(s) leaked past the block "
                f"(still alive {self.grace_s:.1f}s after exit): {names} "
                "— a shutdown path is missing its bounded join "
                "(PT403's runtime twin)")


# -- determinism sanitizer --------------------------------------------------
class DeterminismViolation(AssertionError):
    """A registered pure block produced bitwise-different results on
    immediate replay: hidden state (iteration order, wall clock, RNG,
    in-place mutation of an input) is leaking into a value the repo's
    parity contracts treat as a pure function of its inputs."""


def _bitwise_diff(a, b, path: str = "result") -> Optional[str]:
    """First bitwise difference between two replay results as a human
    'where' string, or None when identical. Comparison is BITWISE —
    NaNs with equal payloads compare equal, ``-0.0`` vs ``0.0`` does
    not — because the contract under test is bit-parity, not ==.
    numpy is imported lazily so this module stays stdlib-importable."""
    if isinstance(a, dict) and isinstance(b, dict):
        if sorted(map(repr, a)) != sorted(map(repr, b)):
            return f"{path}: dict keys differ ({sorted(map(repr, a))} " \
                   f"vs {sorted(map(repr, b))})"
        for k in a:
            where = _bitwise_diff(a[k], b[k], f"{path}[{k!r}]")
            if where:
                return where
        return None
    if isinstance(a, (list, tuple)) and isinstance(b, (list, tuple)):
        if len(a) != len(b):
            return f"{path}: length {len(a)} vs {len(b)}"
        for i, (x, y) in enumerate(zip(a, b)):
            where = _bitwise_diff(x, y, f"{path}[{i}]")
            if where:
                return where
        return None
    if isinstance(a, (bytes, bytearray, memoryview)) and isinstance(
            b, (bytes, bytearray, memoryview)):
        ab, bb = bytes(a), bytes(b)
        if ab == bb:
            return None
        if len(ab) != len(bb):
            return f"{path}: {len(ab)} vs {len(bb)} bytes"
        off = next(i for i, (x, y) in enumerate(zip(ab, bb)) if x != y)
        return (f"{path}: bytes first differ at offset {off} "
                f"(0x{ab[off]:02x} vs 0x{bb[off]:02x})")
    if hasattr(a, "dtype") or hasattr(b, "dtype"):  # np/jnp array-like
        import numpy as np

        av, bv = np.asarray(a), np.asarray(b)
        if av.dtype != bv.dtype or av.shape != bv.shape:
            return (f"{path}: array {av.dtype}{av.shape} vs "
                    f"{bv.dtype}{bv.shape}")
        ab, bb = av.tobytes(), bv.tobytes()
        if ab == bb:
            return None
        mask = np.frombuffer(ab, np.uint8) != np.frombuffer(bb, np.uint8)
        byte = int(np.flatnonzero(mask)[0])
        idx = byte // max(av.dtype.itemsize, 1)
        flat_a, flat_b = av.reshape(-1), bv.reshape(-1)
        return (f"{path}: {av.dtype} array of shape {av.shape} first "
                f"differs at flat index {idx} "
                f"({flat_a[idx]!r} vs {flat_b[idx]!r})")
    if isinstance(a, float) and isinstance(b, float):
        import struct

        if struct.pack("<d", a) != struct.pack("<d", b):
            return f"{path}: {a!r} vs {b!r}"
        return None
    if type(a) is not type(b):
        return (f"{path}: type {type(a).__name__} vs "
                f"{type(b).__name__}")
    if a != b:
        return f"{path}: {a!r} vs {b!r}"
    return None


class DeterminismSanitizer:
    """Replay registered pure blocks twice; bitwise-compare the results.

    The repo's parity guarantees (sharded-vs-single-host, recovered-vs-
    uninterrupted, cached-vs-uncached) all assume certain blocks —
    payload packing, delta computation, gather reassembly, sweep-level
    resyncs — are pure functions of their inputs. Code marks those
    blocks with :func:`deterministic_replay`, a zero-cost passthrough
    when no sanitizer is armed. While one is armed (the simulated
    harness arms one by default)::

        with DeterminismSanitizer() as san:
            run_simulated_fit()
        assert san.replays > 0      # the hooks actually fired

    each registered block runs TWICE and a bitwise difference raises
    :class:`DeterminismViolation` naming the block's label and the
    first differing array index / byte offset. Replayed blocks must be
    cheap and genuinely pure: no collectives (the replay would corrupt
    the trace alignment), no mutation of inputs. Arming is
    process-global: one active sanitizer at a time (enforced, like
    :class:`LockOrderSanitizer`)."""

    _active: Optional["DeterminismSanitizer"] = None

    def __init__(self):
        self.replays = 0
        self.labels: Dict[str, int] = {}
        self._lock = threading.Lock()

    def __enter__(self) -> "DeterminismSanitizer":
        if DeterminismSanitizer._active is not None:
            raise RuntimeError("a DeterminismSanitizer is already "
                               "active (arming is process-global)")
        DeterminismSanitizer._active = self
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        DeterminismSanitizer._active = None
        return False

    @classmethod
    def active(cls) -> Optional["DeterminismSanitizer"]:
        return cls._active

    def run(self, label: str, fn: Callable, *args, **kwargs):
        first = fn(*args, **kwargs)
        second = fn(*args, **kwargs)
        with self._lock:
            self.replays += 1
            self.labels[label] = self.labels.get(label, 0) + 1
        where = _bitwise_diff(first, second)
        if where is not None:
            raise DeterminismViolation(
                f"replayed block '{label}' is not deterministic: two "
                f"back-to-back runs over identical inputs diverged at "
                f"{where} — hidden state (iteration order, wall clock, "
                "RNG, input mutation) is leaking into a parity-bearing "
                "value")
        return first


def deterministic_replay(label: str, fn: Callable, *args, **kwargs):
    """Run ``fn(*args, **kwargs)``, replaying it under the active
    :class:`DeterminismSanitizer` when one is armed. The production
    cost is one global read; the marked block must be pure (no
    collectives, no input mutation) so the replay is observable only
    through time."""
    san = DeterminismSanitizer._active
    if san is None:
        return fn(*args, **kwargs)
    return san.run(label, fn, *args, **kwargs)


# -- NaN guard ---------------------------------------------------------------
class NaNGuardError(AssertionError):
    """A guarded kernel let a NaN/Inf escape to the host."""


def _first_nonfinite(value, path: str = "output") -> Optional[str]:
    """First NaN/Inf in a (nested) result, or None. Float leaves only;
    int/bool/str data cannot carry a NaN. numpy imported lazily."""
    if isinstance(value, dict):
        for k in sorted(value, key=repr):
            where = _first_nonfinite(value[k], f"{path}[{k!r}]")
            if where:
                return where
        return None
    if isinstance(value, (list, tuple)):
        for i, v in enumerate(value):
            where = _first_nonfinite(v, f"{path}[{i}]")
            if where:
                return where
        return None
    if isinstance(value, float):
        import math

        if not math.isfinite(value):
            return f"{path}: {value!r}"
        return None
    if hasattr(value, "dtype"):
        import numpy as np

        arr = np.asarray(value)
        if arr.dtype.kind not in ("f", "c"):
            return None
        bad = ~np.isfinite(arr)
        if bad.any():
            idx = int(np.flatnonzero(bad.reshape(-1))[0])
            val = arr.reshape(-1)[idx]
            n_bad = int(bad.sum())
            return (f"{path}: {arr.dtype} array of shape {arr.shape} "
                    f"has {n_bad} non-finite value(s), first at flat "
                    f"index {idx} ({val!r})")
        return None
    return None


class NaNGuard:
    """Opt-in NaN/Inf trap at a solver kernel's host boundary.

    The jitted kernels (one fused ``lax.while_loop`` for L-BFGS) cannot
    host-check mid-iteration without breaking tracing, so the guard
    scans CONCRETE outputs where they land on the host, naming the
    producing site::

        guard = NaNGuard()
        solve = guard.wrap(lbfgs, site="fe_solver:global")
        with guard:
            w, info = solve(fun_and_grad, w0, cfg)   # raises on NaN/Inf

    Kernels that want guarding without threading a wrapper call
    :func:`nan_guard_check` (a no-op unless a guard context is armed —
    the opt-in is the ``with`` block, per run, not per call site).
    Arming is process-global, one guard at a time."""

    _active: Optional["NaNGuard"] = None

    def __init__(self, site: str = ""):
        self.site = site
        self.checks = 0
        self._lock = threading.Lock()

    def __enter__(self) -> "NaNGuard":
        if NaNGuard._active is not None:
            raise RuntimeError(
                "a NaNGuard is already active (arming is process-global)")
        NaNGuard._active = self
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        NaNGuard._active = None
        return False

    @classmethod
    def armed(cls) -> bool:
        return cls._active is not None

    def check_value(self, site: str, value) -> None:
        with self._lock:
            self.checks += 1
        where = _first_nonfinite(value)
        if where is not None:
            raise NaNGuardError(
                f"non-finite value escaped kernel '{site or self.site}' "
                f"at {where} — the solver diverged (step size, "
                "regularization, or input data) and the NaN would "
                "silently poison every downstream reduction")

    def wrap(self, fn: Callable, site: str = "") -> Callable:
        """Guarded version of ``fn``: outputs are scanned on every call
        (with or without an armed context — wrapping IS the opt-in)."""
        label = site or self.site or getattr(fn, "__name__", "kernel")

        def guarded(*args, **kwargs):
            out = fn(*args, **kwargs)
            self.check_value(label, out)
            return out

        return guarded


def nan_guard_check(site: str, value) -> None:
    """Hook for kernels that guard their own host boundary: no-op (one
    global read) unless a :class:`NaNGuard` context is armed."""
    guard = NaNGuard._active
    if guard is not None:
        guard.check_value(site, value)
