"""Recompile-hazard lint (PH201-PH204).

The serving/CD hot paths keep compile counts flat by construction:
every jit-wrapped executable is either module-level, memoized behind
``functools.lru_cache`` (the RE solver registry), or stored in an
explicit shape-keyed compile cache (``ScoringSession._compiled``), and
every varying dimension is routed through the power-of-two bucket/pad
helpers so the set of distinct shapes is O(log max). This pass flags
the ways that discipline gets broken:

* **PH201** — ``jax.jit`` constructed inside a hot-path function body
  with no memoization: a fresh executable per call.
* **PH202** — ``.item()`` / ``int()`` / ``float()`` applied to a traced
  parameter inside a jit target: forces a host sync and turns a traced
  value into a Python scalar the next trace depends on.
* **PH203** — a call to a jitted executable whose operand takes its
  shape from raw ``len()`` / ``.shape`` instead of the registered
  bucket/pad helpers: every distinct input size becomes a compile.
* **PH204** — a list/dict/set literal passed at a ``static_argnums`` /
  ``static_argnames`` position: unhashable, so the jit cache cannot
  even key it.

Scope: PH201/PH203 run only over the registered hot-path modules
(descent sweeps, RE solver, serving score path, streamed passes) —
cold-path jit construction (e.g. a one-off driver) is fine. PH202/204
run everywhere a jit target is visible.
"""

from __future__ import annotations

import ast
import re
from typing import List, Optional, Sequence, Set

from photon_ml_tpu.analysis.core import (
    PASS_CATALOG,
    Finding,
    ancestors,
    call_name,
    dotted_name,
    enclosing_function,
    snippet_at,
)

__all__ = ["check_modules", "DEFAULT_HOT_PATHS", "SHAPE_HELPERS"]

# Repo-relative hot-path modules: jit churn here is a per-sweep /
# per-request recompile storm, not a one-off.
DEFAULT_HOT_PATHS = (
    "photon_ml_tpu/game/random_effect.py",
    "photon_ml_tpu/game/descent.py",
    "photon_ml_tpu/game/scoring.py",
    "photon_ml_tpu/serve/session.py",
    "photon_ml_tpu/serve/paged_table.py",
    "photon_ml_tpu/parallel/streaming.py",
    "photon_ml_tpu/parallel/data_parallel.py",
    "photon_ml_tpu/optimize/path.py",
    "photon_ml_tpu/evaluation/device.py",
)

# The registered power-of-two bucket/pad helpers: a shape that flows
# through one of these stays on the compiled ladder.
SHAPE_HELPERS = {
    "bucketize", "bucket_ladder", "_active_width", "_pad_entities",
    "pad_to_bucket", "next_power_of_two", "round_up_to_multiple",
}

_JIT_CONSTRUCTORS = {"jax.jit", "jit"}
_CACHED_WRAPPERS = {"cached_jit"}  # repo's shape-keyed jit wrapper
_MEMO_DECORATORS = {"lru_cache", "cache"}
_CACHE_NAME_RE = re.compile(r"cache|compil", re.IGNORECASE)


def _is_jit_call(node: ast.Call) -> bool:
    dn = dotted_name(node)
    return dn in _JIT_CONSTRUCTORS or dn.endswith(".jit")


def _decorated_with_jit(fn) -> bool:
    for dec in fn.decorator_list:
        dn = dotted_name(dec if not isinstance(dec, ast.Call) else dec)
        if dn in _JIT_CONSTRUCTORS or dn.endswith(".jit"):
            return True
        if isinstance(dec, ast.Call):
            inner = dotted_name(dec)
            if inner in _JIT_CONSTRUCTORS or inner.endswith(".jit"):
                return True
            # functools.partial(jax.jit, ...)
            if call_name(dec) == "partial" and any(
                    isinstance(a, (ast.Attribute, ast.Name))
                    and (dotted_name(a) in _JIT_CONSTRUCTORS
                         or dotted_name(a).endswith(".jit"))
                    for a in dec.args):
                return True
    return False


def _memoized(fn) -> bool:
    return any(call_name(d) in _MEMO_DECORATORS
               or dotted_name(d if not isinstance(d, ast.Call) else d)
               .split(".")[-1] in _MEMO_DECORATORS
               for d in fn.decorator_list)


def _stored_in_compile_cache(bound_name: str, fn) -> bool:
    """``self._compiled[key] = run`` (or any cache/compile-named
    subscript) inside the same function marks the construction as
    explicitly memoized."""
    if not bound_name:
        return False
    for node in ast.walk(fn):
        if not isinstance(node, ast.Assign):
            continue
        for tgt in node.targets:
            if (isinstance(tgt, ast.Subscript)
                    and isinstance(node.value, ast.Name)
                    and node.value.id == bound_name
                    and _CACHE_NAME_RE.search(call_name(tgt.value) or "")):
                return True
    return False


def _finding(code, rel, lines, lineno, message) -> Finding:
    return Finding(code=code, path=rel, line=lineno, message=message,
                   hint=PASS_CATALOG[code][1],
                   snippet=snippet_at(lines, lineno))


# -- jit-target discovery ---------------------------------------------------
def _jit_target_defs(tree) -> Set[ast.AST]:
    """FunctionDefs whose body will be traced: decorated with jit, or
    passed by name to jax.jit/cached_jit/shard_map in this module."""
    defs_by_name = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs_by_name.setdefault(node.name, node)
    targets: Set[ast.AST] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if _decorated_with_jit(node):
                targets.add(node)
        elif isinstance(node, ast.Call):
            name = call_name(node)
            if (_is_jit_call(node) or name in _CACHED_WRAPPERS
                    or name == "shard_map"):
                for arg in node.args[:1]:
                    if isinstance(arg, ast.Name) and arg.id in defs_by_name:
                        targets.add(defs_by_name[arg.id])
    return targets


def _jitted_callee_names(tree) -> Set[str]:
    """Names bound to jitted executables in this module: assignment
    targets of jax.jit(...)/cached_jit(...), plus the ``*_jit`` /
    ``_jitted*`` naming convention."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            if (_is_jit_call(node.value)
                    or call_name(node.value) in _CACHED_WRAPPERS):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        out.add(tgt.id)
    return out


def _under_shape_helper(node: ast.AST, stop: ast.AST) -> bool:
    for anc in ancestors(node):
        if anc is stop:
            return False
        if isinstance(anc, ast.Call) and call_name(anc) in SHAPE_HELPERS:
            return True
    return False


# -- the pass ---------------------------------------------------------------
def check_modules(modules, *, hot_paths: Optional[Sequence[str]] = None
                  ) -> List[Finding]:
    hot = set(DEFAULT_HOT_PATHS if hot_paths is None else hot_paths)
    scan_all = "*" in hot
    findings: List[Finding] = []
    for _path, rel, tree, lines in modules:
        is_hot = scan_all or rel in hot or any(rel.endswith(h) for h in hot)
        jit_targets = _jit_target_defs(tree)
        jitted_names = _jitted_callee_names(tree)
        if is_hot:
            findings += _check_ph201(rel, lines, tree)
            findings += _check_ph203(rel, lines, tree, jitted_names)
        findings += _check_ph202(rel, lines, jit_targets)
        findings += _check_ph204(rel, lines, tree)
    return findings


def _check_ph201(rel, lines, tree) -> List[Finding]:
    out: List[Finding] = []
    for node in ast.walk(tree):
        bound = ""
        site = None
        if isinstance(node, ast.Call) and _is_jit_call(node):
            site = node
            par = ancestors(node).__iter__()
            p = next(par, None)
            if isinstance(p, ast.Assign):
                tgt = p.targets[0]
                if isinstance(tgt, ast.Name):
                    bound = tgt.id
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and _decorated_with_jit(node):
            site = node
            bound = node.name
        if site is None:
            continue
        fn = enclosing_function(site)
        if fn is None or (isinstance(site, ast.FunctionDef)
                          and fn is site):
            continue  # module-level jit: compiled once
        chain = [fn] + [a for a in ancestors(fn)
                        if isinstance(a, (ast.FunctionDef,
                                          ast.AsyncFunctionDef))]
        if any(_memoized(f) for f in chain):
            continue
        if any(_stored_in_compile_cache(bound, f) for f in chain):
            continue
        out.append(_finding(
            "PH201", rel, lines, site.lineno,
            f"jit wrapper constructed inside '{fn.name}' with no "
            "memoization: every call compiles a fresh executable"))
    return out


_COERCERS = {"int", "float", "bool"}


def _check_ph202(rel, lines, jit_targets) -> List[Finding]:
    out: List[Finding] = []
    for fn in jit_targets:
        params = {a.arg for a in (fn.args.posonlyargs + fn.args.args
                                  + fn.args.kwonlyargs)}
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr == "item" and not node.args):
                out.append(_finding(
                    "PH202", rel, lines, node.lineno,
                    f"traced value concretized with .item() inside jit "
                    f"target '{fn.name}'"))
                continue
            if (isinstance(node.func, ast.Name)
                    and node.func.id in _COERCERS and node.args):
                touches_param = any(
                    isinstance(n, ast.Name) and n.id in params
                    for n in ast.walk(node.args[0]))
                if touches_param:
                    out.append(_finding(
                        "PH202", rel, lines, node.lineno,
                        f"{node.func.id}() applied to traced parameter "
                        f"inside jit target '{fn.name}' forces a host "
                        "sync per call"))
    return out


def _check_ph203(rel, lines, tree, jitted_names) -> List[Finding]:
    out: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = call_name(node)
        if not (name in jitted_names or name.endswith("_jit")
                or name.startswith("_jitted")):
            continue
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            for sub in ast.walk(arg):
                raw = None
                if (isinstance(sub, ast.Call)
                        and isinstance(sub.func, ast.Name)
                        and sub.func.id == "len"):
                    raw = "len()"
                elif (isinstance(sub, ast.Attribute)
                        and sub.attr == "shape"):
                    raw = ".shape"
                if raw is None or _under_shape_helper(sub, node):
                    continue
                out.append(_finding(
                    "PH203", rel, lines, node.lineno,
                    f"jitted call '{name}' takes a shape from raw {raw} "
                    "not routed through the bucket/pad helpers: every "
                    "distinct size compiles"))
                break
    return out


def _check_ph204(rel, lines, tree) -> List[Finding]:
    """jit constructions with static args, cross-referenced against
    same-module call sites passing unhashable literals there."""
    out: List[Finding] = []
    static_specs = {}  # wrapper name -> (argnums set, argnames set)
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)
                and _is_jit_call(node.value)):
            continue
        nums, names = set(), set()
        for kw in node.value.keywords:
            if kw.arg == "static_argnums":
                for c in ast.walk(kw.value):
                    if isinstance(c, ast.Constant) and isinstance(c.value,
                                                                  int):
                        nums.add(c.value)
            elif kw.arg == "static_argnames":
                for c in ast.walk(kw.value):
                    if isinstance(c, ast.Constant) and isinstance(c.value,
                                                                  str):
                        names.add(c.value)
        if (nums or names) and isinstance(node.targets[0], ast.Name):
            static_specs[node.targets[0].id] = (nums, names)
    if not static_specs:
        return out
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        spec = static_specs.get(call_name(node))
        if spec is None:
            continue
        nums, names = spec
        for i, arg in enumerate(node.args):
            if i in nums and isinstance(arg, (ast.List, ast.Dict, ast.Set)):
                out.append(_finding(
                    "PH204", rel, lines, node.lineno,
                    f"unhashable {type(arg).__name__.lower()} literal at "
                    f"static_argnums position {i}"))
        for kw in node.keywords:
            if kw.arg in names and isinstance(kw.value,
                                              (ast.List, ast.Dict, ast.Set)):
                out.append(_finding(
                    "PH204", rel, lines, node.lineno,
                    f"unhashable {type(kw.value).__name__.lower()} "
                    f"literal for static_argnames '{kw.arg}'"))
    return out
