"""photon-check engine: findings, suppression, file walking, pass registry.

Stdlib-only by design (``ast`` + ``json``): the lint must run in CI and
pre-commit without initializing jax or touching a device, and a pass
over the whole package must take well under a second.

Suppression has two layers, both requiring a human-written reason:

* **Inline pragma** — ``# photon-check: allow[PC101] reason`` on the
  finding's line or the line directly above. An empty reason does not
  suppress (the reason IS the review artifact).
* **Baseline file** — ``photon-check-baseline.json``: a list of entries
  keyed by ``(code, path, snippet)`` where ``snippet`` is the stripped
  source line, so entries survive unrelated line drift. Every entry
  must carry a non-empty ``justification`` that is not a TODO; entries
  matching nothing are reported as stale so the baseline can only
  shrink.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = [
    "Finding", "BaselineEntry", "BaselineError", "PASS_CATALOG",
    "attach_parents", "call_name", "dotted_name", "iter_python_files",
    "load_baseline", "parse_module", "run_check",
]

# code -> (one-line description, fix hint) — the pass catalogue rendered
# by ``photon-check --list-passes`` and docs/analysis.md.
PASS_CATALOG: Dict[str, Tuple[str, str]] = {
    "PC101": (
        "collective call not dominated by a health-barrier guard",
        "wrap the phase in resilience.CollectiveGuard(tag) or call "
        "health_barrier(tag) before the gather (parallel/resilience.py)",
    ),
    "PC102": (
        "collective inside control flow conditioned on process-local "
        "state (SPMD divergence: peers hang in their next collective)",
        "hoist the collective out of the branch, or make every branch "
        "issue the same shape-aligned collective sequence",
    ),
    "PH201": (
        "jit wrapper constructed inside a hot-path function body "
        "(a fresh executable per call: recompile storm)",
        "hoist the jit to module scope, memoize with functools.lru_cache, "
        "or store it in a compile cache keyed by shape",
    ),
    "PH202": (
        "traced-value concretization inside a jit target "
        "(.item()/int()/float() forces a device sync + shape dependence)",
        "keep the value traced (jnp.where / lax.cond) or pass it as a "
        "host-computed static operand",
    ),
    "PH203": (
        "hot-path jit call takes a shape from raw len()/.shape instead "
        "of the registered power-of-two bucket/pad helpers",
        "route the width through bucketize()/bucket_ladder()/"
        "_active_width()/_pad_entities() so shapes stay on the ladder",
    ),
    "PH204": (
        "unhashable Python object passed at a jit static-arg position",
        "pass a hashable scalar/tuple, or drop static_argnums and let "
        "the value be traced",
    ),
    "PB301": (
        "blocking call on the asyncio event loop",
        "dispatch through loop.run_in_executor(...) / asyncio.to_thread "
        "so the loop keeps serving while it runs",
    ),
    "PB302": (
        "event-loop call into a sync function that transitively blocks",
        "move the blocking callee into an executor, or make the "
        "offending leaf async",
    ),
    "PB303": (
        "opaque callable parameter invoked synchronously on the event "
        "loop (implementations may do file IO)",
        "invoke callbacks via loop.run_in_executor(None, cb, ...) unless "
        "the callback is documented non-blocking",
    ),
    "PT401": (
        "instance attribute written on a thread-target path and accessed "
        "elsewhere in the class without a common owning lock",
        "put both sides under the same `with self._lock`, or make the "
        "attribute a synchronizer (Event/Queue) that owns its state",
    ),
    "PT402": (
        "inconsistent nested lock-acquisition order (the opposite "
        "nesting exists in the static lock graph: deadlock window)",
        "pick one global order and restructure the losing site — or "
        "drop to a single lock; `photon-check --lock-graph` dumps the "
        "inferred acquisition graph as DOT",
    ),
    "PT403": (
        "thread started with no reachable bounded join(timeout)",
        "keep a handle to the thread and join it with a timeout at "
        "shutdown, logging + counting expiry like "
        "producer_join_timeouts does",
    ),
    "PT404": (
        "timeout-less blocking Queue.get()/Condition.wait()/Event.wait() "
        "in a worker loop (a wedged peer hangs the thread forever)",
        "use get(timeout=...)/wait(timeout) in a loop that rechecks a "
        "stop event (and producer liveness) each expiry — fail stop, "
        "never hang",
    ),
    "PT405": (
        "callback invoked while holding a lock (a callback that "
        "re-enters the class self-deadlocks)",
        "snapshot the callback list under the lock, release it, then "
        "fire — the PendingRequest._fire_callbacks pattern",
    ),
    "PN501": (
        "bare float accumulation on a hot numeric path (builtin sum() "
        "over floats or a loop '+=': result depends on operand order)",
        "route through the Kahan helpers in parallel/streaming.py "
        "(_kahan_add/_make_kahan_reduce), math.fsum, or a jnp/np "
        "reduction with pinned operand order",
    ),
    "PN502": (
        "dtype narrowing on an f64 path (astype downcast, "
        "np/jnp.float32 value cast, 32-bit dtype literal at a call "
        "site, or a weak-typed float literal into a jitted kernel)",
        "keep parity-bearing paths f64 end-to-end; thread dtype "
        "through a parameter (function-default dtype knobs are exempt)",
    ),
    "PN503": (
        "nondeterministic iteration order feeding downstream state "
        "(unsorted os.listdir/glob/iterdir, or iterating a set)",
        "wrap the listing in sorted(...) — the io/avro.py idiom — and "
        "iterate sorted(the_set); len()/membership tests are exempt",
    ),
    "PN504": (
        "entropy (urandom/uuid4/wall-clock/unseeded RNG) flowing into "
        "a digest, fingerprint, or artifact field",
        "derive the value from content (e.g. a schema/payload digest, "
        "the Avro sync-marker fix) so rebuilds stay byte-identical",
    ),
    "PN505": (
        "cross-process float reduction whose operand order is not "
        "pinned (reducing a set-ordered operand in a gathering "
        "function)",
        "index gathered parts by rank (parts[i] for i in range(n)) "
        "before concatenating/summing",
    ),
    "PN506": (
        "NaN comparison or float-literal equality in a branch "
        "(==/!= NaN never fires; one ulp of drift flips a float== "
        "convergence check)",
        "use np.isnan/math.isnan; compare against tolerances or "
        "integral sentinels (0.0/1.0 are exempt)",
    ),
}


@dataclasses.dataclass(frozen=True)
class Finding:
    """One lint finding, anchored to ``path:line`` with a fix hint."""

    code: str
    path: str  # repo-relative, '/'-separated
    line: int
    message: str
    hint: str = ""
    snippet: str = ""  # stripped source line (the baseline match key)

    def render(self) -> str:
        out = f"{self.path}:{self.line}: {self.code} {self.message}"
        if self.hint:
            out += f"\n    hint: {self.hint}"
        return out

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


class BaselineError(ValueError):
    """The baseline file is malformed or an entry lacks a justification."""


@dataclasses.dataclass(frozen=True)
class BaselineEntry:
    code: str
    path: str
    snippet: str
    justification: str

    @property
    def key(self) -> Tuple[str, str, str]:
        return (self.code, self.path, self.snippet)


_TODO_RE = re.compile(r"^\s*(todo|fixme|xxx|tbd)?\s*$", re.IGNORECASE)


def load_baseline(path: str) -> List[BaselineEntry]:
    """Parse + validate the baseline: every entry must carry a real
    justification — an entry without one is a finding nobody reviewed."""
    with open(path) as f:
        raw = json.load(f)
    entries = raw.get("entries") if isinstance(raw, dict) else None
    if not isinstance(entries, list):
        raise BaselineError(
            f"{path}: expected {{\"entries\": [...]}} at top level")
    out = []
    for i, e in enumerate(entries):
        if not isinstance(e, dict) or not all(
                isinstance(e.get(k), str)
                for k in ("code", "path", "snippet", "justification")):
            raise BaselineError(
                f"{path}: entry {i} needs string fields "
                "code/path/snippet/justification")
        if _TODO_RE.match(e["justification"]):
            raise BaselineError(
                f"{path}: entry {i} ({e['code']} {e['path']}) has no "
                "justification — every suppressed finding must say WHY "
                "it is accepted")
        out.append(BaselineEntry(e["code"], e["path"], e["snippet"],
                                 e["justification"]))
    return out


# -- source + AST helpers ---------------------------------------------------
def iter_python_files(roots: Sequence[str]) -> List[str]:
    files: List[str] = []
    for root in roots:
        if os.path.isfile(root):
            files.append(root)
            continue
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            files.extend(os.path.join(dirpath, f)
                         for f in sorted(filenames) if f.endswith(".py"))
    return sorted(set(files))


def parse_module(path: str) -> Tuple[Optional[ast.Module], List[str]]:
    """(tree, source lines); tree is None on a syntax error (the caller
    emits nothing — a file that does not parse fails its own tests)."""
    with open(path, encoding="utf-8") as f:
        source = f.read()
    lines = source.splitlines()
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError:
        return None, lines
    attach_parents(tree)
    return tree, lines


def attach_parents(tree: ast.AST) -> None:
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            child._pcheck_parent = node  # type: ignore[attr-defined]


def parent(node: ast.AST) -> Optional[ast.AST]:
    return getattr(node, "_pcheck_parent", None)


def ancestors(node: ast.AST) -> Iterable[ast.AST]:
    cur = parent(node)
    while cur is not None:
        yield cur
        cur = parent(cur)


def call_name(node: ast.AST) -> str:
    """Terminal name of a call target: ``a.b.c(...)`` -> ``c``."""
    func = node.func if isinstance(node, ast.Call) else node
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


def dotted_name(node: ast.AST) -> str:
    """Best-effort dotted form: ``jax.jit`` -> ``"jax.jit"``; empty when
    the base is not a plain name chain."""
    func = node.func if isinstance(node, ast.Call) else node
    parts: List[str] = []
    cur = func
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
        return ".".join(reversed(parts))
    return ""


def enclosing_function(node: ast.AST):
    for anc in ancestors(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return anc
    return None


def snippet_at(lines: List[str], lineno: int) -> str:
    if 1 <= lineno <= len(lines):
        return lines[lineno - 1].strip()
    return ""


# -- inline pragma ----------------------------------------------------------
_PRAGMA_RE = re.compile(
    r"#\s*photon-check:\s*allow\[([A-Z0-9,\s]+)\]\s*(.*)")


def pragma_map(lines: List[str]) -> Dict[int, set]:
    """line -> set of allowed codes; a pragma suppresses findings on its
    own line and the line below (pragma-above style). Pragmas without a
    reason are ignored — same contract as the baseline."""
    out: Dict[int, set] = {}
    for i, line in enumerate(lines, start=1):
        m = _PRAGMA_RE.search(line)
        if not m or not m.group(2).strip():
            continue
        codes = {c.strip() for c in m.group(1).split(",") if c.strip()}
        out.setdefault(i, set()).update(codes)
        out.setdefault(i + 1, set()).update(codes)
    return out


# -- engine -----------------------------------------------------------------
def _relpath(path: str, repo_root: Optional[str]) -> str:
    if repo_root:
        try:
            return os.path.relpath(path, repo_root).replace(os.sep, "/")
        except ValueError:  # different drive (windows)
            pass
    return path.replace(os.sep, "/")


def run_check(roots: Sequence[str], *,
              baseline: Sequence[BaselineEntry] = (),
              repo_root: Optional[str] = None,
              passes: Optional[Sequence[str]] = None,
              hot_paths: Optional[Sequence[str]] = None,
              blocking_scope: Optional[Sequence[str]] = None,
              concurrency_scope: Optional[Sequence[str]] = None,
              numerics_scope: Optional[Sequence[str]] = None) -> dict:
    """Run the lint passes over ``roots``.

    Returns a report dict: ``findings`` (unsuppressed), ``suppressed``
    (finding, via) pairs, ``stale_baseline`` entries that matched
    nothing, and ``files_checked``. ``passes`` selects a subset by
    module name (collectives/recompile/blocking/concurrency/numerics);
    ``hot_paths`` / ``blocking_scope`` / ``concurrency_scope`` /
    ``numerics_scope`` override the per-pass file scopes (None = the
    repo defaults; pass ``["*"]`` to scan every file — what the
    fixture tests do)."""
    from photon_ml_tpu.analysis import (
        blocking,
        collectives,
        concurrency,
        numerics,
        recompile,
    )

    files = iter_python_files(roots)
    modules = []
    for path in files:
        tree, lines = parse_module(path)
        if tree is None:
            continue
        modules.append((path, _relpath(path, repo_root), tree, lines))

    selected = set(passes) if passes is not None else {
        "collectives", "recompile", "blocking", "concurrency",
        "numerics"}
    raw: List[Finding] = []
    if "collectives" in selected:
        raw += collectives.check_modules(modules)
    if "recompile" in selected:
        raw += recompile.check_modules(modules, hot_paths=hot_paths)
    if "blocking" in selected:
        raw += blocking.check_modules(modules, scope=blocking_scope)
    if "concurrency" in selected:
        raw += concurrency.check_modules(modules, scope=concurrency_scope)
    if "numerics" in selected:
        raw += numerics.check_modules(modules, scope=numerics_scope)
    raw.sort(key=lambda f: (f.path, f.line, f.code))

    pragmas = {rel: pragma_map(lines) for _p, rel, _t, lines in modules}
    by_key: Dict[Tuple[str, str, str], BaselineEntry] = {
        e.key: e for e in baseline}
    used_keys: set = set()
    findings: List[Finding] = []
    suppressed: List[Tuple[Finding, str]] = []
    for f in raw:
        allowed = pragmas.get(f.path, {}).get(f.line, set())
        if f.code in allowed:
            suppressed.append((f, "pragma"))
            continue
        entry = by_key.get((f.code, f.path, f.snippet))
        if entry is not None:
            used_keys.add(entry.key)
            suppressed.append((f, "baseline"))
            continue
        findings.append(f)
    stale = [e for e in baseline if e.key not in used_keys]
    return {
        "findings": findings,
        "suppressed": suppressed,
        "stale_baseline": stale,
        "files_checked": len(modules),
    }
