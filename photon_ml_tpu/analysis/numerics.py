"""Numerics lint (PN501-PN506): the bit-determinism discipline, checked.

Every load-bearing guarantee in this repo is an f64 *bitwise* parity:
sharded-vs-single-host fits, cached-vs-uncached passes, recovered-vs-
uninterrupted runs, swap-stable serving. Those parities are re-proven
test by test, but nothing enforced the coding discipline that makes
them hold — they silently break the moment someone sums floats in a
plain loop or iterates an unsorted ``os.listdir``. Six shapes:

* **PN501** — bare float accumulation on a hot numeric path: builtin
  ``sum()`` over a float-valued comprehension, or a ``+=`` of a float
  expression inside a loop. Both are order- and rounding-sensitive;
  the approved routes are the Kahan helpers in
  ``parallel/streaming.py`` (``_kahan_add``/``_make_kahan_reduce``),
  ``math.fsum``, or a jnp/np reduction whose operand order is pinned.
  Integer counters, ``len()`` totals, and wall-clock/timing stats
  (``*_s``/``elapsed``/``perf_counter`` — diagnostics, not
  parity-bearing state) are exempt.
* **PN502** — dtype narrowing on an f64 path: ``.astype`` to a 32/16-
  bit float, ``np.float32(x)``/``jnp.float32(x)`` value casts, a
  32/16-bit float ``dtype=`` literal at a *call site* (function-
  parameter *defaults* are configuration knobs and exempt), or a bare
  Python float literal passed positionally to a known-jitted callee
  (jax weak-type promotion changes the kernel's compute dtype).
* **PN503** — nondeterministic-order iteration feeding downstream
  state: ``os.listdir``/``os.scandir``/``glob.glob``/``iterdir``
  results not wrapped in ``sorted(...)`` (directory order is
  filesystem-dependent), and loops/comprehensions iterating a ``set``
  (string hashing is per-process randomized). ``len(...)`` totals and
  ``in`` membership tests over the raw listing are order-free and
  exempt. The fix idiom is ``sorted(os.listdir(p))`` (io/avro.py).
* **PN504** — entropy flowing into digests/fingerprints/artifacts:
  ``os.urandom``/``uuid.uuid4``/``time.time``/``datetime.now``/
  unseeded ``random.*`` feeding a hash call, assigned to a
  marker/digest/fingerprint-named variable, or produced inside a
  function named like one — the PR-3 Avro sync-marker bug class,
  caught statically. Entropy used for IDs, timestamps-as-metadata, or
  jitter stays legal.
* **PN505** — cross-process float reduction with unpinned operand
  order: inside a function that gathers (``allgather_*``/
  ``exchange_score_updates``/``process_allgather``), a reduction
  (``concatenate``/``stack``/``sum``/``fsum``) whose operand iterates
  a set. Gathered parts must be indexed by rank before reducing.
* **PN506** — NaN/float-equality misuse: ``==``/``!=`` against a NaN
  constant (always False/True — use ``isnan``), and ``==``/``!=``
  against a non-integral float literal inside an ``if``/``while``
  test (a convergence check that rounding will flip). Integral
  literals (``0.0``, ``1.0``) and array-vs-array ``!=`` (the delta
  exchange's *deliberate* bitwise-change detection) are exempt.

Scope: PN501/PN502 run over the registered numeric hot-path modules
(``DEFAULT_NUMERIC_HOT_PATHS``; override with ``numerics_scope``,
``["*"]`` scans everything — what the fixture tests do). PN503-PN505
run repo-wide. PN506 runs over modules that import numpy/jax
(content-detected). Like every pass here the analysis is lexical —
a float that arrives through three helper calls is invisible; the
justified baseline exists for the shapes the lattice cannot see.
"""

from __future__ import annotations

import ast
import re
from typing import List, Optional, Sequence, Set

from photon_ml_tpu.analysis.core import (
    PASS_CATALOG,
    Finding,
    ancestors,
    call_name,
    dotted_name,
    enclosing_function,
    snippet_at,
)

__all__ = ["check_modules", "DEFAULT_NUMERIC_HOT_PATHS"]

# Parity-bearing numeric modules: solver kernels, the CD loop, scoring,
# streaming accumulation, the cross-process exchange. PN501/PN502 run
# here by default; grow this list as numeric code grows.
DEFAULT_NUMERIC_HOT_PATHS = (
    "photon_ml_tpu/game/descent.py",
    "photon_ml_tpu/game/random_effect.py",
    "photon_ml_tpu/game/scoring.py",
    "photon_ml_tpu/models/glm.py",
    "photon_ml_tpu/ops/losses.py",
    "photon_ml_tpu/ops/objective.py",
    "photon_ml_tpu/ops/regularization.py",
    "photon_ml_tpu/ops/statistics.py",
    "photon_ml_tpu/optimize/common.py",
    "photon_ml_tpu/optimize/lbfgs.py",
    "photon_ml_tpu/optimize/lbfgs_margin.py",
    "photon_ml_tpu/optimize/linesearch.py",
    "photon_ml_tpu/optimize/owlqn.py",
    "photon_ml_tpu/optimize/path.py",
    "photon_ml_tpu/optimize/tron.py",
    "photon_ml_tpu/evaluation/evaluators.py",
    "photon_ml_tpu/evaluation/device.py",
    "photon_ml_tpu/parallel/entity_shard.py",
    "photon_ml_tpu/parallel/streaming.py",
)

# -- shared predicates ------------------------------------------------------
# Names whose terminal segment says "this value is a float that matters":
# the accumulator vocabulary of the solver/scoring stack.
_FLOATISH_NAME_RE = re.compile(
    r"(loss|score|grad|margin|resid|coef|weight|penalt|objective"
    r"|loglik|likelihood|variance|sigma|lambda|alpha|norm|rmse|auc"
    r"|mean|value|val)s?$", re.IGNORECASE)
# Timing/diagnostic accumulators: stats, not parity-bearing state.
_TIMING_NAME_RE = re.compile(
    r"(_s|_ns|_ms|seconds|elapsed|duration|wall|latency)\d*$",
    re.IGNORECASE)
_TIMING_CALLS = {"perf_counter", "monotonic", "time", "time_ns",
                 "process_time"}
_COMPENSATED = ("kahan", "fsum", "compensated")

_NARROW_DTYPES = {"float32", "float16", "bfloat16", "half", "single"}
_LISTING_CALLS = {"os.listdir", "os.scandir", "glob.glob", "glob.iglob"}
_ENTROPY_CALLS = {"os.urandom", "uuid.uuid4", "uuid.uuid1", "time.time",
                  "time.time_ns", "datetime.now", "datetime.utcnow",
                  "random.random", "random.getrandbits", "random.randint"}
_DIGEST_CALLS = {"sha256", "sha1", "sha512", "md5", "blake2b", "blake2s",
                 "update"}
_ARTIFACT_NAME_RE = re.compile(
    r"(marker|digest|fingerprint|checksum|salt|sync)", re.IGNORECASE)
_GATHER_CALLS = {"allgather_payload", "allgather_blobs",
                 "allgather_objects", "allgather_status",
                 "exchange_score_updates", "process_allgather"}
_REDUCTION_CALLS = {"concatenate", "stack", "hstack", "vstack", "sum",
                    "fsum"}


def _finding(code: str, rel: str, lines, lineno: int, message: str
             ) -> Finding:
    return Finding(code=code, path=rel, line=lineno, message=message,
                   hint=PASS_CATALOG[code][1],
                   snippet=snippet_at(lines, lineno))


def _terminal(node: ast.AST) -> str:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


def _has_float_evidence(expr: ast.AST) -> bool:
    """The expression's value is (or contains) a float that matters:
    a float() cast, a division, a non-integral float literal, .item(),
    or a name from the solver accumulator vocabulary."""
    for node in ast.walk(expr):
        if isinstance(node, ast.Constant) and isinstance(node.value, float):
            if not float(node.value).is_integer():
                return True
        elif isinstance(node, ast.BinOp) and isinstance(node.op, ast.Div):
            return True
        elif isinstance(node, ast.Call):
            name = call_name(node)
            if name in {"float", "float64", "item"}:
                return True
        elif isinstance(node, (ast.Name, ast.Attribute)):
            if _FLOATISH_NAME_RE.search(_terminal(node)):
                return True
    return False


def _is_timing_expr(expr: ast.AST) -> bool:
    for node in ast.walk(expr):
        if isinstance(node, ast.Call) and call_name(node) in _TIMING_CALLS:
            return True
        if isinstance(node, (ast.Name, ast.Attribute)):
            if _TIMING_NAME_RE.search(_terminal(node)):
                return True
    return False


def _is_compensated_context(node: ast.AST) -> bool:
    """The statement already routes through a compensated-summation
    helper (Kahan/fsum) — lexically, by name anywhere in the statement
    or the enclosing function's name."""
    fn = enclosing_function(node)
    if fn is not None and any(k in fn.name.lower() for k in _COMPENSATED):
        return True
    stmt = node
    if not isinstance(stmt, ast.stmt):
        for anc in ancestors(node):
            stmt = anc
            if isinstance(anc, ast.stmt):
                break
    for sub in ast.walk(stmt):
        if isinstance(sub, (ast.Name, ast.Attribute, ast.Call)):
            name = (call_name(sub) if isinstance(sub, ast.Call)
                    else _terminal(sub))
            if any(k in name.lower() for k in _COMPENSATED):
                return True
    return False


def _in_sorted(node: ast.AST) -> bool:
    for anc in ancestors(node):
        if isinstance(anc, ast.Call) and isinstance(anc.func, ast.Name) \
                and anc.func.id in {"sorted", "len", "set", "min", "max",
                                    "frozenset"}:
            # sorted() pins order; len/min/max/set are order-free sinks
            return True
        if isinstance(anc, ast.Compare) and any(
                isinstance(op, (ast.In, ast.NotIn)) for op in anc.ops):
            return True  # membership test: order-free
        if isinstance(anc, ast.stmt):
            return False
    return False


def _narrow_dtype_node(node: ast.AST) -> bool:
    if isinstance(node, (ast.Attribute, ast.Name)):
        return _terminal(node) in _NARROW_DTYPES
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value in _NARROW_DTYPES
    return False


def _jitted_callee_names(tree: ast.Module) -> Set[str]:
    """Names bound to jit-wrapped callables at module/function scope:
    ``step = jax.jit(...)`` / ``kernel = cached_jit(...)``."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign) or not isinstance(
                node.value, ast.Call):
            continue
        name = call_name(node.value)
        dotted = dotted_name(node.value)
        if name in {"cached_jit", "jit"} or dotted in {
                "jax.jit", "jax.pjit", "pjit"}:
            for t in node.targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
    return out


# -- PN501: bare float accumulation -----------------------------------------
def _check_pn501(rel, lines, tree) -> List[Finding]:
    out: List[Finding] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
                and node.func.id == "sum" and node.args:
            arg = node.args[0]
            if isinstance(arg, (ast.GeneratorExp, ast.ListComp)) \
                    and _has_float_evidence(arg.elt) \
                    and not _is_timing_expr(arg.elt) \
                    and not _is_compensated_context(node):
                out.append(_finding(
                    "PN501", rel, lines, node.lineno,
                    "builtin sum() over a float comprehension: "
                    "left-to-right rounding makes the result depend on "
                    "operand order"))
        elif isinstance(node, ast.AugAssign) and isinstance(
                node.op, ast.Add):
            in_loop = any(isinstance(a, (ast.For, ast.While))
                          for a in ancestors(node))
            if not in_loop:
                continue
            if not _has_float_evidence(node.value):
                continue
            if _is_timing_expr(node.value) or _is_timing_expr(node.target):
                continue
            if _is_compensated_context(node):
                continue
            out.append(_finding(
                "PN501", rel, lines, node.lineno,
                f"float '+=' accumulation in a loop "
                f"(target '{_terminal(node.target) or '?'}'): rounding "
                "error accumulates in iteration order"))
    return out


# -- PN502: dtype narrowing --------------------------------------------------
def _check_pn502(rel, lines, tree) -> List[Finding]:
    out: List[Finding] = []
    # function-parameter defaults are configuration, not narrowing
    default_nodes: Set[int] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for d in (list(node.args.defaults)
                      + [d for d in node.args.kw_defaults if d]):
                for sub in ast.walk(d):
                    default_nodes.add(id(sub))
    jitted = _jitted_callee_names(tree)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = call_name(node)
        if name == "astype" and node.args \
                and _narrow_dtype_node(node.args[0]):
            out.append(_finding(
                "PN502", rel, lines, node.lineno,
                "astype() downcast to a 32/16-bit float on an f64 path"))
            continue
        if name in _NARROW_DTYPES and node.args \
                and id(node) not in default_nodes:
            out.append(_finding(
                "PN502", rel, lines, node.lineno,
                f"{name}() value cast narrows to 32/16-bit on an f64 "
                "path"))
            continue
        for kw in node.keywords:
            if kw.arg == "dtype" and _narrow_dtype_node(kw.value) \
                    and id(kw.value) not in default_nodes:
                out.append(_finding(
                    "PN502", rel, lines, kw.value.lineno,
                    "32/16-bit float dtype literal at a call site on an "
                    "f64 path"))
        if isinstance(node.func, ast.Name) and node.func.id in jitted:
            for arg in node.args:
                if isinstance(arg, ast.Constant) \
                        and isinstance(arg.value, float):
                    out.append(_finding(
                        "PN502", rel, lines, node.lineno,
                        f"bare Python float literal passed to jitted "
                        f"'{node.func.id}': weak-type promotion can "
                        "change the kernel's compute dtype"))
    return out


# -- PN503: nondeterministic iteration order --------------------------------
def _check_pn503(rel, lines, tree) -> List[Finding]:
    out: List[Finding] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            dotted = dotted_name(node)
            name = call_name(node)
            listing = (dotted in _LISTING_CALLS
                       or (dotted == "" and name in {"listdir", "scandir",
                                                     "iglob"})
                       or name == "iterdir")
            if listing and not _in_sorted(node):
                out.append(_finding(
                    "PN503", rel, lines, node.lineno,
                    f"unsorted {name}(): directory order is "
                    "filesystem-dependent and flows into downstream "
                    "state"))
        iter_sources: List[ast.AST] = []
        if isinstance(node, (ast.For, ast.AsyncFor)):
            iter_sources.append(node.iter)
        elif isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                               ast.GeneratorExp)):
            iter_sources.extend(g.iter for g in node.generators)
        for src in iter_sources:
            is_set = (isinstance(src, (ast.Set, ast.SetComp))
                      or (isinstance(src, ast.Call)
                          and isinstance(src.func, ast.Name)
                          and src.func.id in {"set", "frozenset"}))
            if is_set:
                out.append(_finding(
                    "PN503", rel, lines, src.lineno,
                    "iteration over a set: string-hash order is "
                    "randomized per process"))
    return out


# -- PN504: entropy into digests/fingerprints -------------------------------
def _check_pn504(rel, lines, tree) -> List[Finding]:
    out: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        dotted = dotted_name(node)
        name = call_name(node)
        if not (dotted in _ENTROPY_CALLS or name == "urandom"
                or (dotted.endswith(".now") and "datetime" in dotted)):
            continue
        reason = ""
        for anc in ancestors(node):
            if isinstance(anc, ast.Call) \
                    and call_name(anc) in _DIGEST_CALLS:
                reason = f"feeds a {call_name(anc)}() digest"
                break
            if isinstance(anc, ast.Assign):
                for t in anc.targets:
                    if _ARTIFACT_NAME_RE.search(_terminal(t)):
                        reason = (f"assigned to artifact-bearing "
                                  f"'{_terminal(t)}'")
                        break
                if reason:
                    break
        if not reason:
            fn = enclosing_function(node)
            if fn is not None and _ARTIFACT_NAME_RE.search(fn.name):
                reason = f"inside {fn.name}()"
        if reason:
            out.append(_finding(
                "PN504", rel, lines, node.lineno,
                f"entropy source {name or dotted}() {reason}: the "
                "value lands in a digest/fingerprint/artifact and "
                "breaks byte-identical rebuilds (the Avro sync-marker "
                "bug class)"))
    return out


# -- PN505: unpinned cross-process reduction --------------------------------
def _contains_set_source(expr: ast.AST) -> bool:
    for node in ast.walk(expr):
        if isinstance(node, (ast.Set, ast.SetComp)):
            return True
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
                and node.func.id in {"set", "frozenset"}:
            return True
    return False


def _check_pn505(rel, lines, tree) -> List[Finding]:
    out: List[Finding] = []
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        gathers = any(isinstance(n, ast.Call)
                      and call_name(n) in _GATHER_CALLS
                      for n in ast.walk(fn))
        if not gathers:
            continue
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            if call_name(node) not in _REDUCTION_CALLS:
                continue
            if _contains_set_source(node.args[0]):
                out.append(_finding(
                    "PN505", rel, lines, node.lineno,
                    f"{call_name(node)}() over a set-ordered operand in "
                    f"gathering function '{fn.name}': cross-process "
                    "reduction order is not pinned by rank"))
    return out


# -- PN506: NaN / float-equality misuse -------------------------------------
def _is_nan_expr(node: ast.AST) -> bool:
    if isinstance(node, (ast.Attribute, ast.Name)) \
            and _terminal(node) == "nan":
        return True
    return (isinstance(node, ast.Call) and call_name(node) == "float"
            and node.args and isinstance(node.args[0], ast.Constant)
            and str(node.args[0].value).lower() == "nan")


def _nonintegral_float(node: ast.AST) -> bool:
    return (isinstance(node, ast.Constant)
            and isinstance(node.value, float)
            and node.value == node.value  # not nan
            and not float(node.value).is_integer())


def _check_pn506(rel, lines, tree) -> List[Finding]:
    out: List[Finding] = []
    test_compares: Set[int] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.If, ast.While)):
            for sub in ast.walk(node.test):
                if isinstance(sub, ast.Compare):
                    test_compares.add(id(sub))
    for node in ast.walk(tree):
        if not isinstance(node, ast.Compare):
            continue
        if not any(isinstance(op, (ast.Eq, ast.NotEq)) for op in node.ops):
            continue
        sides = [node.left] + list(node.comparators)
        if any(_is_nan_expr(s) for s in sides):
            out.append(_finding(
                "PN506", rel, lines, node.lineno,
                "==/!= against NaN is always False/True (IEEE 754): "
                "the branch never fires"))
            continue
        if id(node) in test_compares and any(
                _nonintegral_float(s) for s in sides):
            out.append(_finding(
                "PN506", rel, lines, node.lineno,
                "float-literal equality in a branch condition: one ulp "
                "of drift flips the check"))
    return out


# -- entry point ------------------------------------------------------------
def check_modules(modules, *, scope: Optional[Sequence[str]] = None
                  ) -> List[Finding]:
    """``modules`` is ``[(path, rel, tree, lines), ...]``. ``scope``
    overrides the PN501/PN502 hot-path list (``["*"]`` scans every
    module for every check — the fixture-test mode)."""
    scan_all = scope is not None and list(scope) == ["*"]
    hot = tuple(scope) if scope is not None else DEFAULT_NUMERIC_HOT_PATHS
    out: List[Finding] = []
    for _path, rel, tree, lines in modules:
        is_hot = scan_all or rel in hot or any(
            rel.endswith(h) for h in hot)
        if is_hot:
            out += _check_pn501(rel, lines, tree)
            out += _check_pn502(rel, lines, tree)
        out += _check_pn503(rel, lines, tree)
        out += _check_pn504(rel, lines, tree)
        out += _check_pn505(rel, lines, tree)
        src = "\n".join(lines)
        if scan_all or is_hot or "numpy" in src or "jax" in src:
            out += _check_pn506(rel, lines, tree)
    return out
