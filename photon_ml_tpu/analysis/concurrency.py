"""Concurrency lint (PT401-PT405) for the threaded serving/streaming stack.

The production-QPS path spans ~15 locks and a dozen daemon threads (the
batcher worker, the paged-table installer, the registry watcher, the
prefetch ring, ThreadTransport, the asyncio front door). A data race or
a lock-order inversion there silently corrupts a hot swap or hangs a
replica — exactly the failure class the PR-1 fail-stop runtime exists
to eliminate, and invisible to the collectives/recompile/blocking
passes. Five shapes:

* **PT401** — an instance attribute written from a ``threading.Thread``
  target (or any method reachable from one via ``self`` calls) and
  accessed elsewhere in the class, with the two sides not both under
  the owning ``with self._lock``. ``__init__`` accesses are exempt
  (they happen-before ``start()``), as are attributes that ARE
  synchronizers (locks, events, queues — internally synchronized).
* **PT402** — inconsistent nested lock-acquisition order: a per-class /
  per-module static lock graph records every ``with A: ... with B:``
  nesting (including one ``self``-call hop: ``with A: self.m()`` where
  ``m`` acquires ``B``); any edge whose reverse is reachable is a
  deadlock window. ``photon-check --lock-graph`` dumps the graph as
  DOT.
* **PT403** — a ``Thread(...)``/``Timer(...)`` started with no
  reachable bounded ``join(timeout)``: bound to ``self.X``, the class
  must join ``X`` with a timeout somewhere; bound locally, the
  enclosing function must; anonymous ``Thread(...).start()`` always
  flags. The leak class ``producer_join_timeouts`` already warns about
  at runtime, caught statically.
* **PT404** — a timeout-less blocking ``Queue.get()`` /
  ``Condition.wait()`` / ``Event.wait()`` in a worker loop (inside a
  ``while``, or directly in a thread-target function). A wedged
  producer/consumer then hangs the worker forever instead of failing
  stop — the hang hazard against PR 1's guarantee. ``await``-ed waits
  (asyncio primitives) are exempt.
* **PT405** — a callback invoked while holding a lock: an opaque
  ``on_*`` / ``*_callback`` / ``cb`` callable called lexically inside a
  ``with <lock>`` block. A callback that re-enters the class (or just
  blocks) self-deadlocks — the shape ``PendingRequest._fire_callbacks``
  deliberately avoids by draining the list under ``_cb_lock`` and
  firing outside it.

Scope: modules that use ``threading`` (content-detected), which is the
serve/ + streaming/ + resilience + driver set today and follows the
code as it grows. Like every pass here the analysis is lexical: lock
identity resolves only for ``self`` attributes and module-level names,
and the PT402 call hop follows ``self`` methods one level — guards and
joins living across objects are what the justified baseline is for.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from photon_ml_tpu.analysis.core import (
    PASS_CATALOG,
    Finding,
    ancestors,
    call_name,
    enclosing_function,
    parent,
    snippet_at,
)

__all__ = ["check_modules", "build_lock_graph", "lock_graph_dot"]

# Constructors whose product is a lock (a `with` on it is an acquisition).
_LOCK_CONSTRUCTORS = {"Lock", "RLock", "Condition"}
# Constructors whose product is internally synchronized: attributes
# holding these are not PT401 data (mutating them IS the safe pattern).
_SYNC_CONSTRUCTORS = _LOCK_CONSTRUCTORS | {
    "Event", "Semaphore", "BoundedSemaphore", "Barrier", "local",
    "Queue", "LifoQueue", "PriorityQueue", "SimpleQueue", "Thread",
    "Timer", "deque",
}
_THREAD_CONSTRUCTORS = {"Thread", "Timer"}

# Fallback lock naming for `with` targets whose constructor is not
# visible in the module (e.g. a lock passed in): name says lock.
_LOCKISH_RE = re.compile(
    r"(^|_)(lock|rlock|mutex|cond|condition)s?$", re.IGNORECASE)

_CALLBACK_NAME_RE = re.compile(
    r"^(on_[a-z0-9_]+|cb|cbs|hook|hooks|callback|callbacks"
    r"|.*(_cb|_cbs|_callback|_callbacks|_hook|_hooks))$")
# registration/maintenance APIs are not invocations
_CALLBACK_EXEMPT_PREFIXES = ("add_", "register_", "set_", "remove_",
                             "clear_", "fire_", "_fire")


def _queueish(name: str) -> bool:
    low = name.lower()
    return low == "q" or low.endswith("_q") or "queue" in low


def _terminal(node: ast.AST) -> str:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


def _is_self_attr(node: ast.AST) -> bool:
    return (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self")


def _finding(code: str, rel: str, lines, lineno: int, message: str
             ) -> Finding:
    return Finding(code=code, path=rel, line=lineno, message=message,
                   hint=PASS_CATALOG[code][1],
                   snippet=snippet_at(lines, lineno))


def _select(modules, scope: Optional[Sequence[str]]):
    """Default scope is content-based: any module that touches
    ``threading`` is part of the threaded stack and gets scanned."""
    if scope is None:
        return [m for m in modules
                if any("threading" in ln for ln in m[3])]
    if "*" in scope:
        return list(modules)
    return [m for m in modules if any(s in m[1] for s in scope)]


# -- lock identity ----------------------------------------------------------
# A lock id is (owner, name): owner is the class name for self attrs,
# "" for module-level names. Everything else is unresolvable (lexical
# pass: no cross-object aliasing).
LockId = Tuple[str, str]


def _fmt_lock(lock: LockId) -> str:
    owner, name = lock
    return f"{owner}.{name}" if owner else name


class _ModuleLocks:
    """Lock/synchronizer bindings visible in one module."""

    def __init__(self, tree: ast.Module):
        self.class_locks: Dict[str, Set[str]] = {}
        self.class_sync_attrs: Dict[str, Set[str]] = {}
        self.module_locks: Set[str] = set()
        for node in ast.walk(tree):
            if not isinstance(node, ast.Assign) or not isinstance(
                    node.value, ast.Call):
                continue
            ctor = call_name(node.value)
            if ctor not in _SYNC_CONSTRUCTORS:
                continue
            for target in node.targets:
                if _is_self_attr(target):
                    cls = _enclosing_class(node)
                    if cls is None:
                        continue
                    self.class_sync_attrs.setdefault(
                        cls.name, set()).add(target.attr)
                    if ctor in _LOCK_CONSTRUCTORS:
                        self.class_locks.setdefault(
                            cls.name, set()).add(target.attr)
                elif (isinstance(target, ast.Name)
                      and ctor in _LOCK_CONSTRUCTORS
                      and _enclosing_class(node) is None
                      and enclosing_function(node) is None):
                    self.module_locks.add(target.id)

    def lock_id_of(self, expr: ast.AST, cls_name: str) -> Optional[LockId]:
        """Resolve a ``with`` target to a lock id, or None when it is
        not a lock (or not resolvable)."""
        if _is_self_attr(expr):
            name = expr.attr
            if (name in self.class_locks.get(cls_name, ())
                    or _LOCKISH_RE.search(name)):
                return (cls_name, name)
            return None
        if isinstance(expr, ast.Name):
            if (expr.id in self.module_locks
                    or _LOCKISH_RE.search(expr.id)):
                return ("", expr.id)
        return None


def _enclosing_class(node: ast.AST) -> Optional[ast.ClassDef]:
    for anc in ancestors(node):
        if isinstance(anc, ast.ClassDef):
            return anc
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # keep climbing: methods live inside the class
            continue
    return None


def _methods(cls: ast.ClassDef) -> Dict[str, ast.FunctionDef]:
    return {n.name: n for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}


def _self_calls(fn) -> Set[str]:
    """Names of ``self.m(...)`` calls inside ``fn`` (nested defs
    included: worker closures call back into the class)."""
    out: Set[str] = set()
    for node in ast.walk(fn):
        if (isinstance(node, ast.Call)
                and _is_self_attr(node.func)):
            out.add(node.func.attr)
    return out


def _thread_target_methods(cls: ast.ClassDef) -> Set[str]:
    """Method names passed as ``target=self.m`` to Thread/Timer inside
    the class."""
    out: Set[str] = set()
    for node in ast.walk(cls):
        if not (isinstance(node, ast.Call)
                and call_name(node) in _THREAD_CONSTRUCTORS):
            continue
        for kw in node.keywords:
            if kw.arg == "target" and _is_self_attr(kw.value):
                out.add(kw.value.attr)
    return out


def _module_thread_targets(tree: ast.Module) -> Set[str]:
    """Every name passed as ``target=`` to a Thread/Timer anywhere in
    the module (plain functions and methods alike) — the PT404
    worker-loop context for loop-less thread bodies."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and call_name(node) in _THREAD_CONSTRUCTORS):
            continue
        for kw in node.keywords:
            if kw.arg == "target":
                name = _terminal(kw.value)
                if name:
                    out.add(name)
    return out


def _under_lock(node: ast.AST, mlocks: _ModuleLocks, cls_name: str
                ) -> Set[LockId]:
    """Lock ids held lexically at ``node`` (enclosing ``with`` blocks
    within the same function)."""
    held: Set[LockId] = set()
    fn = enclosing_function(node)
    for anc in ancestors(node):
        if anc is fn:
            break
        if isinstance(anc, (ast.With, ast.AsyncWith)):
            for item in anc.items:
                lid = mlocks.lock_id_of(item.context_expr, cls_name)
                if lid is not None:
                    held.add(lid)
    return held


# -- PT401: unlocked cross-thread attribute ---------------------------------
def _attr_accesses(fn, *, writes_only: bool) -> List[Tuple[str, int, bool]]:
    """(attr, line, is_write) for ``self.X`` accesses in ``fn``.
    Subscript stores (``self.X[k] = v``) count as writes — they mutate
    the shared object."""
    out: List[Tuple[str, int, bool]] = []
    for node in ast.walk(fn):
        if not (_is_self_attr(node) and isinstance(node, ast.Attribute)):
            continue
        is_write = isinstance(node.ctx, (ast.Store, ast.Del))
        if not is_write:
            par = parent(node)
            if (isinstance(par, ast.Subscript)
                    and isinstance(par.ctx, (ast.Store, ast.Del))
                    and par.value is node):
                is_write = True
        if writes_only and not is_write:
            continue
        out.append((node.attr, node.lineno, is_write))
    return out


def _check_pt401(rel, lines, cls: ast.ClassDef, mlocks: _ModuleLocks
                 ) -> List[Finding]:
    targets = _thread_target_methods(cls)
    if not targets:
        return []
    methods = _methods(cls)
    # reachable-from-thread-target set via self calls
    reach: Set[str] = set()
    frontier = [t for t in targets if t in methods]
    while frontier:
        m = frontier.pop()
        if m in reach:
            continue
        reach.add(m)
        frontier.extend(c for c in _self_calls(methods[m])
                        if c in methods and c not in reach)

    sync_attrs = mlocks.class_sync_attrs.get(cls.name, set())
    # thread-side writes: attr -> (line, locks held)
    thread_writes: Dict[str, Tuple[int, Set[LockId]]] = {}
    write_nodes: Dict[str, ast.AST] = {}
    for m in reach:
        fn = methods[m]
        for node in ast.walk(fn):
            if not (_is_self_attr(node)
                    and isinstance(node, ast.Attribute)):
                continue
            is_write = isinstance(node.ctx, (ast.Store, ast.Del))
            par = parent(node)
            if (not is_write and isinstance(par, ast.Subscript)
                    and isinstance(par.ctx, (ast.Store, ast.Del))
                    and par.value is node):
                is_write = True
            if not is_write or node.attr in sync_attrs:
                continue
            if node.attr not in thread_writes:
                thread_writes[node.attr] = (
                    node.lineno, _under_lock(node, mlocks, cls.name))
                write_nodes[node.attr] = node
    if not thread_writes:
        return []

    findings: List[Finding] = []
    for attr, (w_line, w_locks) in sorted(thread_writes.items()):
        # accesses outside the thread-reachable set, __init__ exempt
        other: List[Tuple[int, Set[LockId]]] = []
        for name, fn in methods.items():
            if name in reach or name == "__init__":
                continue
            for node in ast.walk(fn):
                if (_is_self_attr(node)
                        and isinstance(node, ast.Attribute)
                        and node.attr == attr):
                    other.append(
                        (node.lineno, _under_lock(node, mlocks,
                                                  cls.name)))
        if not other:
            continue
        # both sides under a common lock -> disciplined
        unlocked_other = [ln for ln, locks in other if not locks]
        common = (set.intersection(w_locks, *[locks for _ln, locks
                                              in other])
                  if w_locks and all(locks for _ln, locks in other)
                  else set())
        if common:
            continue
        where = unlocked_other[0] if unlocked_other else other[0][0]
        findings.append(_finding(
            "PT401", rel, lines, w_line,
            f"'{cls.name}.{attr}' is written on the thread target path "
            f"here but accessed at line {where} without both sides "
            "holding the same lock: cross-thread data race"))
    return findings


# -- PT402: lock-order graph + inversions -----------------------------------
# edge key (src, dst) -> list of (rel, line, via) sites
EdgeMap = Dict[Tuple[LockId, LockId], List[Tuple[str, int, str]]]


def _method_locks(fn, mlocks: _ModuleLocks, cls_name: str
                  ) -> Set[LockId]:
    """Every lock ``fn`` acquires lexically anywhere in its body."""
    out: Set[LockId] = set()
    for node in ast.walk(fn):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                lid = mlocks.lock_id_of(item.context_expr, cls_name)
                if lid is not None:
                    out.add(lid)
    return out


def _scan_lock_nesting(rel, tree, mlocks: _ModuleLocks, edges: EdgeMap,
                       callbacks_out: List[Tuple[ast.Call, LockId]]
                       ) -> None:
    """One walk serving PT402 (nesting edges + one self-call hop) and
    PT405 (callback calls under a lock)."""

    def visit(node, held: List[LockId], cls_name: str,
              methods: Dict[str, ast.FunctionDef]):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            acquired: List[LockId] = []
            for item in node.items:
                lid = mlocks.lock_id_of(item.context_expr, cls_name)
                if lid is None:
                    continue
                for h in held + acquired:
                    if h != lid:
                        edges.setdefault((h, lid), []).append(
                            (rel, node.lineno, "nested with"))
                acquired.append(lid)
            for child in node.body:
                visit(child, held + acquired, cls_name, methods)
            return
        if isinstance(node, ast.ClassDef):
            m = _methods(node)
            for child in node.body:
                visit(child, [], node.name, m)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            body = node.body if not isinstance(node, ast.Lambda) else []
            for child in body:
                visit(child, list(held), cls_name, methods)
            return
        if held and isinstance(node, ast.Call):
            # PT405 candidate
            callbacks_out.append((node, held[-1]))
            # one-hop: with A held, self.m() acquiring B => A -> B
            if _is_self_attr(node.func):
                callee = methods.get(node.func.attr)
                if callee is not None:
                    for lid in _method_locks(callee, mlocks, cls_name):
                        for h in held:
                            if h != lid:
                                edges.setdefault((h, lid), []).append(
                                    (rel, node.lineno,
                                     f"via self.{node.func.attr}()"))
        for child in ast.iter_child_nodes(node):
            visit(child, held, cls_name, methods)

    for stmt in tree.body:
        visit(stmt, [], "", {})


def _reachable(edges: EdgeMap, src: LockId, dst: LockId) -> bool:
    adj: Dict[LockId, Set[LockId]] = {}
    for (a, b) in edges:
        adj.setdefault(a, set()).add(b)
    seen: Set[LockId] = set()
    stack = [src]
    while stack:
        cur = stack.pop()
        if cur == dst:
            return True
        if cur in seen:
            continue
        seen.add(cur)
        stack.extend(adj.get(cur, ()))
    return False


def _check_pt402(rel, lines, edges: EdgeMap) -> List[Finding]:
    findings: List[Finding] = []
    for (a, b), sites in sorted(edges.items(),
                                key=lambda kv: kv[1][0][1]):
        # an edge is an inversion when the OPPOSITE order is reachable
        # with this edge removed (a 2-cycle needs the b->a edge itself)
        rest: EdgeMap = {k: v for k, v in edges.items() if k != (a, b)}
        if not _reachable(rest, b, a):
            continue
        opposite = rest.get((b, a))
        opp = (f" (opposite order at "
               f"{opposite[0][0]}:{opposite[0][1]})" if opposite else
               " (reverse path exists in the acquisition graph)")
        site_rel, site_line, via = sites[0]
        findings.append(_finding(
            "PT402", site_rel, lines, site_line,
            f"lock '{_fmt_lock(b)}' acquired while holding "
            f"'{_fmt_lock(a)}' ({via}), but the opposite order also "
            f"exists{opp}: lock-order inversion, a deadlock window"))
    return findings


# -- PT403: unjoined threads ------------------------------------------------
def _bounded_join_calls(scope_node) -> List[str]:
    """Receiver names of ``X.join(<bounded>)`` calls inside
    ``scope_node`` (a join with at least one argument)."""
    out: List[str] = []
    for node in ast.walk(scope_node):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "join"
                and (node.args or node.keywords)):
            out.append(_terminal(node.func.value))
    return out


def _check_pt403(rel, lines, tree) -> List[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and call_name(node) in _THREAD_CONSTRUCTORS):
            continue
        # `threading.Timer` vs a local def named Thread: require the
        # threading module (or bare name from `from threading import`)
        dotted_ok = True
        if isinstance(node.func, ast.Attribute):
            dotted_ok = _terminal(node.func.value) == "threading"
        if not dotted_ok:
            continue
        binding: Optional[str] = None
        bound_to_self = False
        assign = None
        for anc in ancestors(node):
            if isinstance(anc, ast.Assign):
                assign = anc
                break
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.ClassDef)):
                break
        if assign is not None and len(assign.targets) == 1:
            target = assign.targets[0]
            if _is_self_attr(target):
                binding, bound_to_self = target.attr, True
            elif isinstance(target, ast.Name):
                binding = target.id
        joined = False
        if bound_to_self:
            cls = _enclosing_class(node)
            if cls is not None and binding in _bounded_join_calls(cls):
                joined = True
        elif binding is not None:
            fn = enclosing_function(node)
            scope_node = fn if fn is not None else tree
            # local bindings flow through lists/comprehensions; accept
            # any bounded join in the same function scope
            if _bounded_join_calls(scope_node):
                joined = True
        if joined:
            continue
        what = (f"bound to 'self.{binding}'" if bound_to_self
                else f"bound to '{binding}'" if binding
                else "anonymous (started inline)")
        findings.append(_finding(
            "PT403", rel, lines, node.lineno,
            f"thread {what} is started with no reachable bounded "
            "join(timeout): on shutdown it leaks (or wedges an "
            "unbounded join) instead of failing stop"))
    return findings


# -- PT404: timeout-less blocking waits in worker loops ---------------------
def _unbounded_get(node: ast.Call) -> bool:
    if any(kw.arg == "timeout" and not (
            isinstance(kw.value, ast.Constant)
            and kw.value.value is None) for kw in node.keywords):
        return False
    if len(node.args) >= 2:  # get(block, timeout)
        return isinstance(node.args[1], ast.Constant) \
            and node.args[1].value is None
    if len(node.args) == 1:  # get(key) is dict.get; get(True) blocks
        return (isinstance(node.args[0], ast.Constant)
                and node.args[0].value is True)
    return not any(kw.arg == "timeout" for kw in node.keywords)


def _in_worker_loop(node: ast.AST, thread_targets: Set[str]) -> bool:
    fn = enclosing_function(node)
    for anc in ancestors(node):
        if anc is fn:
            break
        if isinstance(anc, ast.While):
            return True
    return fn is not None and fn.name in thread_targets


def _check_pt404(rel, lines, tree, thread_targets: Set[str]
                 ) -> List[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)):
            continue
        if isinstance(parent(node), ast.Await):
            continue  # asyncio primitives; PB3xx territory
        attr = node.func.attr
        recv = _terminal(node.func.value)
        if attr == "get":
            if not _queueish(recv) or not _unbounded_get(node):
                continue
            kind = f"'{recv}.get()'"
        elif attr == "wait":
            if node.args or node.keywords:
                continue
            kind = f"'{recv}.wait()'"
        else:
            continue
        if not _in_worker_loop(node, thread_targets):
            continue
        findings.append(_finding(
            "PT404", rel, lines, node.lineno,
            f"timeout-less blocking {kind} in a worker loop: a wedged "
            "producer/consumer hangs this thread forever instead of "
            "failing stop (PR-1 discipline: bound every wait)"))
    return findings


# -- PT405: callback under a lock -------------------------------------------
def _callbackish(name: str) -> bool:
    return (bool(_CALLBACK_NAME_RE.match(name))
            and not name.startswith(_CALLBACK_EXEMPT_PREFIXES))


def _check_pt405(rel, lines,
                 calls_under_lock: List[Tuple[ast.Call, LockId]]
                 ) -> List[Finding]:
    findings: List[Finding] = []
    seen: Set[int] = set()
    for node, lock in calls_under_lock:
        name = _terminal(node.func)
        if not name or not _callbackish(name):
            continue
        if node.lineno in seen:
            continue
        seen.add(node.lineno)
        findings.append(_finding(
            "PT405", rel, lines, node.lineno,
            f"callback '{name}' invoked while holding "
            f"'{_fmt_lock(lock)}': a callback that re-enters this "
            "class (or merely blocks) self-deadlocks every caller of "
            "the lock"))
    return findings


# -- entry points -----------------------------------------------------------
def check_modules(modules, *, scope: Optional[Sequence[str]] = None
                  ) -> List[Finding]:
    findings: List[Finding] = []
    for _path, rel, tree, lines in _select(modules, scope):
        mlocks = _ModuleLocks(tree)
        edges: EdgeMap = {}
        under_lock: List[Tuple[ast.Call, LockId]] = []
        _scan_lock_nesting(rel, tree, mlocks, edges, under_lock)
        thread_targets = _module_thread_targets(tree)
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                findings += _check_pt401(rel, lines, node, mlocks)
        findings += _check_pt402(rel, lines, edges)
        findings += _check_pt403(rel, lines, tree)
        findings += _check_pt404(rel, lines, tree, thread_targets)
        findings += _check_pt405(rel, lines, under_lock)
    return findings


def build_lock_graph(modules, *, scope: Optional[Sequence[str]] = None
                     ) -> Dict[Tuple[str, str], List[Tuple[str, int, str]]]:
    """The inferred acquisition-order graph over ``modules``:
    ``(src, dst) -> [(path, line, via), ...]`` with lock names already
    rendered (``Class.attr`` / module-level name)."""
    out: Dict[Tuple[str, str], List[Tuple[str, int, str]]] = {}
    for _path, rel, tree, _lines in _select(modules, scope):
        mlocks = _ModuleLocks(tree)
        edges: EdgeMap = {}
        _scan_lock_nesting(rel, tree, mlocks, edges, [])
        for (a, b), sites in edges.items():
            out.setdefault((_fmt_lock(a), _fmt_lock(b)), []).extend(
                sites)
    return out


def lock_graph_dot(modules, *, scope: Optional[Sequence[str]] = None
                   ) -> str:
    """DOT rendering of :func:`build_lock_graph` (what
    ``photon-check --lock-graph`` prints; docs/analysis.md embeds it)."""
    graph = build_lock_graph(modules, scope=scope)
    nodes = sorted({n for edge in graph for n in edge})
    lines = ["digraph lock_order {", "  rankdir=LR;",
             '  node [shape=box, fontname="monospace"];']
    for n in nodes:
        lines.append(f'  "{n}";')
    for (a, b), sites in sorted(graph.items()):
        rel, line, _via = sites[0]
        label = f"{rel}:{line}"
        if len(sites) > 1:
            label += f" (+{len(sites) - 1})"
        lines.append(f'  "{a}" -> "{b}" [label="{label}"];')
    lines.append("}")
    return "\n".join(lines)
