"""photon-check: repo-specific static analysis + runtime sanitizers.

The invariants PRs 1-7 established — every cross-process collective is a
guarded, fault-injectable boundary; hot-path compile counts stay flat;
the asyncio serving loop never blocks — were enforced only by convention
and ad-hoc per-test counters. This package makes them machine-checked:

* **Lint passes** (AST-based, stdlib-only, no jax import so the CLI is
  instant and CPU-safe):

  - ``collectives``  — PC101 (collective not dominated by a
    health-barrier guard) and PC102 (collective inside control flow
    conditioned on process-local state: rank, queue depth, filesystem
    probes — the SPMD-hang shape).
  - ``recompile``    — PH201 (jit constructed per call in a hot-path
    function), PH202 (traced-value ``.item()``/``int()``/``float()``
    concretization inside a jit target), PH203 (jit call whose shape
    operand bypasses the registered power-of-two bucket/pad helpers),
    PH204 (unhashable Python-object passed at a static arg position).
  - ``blocking``     — PB301 (blocking primitive on the asyncio event
    loop), PB302 (call into a sync function that transitively blocks),
    PB303 (opaque callable parameter invoked synchronously on the loop).
  - ``concurrency``  — PT401 (cross-thread attribute write without a
    common owning lock), PT402 (inconsistent nested lock-acquisition
    order in the static lock graph — ``photon-check --lock-graph``
    dumps it as DOT), PT403 (thread started with no reachable bounded
    ``join(timeout)``), PT404 (timeout-less blocking
    ``Queue.get()``/``wait()`` in a worker loop), PT405 (callback
    invoked while holding a lock).
  - ``numerics``     — PN501 (bare float accumulation on a hot numeric
    path), PN502 (dtype narrowing on an f64 path), PN503
    (nondeterministic iteration order: unsorted listdir/glob, set
    iteration), PN504 (entropy flowing into digests/fingerprints —
    the Avro sync-marker bug class), PN505 (cross-process float
    reduction with unpinned operand order), PN506 (NaN comparison /
    float-literal equality in branch conditions). ``photon-check
    --numerics`` runs just these.

* **Fault-site audit** (``photon-check --fault-sites``): every
  ``fault_injection`` site registered in the package must be exercised
  by at least one tier-1 test, or the coordinated-abort machinery it
  guards is dead code until the first real outage.

* **Runtime sanitizers** (:mod:`.sanitizers`): the collective-trace
  sanitizer asserts per-process collective-sequence alignment in the
  simulated multi-controller harness (a race detector for SPMD code),
  :class:`~.sanitizers.CompileSanitizer` subsumes the ad-hoc
  flat-compile counters in the serving/CD tests,
  :class:`~.sanitizers.LockOrderSanitizer` raises on acquisition-order
  cycles with both stacks (deadlock detection without deadlocking),
  :class:`~.sanitizers.ThreadLeakSanitizer` asserts no photon-named
  thread outlives its block,
  :class:`~.sanitizers.DeterminismSanitizer` replays registered pure
  blocks twice and raises on any bitwise divergence (the PN5xx runtime
  twin), and :class:`~.sanitizers.NaNGuard` traps NaN/Inf escaping a
  solver kernel's host boundary with the producing site named.

Findings carry ``path:line`` + a fix hint. Accepted findings are
suppressed by the checked-in ``photon-check-baseline.json`` (every entry
requires a justification) or an inline
``# photon-check: allow[CODE] reason`` pragma. ``scripts/ci_lint.sh``
fails CI on any new violation.
"""

from __future__ import annotations

__version__ = "1.0.0"

from photon_ml_tpu.analysis.core import (  # noqa: F401
    Finding,
    PASS_CATALOG,
    load_baseline,
    run_check,
)
from photon_ml_tpu.analysis.sanitizers import (  # noqa: F401
    CollectiveTraceMismatch,
    CollectiveTraceSanitizer,
    CompileSanitizer,
    CompileSanitizerError,
    DeterminismSanitizer,
    DeterminismViolation,
    LockOrderSanitizer,
    LockOrderViolation,
    NaNGuard,
    NaNGuardError,
    ThreadLeakError,
    ThreadLeakSanitizer,
    deterministic_replay,
    nan_guard_check,
)

__all__ = [
    "__version__", "Finding", "PASS_CATALOG", "run_check", "load_baseline",
    "CollectiveTraceSanitizer", "CollectiveTraceMismatch",
    "CompileSanitizer", "CompileSanitizerError",
    "DeterminismSanitizer", "DeterminismViolation", "deterministic_replay",
    "LockOrderSanitizer", "LockOrderViolation",
    "NaNGuard", "NaNGuardError", "nan_guard_check",
    "ThreadLeakSanitizer", "ThreadLeakError", "repo_report",
]

_REPO_REPORT_CACHE: dict = {}


def repo_report(root: str | None = None) -> dict:
    """One-line summary of the repo's lint state — recorded in the shared
    ``_environment()`` block of every ``BENCH_*.json`` so a benchmark
    result carries the lint posture it was measured under."""
    import os

    from photon_ml_tpu.analysis import core

    if root is None:
        root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
    if root in _REPO_REPORT_CACHE:
        return _REPO_REPORT_CACHE[root]
    pkg = os.path.join(root, "photon_ml_tpu")
    baseline_path = os.path.join(root, "photon-check-baseline.json")
    try:
        baseline = (load_baseline(baseline_path)
                    if os.path.exists(baseline_path) else [])
        report = run_check([pkg], baseline=baseline, repo_root=root)
        out = {
            "version": __version__,
            "files_checked": report["files_checked"],
            "findings": len(report["findings"]),
            "suppressed": len(report["suppressed"]),
            # the concurrency passes' share (PT4xx), so a bench result
            # records the threading-lint posture it was measured under
            "concurrency_findings": sum(
                1 for f in report["findings"]
                if f.code.startswith("PT4")),
            # the numerics passes' share (PN5xx): the bit-determinism
            # posture the bench's parity-bearing numbers rode on
            "numerics_findings": sum(
                1 for f in report["findings"]
                if f.code.startswith("PN5")),
        }
    except Exception as e:  # bench must never die on a lint bug
        out = {"version": __version__, "error": str(e)}
    _REPO_REPORT_CACHE[root] = out
    return out
