"""Entity-affinity front door, end to end over real sockets: owner
routing pins each replica's paged table to its owned slice, mixed-owner
batches scatter and merge in row order, a dead owner fails over with the
``routing: fallback`` label (never a 5xx) and the epoch re-owns its
slice, a rejoin gets its moved ids prefetched before the commit, hedge
duplicates that win on a non-owner are labeled + counted without
tripping the owner's breaker, and the ``fd.route`` / ``fd.membership``
fault sites degrade routing without failing requests."""

import asyncio
import json

import numpy as np
import pytest

from photon_ml_tpu.parallel import fault_injection
from photon_ml_tpu.parallel.fault_injection import Fault
from tests.conftest import serving_rows
from tests.test_serving_async import _http, _service


@pytest.fixture(autouse=True)
def _clean_faults():
    fault_injection.clear()
    yield
    fault_injection.clear()


def _door_setup(saved_game_model, n_replicas=2):
    """N independent in-process replica services over the same saved
    model directory (each with its own session/paged table)."""
    services = []
    bundle = None
    for _ in range(n_replicas):
        svc, bundle = _service(saved_game_model)
        services.append(svc)
    return services, bundle


async def _start_door(services, **door_kw):
    from photon_ml_tpu.serve import AsyncFrontDoor, AsyncScoringServer

    servers = []
    for svc in services:
        servers.append(await AsyncScoringServer(svc).start())
    door = await AsyncFrontDoor(
        [f"{s.host}:{s.port}" for s in servers],
        affinity=True, **door_kw).start()
    return door, servers


def test_owner_routing_pins_owned_slices(saved_game_model):
    """Single-owner requests land on the owning replica; after traffic
    over every entity, each replica's paged table holds ONLY its owned
    slice — the aggregate working set is partitioned, not mirrored."""
    services, bundle = _door_setup(saved_game_model)
    n_ent = bundle["n_entities"]
    ref_svc, _ = _service(saved_game_model)

    async def run():
        door, servers = await _start_door(services)
        out = {"scores": {}, "status": []}
        assert (await door.sync_membership())["committed"] is True
        out["epoch"] = door.membership_epoch
        out["addrs"] = [f"{s.host}:{s.port}" for s in servers]
        for ent in range(n_ent):
            idx = [i for i in range(len(bundle["uid"]))
                   if bundle["uid"][i] == ent][:4]
            if not idx:
                continue
            rows = serving_rows(bundle, idx)
            status, _h, body = await _http(door.host, door.port, "POST",
                                           "/score", {"rows": rows})
            out["status"].append(status)
            out["scores"][ent] = (idx, body["scores"])
        out["stats"] = door.stats()
        await door.aclose()
        for s in servers:
            await s.aclose()
        return out

    out = asyncio.run(run())
    assert set(out["status"]) == {200}
    epoch = out["epoch"]
    assert epoch.num_shards == 2
    # scores match the un-sharded reference session
    for ent, (idx, scores) in out["scores"].items():
        ref = ref_svc.session.score_rows(serving_rows(bundle, idx))
        np.testing.assert_allclose(scores, np.asarray(ref),
                                   rtol=0, atol=1e-9)
    aff = out["stats"]["affinity"]
    assert aff["ownerRouted"] > 0
    assert aff["fallbackServed"] == 0
    # each replica paged ONLY its owned slice (replicas are sorted by
    # address in the epoch, so map each service back through its addr)
    for svc, addr in zip(services, out["addrs"]):
        shard = epoch.replicas.index(addr)
        svc.session.drain_installs()
        resident = svc.session._state.paged["per-user"].resident_ids()
        assert resident, "owner traffic must page the owned slice"
        for eid in resident:
            assert int(epoch.owner_of([eid])[0]) == shard
        view = svc.session.membership
        assert view.active and view.shard_index == shard


def test_scatter_merge_row_order_and_components(saved_game_model):
    """A batch spanning both owners (plus a row with no entity id) is
    scattered by owner and reassembled in request order: scores, echoed
    uids, per-coordinate components, and the scatter routing label."""
    services, bundle = _door_setup(saved_game_model)
    ref_svc, _ = _service(saved_game_model)
    idx = list(range(12))
    rows = serving_rows(bundle, idx)
    for pos, r in enumerate(rows):
        r["uid"] = f"row-{pos}"
    rows.append({"features": [{"name": "g0", "value": 1.0}],
                 "uid": "row-free"})  # no entityIds: rides along

    async def run():
        door, servers = await _start_door(services)
        await door.sync_membership()
        status, _h, body = await _http(
            door.host, door.port, "POST", "/score",
            {"rows": rows, "perCoordinate": True})
        stats = door.stats()
        await door.aclose()
        for s in servers:
            await s.aclose()
        return status, body, stats

    status, body, stats = asyncio.run(run())
    assert status == 200
    assert body["routing"] == "scatter"
    assert stats["affinity"]["scattered"] == 1
    assert body["uids"] == [f"row-{p}" for p in range(12)] + ["row-free"]
    ref, parts = ref_svc.session.score_rows(rows, True)
    np.testing.assert_allclose(body["scores"], np.asarray(ref),
                               rtol=0, atol=1e-9)
    for name, vals in parts.items():
        np.testing.assert_allclose(body["scoreComponents"][name],
                                   np.asarray(vals), rtol=0, atol=1e-9)


def test_owner_death_fails_over_then_reowns_then_rejoins(
        saved_game_model):
    """Kill one replica: its entities' requests fail over (200 with the
    fallback routing label, owner_miss{breaker}, never a 5xx), the next
    epoch re-owns everything onto the survivor, and a rejoin commits an
    epoch that prefetched the moved ids into the joiner BEFORE routing
    to it."""
    from photon_ml_tpu.serve import AsyncScoringServer

    services, bundle = _door_setup(saved_game_model)
    n_ent = bundle["n_entities"]

    async def run():
        door, servers = await _start_door(services,
                                          breaker_threshold=1)
        await door.sync_membership()
        epoch1 = door.membership_epoch
        # warm traffic over every entity (also fills the hot tracker)
        ents = {}
        for ent in range(n_ent):
            idx = [i for i in range(len(bundle["uid"]))
                   if bundle["uid"][i] == ent][:2]
            if idx:
                ents[ent] = serving_rows(bundle, idx)
                await _http(door.host, door.port, "POST", "/score",
                            {"rows": ents[ent]})
        # kill the shard-1 owner (server drain also closes its service)
        dead_addr = epoch1.replicas[1]
        dead_i = next(i for i, s in enumerate(servers)
                      if f"{s.host}:{s.port}" == dead_addr)
        # abrupt kill (short drain — the door still holds pooled
        # connections to the victim; waiting out the full drain window
        # would model a graceful leave, not a crash)
        await servers[dead_i].aclose(drain_timeout_s=0.2)
        dead_owned = [e for e in ents
                      if int(epoch1.owner_of([str(e)])[0]) == 1]
        statuses, labels = [], []
        for e in dead_owned:
            st, _h, body = await _http(door.host, door.port, "POST",
                                       "/score", {"rows": ents[e]})
            statuses.append(st)
            labels.append(body.get("routing"))
        miss_after_kill = dict(door.owner_miss)
        # converge the epoch onto the survivor
        sync = await door.sync_membership()
        epoch2 = door.membership_epoch
        # rejoin: a brand-new replica process (fresh service, cold
        # paged table) joins on a new port — the prefetch-before-commit
        # contract must hand it its slice warm
        svc_new, _b = _service(saved_game_model)
        revived = await AsyncScoringServer(svc_new).start()
        join_addr = f"{revived.host}:{revived.port}"
        st_join, _h, join_body = await _http(
            door.host, door.port, "POST", "/fd/admin/join",
            {"address": join_addr})
        epoch3 = door.membership_epoch
        svc_new.session.drain_installs()
        joiner_resident = list(
            svc_new.session._state.paged["per-user"].resident_ids())
        # post-join traffic: still zero 5xx, owner-routed
        post = []
        for e in ents:
            st, _h, _b = await _http(door.host, door.port, "POST",
                                     "/score", {"rows": ents[e]})
            post.append(st)
        stats = door.stats()
        await door.aclose()
        for i, s in enumerate(servers):
            if i != dead_i:
                await s.aclose()
        await revived.aclose()
        return dict(statuses=statuses, labels=labels, sync=sync,
                    epoch1=epoch1, epoch2=epoch2, epoch3=epoch3,
                    miss=miss_after_kill, st_join=st_join,
                    join_body=join_body, post=post, stats=stats,
                    dead_addr=dead_addr, join_addr=join_addr,
                    joiner_resident=joiner_resident)

    out = asyncio.run(run())
    # availability 1.0 through the kill: every response is a 200, and
    # the ones that missed their owner say so
    assert set(out["statuses"]) == {200}
    assert "fallback" in out["labels"]
    assert out["miss"]["breaker"] >= 1
    # re-owned onto the survivor (a background rebalance kicked from
    # the request path may already have converged — then the explicit
    # sync reports "unchanged"; either way the epoch excludes the dead)
    sync = out["sync"]
    assert sync["committed"] or sync.get("reason") == "unchanged"
    assert out["epoch2"].num_shards == 1
    assert out["dead_addr"] not in out["epoch2"].replicas
    # rejoin committed a wider epoch and prefetched the joiner's slice
    assert out["st_join"] == 200
    assert out["join_body"]["rebalance"]["committed"] is True
    assert out["epoch3"].num_shards == 2
    assert out["join_addr"] in out["epoch3"].replicas
    join_idx = out["epoch3"].replicas.index(out["join_addr"])
    moved_hot = [e for e in out["joiner_resident"]
                 if int(out["epoch3"].owner_of([e])[0]) == join_idx]
    assert moved_hot, "join must arrive with prefetched owned pages"
    assert set(out["post"]) == {200}
    assert out["stats"]["affinity"]["prefetchedEntities"] > 0


def test_hedge_win_on_non_owner_is_fallback_not_owner_failure(
        saved_game_model):
    """Force the owner to stall past the hedge delay: the duplicate on
    the non-owner wins, the response is fallback-labeled, the miss is
    counted under reason=hedge, and the owner's breaker stays closed
    (a cancelled hedge loser is not a failure)."""
    services, bundle = _door_setup(saved_game_model)
    rows = serving_rows(bundle, [0])
    ent = str(bundle["uid"][0])

    async def run():
        door, servers = await _start_door(services, hedge_enabled=True)
        await door.sync_membership()
        epoch = door.membership_epoch
        owner = door._backend_by_address(epoch.owner_address(ent))
        door._hedge_delay = lambda backend: 0.005
        real_exchange = door._backend_exchange

        async def stalling(backend, request):
            if backend is owner and b"POST /score" in request:
                await asyncio.sleep(0.5)
            return await real_exchange(backend, request)

        door._backend_exchange = stalling
        status, _h, body = await _http(door.host, door.port, "POST",
                                       "/score", {"rows": rows})
        out = dict(status=status, body=body, stats=door.stats(),
                   owner_state=owner.state, owner_fails=owner.fails)
        await door.aclose()
        for s in servers:
            await s.aclose()
        return out

    out = asyncio.run(run())
    assert out["status"] == 200
    assert out["body"]["routing"] == "fallback"
    aff = out["stats"]["affinity"]
    assert aff["ownerMiss"]["hedge"] == 1
    assert out["stats"]["hedgeWins"] == 1
    assert out["owner_state"] == "closed"
    assert out["owner_fails"] == 0


def test_fd_route_fault_degrades_to_plain_proxy(saved_game_model):
    """An armed ``fd.route`` fault (the chaos harness's routing fault
    site) must degrade affinity to the dumb least-loaded proxy — the
    request still answers 200."""
    services, bundle = _door_setup(saved_game_model)
    rows = serving_rows(bundle, [0, 1])

    async def run():
        door, servers = await _start_door(services)
        await door.sync_membership()
        fault_injection.install([
            Fault("fd.route", kind="raise", at=-1,
                  message="routing blackout")])
        status, _h, body = await _http(door.host, door.port, "POST",
                                       "/score", {"rows": rows})
        fault_injection.clear()
        stats = door.stats()
        await door.aclose()
        for s in servers:
            await s.aclose()
        return status, body, stats

    status, body, stats = asyncio.run(run())
    assert status == 200
    assert "scores" in body and "routing" not in body
    assert stats["affinity"]["routeFaults"] >= 1
    assert stats["affinity"]["ownerRouted"] == 0


def test_fd_membership_fault_blocks_commit_not_serving(saved_game_model):
    """An armed ``fd.membership`` fault makes the rebalance fail closed
    (counted, no commit, epoch unchanged) while scoring keeps
    answering — a broken control plane never takes down the data
    plane."""
    services, bundle = _door_setup(saved_game_model)
    rows = serving_rows(bundle, [0, 1])

    async def run():
        door, servers = await _start_door(services)
        fault_injection.install([
            Fault("fd.membership", kind="raise", at=-1,
                  message="membership blackout")])
        sync = await door.sync_membership()
        status, _h, _body = await _http(door.host, door.port, "POST",
                                        "/score", {"rows": rows})
        fault_injection.clear()
        recovered = await door.sync_membership()
        stats = door.stats()
        await door.aclose()
        for s in servers:
            await s.aclose()
        return sync, status, recovered, stats

    sync, status, recovered, stats = asyncio.run(run())
    assert sync["committed"] is False and "error" in sync
    assert status == 200
    assert recovered["committed"] is True
    assert stats["affinity"]["membershipFaults"] >= 1


def test_membership_endpoint_contract(saved_game_model):
    """``POST /admin/membership`` on a replica: apply + prefetch in one
    round trip, stale epochs answer ``applied: false``, malformed
    payloads 400, and ``/healthz`` reports the applied epoch."""
    services, bundle = _door_setup(saved_game_model, n_replicas=1)
    svc = services[0]
    n_ent = bundle["n_entities"]

    async def run():
        from photon_ml_tpu.serve import AsyncScoringServer

        server = await AsyncScoringServer(svc).start()
        h, p = server.host, server.port
        ids = [str(i) for i in range(n_ent)]
        applied = await _http(h, p, "POST", "/admin/membership",
                              {"epoch": 5, "replicas": ["a:1", "b:2"],
                               "selfIndex": 0,
                               "prefetchEntityIds": ids})
        stale = await _http(h, p, "POST", "/admin/membership",
                            {"epoch": 4, "numShards": 2,
                             "shardIndex": 1})
        bad = await _http(h, p, "POST", "/admin/membership",
                          {"replicas": []})
        health = await _http(h, p, "GET", "/healthz")
        await server.aclose()
        return applied, stale, bad, health

    applied, stale, bad, health = asyncio.run(run())
    assert applied[0] == 200 and applied[2]["applied"] is True
    assert applied[2]["membership"]["epoch"] == 5
    # the replica prefetches EXACTLY its owned slice of the ids pushed
    from photon_ml_tpu.parallel.entity_shard import serving_owner_of

    owners = serving_owner_of([str(i) for i in range(n_ent)], 2, "auto")
    expected = sum(1 for o in owners if int(o) == 0)
    assert expected > 0  # fixture sanity: shard 0 owns something
    assert applied[2]["prefetched"] == expected
    assert applied[2]["prefetchBytes"] > 0
    assert stale[0] == 200 and stale[2]["applied"] is False
    assert stale[2]["membership"]["epoch"] == 5  # unchanged
    assert bad[0] == 400
    assert health[2]["membership"]["epoch"] == 5
    # only the owned slice was prefetched
    view = svc.session.membership
    resident = svc.session._state.paged["per-user"].resident_ids()
    assert resident and all(view.owned(e) for e in resident)
