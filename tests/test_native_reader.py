"""Native C++ Avro ingestion: parity with the pure-Python codec.

The native decoder (native/avro_decoder.cpp + io/native_reader.py) is the
host-side hot path (SURVEY.md §7); these tests pin its outputs to the
Python reader's on randomized data across index-map backends, codecs and
schema shapes, and check the fallback triggers for unsupported shapes.
"""

import os

import numpy as np
import pytest

from photon_ml_tpu.io.data_reader import (
    InputColumnsNames,
    read_training_examples,
    write_training_examples,
)
from photon_ml_tpu.io.hashing import HashingIndexMap
from photon_ml_tpu.io.index_map import IndexMap
from photon_ml_tpu.io.native_reader import (
    NativeUnsupported,
    read_training_examples_native,
)


def _random_rows(rng, n, vocab, max_k=8):
    rows = []
    for _ in range(n):
        k = int(rng.integers(0, max_k))
        feats = []
        for _ in range(k):
            name = f"f{int(rng.integers(0, vocab))}"
            term = f"t{int(rng.integers(0, 3))}" if rng.random() < 0.5 else ""
            feats.append((name, term, float(rng.normal())))
        rows.append(feats)
    return rows


def _write(tmp_path, rng, n=200, codec="deflate", with_entities=True,
           labels=True):
    rows = _random_rows(rng, n, vocab=40)
    path = str(tmp_path / "data.avro")
    entity_ids = ({"userId": [f"u{int(rng.integers(0, 9))}" for _ in range(n)]}
                  if with_entities else None)
    write_training_examples(
        path, rows,
        labels=rng.integers(0, 2, n).astype(float) if labels else None,
        offsets=rng.normal(size=n),
        weights=rng.random(n) + 0.5,
        entity_ids=entity_ids,
        uids=[f"row-{i}" for i in range(n)],
        codec=codec,
    )
    return path, rows


def _build_index_map(rows, add_intercept=True):
    from photon_ml_tpu.io.schemas import feature_key

    keys = sorted({feature_key(name, term)
                   for row in rows for name, term, _ in row})
    return IndexMap({k: i for i, k in enumerate(keys)},
                    add_intercept=add_intercept)


def _assert_same(a, b):
    fa, la, oa, wa, ea, ua = a
    fb, lb, ob, wb, eb, ub = b
    np.testing.assert_allclose(la, lb, rtol=0, atol=0)
    np.testing.assert_allclose(oa, ob)
    np.testing.assert_allclose(wa, wb)
    assert ua == ub
    assert set(ea) == set(eb)
    for c in ea:
        assert list(ea[c]) == list(eb[c])
    assert set(fa) == set(fb)
    for s in fa:
        assert fa[s].dim == fb[s].dim
        # padded layouts agree exactly (same per-row order and padding rule)
        np.testing.assert_array_equal(fa[s].indices, fb[s].indices)
        np.testing.assert_allclose(fa[s].values, fb[s].values)


@pytest.mark.parametrize("codec", ["null", "deflate"])
def test_native_parity_in_memory_map(tmp_path, rng, codec):
    path, rows = _write(tmp_path, rng, codec=codec)
    imap = _build_index_map(rows)
    cols = InputColumnsNames()
    native = read_training_examples_native(
        path, {"global": imap}, ["userId"], cols, True)
    os.environ["PHOTON_ML_TPU_NO_NATIVE"] = "1"
    try:
        python = read_training_examples(path, imap, ["userId"])
    finally:
        del os.environ["PHOTON_ML_TPU_NO_NATIVE"]
    _assert_same(native, python)


def test_native_parity_hashing_map(tmp_path, rng):
    path, rows = _write(tmp_path, rng)
    imap = HashingIndexMap(512)
    cols = InputColumnsNames()
    native = read_training_examples_native(
        path, {"global": imap}, [], cols, True)
    os.environ["PHOTON_ML_TPU_NO_NATIVE"] = "1"
    try:
        python = read_training_examples(path, imap)
    finally:
        del os.environ["PHOTON_ML_TPU_NO_NATIVE"]
    _assert_same(native, python)


def test_native_parity_persistent_store(tmp_path, rng):
    from photon_ml_tpu.io.paldb import PersistentIndexMap

    path, rows = _write(tmp_path, rng)
    imap = _build_index_map(rows)
    store = PersistentIndexMap.build(imap.forward,
                                     str(tmp_path / "store.fis"))
    cols = InputColumnsNames()
    native = read_training_examples_native(
        path, {"global": store}, ["userId"], cols, True)
    os.environ["PHOTON_ML_TPU_NO_NATIVE"] = "1"
    try:
        python = read_training_examples(path, store, ["userId"])
    finally:
        del os.environ["PHOTON_ML_TPU_NO_NATIVE"]
    _assert_same(native, python)


def test_native_unlabeled_and_default_path(tmp_path, rng):
    path, rows = _write(tmp_path, rng, labels=False)
    imap = _build_index_map(rows)
    # default read_training_examples dispatches to the native path
    out = read_training_examples(path, imap, require_response=False)
    assert np.isnan(out[1]).all()
    with pytest.raises(ValueError, match="training data must be labeled"):
        read_training_examples(path, imap, require_response=True)


def test_native_multi_shard(tmp_path, rng):
    path, rows = _write(tmp_path, rng)
    full = _build_index_map(rows)
    # second shard sees only even-numbered features (per-shard selection)
    partial = _build_index_map(
        [[(n, t, v) for n, t, v in row if int(n[1:]) % 2 == 0]
         for row in rows])
    maps = {"all": full, "even": partial}
    cols = InputColumnsNames()
    native = read_training_examples_native(path, maps, [], cols, True)
    os.environ["PHOTON_ML_TPU_NO_NATIVE"] = "1"
    try:
        python = read_training_examples(path, maps)
    finally:
        del os.environ["PHOTON_ML_TPU_NO_NATIVE"]
    _assert_same(native, python)


def test_native_rejects_unsupported_schema(tmp_path, rng):
    from photon_ml_tpu.io.avro import write_avro_file

    # a record whose response is [null, string] cannot be captured natively
    schema = {
        "type": "record", "name": "Odd",
        "fields": [
            {"name": "response", "type": ["null", "string"]},
            {"name": "features", "type": {"type": "array", "items": {
                "type": "record", "name": "F",
                "fields": [{"name": "name", "type": "string"},
                           {"name": "term", "type": "string"},
                           {"name": "value", "type": "double"}]}}},
        ],
    }
    path = str(tmp_path / "odd.avro")
    write_avro_file(path, [{"response": "yes", "features": []}], schema)
    imap = IndexMap({"f0": 0})
    with pytest.raises(NativeUnsupported):
        read_training_examples_native(
            path, {"global": imap}, [], InputColumnsNames(), False)


def test_native_accepts_empty_entity_value(tmp_path, rng):
    """A present-but-empty entity id must round-trip as '' (only truly
    absent keys raise), matching the Python path."""
    path = str(tmp_path / "empty-ent.avro")
    write_training_examples(
        path, [[("f0", "", 1.0)], [("f1", "", 2.0)]], labels=[0.0, 1.0],
        entity_ids={"userId": ["", "u1"]})
    imap = _build_index_map([[("f0", "", 1.0)], [("f1", "", 2.0)]])
    native = read_training_examples_native(
        path, {"global": imap}, ["userId"], InputColumnsNames(), True)
    assert list(native[4]["userId"]) == ["", "u1"]


def test_native_missing_features_field_falls_back(tmp_path):
    """Schema without a features field: native path must refuse (fallback
    then raises the Python KeyError) rather than yield intercept-only rows."""
    from photon_ml_tpu.io.avro import write_avro_file

    schema = {"type": "record", "name": "NoFeat",
              "fields": [{"name": "response", "type": "double"}]}
    path = str(tmp_path / "nofeat.avro")
    write_avro_file(path, [{"response": 1.0}], schema)
    imap = _build_index_map([])
    with pytest.raises(NativeUnsupported):
        read_training_examples_native(
            path, {"global": imap}, [], InputColumnsNames(), True)
    with pytest.raises(KeyError):
        read_training_examples(path, imap)


def test_native_no_temp_store_leak(tmp_path, rng):
    """Temp .fis stores built for in-memory maps are removed even when a
    later shard's backend is unsupported."""
    import glob
    import tempfile

    class Opaque:
        size = 3
        intercept_index = -1

        def index_of(self, name, term=""):
            return None

    path, rows = _write(tmp_path, rng, n=10)
    imap = _build_index_map(rows)
    before = set(glob.glob(os.path.join(tempfile.gettempdir(), "*.fis")))
    with pytest.raises(NativeUnsupported):
        read_training_examples_native(
            path, {"a": imap, "b": Opaque()}, [], InputColumnsNames(), True)
    after = set(glob.glob(os.path.join(tempfile.gettempdir(), "*.fis")))
    assert before == after


def test_native_uid_shapes(tmp_path):
    """uid as plain string, single-branch union, and [null,string,long]
    union all decode correctly (Avro writes a branch index for every union,
    even 1-branch ones)."""
    from photon_ml_tpu.io.avro import write_avro_file

    feat = {"type": "array", "items": {
        "type": "record", "name": "F",
        "fields": [{"name": "name", "type": "string"},
                   {"name": "term", "type": "string"},
                   {"name": "value", "type": "double"}]}}
    for uid_type, uid_val, expect in [
        ("string", "u1", "u1"),
        (["string"], "u2", "u2"),
        (["long"], 7, 7),
        (["null", "string", "long"], 42, 42),
        (["null", "string", "long"], None, None),
    ]:
        schema = {"type": "record", "name": "R", "fields": [
            {"name": "uid", "type": uid_type},
            {"name": "response", "type": "double"},
            {"name": "features", "type": feat},
        ]}
        path = str(tmp_path / "uid.avro")
        write_avro_file(path, [{
            "uid": uid_val, "response": 1.0,
            "features": [{"name": "f0", "term": "", "value": 3.0}],
        }], schema)
        imap = _build_index_map([[("f0", "", 3.0)]])
        out = read_training_examples_native(
            path, {"global": imap}, [], InputColumnsNames(), True)
        assert out[5] == [expect], f"uid_type={uid_type}"
        assert out[1][0] == 1.0
        np.testing.assert_allclose(out[0]["global"].values[0][0], 3.0)


def test_native_fuzz_many_shapes(tmp_path, rng):
    """Randomized round-trips across sizes (incl. empty feature rows)."""
    for trial in range(4):
        n = int(rng.integers(1, 60))
        path, rows = _write(tmp_path, rng, n=n,
                            codec="null" if trial % 2 else "deflate",
                            with_entities=trial % 2 == 0)
        imap = _build_index_map(rows)
        ents = ["userId"] if trial % 2 == 0 else []
        native = read_training_examples_native(
            path, {"global": imap}, ents, InputColumnsNames(), True)
        os.environ["PHOTON_ML_TPU_NO_NATIVE"] = "1"
        try:
            python = read_training_examples(path, imap, ents)
        finally:
            del os.environ["PHOTON_ML_TPU_NO_NATIVE"]
        _assert_same(native, python)
