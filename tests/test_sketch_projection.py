"""Random-projection (count-sketch) projector for random effects: the
reference's RandomProjection role (SURVEY.md §3.2 projector row). Training,
scoring, save/load round-trip, and warm start in the sketched space."""

import numpy as np
import pytest

from photon_ml_tpu.estimators import GameTransformer
from photon_ml_tpu.evaluation import get_evaluator
from photon_ml_tpu.game.data import SketchProjection, build_random_effect_data
from photon_ml_tpu.game.descent import CoordinateConfig, CoordinateDescent
from photon_ml_tpu.testing import game_dataset_from_synthetic, synthetic_game_data


def test_sketch_projection_stable_and_signed():
    sk = SketchProjection(64, seed=1)
    gids = np.arange(1000)
    s1, sg1 = sk.slots_signs(gids)
    s2, sg2 = sk.slots_signs(gids)
    np.testing.assert_array_equal(s1, s2)
    np.testing.assert_array_equal(sg1, sg2)
    assert s1.min() >= 0 and s1.max() < 64
    assert set(np.unique(sg1)) == {-1.0, 1.0}
    # roughly balanced signs and spread slots
    assert 0.4 < (sg1 > 0).mean() < 0.6
    assert len(np.unique(s1)) == 64
    # different seed, different mapping
    s3, _ = SketchProjection(64, seed=2).slots_signs(gids)
    assert (s1 != s3).any()


def test_build_random_effect_data_sketch_shapes(rng):
    X = rng.normal(size=(60, 12)) * (rng.random((60, 12)) < 0.5)
    y = (rng.random(60) < 0.5).astype(float)
    ents = rng.integers(0, 5, size=60)
    data = build_random_effect_data(
        X, y, np.ones(60), ents, num_buckets=2,
        projection="random", projection_dim=8,
    )
    for b in data.buckets:
        assert b.local_dim == 8
        assert (b.projection == -1).all()
        assert isinstance(b.local_maps[0], SketchProjection)
    with pytest.raises(ValueError, match="projection_dim"):
        build_random_effect_data(X, y, np.ones(60), ents, projection="random")


def _game_configs(projection_dim=None):
    re_kwargs = {}
    if projection_dim:
        re_kwargs = {"projection": "random", "projection_dim": projection_dim}
    return [
        CoordinateConfig("fixed", coordinate_type="fixed",
                         feature_shard="global", reg_type="l2",
                         reg_weight=0.1, max_iters=50),
        CoordinateConfig("per-user", coordinate_type="random",
                         feature_shard="entity", entity_column="userId",
                         reg_type="l2", reg_weight=1.0, max_iters=30,
                         **re_kwargs),
    ]


def test_sketched_random_effect_learns(tmp_path):
    data = synthetic_game_data({"userId": 12}, seed=4)
    train = game_dataset_from_synthetic(data)
    # sketch width 8 over a 3-dim entity space: projection loses little
    model, _ = CoordinateDescent(_game_configs(projection_dim=8),
                                 task="logistic", n_iterations=2).run(train)
    auc = get_evaluator("auc").evaluate(
        np.asarray(GameTransformer(model).transform(train)),
        train.labels, train.weights)
    assert auc > 0.8, auc

    bucket = model["per-user"].buckets[0]
    assert bucket.sketch is not None

    # save / load round-trip preserves scores exactly
    from photon_ml_tpu.io.index_map import IndexMap
    from photon_ml_tpu.io.model_io import load_game_model, save_game_model

    d_g = data.features["global"].shape[1]
    d_u = data.features["entity"].shape[1]
    imaps = {
        "global": IndexMap({f"g{j}": j for j in range(d_g)}),
        "entity": IndexMap({f"u{j}": j for j in range(d_u)}),
    }
    save_game_model(model, str(tmp_path / "m"), imaps)
    loaded = load_game_model(str(tmp_path / "m"))
    assert loaded["per-user"].buckets[0].sketch == bucket.sketch
    s_orig = np.asarray(GameTransformer(model).transform(train))
    s_loaded = np.asarray(GameTransformer(loaded).transform(train))
    np.testing.assert_allclose(s_loaded, s_orig, rtol=1e-6, atol=1e-7)

    # warm start from the loaded sketched model reproduces its scores at init
    cd = CoordinateDescent(_game_configs(projection_dim=8), task="logistic",
                          n_iterations=1)
    model2, history = cd.run(train, warm_start=loaded,
                             locked=["fixed", "per-user"])
    s_warm = np.asarray(GameTransformer(model2).transform(train))
    np.testing.assert_allclose(s_warm, s_orig, rtol=1e-5, atol=1e-6)
