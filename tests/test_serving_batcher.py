"""MicroBatcher: coalescing, deadlines, bounded-queue load shedding (no
hangs), stuck-batch watchdog, and result slicing. All tests drive fake
score functions — no model, no device."""

import threading
import time

import numpy as np
import pytest


def _echo_score(rows, per_coordinate=False):
    scores = np.asarray([float(r["v"]) for r in rows])
    if per_coordinate:
        return scores, {"fixed": scores * 2}
    return scores


def _rows(*vals):
    return [{"v": v} for v in vals]


def test_coalesces_requests_into_batches():
    from photon_ml_tpu.serve import MicroBatcher

    batches = []
    gate = threading.Event()

    def score(rows, per_coordinate=False):
        gate.wait(5.0)
        batches.append(len(rows))
        return _echo_score(rows)

    b = MicroBatcher(score, max_batch=8, max_delay_ms=50.0, max_queue=64)
    try:
        pending = [b.submit(_rows(float(i))) for i in range(8)]
        gate.set()  # all 8 one-row requests admitted before scoring runs
        results = [p.result(10.0) for p in pending]
        assert [r[0] for r in results] == [float(i) for i in range(8)]
        # the first batch may dispatch with however many had arrived when
        # the worker woke, but far fewer executions than requests
        assert sum(batches) == 8
        assert len(batches) < 8
        assert max(batches) <= 8
    finally:
        b.close()


def test_deadline_dispatches_partial_batch():
    from photon_ml_tpu.serve import MicroBatcher

    b = MicroBatcher(_echo_score, max_batch=64, max_delay_ms=20.0,
                     max_queue=8)
    try:
        t0 = time.monotonic()
        out = b.score(_rows(3.0), timeout=10.0)
        elapsed = time.monotonic() - t0
        assert out[0] == 3.0
        assert elapsed < 5.0  # deadline fired; nothing waited for 64 rows
    finally:
        b.close()


def test_queue_full_sheds_immediately():
    from photon_ml_tpu.serve import MicroBatcher, QueueFullError

    release = threading.Event()

    def blocked(rows, per_coordinate=False):
        release.wait(10.0)
        return _echo_score(rows)

    b = MicroBatcher(blocked, max_batch=1, max_delay_ms=1.0, max_queue=2)
    try:
        first = b.submit(_rows(1.0))  # worker takes it, blocks in score
        time.sleep(0.05)
        held = [b.submit(_rows(2.0)), b.submit(_rows(3.0))]  # fills queue
        t0 = time.monotonic()
        with pytest.raises(QueueFullError, match="shed"):
            b.submit(_rows(4.0))
        assert time.monotonic() - t0 < 1.0  # shed, not queued/blocked
        release.set()
        assert first.result(10.0)[0] == 1.0
        assert [h.result(10.0)[0] for h in held] == [2.0, 3.0]
    finally:
        release.set()
        b.close()


def test_shed_is_counted():
    from photon_ml_tpu.serve import MicroBatcher, QueueFullError
    from photon_ml_tpu.serve.metrics import ServingMetrics

    release = threading.Event()
    metrics = ServingMetrics()

    def blocked(rows, per_coordinate=False):
        release.wait(10.0)
        return _echo_score(rows)

    b = MicroBatcher(blocked, max_batch=1, max_delay_ms=1.0, max_queue=1,
                     metrics=metrics)
    try:
        b.submit(_rows(1.0))
        time.sleep(0.05)
        b.submit(_rows(2.0))
        with pytest.raises(QueueFullError):
            b.submit(_rows(3.0))
        assert metrics.snapshot()["shed_total"] == 1
    finally:
        release.set()
        b.close()


def test_watchdog_fails_stuck_batch_and_worker_survives():
    from photon_ml_tpu.serve import BatchWatchdogTimeout, MicroBatcher
    from photon_ml_tpu.parallel.resilience import WatchdogTimeout

    hang = threading.Event()
    calls = []

    def sometimes_stuck(rows, per_coordinate=False):
        calls.append(len(rows))
        if rows[0]["v"] == -1.0:
            hang.wait(30.0)  # simulated wedged execution
        return _echo_score(rows)

    b = MicroBatcher(sometimes_stuck, max_batch=4, max_delay_ms=1.0,
                     max_queue=8, watchdog_s=0.2)
    try:
        stuck = b.submit(_rows(-1.0))
        with pytest.raises(BatchWatchdogTimeout, match="watchdog"):
            stuck.result(10.0)
        assert isinstance(stuck._error, WatchdogTimeout)  # PR-1 taxonomy
        # the worker abandoned the wedged execution and keeps serving
        assert b.score(_rows(5.0), timeout=10.0)[0] == 5.0
    finally:
        hang.set()
        b.close()


def test_multi_row_requests_slice_in_order():
    from photon_ml_tpu.serve import MicroBatcher

    b = MicroBatcher(_echo_score, max_batch=8, max_delay_ms=20.0,
                     max_queue=16)
    try:
        p1 = b.submit(_rows(1.0, 2.0, 3.0))
        p2 = b.submit(_rows(10.0), per_coordinate=True)
        p3 = b.submit(_rows(20.0, 30.0))
        assert list(p1.result(10.0)) == [1.0, 2.0, 3.0]
        scores, parts = p2.result(10.0)
        assert list(scores) == [10.0]
        assert list(parts["fixed"]) == [20.0]
        assert list(p3.result(10.0)) == [20.0, 30.0]
    finally:
        b.close()


def test_oversized_and_empty_requests_rejected():
    from photon_ml_tpu.serve import MicroBatcher

    b = MicroBatcher(_echo_score, max_batch=2, max_delay_ms=1.0,
                     max_queue=4)
    try:
        with pytest.raises(ValueError, match="max_batch"):
            b.submit(_rows(1.0, 2.0, 3.0))
        with pytest.raises(ValueError, match="empty"):
            b.submit([])
        # a request that would overflow the current batch is carried to
        # the next execution, not dropped
        p1 = b.submit(_rows(1.0))
        p2 = b.submit(_rows(2.0, 3.0))
        assert list(p1.result(10.0)) == [1.0]
        assert list(p2.result(10.0)) == [2.0, 3.0]
    finally:
        b.close()


def test_scoring_error_propagates_to_all_requests_of_batch():
    from photon_ml_tpu.serve import MicroBatcher

    def boom(rows, per_coordinate=False):
        raise RuntimeError("synthetic scoring failure")

    b = MicroBatcher(boom, max_batch=4, max_delay_ms=20.0, max_queue=8)
    try:
        p1 = b.submit(_rows(1.0))
        p2 = b.submit(_rows(2.0))
        for p in (p1, p2):
            with pytest.raises(RuntimeError, match="synthetic"):
                p.result(10.0)
    finally:
        b.close()


def test_close_rejects_new_submissions():
    from photon_ml_tpu.serve import MicroBatcher

    b = MicroBatcher(_echo_score, max_batch=2, max_delay_ms=1.0,
                     max_queue=4)
    b.close()
    with pytest.raises(RuntimeError, match="closed"):
        b.submit(_rows(1.0))


def test_queue_full_carries_retry_after_hint():
    """429s must tell the client HOW LONG to back off: retry_after_s
    derives from queue depth x the batching deadline and is floored at
    one deadline."""
    from photon_ml_tpu.serve import MicroBatcher, QueueFullError

    gate = threading.Event()

    def slow(rows, per_coordinate=False):
        gate.wait(5.0)
        return _echo_score(rows)

    b = MicroBatcher(slow, max_batch=2, max_delay_ms=10.0, max_queue=2)
    try:
        for i in range(3):  # worker holds one, queue holds two
            b.submit(_rows(float(i)))
            time.sleep(0.02 if i == 0 else 0.0)
        with pytest.raises(QueueFullError) as exc:
            b.submit(_rows(9.0))
        assert exc.value.cause == "queue_full"
        assert exc.value.retry_after_s >= b.max_delay_s
    finally:
        gate.set()
        b.close()


def test_deadline_shed_splits_metrics_by_cause():
    """Requests whose deadline expires while queued are shed by the
    worker with cause='deadline'; the metrics split the two shed causes
    and shed_total stays their sum."""
    from photon_ml_tpu.serve import (
        MicroBatcher,
        QueueFullError,
        ServingMetrics,
    )

    metrics = ServingMetrics()
    release = threading.Event()

    def slow(rows, per_coordinate=False):
        release.wait(5.0)
        return _echo_score(rows)

    b = MicroBatcher(slow, max_batch=1, max_delay_ms=1.0, max_queue=8,
                     request_deadline_s=0.05, metrics=metrics)
    try:
        first = b.submit(_rows(1.0))   # occupies the worker
        stale = b.submit(_rows(2.0))   # waits past its deadline
        time.sleep(0.15)
        release.set()
        assert first.result(5.0)[0] == 1.0
        with pytest.raises(QueueFullError) as exc:
            stale.result(5.0)
        assert exc.value.cause == "deadline"
        assert exc.value.retry_after_s > 0
        snap = metrics.snapshot()
        assert snap["shed_deadline_total"] == 1
        assert snap["shed_total"] == (snap["shed_queue_full_total"]
                                      + snap["shed_deadline_total"])
    finally:
        release.set()
        b.close()


def test_request_latency_splits_into_queue_wait_and_compute():
    """The queue-wait / device-compute histograms must account for the
    request latency: a stalled batch shows up as queue wait for the
    request behind it and as compute for its own batch."""
    from photon_ml_tpu.serve import MicroBatcher, ServingMetrics

    metrics = ServingMetrics()

    def slow(rows, per_coordinate=False):
        time.sleep(0.03)
        return _echo_score(rows)

    b = MicroBatcher(slow, max_batch=1, max_delay_ms=1.0, max_queue=8,
                     metrics=metrics)
    try:
        pending = [b.submit(_rows(float(i))) for i in range(3)]
        for p in pending:
            p.result(10.0)
        snap = metrics.snapshot()
        # batch 3 waited behind ~2 executions of ~30ms each
        assert snap["queue_wait_p99_ms"] >= 30.0
        assert snap["compute_p50_ms"] >= 25.0
        assert metrics.queue_wait_ms.total == 3
        assert metrics.compute_ms.total == 3
        rendered = metrics.render()
        assert "photon_serve_queue_wait_ms_bucket" in rendered
        assert "photon_serve_compute_ms_bucket" in rendered
    finally:
        b.close()


def test_done_callback_fires_on_resolution_any_order():
    """add_done_callback is the asyncio bridge: it must fire exactly
    once whether registered before or after the request resolves."""
    from photon_ml_tpu.serve import MicroBatcher

    b = MicroBatcher(_echo_score, max_batch=4, max_delay_ms=1.0)
    try:
        fired = []
        req = b.submit(_rows(1.0))
        req.add_done_callback(lambda r: fired.append(r.result(0)[0]))
        req.result(5.0)
        deadline = time.monotonic() + 5.0
        while not fired and time.monotonic() < deadline:
            time.sleep(0.001)
        assert fired == [1.0]
        # late registration: resolved request -> immediate callback
        req.add_done_callback(lambda r: fired.append("late"))
        assert fired == [1.0, "late"]
        assert req.error is None
    finally:
        b.close()


def test_close_joins_worker_without_thread_leak():
    """close() must actually reap the worker (bounded join, PT403's
    runtime discipline) — verified by the thread-leak sanitizer."""
    from photon_ml_tpu.analysis.sanitizers import ThreadLeakSanitizer
    from photon_ml_tpu.serve import MicroBatcher

    with ThreadLeakSanitizer():
        b = MicroBatcher(_echo_score, max_batch=8, max_delay_ms=10.0,
                         max_queue=8)
        assert b.score(_rows(1.0), timeout=10.0)[0] == 1.0
        b.close()
        assert not b._worker.is_alive()
        assert b.join_timeouts == 0
        b.close()  # idempotent


def test_close_idle_worker_wakes_from_bounded_poll():
    """A worker that never saw a request parks in the bounded idle
    poll; close() must still reap it promptly via the stop event."""
    from photon_ml_tpu.serve import MicroBatcher

    b = MicroBatcher(_echo_score, max_batch=8, max_delay_ms=10.0)
    t0 = time.monotonic()
    b.close()
    assert not b._worker.is_alive()
    assert time.monotonic() - t0 < 5.0


def test_close_times_out_on_wedged_scoring_and_warns(caplog):
    """A wedged scoring execution must not wedge close(): the bounded
    join expires, the leak is counted and logged (the
    producer_join_timeouts idiom), and the request still resolves when
    the execution finally returns."""
    import logging

    from photon_ml_tpu.serve import MicroBatcher

    release = threading.Event()

    def wedged(rows, per_coordinate=False):
        release.wait(30.0)
        return _echo_score(rows)

    b = MicroBatcher(wedged, max_batch=8, max_delay_ms=1.0, max_queue=8)
    req = b.submit(_rows(2.0))
    deadline = time.monotonic() + 5.0
    while b.queue_depth and time.monotonic() < deadline:
        time.sleep(0.005)  # worker picked it up and is inside wedged()
    with caplog.at_level(logging.WARNING,
                         logger="photon_ml_tpu.serve.batcher"):
        b.close(drain_timeout_s=0.1)
    assert b.join_timeouts == 1
    assert any("still alive" in r.getMessage() for r in caplog.records)
    release.set()
    assert req.result(10.0)[0] == 2.0
    b._worker.join(10.0)
    assert not b._worker.is_alive()
