"""PN503 regression: directory-listing order must not be load-bearing.

Each scenario runs the same housekeeping operation twice over
identically-prepared trees — once with the real ``os.listdir`` and once
with a scrambled one that returns entries in reverse order — and asserts
the outcome is byte-identical: same surviving files, same contents, same
selection. These are the four sites ISSUE/PR 14 fixed to the
``sorted(os.listdir(...))`` idiom (io/avro.py's): recovery snapshot
pruning, registry GC (including staging cleanup), chunk-cache sweeps,
and the driver's latest-checkpoint resolution."""

import hashlib
import os
import shutil

import pytest

from photon_ml_tpu.cli.game_training_driver import _latest_checkpoint
from photon_ml_tpu.io.chunk_cache import ChunkCacheSource
from photon_ml_tpu.parallel.recovery import RecoveryManager
from photon_ml_tpu.registry.store import ModelRegistry

_REAL_LISTDIR = os.listdir


def _scrambled_listdir(path="."):
    # the adversarial filesystem: same entries, reversed return order
    # (listdir order is an OS/filesystem artifact, never a contract)
    return list(reversed(_REAL_LISTDIR(path)))


@pytest.fixture
def scrambled(monkeypatch):
    def arm():
        monkeypatch.setattr(os, "listdir", _scrambled_listdir)

    def disarm():
        monkeypatch.setattr(os, "listdir", _REAL_LISTDIR)

    return arm, disarm


def _tree_state(root):
    """{relative path: sha256(content) | 'dir'} for the whole tree —
    the byte-identical comparison basis."""
    state = {}
    for dirpath, dirnames, filenames in os.walk(root):
        for d in dirnames:
            rel = os.path.relpath(os.path.join(dirpath, d), root)
            state[rel] = "dir"
        for f in filenames:
            full = os.path.join(dirpath, f)
            rel = os.path.relpath(full, root)
            with open(full, "rb") as fh:
                state[rel] = hashlib.sha256(fh.read()).hexdigest()
    return state


# -- recovery snapshot pruning ----------------------------------------------
def _seed_snapshots(d):
    os.makedirs(d)
    for rank, sweeps in ((0, (1, 2, 3, 4, 5)), (1, (3,))):
        for s in sweeps:
            with open(os.path.join(d, f"shard-r{rank}-s{s}.snap.npz"),
                      "wb") as fh:
                fh.write(f"payload r{rank} s{s}".encode())


def _prune_rank0(d, keep_sweep):
    mgr = RecoveryManager(d)
    mgr.rank = 0
    mgr._prune(keep_sweep=keep_sweep)


def test_recovery_prune_order_independent(tmp_path, scrambled):
    arm, disarm = scrambled
    natural = str(tmp_path / "natural")
    adversarial = str(tmp_path / "adversarial")
    _seed_snapshots(natural)
    _seed_snapshots(adversarial)

    _prune_rank0(natural, keep_sweep=3)
    arm()
    _prune_rank0(adversarial, keep_sweep=3)
    disarm()

    state = _tree_state(natural)
    assert state == _tree_state(adversarial)
    # and the prune itself did what it claims: rank 0 keeps only s3,
    # rank 1's snapshot (a dead peer's last commit) is untouched
    assert sorted(state) == ["shard-r0-s3.snap.npz",
                             "shard-r1-s3.snap.npz"]


# -- registry GC + staging cleanup -------------------------------------------
def _seed_registry(root):
    versions = os.path.join(root, "versions")
    os.makedirs(versions)
    for v in ("v000001", "v000002", "v000003", "v000004"):
        vdir = os.path.join(versions, v)
        os.makedirs(vdir)
        with open(os.path.join(vdir, "manifest.json"), "w") as fh:
            fh.write('{"version": "%s"}' % v)
    for stale in (".tmp-1111-aa", ".tmp-2222-bb"):
        sdir = os.path.join(versions, stale)
        os.makedirs(sdir)
        old = 1.0  # epoch-old mtime: far past any staging grace
        os.utime(sdir, (old, old))


def test_registry_gc_order_independent(tmp_path, scrambled):
    arm, disarm = scrambled
    natural = str(tmp_path / "natural")
    adversarial = str(tmp_path / "adversarial")
    _seed_registry(natural)
    _seed_registry(adversarial)

    removed_nat = ModelRegistry(natural).gc(keep=2, clean_staging=True)
    arm()
    removed_adv = ModelRegistry(adversarial).gc(keep=2,
                                                clean_staging=True)
    disarm()

    assert removed_nat == removed_adv == ["v000001", "v000002"]
    state = _tree_state(natural)
    assert state == _tree_state(adversarial)
    # newest two survive; both epoch-old staging dirs are swept
    assert sorted(d for d in state if state[d] == "dir") == [
        "versions", "versions/v000003", "versions/v000004"]


# -- chunk-cache sweep --------------------------------------------------------
def _seed_cache(d, live_suffix):
    os.makedirs(d)
    # a committed cache for a DIFFERENT fingerprint: stale, must go
    stale = os.path.join(d, "chunks-" + "0" * 16)
    os.makedirs(stale)
    with open(os.path.join(stale, "meta.json"), "w") as fh:
        fh.write("{}")
    # two orphaned staging dirs whose writer pids are long dead
    for tmp in (".tmp-999901-x", ".tmp-999902-y"):
        os.makedirs(os.path.join(d, tmp))
    # the live cache (matches the fingerprint the source will hash to)
    live = os.path.join(d, "chunks-" + live_suffix)
    os.makedirs(live)
    with open(os.path.join(live, "payload.bin"), "wb") as fh:
        fh.write(b"\x00\x01live-bytes")


def _sweep(d):
    # construction runs _sweep(); the fingerprint is pinned so both
    # trees hash to the same live cache path
    src = ChunkCacheSource([], d, fingerprint={"pin": 1})
    return os.path.basename(src.cache_path)


def test_chunk_cache_sweep_order_independent(tmp_path, scrambled):
    arm, disarm = scrambled
    probe = ChunkCacheSource([], str(tmp_path / "probe"),
                             fingerprint={"pin": 1})
    live_suffix = os.path.basename(probe.cache_path)[len("chunks-"):]

    natural = str(tmp_path / "natural")
    adversarial = str(tmp_path / "adversarial")
    _seed_cache(natural, live_suffix)
    _seed_cache(adversarial, live_suffix)

    _sweep(natural)
    arm()
    _sweep(adversarial)
    disarm()

    state = _tree_state(natural)
    assert state == _tree_state(adversarial)
    # orphans and the stale-fingerprint cache are gone, live cache's
    # payload survives bit-for-bit
    assert sorted(state) == ["chunks-" + live_suffix,
                             f"chunks-{live_suffix}/payload.bin"]


# -- driver latest-checkpoint resolution --------------------------------------
def _seed_checkpoints(out_dir):
    root = os.path.join(out_dir, "checkpoints")
    os.makedirs(root)
    # identical mtimes force the numeric tiebreak: iter-10 must beat
    # iter-9 regardless of the order listdir surfaces them
    stamp = 1700000000.0
    for name in ("run-iter-9", "run-iter-10", "run-iter-2"):
        d = os.path.join(root, name)
        os.makedirs(d)
        os.utime(d, (stamp, stamp))
    os.utime(root, (stamp, stamp))


def test_latest_checkpoint_order_independent(tmp_path, scrambled):
    arm, disarm = scrambled
    out = str(tmp_path / "out")
    _seed_checkpoints(out)

    natural = _latest_checkpoint(out)
    arm()
    adversarial = _latest_checkpoint(out)
    disarm()

    assert natural == adversarial
    assert os.path.basename(natural) == "run-iter-10"


# -- the idiom itself ---------------------------------------------------------
def test_scrambler_actually_scrambles(tmp_path, scrambled):
    # guard the guard: if the adversarial listdir ever degrades into a
    # passthrough, every test above passes vacuously
    arm, disarm = scrambled
    for name in ("a", "b", "c"):
        (tmp_path / name).touch()
    arm()
    scrambled_names = os.listdir(str(tmp_path))
    disarm()
    assert scrambled_names == list(reversed(_REAL_LISTDIR(str(tmp_path))))
    assert sorted(scrambled_names) == ["a", "b", "c"]
