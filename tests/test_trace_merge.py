"""photon-trace merge: clock alignment on collective sites, schema
validation, and the end-to-end 4-rank path through the real tracer and
the real entity-sharded exchange (simulated multi-process harness).
"""

import glob
import json
import os

import numpy as np
import pytest

from photon_ml_tpu.obs import trace
from photon_ml_tpu.obs.trace_cli import merge_traces, validate_trace
from photon_ml_tpu.testing import run_simulated_processes


def _span(name, ts, dur, pid, cat="app", **args):
    return {"name": name, "cat": cat, "ph": "X", "ts": ts, "dur": dur,
            "pid": pid, "tid": 1, "args": args}


def _doc(rank, events):
    return {"traceEvents": events, "metadata": {"rank": rank}}


def _write(tmp_path, rank, events):
    p = os.path.join(str(tmp_path), f"trace-rank{rank}.json")
    with open(p, "w") as f:
        json.dump(_doc(rank, events), f)
    return p


class TestAlignment:
    def test_known_clock_offset_is_recovered(self, tmp_path):
        # rank 1's perf_counter origin is 500µs behind rank 0: its copy
        # of every rendezvous END reads 500 lower. The merge must shift
        # rank 1 by +500.
        r0 = [
            _span("exchange", 100.0, 50.0, 0, cat="collective", site="x:0"),
            _span("exchange", 300.0, 50.0, 0, cat="collective", site="x:1"),
        ]
        r1 = [
            _span("exchange", -400.0, 50.0, 1, cat="collective", site="x:0"),
            _span("exchange", -200.0, 50.0, 1, cat="collective", site="x:1"),
        ]
        p0 = _write(tmp_path, 0, r0)
        p1 = _write(tmp_path, 1, r1)
        merged = merge_traces([p0, p1])
        assert merged["metadata"]["clock_shifts_us"] == {"0": 0.0,
                                                         "1": 500.0}
        ends = {e["pid"]: e["ts"] + e["dur"]
                for e in merged["traceEvents"]
                if e.get("args", {}).get("site") == "x:0"}
        assert ends[0] == pytest.approx(ends[1])

    def test_median_shift_is_robust_to_a_straggler_occurrence(
            self, tmp_path):
        # one late entry (rank 1 blocked 1000µs extra on site x:1) must
        # not drag the whole shift: median over 3 matched ends ignores it
        r0 = [_span("c", 100.0 * k, 10.0, 0, cat="collective",
                    site=f"x:{k}") for k in range(3)]
        r1 = [_span("c", 100.0 * k - 700.0, 10.0, 1, cat="collective",
                    site=f"x:{k}") for k in range(3)]
        r1[1]["ts"] -= 1000.0  # straggler: this end reads 1000 lower
        p0 = _write(tmp_path, 0, r0)
        p1 = _write(tmp_path, 1, r1)
        merged = merge_traces([p0, p1])
        assert merged["metadata"]["clock_shifts_us"]["1"] == 700.0

    def test_repeated_site_matches_by_occurrence_index(self, tmp_path):
        # the SAME site label twice (a loop over sweeps): k-th matches
        # k-th, so the two occurrences contribute two deltas, not one
        r0 = [_span("c", 100.0, 10.0, 0, cat="collective", site="loop"),
              _span("c", 200.0, 10.0, 0, cat="collective", site="loop")]
        r1 = [_span("c", 60.0, 10.0, 1, cat="collective", site="loop"),
              _span("c", 160.0, 10.0, 1, cat="collective", site="loop")]
        merged = merge_traces([_write(tmp_path, 0, r0),
                               _write(tmp_path, 1, r1)])
        assert merged["metadata"]["clock_shifts_us"]["1"] == 40.0

    def test_rank_without_collectives_merges_unshifted_with_warning(
            self, tmp_path):
        r0 = [_span("c", 100.0, 10.0, 0, cat="collective", site="x:0")]
        r1 = [_span("local", 50.0, 10.0, 1, cat="train")]
        merged = merge_traces([_write(tmp_path, 0, r0),
                               _write(tmp_path, 1, r1)])
        assert merged["metadata"]["unaligned_ranks"] == [1]
        local = [e for e in merged["traceEvents"] if e["name"] == "local"]
        assert local[0]["ts"] == 50.0

    def test_empty_input_raises(self):
        with pytest.raises(ValueError):
            merge_traces([])


class TestValidate:
    def test_valid_doc_passes(self, tmp_path):
        assert validate_trace(_doc(0, [_span("a", 1.0, 2.0, 0)])) == []

    def test_missing_fields_reported(self):
        doc = {"traceEvents": [{"name": "a", "ph": "X", "ts": 1.0}]}
        problems = validate_trace(doc)
        assert any("pid" in p for p in problems)
        assert any("dur" in p for p in problems)

    def test_empty_events_reported(self):
        assert validate_trace({"traceEvents": []}) == [
            "traceEvents missing or empty"]

    def test_metadata_only_doc_reported(self):
        doc = {"traceEvents": [{"name": "process_name", "ph": "M",
                                "pid": 0, "tid": 0}]}
        assert "no complete ('X') span events" in validate_trace(doc)


def _rank_fn(rank: int):
    from photon_ml_tpu.parallel.entity_shard import exchange_score_updates

    with trace.span("fit", cat="train", rank=rank):
        for sweep in range(2):
            rows = np.asarray([rank * 2, rank * 2 + 1], np.int64)
            vals = np.asarray([float(rank), 1.0], np.float64)
            exchange_score_updates((rows, vals), tag=f"sweep:{sweep}")


class TestEndToEnd:
    def test_four_rank_exchange_traces_merge_and_align(self, tmp_path):
        """The acceptance path: 4 simulated ranks run the real sharded
        exchange under the real tracer; per-rank files merge into one
        schema-valid timeline whose collective spans overlap per site."""
        trace.start(str(tmp_path), export_thread=False)
        try:
            outcomes = run_simulated_processes(4, _rank_fn)
        finally:
            trace.stop()
        bad = [o for o in outcomes if isinstance(o, BaseException)]
        assert not bad, bad

        paths = sorted(glob.glob(
            os.path.join(str(tmp_path), "trace-rank*.json")))
        assert len(paths) == 4
        merged = merge_traces(paths)
        assert validate_trace(merged) == []
        assert merged["metadata"]["ranks"] == [0, 1, 2, 3]
        assert merged["metadata"]["unaligned_ranks"] == []

        # per collective site: all 4 ranks present, intervals overlap
        # pairwise (they leave the rendezvous together). Tolerance: the
        # simulated ranks already share one clock, so the aligner's
        # per-rank shift is pure scheduler wake jitter (median of
        # end_0 - end_N) — µs-scale barrier spans can miss strict
        # overlap by that jitter. 10ms still catches real misalignment.
        jitter_us = 10_000.0
        by_site = {}
        for e in merged["traceEvents"]:
            if e.get("cat") != "collective":
                continue
            site = (e.get("args") or {}).get("site")
            if site:
                by_site.setdefault(site, []).append(e)
        assert by_site, "exchange produced no collective spans"
        for site, evs in by_site.items():
            assert {e["pid"] for e in evs} == {0, 1, 2, 3}, site
            latest_start = max(e["ts"] for e in evs)
            earliest_end = min(e["ts"] + e["dur"] for e in evs)
            assert latest_start <= earliest_end + jitter_us, (
                f"site {site}: rank intervals do not overlap")
