"""Worker for the real multi-process tests (tests/test_multiprocess.py).

Each of the two OS processes runs this script with a distinct
--process-id, rendezvouses via ``jax.distributed.initialize`` over
localhost, and runs the SAME deterministic workloads; process 0 writes the
results as JSON for the parent test to compare against a single-process
reference. This is the 2-process leg the round-1 suite lacked (VERDICT r1
missing #4): shard_map/psum reductions crossing a real process boundary.
"""

import argparse
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def make_problem():
    import numpy as np

    rng = np.random.default_rng(42)
    n, d = 256, 12
    X = (rng.random((n, d)) < 0.5) * rng.normal(size=(n, d))
    w_true = rng.normal(size=d)
    ids = rng.integers(0, 6, n)
    u_eff = rng.normal(size=6)
    y = (rng.random(n) < 1 / (1 + np.exp(-(X @ w_true + u_eff[ids])))
         ).astype(float)
    return X, y, ids


def run_fit_distributed():
    """Global-mesh in-memory fit: batch formed from per-process shards via
    make_array_from_process_local_data, psum over both processes."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from photon_ml_tpu.ops.objective import make_objective
    from photon_ml_tpu.optimize import OptimizerConfig
    from photon_ml_tpu.parallel.data_parallel import fit_distributed
    from photon_ml_tpu.parallel.mesh import make_mesh
    from photon_ml_tpu.parallel.multihost import process_span
    from photon_ml_tpu.types import LabeledBatch, SparseFeatures

    X, y, _ = make_problem()
    n, d = X.shape
    mesh = make_mesh()  # all global devices on one data axis
    sharding = NamedSharding(mesh, P("data"))

    start, stop = process_span(n)

    def gshard(a):
        return jax.make_array_from_process_local_data(sharding,
                                                      np.asarray(a[start:stop]))

    indices = np.broadcast_to(np.arange(d, dtype=np.int32), X.shape).copy()
    batch = LabeledBatch(
        SparseFeatures(gshard(indices), gshard(X), dim=d),
        gshard(y), gshard(np.zeros(n)), gshard(np.ones(n)),
    )
    obj = make_objective("logistic")
    res = fit_distributed(obj, batch, mesh, jnp.zeros(d), l2=0.5,
                          config=OptimizerConfig(max_iters=100,
                                                 tolerance=1e-12))
    return {"w": np.asarray(res.w).tolist(), "value": float(res.value),
            "converged": bool(res.converged)}


def run_game_streaming_step():
    """One GAME CD iteration (streamed fixed effect + random effect), data
    split across processes by process_span inside _FixedState."""
    import jax.numpy as jnp
    import numpy as np

    from photon_ml_tpu.game.descent import (
        CoordinateConfig,
        CoordinateDescent,
        make_game_dataset,
    )

    X, y, ids = make_problem()
    ds = make_game_dataset(X, y, entity_ids={"userId": ids.astype(str)})
    cfgs = [
        CoordinateConfig("global", streaming=True, chunk_rows=64,
                         reg_type="l2", reg_weight=0.5,
                         max_iters=200, tolerance=1e-13),
        CoordinateConfig("per-user", coordinate_type="random",
                         entity_column="userId", reg_type="l2",
                         reg_weight=1.0, max_iters=200, tolerance=1e-13),
    ]
    cd = CoordinateDescent(cfgs, task="logistic", n_iterations=2,
                           dtype=jnp.float64)
    model, _ = cd.run(ds)
    w = np.asarray(model.coordinates["global"].model.coefficients.means)
    return {"w_fixed": w.tolist()}


def run_ooc_streamed_fit(data_dir):
    """fit_streaming over a DISK-backed AvroChunkSource with each process
    holding its own process_part block share — the out-of-core training
    path's DCN leg (per-process partials reduce in _cross_process_sum)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from photon_ml_tpu.io.data_reader import write_training_examples
    from photon_ml_tpu.io.index_map import IndexMap
    from photon_ml_tpu.io.stream_source import AvroChunkSource
    from photon_ml_tpu.ops.objective import make_objective
    from photon_ml_tpu.optimize import OptimizerConfig
    from photon_ml_tpu.parallel.streaming import fit_streaming

    path = os.path.join(data_dir, "ooc_mp.avro")
    if jax.process_index() == 0:
        X, y, _ = make_problem()
        rows = [[(f"f{j}", "", float(v)) for j, v in enumerate(r)
                 if v != 0] for r in X]
        write_training_examples(path, rows, y, block_size=16)
        open(path + ".done", "w").close()
    else:  # wait for process 0's file (no shared barrier before init)
        import time

        while not os.path.exists(path + ".done"):
            time.sleep(0.05)
    d = 12
    imap = IndexMap({f"f{j}": j for j in range(d)}, add_intercept=False)
    src = AvroChunkSource(
        path, imap, chunk_rows=32, dtype=np.float64,
        process_part=(jax.process_index(), jax.process_count()))
    obj = make_objective("logistic")
    res = fit_streaming(obj, src, src.dim, l2=0.5,
                        config=OptimizerConfig(max_iters=150,
                                               tolerance=1e-12),
                        dtype=jnp.float64)
    return {"w": np.asarray(res.w).tolist(), "value": float(res.value),
            "data_path": path}


def run_game_ooc_step(data_dir):
    """One GAME CD run whose FIXED EFFECT streams from disk with
    per-process block shares (GameDataset.feature_sources +
    AvroChunkSource(process_part)): partials reduce across processes,
    scores reassemble via part spans."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from photon_ml_tpu.game.descent import (
        CoordinateConfig,
        CoordinateDescent,
        GameDataset,
    )
    from photon_ml_tpu.game.data import HostSparse
    from photon_ml_tpu.io.data_reader import write_training_examples
    from photon_ml_tpu.io.index_map import IndexMap
    from photon_ml_tpu.io.stream_source import AvroChunkSource

    path = os.path.join(data_dir, "game_ooc_mp.avro")
    X, y, ids = make_problem()
    n, d = X.shape
    if jax.process_index() == 0:
        rows = [[(f"f{j}", "", float(v)) for j, v in enumerate(r)
                 if v != 0] for r in X]
        write_training_examples(path, rows, y,
                                entity_ids={"userId": ids.astype(str)},
                                block_size=16)
        open(path + ".done2", "w").close()
    else:
        import time

        while not os.path.exists(path + ".done2"):
            time.sleep(0.05)
    imap = IndexMap({f"f{j}": j for j in range(d)}, add_intercept=False)
    src = AvroChunkSource(
        path, imap, chunk_rows=32, dtype=np.float64,
        process_part=(jax.process_index(), jax.process_count()))
    # RE shard stays resident per process (dense X rebuilt as sparse rows)
    idx = np.broadcast_to(np.arange(d, dtype=np.int32), X.shape).copy()
    ds = GameDataset({"re": HostSparse(idx, X, d)}, y, None, None,
                     {"userId": ids.astype(str)},
                     feature_sources={"global": src})
    cfgs = [
        CoordinateConfig("global", streaming=True, chunk_rows=32,
                         reg_type="l2", reg_weight=0.5,
                         max_iters=150, tolerance=1e-13),
        CoordinateConfig("per-user", coordinate_type="random",
                         feature_shard="re", entity_column="userId",
                         reg_type="l2", reg_weight=1.0, max_iters=150,
                         tolerance=1e-13),
    ]
    cd = CoordinateDescent(cfgs, task="logistic", n_iterations=2,
                           dtype=jnp.float64)
    model, _ = cd.run(ds)
    w = np.asarray(model.coordinates["global"].model.coefficients.means)
    return {"w_fixed": w.tolist(), "data_path": path}


def run_resilience_barrier():
    """Real-runtime leg of the coordinated-abort contract: a healthy
    health barrier across two OS processes, then a guarded phase where
    process 1 raises locally — BOTH processes must raise PeerFailure
    (process 0 having learned of it only through the status allgather)."""
    import jax

    from photon_ml_tpu.parallel import resilience

    resilience.health_barrier("mp-healthy", timeout=120)
    try:
        with resilience.CollectiveGuard("mp-abort", timeout=120):
            if jax.process_index() == 1:
                raise ValueError("injected local failure on process 1")
    except resilience.PeerFailure as e:
        return {"peer_failure": True,
                "failed_ranks": sorted(e.failed),
                "codes": sorted(e.failed.values()),
                "device_loss": e.device_loss}
    return {"peer_failure": False}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--coordinator", required=True)
    ap.add_argument("--process-id", type=int, required=True)
    ap.add_argument("--num-processes", type=int, default=2)
    ap.add_argument("--out", required=True)
    args = ap.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)
    try:
        # legacy jax: CPU cross-process collectives need gloo selected
        # explicitly or every multiprocess computation fails to compile;
        # newer jax auto-selects and has dropped the config knob
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:
        pass
    jax.distributed.initialize(
        coordinator_address=args.coordinator,
        num_processes=args.num_processes,
        process_id=args.process_id,
    )
    assert jax.process_count() == args.num_processes

    results = {
        "process_count": jax.process_count(),
        "resilience": run_resilience_barrier(),
        "fit_distributed": run_fit_distributed(),
        "game_streaming": run_game_streaming_step(),
        "ooc_streaming": run_ooc_streamed_fit(os.path.dirname(args.out)),
        "game_ooc": run_game_ooc_step(os.path.dirname(args.out)),
    }
    if args.process_id == 0:
        with open(args.out, "w") as f:
            json.dump(results, f)
    return 0


if __name__ == "__main__":
    sys.exit(main())
