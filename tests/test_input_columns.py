"""InputColumnsNames: reading datasets whose record fields use non-default
names (the reference's input-column remapping, SURVEY.md §3.2)."""

import json

import numpy as np
import pytest

from photon_ml_tpu.io.avro import write_avro_file
from photon_ml_tpu.io.data_reader import InputColumnsNames, read_training_examples
from photon_ml_tpu.io.index_map import IndexMap


CUSTOM_SCHEMA = {
    "type": "record",
    "name": "CustomExample",
    "fields": [
        {"name": "id", "type": ["null", "string"], "default": None},
        {"name": "label", "type": "double"},
        {"name": "bias", "type": ["null", "double"], "default": None},
        {"name": "importance", "type": ["null", "double"], "default": None},
        {"name": "feats", "type": {"type": "array", "items": {
            "type": "record", "name": "F", "fields": [
                {"name": "name", "type": "string"},
                {"name": "term", "type": "string", "default": ""},
                {"name": "value", "type": "double"},
            ]}}},
        {"name": "context", "type": {"type": "map", "values": "string"},
         "default": {}},
    ],
}


def _write_custom(path, rng, n=20):
    def records():
        for i in range(n):
            yield {
                "id": str(i),
                "label": float(i % 2),
                "bias": 0.5,
                "importance": 2.0,
                "feats": [{"name": "x", "term": "", "value": float(i)}],
                "context": {"userId": str(i % 3)},
            }

    write_avro_file(path, records(), CUSTOM_SCHEMA)


def test_read_with_remapped_columns(tmp_path, rng):
    path = str(tmp_path / "custom.avro")
    _write_custom(path, rng)
    cols = InputColumnsNames(response="label", offset="bias",
                             weight="importance", uid="id",
                             features="feats", metadata_map="context")
    imap = IndexMap({"x": 0})
    feats, labels, offsets, weights, ents, uids = read_training_examples(
        [path], imap, entity_columns=["userId"], columns=cols
    )
    assert labels.tolist() == [float(i % 2) for i in range(20)]
    assert offsets.tolist() == [0.5] * 20
    assert weights.tolist() == [2.0] * 20
    assert uids[3] == "3"
    assert ents["userId"][4] == "1"
    assert feats["global"].values[5, 0] == 5.0


def test_from_dict_rejects_unknown_keys():
    with pytest.raises(ValueError, match="unknown input column"):
        InputColumnsNames.from_dict({"respnse": "label"})
    assert InputColumnsNames.from_dict(None) == InputColumnsNames()


def test_game_driver_with_input_columns(tmp_path, rng):
    from photon_ml_tpu.cli.game_training_driver import main as train_main

    path = str(tmp_path / "custom.avro")
    _write_custom(path, rng, n=40)
    out = tmp_path / "out"
    coords = [{"name": "fixed", "coordinate_type": "fixed",
               "reg_type": "l2", "reg_weight": 1.0, "max_iters": 20}]
    rc = train_main([
        "--train-data", path,
        "--output-dir", str(out),
        "--coordinates", json.dumps(coords),
        "--input-columns", json.dumps({
            "response": "label", "offset": "bias", "weight": "importance",
            "uid": "id", "features": "feats", "metadata_map": "context",
        }),
        "--dtype", "float64",
    ])
    assert rc == 0
    assert (out / "best" / "metadata.json").exists()
