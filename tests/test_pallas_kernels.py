"""Pallas fused multiply + prefix-sum kernel (interpret mode on CPU): exact
parity with jnp.cumsum and with the XLA CSC gradient path."""

import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.ops.pallas_kernels import (
    csc_transpose_apply_pallas,
    multiply_prefix_sum,
)


@pytest.mark.parametrize("nnz", [1, 100, 128 * 256, 128 * 256 * 3 + 17])
def test_multiply_prefix_sum_tile_local(nnz, rng):
    """The kernel returns TILE-LOCAL inclusive prefixes + tile totals
    (the blocked-combine contract): within each tile the scan matches
    cumsum of that tile's slice; totals are the tile sums."""
    v = jnp.asarray(rng.normal(size=nnz))
    d = jnp.asarray(rng.normal(size=nnz))
    local, totals, tile = multiply_prefix_sum(v, d, block_rows=256)
    x = np.zeros(len(local))
    x[:nnz] = np.asarray(v * d)
    assert tile == 128 * 256
    for t in range(len(totals)):
        sl = x[t * tile:(t + 1) * tile]
        np.testing.assert_allclose(np.asarray(local[t * tile:(t + 1) * tile]),
                                   np.cumsum(sl), rtol=1e-10, atol=1e-10)
        np.testing.assert_allclose(float(totals[t]), sl.sum(),
                                   rtol=1e-10, atol=1e-10)


def test_multiple_tiles_no_carry(rng):
    # small block size forces many grid steps; every tile restarts at zero
    nnz = 128 * 8 * 5 + 3
    v = jnp.asarray(rng.normal(size=nnz))
    d = jnp.ones((nnz,))
    local, totals, tile = multiply_prefix_sum(v, d, block_rows=8)
    assert tile == 128 * 8 and len(totals) == 6
    x = np.zeros(len(local))
    x[:nnz] = np.asarray(v)
    want = np.concatenate([np.cumsum(x[t * tile:(t + 1) * tile])
                           for t in range(6)])
    np.testing.assert_allclose(np.asarray(local), want,
                               rtol=1e-10, atol=1e-10)


def test_csc_apply_pallas_matches_xla(rng):
    from photon_ml_tpu.types import (
        build_csc_transpose,
        csc_transpose_apply,
        sparse_from_scipy,
    )
    import scipy.sparse as sp

    X = sp.random(300, 50, density=0.2, random_state=5, format="csr")
    feats = sparse_from_scipy(X, dtype=jnp.float64)
    csc = build_csc_transpose(feats.indices, feats.values, feats.dim)
    d = jnp.asarray(rng.normal(size=300))
    got = csc_transpose_apply_pallas(csc, d)
    want = csc_transpose_apply(csc, d)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-10, atol=1e-10)


def test_fit_csc_pallas_matches_scatter(rng):
    import scipy.sparse as sp

    from photon_ml_tpu.ops.objective import make_objective
    from photon_ml_tpu.optimize import OptimizerConfig
    from photon_ml_tpu.parallel.data_parallel import fit_distributed
    from photon_ml_tpu.parallel.mesh import make_mesh
    from photon_ml_tpu.types import make_batch, sparse_from_scipy

    n, d = 512, 32
    X = sp.random(n, d, density=0.2, random_state=2, format="csr")
    w_true = rng.normal(size=d)
    y = (rng.random(n) < 1 / (1 + np.exp(-np.asarray(X @ w_true)))).astype(float)
    batch = make_batch(sparse_from_scipy(X, dtype=jnp.float64), y,
                       dtype=jnp.float64)
    obj = make_objective("logistic")
    mesh = make_mesh()
    cfg = OptimizerConfig(max_iters=100, tolerance=1e-12)
    res_sc = fit_distributed(obj, batch, mesh, jnp.zeros(d), l2=0.4,
                             config=cfg)
    res_pl = fit_distributed(obj, batch, mesh, jnp.zeros(d), l2=0.4,
                             config=cfg, sparse_grad="csc_pallas")
    assert bool(res_pl.converged)
    np.testing.assert_allclose(np.asarray(res_pl.w), np.asarray(res_sc.w),
                               rtol=1e-5, atol=1e-8)


def test_kernel_lowers_to_mosaic_for_tpu():
    """The kernel must LOWER for the TPU target, not just run in interpret
    mode: jax.export with platforms=["tpu"] executes the Pallas->Mosaic
    lowering without a TPU client. Round 4 this caught a real chip-blocking
    bug (a (1,1) SMEM output block violating Mosaic's block-shape rule)
    that three rounds of interpret-mode CI never could (VERDICT r3 #4)."""
    import jax
    from jax import export

    from photon_ml_tpu.ops.pallas_kernels import multiply_prefix_sum

    nnz = 1 << 20
    fn = lambda v, d: multiply_prefix_sum(v, d, interpret=False)[:2]
    exp = export.export(jax.jit(fn), platforms=["tpu"])(
        jax.ShapeDtypeStruct((nnz,), jnp.float32),
        jax.ShapeDtypeStruct((nnz,), jnp.float32))
    assert "tpu_custom_call" in exp.mlir_module()


def test_hot_path_lowers_for_tpu_target():
    """The full single-device hot path — jitted L-BFGS fit (lax.while_loop
    + implicit-ones sparse passes) and the csc_pallas transpose-apply —
    lowers for the TPU target end to end, so a live chip session starts at
    'compile', not 'debug the lowering'."""
    import jax
    from jax import export

    from photon_ml_tpu.ops.objective import make_objective
    from photon_ml_tpu.ops.pallas_kernels import csc_transpose_apply_pallas
    from photon_ml_tpu.optimize import OptimizerConfig, get_optimizer
    from photon_ml_tpu.types import (LabeledBatch, SparseFeatures,
                                     build_csc_transpose)

    n, d, k = 1024, 512, 8
    obj = make_objective("logistic")
    cfg = OptimizerConfig(max_iters=5, tolerance=0.0)

    def fit(w0, indices, labels):
        batch = LabeledBatch(
            SparseFeatures(indices, None, dim=d), labels,
            jnp.zeros((n,), jnp.float32), jnp.ones((n,), jnp.float32))
        opt = get_optimizer("lbfgs")
        return opt(lambda w: obj.value_and_grad(w, batch, 1.0), w0, cfg).w

    export.export(jax.jit(fit), platforms=["tpu"])(
        jax.ShapeDtypeStruct((d,), jnp.float32),
        jax.ShapeDtypeStruct((n, k), jnp.int32),
        jax.ShapeDtypeStruct((n,), jnp.float32))

    def tapply(indices, vals, dvec):
        return csc_transpose_apply_pallas(
            build_csc_transpose(indices, vals, d), dvec)

    exp = export.export(jax.jit(tapply), platforms=["tpu"])(
        jax.ShapeDtypeStruct((n, k), jnp.int32),
        jax.ShapeDtypeStruct((n, k), jnp.float32),
        jax.ShapeDtypeStruct((n,), jnp.float32))
    assert "tpu_custom_call" in exp.mlir_module()
