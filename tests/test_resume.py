"""Failure recovery: deterministic re-run from coarse checkpoints
(SURVEY.md §5.3-4 — the reference delegates to Spark lineage; here a killed
run resumes from the last per-iteration checkpoint via warm start)."""

import json

import numpy as np

from photon_ml_tpu.cli.game_training_driver import main as train_main
from photon_ml_tpu.io.model_io import load_game_model
from photon_ml_tpu.testing import synthetic_game_data, write_game_avro_fixture


def test_resume_from_checkpoint_matches_uninterrupted(tmp_path):
    data = synthetic_game_data({"userId": 10}, seed=2)
    path = str(tmp_path / "train.avro")
    write_game_avro_fixture(path, data)
    coords = json.dumps([
        {"name": "fixed", "coordinate_type": "fixed", "feature_shard": "global",
         "reg_type": "l2", "reg_weight": 0.5, "max_iters": 40},
        {"name": "per-user", "coordinate_type": "random",
         "feature_shard": "entity", "entity_column": "userId",
         "reg_type": "l2", "reg_weight": 1.0, "max_iters": 25},
    ])
    shards = json.dumps({"global": ["g"], "entity": ["u"]})

    # uninterrupted: 3 outer CD iterations
    full = tmp_path / "full"
    assert train_main([
        "--train-data", path, "--output-dir", str(full),
        "--coordinates", coords, "--feature-shards", shards,
        "--n-iterations", "3", "--dtype", "float64",
    ]) == 0

    # "crashed" run: only 2 iterations, with checkpoints
    part = tmp_path / "part"
    assert train_main([
        "--train-data", path, "--output-dir", str(part),
        "--coordinates", coords, "--feature-shards", shards,
        "--n-iterations", "2", "--checkpoint", "--dtype", "float64",
    ]) == 0
    ckpt = part / "checkpoints" / "config-0-iter-1"
    assert (ckpt / "metadata.json").exists()

    # resume: 1 more iteration warm-started from the checkpoint
    resumed = tmp_path / "resumed"
    assert train_main([
        "--train-data", path, "--output-dir", str(resumed),
        "--coordinates", coords, "--feature-shards", shards,
        "--n-iterations", "1", "--warm-start-model", str(ckpt),
        "--dtype", "float64",
    ]) == 0

    w_full = np.asarray(
        load_game_model(str(full / "best"))["fixed"].model.coefficients.means
    )
    w_resumed = np.asarray(
        load_game_model(str(resumed / "best"))["fixed"].model.coefficients.means
    )
    # coarse checkpointing preserves coefficients, not optimizer internals,
    # so resumed ~ uninterrupted rather than bit-identical
    np.testing.assert_allclose(w_resumed, w_full, rtol=5e-2, atol=5e-3)
