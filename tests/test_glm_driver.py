"""Classic GLM driver (staged pipeline) + data-validator tests — the
reference's legacy ``Driver`` tier (SURVEY.md §3.3, integTest style §8)."""

import json

import numpy as np
import pytest

from photon_ml_tpu.cli.glm_driver import main as glm_main
from photon_ml_tpu.io.data_reader import feature_tuples_from_dense, write_training_examples
from photon_ml_tpu.io.validators import DataValidationError, validate_training_data


def _write_libsvm(path, X, y):
    with open(path, "w") as f:
        for i in range(X.shape[0]):
            toks = [f"{int(y[i]) * 2 - 1}"]
            for j in np.nonzero(X[i])[0]:
                toks.append(f"{j + 1}:{X[i, j]:.6f}")
            f.write(" ".join(toks) + "\n")


@pytest.fixture
def logistic_data(rng):
    n, d = 400, 10
    X = (rng.random((n, d)) < 0.4) * rng.normal(size=(n, d))
    w = rng.normal(size=d)
    y = (rng.random(n) < 1 / (1 + np.exp(-(X @ w)))).astype(float)
    return X, y


def test_glm_driver_libsvm_lambda_grid(tmp_path, logistic_data):
    X, y = logistic_data
    _write_libsvm(tmp_path / "train.svm", X[:300], y[:300])
    _write_libsvm(tmp_path / "val.svm", X[300:], y[300:])
    out = tmp_path / "out"
    rc = glm_main([
        "--train-data", str(tmp_path / "train.svm"),
        "--validation-data", str(tmp_path / "val.svm"),
        "--input-format", "libsvm",
        "--output-dir", str(out),
        "--reg-weights", "10.0", "1.0", "0.1",
        "--compute-variances",
        "--dtype", "float64",
    ])
    assert rc == 0
    assert (out / "best" / "metadata.json").exists()
    # every lambda lands under all/ (best is also mirrored there)
    for lam in ("10", "1", "0.1"):
        assert (out / "all" / f"lambda-{lam}" / "metadata.json").exists()
    log = [json.loads(l) for l in (out / "photon.log.jsonl").read_text().splitlines()]
    trained = [r for r in log if r["event"] == "lambda_trained"]
    assert [r["reg_weight"] for r in trained] == [10.0, 1.0, 0.1]
    assert all(r["metrics"]["auc"] > 0.6 for r in trained)
    done = [r for r in log if r["event"] == "driver_done"][0]
    # selection picks the grid point with the best validation AUC
    best = max(trained, key=lambda r: r["metrics"]["auc"])
    assert done["best_reg_weight"] == best["reg_weight"]
    assert done["best_metrics"]["auc"] == best["metrics"]["auc"]

    # model round-trips through the standard GAME loader
    from photon_ml_tpu.io.model_io import load_game_model

    model = load_game_model(str(out / "best"))
    w = np.asarray(model["global"].model.coefficients.means)
    assert w.shape[0] == X.shape[1] + 1  # + intercept
    assert model["global"].model.coefficients.variances is not None


def test_glm_driver_avro_elastic_net(tmp_path, logistic_data):
    X, y = logistic_data
    write_training_examples(
        str(tmp_path / "train.avro"), feature_tuples_from_dense(X[:300]), y[:300]
    )
    write_training_examples(
        str(tmp_path / "val.avro"), feature_tuples_from_dense(X[300:]), y[300:]
    )
    out = tmp_path / "out"
    rc = glm_main([
        "--train-data", str(tmp_path / "train.avro"),
        "--validation-data", str(tmp_path / "val.avro"),
        "--output-dir", str(out),
        "--reg-type", "elastic_net",
        "--reg-weights", "0.5",
        "--normalization", "standardization",
        "--summarize-features",
        "--dtype", "float64",
    ])
    assert rc == 0
    assert (out / "feature-summary.avro").exists()
    log = [json.loads(l) for l in (out / "photon.log.jsonl").read_text().splitlines()]
    # elastic net forces the OWL-QN override
    assert any(r["event"] == "optimizer_override" and r["used"] == "owlqn"
               for r in log)
    trained = [r for r in log if r["event"] == "lambda_trained"]
    assert trained[0]["metrics"]["auc"] > 0.6


def test_glm_driver_streaming_matches_in_memory(tmp_path, logistic_data):
    X, y = logistic_data
    _write_libsvm(tmp_path / "train.svm", X[:300], y[:300])
    _write_libsvm(tmp_path / "val.svm", X[300:], y[300:])
    common = [
        "--train-data", str(tmp_path / "train.svm"),
        "--validation-data", str(tmp_path / "val.svm"),
        "--input-format", "libsvm",
        "--reg-weights", "1.0",
        "--normalization", "standardization",
        "--compute-variances",
        "--dtype", "float64",
    ]
    assert glm_main(common + ["--output-dir", str(tmp_path / "mem")]) == 0
    assert glm_main(common + ["--output-dir", str(tmp_path / "str"),
                              "--streaming", "--chunk-rows", "64"]) == 0

    from photon_ml_tpu.io.model_io import load_game_model

    w_mem = np.asarray(
        load_game_model(str(tmp_path / "mem" / "best"))["global"]
        .model.coefficients.means
    )
    best = load_game_model(str(tmp_path / "str" / "best"))["global"].model
    w_str = np.asarray(best.coefficients.means)
    np.testing.assert_allclose(w_str, w_mem, rtol=1e-4, atol=1e-6)
    assert best.coefficients.variances is not None
    log = [json.loads(l)
           for l in (tmp_path / "str" / "photon.log.jsonl").read_text().splitlines()]
    auc_str = [r for r in log if r["event"] == "lambda_trained"][0]["metrics"]["auc"]
    assert auc_str > 0.6


def test_glm_driver_out_of_core_matches_streaming(tmp_path, logistic_data):
    """--out-of-core (disk-backed AvroChunkSource, VERDICT r4 #2) must
    reproduce the in-RAM streamed fit under the same pinned feature space."""
    from photon_ml_tpu.io.data_reader import (
        feature_tuples_from_dense,
        write_training_examples,
    )

    X, y = logistic_data
    write_training_examples(
        str(tmp_path / "train.avro"), feature_tuples_from_dense(X[:300]),
        y[:300])
    write_training_examples(
        str(tmp_path / "val.avro"), feature_tuples_from_dense(X[300:]),
        y[300:])
    common = [
        "--train-data", str(tmp_path / "train.avro"),
        "--validation-data", str(tmp_path / "val.avro"),
        "--reg-weights", "1.0",
        "--hash-dim", "512",
        "--compute-variances",
        "--chunk-rows", "64",
    ]
    assert glm_main(common + ["--output-dir", str(tmp_path / "ram"),
                              "--streaming"]) == 0
    assert glm_main(common + ["--output-dir", str(tmp_path / "ooc"),
                              "--out-of-core"]) == 0

    from photon_ml_tpu.io.model_io import load_game_model

    w_ram = np.asarray(
        load_game_model(str(tmp_path / "ram" / "best"))["global"]
        .model.coefficients.means)
    best = load_game_model(str(tmp_path / "ooc" / "best"))["global"].model
    w_ooc = np.asarray(best.coefficients.means)
    np.testing.assert_allclose(w_ooc, w_ram, rtol=1e-4, atol=1e-6)
    assert best.coefficients.variances is not None
    log = [json.loads(l) for l in
           (tmp_path / "ooc" / "photon.log.jsonl").read_text().splitlines()]
    assert [r for r in log if r["event"] == "validate_skipped_out_of_core"]
    auc = [r for r in log
           if r["event"] == "lambda_trained"][0]["metrics"]["auc"]
    assert auc > 0.6


def test_glm_driver_out_of_core_needs_pinned_space(tmp_path, logistic_data):
    from photon_ml_tpu.io.data_reader import (
        feature_tuples_from_dense,
        write_training_examples,
    )

    X, y = logistic_data
    write_training_examples(
        str(tmp_path / "train.avro"), feature_tuples_from_dense(X[:50]),
        y[:50])
    with pytest.raises(SystemExit, match="pinned feature space"):
        glm_main([
            "--train-data", str(tmp_path / "train.avro"),
            "--output-dir", str(tmp_path / "out"),
            "--reg-weights", "1.0", "--out-of-core",
        ])


def test_glm_driver_validation_rejects_bad_labels(tmp_path, logistic_data):
    X, y = logistic_data
    y_bad = y.copy()
    y_bad[0] = 3.0  # not a binary label
    write_training_examples(
        str(tmp_path / "train.avro"), feature_tuples_from_dense(X), y_bad
    )
    with pytest.raises(DataValidationError, match="outside"):
        glm_main([
            "--train-data", str(tmp_path / "train.avro"),
            "--output-dir", str(tmp_path / "out"),
            "--reg-weights", "1.0",
        ])


def test_validate_training_data_rules():
    X = np.ones((4, 2))
    y = np.array([0.0, 1.0, 1.0, 0.0])
    validate_training_data(X, y, task="logistic")  # clean passes

    with pytest.raises(DataValidationError, match="non-finite labels"):
        validate_training_data(X, np.array([0.0, np.nan, 1.0, 0.0]))
    with pytest.raises(DataValidationError, match="negative labels"):
        validate_training_data(X, np.array([1.0, -2.0, 0.0, 3.0]), task="poisson")
    with pytest.raises(DataValidationError, match="non-finite feature"):
        validate_training_data(np.array([[np.inf, 1.0]]), np.array([1.0]))
    with pytest.raises(DataValidationError, match="non-positive weights"):
        validate_training_data(X, y, weights=np.array([1.0, 0.0, 1.0, 1.0]))
    with pytest.raises(DataValidationError, match="non-finite offsets"):
        validate_training_data(X, y, offsets=np.array([0.0, np.nan, 0.0, 0.0]))


def test_glm_device_loss_persists_lambdas_and_resumes(tmp_path, logistic_data,
                                                      monkeypatch):
    """Device loss mid-grid: finished lambdas persist to RESUME_GLM.npz and
    exit 75; --auto-resume replays them (same warm-start chain) and the
    final outputs match an uninterrupted run."""
    import jax

    from photon_ml_tpu.parallel import data_parallel as dp

    X, y = logistic_data
    _write_libsvm(tmp_path / "train.svm", X[:300], y[:300])
    _write_libsvm(tmp_path / "val.svm", X[300:], y[300:])
    argv = [
        "--train-data", str(tmp_path / "train.svm"),
        "--validation-data", str(tmp_path / "val.svm"),
        "--input-format", "libsvm",
        "--reg-weights", "10.0", "1.0", "0.1",
        "--dtype", "float64",
    ]
    ref_out = tmp_path / "ref_out"
    assert glm_main(argv + ["--output-dir", str(ref_out)]) == 0

    out = tmp_path / "out"
    real_fit = dp.fit_distributed
    calls = {"n": 0}

    def crashing_fit(*a, **kw):
        calls["n"] += 1
        if calls["n"] == 3:  # die INSIDE the second lambda's fit
            raise jax.errors.JaxRuntimeError(
                "UNAVAILABLE: TPU worker process crashed or restarted.")
        return real_fit(*a, **kw)

    # the driver binds fit_distributed at module import; patch its module
    from photon_ml_tpu.cli import glm_driver as drv

    monkeypatch.setattr(drv, "fit_distributed", crashing_fit)
    rc = glm_main(argv + ["--output-dir", str(out)])
    # calls 1-2 = first lambda warm-up? (one call per lambda) -> crash on
    # lambda #3's call or #2 depending on internals; either way rc==75
    assert rc == 75
    assert (out / "RESUME_GLM.npz").exists()

    monkeypatch.setattr(drv, "fit_distributed", real_fit)
    rc = glm_main(argv + ["--output-dir", str(out), "--auto-resume"])
    assert rc == 0
    assert not (out / "RESUME_GLM.npz").exists()

    log = [json.loads(l)
           for l in (out / "photon.log.jsonl").read_text().splitlines()]
    assert any(r["event"] == "device_lost" for r in log)
    ref_log = [json.loads(l)
               for l in (ref_out / "photon.log.jsonl").read_text().splitlines()]

    def trained(lg):
        return {r["reg_weight"]: r["metrics"]["auc"] for r in lg
                if r["event"] == "lambda_trained"}

    # union of pre-crash (first run) + post-resume lambdas == the full grid,
    # with the same per-lambda validation metrics as the uninterrupted run
    seen = trained(log)
    ref = trained(ref_log)
    assert set(seen) == set(ref)
    for lam, auc in seen.items():
        np.testing.assert_allclose(auc, ref[lam], rtol=1e-6)
    done = [r for r in log if r["event"] == "driver_done"][0]
    ref_done = [r for r in ref_log if r["event"] == "driver_done"][0]
    assert done["best_reg_weight"] == ref_done["best_reg_weight"]
    # native-dtype persistence: the resumed warm-start chain reproduces the
    # uninterrupted run's best model EXACTLY (f64 end to end)
    np.testing.assert_array_equal(_best_means(out), _best_means(ref_out))


def _best_means(out):
    from photon_ml_tpu.io.model_io import load_game_model

    m = load_game_model(str(out / "best"))
    return np.asarray(m.coordinates["global"].model.coefficients.means)


def test_glm_resume_refuses_changed_grid_or_evaluators(tmp_path,
                                                       logistic_data,
                                                       monkeypatch):
    """The resume marker must be a prefix of the SAME grid and cover the
    current evaluator — mixed settings are refused loudly, not merged."""
    import jax
    import pytest

    from photon_ml_tpu.cli import glm_driver as drv

    X, y = logistic_data
    _write_libsvm(tmp_path / "train.svm", X[:300], y[:300])
    _write_libsvm(tmp_path / "val.svm", X[300:], y[300:])
    out = tmp_path / "out"
    base = ["--train-data", str(tmp_path / "train.svm"),
            "--input-format", "libsvm", "--output-dir", str(out),
            "--dtype", "float64"]

    real_fit = drv.fit_distributed
    calls = {"n": 0}

    def crashing_fit(*a, **kw):
        calls["n"] += 1
        if calls["n"] == 2:
            raise jax.errors.JaxRuntimeError("UNAVAILABLE: worker crashed")
        return real_fit(*a, **kw)

    monkeypatch.setattr(drv, "fit_distributed", crashing_fit)
    assert glm_main(base + ["--reg-weights", "10.0", "1.0"]) == 75
    monkeypatch.setattr(drv, "fit_distributed", real_fit)

    with pytest.raises(ValueError, match="not a\n?.*prefix|prefix"):
        glm_main(base + ["--reg-weights", "5.0", "1.0", "--auto-resume"])
    with pytest.raises(ValueError, match="evaluator"):
        glm_main(base + ["--reg-weights", "10.0", "1.0", "--auto-resume",
                         "--validation-data", str(tmp_path / "val.svm")])
    # unchanged settings resume fine, and the marker is consumed
    assert glm_main(base + ["--reg-weights", "10.0", "1.0",
                            "--auto-resume"]) == 0
    assert not (out / "RESUME_GLM.npz").exists()
