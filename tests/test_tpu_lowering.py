"""TPU-target lowering certification (chip readiness without a chip).

``jax.export(..., platforms=["tpu"])`` runs the full StableHLO (and
Pallas->Mosaic) lowering for the TPU target from a CPU host — the layer
interpret-mode execution parity can never exercise. Round 4 this caught
two chip-blocking kernel bugs (docs/PERF.md "Round-4 Mosaic lowering"),
so every distributed hot-path program is pinned here: a live chip session
must start at "compile", not "debug the lowering" (VERDICT r3 #4).

These certify LOWERING only; Mosaic's compile to LLO and the numerics
still need the chip (scripts/tpu_session.sh).
"""

import jax
import jax.numpy as jnp
import pytest
from jax import export

from photon_ml_tpu.ops.objective import make_objective
from photon_ml_tpu.optimize import OptimizerConfig
from photon_ml_tpu.parallel.data_parallel import fit_distributed
from photon_ml_tpu.parallel.mesh import make_mesh
from photon_ml_tpu.types import LabeledBatch, SparseFeatures

N, D, K = 2048, 512, 8


def _fit_exporter(mesh_axes={"data": 8}, **kw):
    obj = make_objective("logistic")
    cfg = OptimizerConfig(max_iters=4, tolerance=0.0)
    mesh = make_mesh(dict(mesh_axes))

    def f(w0, indices, labels):
        batch = LabeledBatch(
            SparseFeatures(indices, None, dim=D), labels,
            jnp.zeros((N,), jnp.float32), jnp.ones((N,), jnp.float32))
        r = fit_distributed(obj, batch, mesh, w0, l2=0.5, config=cfg, **kw)
        return r.w, r.value

    return export.export(jax.jit(f), platforms=["tpu"])(
        jax.ShapeDtypeStruct((D,), jnp.float32),
        jax.ShapeDtypeStruct((N, K), jnp.int32),
        jax.ShapeDtypeStruct((N,), jnp.float32))


@pytest.mark.parametrize("kw", [
    dict(optimizer="lbfgs"),                             # margin + scatter
    dict(optimizer="lbfgs", sparse_grad="csc"),
    dict(optimizer="lbfgs", sparse_grad="csc_segment"),
    dict(optimizer="tron", line_search="full"),
    dict(optimizer="owlqn", line_search="full"),
], ids=lambda kw: "-".join(str(v) for v in kw.values()))
def test_distributed_fit_lowers_for_tpu(kw):
    exp = _fit_exporter(**kw)
    assert exp.nr_devices == 8


def test_sharded_csc_pallas_lowers_with_mosaic_kernel():
    """Under shard_map, lax.platform_dependent must still pick the REAL
    Mosaic kernel for the TPU target (not the interpret branch)."""
    exp = _fit_exporter(optimizer="lbfgs", sparse_grad="csc_pallas")
    assert exp.nr_devices == 8
    assert "tpu_custom_call" in exp.mlir_module()


def test_newton_re_solver_lowers_for_tpu():
    """The batched dense-Newton RE solver (einsum Hessians + batched SPD
    solve) under an entity-axis shard_map lowers for TPU."""
    from photon_ml_tpu.game.random_effect import _jitted_sharded_solver

    E, D_loc, rows = 16, 6, 32
    run = _jitted_sharded_solver(
        D_loc, "logistic", "newton",
        OptimizerConfig(max_iters=5, tolerance=1e-6),
        False, make_mesh({"entity": 8}), "entity", 0)
    s = jax.ShapeDtypeStruct
    exp = export.export(run, platforms=["tpu"])(
        s((E, rows, D_loc), jnp.int32), s((E, rows, D_loc), jnp.float32),
        s((E, rows), jnp.float32), s((E, rows), jnp.float32),
        s((E, rows), jnp.float32), s((E, D_loc), jnp.float32),
        s((E, 1), jnp.float32), s((E, 1), jnp.float32),
        s((), jnp.float32), s((), jnp.float32))
    assert exp.nr_devices == 8


def test_fixed_fit_lowers_on_two_axis_game_mesh():
    """The GAME CD loop runs the fixed-effect fit on the 'data' axis of a
    2-axis (data x entity) mesh — axis-name handling must lower for TPU
    with the extra axis present."""
    exp = _fit_exporter(mesh_axes={"data": 2, "entity": 4},
                        sparse_grad="csc")
    assert exp.nr_devices == 8


def test_streamed_chunk_kernels_lower_for_tpu_collective_free():
    """The streamed per-chunk kernels (fg / hvp / diag / ladder trial)
    must lower for TPU with ZERO collectives in the chunk program — the
    per-device-partials design (streaming._shard_map_chunk) that fixed
    the XLA:CPU rendezvous deadlock is also the one-all-reduce-per-pass
    ICI cost model; a collective sneaking back in (e.g. check_vma
    auto-psum) would silently restore both problems."""
    from photon_ml_tpu.ops.losses import apply_weights, mask_margins  # noqa
    from photon_ml_tpu.optimize import OptimizerConfig as Cfg
    from photon_ml_tpu.parallel.data_parallel import cached_jit
    from photon_ml_tpu.parallel.streaming import (
        fit_streaming,
        streaming_hessian_diagonal,
        streaming_hvp,
        streaming_value_and_grad,
    )

    obj = make_objective("logistic")
    mesh = make_mesh({"data": 8})
    rows = 256
    # instantiate every cached kernel (empty chunk lists: the kernels are
    # built before iteration, and lowering needs only their closures)
    streaming_value_and_grad(obj, [], D, mesh=mesh)
    streaming_hvp(obj, [], D, mesh=mesh)
    streaming_hessian_diagonal(obj, [], D, jnp.zeros((D,)), mesh=mesh)
    fit_streaming(obj, [], D, config=Cfg(max_iters=1, tolerance=0.0),
                  mesh=mesh)  # builds the margin trial ladder kernel
    s = jax.ShapeDtypeStruct
    f32, i32 = jnp.float32, jnp.int32

    def assert_no_collective(exp, name):
        mlir = exp.mlir_module()
        for spelling in ("all_reduce", "all-reduce", "psum"):
            assert spelling not in mlir, f"{name}: {spelling} found"

    batch_args = (s((rows, K), i32), (), s((rows,), f32),
                  s((rows,), f32), s((rows,), f32))
    fg_k = cached_jit(obj, ("stream_fg", mesh, "data", D), lambda: None)
    exp = export.export(fg_k, platforms=["tpu"])(
        s((D,), f32), *batch_args,
        s((8,), f32), s((8,), f32), s((8, D), f32), s((8, D), f32))
    assert exp.nr_devices == 8
    assert_no_collective(exp, "stream_fg")
    hvp_k = cached_jit(obj, ("stream_hvp", mesh, "data", D), lambda: None)
    assert_no_collective(export.export(hvp_k, platforms=["tpu"])(
        (s((D,), f32), s((D,), f32)), *batch_args,
        s((8, D), f32), s((8, D), f32)), "stream_hvp")
    diag_k = cached_jit(obj, ("stream_diag", mesh, "data", D), lambda: None)
    assert_no_collective(export.export(diag_k, platforms=["tpu"])(
        s((D,), f32), *batch_args,
        s((8, D), f32), s((8, D), f32)), "stream_diag")
    L = 8  # default ladder width (min(max_line_search_steps, 8))
    trial_k = cached_jit(obj, ("stream_trial_delta_ladder", mesh, "data", L),
                         lambda: None)
    assert_no_collective(export.export(trial_k, platforms=["tpu"])(
        s((L,), f32), s((rows,), f32), s((rows,), f32), s((rows,), f32),
        s((rows,), f32), s((8, L), f32), s((8, L), f32)), "stream_trial")


def test_device_auc_evaluator_lowers_for_tpu():
    """The per-iteration device AUC (histogram form on a mesh, exact sort
    single-device) used for CD validation lowers for TPU."""
    from photon_ml_tpu.evaluation.device import make_device_evaluator

    mesh = make_mesh({"data": 8})
    fn = make_device_evaluator("auc", mesh)
    s = jax.ShapeDtypeStruct
    exp = export.export(jax.jit(fn), platforms=["tpu"])(
        s((N,), jnp.float32), s((N,), jnp.float32), s((N,), jnp.float32))
    assert exp.nr_devices == 8


def test_grouped_device_evaluators_lower_for_tpu():
    """The per_group_* device evaluators (lexsort + segment ops over
    factorized group ids) used for CD per-iteration monitoring lower for
    the TPU target."""
    import numpy as np

    from photon_ml_tpu.evaluation.device import make_grouped_device_evaluator

    groups = np.arange(N) % 17
    s = jax.ShapeDtypeStruct
    for name in ("per_group_auc", "per_group_logistic_loss",
                 "per_group_precision_at_5"):
        fn = make_grouped_device_evaluator(name, groups)
        exp = export.export(jax.jit(fn), platforms=["tpu"])(
            s((N,), jnp.float32), s((N,), jnp.float32), s((N,), jnp.float32))
        assert "stablehlo" in exp.mlir_module(), name


def test_vector_gather_fit_lowers_for_tpu():
    """The r05 vectorized table gather ('auto' on hardware). jax.export
    runs from a CPU host, where 'auto' traces the SCALAR branch — so the
    chip's actual path must be pinned to 'vector' explicitly here or the
    certification would silently cover the wrong program."""
    from photon_ml_tpu import types as T

    prev = T.gather_mode()
    T.set_gather_mode("vector")
    try:
        for kw in (dict(optimizer="lbfgs"),
                   dict(optimizer="lbfgs", sparse_grad="csc"),
                   dict(optimizer="lbfgs", sparse_grad="csc_pallas")):
            exp = _fit_exporter(**kw)
            assert exp.nr_devices == 8
    finally:
        T.set_gather_mode(prev)


def test_vector_gather_chunked_lowers_for_tpu():
    """The lax.map-chunked large-nnz form (bench shape takes it)."""
    from photon_ml_tpu import types as T

    prev = T.gather_mode()
    T.set_gather_mode("vector")
    old = T._GATHER_CHUNK
    T._GATHER_CHUNK = 1 << 12  # force chunking at test size
    try:
        def f(w, idx):
            return T.table_gather(w, idx).sum()

        exp = export.export(jax.jit(f), platforms=["tpu"])(
            jax.ShapeDtypeStruct((1 << 14,), jnp.float32),
            jax.ShapeDtypeStruct((1 << 14, 8), jnp.int32))
        assert exp.platforms == ("tpu",)
    finally:
        T._GATHER_CHUNK = old
        T.set_gather_mode(prev)
