"""f32-vs-f64 numerics parity (SURVEY.md §7 hard part): the harness the
real-chip evidence uses, exercised CPU-vs-CPU in CI. The TPU leg runs the
same script with the default platform (scripts/f32_parity.py compare)."""

import json
import os
import subprocess
import sys


def test_f32_parity_harness_cpu():
    script = os.path.join(os.path.dirname(os.path.dirname(__file__)),
                          "scripts", "f32_parity.py")
    out = subprocess.run(
        [sys.executable, script, "compare", "--platform", "cpu"],
        capture_output=True, text=True, timeout=900,
        env=dict(os.environ, PYTHONPATH=os.path.dirname(os.path.dirname(__file__))),
    )
    assert out.returncode == 0, out.stderr[-2000:]
    report = json.loads(out.stdout)
    assert report["pass"]
    assert report["delta_auc"] < 1e-3
    assert report["rel_delta_val_loss"] < 1e-4
    # both legs converged on the same problem
    assert report["f64_cpu"]["converged"] and report["f32"]["converged"]
