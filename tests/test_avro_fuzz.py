"""Property-based fuzzing of the self-contained Avro codec: arbitrary
records must round-trip bit-exact through write_avro_file/read_avro_file
(the external data contract — SURVEY.md §3.4)."""

import math

from hypothesis import given, settings, strategies as st

from photon_ml_tpu.io.avro import read_avro_file, write_avro_file


FUZZ_SCHEMA = {
    "type": "record",
    "name": "Fuzz",
    "fields": [
        {"name": "uid", "type": ["null", "string", "long"], "default": None},
        {"name": "response", "type": "double"},
        {"name": "flag", "type": "boolean"},
        {"name": "count", "type": "long"},
        {"name": "ratio", "type": "float"},
        {"name": "blob", "type": "bytes"},
        {"name": "features", "type": {"type": "array", "items": {
            "type": "record", "name": "FeatureFuzz", "fields": [
                {"name": "name", "type": "string"},
                {"name": "term", "type": "string", "default": ""},
                {"name": "value", "type": "double"},
            ]}}},
        {"name": "metadataMap", "type": {"type": "map", "values": "string"},
         "default": {}},
    ],
}

finite_doubles = st.floats(allow_nan=False, allow_infinity=False, width=64)
text = st.text(max_size=30)

feature = st.fixed_dictionaries({
    "name": text, "term": text, "value": finite_doubles,
})

record = st.fixed_dictionaries({
    "uid": st.one_of(st.none(), text,
                     st.integers(-(2**62), 2**62)),
    "response": finite_doubles,
    "flag": st.booleans(),
    "count": st.integers(-(2**63), 2**63 - 1),
    "ratio": st.floats(allow_nan=False, allow_infinity=False, width=32),
    "blob": st.binary(max_size=40),
    "features": st.lists(feature, max_size=5),
    "metadataMap": st.dictionaries(text, text, max_size=4),
})


@settings(max_examples=40, deadline=None)
@given(records=st.lists(record, max_size=8),
       codec=st.sampled_from(["null", "deflate"]))
def test_avro_roundtrip_fuzz(tmp_path_factory, records, codec):
    path = str(tmp_path_factory.mktemp("avro") / "fuzz.avro")
    write_avro_file(path, records, FUZZ_SCHEMA, codec=codec)
    got, schema = read_avro_file(path)
    assert len(got) == len(records)
    for a, b in zip(got, records):
        assert a["uid"] == b["uid"]
        assert a["response"] == b["response"]
        assert a["flag"] == b["flag"]
        assert a["count"] == b["count"]
        assert math.isclose(a["ratio"], b["ratio"], rel_tol=1e-6, abs_tol=1e-30)
        assert a["blob"] == b["blob"]
        assert a["features"] == b["features"]
        assert a["metadataMap"] == b["metadataMap"]
