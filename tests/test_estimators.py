"""GameEstimator / GameTransformer tests (SURVEY.md §3.2 layer 5)."""

import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.estimators import GameEstimator, GameTransformer
from photon_ml_tpu.game.descent import CoordinateConfig, make_game_dataset


def _binary_data(rng, n=400, d=8):
    X = rng.normal(size=(n, d))
    w = rng.normal(size=d)
    y = (rng.random(n) < 1 / (1 + np.exp(-X @ w))).astype(float)
    return X, y


def test_estimator_grid_and_selection(rng):
    X, y = _binary_data(rng)
    tr, va = np.arange(300), np.arange(300, 400)
    ds_tr = make_game_dataset(X[tr], y[tr])
    ds_va = make_game_dataset(X[va], y[va])
    est = GameEstimator(task="logistic", evaluators=["auc", "logistic_loss"],
                        dtype=jnp.float64)
    grid = [
        [CoordinateConfig("fixed", reg_type="l2", reg_weight=w)]
        for w in (0.01, 1.0, 1000.0)
    ]
    results = est.fit(ds_tr, ds_va, config_grid=grid)
    assert len(results) == 3
    for r in results:
        assert set(r.evaluation.metrics) == {"auc", "logistic_loss"}
    best = est.select_best(results)
    assert best.evaluation.primary_value == max(
        r.evaluation.metrics["auc"] for r in results
    )
    # with logistic_loss primary (lower is better), selection flips direction:
    # the over-regularized w->0 model has the worst calibrated loss
    est_ll = GameEstimator(task="logistic", evaluators=["logistic_loss"],
                           dtype=jnp.float64)
    results_ll = est_ll.fit(ds_tr, ds_va, config_grid=grid)
    best_ll = est_ll.select_best(results_ll)
    assert best_ll.configs[0].reg_weight != 1000.0
    assert best_ll.evaluation.primary_value == min(
        r.evaluation.metrics["logistic_loss"] for r in results_ll
    )


def test_estimator_empty_grid_rejected(rng):
    X, y = _binary_data(rng, n=50)
    est = GameEstimator()
    with pytest.raises(ValueError, match="config_grid"):
        est.fit(make_game_dataset(X, y))


def test_transformer_scores_match_cd_validation_scores(rng):
    # transformer scoring a dataset == CD's own validation scoring
    from photon_ml_tpu.game.descent import CoordinateDescent

    n_users = 10
    Xg = rng.normal(size=(300, 6))
    Xu = rng.normal(size=(300, 3))
    uid = rng.integers(0, n_users, 300)
    y = (rng.random(300) < 0.5).astype(float)
    feats = {"g": Xg, "u": Xu}
    ds = make_game_dataset(feats, y, entity_ids={"userId": uid})
    cd = CoordinateDescent(
        [CoordinateConfig("fixed", feature_shard="g", reg_type="l2", reg_weight=1.0),
         CoordinateConfig("per-user", coordinate_type="random", feature_shard="u",
                          entity_column="userId", reg_type="l2", reg_weight=2.0)],
        task="logistic", evaluators=["auc"], dtype=jnp.float64,
    )
    model, hist = cd.run(ds, ds)  # validation == train for comparison
    tf = GameTransformer(model, dtype=jnp.float64)
    metrics = tf.evaluate(ds, ["auc"])
    assert np.isclose(metrics["auc"], hist[-1]["auc"], atol=1e-9)
    # probabilities are sigmoid of margins
    probs = tf.predict_mean(ds)
    assert np.all((probs >= 0) & (probs <= 1))
    # per-coordinate breakdown sums to the total
    total, parts = tf.transform(ds, per_coordinate=True)
    np.testing.assert_allclose(
        np.asarray(total), sum(np.asarray(p) for p in parts.values()), rtol=1e-10
    )
