"""Request-id propagation end to end, over real sockets: the front
door and both server flavors honor a client ``X-Request-Id`` (or assign
one), echo it on EVERY response including 400/404/429/503 bodies, and —
with tracing on — one request's spans line up under that id across
front-door proxy, replica HTTP handling, batcher execution, and
device compute."""

import asyncio
import json
import urllib.error
import urllib.request

import pytest

from photon_ml_tpu.obs import trace
from tests.conftest import serving_rows


async def _http(host, port, method, path, payload=None, headers=None):
    """Minimal HTTP/1.1 client returning (status, headers, body_json)."""
    reader, writer = await asyncio.open_connection(host, port)
    body = b"" if payload is None else json.dumps(payload).encode()
    extra = "".join(f"{k}: {v}\r\n" for k, v in (headers or {}).items())
    writer.write(
        (f"{method} {path} HTTP/1.1\r\nHost: t\r\n"
         f"Content-Type: application/json\r\n{extra}"
         f"Content-Length: {len(body)}\r\n\r\n").encode() + body)
    await writer.drain()
    head = await reader.readuntil(b"\r\n\r\n")
    lines = head.decode().split("\r\n")
    status = int(lines[0].split(" ")[1])
    hdrs = {}
    for line in lines[1:]:
        if ":" in line:
            k, _, v = line.partition(":")
            hdrs[k.strip().lower()] = v.strip()
    length = int(hdrs.get("content-length", "0"))
    raw = await reader.readexactly(length) if length else b""
    try:
        parsed = json.loads(raw) if raw else None
    except json.JSONDecodeError:
        parsed = raw.decode()
    writer.close()
    return status, hdrs, parsed


def _service(saved_game_model, **batcher_kw):
    from photon_ml_tpu.serve import (
        MicroBatcher,
        ScoringService,
        ScoringSession,
    )

    model_dir, bundle = saved_game_model
    session = ScoringSession(model_dir, dtype="float64", max_batch=16,
                             coeff_cache_entries=32)
    batcher_kw.setdefault("max_batch", 16)
    batcher_kw.setdefault("max_delay_ms", 2.0)
    batcher = MicroBatcher(session.score_rows, metrics=session.metrics,
                           **batcher_kw)
    return ScoringService(session, batcher), bundle


class TestAsyncServer:
    def test_echo_and_assignment_on_every_path(self, saved_game_model):
        from photon_ml_tpu.serve import AsyncScoringServer

        service, bundle = _service(saved_game_model)
        rows = serving_rows(bundle, [0, 1])
        rid = {"X-Request-Id": "client-rid-1"}

        async def run():
            server = await AsyncScoringServer(service).start()
            h, p = server.host, server.port
            out = {
                "score": await _http(h, p, "POST", "/score",
                                     {"rows": rows}, headers=rid),
                "assigned": await _http(h, p, "POST", "/score",
                                        {"rows": rows}),
                "health": await _http(h, p, "GET", "/healthz",
                                      headers=rid),
                "notfound": await _http(h, p, "GET", "/nope",
                                        headers=rid),
                "bad": await _http(h, p, "POST", "/score", {"rows": []},
                                   headers=rid),
            }
            await server.aclose()
            return out

        out = asyncio.run(run())
        for name in ("score", "health", "notfound", "bad"):
            assert out[name][1]["x-request-id"] == "client-rid-1", name
        # no client id -> the server assigns one and still echoes it
        assigned = out["assigned"][1]["x-request-id"]
        assert assigned and assigned != "client-rid-1"
        # the 400 body names the request so client logs can correlate
        assert out["bad"][0] == 400
        assert out["bad"][2]["requestId"] == "client-rid-1"

    def test_shed_429_body_carries_request_id(self, saved_game_model):
        from photon_ml_tpu.serve import AsyncScoringServer

        service, bundle = _service(saved_game_model, max_queue=2,
                                   max_delay_ms=20.0)
        rows = serving_rows(bundle, [0])

        async def run():
            server = await AsyncScoringServer(service).start()
            h, p = server.host, server.port
            results = await asyncio.gather(
                *[_http(h, p, "POST", "/score", {"rows": rows},
                        headers={"X-Request-Id": f"burst-{i}"})
                  for i in range(30)])
            await server.aclose()
            return results

        results = asyncio.run(run())
        shed = [r for r in results if r[0] == 429]
        assert shed, "burst over a 2-deep queue must shed"
        for _s, headers, body in shed:
            assert headers["x-request-id"].startswith("burst-")
            assert body["requestId"] == headers["x-request-id"]
            assert "retry-after" in headers


class TestFrontDoor:
    def test_proxy_echo_and_503_body(self, saved_game_model):
        from photon_ml_tpu.serve import AsyncFrontDoor, AsyncScoringServer

        service, bundle = _service(saved_game_model)
        rows = serving_rows(bundle, [0, 1])
        rid = {"X-Request-Id": "door-rid-9"}

        async def run():
            backend = await AsyncScoringServer(service).start()
            door = await AsyncFrontDoor(
                [f"127.0.0.1:{backend.port}"],
                retry_backend_s=0.05).start()
            ok = await _http(door.host, door.port, "POST", "/score",
                             {"rows": rows}, headers=rid)
            await backend.aclose()
            dead = await _http(door.host, door.port, "POST", "/score",
                               {"rows": rows}, headers=rid)
            await door.aclose()
            return ok, dead

        ok, dead = asyncio.run(run())
        # echoed back THROUGH the proxy: the replica saw the same id
        assert ok[0] == 200
        assert ok[1]["x-request-id"] == "door-rid-9"
        assert dead[0] == 503
        assert dead[1]["x-request-id"] == "door-rid-9"
        assert dead[2]["requestId"] == "door-rid-9"

    def test_fd_metrics_merges_replica_scrapes(self, saved_game_model):
        from photon_ml_tpu.serve import AsyncFrontDoor, AsyncScoringServer

        service_a, bundle = _service(saved_game_model)
        service_b, _ = _service(saved_game_model)
        rows = serving_rows(bundle, [0, 1, 2])

        async def run():
            a = await AsyncScoringServer(service_a).start()
            b = await AsyncScoringServer(service_b).start()
            door = await AsyncFrontDoor(
                [f"127.0.0.1:{a.port}", f"127.0.0.1:{b.port}"]).start()
            for _ in range(8):
                await _http(door.host, door.port, "POST", "/score",
                            {"rows": rows})
            got = await _http(door.host, door.port, "GET", "/fd/metrics")
            await door.aclose()
            await a.aclose()
            await b.aclose()
            return got, a.port, b.port

        (status, headers, text), pa, pb = asyncio.run(run())
        assert status == 200
        assert headers["content-type"].startswith("text/plain")
        # every replica's series appear, disambiguated by the label
        assert f'replica="127.0.0.1:{pa}"' in text
        assert f'replica="127.0.0.1:{pb}"' in text
        assert 'photon_serve_requests_total{replica=' in text
        # the door's own counters ride along
        assert "photon_fd_proxied_total 8" in text
        for port in (pa, pb):
            assert (f'photon_fd_backend_picked_total{{'
                    f'backend="127.0.0.1:{port}"}}') in text
        # TYPE/HELP lines are deduped across replicas
        assert text.count("# TYPE photon_serve_requests_total") == 1


class TestThreadedServer:
    def test_request_id_parity_with_async_flavor(self, saved_game_model):
        """The blocking server honors the same header contract."""
        from photon_ml_tpu.serve import ScoringServer

        svc, bundle = _service(saved_game_model)
        server = ScoringServer(svc, port=0).start()
        try:
            url = f"http://127.0.0.1:{server.port}"
            req = urllib.request.Request(
                url + "/score",
                data=json.dumps(
                    {"rows": serving_rows(bundle, [0])}).encode(),
                headers={"Content-Type": "application/json",
                         "X-Request-Id": "thr-1"})
            with urllib.request.urlopen(req, timeout=30) as r:
                assert r.status == 200
                assert r.headers["X-Request-Id"] == "thr-1"
            with urllib.request.urlopen(url + "/healthz",
                                        timeout=30) as r:
                assert r.headers["X-Request-Id"]  # assigned
            bad = urllib.request.Request(
                url + "/score", data=b'{"rows": []}',
                headers={"Content-Type": "application/json",
                         "X-Request-Id": "thr-2"})
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(bad, timeout=30)
            assert ei.value.code == 400
            assert ei.value.headers["X-Request-Id"] == "thr-2"
            assert json.loads(ei.value.read())["requestId"] == "thr-2"
        finally:
            server.close()


class TestTraceCorrelation:
    def test_one_request_spans_share_id_across_the_stack(
            self, saved_game_model, tmp_path):
        """The acceptance path: front-door proxy -> replica http.score
        -> batch.execute -> session device compute, all in one process
        here, every span stamped with the client's request id."""
        from photon_ml_tpu.serve import AsyncFrontDoor, AsyncScoringServer

        service, bundle = _service(saved_game_model)
        rows = serving_rows(bundle, [0, 1])
        tracer = trace.start(str(tmp_path), sample=1.0,
                             export_thread=False)
        try:
            async def run():
                backend = await AsyncScoringServer(service).start()
                door = await AsyncFrontDoor(
                    [f"127.0.0.1:{backend.port}"]).start()
                got = await _http(door.host, door.port, "POST", "/score",
                                  {"rows": rows},
                                  headers={"X-Request-Id": "trace-me"})
                await door.aclose()
                await backend.aclose()
                return got

            status, headers, _ = asyncio.run(run())
            assert status == 200
            assert headers["x-request-id"] == "trace-me"
            events = list(tracer._events)
        finally:
            trace.stop()

        mine = [e for e in events
                if e["args"].get("request_id") == "trace-me"
                or "trace-me" in (e["args"].get("request_ids") or [])]
        names = {e["name"] for e in mine}
        assert {"fd.proxy", "http.score", "batch.execute",
                "session.device_compute"} <= names, names
        # cross-process correlation is by request id; WITHIN the
        # replica, one trace id covers http handling through device
        # compute (the door, a separate logical process, roots its own)
        replica = {e["args"]["trace_id"] for e in mine
                   if e["name"] in ("http.score", "batch.execute",
                                    "session.device_compute")}
        assert len(replica) == 1
