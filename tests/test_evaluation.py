"""Evaluator parity tests vs sklearn (tie handling included)."""

import numpy as np
import pytest
from sklearn.metrics import mean_squared_error, roc_auc_score

from photon_ml_tpu.evaluation import get_evaluator


def test_auc_matches_sklearn(rng):
    y = (rng.random(300) < 0.4).astype(float)
    s = rng.normal(size=300)
    ev = get_evaluator("auc")
    assert np.isclose(ev.evaluate(s, y), roc_auc_score(y, s), atol=1e-12)


def test_auc_with_ties_matches_sklearn(rng):
    y = (rng.random(500) < 0.5).astype(float)
    s = rng.integers(0, 5, size=500).astype(float)  # heavy ties
    ev = get_evaluator("auc")
    assert np.isclose(ev.evaluate(s, y), roc_auc_score(y, s), atol=1e-12)


def test_weighted_auc_equals_replication(rng):
    # integer weights == replicating rows
    y = (rng.random(60) < 0.5).astype(float)
    s = rng.normal(size=60)
    w = rng.integers(1, 4, size=60).astype(float)
    ev = get_evaluator("auc")
    y_rep = np.repeat(y, w.astype(int))
    s_rep = np.repeat(s, w.astype(int))
    assert np.isclose(ev.evaluate(s, y, w), roc_auc_score(y_rep, s_rep), atol=1e-10)


def test_auc_degenerate_single_class():
    ev = get_evaluator("auc")
    assert np.isnan(ev.evaluate(np.array([1.0, 2.0]), np.array([1.0, 1.0])))
    # grouped variant skips degenerate groups instead of failing
    g = get_evaluator("per_group_auc")
    scores = np.array([1.0, 2.0, 3.0, 0.5])
    labels = np.array([1.0, 1.0, 1.0, 0.0])
    groups = np.array([0, 0, 1, 1])
    v = g.evaluate(scores, labels, group_ids=groups)
    assert np.isclose(v, 1.0)  # only group 1 is evaluable; AUC there is 1


def test_rmse_and_losses(rng):
    y = rng.normal(size=100)
    s = y + rng.normal(size=100) * 0.1
    ev = get_evaluator("rmse")
    assert np.isclose(ev.evaluate(s, y), np.sqrt(mean_squared_error(y, s)), atol=1e-12)
    ll = get_evaluator("logistic_loss")
    yb = (rng.random(100) < 0.5).astype(float)
    expected = np.mean(np.logaddexp(0, s) - yb * s)
    assert np.isclose(ll.evaluate(s, yb), expected, atol=1e-12)


def test_precision_at_k(rng):
    scores = np.array([5.0, 4.0, 3.0, 2.0, 1.0])
    labels = np.array([1.0, 0.0, 1.0, 0.0, 0.0])
    groups = np.zeros(5)
    ev = get_evaluator("precision_at_2")
    assert np.isclose(ev.evaluate(scores, labels, group_ids=groups), 0.5)
    ev3 = get_evaluator("precision_at_3")
    assert np.isclose(ev3.evaluate(scores, labels, group_ids=groups), 2 / 3)


def test_evaluator_selection_direction():
    assert get_evaluator("auc").better(0.9, 0.8)
    assert get_evaluator("rmse").better(0.1, 0.2)
    with pytest.raises(ValueError, match="unknown evaluator"):
        get_evaluator("f1")
