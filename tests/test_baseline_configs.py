"""The five capability configs from BASELINE.md, exercised end-to-end —
one test per config so the parity matrix is explicit:

1. fixed-effect logistic regression (LIBSVM, L-BFGS, L2)
2. linear / Poisson / smoothed-hinge objectives
3. TRON + L1 / elastic-net regularization
4. GAME: fixed effect + per-user random effect (coordinate descent)
5. GAME: per-user + per-item random effects + Bayesian auto-tune
"""

import json

import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.ops.objective import make_objective
from photon_ml_tpu.optimize import OptimizerConfig, get_optimizer
from photon_ml_tpu.testing import (
    game_dataset_from_synthetic,
    synthetic_game_data,
    synthetic_glm_data,
)
from photon_ml_tpu.types import make_batch


def test_config1_libsvm_logistic_lbfgs_l2(tmp_path, rng):
    from photon_ml_tpu.cli.glm_driver import main as glm_main

    data = synthetic_glm_data(500, 12, density=0.4, seed=11)
    with open(tmp_path / "a1a-like.svm", "w") as f:
        for i in range(400):
            toks = [f"{int(data.y[i]) * 2 - 1}"]
            toks += [f"{j + 1}:{data.X[i, j]:.6f}"
                     for j in np.nonzero(data.X[i])[0]]
            f.write(" ".join(toks) + "\n")
    with open(tmp_path / "val.svm", "w") as f:
        for i in range(400, 500):
            toks = [f"{int(data.y[i]) * 2 - 1}"]
            toks += [f"{j + 1}:{data.X[i, j]:.6f}"
                     for j in np.nonzero(data.X[i])[0]]
            f.write(" ".join(toks) + "\n")
    out = tmp_path / "out"
    assert glm_main([
        "--train-data", str(tmp_path / "a1a-like.svm"),
        "--validation-data", str(tmp_path / "val.svm"),
        "--input-format", "libsvm", "--optimizer", "lbfgs",
        "--reg-type", "l2", "--reg-weights", "1.0",
        "--output-dir", str(out), "--dtype", "float64",
    ]) == 0
    log = [json.loads(l) for l in (out / "photon.log.jsonl").read_text().splitlines()]
    auc = [r for r in log if r["event"] == "lambda_trained"][0]["metrics"]["auc"]
    assert auc > 0.75, auc


@pytest.mark.parametrize("task,metric_bound", [
    ("linear", 0.2),          # RMSE close to the noise floor (0.1)
    ("poisson", None),        # converged fit, finite loss
    ("smoothed_hinge", 0.75), # AUC
])
def test_config2_other_objectives(task, metric_bound, rng):
    gen_task = {"linear": "squared"}.get(task, task)
    data = synthetic_glm_data(600, 8, task=gen_task, seed=7)
    batch = make_batch(data.X, data.y, dtype=jnp.float64)
    loss_name = {"linear": "squared"}.get(task, task)
    obj = make_objective(loss_name)
    res = get_optimizer("lbfgs")(
        lambda w: obj.value_and_grad(w, batch, 1e-3),
        jnp.zeros(8, jnp.float64), OptimizerConfig(max_iters=200)
    )
    assert bool(res.converged) and np.isfinite(float(res.value))
    if task == "linear":
        rmse = float(np.sqrt(np.mean(
            (np.asarray(obj.predict(res.w, batch)) - data.y) ** 2)))
        assert rmse < metric_bound, rmse
    elif task == "smoothed_hinge":
        from sklearn.metrics import roc_auc_score

        auc = roc_auc_score(data.y, np.asarray(
            obj.margins(res.w, batch)))
        assert auc > metric_bound, auc
    else:  # poisson: learned rates correlate with labels
        rates = np.asarray(obj.predict(res.w, batch))
        assert np.corrcoef(rates, data.y)[0, 1] > 0.5


def test_config3_tron_and_l1_elastic_net(rng):
    data = synthetic_glm_data(500, 10, seed=3)
    batch = make_batch(data.X, data.y, dtype=jnp.float64)
    obj = make_objective("logistic")
    # TRON (trust region Newton with CG HVPs)
    res_tron = get_optimizer("tron")(
        lambda w: obj.value_and_grad(w, batch, 1.0),
        jnp.zeros(10, jnp.float64), OptimizerConfig(max_iters=60),
        hvp=lambda w, v: obj.hvp(w, v, batch, 1.0),
    )
    assert bool(res_tron.converged)
    # same optimum as L-BFGS
    res_lbfgs = get_optimizer("lbfgs")(
        lambda w: obj.value_and_grad(w, batch, 1.0),
        jnp.zeros(10, jnp.float64), OptimizerConfig(max_iters=200)
    )
    np.testing.assert_allclose(np.asarray(res_tron.w),
                               np.asarray(res_lbfgs.w), rtol=1e-3, atol=1e-4)
    # OWL-QN with strong L1 produces sparsity
    res_l1 = get_optimizer("owlqn")(
        lambda w: obj.value_and_grad(w, batch, 0.0),
        jnp.zeros(10, jnp.float64), 50.0, OptimizerConfig(max_iters=200)
    )
    assert np.sum(np.abs(np.asarray(res_l1.w)) < 1e-10) >= 4


def test_config4_game_fixed_plus_user(rng):
    from photon_ml_tpu.estimators import GameTransformer
    from photon_ml_tpu.evaluation import get_evaluator
    from photon_ml_tpu.game.descent import CoordinateConfig, CoordinateDescent

    data = synthetic_game_data({"userId": 15}, seed=5)
    train = game_dataset_from_synthetic(data)
    cd = CoordinateDescent([
        CoordinateConfig("fixed", coordinate_type="fixed",
                         feature_shard="global", reg_type="l2",
                         reg_weight=0.1, max_iters=60),
        CoordinateConfig("per-user", coordinate_type="random",
                         feature_shard="entity", entity_column="userId",
                         reg_type="l2", reg_weight=1.0, max_iters=40),
    ], task="logistic", n_iterations=2)
    model, history = cd.run(train)
    auc = get_evaluator("auc").evaluate(
        np.asarray(GameTransformer(model).transform(train)),
        train.labels, train.weights)
    assert auc > 0.8, auc
    # per-user coordinate must improve on the fixed effect alone
    fixed_only = CoordinateDescent([
        CoordinateConfig("fixed", coordinate_type="fixed",
                         feature_shard="global", reg_type="l2",
                         reg_weight=0.1, max_iters=60),
    ], task="logistic").run(train)[0]
    auc_fixed = get_evaluator("auc").evaluate(
        np.asarray(GameTransformer(fixed_only).transform(train)),
        train.labels, train.weights)
    assert auc > auc_fixed + 0.03


def test_config5_game_two_effects_bayesian_tune(rng):
    from photon_ml_tpu.estimators import GameEstimator
    from photon_ml_tpu.game.descent import CoordinateConfig
    from photon_ml_tpu.tuning import tune_game

    data = synthetic_game_data({"userId": 10, "itemId": 6}, seed=9)
    full = game_dataset_from_synthetic(data)
    n = len(data.labels)
    rows = np.arange(n)
    tr, va = rows[: int(n * 0.8)], rows[int(n * 0.8):]

    def subset(ds, idx):
        import dataclasses as dc

        from photon_ml_tpu.game.descent import make_game_dataset

        return make_game_dataset(
            {s: data.features[s][idx] for s in data.features},
            labels=data.labels[idx],
            entity_ids={c: v[idx] for c, v in data.entity_ids.items()},
        )

    train, val = subset(full, tr), subset(full, va)
    configs = [
        CoordinateConfig("fixed", coordinate_type="fixed",
                         feature_shard="global", reg_type="l2",
                         reg_weight=0.1, max_iters=40),
        CoordinateConfig("per-user", coordinate_type="random",
                         feature_shard="entity", entity_column="userId",
                         reg_type="l2", reg_weight=1.0, max_iters=25),
        CoordinateConfig("per-item", coordinate_type="random",
                         feature_shard="entity", entity_column="itemId",
                         reg_type="l2", reg_weight=1.0, max_iters=25),
    ]
    est = GameEstimator(task="logistic", n_iterations=1, evaluators=["auc"])
    grid_results = est.fit(train, val, config_grid=[configs])
    tuned = tune_game(est, train, val, configs, n_iterations=3,
                      mode="bayesian", reg_range=(1e-3, 1e2),
                      prior_results=grid_results, seed=0)
    assert len(tuned) == 3
    best = est.select_best(grid_results + tuned)
    assert best.evaluation.primary_value > 0.75
