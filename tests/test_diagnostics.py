"""Diagnostics stage (the classic driver's final stage): HL fit test,
vmapped bootstrap CIs, feature importance, and the driver integration."""

import json

import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.diagnostics import (
    bootstrap_coefficients,
    feature_importance,
    hosmer_lemeshow,
)


def test_hosmer_lemeshow_calibrated_vs_miscalibrated(rng):
    n = 4000
    p = rng.uniform(0.05, 0.95, size=n)
    y_good = (rng.random(n) < p).astype(float)
    good = hosmer_lemeshow(p, y_good)
    # calibrated probabilities: no evidence of misfit
    assert good["p_value"] > 0.01
    # badly miscalibrated: overconfident probabilities
    p_bad = np.clip(p**3, 0.01, 0.99)
    bad = hosmer_lemeshow(p_bad, y_good)
    assert bad["statistic"] > good["statistic"]
    assert bad["p_value"] < 1e-4


def test_bootstrap_coefficients_cover_truth(rng):
    from photon_ml_tpu.ops.objective import make_objective
    from photon_ml_tpu.optimize import OptimizerConfig
    from photon_ml_tpu.optimize.lbfgs import lbfgs
    from photon_ml_tpu.types import make_batch

    n, d = 800, 4
    X = rng.normal(size=(n, d))
    w_true = np.array([1.0, -0.5, 0.0, 0.25])
    y = (rng.random(n) < 1 / (1 + np.exp(-(X @ w_true)))).astype(float)
    batch = make_batch(jnp.asarray(X), y, dtype=jnp.float64)
    obj = make_objective("logistic")
    res = lbfgs(lambda w: obj.value_and_grad(w, batch, 1e-3),
                jnp.zeros(d, jnp.float64), OptimizerConfig())
    boot = bootstrap_coefficients(obj, batch, res.w, l2=1e-3,
                                  n_replicates=24, seed=1)
    assert boot["replicates"].shape == (24, d)
    # intervals are ordered and (for this well-specified problem) cover truth
    assert np.all(boot["lower"] <= boot["upper"])
    covered = (boot["lower"] <= w_true) & (w_true <= boot["upper"])
    assert covered.sum() >= 3, (boot["lower"], w_true, boot["upper"])
    assert np.all(boot["std"] > 0)


def test_feature_importance_ranking():
    w = np.array([0.1, -2.0, 0.5])
    std = np.array([10.0, 0.1, 1.0])
    imp = feature_importance(w, std)
    # |0.1*10| = 1.0, |-2*0.1| = 0.2, |0.5*1| = 0.5
    assert imp["index"].tolist() == [0, 2, 1]
    imp2 = feature_importance(w, None, top_k=1)
    assert imp2["index"].tolist() == [1]


def test_glm_driver_diagnostics_output(tmp_path, rng):
    from photon_ml_tpu.cli.glm_driver import main as glm_main
    from photon_ml_tpu.io.data_reader import (
        feature_tuples_from_dense,
        write_training_examples,
    )

    n, d = 400, 6
    X = rng.normal(size=(n, d))
    w = rng.normal(size=d)
    y = (rng.random(n) < 1 / (1 + np.exp(-(X @ w)))).astype(float)
    write_training_examples(
        str(tmp_path / "train.avro"), feature_tuples_from_dense(X[:300]), y[:300]
    )
    write_training_examples(
        str(tmp_path / "val.avro"), feature_tuples_from_dense(X[300:]), y[300:]
    )
    out = tmp_path / "out"
    rc = glm_main([
        "--train-data", str(tmp_path / "train.avro"),
        "--validation-data", str(tmp_path / "val.avro"),
        "--output-dir", str(out),
        "--reg-weights", "1.0",
        "--diagnostics", "--bootstrap-replicates", "8",
        "--summarize-features",
        "--dtype", "float64",
    ])
    assert rc == 0
    report = json.loads((out / "diagnostics.json").read_text())
    assert report["reg_weight"] == 1.0
    assert len(report["feature_importance"]) == d + 1  # + intercept
    assert {"statistic", "dof", "p_value"} <= set(report["hosmer_lemeshow"])
    assert len(report["bootstrap"]["std"]) == d + 1
