"""GAME layer tests: random-effect data building, vmapped entity solves,
score views, coordinate descent on synthetic mixed-effect data (the
reference's GameTestUtils-style synthetic structure — SURVEY.md §8)."""

import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.game.data import build_random_effect_data, build_score_view
from photon_ml_tpu.game.descent import CoordinateConfig, CoordinateDescent, make_game_dataset
from photon_ml_tpu.game.random_effect import score_random_effect, train_random_effect
from photon_ml_tpu.game.sampling import down_sample
from photon_ml_tpu.optimize import OptimizerConfig


def _mixed_effect_data(rng, n_users=20, rows_per_user=(5, 40), d_global=8, d_user=4):
    """fixed effect on global features + per-user effect on user features."""
    w_fixed = rng.normal(size=d_global)
    rows = []
    Xg_all, Xu_all, y_all, uid_all = [], [], [], []
    user_coefs = rng.normal(size=(n_users, d_user)) * 1.5
    for u in range(n_users):
        m = rng.integers(*rows_per_user)
        Xg = rng.normal(size=(m, d_global))
        Xu = rng.normal(size=(m, d_user))
        margin = Xg @ w_fixed + Xu @ user_coefs[u]
        y = (rng.random(m) < 1 / (1 + np.exp(-margin))).astype(float)
        Xg_all.append(Xg); Xu_all.append(Xu); y_all.append(y)
        uid_all.append(np.full(m, u))
    return (np.concatenate(Xg_all), np.concatenate(Xu_all),
            np.concatenate(y_all), np.concatenate(uid_all), w_fixed, user_coefs)


def test_re_data_roundtrip(rng):
    n, d = 60, 10
    X = rng.normal(size=(n, d)) * (rng.random((n, d)) < 0.5)
    y = (rng.random(n) < 0.5).astype(float)
    w = rng.random(n) + 0.5
    ids = rng.integers(0, 7, size=n)
    data = build_random_effect_data(X, y, w, ids, num_buckets=3)
    assert data.num_entities == len(np.unique(ids))
    # every row appears exactly once across buckets (no cap -> all active)
    seen = np.concatenate([b.sample_idx[b.sample_idx >= 0] for b in data.buckets])
    assert sorted(seen.tolist()) == list(range(n))
    # labels/weights round-trip and local features match global through projection
    for b in data.buckets:
        for r in range(b.num_entities):
            for j in range(b.sample_idx.shape[1]):
                i = b.sample_idx[r, j]
                if i < 0:
                    continue
                assert b.labels[r, j] == y[i]
                assert b.weights[r, j] == w[i]
                # reconstruct dense global row from local representation
                dense = np.zeros(d)
                for slot, v in zip(b.indices[r, j], b.values[r, j]):
                    if v != 0:
                        gid = b.projection[r, slot]
                        dense[gid] += v
                np.testing.assert_allclose(dense, X[i], atol=1e-12)


def test_re_active_cap(rng):
    n = 100
    X = rng.normal(size=(n, 5))
    ids = np.zeros(n, int)  # one entity
    data = build_random_effect_data(X, np.zeros(n), np.ones(n), ids, active_cap=10)
    active = data.buckets[0].sample_idx
    assert (active >= 0).sum() == 10


def test_score_view_matches_direct(rng):
    n, d = 50, 8
    X = rng.normal(size=(n, d)) * (rng.random((n, d)) < 0.6)
    ids = rng.integers(0, 5, size=n)
    data = build_random_effect_data(X, np.zeros(n), np.ones(n), ids, num_buckets=2)
    view = build_score_view(data, X, ids)
    # random per-entity coefficients in local space
    coeffs = [rng.normal(size=(b.num_entities, b.local_dim)) for b in data.buckets]
    scores = np.asarray(score_random_effect(view, coeffs, n, dtype=jnp.float64))
    # direct: w_e in global space
    for b, bucket in enumerate(data.buckets):
        for r, eid in enumerate(bucket.entity_ids):
            w_global = np.zeros(d)
            for slot in range(bucket.local_dim):
                gid = bucket.projection[r, slot]
                if gid >= 0:
                    w_global[gid] = coeffs[b][r, slot]
            for i in np.nonzero(ids == eid)[0]:
                np.testing.assert_allclose(scores[i], X[i] @ w_global, rtol=1e-8,
                                           atol=1e-8)


def test_train_random_effect_matches_direct_fit(rng):
    # one entity's vmapped solve == direct single-problem fit
    from photon_ml_tpu.ops.objective import make_objective
    from photon_ml_tpu.optimize import lbfgs
    from photon_ml_tpu.types import make_batch

    n, d = 80, 6
    X = rng.normal(size=(n, d))
    y = (rng.random(n) < 0.5).astype(float)
    ids = np.zeros(n, int)
    data = build_random_effect_data(X, y, np.ones(n), ids)
    fit = train_random_effect(data, np.zeros(n), l2=0.5, dtype=jnp.float64,
                              config=OptimizerConfig(max_iters=100, tolerance=1e-10))
    # map local coefficients back to global space
    bucket = data.buckets[0]
    w_global = np.zeros(d)
    for slot in range(bucket.local_dim):
        gid = bucket.projection[0, slot]
        if gid >= 0:
            w_global[gid] = fit.coefficients[0][0, slot]
    obj = make_objective("logistic")
    batch = make_batch(jnp.asarray(X), y, dtype=jnp.float64)
    ref = lbfgs(lambda w: obj.value_and_grad(w, batch, 0.5), jnp.zeros(d),
                OptimizerConfig(max_iters=100, tolerance=1e-10))
    np.testing.assert_allclose(w_global, np.asarray(ref.w), rtol=1e-4, atol=1e-6)
    assert fit.converged_fraction == 1.0


def test_coordinate_descent_fixed_only_matches_direct(rng):
    from photon_ml_tpu.ops.objective import make_objective
    from photon_ml_tpu.optimize import lbfgs
    from photon_ml_tpu.types import make_batch

    n, d = 120, 7
    X = rng.normal(size=(n, d))
    y = (rng.random(n) < 0.5).astype(float)
    ds = make_game_dataset(X, y)
    cd = CoordinateDescent(
        [CoordinateConfig("fixed", reg_type="l2", reg_weight=1.0,
                          tolerance=1e-10, max_iters=200)],
        task="logistic", n_iterations=1, dtype=jnp.float64,
    )
    model, history = cd.run(ds)
    w = np.asarray(model["fixed"].model.coefficients.means)
    obj = make_objective("logistic")
    batch = make_batch(jnp.asarray(X), y, dtype=jnp.float64)
    ref = lbfgs(lambda w: obj.value_and_grad(w, batch, 1.0), jnp.zeros(d),
                OptimizerConfig(max_iters=200, tolerance=1e-10))
    np.testing.assert_allclose(w, np.asarray(ref.w), rtol=1e-5, atol=1e-7)


def test_coordinate_descent_mixed_effects_beats_fixed_only(rng):
    Xg, Xu, y, uid, w_fixed, user_coefs = _mixed_effect_data(rng)
    n = len(y)
    split = int(n * 0.8)
    perm = rng.permutation(n)
    tr, va = perm[:split], perm[split:]
    feats = {"global": Xg, "per_user": Xu}
    ds_tr = make_game_dataset({k: v[tr] for k, v in feats.items()}, y[tr],
                              entity_ids={"userId": uid[tr]})
    ds_va = make_game_dataset({k: v[va] for k, v in feats.items()}, y[va],
                              entity_ids={"userId": uid[va]})
    fixed_cfg = CoordinateConfig("fixed", feature_shard="global",
                                 reg_type="l2", reg_weight=1.0)
    re_cfg = CoordinateConfig("per-user", coordinate_type="random",
                              feature_shard="per_user", entity_column="userId",
                              reg_type="l2", reg_weight=1.0)
    cd_fixed = CoordinateDescent([fixed_cfg], task="logistic",
                                 evaluators=["auc"], dtype=jnp.float64)
    _, hist_fixed = cd_fixed.run(ds_tr, ds_va)
    cd_game = CoordinateDescent([fixed_cfg, re_cfg], task="logistic",
                                n_iterations=2, evaluators=["auc"], dtype=jnp.float64)
    model, hist_game = cd_game.run(ds_tr, ds_va)
    auc_fixed = hist_fixed[-1]["auc"]
    auc_game = hist_game[-1]["auc"]
    assert auc_game > auc_fixed + 0.02, (auc_fixed, auc_game)
    # residual trick: training AUC from model scoring should be high
    assert model["per-user"].num_entities == 20


def test_coordinate_descent_warm_start_and_locked(rng):
    Xg, Xu, y, uid, *_ = _mixed_effect_data(rng, n_users=10)
    ds = make_game_dataset({"global": Xg, "per_user": Xu}, y,
                           entity_ids={"userId": uid})
    fixed_cfg = CoordinateConfig("fixed", feature_shard="global",
                                 reg_type="l2", reg_weight=1.0)
    re_cfg = CoordinateConfig("per-user", coordinate_type="random",
                              feature_shard="per_user", entity_column="userId",
                              reg_type="l2", reg_weight=1.0)
    cd = CoordinateDescent([fixed_cfg, re_cfg], task="logistic", dtype=jnp.float64)
    model1, _ = cd.run(ds)
    # warm start + lock the fixed coordinate: fixed coefficients unchanged
    model2, _ = cd.run(ds, warm_start=model1, locked=["fixed"])
    np.testing.assert_allclose(
        np.asarray(model2["fixed"].model.coefficients.means),
        np.asarray(model1["fixed"].model.coefficients.means), rtol=1e-12,
    )
    with pytest.raises(ValueError, match="locked"):
        cd.run(ds, warm_start=model1, locked=["nope"])


def test_down_sample_binary_keeps_positives(rng):
    y = (rng.random(1000) < 0.2).astype(float)
    w = np.ones(1000)
    idx, w2 = down_sample(y, w, 0.25, task="logistic", seed=1)
    assert set(np.nonzero(y > 0.5)[0]).issubset(set(idx))
    neg_mask = y[idx] <= 0.5
    np.testing.assert_allclose(w2[neg_mask], 4.0)
    np.testing.assert_allclose(w2[~neg_mask], 1.0)
    # uniform sampler preserves expected total weight
    idx_u, w_u = down_sample(y, w, 0.5, task="squared", seed=2)
    assert abs(w_u.sum() - 1000) < 150


def test_duplicate_coordinate_names_rejected():
    with pytest.raises(ValueError, match="duplicate"):
        CoordinateDescent([CoordinateConfig("a"), CoordinateConfig("a")])


@pytest.mark.parametrize("optimizer", ["lbfgs", "newton"])
def test_train_random_effect_entity_sharded_matches(rng, optimizer):
    # entity-axis shard_map path == unsharded path (review/verify regression)
    from photon_ml_tpu.parallel import make_mesh

    n, d = 120, 6
    X = rng.normal(size=(n, d))
    y = (rng.random(n) < 0.5).astype(float)
    ids = rng.integers(0, 11, size=n)  # 11 entities, not divisible by mesh axis
    data = build_random_effect_data(X, y, np.ones(n), ids, num_buckets=2)
    mesh = make_mesh({"entity": 4})
    cfg = OptimizerConfig(max_iters=60, tolerance=1e-10)
    fit_plain = train_random_effect(data, np.zeros(n), l2=0.4, dtype=jnp.float64,
                                    config=cfg, optimizer=optimizer)
    fit_mesh = train_random_effect(data, np.zeros(n), l2=0.4, dtype=jnp.float64,
                                   config=cfg, mesh=mesh, optimizer=optimizer)
    for a, b in zip(fit_plain.coefficients, fit_mesh.coefficients):
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-8)
    assert fit_mesh.converged_fraction == 1.0


def test_random_effect_l1_regularization(rng):
    # review finding: RE coordinates must honor L1 (auto-routed to OWL-QN)
    n, d = 150, 8
    X = rng.normal(size=(n, d))
    y = (rng.random(n) < 0.5).astype(float)
    ids = np.zeros(n, int)
    data = build_random_effect_data(X, y, np.ones(n), ids)
    cfg = OptimizerConfig(max_iters=150, tolerance=1e-10)
    fit_l1 = train_random_effect(data, np.zeros(n), l1=5.0, dtype=jnp.float64,
                                 config=cfg)
    fit_none = train_random_effect(data, np.zeros(n), dtype=jnp.float64, config=cfg)
    nz_l1 = (np.abs(fit_l1.coefficients[0]) > 1e-8).sum()
    nz_none = (np.abs(fit_none.coefficients[0]) > 1e-8).sum()
    assert nz_l1 < nz_none  # L1 produces sparsity


def test_locked_without_warm_start_rejected(rng):
    from photon_ml_tpu.game.descent import make_game_dataset

    X = rng.normal(size=(50, 4))
    y = (rng.random(50) < 0.5).astype(float)
    ds = make_game_dataset(X, y)
    cd = CoordinateDescent([CoordinateConfig("fixed")])
    with pytest.raises(ValueError, match="warm_start"):
        cd.run(ds, locked=["fixed"])


def test_random_coordinate_normalization_sketch_rejected():
    from photon_ml_tpu.ops.normalization import NormalizationContext
    import jax.numpy as jnp2

    ctx = NormalizationContext(jnp2.ones(3), None)
    with pytest.raises(ValueError, match="projection='random'"):
        CoordinateConfig("re", coordinate_type="random", entity_column="u",
                         normalization=ctx, projection="random",
                         projection_dim=8)


def test_random_effect_normalization_matches_materialized(rng):
    """Per-entity normalization inside the solve == training on explicitly
    standardized features: identical predictions (coefficients come back in
    raw feature space)."""
    from photon_ml_tpu.game.data import build_random_effect_data, build_score_view
    from photon_ml_tpu.game.random_effect import (
        score_random_effect,
        train_random_effect,
    )
    from photon_ml_tpu.ops.normalization import NormalizationContext

    n, d = 240, 6
    X = rng.normal(size=(n, d)) * np.array([30.0, 0.05, 1.0, 4.0, 1.0, 2.0])
    X = X * (rng.random((n, d)) < 0.7)
    Xi = np.concatenate([X, np.ones((n, 1))], axis=1)  # intercept col = d
    ids = rng.integers(0, 8, n)
    u_eff = rng.normal(size=(8, d + 1))
    y = (rng.random(n) < 1 / (1 + np.exp(-np.sum(Xi * u_eff[ids], axis=1)))
         ).astype(float)
    weights = rng.uniform(0.5, 2.0, n)

    mean = Xi.mean(axis=0)
    std = np.where(Xi.std(axis=0) > 0, Xi.std(axis=0), 1.0)
    ctx = NormalizationContext(jnp.asarray(1.0 / std), jnp.asarray(mean),
                               intercept_index=d)

    kw = dict(task="logistic", l2=0.5, optimizer="lbfgs", dtype=jnp.float64)
    data_raw = build_random_effect_data(Xi, y, weights, ids, num_buckets=2)
    fit_norm = train_random_effect(data_raw, np.zeros(n), normalization=ctx,
                                   **kw)

    # reference: explicitly standardized dense features, no context
    Xn = (Xi - mean) / std
    Xn[:, d] = 1.0  # intercept untouched
    data_mat = build_random_effect_data(Xn, y, weights, ids, num_buckets=2)
    fit_mat = train_random_effect(data_mat, np.zeros(n), **kw)

    view_raw = build_score_view(data_raw, Xi, ids)
    view_mat = build_score_view(data_mat, Xn, ids)
    s_norm = np.asarray(score_random_effect(view_raw, fit_norm.coefficients,
                                            n, jnp.float64))
    s_mat = np.asarray(score_random_effect(view_mat, fit_mat.coefficients,
                                           n, jnp.float64))
    np.testing.assert_allclose(s_norm, s_mat, rtol=1e-6, atol=1e-8)
    assert fit_norm.converged_fraction == 1.0


def test_random_effect_full_variance(rng):
    """compute_variance='full' on random effects: per-entity diag(H^-1),
    distinct from the diagonal approximation but equal for a single-feature
    entity (where H is 1x1)."""
    n, d = 120, 5
    X = rng.normal(size=(n, d))
    y = (rng.random(n) < 0.5).astype(float)
    ids = np.repeat(np.arange(4), n // 4)
    data = build_random_effect_data(X, y, np.ones(n), ids, num_buckets=1)
    kw = dict(l2=0.5, dtype=jnp.float64,
              config=OptimizerConfig(max_iters=100, tolerance=1e-10))
    fit_d = train_random_effect(data, np.zeros(n), compute_variance="diagonal", **kw)
    fit_f = train_random_effect(data, np.zeros(n), compute_variance="full", **kw)
    vd, vf = fit_d.variances[0], fit_f.variances[0]
    assert vd.shape == vf.shape
    np.testing.assert_allclose(fit_d.coefficients[0], fit_f.coefficients[0],
                               rtol=1e-12)
    assert not np.allclose(vd, vf, rtol=1e-12)  # correlations matter
    np.testing.assert_allclose(vd, vf, rtol=1.0)  # but same scale


def test_coordinate_config_validates_variance():
    import pytest as _pytest

    with _pytest.raises(ValueError, match="compute_variance"):
        CoordinateConfig(name="x", compute_variance="Full")
    with _pytest.raises(ValueError, match="streaming"):
        CoordinateConfig(name="x", compute_variance="full", streaming=True)
    CoordinateConfig(name="x", compute_variance="full")  # ok


def test_game_with_implicit_ones_features(rng):
    """A full GAME run (fixed + random effect + transformer scoring) over
    the implicit-ones layout == the same run with explicit 1.0 values."""
    from photon_ml_tpu.estimators import GameTransformer
    from photon_ml_tpu.game.data import HostSparse

    n, d, k = 600, 50, 5
    idx = rng.integers(0, d, (n, k)).astype(np.int32)
    y = (rng.random(n) < 0.5).astype(float)
    users = rng.integers(0, 12, n)
    configs = [
        CoordinateConfig(name="fe", feature_shard="global", reg_type="l2",
                         reg_weight=1.0, max_iters=20),
        CoordinateConfig(name="per_user", coordinate_type="random",
                         entity_column="user", reg_type="l2",
                         reg_weight=1.0, max_iters=8, num_buckets=2),
    ]
    preds = {}
    for name, vals in (("binary", None), ("explicit", np.ones((n, k)))):
        train = make_game_dataset({"global": HostSparse(idx, vals, d)}, y,
                                  entity_ids={"user": users})
        cd = CoordinateDescent(configs, task="logistic", n_iterations=2)
        model, _ = cd.run(train)
        preds[name] = GameTransformer(model).predict_mean(train)
    np.testing.assert_allclose(preds["binary"], preds["explicit"],
                               rtol=1e-6, atol=1e-7)


def test_newton_dense_re_solver_matches_lbfgs(rng):
    """The batched dense-Newton RE solver (optimizer='newton') matches the
    vmapped L-BFGS path: coefficients, variances (diagonal + full),
    offsets, weights, and per-entity normalization all agree."""
    from photon_ml_tpu.game.data import build_random_effect_data
    from photon_ml_tpu.game.random_effect import train_random_effect
    from photon_ml_tpu.ops.normalization import NormalizationContext

    n, d, E = 360, 5, 12
    X = rng.normal(size=(n, d)) * np.array([10.0, 0.2, 1.0, 3.0, 1.0])
    X = X * (rng.random((n, d)) < 0.8)
    ids = rng.integers(0, E, n)
    u = rng.normal(size=(E, d))
    y = (rng.random(n) < 1 / (1 + np.exp(-np.sum(X * u[ids], axis=1)))
         ).astype(float)
    weights = rng.uniform(0.5, 2.0, n)
    offs = rng.normal(size=n) * 0.3

    from photon_ml_tpu.optimize import OptimizerConfig

    data = build_random_effect_data(X, y, weights, ids, num_buckets=2)
    cfg_kw = dict(task="logistic", l2=0.7, dtype=jnp.float64,
                  config=OptimizerConfig(max_iters=100, tolerance=1e-10))
    f_lb = train_random_effect(data, offs, optimizer="lbfgs",
                               compute_variance="full", **cfg_kw)
    f_nt = train_random_effect(data, offs, optimizer="newton",
                               compute_variance="full", **cfg_kw)
    assert f_nt.converged_fraction == 1.0
    assert f_nt.mean_iterations <= f_lb.mean_iterations  # Newton is quadratic
    for b in range(len(f_lb.coefficients)):
        np.testing.assert_allclose(f_nt.coefficients[b], f_lb.coefficients[b],
                                   rtol=1e-4, atol=1e-6)
        np.testing.assert_allclose(f_nt.variances[b], f_lb.variances[b],
                                   rtol=1e-4, atol=1e-8)
    d_lb = train_random_effect(data, offs, optimizer="lbfgs",
                               compute_variance="diagonal", **cfg_kw)
    d_nt = train_random_effect(data, offs, optimizer="newton",
                               compute_variance="diagonal", **cfg_kw)
    for b in range(len(d_lb.variances)):
        np.testing.assert_allclose(d_nt.variances[b], d_lb.variances[b],
                                   rtol=1e-4, atol=1e-8)

    # normalization (factors + shifts through the intercept) parity
    Xi = np.concatenate([X, np.ones((n, 1))], axis=1)
    mean = Xi.mean(axis=0)
    std = np.where(Xi.std(axis=0) > 0, Xi.std(axis=0), 1.0)
    ctx = NormalizationContext(jnp.asarray(1.0 / std), jnp.asarray(mean),
                               intercept_index=d)
    data_i = build_random_effect_data(Xi, y, weights, ids, num_buckets=2)
    g_lb = train_random_effect(data_i, offs, optimizer="lbfgs",
                               normalization=ctx, **cfg_kw)
    g_nt = train_random_effect(data_i, offs, optimizer="newton",
                               normalization=ctx, **cfg_kw)
    for b in range(len(g_lb.coefficients)):
        np.testing.assert_allclose(g_nt.coefficients[b], g_lb.coefficients[b],
                                   rtol=1e-4, atol=1e-6)


def test_newton_rejected_for_fixed_coordinates():
    from photon_ml_tpu.game.descent import CoordinateConfig

    with pytest.raises(ValueError, match="newton"):
        CoordinateConfig("fixed", coordinate_type="fixed",
                         optimizer="newton")


def test_re_optimizer_auto_resolves_per_platform(rng):
    """optimizer="auto" picks the measured per-platform default (CPU:
    vmapped L-BFGS) and produces the same fit as naming it explicitly
    (VERDICT r3 #7: the default is data-driven, one table entry per
    platform in random_effect._RE_SOLVER_DEFAULT)."""
    from photon_ml_tpu.game.data import build_random_effect_data
    from photon_ml_tpu.game.random_effect import (
        resolve_re_optimizer, train_random_effect)
    from photon_ml_tpu.optimize import OptimizerConfig

    assert resolve_re_optimizer("newton") == "newton"
    assert resolve_re_optimizer("auto") == "lbfgs"  # tests run on CPU

    n, d, E = 120, 4, 6
    X = rng.normal(size=(n, d))
    ids = rng.integers(0, E, n)
    y = (rng.random(n) < 0.5).astype(float)
    data = build_random_effect_data(X, y, np.ones(n), ids, num_buckets=1)
    kw = dict(task="logistic", l2=0.5,
              config=OptimizerConfig(max_iters=50, tolerance=1e-8))
    f_auto = train_random_effect(data, np.zeros(n), optimizer="auto", **kw)
    f_lb = train_random_effect(data, np.zeros(n), optimizer="lbfgs", **kw)
    for b in range(len(f_lb.coefficients)):
        np.testing.assert_array_equal(f_auto.coefficients[b],
                                      f_lb.coefficients[b])


def _timed_fill(W, bucket, prev_bucket, prs):
    import time

    from photon_ml_tpu.game.descent import _warm_fill_bucket

    t0 = time.perf_counter()
    _warm_fill_bucket(W, bucket, np.arange(bucket.num_entities),
                      prev_bucket, prs)
    return time.perf_counter() - t0


def test_warm_fill_bucket_vectorized_matches_loop_and_scales(rng):
    """The warm-start slot remap is a numpy composite-key join, not a
    per-entity/per-slot Python loop (VERDICT r4 #7): it must match the
    straightforward loop on a small case AND warm-start 100k entities
    well under 2s."""
    import time

    from photon_ml_tpu.game.descent import _warm_fill_bucket
    from photon_ml_tpu.models import RandomEffectBucket

    def make_pair(E, D_prev, D_cur, gid_space):
        prev_proj = np.full((E, D_prev), -1, np.int32)
        cur_proj = np.full((E, D_cur), -1, np.int32)
        for r in range(E):
            gids = rng.choice(gid_space, size=D_prev + D_cur // 2,
                              replace=False)
            prev_proj[r] = np.sort(gids[:D_prev])
            # current subspace overlaps ~half the previous one
            cur = np.concatenate([gids[D_prev // 2: D_prev],
                                  gids[D_prev:]])[:D_cur]
            cur_proj[r, : len(cur)] = np.sort(cur)
        coefs = rng.normal(size=(E, D_prev))
        return prev_proj, cur_proj, coefs

    # correctness vs the reference loop
    E, Dp, Dc = 40, 6, 8
    prev_proj, cur_proj, coefs = make_pair(E, Dp, Dc, 200)
    prev_bucket = RandomEffectBucket([f"e{i}" for i in range(E)],
                                     coefs, prev_proj)
    local_maps = [{int(g): s for s, g in enumerate(cur_proj[r])
                   if g >= 0} for r in range(E)]
    bucket = type("B", (), {})()
    bucket.num_entities = E
    bucket.local_maps = local_maps
    bucket.projection = cur_proj
    rows = np.arange(E)
    prs = rng.permutation(E)
    W = np.zeros((E, Dc))
    _warm_fill_bucket(W, bucket, rows, prev_bucket, prs)
    W_ref = np.zeros((E, Dc))
    for r in range(E):
        pr = prs[r]
        for slot, gid in enumerate(prev_proj[pr]):
            if gid >= 0 and int(gid) in local_maps[r]:
                W_ref[r, local_maps[r][int(gid)]] = coefs[pr, slot]
    np.testing.assert_allclose(W, W_ref)

    # scale: 100k entities x 16 slots in well under 2s
    E, Dp, Dc = 100_000, 16, 16
    prev_proj = rng.integers(0, 1 << 20, (E, Dp)).astype(np.int32)
    prev_proj.sort(axis=1)
    prs = rng.permutation(E)
    # each current row carries its MATCHED prev row's subspace: every slot
    # should remap
    cur_proj = prev_proj[prs]
    coefs = rng.normal(size=(E, Dp))
    prev_bucket = RandomEffectBucket(np.arange(E), coefs, prev_proj)
    bucket = type("B", (), {})()
    bucket.num_entities = E
    bucket.local_maps = [None]  # only [0] is touched, for the sketch check
    bucket.projection = cur_proj
    W = np.zeros((E, Dc))
    # min-of-3: the bound is about algorithmic complexity, and a single
    # wall-clock sample on a 1-core box loses to unrelated process
    # contention (observed flaking in full-suite runs)
    dt = min(_timed_fill(W, bucket, prev_bucket, prs)
             for _ in range(3))
    assert dt < 2.0, f"warm-fill at 100k entities took {dt:.2f}s"
    assert np.count_nonzero(W) > 0.99 * E * Dp


def test_warm_start_prev_subspace_into_sketch(rng):
    """A previous exact-subspace model warm-starts a sketched coordinate by
    pushing (gid, coef) through the sketch (the projector's own embedding);
    the old per-slot loop raised TypeError on this path."""
    from photon_ml_tpu.game.data import SketchProjection
    from photon_ml_tpu.game.descent import _warm_fill_bucket
    from photon_ml_tpu.models import RandomEffectBucket

    E, Dp, dim = 10, 4, 32
    sketch = SketchProjection(dim, seed=3)
    prev_proj = rng.integers(0, 1000, (E, Dp)).astype(np.int32)
    coefs = rng.normal(size=(E, Dp))
    prev_bucket = RandomEffectBucket(np.arange(E), coefs, prev_proj)
    bucket = type("B", (), {})()
    bucket.num_entities = E
    bucket.local_maps = [sketch] * E
    bucket.projection = np.full((E, dim), -1, np.int32)
    W = np.zeros((E, dim))
    _warm_fill_bucket(W, bucket, np.arange(E), prev_bucket, np.arange(E))
    for r in range(3):  # spot-check the embedding
        expect = np.zeros(dim)
        slots, signs = sketch.slots_signs(prev_proj[r])
        for j in range(Dp):
            expect[slots[j]] += signs[j] * coefs[r, j]
        np.testing.assert_allclose(W[r], expect, rtol=1e-12)


@pytest.mark.parametrize("use_mesh", [False, True])
def test_train_random_effect_blocked_matches_unblocked(rng, monkeypatch,
                                                       use_mesh):
    """Entity-block bounded execution (the v5e HBM guard) must reproduce
    the single-program solve exactly — including with an entity mesh,
    where the block width rounds to the mesh axis."""
    from photon_ml_tpu.game import random_effect as re_mod
    from photon_ml_tpu.parallel import make_mesh

    n, d = 160, 6
    X = rng.normal(size=(n, d))
    y = (rng.random(n) < 0.5).astype(float)
    ids = rng.integers(0, 13, size=n)  # 13 entities
    data = build_random_effect_data(X, y, np.ones(n), ids)
    cfg = OptimizerConfig(max_iters=60, tolerance=1e-10)
    mesh = make_mesh({"entity": 4}) if use_mesh else None
    want = train_random_effect(data, np.zeros(n), l2=0.4, dtype=jnp.float64,
                               config=cfg, mesh=mesh)
    monkeypatch.setattr(re_mod, "_RE_BLOCK_ENTITIES", 5)  # forces blocks
    got = train_random_effect(data, np.zeros(n), l2=0.4, dtype=jnp.float64,
                              config=cfg, mesh=mesh)
    for a, b in zip(want.coefficients, got.coefficients):
        np.testing.assert_allclose(a, b, rtol=1e-12, atol=1e-12)
    assert got.converged_fraction == want.converged_fraction
    assert got.mean_iterations == want.mean_iterations


def test_re_auto_solver_dimension_gate(monkeypatch):
    """'auto' only picks dense-Newton up to _RE_NEWTON_MAX_DIM: its
    [block, d, d] Hessians exhaust HBM (and crashed the Mosaic batched-
    Cholesky compile at the d=351 CD bucket on the v5e); wide subspaces
    route to the O(d)-memory vmapped L-BFGS."""
    from photon_ml_tpu.game import random_effect as re_mod

    monkeypatch.setattr(re_mod, "_RE_SOLVER_DEFAULT",
                        {"cpu": "newton", "tpu": "newton"})
    assert re_mod.resolve_re_optimizer("auto", 32) == "newton"
    assert re_mod.resolve_re_optimizer("auto",
                                       re_mod._RE_NEWTON_MAX_DIM) == "newton"
    assert re_mod.resolve_re_optimizer("auto",
                                       re_mod._RE_NEWTON_MAX_DIM + 1) == "lbfgs"
    assert re_mod.resolve_re_optimizer("auto", None) == "newton"
    assert re_mod.resolve_re_optimizer("newton", 351) == "newton"  # explicit
