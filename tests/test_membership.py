"""serve/membership.py + the owned-slice serving plumbing: train<->serve
owner-map parity (int AND string id dtypes — the FNV-vs-splitmix edge),
epoch/manager/view state machines, paged-table re-owning
(``retain_only``), and the session-level membership API
(``set_membership`` / ``prefetch_entities`` / non-owned install gating
with bit-identical scores)."""

import numpy as np
import pytest

from photon_ml_tpu.parallel.entity_shard import (
    EntityShardSpec,
    serving_owner_of,
)
from photon_ml_tpu.serve.membership import (
    MembershipEpoch,
    MembershipManager,
    MembershipView,
)
from tests.conftest import serving_rows


class TestOwnerMapParity:
    """The acceptance-critical invariant: the front door's router and
    the training shard spec put every entity id on the SAME owner."""

    @pytest.mark.parametrize("num_shards", [2, 3, 4, 7])
    def test_int_ids_match_training_spec(self, num_shards):
        ids = np.array([0, 1, 2, 9, 123, 10**12, 2**62], np.int64)
        spec = EntityShardSpec(num_shards=num_shards, shard_index=0)
        train = spec.owner_of(ids)
        np.testing.assert_array_equal(
            train, serving_owner_of(ids.tolist(), num_shards, "int"))
        # the wire form: serving sees str(uid) (JSON entityIds values
        # are strings) — "auto" must hash digits back in the INT domain
        # or the serve owner diverges from the training owner for every
        # integer-keyed model
        wire = [str(i) for i in ids.tolist()]
        np.testing.assert_array_equal(
            train, serving_owner_of(wire, num_shards, "auto"))
        np.testing.assert_array_equal(
            train, serving_owner_of(ids.tolist(), num_shards, "auto"))

    @pytest.mark.parametrize("num_shards", [2, 3, 5])
    def test_string_ids_match_training_spec(self, num_shards):
        ids = np.array(["alice", "bob", "user-7", "", "Ω"], object)
        spec = EntityShardSpec(num_shards=num_shards, shard_index=0)
        train = spec.owner_of(ids)
        np.testing.assert_array_equal(
            train, serving_owner_of(ids.tolist(), num_shards, "str"))
        np.testing.assert_array_equal(
            train, serving_owner_of(ids.tolist(), num_shards, "auto"))

    def test_auto_decides_per_id_not_per_batch(self):
        # one non-numeric id must not push the NUMERIC ids into the
        # string hash domain (that would move every owner in the batch)
        num_shards = 4
        mixed = ["123", "alice", "7"]
        out = serving_owner_of(mixed, num_shards, "auto")
        assert out[0] == serving_owner_of([123], num_shards, "int")[0]
        assert out[2] == serving_owner_of([7], num_shards, "int")[0]
        assert out[1] == serving_owner_of(["alice"], num_shards, "str")[0]

    def test_int_like_edges(self):
        num_shards = 3
        # out-of-int64-range digit strings and bools are NOT int-like
        big = str(2**70)
        assert (serving_owner_of([big], num_shards, "auto")[0]
                == serving_owner_of([big], num_shards, "str")[0])
        assert (serving_owner_of([True], num_shards, "auto")[0]
                == serving_owner_of([True], num_shards, "str")[0])
        # negative digit strings stay in the int domain
        assert (serving_owner_of(["-5"], num_shards, "auto")[0]
                == serving_owner_of([-5], num_shards, "int")[0])

    def test_bad_id_kind_raises(self):
        with pytest.raises(ValueError, match="id_kind"):
            serving_owner_of([1], 2, "float")

    def test_owner_in_range(self):
        out = serving_owner_of(list(range(200)), 5, "int")
        assert out.min() >= 0 and out.max() < 5
        assert len(set(out.tolist())) == 5  # all shards used


class TestMembershipEpoch:
    def test_validation(self):
        with pytest.raises(ValueError, match="epoch"):
            MembershipEpoch(0, ("a:1",))
        with pytest.raises(ValueError, match="replica"):
            MembershipEpoch(1, ())
        with pytest.raises(ValueError, match="sorted"):
            MembershipEpoch(1, ("b:2", "a:1"))
        with pytest.raises(ValueError, match="id_kind"):
            MembershipEpoch(1, ("a:1",), id_kind="weird")

    def test_payload_roundtrip(self):
        e = MembershipEpoch(3, ("a:1", "b:2"), id_kind="str")
        p = e.payload(1, ["u1", "u2"])
        assert p == {"epoch": 3, "replicas": ["a:1", "b:2"],
                     "selfIndex": 1, "idKind": "str",
                     "prefetchEntityIds": ["u1", "u2"]}
        assert MembershipEpoch.from_payload(p) == e
        assert "prefetchEntityIds" not in e.payload(0)

    def test_owner_address_is_position(self):
        e = MembershipEpoch(1, ("a:1", "b:2", "c:3"))
        for eid in ["1", "2", "77", "alice"]:
            idx = e.owner_index(eid)
            assert e.owner_address(eid) == e.replicas[idx]


class TestMembershipManager:
    def test_initial_epoch_and_unchanged_propose(self):
        m = MembershipManager(["b:2", "a:1", "a:1"])
        assert m.epoch.epoch == 1
        assert m.epoch.replicas == ("a:1", "b:2")
        assert m.propose(["a:1", "b:2"]) is None

    def test_propose_commit_monotonic(self):
        m = MembershipManager(["a:1", "b:2"])
        new = m.propose(["a:1", "b:2", "c:3"])
        assert new.epoch == 2
        assert m.commit(new) is True
        assert m.epoch is new
        # replaying an old epoch can never roll membership back
        stale = MembershipEpoch(2, ("a:1",))
        assert m.commit(stale) is False
        assert m.epoch is new
        assert m.propose(["a:1"]).epoch == 3

    def test_hot_tracker_bounded_lru(self):
        m = MembershipManager(["a:1"], hot_track=3)
        for e in ["1", "2", "3", "1", "4"]:
            m.note_routed(e)
        # "2" was the least recently routed when "4" pushed past bound
        assert m.hot_ids() == ["3", "1", "4"]

    def test_moved_ids_only_moved_grouped_by_new_owner(self):
        m = MembershipManager(["a:1", "b:2"])
        ids = [str(i) for i in range(40)]
        for e in ids:
            m.note_routed(e)
        new = m.propose(["a:1", "b:2", "c:3"])
        moved = m.moved_ids(new)
        cur = m.epoch
        for new_idx, group in moved.items():
            for eid in group:
                # grouped under its NEW owner...
                assert new.owner_index(eid) == new_idx
                # ...and its owner ADDRESS actually changed
                assert (new.replicas[new_idx]
                        != cur.owner_address(eid))
        flat = {e for g in moved.values() for e in g}
        for eid in set(ids) - flat:  # unmoved ids stay untouched
            assert (new.owner_address(eid) == cur.owner_address(eid))
        assert flat  # 2 -> 3 shards must move SOMETHING hot


class TestMembershipView:
    def test_inactive_owns_everything(self):
        v = MembershipView()
        assert v.epoch == 0 and not v.active
        assert v.owned_many(["a", "b"]) == [True, True]

    def test_apply_monotonic_and_partition(self):
        v = MembershipView()
        assert v.apply(2, 3, 1) is True
        assert v.active and v.epoch == 2
        assert v.apply(2, 3, 0) is False  # stale: refused, unchanged
        assert v.shard_index == 1
        ids = [str(i) for i in range(30)]
        owners = serving_owner_of(ids, 3, "auto")
        assert v.owned_many(ids) == [int(o) == 1 for o in owners]
        assert v.describe() == {"epoch": 2, "numShards": 3,
                                "shardIndex": 1, "idKind": "auto"}

    def test_single_shard_epoch_is_inactive(self):
        v = MembershipView()
        assert v.apply(1, 1, 0) is True
        assert not v.active
        assert v.owned_many(["x"]) == [True]

    def test_bad_apply_raises(self):
        v = MembershipView()
        with pytest.raises(ValueError, match="shard_index"):
            v.apply(1, 2, 2)
        with pytest.raises(ValueError, match="id_kind"):
            v.apply(1, 2, 0, id_kind="nope")


class TestRetainOnly:
    def _table(self):
        from photon_ml_tpu.serve.coeff_cache import CoeffEntry
        from photon_ml_tpu.serve.paged_table import PagedCoefficientTable

        t = PagedCoefficientTable(4, pages=3, page_rows=2, name="u")
        entries = {str(i): CoeffEntry({j: j for j in range(4)},
                                      np.full(4, float(i)))
                   for i in range(5)}
        t.install(entries)
        t.install({"ghost": None})  # absent mark must survive re-owning
        return t

    def test_drops_compacts_and_counts(self):
        t = self._table()
        keep = {"0", "2", "4"}
        assert t.retain_only(lambda e: e in keep) == 2
        assert sorted(t.resident_ids()) == sorted(keep)
        assert t.stats()["membership_drops"] == 2
        # survivors compacted into the low pages: 3 rows -> 2 pages
        buf, slots, missing = t.lookup(["0", "2", "4"])
        assert slots.max() < 4 and slots.min() >= 0
        host = np.asarray(buf)
        for eid, slot in zip(["0", "2", "4"], slots):
            np.testing.assert_array_equal(host[slot],
                                          np.full(4, float(eid)))
        # dropped entities fault again (missing), absents stay absent
        _, s2, miss = t.lookup(["1", "3", "ghost"])
        assert sorted(miss) == ["1", "3"]
        assert (s2 == -1).all()

    def test_noop_when_all_kept(self):
        t = self._table()
        assert t.retain_only(lambda e: True) == 0
        assert t.stats()["membership_drops"] == 0


class TestSessionMembership:
    def _session(self, saved_game_model):
        from photon_ml_tpu.serve import ScoringSession

        model_dir, bundle = saved_game_model
        return ScoringSession(model_dir, dtype="float64", max_batch=16,
                              coeff_cache_entries=32), bundle

    def test_set_membership_monotonic_and_eviction(self, saved_game_model):
        session, bundle = self._session(saved_game_model)
        rows = serving_rows(bundle, list(range(16)))
        session.score_rows(rows)  # populate the paged table
        session.drain_installs()
        assert session.set_membership(epoch=2, num_shards=2,
                                      shard_index=0) is True
        assert session.set_membership(epoch=2, num_shards=2,
                                      shard_index=1) is False
        view = session.membership
        assert view.epoch == 2 and view.active
        table = session._state.paged["per-user"]
        for eid in table.resident_ids():
            assert view.owned(eid)  # non-owned rows were dropped
        assert session.metrics.snapshot()["membership_epoch"] == 2

    def test_prefetch_entities_owned_slice_only(self, saved_game_model):
        session, bundle = self._session(saved_game_model)
        session.set_membership(epoch=1, num_shards=2, shard_index=0)
        view = session.membership
        all_ids = [str(i) for i in range(bundle["n_entities"])]
        owned = [e for e, o in zip(all_ids, view.owned_many(all_ids))
                 if o]
        n, nbytes = session.prefetch_entities(all_ids)
        assert n == len(owned) and nbytes > 0
        table = session._state.paged["per-user"]
        assert sorted(table.resident_ids()) == sorted(owned)
        snap = session.metrics.snapshot()
        assert snap["membership_prefetch_entities"] == n
        assert snap["membership_prefetch_bytes"] == nbytes

    def test_scores_stable_under_membership(self, saved_game_model):
        """Non-owned entities score through the LRU host-math path —
        within the repo's paged-vs-host parity tolerance (rtol=0,
        atol=1e-9, the bound every paged-table test pins), so churn can
        degrade residency but never change scores."""
        session, bundle = self._session(saved_game_model)
        rows = serving_rows(bundle, list(range(16)))
        ref = np.asarray(session.score_rows(rows))
        session.drain_installs()
        session.set_membership(epoch=3, num_shards=2, shard_index=1)
        got = np.asarray(session.score_rows(rows))
        session.drain_installs()
        np.testing.assert_allclose(got, ref, rtol=0, atol=1e-9)
        snap = session.metrics.snapshot()
        assert snap["membership_non_owned_skips"] > 0
        table = session._state.paged["per-user"]
        for eid in table.resident_ids():
            assert session.membership.owned(eid)
