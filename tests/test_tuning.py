"""Hyperparameter tuning: GP regression, random & Bayesian search, GAME
auto-tune (SURVEY.md §3.1/§4.5 parity)."""

import math

import numpy as np
import pytest

from photon_ml_tpu.tuning import (
    GaussianProcessSearch,
    ParamRange,
    RandomSearch,
    fit_gp,
    matern52,
    tune_game,
)


def test_matern52_kernel_properties():
    rng = np.random.default_rng(0)
    x = rng.random((12, 3))
    k = matern52(x, x, lengthscale=0.5, amplitude=2.0)
    # symmetric, unit diagonal * amplitude, PSD
    assert np.allclose(k, k.T)
    assert np.allclose(np.diag(k), 2.0)
    eigs = np.linalg.eigvalsh(k)
    assert eigs.min() > -1e-9
    # monotone decreasing in distance
    k2 = matern52(np.array([[0.0]]), np.array([[0.1], [0.5], [2.0]]), 0.5)
    assert k2[0, 0] > k2[0, 1] > k2[0, 2]


def test_gp_regression_recovers_smooth_function():
    rng = np.random.default_rng(1)
    x = rng.random((40, 1))
    y = np.sin(6.0 * x[:, 0]) + 0.01 * rng.normal(size=40)
    gp = fit_gp(x, y)
    xq = np.linspace(0.05, 0.95, 50)[:, None]
    mean, std = gp.predict(xq)
    rmse = np.sqrt(np.mean((mean - np.sin(6.0 * xq[:, 0])) ** 2))
    assert rmse < 0.1
    # predictive std collapses at observed points relative to far points
    m_at, s_at = gp.predict(x[:1])
    assert s_at[0] < std.max()


def test_gp_constant_targets_do_not_crash():
    x = np.linspace(0, 1, 5)[:, None]
    gp = fit_gp(x, np.ones(5))
    mean, std = gp.predict(np.array([[0.5]]))
    assert np.isfinite(mean).all() and np.isfinite(std).all()


def test_param_range_roundtrip_and_log_scale():
    lin = ParamRange("a", -2.0, 6.0)
    log = ParamRange("b", 1e-4, 1e4, log=True)
    for v in [-2.0, 0.0, 6.0]:
        assert lin.from_unit(lin.to_unit(v)) == pytest.approx(v)
    for v in [1e-4, 1.0, 1e4]:
        assert log.from_unit(log.to_unit(v)) == pytest.approx(v, rel=1e-9)
    # log midpoint is the geometric mean
    assert log.from_unit(0.5) == pytest.approx(1.0, rel=1e-6)
    with pytest.raises(ValueError):
        ParamRange("c", 1.0, 1.0)
    with pytest.raises(ValueError):
        ParamRange("d", 0.0, 1.0, log=True)


def _quadratic(params):
    return -((params["x"] - 0.7) ** 2) - (params["y"] + 0.2) ** 2


def test_random_search_improves():
    ranges = [ParamRange("x", -2.0, 2.0), ParamRange("y", -2.0, 2.0)]
    search = RandomSearch(ranges, _quadratic, seed=0, maximize=True)
    obs = search.find(60)
    assert len(obs) == 60
    best = search.best()
    assert best.value > -0.2  # near the optimum at (0.7, -0.2)


def test_gp_search_beats_random_budget():
    ranges = [ParamRange("x", -2.0, 2.0), ParamRange("y", -2.0, 2.0)]
    gp_search = GaussianProcessSearch(ranges, _quadratic, seed=3, maximize=True)
    gp_search.find(25)
    assert gp_search.best().value > -0.05


def test_gp_search_minimize_direction():
    ranges = [ParamRange("x", 0.0, 1.0)]
    search = GaussianProcessSearch(
        ranges, lambda p: (p["x"] - 0.3) ** 2, seed=0, maximize=False
    )
    search.find(20)
    assert abs(search.best().params["x"] - 0.3) < 0.1


def test_prior_observations_seed_the_search():
    ranges = [ParamRange("x", 0.0, 1.0)]
    calls = []

    def f(p):
        calls.append(p["x"])
        return -((p["x"] - 0.5) ** 2)

    search = GaussianProcessSearch(ranges, f, seed=0, maximize=True)
    for v in [0.1, 0.45, 0.9]:
        search.on_prior_observation({"x": v}, -((v - 0.5) ** 2))
    search.find(8)
    assert len(search.observations) == 11
    assert abs(search.best().params["x"] - 0.5) < 0.1


def test_tune_game_improves_over_bad_grid(game_dataset_pair):
    import jax.numpy as jnp

    from photon_ml_tpu.estimators import GameEstimator
    from photon_ml_tpu.game.descent import CoordinateConfig

    train, val = game_dataset_pair
    estimator = GameEstimator(task="logistic", n_iterations=1,
                              evaluators=["auc"], dtype=jnp.float64)
    # deliberately over-regularized starting grid
    base = [CoordinateConfig(name="fixed", coordinate_type="fixed",
                             reg_type="l2", reg_weight=1e4, max_iters=40)]
    grid_fits = estimator.fit(train, val, config_grid=[base])
    results = tune_game(
        estimator, train, val, base,
        n_iterations=4, mode="bayesian", reg_range=(1e-3, 1e4),
        prior_results=grid_fits, seed=0,
    )
    assert len(results) == 4
    best_tuned = max(r.evaluation.metrics["auc"] for r in results)
    assert best_tuned >= grid_fits[0].evaluation.metrics["auc"] - 1e-9
    # the tuned reg weights actually moved off the seed value
    tuned_weights = {r.configs[0].reg_weight for r in results}
    assert any(w != 1e4 for w in tuned_weights)


def test_tune_game_validates_inputs(game_dataset_pair):
    import jax.numpy as jnp

    from photon_ml_tpu.estimators import GameEstimator
    from photon_ml_tpu.game.descent import CoordinateConfig

    train, val = game_dataset_pair
    base = [CoordinateConfig(name="fixed", coordinate_type="fixed")]
    no_eval = GameEstimator(task="logistic", evaluators=[])
    with pytest.raises(ValueError, match="evaluator"):
        tune_game(no_eval, train, val, base, n_iterations=1)
    est = GameEstimator(task="logistic", evaluators=["auc"], dtype=jnp.float64)
    with pytest.raises(ValueError, match="mode"):
        tune_game(est, train, val, base, n_iterations=1, mode="grid")
    with pytest.raises(ValueError, match="not in configs"):
        tune_game(est, train, val, base, n_iterations=1,
                  tuned_coordinates=["nope"])
