"""Multi-host wiring (single-process testable surface): the initialize
no-op path, argument validation, and the per-process input-split math."""

import pytest

from photon_ml_tpu.parallel.multihost import (
    initialize_multihost,
    process_span,
    runtime_info,
)


def test_initialize_noop_without_coordinator():
    assert initialize_multihost() is False


def test_initialize_validates_pairing():
    with pytest.raises(ValueError, match="go together"):
        initialize_multihost("host:1234", num_processes=2, process_id=None)


def test_process_span_single_process():
    # single process owns everything
    assert process_span(100) == (0, 100)
    assert process_span(0) == (0, 0)


def test_runtime_info_shape():
    info = runtime_info()
    assert info["process_count"] == 1
    assert info["process_index"] == 0
    assert info["global_devices"] >= info["local_devices"] >= 1
    assert info["platform"] == "cpu"  # conftest pins the test platform


def test_span_partition_math():
    # simulate the formula for p processes without a real multi-host runtime
    def spans(total, p):
        base, extra = divmod(total, p)
        out = []
        for i in range(p):
            start = i * base + min(i, extra)
            out.append((start, start + base + (1 if i < extra else 0)))
        return out

    s = spans(10, 3)
    assert s == [(0, 4), (4, 7), (7, 10)]
    # contiguous, disjoint, covering
    assert s[0][0] == 0 and s[-1][1] == 10
    assert all(s[i][1] == s[i + 1][0] for i in range(2))
