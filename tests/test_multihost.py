"""Multi-host wiring (single-process testable surface): the initialize
no-op path, argument validation, and the per-process input-split math.
The REAL 2-process runtime (rendezvous, psum across processes, streamed
GAME) is exercised in tests/test_multiprocess.py."""

import numpy as np
import pytest

from photon_ml_tpu.parallel.multihost import (
    allgather_spans,
    initialize_multihost,
    process_span,
    runtime_info,
    span_of,
)


def test_initialize_noop_without_coordinator():
    assert initialize_multihost() is False


def test_initialize_validates_pairing():
    with pytest.raises(ValueError, match="go together"):
        initialize_multihost("host:1234", num_processes=2, process_id=None)


def test_process_span_single_process():
    # single process owns everything
    assert process_span(100) == (0, 100)
    assert process_span(0) == (0, 0)


def test_runtime_info_shape():
    info = runtime_info()
    assert info["process_count"] == 1
    assert info["process_index"] == 0
    assert info["global_devices"] >= info["local_devices"] >= 1
    assert info["platform"] == "cpu"  # conftest pins the test platform


@pytest.mark.parametrize("total,p", [(10, 3), (0, 4), (7, 8), (64, 8),
                                     (101, 7)])
def test_span_partition_math(total, p):
    # the production span_of itself (not a re-typed copy): contiguous,
    # disjoint, covering, sizes within 1 of each other
    s = [span_of(total, i, p) for i in range(p)]
    assert s[0][0] == 0 and s[-1][1] == total
    assert all(s[i][1] == s[i + 1][0] for i in range(p - 1))
    sizes = [b - a for a, b in s]
    assert max(sizes) - min(sizes) <= 1
    if (total, p) == (10, 3):
        assert s == [(0, 4), (4, 7), (7, 10)]


def test_process_span_uses_span_of():
    # single-process runtime: process_span must agree with span_of(., 0, 1)
    assert process_span(100) == span_of(100, 0, 1)


def test_allgather_spans_single_process_identity():
    x = np.arange(7.0)
    np.testing.assert_array_equal(allgather_spans(x, 7), x)
