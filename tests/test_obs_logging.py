"""obs/logging.py: the context filter's rank/trace/request stamps, the
idempotent driver-side configure, and the slow-request exemplar log."""

import logging

import pytest

from photon_ml_tpu.obs import trace
from photon_ml_tpu.obs.logging import (
    DEFAULT_FORMAT,
    ContextFilter,
    SlowRequestLog,
    configure,
)


@pytest.fixture(autouse=True)
def _tracer_off():
    trace.stop()
    yield
    trace.stop()


def _record(msg="m"):
    return logging.LogRecord("photon_ml_tpu.test", logging.INFO,
                             __file__, 1, msg, (), None)


class TestContextFilter:
    def test_untraced_record_gets_dash_stamps(self):
        rec = _record()
        assert ContextFilter().filter(rec) is True
        assert rec.rank == 0
        assert rec.trace_id == "-"
        assert rec.request_id == "-"

    def test_traced_record_carries_ambient_ids(self, tmp_path):
        trace.start(str(tmp_path), export_thread=False)
        with trace.request_context(request_id="req-log-1"):
            rec = _record()
            ContextFilter().filter(rec)
            assert rec.request_id == "req-log-1"
            assert rec.trace_id == trace.current_context().trace_id

    def test_default_format_renders_stamped_record(self):
        rec = _record("hello")
        ContextFilter().filter(rec)
        line = logging.Formatter(DEFAULT_FORMAT).format(rec)
        assert "rank=0" in line
        assert "trace=- req=-" in line
        assert line.endswith("photon_ml_tpu.test: hello")


class TestConfigure:
    def test_idempotent_single_handler(self):
        name = "photon_ml_tpu_test_cfg"
        logger = configure(logger_name=name)
        again = configure(logger_name=name)
        assert again is logger
        ours = [h for h in logger.handlers
                if getattr(h, "_photon_obs_handler", False)]
        assert len(ours) == 1
        filters = [f for f in logger.filters
                   if isinstance(f, ContextFilter)]
        assert len(filters) == 1
        for h in ours:
            logger.removeHandler(h)


class TestSlowRequestLog:
    def test_top_n_kept_worst_first(self):
        srl = SlowRequestLog(top_n=3)
        for i, lat in enumerate([5.0, 50.0, 1.0, 20.0, 9.0]):
            srl.note(f"r{i}", lat, rows=i)
        snap = srl.snapshot()
        assert [e["request_id"] for e in snap] == ["r1", "r3", "r4"]
        assert [e["latency_ms"] for e in snap] == [50.0, 20.0, 9.0]

    def test_entrants_logged_with_breakdown(self, caplog):
        srl = SlowRequestLog(top_n=1,
                             logger=logging.getLogger("photon_test_srl"))
        with caplog.at_level(logging.INFO, logger="photon_test_srl"):
            srl.note("slow-1", 100.0, queue_wait_ms=70.0, compute_ms=30.0)
            srl.note("fast-1", 1.0)  # below the bar: not logged
        assert len(caplog.records) == 1
        msg = caplog.records[0].getMessage()
        assert "slow-1" in msg and "queue_wait_ms" in msg

    def test_none_request_id_becomes_dash(self):
        srl = SlowRequestLog(top_n=2)
        srl.note(None, 3.0)
        assert srl.snapshot()[0]["request_id"] == "-"
