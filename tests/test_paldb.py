"""Native persistent index store (the PalDB replacement — SURVEY.md §3.3):
build → reopen → lookup parity with the in-memory IndexMap."""

import numpy as np
import pytest

from photon_ml_tpu.io.index_map import IndexMap
from photon_ml_tpu.io.paldb import PersistentIndexMap, build_store, load_index_map
from photon_ml_tpu.io.schemas import INTERCEPT_KEY, feature_key


@pytest.fixture(scope="module")
def store(tmp_path_factory):
    forward = {feature_key(f"name{i}", f"t{i % 7}"): i for i in range(5000)}
    forward["unicode→feature"] = 5000
    forward[INTERCEPT_KEY] = 5001
    path = str(tmp_path_factory.mktemp("paldb") / "index.store")
    build_store(forward, path)
    return forward, path


def test_build_open_lookup_parity(store):
    forward, path = store
    pmap = PersistentIndexMap(path)
    assert pmap.size == len(forward)
    assert pmap.intercept_index == 5001
    # every key resolves to the same index as the dict
    for key, idx in list(forward.items())[::97]:
        name, _, term = key.partition("\x01")
        assert pmap.index_of(name, term) == idx
    assert pmap.index_of("nope") is None
    assert pmap.index_of("name1", "wrong-term") is None


def test_inverse_and_items_roundtrip(store):
    forward, path = store
    pmap = PersistentIndexMap(path)
    assert dict(pmap.items()) == forward
    inv = pmap.inverse()
    assert len(inv) == len(forward)
    assert inv[5000] == "unicode→feature"


def test_lookup_batch(store):
    forward, path = store
    pmap = PersistentIndexMap(path)
    keys = list(forward)[:100] + ["missing-a", "missing-b"]
    out = pmap.lookup_batch(keys)
    expect = np.array([forward.get(k, -1) for k in keys], np.int32)
    np.testing.assert_array_equal(out, expect)


def test_duplicate_keys_rejected(tmp_path):
    import photon_ml_tpu.io.paldb as paldb

    with pytest.raises(OSError):
        # same key twice via the raw builder path
        lib = paldb._lib()
        import ctypes

        blob = b"aa" + b"aa"
        offsets = np.array([0, 2], np.uint64)
        lens = np.array([2, 2], np.uint32)
        indices = np.array([0, 1], np.int32)
        rc = lib.fis_build(
            blob,
            offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            lens.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)),
            indices.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            ctypes.c_uint64(2),
            str(tmp_path / "dup.store").encode(),
        )
        if rc != 0:
            raise OSError(-rc, "duplicate")


def test_load_index_map_sniffs_backend(store, tmp_path):
    forward, path = store
    assert isinstance(load_index_map(path), PersistentIndexMap)
    jmap = IndexMap({"a": 0, "b": 1})
    jpath = str(tmp_path / "map.json")
    jmap.save(jpath)
    loaded = load_index_map(jpath)
    assert isinstance(loaded, IndexMap)
    assert loaded.index_of("b") == 1


def test_empty_store(tmp_path):
    path = str(tmp_path / "empty.store")
    build_store({}, path)
    pmap = PersistentIndexMap(path)
    assert pmap.size == 0
    assert pmap.intercept_index == -1
    assert pmap.index_of("anything") is None
    assert dict(pmap.items()) == {}


def test_indexing_driver_paldb_format(tmp_path, rng):
    from photon_ml_tpu.cli.feature_indexing_driver import main as index_main
    from photon_ml_tpu.io.data_reader import (
        feature_tuples_from_dense,
        write_training_examples,
    )

    X = rng.normal(size=(20, 4))
    y = (rng.random(20) < 0.5).astype(float)
    write_training_examples(
        str(tmp_path / "d.avro"), feature_tuples_from_dense(X), y
    )
    out = str(tmp_path / "index.store")
    rc = index_main(["--data", str(tmp_path / "d.avro"),
                     "--output", out, "--store-format", "paldb"])
    assert rc == 0
    pmap = load_index_map(out)
    assert isinstance(pmap, PersistentIndexMap)
    assert pmap.size == 5  # 4 features + intercept
    assert pmap.intercept_index >= 0
