"""Test scaffolding: 8 virtual CPU devices, f64 enabled for math-parity tests.

The moral equivalent of the reference's ``SparkTestUtils`` local-mode
SparkSession (SURVEY.md §8): "distributed" code is exercised on
``--xla_force_host_platform_device_count=8`` CPU devices without real TPUs.
Must run before jax initializes, hence the env mutation at import time.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# The axon sitecustomize force-sets jax_platforms=axon,cpu at interpreter
# startup (overriding JAX_PLATFORMS); override it back before first backend use.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(0)


@pytest.fixture
def game_dataset_pair():
    """Small logistic train/validation GameDataset pair (shared by tuning
    and estimator tests)."""
    from photon_ml_tpu.game.descent import make_game_dataset

    r = np.random.default_rng(7)
    n, d = 500, 8
    X = r.normal(size=(n, d))
    w = r.normal(size=d)
    y = (r.random(n) < 1 / (1 + np.exp(-X @ w))).astype(float)
    tr, va = np.arange(350), np.arange(350, n)
    return (make_game_dataset(X[tr], y[tr]), make_game_dataset(X[va], y[va]))
