"""Test scaffolding: 8 virtual CPU devices, f64 enabled for math-parity tests.

The moral equivalent of the reference's ``SparkTestUtils`` local-mode
SparkSession (SURVEY.md §8): "distributed" code is exercised on
``--xla_force_host_platform_device_count=8`` CPU devices without real TPUs.
Must run before jax initializes, hence the env mutation at import time.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# The axon sitecustomize force-sets jax_platforms=axon,cpu at interpreter
# startup (overriding JAX_PLATFORMS); override it back before first backend use.
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(0)


@pytest.fixture(scope="session")
def saved_game_model(tmp_path_factory):
    """A small trained GAME model (fixed + per-user random effect) saved
    to disk in the io/model_io layout, shared by the serving tests.
    Returns (model_dir, bundle) where bundle carries the raw arrays and
    the in-memory model for parity references."""
    import jax.numpy as jnp

    from photon_ml_tpu.game.descent import (
        CoordinateConfig, CoordinateDescent, make_game_dataset,
    )
    from photon_ml_tpu.io.index_map import IndexMap
    from photon_ml_tpu.io.model_io import load_game_model, save_game_model

    r = np.random.default_rng(11)
    n, d_fix, d_re, n_entities = 160, 6, 4, 9
    Xg = r.normal(size=(n, d_fix))
    Xu = r.normal(size=(n, d_re))
    uid = r.integers(0, n_entities, n)
    y = (r.random(n) < 0.5).astype(float)
    ds = make_game_dataset({"g": Xg, "u": Xu}, y,
                           entity_ids={"userId": uid})
    cd = CoordinateDescent(
        [CoordinateConfig("fixed", feature_shard="g", reg_type="l2",
                          reg_weight=1.0),
         CoordinateConfig("per-user", coordinate_type="random",
                          feature_shard="u", entity_column="userId",
                          reg_type="l2", reg_weight=1.0)],
        task="logistic", dtype=jnp.float64)
    model, _ = cd.run(ds)
    model_dir = str(tmp_path_factory.mktemp("serving") / "model")
    save_game_model(model, model_dir, {
        "g": IndexMap({f"g{j}": j for j in range(d_fix)}),
        "u": IndexMap({f"u{j}": j for j in range(d_re)}),
    })
    bundle = {
        "Xg": Xg, "Xu": Xu, "uid": uid, "d_fix": d_fix, "d_re": d_re,
        "n_entities": n_entities, "loaded": load_game_model(model_dir),
    }
    return model_dir, bundle


def serving_rows(bundle, row_idx, entity_ids=None, offsets=None):
    """Request rows (the serving JSON shape) for a slice of the shared
    fixture's data — used by several serving test files."""
    Xg, Xu = bundle["Xg"], bundle["Xu"]
    uid = bundle["uid"] if entity_ids is None else entity_ids
    rows = []
    for pos, i in enumerate(row_idx):
        feats = [{"name": f"g{j}", "value": float(Xg[i, j])}
                 for j in range(bundle["d_fix"])]
        feats += [{"name": f"u{j}", "value": float(Xu[i, j])}
                  for j in range(bundle["d_re"])]
        row = {"features": feats, "entityIds": {"userId": str(uid[i])}}
        if offsets is not None:
            row["offset"] = float(offsets[pos])
        rows.append(row)
    return rows


@pytest.fixture
def game_dataset_pair():
    """Small logistic train/validation GameDataset pair (shared by tuning
    and estimator tests)."""
    from photon_ml_tpu.game.descent import make_game_dataset

    r = np.random.default_rng(7)
    n, d = 500, 8
    X = r.normal(size=(n, d))
    w = r.normal(size=d)
    y = (r.random(n) < 1 / (1 + np.exp(-X @ w))).astype(float)
    tr, va = np.arange(350), np.arange(350, n)
    return (make_game_dataset(X[tr], y[tr]), make_game_dataset(X[va], y[va]))
