"""Runtime sanitizers: collective-trace alignment over the simulated
multi-controller harness, and the CompileSanitizer flat-counter
contract."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from photon_ml_tpu.analysis.sanitizers import (
    CollectiveTraceMismatch,
    CollectiveTraceSanitizer,
    CompileSanitizer,
    CompileSanitizerError,
    describe_payload,
)
from photon_ml_tpu.testing import run_simulated_processes


# -- trace verifier (pure) --------------------------------------------------
def test_verify_accepts_aligned_and_prefix_traces():
    a = [("status", "p1", "i32"), ("payload", "x", "bytes")]
    CollectiveTraceSanitizer.verify({0: a, 1: list(a)})
    # fail-stop: a dead process's shorter trace is a clean prefix
    CollectiveTraceSanitizer.verify({0: a, 1: a[:1], 2: []})


def test_verify_names_step_site_and_ranks_on_divergence():
    traces = {
        0: [("status", "p1", "i32"), ("payload", "extra", "bytes")],
        1: [("status", "p1", "i32"), ("status", "p2", "i32")],
    }
    with pytest.raises(CollectiveTraceMismatch) as err:
        CollectiveTraceSanitizer.verify(traces, context="unit")
    msg = str(err.value)
    assert "step 1" in msg and "unit" in msg
    assert "'extra'" in msg and "'p2'" in msg
    assert "process 0" in msg and "process 1" in msg


def test_describe_payload_kinds():
    assert describe_payload(b"xx") == "bytes"
    assert describe_payload(3) == "i32"
    assert describe_payload(np.zeros((2, 3))) == "float64[2d]"
    assert describe_payload(None) == "none"


# -- wired into the simulated harness --------------------------------------
def test_simulated_aligned_collectives_pass():
    """Barriers + a payload exchange on every rank: the default-on trace
    verification accepts the run (the 4-process legs the entity-shard
    and resilience tests run stay green under the sanitizer)."""
    from photon_ml_tpu.parallel import resilience
    from photon_ml_tpu.parallel.entity_shard import exchange_score_updates

    def fn(rank):
        resilience.health_barrier("phase1", timeout=10.0)
        rows = np.arange(rank + 1, dtype=np.int32)  # rank-varying SIZE ok
        got = exchange_score_updates(
            [rows, rows.astype(np.float64)], tag="t", timeout=10.0)
        resilience.health_barrier("phase2", timeout=10.0)
        return len(got)

    outcomes = run_simulated_processes(4, fn, join_timeout=30.0)
    assert outcomes == [4, 4, 4, 4]


def test_simulated_rank_conditioned_extra_allgather_detected():
    """THE acceptance fixture: one rank issues an extra collective
    behind a rank condition. The generations pair up mismatched ops —
    exactly the silent corruption the sanitizer exists to catch — and
    verification at join reports site + ranks."""
    from photon_ml_tpu.parallel import resilience

    def fn(rank):
        resilience.health_barrier("phase1", timeout=5.0)
        if rank == 0:  # process-divergent collective (PC102 at runtime)
            tp = resilience.current_transport()
            tp.allgather_payload(b"rogue", 2.0)
        resilience.health_barrier("phase2", timeout=2.0)

    with pytest.raises(CollectiveTraceMismatch) as err:
        run_simulated_processes(4, fn, join_timeout=30.0)
    msg = str(err.value)
    assert "payload" in msg and "'phase2'" in msg
    assert "process" in msg and "diverged" in msg


def test_simulated_failstop_prefix_tolerated():
    """A process that dies locally stops issuing collectives; peers
    coordinate the abort at the next barrier. Traces diverge in LENGTH
    only — the sanitizer must not flag fail-stop."""
    from photon_ml_tpu.parallel import fault_injection, resilience

    def fn(rank):
        resilience.health_barrier("phase1", timeout=10.0)
        fault_injection.check("sanitizer.work")
        resilience.health_barrier("phase2", timeout=10.0)
        return "ok"

    fault_injection.install([fault_injection.Fault(
        site="sanitizer.work", kind="raise", process=2)])
    try:
        outcomes = run_simulated_processes(
            3, lambda r: _guarded(fn, r), join_timeout=30.0)
    finally:
        fault_injection.clear()
    assert isinstance(outcomes[2], resilience.PeerFailure)  # reporter
    assert isinstance(outcomes[0], resilience.PeerFailure)
    assert isinstance(outcomes[1], resilience.PeerFailure)


def _guarded(fn, rank):
    from photon_ml_tpu.parallel.resilience import CollectiveGuard

    with CollectiveGuard("sanitizer.step", timeout=10.0):
        return fn(rank)


def test_simulated_divergent_phase_tags_detected_on_clean_run():
    """Two processes sitting in DIFFERENT phases whose barriers happen
    to pair up (same op, same payload kind, both report OK) complete
    'successfully' — the classic silent phase skew. On a clean run the
    sanitizer compares sites strictly and catches it."""
    from photon_ml_tpu.parallel import resilience

    def fn(rank):
        resilience.health_barrier("phase1", timeout=5.0)
        resilience.health_barrier("warmup" if rank == 0 else "train",
                                  timeout=5.0)
        return "ok"

    with pytest.raises(CollectiveTraceMismatch) as err:
        run_simulated_processes(2, fn, join_timeout=30.0)
    assert "'warmup'" in str(err.value) and "'train'" in str(err.value)


def test_verify_collectives_can_be_disabled():
    from photon_ml_tpu.parallel import resilience

    def fn(rank):
        if rank == 0:
            tp = resilience.current_transport()
            tp.allgather_payload(b"rogue", 1.0)

    outcomes = run_simulated_processes(2, fn, join_timeout=15.0,
                                       verify_collectives=False)
    # rank 1 exits without collectives; rank 0's rogue gather times out
    assert outcomes[1] is None


# -- CompileSanitizer -------------------------------------------------------
class _FakeSession:
    def __init__(self):
        self.compile_count = 0


def test_compile_sanitizer_flat_block_passes():
    session = _FakeSession()
    with CompileSanitizer(session, label="fake") as san:
        san.check("mid")
        assert san.new_compiles == 0


def test_compile_sanitizer_raises_with_label_and_moment():
    session = _FakeSession()
    with pytest.raises(CompileSanitizerError) as err:
        with CompileSanitizer(session, label="serving ladder") as san:
            session.compile_count += 2
            san.check("request wave 3")
    msg = str(err.value)
    assert "serving ladder" in msg and "request wave 3" in msg
    assert "0 -> 2" in msg


def test_compile_sanitizer_checks_at_exit_and_max_new():
    session = _FakeSession()
    with pytest.raises(CompileSanitizerError, match="block exit"):
        with CompileSanitizer(session):
            session.compile_count += 1
    # an allowed lazy first-touch budget
    session = _FakeSession()
    with CompileSanitizer(session, max_new=1):
        session.compile_count += 1


def test_compile_sanitizer_callable_counter_and_multi():
    counts = {"a": 0, "b": 0}
    with CompileSanitizer(lambda: counts["a"], lambda: counts["b"]) as san:
        assert san.new_compiles == 0
    with pytest.raises(CompileSanitizerError):
        with CompileSanitizer(lambda: counts["a"], lambda: counts["b"]):
            counts["b"] += 1


def test_compile_sanitizer_does_not_mask_body_exception():
    session = _FakeSession()
    with pytest.raises(ValueError, match="body"):
        with CompileSanitizer(session):
            session.compile_count += 5  # would fail the exit check
            raise ValueError("body")  # but the body error wins


def test_compile_sanitizer_rejects_bad_counter():
    with pytest.raises(TypeError, match="compile_count"):
        CompileSanitizer(object())
    with pytest.raises(ValueError, match="at least one"):
        CompileSanitizer()
