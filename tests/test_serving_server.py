"""Scoring service + HTTP server: in-process (no-socket) endpoint tests,
the load-shedding status contract, one real-HTTP smoke test, and a slow
concurrency soak (excluded from tier-1 via the ``slow`` marker)."""

import json
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax.numpy as jnp

from tests.conftest import serving_rows


@pytest.fixture
def service(saved_game_model):
    from photon_ml_tpu.serve import (
        MicroBatcher,
        ScoringService,
        ScoringSession,
    )

    model_dir, bundle = saved_game_model
    session = ScoringSession(model_dir, dtype="float64", max_batch=16,
                             coeff_cache_entries=16)
    batcher = MicroBatcher(session.score_rows, max_batch=16,
                           max_delay_ms=2.0, max_queue=32,
                           metrics=session.metrics)
    svc = ScoringService(session, batcher, request_timeout_s=30.0)
    yield svc, bundle
    svc.close()


def test_score_endpoint_in_process(service):
    from photon_ml_tpu.game.scoring import score_game_model

    svc, bundle = service
    idx = list(range(6))
    rows = serving_rows(bundle, idx)
    for pos, r in enumerate(rows):
        r["uid"] = f"req-{pos}"
    status, body = svc.handle_score({"rows": rows, "perCoordinate": True})
    assert status == 200
    ref = score_game_model(
        bundle["loaded"],
        {"g": bundle["Xg"][idx], "u": bundle["Xu"][idx]},
        {"userId": np.asarray([str(bundle["uid"][i]) for i in idx])},
        dtype=jnp.float64)
    np.testing.assert_allclose(body["scores"], np.asarray(ref), atol=1e-9)
    assert body["uids"] == [f"req-{p}" for p in range(6)]
    assert set(body["scoreComponents"]) == {"fixed", "per-user"}


def test_malformed_requests_are_400(service):
    svc, _ = service
    for payload in (None, [], {"rows": "nope"}, {"rows": []},
                    {"rows": [42]}):
        status, body = svc.handle_score(payload)
        assert status == 400, payload
        assert "error" in body
    # oversized single request: explicit 400, not a hang or a shed
    status, body = svc.handle_score(
        {"rows": [{"features": []} for _ in range(17)]})
    assert status == 400
    assert "max_batch" in body["error"]


def test_healthz_and_metrics_surface(service):
    svc, bundle = service
    svc.handle_score({"rows": serving_rows(bundle, [0, 1])})
    status, health = svc.handle_healthz()
    assert status == 200
    assert health["status"] == "ok"
    assert health["task"] == "logistic"
    status, text = svc.handle_metrics()
    assert status == 200
    for series in (
        "photon_serve_requests_total",
        "photon_serve_request_latency_ms_bucket",
        "photon_serve_queue_depth",
        "photon_serve_batch_fill_ratio",
        "photon_serve_compile_cache_hit_rate",
        "photon_serve_coeff_cache_hit_rate",
        "photon_serve_shed_total",
    ):
        assert series in text, f"missing {series} in /metrics"
    snap = svc.metrics.snapshot()
    assert snap["requests_total"] >= 1
    assert snap["rows_total"] >= 2
    assert 0 < snap["batch_fill_ratio"] <= 1.0


def test_queue_full_is_429(saved_game_model):
    """The bounded queue surfaces as HTTP 429 with shed=true — the
    load-shedding contract, asserted without hangs."""
    from photon_ml_tpu.serve import (
        MicroBatcher,
        ScoringService,
        ScoringSession,
    )

    model_dir, bundle = saved_game_model
    session = ScoringSession(model_dir, max_batch=4, warmup=False)
    release = threading.Event()

    def blocked(rows, per_coordinate=False):
        release.wait(10.0)
        return session.score_rows(rows, per_coordinate)

    batcher = MicroBatcher(blocked, max_batch=4, max_delay_ms=1.0,
                           max_queue=1, metrics=session.metrics)
    svc = ScoringService(session, batcher, request_timeout_s=30.0)
    rows = serving_rows(bundle, [0])
    try:
        holder = batcher.submit(rows)  # worker takes it, blocks
        import time

        time.sleep(0.05)
        batcher.submit(rows)  # fills the queue
        status, body = svc.handle_score({"rows": rows})
        assert status == 429
        assert body["shed"] is True
        assert svc.metrics.snapshot()["shed_total"] == 1
        release.set()
        holder.result(10.0)
    finally:
        release.set()
        svc.close()


def test_http_smoke(service):
    """One REAL-socket test: the stdlib server answers /score, /healthz,
    /metrics, and 404s unknown paths over the wire."""
    from photon_ml_tpu.serve import ScoringServer

    svc, bundle = service
    server = ScoringServer(svc, port=0).start()
    url = f"http://127.0.0.1:{server.port}"
    try:
        rows = serving_rows(bundle, [0, 1, 2])
        req = urllib.request.Request(
            url + "/score",
            data=json.dumps({"rows": rows}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as r:
            assert r.status == 200
            body = json.loads(r.read())
        assert len(body["scores"]) == 3
        with urllib.request.urlopen(url + "/healthz", timeout=30) as r:
            assert json.loads(r.read())["status"] == "ok"
        with urllib.request.urlopen(url + "/metrics", timeout=30) as r:
            assert b"photon_serve_requests_total" in r.read()
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(url + "/nope", timeout=30)
        assert err.value.code == 404
        # bad JSON -> 400 over the wire
        bad = urllib.request.Request(
            url + "/score", data=b"{not json",
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(bad, timeout=30)
        assert err.value.code == 400
    finally:
        server._httpd.shutdown()
        server._httpd.server_close()


def test_serving_driver_build(saved_game_model):
    """The CLI driver wires session/batcher/server from args (ephemeral
    port) and rejects non-positive sizing flags."""
    from photon_ml_tpu.cli.serving_driver import build_arg_parser, build_server

    model_dir, bundle = saved_game_model
    parser = build_arg_parser()
    with pytest.raises(SystemExit):
        parser.parse_args(["--model-dir", model_dir, "--max-batch", "0"])
    with pytest.raises(SystemExit):
        parser.parse_args(["--model-dir", model_dir, "--max-queue", "-1"])
    args = parser.parse_args([
        "--model-dir", model_dir, "--port", "0", "--max-batch", "8",
        "--watchdog-s", "0",  # <= 0 disables the watchdog
    ])
    server, registry = build_server(args)
    assert registry is None  # --model-dir mode has no registry
    try:
        assert server.port > 0
        assert server.service.batcher.watchdog_s is None
        assert server.service.session.compile_count >= 1  # warmed up
        status, body = server.service.handle_score(
            {"rows": serving_rows(bundle, [0, 1])})
        assert status == 200 and len(body["scores"]) == 2
    finally:
        server.close()


@pytest.mark.slow
def test_concurrency_soak(saved_game_model):
    """Long leg: many client threads hammering the HTTP server; every
    non-shed response must be correct, metrics must reconcile, and the
    compile cache must stay flat after warmup."""
    from photon_ml_tpu.serve import (
        MicroBatcher,
        ScoringServer,
        ScoringService,
        ScoringSession,
    )

    model_dir, bundle = saved_game_model
    session = ScoringSession(model_dir, dtype="float64", max_batch=16)
    warm = session.compile_count
    batcher = MicroBatcher(session.score_rows, max_batch=16,
                           max_delay_ms=2.0, max_queue=128,
                           metrics=session.metrics)
    svc = ScoringService(session, batcher)
    server = ScoringServer(svc, port=0).start()
    url = f"http://127.0.0.1:{server.port}/score"
    rng = np.random.default_rng(5)
    errors, shed, ok = [], [0], [0]

    def client(seed):
        r = np.random.default_rng(seed)
        for _ in range(25):
            n = int(r.integers(1, 5))
            idx = r.integers(0, len(bundle["uid"]), n)
            rows = serving_rows(bundle, idx)
            req = urllib.request.Request(
                url, data=json.dumps({"rows": rows}).encode(),
                headers={"Content-Type": "application/json"})
            try:
                with urllib.request.urlopen(req, timeout=60) as resp:
                    body = json.loads(resp.read())
                    if len(body["scores"]) != n:
                        errors.append("row-count mismatch")
                    ok[0] += 1
            except urllib.error.HTTPError as e:
                if e.code == 429:
                    shed[0] += 1
                else:
                    errors.append(f"http {e.code}")
            except Exception as e:  # noqa: BLE001 - soak must report all
                errors.append(repr(e))

    threads = [threading.Thread(target=client, args=(s,))
               for s in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(120)
    try:
        assert not errors, errors[:5]
        assert ok[0] + shed[0] == 8 * 25
        assert ok[0] > 0
        assert session.compile_count == warm, "soak must not recompile"
        snap = svc.metrics.snapshot()
        assert snap["requests_total"] == ok[0]
        assert snap["shed_total"] == shed[0]
    finally:
        server.close()
