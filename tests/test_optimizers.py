"""Optimizer tests vs scipy/sklearn ground truth on convex problems
(the reference's optimizer unit tier: known convex problems, SURVEY.md §8)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import scipy.optimize

from photon_ml_tpu.ops.objective import make_objective
from photon_ml_tpu.optimize import OptimizerConfig, lbfgs, owlqn, tron
from photon_ml_tpu.types import make_batch


def _logreg_problem(rng, n=200, d=10, l2=1.0):
    X = rng.normal(size=(n, d))
    w_true = rng.normal(size=d)
    y = (rng.random(n) < 1 / (1 + np.exp(-X @ w_true))).astype(float)
    batch = make_batch(jnp.asarray(X), y, dtype=jnp.float64)
    obj = make_objective("logistic")
    fg = lambda w: obj.value_and_grad(w, batch, l2)
    # scipy reference solution
    def f_np(w):
        m = X @ w
        return np.sum(np.logaddexp(0, m) - y * m) + 0.5 * l2 * w @ w
    def g_np(w):
        m = X @ w
        return X.T @ (1 / (1 + np.exp(-m)) - y) + l2 * w
    ref = scipy.optimize.minimize(f_np, np.zeros(d), jac=g_np, method="L-BFGS-B",
                                  options={"ftol": 1e-14, "gtol": 1e-10})
    return fg, obj, batch, X, y, ref, l2


def test_lbfgs_matches_scipy(rng):
    fg, obj, batch, X, y, ref, l2 = _logreg_problem(rng)
    res = lbfgs(fg, jnp.zeros(X.shape[1]), OptimizerConfig(max_iters=200, tolerance=1e-10))
    assert bool(res.converged)
    np.testing.assert_allclose(res.value, ref.fun, rtol=1e-8)
    np.testing.assert_allclose(res.w, ref.x, rtol=1e-4, atol=1e-5)
    # history recorded, monotone-ish decreasing, NaN-padded after `iterations`
    it = int(res.iterations)
    hist = np.asarray(res.loss_history)
    assert np.all(np.isfinite(hist[:it])) and np.all(np.isnan(hist[it:]))
    assert hist[it - 1] <= hist[0] + 1e-12


def test_lbfgs_jits_and_quadratic_exact(rng):
    A = rng.normal(size=(12, 8))
    Q = A.T @ A + 0.5 * np.eye(8)
    b = rng.normal(size=8)
    fun = lambda w: (0.5 * w @ jnp.asarray(Q) @ w - jnp.asarray(b) @ w,
                     jnp.asarray(Q) @ w - jnp.asarray(b))
    run = jax.jit(lambda w0: lbfgs(fun, w0, OptimizerConfig(max_iters=100, tolerance=1e-12)))
    res = run(jnp.zeros(8))
    np.testing.assert_allclose(res.w, np.linalg.solve(Q, b), rtol=1e-6, atol=1e-8)


def test_tron_matches_scipy(rng):
    fg, obj, batch, X, y, ref, l2 = _logreg_problem(rng)
    res = tron(fg, jnp.zeros(X.shape[1]), OptimizerConfig(max_iters=100, tolerance=1e-10))
    assert bool(res.converged)
    np.testing.assert_allclose(res.value, ref.fun, rtol=1e-9)
    np.testing.assert_allclose(res.w, ref.x, rtol=1e-4, atol=1e-6)


def test_tron_poisson(rng):
    n, d = 150, 6
    X = rng.normal(size=(n, d)) * 0.5
    w_true = rng.normal(size=d) * 0.5
    y = rng.poisson(np.exp(X @ w_true)).astype(float)
    batch = make_batch(jnp.asarray(X), y, dtype=jnp.float64)
    obj = make_objective("poisson")
    fg = lambda w: obj.value_and_grad(w, batch, 0.5)
    res = tron(fg, jnp.zeros(d), OptimizerConfig(max_iters=100, tolerance=1e-10))
    def f_np(w):
        m = X @ w
        return np.sum(np.exp(m) - y * m) + 0.25 * w @ w
    ref = scipy.optimize.minimize(f_np, np.zeros(d), method="L-BFGS-B",
                                  options={"ftol": 1e-14, "gtol": 1e-10})
    np.testing.assert_allclose(res.value, ref.fun, rtol=1e-8)


def test_owlqn_matches_sklearn_l1(rng):
    from sklearn.linear_model import LogisticRegression

    n, d = 300, 12
    X = rng.normal(size=(n, d))
    w_true = np.where(rng.random(d) < 0.5, 0.0, rng.normal(size=d))
    y = (rng.random(n) < 1 / (1 + np.exp(-X @ w_true))).astype(float)
    l1 = 3.0
    batch = make_batch(jnp.asarray(X), y, dtype=jnp.float64)
    obj = make_objective("logistic")
    fg = lambda w: obj.value_and_grad(w, batch, 0.0)
    res = owlqn(fg, jnp.zeros(d), l1, OptimizerConfig(max_iters=300, tolerance=1e-9))
    # sklearn liblinear: C = 1/l1 (sum-loss convention), no intercept
    sk = LogisticRegression(penalty="l1", C=1.0 / l1, solver="liblinear",
                            fit_intercept=False, tol=1e-10, max_iter=5000)
    sk.fit(X, y)
    w_sk = sk.coef_.ravel()
    F = lambda w: float(obj.value(jnp.asarray(w), batch, 0.0)) + l1 * np.abs(w).sum()
    # objective value parity (coefficients may differ slightly at equal loss)
    assert F(np.asarray(res.w)) <= F(w_sk) * (1 + 1e-5)
    # sparsity: recovered support should be sparse like sklearn's
    assert (np.abs(np.asarray(res.w)) < 1e-8).sum() > 0
    np.testing.assert_allclose(np.asarray(res.w), w_sk, atol=5e-3)


def test_owlqn_zero_l1_equals_lbfgs(rng):
    fg, obj, batch, X, y, ref, l2 = _logreg_problem(rng)
    res = owlqn(fg, jnp.zeros(X.shape[1]), 0.0, OptimizerConfig(max_iters=200, tolerance=1e-10))
    np.testing.assert_allclose(res.value, ref.fun, rtol=1e-7)


def test_elastic_net_via_owlqn_plus_l2(rng):
    # elastic net = L2 folded into smooth objective + L1 via OWL-QN
    from photon_ml_tpu.ops.regularization import RegularizationContext, RegularizationType

    ctx = RegularizationContext(RegularizationType.ELASTIC_NET, alpha=0.4)
    lam = 2.0
    assert np.isclose(ctx.l1_weight(lam), 0.8)
    assert np.isclose(ctx.l2_weight(lam), 1.2)
    n, d = 100, 5
    X = rng.normal(size=(n, d))
    y = (rng.random(n) < 0.5).astype(float)
    batch = make_batch(jnp.asarray(X), y, dtype=jnp.float64)
    obj = make_objective("logistic")
    fg = lambda w: obj.value_and_grad(w, batch, ctx.l2_weight(lam))
    res = owlqn(fg, jnp.zeros(d), ctx.l1_weight(lam), OptimizerConfig(max_iters=200))
    assert bool(res.converged)
    assert np.isfinite(float(res.value))


def test_line_search_failure_at_optimum_reports_converged(rng):
    """Starting AT the minimizer, the first line search cannot make
    progress (zero/tiny gradient); that must report converged=True via
    the gradient test, not a stall — and never a spurious relative-loss
    'convergence' from the unchanged f."""
    n, d = 300, 8
    X = rng.normal(size=(n, d))
    w_true = rng.normal(size=d)
    y = (rng.random(n) < 1 / (1 + np.exp(-X @ w_true))).astype(float)
    batch = make_batch(jnp.asarray(X), y, dtype=jnp.float64)
    obj = make_objective("logistic")
    cfg = OptimizerConfig(max_iters=200, tolerance=1e-10)

    fg = lambda w: obj.value_and_grad(w, batch, 1.0)
    first = lbfgs(fg, jnp.zeros(d, jnp.float64), cfg)
    assert bool(first.converged)
    # restart from the solution: immediate gradient-test convergence
    again = lbfgs(fg, first.w, cfg)
    assert bool(again.converged)
    assert int(again.iterations) <= 2
    # it may take one more tiny productive step before the gradient
    # test fires; the point must stay at the same optimum
    np.testing.assert_allclose(np.asarray(again.w), np.asarray(first.w),
                               rtol=1e-5, atol=1e-7)


def test_tron_jacobi_preconditioner(rng):
    """Jacobi-preconditioned TRON: same optimum, far fewer outer
    iterations on a badly-scaled problem (each CG step in the distributed
    setting is a full data pass, so this is the cost that matters)."""
    from photon_ml_tpu.optimize.tron import tron

    n, d = 2000, 40
    scales = np.logspace(-2, 2, d)
    X = rng.normal(size=(n, d)) * scales
    w_true = rng.normal(size=d) / scales
    y = (rng.random(n) < 1 / (1 + np.exp(-X @ w_true))).astype(float)
    batch = make_batch(jnp.asarray(X), y, dtype=jnp.float64)
    obj = make_objective("logistic")
    fg = lambda w: obj.value_and_grad(w, batch, 1.0)
    hvp = lambda w, v: obj.hvp(w, v, batch, 1.0)
    diag = lambda w: obj.diagonal_hessian(w, batch, 1.0)
    cfg = OptimizerConfig(max_iters=100, tolerance=1e-10)

    plain = tron(fg, jnp.zeros(d, jnp.float64), cfg, hvp=hvp)
    prec = tron(fg, jnp.zeros(d, jnp.float64), cfg, hvp=hvp, precond=diag)
    assert bool(prec.converged)
    assert int(prec.iterations) < int(plain.iterations)
    np.testing.assert_allclose(float(prec.value), float(plain.value),
                               rtol=1e-6)
    np.testing.assert_allclose(np.asarray(prec.w), np.asarray(plain.w),
                               rtol=1e-2, atol=1e-4)
