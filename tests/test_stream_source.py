"""Out-of-core AvroChunkSource: disk-backed streamed fits (VERDICT r4 #2).

Contract under test: a fit_streaming over an AvroChunkSource equals the
same fit over in-RAM chunks of the same data; the source is re-iterable
(every optimizer pass re-decodes from disk); host memory stays bounded by
the prefetch depth, not the dataset; both decode backends (native C++,
pure-Python codec) agree.
"""

import os
import threading
import time

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp

from photon_ml_tpu.io.data_reader import (
    read_training_examples,
    write_training_examples,
)
from photon_ml_tpu.io.index_map import IndexMap
from photon_ml_tpu.io.stream_source import AvroChunkSource, scan_blocks
from photon_ml_tpu.ops.objective import make_objective
from photon_ml_tpu.optimize import OptimizerConfig
from photon_ml_tpu.parallel.streaming import fit_streaming, make_host_chunks
from photon_ml_tpu.game.data import HostSparse


def _write_dataset(tmp_path, rng, n=300, vocab=40, max_k=6, name="train",
                   block_size=4096):
    rows = []
    for _ in range(n):
        k = int(rng.integers(1, max_k + 1))
        cols = rng.choice(vocab, size=k, replace=False)
        rows.append([(f"f{c}", "", float(rng.normal())) for c in cols])
    labels = rng.integers(0, 2, n).astype(float)
    weights = rng.uniform(0.5, 2.0, n)
    offsets = rng.normal(0, 0.1, n)
    path = str(tmp_path / f"{name}.avro")
    write_training_examples(path, rows, labels, offsets=offsets,
                            weights=weights, block_size=block_size)
    imap = IndexMap({f"f{c}": c for c in range(vocab)}, add_intercept=True)
    return path, imap


@pytest.fixture
def rng():
    return np.random.default_rng(7)


def _ram_chunks(path, imap, chunk_rows, pad_nnz):
    feats, labels, offsets, weights, _, _ = read_training_examples(
        path, {"global": imap})
    hs = feats["global"]
    chunks, dim = make_host_chunks(
        HostSparse(hs.indices, hs.values, hs.dim), labels, offsets, weights,
        chunk_rows=chunk_rows, pad_nnz=pad_nnz)
    return chunks, dim


@pytest.mark.parametrize("native", [True, False])
def test_source_matches_in_ram_fit(tmp_path, rng, native, monkeypatch):
    if not native:
        monkeypatch.setenv("PHOTON_ML_TPU_NO_NATIVE", "1")
    path, imap = _write_dataset(tmp_path, rng)
    src = AvroChunkSource(path, imap, chunk_rows=64)
    chunks, dim = _ram_chunks(path, imap, 64, src.pad_nnz)
    assert dim == src.dim
    assert len(src) == len(chunks)

    obj = make_objective("logistic")
    cfg = OptimizerConfig(max_iters=8, tolerance=0.0)
    r_src = fit_streaming(obj, src, src.dim, l2=0.5, config=cfg)
    r_ram = fit_streaming(obj, chunks, dim, l2=0.5, config=cfg)
    np.testing.assert_allclose(np.asarray(r_src.w), np.asarray(r_ram.w),
                               rtol=1e-5, atol=1e-6)
    # the margin-path fit iterates the source many times per iteration
    assert src.passes >= 2


def test_native_and_python_chunks_identical(tmp_path, rng, monkeypatch):
    path, imap = _write_dataset(tmp_path, rng, n=150)
    src_n = AvroChunkSource(path, imap, chunk_rows=64)
    monkeypatch.setenv("PHOTON_ML_TPU_NO_NATIVE", "1")
    src_p = AvroChunkSource(path, imap, chunk_rows=64)
    assert src_n._use_native and not src_p._use_native
    assert src_n.pad_nnz == src_p.pad_nnz
    for a, b in zip(src_n, src_p):
        np.testing.assert_array_equal(a.indices, b.indices)
        np.testing.assert_allclose(a.values, b.values, rtol=1e-6)
        np.testing.assert_allclose(a.labels, b.labels)
        np.testing.assert_allclose(a.offsets, b.offsets, atol=1e-7)
        np.testing.assert_allclose(a.weights, b.weights, rtol=1e-6)


def test_reiteration_is_deterministic(tmp_path, rng):
    path, imap = _write_dataset(tmp_path, rng, n=100)
    src = AvroChunkSource(path, imap, chunk_rows=32)
    first = [(c.indices.copy(), c.labels.copy()) for c in src]
    second = [(c.indices.copy(), c.labels.copy()) for c in src]
    assert len(first) == len(second) == len(src)
    for (ia, la), (ib, lb) in zip(first, second):
        np.testing.assert_array_equal(ia, ib)
        np.testing.assert_array_equal(la, lb)
    assert src.passes == 2


def test_producer_is_bounded_by_prefetch(tmp_path, rng):
    """A paused consumer must not let the producer decode ahead unbounded —
    that is the entire out-of-core contract."""
    path, imap = _write_dataset(tmp_path, rng, n=400)
    src = AvroChunkSource(path, imap, chunk_rows=16, prefetch=2)
    assert len(src) > 10
    it = iter(src)
    next(it)
    time.sleep(0.5)  # give the producer every chance to run ahead
    # 1 consumed + queue capacity (2) + 1 in-flight put
    assert src.chunks_produced <= 4
    it.close()
    # producer thread must wind down after consumer abandons the pass
    deadline = time.time() + 5
    while time.time() < deadline and any(
            t.name == "avro-chunk-producer" and t.is_alive()
            for t in threading.enumerate()):
        time.sleep(0.05)
    assert not any(t.name == "avro-chunk-producer" and t.is_alive()
                   for t in threading.enumerate())


def test_pad_nnz_overflow_raises(tmp_path, rng):
    path, imap = _write_dataset(tmp_path, rng, n=60)
    src = AvroChunkSource(path, imap, chunk_rows=32, pad_nnz=2)
    with pytest.raises(ValueError, match="pad_nnz"):
        list(src)


def test_scan_blocks_counts_rows_without_decoding(tmp_path, rng):
    path, imap = _write_dataset(tmp_path, rng, n=123)
    blocks, schema = scan_blocks(path)
    assert sum(b.count for b in blocks) == 123
    assert schema["type"] == "record"


def test_multiple_files(tmp_path, rng):
    p1, imap = _write_dataset(tmp_path, rng, n=70, name="a")
    p2, _ = _write_dataset(tmp_path, rng, n=50, name="b")
    src = AvroChunkSource([p1, p2], imap, chunk_rows=48)
    assert src.rows == 120
    chunks = list(src)
    assert len(chunks) == len(src) == 3
    # padding rows of the final chunk are weight-0
    assert np.all(chunks[-1].weights[120 - 2 * 48:] == 0)


def test_implicit_ones_contract(tmp_path, rng):
    # uniform-arity all-ones rows, chunk_rows dividing n: value-free layout
    n, vocab, k = 96, 30, 3
    rows = []
    for _ in range(n):
        cols = rng.choice(vocab, size=k, replace=False)
        rows.append([(f"f{c}", "", 1.0) for c in cols])
    labels = rng.integers(0, 2, n).astype(float)
    path = str(tmp_path / "ones.avro")
    write_training_examples(path, rows, labels)
    imap = IndexMap({f"f{c}": c for c in range(vocab)}, add_intercept=True)
    src = AvroChunkSource(path, imap, chunk_rows=48, implicit_ones=True)
    chunks = list(src)
    assert all(c.values is None for c in chunks)
    # non-uniform arity refuses the layout
    path2, imap2 = _write_dataset(tmp_path, rng, n=64, name="varied")
    src2 = AvroChunkSource(path2, imap2, chunk_rows=32, implicit_ones=True)
    with pytest.raises(ValueError, match="implicit_ones"):
        list(src2)


def test_unlabeled_raises_when_required(tmp_path, rng):
    rows = [[("f0", "", 1.0)], [("f1", "", 2.0)]]
    path = str(tmp_path / "nolabel.avro")
    write_training_examples(path, rows, labels=None)
    imap = IndexMap({"f0": 0, "f1": 1}, add_intercept=True)
    src = AvroChunkSource(path, imap, chunk_rows=2, pad_nnz=2)
    with pytest.raises(ValueError, match="label"):
        list(src)


def test_process_part_partitions_blocks(tmp_path, rng):
    """process_part=(i, n) gives disjoint, exhaustive, order-preserving
    block shares — the multi-controller input split; the cross-process
    partial reduction is row-partition agnostic, so block granularity is
    all that is required."""
    path, imap = _write_dataset(tmp_path, rng, n=210, block_size=16)
    full = AvroChunkSource(path, imap, chunk_rows=32)

    def rows_of(src):
        out = []
        for c in src:
            live = c.weights > 0
            out.append(np.column_stack([c.labels[live], c.offsets[live]]))
        return np.concatenate(out)

    all_rows = rows_of(full)
    parts = []
    for i in range(3):
        src_i = AvroChunkSource(path, imap, chunk_rows=32,
                                pad_nnz=full.pad_nnz, process_part=(i, 3))
        assert src_i.rows > 0
        parts.append(rows_of(src_i))
    got = np.concatenate(parts)
    assert got.shape == all_rows.shape
    # contiguous parts in order: concatenation IS the full dataset
    np.testing.assert_allclose(got, all_rows)
    with pytest.raises(ValueError, match="out of range"):
        AvroChunkSource(path, imap, chunk_rows=32, pad_nnz=full.pad_nnz,
                        process_part=(3, 3))


def test_game_cd_fixed_out_of_core_matches_in_ram(tmp_path, rng):
    """A GAME coordinate descent whose fixed effect streams from DISK
    (GameDataset.feature_sources) must reproduce the in-RAM run — fixed
    coefficients, random-effect coefficients, and history losses."""
    from photon_ml_tpu.game.descent import (
        CoordinateConfig,
        CoordinateDescent,
        GameDataset,
    )

    n, vocab = 240, 30
    path, imap = _write_dataset(tmp_path, rng, n=n, vocab=vocab,
                                block_size=64)
    feats, labels, offsets, weights, _, _ = read_training_examples(
        path, {"global": imap})
    users = rng.integers(0, 12, n).astype(str)
    hs = feats["global"]

    configs = [
        CoordinateConfig("fixed", "fixed", feature_shard="global",
                         streaming=True, chunk_rows=64, max_iters=12,
                         reg_type="l2", reg_weight=0.5),
        # the RE keeps resident features for ITS shard; here the single
        # shard doubles for both, under a second name
        CoordinateConfig("per-user", "random", feature_shard="re",
                         entity_column="userId", max_iters=12,
                         reg_type="l2", reg_weight=1.0),
    ]
    ds_ram = GameDataset({"global": hs, "re": hs}, labels, weights,
                         offsets, {"userId": users})
    model_ram, hist_ram = CoordinateDescent(
        configs, n_iterations=2).run(ds_ram)

    src = AvroChunkSource(path, imap, chunk_rows=64)
    ds_ooc = GameDataset({"re": hs}, labels, weights, offsets,
                         {"userId": users},
                         feature_sources={"global": src})
    model_ooc, hist_ooc = CoordinateDescent(
        configs, n_iterations=2).run(ds_ooc)

    w_ram = np.asarray(model_ram.coordinates["fixed"]
                       .model.coefficients.means)
    w_ooc = np.asarray(model_ooc.coordinates["fixed"]
                       .model.coefficients.means)
    np.testing.assert_allclose(w_ooc, w_ram, rtol=2e-4, atol=1e-6)
    re_ram = model_ram.coordinates["per-user"].buckets[0].coefficients
    re_ooc = model_ooc.coordinates["per-user"].buckets[0].coefficients
    np.testing.assert_allclose(np.asarray(re_ooc), np.asarray(re_ram),
                               rtol=2e-4, atol=1e-6)
    for a, b in zip(hist_ram, hist_ooc):
        if "loss" in a:
            np.testing.assert_allclose(b["loss"], a["loss"], rtol=2e-4)


def test_streamed_summary_matches_in_ram(tmp_path, rng):
    """summarize_features_streamed over a disk-backed source (padded final
    chunk included) == summarize_features over the resident data."""
    from photon_ml_tpu.ops.statistics import (
        summarize_features,
        summarize_features_streamed,
    )
    from photon_ml_tpu.types import LabeledBatch

    path, imap = _write_dataset(tmp_path, rng, n=190)
    feats, labels, *_ = read_training_examples(path, {"global": imap})
    hs = feats["global"]
    ref = summarize_features(
        LabeledBatch(hs, labels, np.zeros_like(labels),
                     np.ones_like(labels)))
    # f64 source: exact parity (an f32 source quantizes INPUTS to 1e-7
    # relative; accumulation is f64 either way)
    src = AvroChunkSource(path, imap, chunk_rows=64,  # 190 % 64: pad tail
                          dtype=np.float64)
    got = summarize_features_streamed(src, src.dim, src.rows)
    assert got.count == ref.count == 190
    for field in ("mean", "variance", "std", "min", "max", "num_nonzeros"):
        np.testing.assert_allclose(getattr(got, field), getattr(ref, field),
                                   rtol=1e-12, atol=1e-12, err_msg=field)


def test_streamed_summary_implicit_ones(rng):
    from photon_ml_tpu.ops.statistics import (
        summarize_features,
        summarize_features_streamed,
    )
    from photon_ml_tpu.types import LabeledBatch

    n, d, k = 100, 20, 4
    idx = np.stack([rng.choice(d, size=k, replace=False)
                    for _ in range(n)]).astype(np.int32)
    hs = HostSparse(idx, None, d)
    labels = np.zeros(n)
    ref = summarize_features(LabeledBatch(hs, labels, labels, labels + 1))
    chunks, _ = make_host_chunks(hs, labels, chunk_rows=32)  # padded tail
    got = summarize_features_streamed(chunks, d, n)
    for field in ("mean", "variance", "num_nonzeros", "min", "max"):
        np.testing.assert_allclose(getattr(got, field), getattr(ref, field),
                                   err_msg=field)


def test_corrupt_file_fails_cleanly_not_hangs(tmp_path, rng):
    """Truncation or flipped sync markers surface as a clean ValueError
    from the consuming iterator (propagated out of the producer thread),
    never a hang or a silent short read."""
    path, imap = _write_dataset(tmp_path, rng, n=120, block_size=16)
    raw = open(path, "rb").read()

    # truncated mid-block: scan (header walk) must reject it
    trunc = tmp_path / "trunc.avro"
    trunc.write_bytes(raw[: len(raw) - 37])
    with pytest.raises(ValueError):
        AvroChunkSource(str(trunc), imap, chunk_rows=32, pad_nnz=8)

    # valid scan, corrupted payload byte: decode must raise, and the
    # error must reach the CONSUMER of the bounded queue
    src_ok = AvroChunkSource(path, imap, chunk_rows=32)
    blocks, _ = scan_blocks(path)
    corrupt = bytearray(raw)
    mid = blocks[1].payload_offset + blocks[1].payload_size // 2
    corrupt[mid] ^= 0xFF
    bad = tmp_path / "bad.avro"
    bad.write_bytes(bytes(corrupt))
    src = AvroChunkSource(str(bad), imap, chunk_rows=32,
                          pad_nnz=src_ok.pad_nnz)
    with pytest.raises(Exception):
        list(src)


def test_part_reduced_summarization_matches_global(tmp_path, rng):
    """Per-part streamed summaries, all-reduced via the moment hook +
    finalized against the GLOBAL row count, must equal the single-source
    summary — the multi-controller normalization contract (each process
    streams only its block part; without the reduce each would build a
    divergent normalization context)."""
    from photon_ml_tpu.ops.statistics import summarize_features_streamed

    path, imap = _write_dataset(tmp_path, rng, n=210, block_size=16)
    full = AvroChunkSource(path, imap, chunk_rows=32)
    want = summarize_features_streamed(full, full.dim, full.rows)

    n_parts = 3
    parts = [AvroChunkSource(path, imap, chunk_rows=32, pad_nnz=full.pad_nnz,
                             process_part=(i, n_parts))
             for i in range(n_parts)]
    # emulate allreduce_summary_moments without a multi-process runtime:
    # capture each part's raw moments, then hand every part the reduced set
    raw = []

    def capture(*m):
        raw.append(m)
        return m

    for p in parts:
        summarize_features_streamed(p, p.dim, p.rows, part_reduce=capture)
    reduced = (sum(m[0] for m in raw), sum(m[1] for m in raw),
               sum(m[2] for m in raw),
               np.maximum.reduce([m[3] for m in raw]),
               np.minimum.reduce([m[4] for m in raw]))
    for p in parts:
        got = summarize_features_streamed(
            p, p.dim, p.rows, total_rows=full.rows,
            part_reduce=lambda *m: reduced)
        for f in ("mean", "variance", "std", "min", "max", "num_nonzeros"):
            np.testing.assert_allclose(getattr(got, f), getattr(want, f),
                                       err_msg=f, atol=1e-12)
        assert got.count == full.rows


def test_empty_process_part_raises_actionable_error(tmp_path, rng):
    """Fewer container blocks than processes: the starved process must get
    the 'rewrite with a smaller block_size' diagnosis, not a misleading
    'no records' error."""
    path, imap = _write_dataset(tmp_path, rng, n=50, block_size=4096)
    full = AvroChunkSource(path, imap, chunk_rows=32)  # one block
    with pytest.raises(ValueError, match="smaller block_size"):
        AvroChunkSource(path, imap, chunk_rows=32, pad_nnz=full.pad_nnz,
                        process_part=(1, 2))


def test_native_python_decode_parity_fuzz(tmp_path):
    """Property sweep of the python-vs-native decoder parity: randomized
    record shapes (empty rows, duplicate features, extreme values, odd
    block sizes, varying chunk_rows) must decode identically through both
    paths. Complements the single-dataset parity test above."""
    import os

    from hypothesis import given, settings, strategies as st

    @st.composite
    def dataset(draw):
        n = draw(st.integers(1, 80))
        vocab = draw(st.integers(1, 25))
        block = draw(st.sampled_from([1, 3, 16, 4096]))
        chunk = draw(st.sampled_from([1, 7, 32]))
        rows, labels, weights, offsets = [], [], [], []
        for _ in range(n):
            k = draw(st.integers(0, 5))
            feats = [(f"f{draw(st.integers(0, vocab - 1))}", "",
                      draw(st.floats(-1e6, 1e6, width=32)))
                     for _ in range(k)]
            rows.append(feats)
            labels.append(float(draw(st.integers(0, 1))))
            weights.append(draw(st.floats(0.125, 10.0, width=32)))
            offsets.append(draw(st.floats(-10.0, 10.0, width=32)))
        return n, vocab, block, chunk, rows, labels, weights, offsets

    @settings(max_examples=12, deadline=None)
    @given(dataset())
    def check(ds):
        n, vocab, block, chunk, rows, labels, weights, offsets = ds
        sub = tmp_path / f"fz{abs(hash(str(ds))) % (1 << 30)}"
        sub.mkdir(exist_ok=True)
        path = str(sub / "d.avro")
        write_training_examples(path, rows, np.asarray(labels),
                                offsets=np.asarray(offsets),
                                weights=np.asarray(weights),
                                block_size=block)
        imap = IndexMap({f"f{c}": c for c in range(vocab)},
                        add_intercept=True)
        src_n = AvroChunkSource(path, imap, chunk_rows=chunk)
        os.environ["PHOTON_ML_TPU_NO_NATIVE"] = "1"
        try:
            src_p = AvroChunkSource(path, imap, chunk_rows=chunk,
                                    pad_nnz=src_n.pad_nnz)
        finally:
            del os.environ["PHOTON_ML_TPU_NO_NATIVE"]
        assert not src_p._use_native
        chunks_n, chunks_p = list(src_n), list(src_p)
        assert len(chunks_n) == len(chunks_p)
        for a, b in zip(chunks_n, chunks_p):
            np.testing.assert_array_equal(a.indices, b.indices)
            np.testing.assert_allclose(a.values, b.values, rtol=1e-6)
            np.testing.assert_allclose(a.labels, b.labels)
            np.testing.assert_allclose(a.offsets, b.offsets, atol=1e-6)
            np.testing.assert_allclose(a.weights, b.weights, rtol=1e-6)

    check()


def test_producer_death_without_sentinel_fails_stop(tmp_path, rng,
                                                    monkeypatch):
    """A producer thread that dies without delivering its end-of-pass
    sentinel must surface as a RuntimeError at the consumer's bounded
    poll — never an unbounded q.get() hang (PT404's runtime contract)."""
    path, imap = _write_dataset(tmp_path, rng, n=100, name="deadprod")
    monkeypatch.setattr(AvroChunkSource, "_consumer_poll_s", 0.05)
    # the producer "succeeds" while delivering nothing — the observable
    # shape of a crash hard enough to skip the BaseException relay
    monkeypatch.setattr(AvroChunkSource, "_put_or_stop",
                        staticmethod(lambda q, stop, item: True))
    src = AvroChunkSource(path, imap, chunk_rows=64)
    with pytest.raises(RuntimeError, match="without delivering"):
        list(src)
