"""Decode-once chunk cache (io/chunk_cache.py): warm passes must be
bit-faithful to the decoded source, invalidation must be airtight (touched
files, changed chunk geometry, changed index map), interrupted writes must
never publish a partial cache, and a blown disk budget must fall through
to plain re-decode — the cache is a transparent accelerator, never a new
failure mode."""

import json
import logging
import os
import time

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp

from photon_ml_tpu.io.chunk_cache import ChunkCacheSource
from photon_ml_tpu.io.data_reader import write_training_examples
from photon_ml_tpu.io.index_map import IndexMap
from photon_ml_tpu.io.stream_source import AvroChunkSource, ScalarOverlaySource
from photon_ml_tpu.ops.objective import make_objective
from photon_ml_tpu.optimize import OptimizerConfig
from photon_ml_tpu.parallel import fault_injection
from photon_ml_tpu.parallel.streaming import fit_streaming


def _write_dataset(tmp_path, rng, n=240, vocab=40, max_k=6, name="train",
                   block_size=2048):
    rows = []
    for _ in range(n):
        k = int(rng.integers(1, max_k + 1))
        cols = rng.choice(vocab, size=k, replace=False)
        rows.append([(f"f{c}", "", float(rng.normal())) for c in cols])
    labels = rng.integers(0, 2, n).astype(float)
    weights = rng.uniform(0.5, 2.0, n)
    offsets = rng.normal(0, 0.1, n)
    path = str(tmp_path / f"{name}.avro")
    write_training_examples(path, rows, labels, offsets=offsets,
                            weights=weights, block_size=block_size)
    imap = IndexMap({f"f{c}": c for c in range(vocab)}, add_intercept=True)
    return path, imap


@pytest.fixture
def rng():
    return np.random.default_rng(11)


def _chunk_fields_equal(a, b):
    for f in ("indices", "values", "labels", "offsets", "weights"):
        fa, fb = getattr(a, f), getattr(b, f)
        if fa is None or fb is None:
            assert fa is None and fb is None
        else:
            np.testing.assert_array_equal(np.asarray(fa), np.asarray(fb))


def test_warm_chunks_bit_identical_to_source(tmp_path, rng):
    path, imap = _write_dataset(tmp_path, rng)
    src = AvroChunkSource(path, imap, chunk_rows=64)
    cache = ChunkCacheSource(src, str(tmp_path / "cache"))
    ref = list(src)
    cold = list(cache)
    warm = list(cache)
    warm2 = list(cache)
    assert cache.cold_passes == 1 and cache.warm_passes == 2
    # decode ran exactly twice: the reference pass + the single cold pass
    assert src.passes == 2
    assert len(ref) == len(cold) == len(warm) == len(cache)
    for a, b, c, d in zip(ref, cold, warm, warm2):
        _chunk_fields_equal(a, b)
        _chunk_fields_equal(a, c)
        _chunk_fields_equal(a, d)
    assert cache.bytes_written > 0
    # committed layout: META + one packed file per field, no staging left
    names = sorted(os.listdir(cache.cache_path))
    assert "META.json" in names
    assert not any(n.startswith(".tmp-")
                   for n in os.listdir(cache.cache_dir))


def test_cache_survives_reconstruction_and_fits_identically(tmp_path, rng):
    """A second job over the same inputs opens the committed cache warm
    (no cold pass at all), and a cached f64 fit matches the no-cache
    streamed fit to <= 1e-9 — the acceptance contract."""
    path, imap = _write_dataset(tmp_path, rng)
    cache_dir = str(tmp_path / "cache")
    src = AvroChunkSource(path, imap, chunk_rows=64)
    list(ChunkCacheSource(src, cache_dir))  # job 1: cold pass commits

    src2 = AvroChunkSource(path, imap, chunk_rows=64)
    cache2 = ChunkCacheSource(src2, cache_dir)
    obj = make_objective("logistic")
    cfg = OptimizerConfig(max_iters=8, tolerance=0.0)
    r_cached = fit_streaming(obj, cache2, cache2.dim, l2=0.5, config=cfg,
                             dtype=jnp.float64)
    assert cache2.cold_passes == 0 and cache2.warm_passes > 0
    assert src2.passes == 0  # decode-once: the warm job never decodes

    src3 = AvroChunkSource(path, imap, chunk_rows=64)
    r_raw = fit_streaming(obj, src3, src3.dim, l2=0.5, config=cfg,
                          dtype=jnp.float64)
    diff = np.max(np.abs(np.asarray(r_cached.w) - np.asarray(r_raw.w)))
    assert diff <= 1e-9, diff


@pytest.mark.parametrize("staleness", ["touch", "chunk_rows", "index_map"])
def test_stale_fingerprint_forces_redecode(tmp_path, rng, staleness):
    path, imap = _write_dataset(tmp_path, rng)
    cache_dir = str(tmp_path / "cache")
    src = AvroChunkSource(path, imap, chunk_rows=64)
    cache = ChunkCacheSource(src, cache_dir)
    list(cache)
    assert cache.cold_passes == 1

    chunk_rows = 64
    if staleness == "touch":
        st = os.stat(path)
        os.utime(path, ns=(st.st_atime_ns, st.st_mtime_ns + 1_000_000))
    elif staleness == "chunk_rows":
        chunk_rows = 32
    else:
        imap = IndexMap({f"f{c}": c + 1 if c else 0 for c in range(40)},
                        add_intercept=True)
    src2 = AvroChunkSource(path, imap, chunk_rows=chunk_rows,
                           pad_nnz=src.pad_nnz)
    cache2 = ChunkCacheSource(src2, cache_dir)
    chunks = list(cache2)
    # the stale cache was neither opened nor kept: this was a cold pass
    assert cache2.cold_passes == 1 and cache2.warm_passes == 0
    assert src2.passes == 1
    assert len(chunks) == len(src2)
    # ... and the old committed dir was swept (only the fresh one remains)
    committed = [d for d in os.listdir(cache_dir)
                 if d.startswith("chunks-")]
    assert committed == [os.path.basename(cache2.cache_path)]


@pytest.mark.parametrize("site,at", [("chunk_cache.spill", 2),
                                     ("chunk_cache.commit", 0)])
def test_interrupted_cache_write_leaves_no_partial_cache(tmp_path, rng,
                                                         site, at):
    """A crash mid-spill or right before the atomic rename must leave NO
    visible cache — the next pass re-decodes cold and commits cleanly."""
    path, imap = _write_dataset(tmp_path, rng)
    cache_dir = str(tmp_path / "cache")
    src = AvroChunkSource(path, imap, chunk_rows=64)
    cache = ChunkCacheSource(src, cache_dir)
    fault_injection.install([fault_injection.Fault(site=site, at=at)])
    try:
        with pytest.raises(fault_injection.InjectedFault):
            list(cache)
    finally:
        fault_injection.clear()
    assert not any(d.startswith("chunks-") for d in os.listdir(cache_dir))
    # staging from THIS live process is cleaned by the generator unwind
    assert not any(d.startswith(".tmp-") for d in os.listdir(cache_dir))

    ref = list(src)
    again = list(cache)
    assert cache.warm_passes == 0  # both passes above were interrupted/cold
    warm = list(cache)
    assert cache.warm_passes == 1
    for a, b, c in zip(ref, again, warm):
        _chunk_fields_equal(a, b)
        _chunk_fields_equal(a, c)


def test_disk_budget_overflow_falls_through_with_warning(tmp_path, rng,
                                                         caplog):
    path, imap = _write_dataset(tmp_path, rng)
    src = AvroChunkSource(path, imap, chunk_rows=64)
    cache = ChunkCacheSource(src, str(tmp_path / "cache"), max_bytes=128)
    ref = list(src)
    with caplog.at_level(logging.WARNING, logger="photon_ml_tpu"):
        got = list(cache)
    assert any("disk budget" in r.message for r in caplog.records)
    assert not cache.enabled
    for a, b in zip(ref, got):
        _chunk_fields_equal(a, b)
    # later passes re-decode (fall-through), never a partial cache
    list(cache)
    assert cache.fallthrough_passes == 1 and cache.warm_passes == 0
    assert not any(d.startswith("chunks-")
                   for d in os.listdir(str(tmp_path / "cache")))


def test_corrupt_committed_cache_is_removed_and_redecoded(tmp_path, rng):
    path, imap = _write_dataset(tmp_path, rng)
    cache_dir = str(tmp_path / "cache")
    src = AvroChunkSource(path, imap, chunk_rows=64)
    cache = ChunkCacheSource(src, cache_dir)
    ref = list(cache)
    # truncate one packed field file behind the cache's back
    victim = os.path.join(cache.cache_path, "labels.bin")
    with open(victim, "r+b") as f:
        f.truncate(os.path.getsize(victim) // 2)
    cache2 = ChunkCacheSource(AvroChunkSource(path, imap, chunk_rows=64),
                              cache_dir)
    got = list(cache2)
    assert cache2.cold_passes == 1  # corrupt cache refused, re-decoded
    for a, b in zip(ref, got):
        _chunk_fields_equal(a, b)
    list(cache2)
    assert cache2.warm_passes == 1  # and the fresh commit serves warm


def test_scalar_overlay_on_warm_cache_updates_offsets(tmp_path, rng):
    """The CD residual-offset path: per-pass scalars must overlay cached
    (memmap-backed) chunks without touching the decoder."""
    path, imap = _write_dataset(tmp_path, rng, n=200)
    src = AvroChunkSource(path, imap, chunk_rows=64)
    cache = ChunkCacheSource(src, str(tmp_path / "cache"))
    list(cache)  # commit
    n = src.rows
    for k in range(3):  # a fresh overlay per "CD step"
        offs = np.arange(n, dtype=float) + 100 * k
        ov = ScalarOverlaySource(cache, offsets=offs)
        got = np.concatenate([c.offsets[c.weights > 0] for c in ov])
        np.testing.assert_allclose(got, offs)
    assert src.passes == 1  # every overlay pass was a cache hit


def test_game_cd_out_of_core_cached_matches_uncached(tmp_path, rng):
    """End to end: a GAME CD whose fixed effect streams through the chunk
    cache must reproduce the uncached out-of-core run exactly (the cache
    serves the same bytes, so even f32 trajectories are bit-equal)."""
    from photon_ml_tpu.game.descent import (
        CoordinateConfig,
        CoordinateDescent,
        GameDataset,
    )
    from photon_ml_tpu.io.data_reader import read_training_examples

    n = 192
    path, imap = _write_dataset(tmp_path, rng, n=n, block_size=64)
    feats, labels, offsets, weights, _, _ = read_training_examples(
        path, {"global": imap})
    users = rng.integers(0, 8, n).astype(str)
    hs = feats["global"]
    configs = [
        CoordinateConfig("fixed", "fixed", feature_shard="global",
                         streaming=True, chunk_rows=64, max_iters=8,
                         reg_type="l2", reg_weight=0.5, prefetch_depth=3),
        CoordinateConfig("per-user", "random", feature_shard="re",
                         entity_column="userId", max_iters=8,
                         reg_type="l2", reg_weight=1.0),
    ]

    def run(source):
        ds = GameDataset({"re": hs}, labels, weights, offsets,
                         {"userId": users},
                         feature_sources={"global": source})
        return CoordinateDescent(configs, n_iterations=2).run(ds)

    src_a = AvroChunkSource(path, imap, chunk_rows=64)
    model_raw, hist_raw = run(src_a)
    src_b = AvroChunkSource(path, imap, chunk_rows=64)
    cache = ChunkCacheSource(src_b, str(tmp_path / "cache"))
    model_cached, hist_cached = run(cache)

    assert cache.cold_passes == 1 and cache.warm_passes > 0
    # every pass after the first was decode-free
    assert src_b.passes == 1 < src_a.passes
    w_raw = np.asarray(model_raw.coordinates["fixed"]
                       .model.coefficients.means)
    w_cached = np.asarray(model_cached.coordinates["fixed"]
                          .model.coefficients.means)
    np.testing.assert_array_equal(w_cached, w_raw)
    for a, b in zip(hist_raw, hist_cached):
        if "loss" in a:
            assert b["loss"] == a["loss"]
    # the streamed fixed effect recorded its stall breakdown
    streamed = [h for h in hist_cached if h["coordinate"] == "fixed"]
    assert all("stream" in h for h in streamed)


def test_glm_driver_chunk_cache_flags(tmp_path, rng):
    """Driver leg: --out-of-core --chunk-cache-dir --prefetch-depth runs,
    commits a cache, and a rerun serves it warm; the cache flags refuse
    in-RAM runs."""
    from photon_ml_tpu.cli import glm_driver

    path, imap_ = _write_dataset(tmp_path, rng, n=150)
    imap_path = str(tmp_path / "imap.json")
    imap_.save(imap_path)
    cache_dir = str(tmp_path / "cache")
    out1, out2 = str(tmp_path / "out1"), str(tmp_path / "out2")
    argv = ["--train-data", path, "--input-format", "avro",
            "--out-of-core", "--index-map", imap_path,
            "--chunk-rows", "64", "--max-iters", "4",
            "--chunk-cache-dir", cache_dir, "--chunk-cache-gb", "1",
            "--prefetch-depth", "3", "--reg-weights", "1.0"]
    assert glm_driver.main(argv + ["--output-dir", out1]) == 0
    committed = [d for d in os.listdir(cache_dir)
                 if d.startswith("chunks-")]
    assert len(committed) == 1
    meta = json.load(open(os.path.join(cache_dir, committed[0],
                                       "META.json")))
    assert meta["n_chunks"] == 3  # 150 rows / 64
    # rerun: same fingerprint, cache reused (mtime preserved, same map)
    assert glm_driver.main(argv + ["--output-dir", out2]) == 0
    assert [d for d in os.listdir(cache_dir)
            if d.startswith("chunks-")] == committed
    # per-lambda log records carry the stream stall breakdown
    with open(os.path.join(out1, "photon.log.jsonl")) as f:
        recs = [json.loads(line) for line in f]
    trained = [r for r in recs if r.get("event") == "lambda_trained"]
    assert trained and all("stream" in r for r in trained)

    with pytest.raises(SystemExit, match="chunk-cache-dir requires"):
        glm_driver.main(["--train-data", path, "--output-dir", out1,
                         "--chunk-cache-dir", cache_dir])


def test_fingerprint_requires_introspectable_source(tmp_path, rng):
    """A source the cache cannot fingerprint is refused loudly unless the
    caller provides the invalidation key; with one, plain chunk lists
    cache fine (the test-harness path)."""
    from photon_ml_tpu.game.data import HostSparse
    from photon_ml_tpu.parallel.streaming import make_host_chunks

    idx = rng.integers(0, 16, (96, 3)).astype(np.int32)
    vals = rng.normal(size=(96, 3))
    chunks, _ = make_host_chunks(HostSparse(idx, vals, 16),
                                 rng.integers(0, 2, 96).astype(float),
                                 chunk_rows=32)
    with pytest.raises(ValueError, match="fingerprint"):
        ChunkCacheSource(chunks, str(tmp_path / "cache"))
    cache = ChunkCacheSource(chunks, str(tmp_path / "cache"),
                             fingerprint={"test": "key"})
    cold, warm = list(cache), list(cache)
    assert cache.cold_passes == 1 and cache.warm_passes == 1
    for a, b in zip(cold, warm):
        _chunk_fields_equal(a, b)


def test_producer_join_timeout_is_detected(tmp_path, rng, monkeypatch,
                                           caplog):
    """Satellite: a wedged producer thread surviving the end-of-pass join
    must be counted and warned about, never leaked invisibly."""
    path, imap = _write_dataset(tmp_path, rng, n=80)
    src = AvroChunkSource(path, imap, chunk_rows=32)
    first_chunk = next(iter(src))

    def wedged(q, stop, fault_proc=None):
        q.put(first_chunk)
        time.sleep(1.5)  # a decoder stuck outside the stop event

    monkeypatch.setattr(src, "_produce", wedged)
    monkeypatch.setattr(AvroChunkSource, "_join_timeout", 0.05)
    it = iter(src)
    next(it)
    with caplog.at_level(logging.WARNING, logger="photon_ml_tpu"):
        it.close()
    assert src.producer_join_timeouts == 1
    assert any("avro-chunk-producer" in r.getMessage()
               for r in caplog.records)
