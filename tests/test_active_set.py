"""Active-set coordinate descent: converged-entity freezing with
offset-drift re-activation, incremental delta scoring, the running
residual total, sweep-level early exit, and the inexact-solve tolerance
schedule (game/descent.py + game/random_effect.py + optimize/common.py)."""

import logging

import jax.numpy as jnp
import numpy as np
import pytest

from photon_ml_tpu.game import descent as descent_mod
from photon_ml_tpu.game.data import build_random_effect_data, build_score_view
from photon_ml_tpu.game.descent import (
    CoordinateConfig,
    CoordinateDescent,
    make_game_dataset,
)
from photon_ml_tpu.game.random_effect import (
    re_solver_compile_count,
    score_random_effect,
    train_random_effect,
)
from photon_ml_tpu.optimize import (
    OptimizerConfig,
    ToleranceSchedule,
    parse_tolerance_schedule,
)


def _synth_game(seed=0, n_users=60, d_g=6, d_u=4):
    rng = np.random.default_rng(seed)
    w_fixed = rng.normal(size=d_g)
    U = rng.normal(size=(n_users, d_u))
    Xg, Xu, y, uid = [], [], [], []
    for u in range(n_users):
        m = int(rng.integers(10, 24))
        xg, xu = rng.normal(size=(m, d_g)), rng.normal(size=(m, d_u))
        marg = xg @ w_fixed + xu @ U[u]
        y.append((rng.random(m) < 1 / (1 + np.exp(-marg))).astype(float))
        Xg.append(xg)
        Xu.append(xu)
        uid.append(np.full(m, u))
    Xg, Xu, y, uid = map(np.concatenate, (Xg, Xu, y, uid))
    return make_game_dataset({"g": Xg, "u": Xu}, y,
                             entity_ids={"userId": uid})


@pytest.fixture(scope="module")
def game_ds():
    return _synth_game()


N_USERS = 60


def _configs(active_set, fixed_kw=None, **re_kw):
    re_kw.setdefault("tolerance", 1e-11)
    re_kw.setdefault("optimizer", "newton")
    re_kw.setdefault("refresh_every", 5)
    re_kw.setdefault("active_tol", 1e-10)
    return [
        CoordinateConfig("fixed", feature_shard="g", reg_type="l2",
                         reg_weight=2.0, tolerance=1e-12,
                         **(fixed_kw or {})),
        CoordinateConfig("per-user", coordinate_type="random",
                         feature_shard="u", entity_column="userId",
                         reg_type="l2", reg_weight=2.0,
                         active_set=active_set, **re_kw),
    ]


def _coeff_diff(m_a, m_b):
    diffs = [np.max(np.abs(
        np.asarray(m_a.coordinates["fixed"].model.coefficients.means)
        - np.asarray(m_b.coordinates["fixed"].model.coefficients.means)))]
    for ba, bb in zip(m_a.coordinates["per-user"].buckets,
                      m_b.coordinates["per-user"].buckets):
        if np.asarray(ba.coefficients).size:
            diffs.append(np.max(np.abs(np.asarray(ba.coefficients)
                                       - np.asarray(bb.coefficients))))
    return max(diffs)


def _solved(history):
    return [r["entities_solved"] for r in history
            if r["coordinate"] == "per-user" and "entities_solved" in r]


def test_active_set_matches_full_sweeps_f64(game_ds):
    """The tentpole gate: active-set CD (freezing + incremental rescoring
    + drift re-activation) agrees with the full-sweep fit to <= 1e-9 in
    f64 over the same sweep budget, while actually shrinking the per-sweep
    frontier."""
    n_it = 14
    m_full, h_full = CoordinateDescent(
        _configs(False), task="logistic", n_iterations=n_it,
        dtype=jnp.float64).run(game_ds)
    m_act, h_act = CoordinateDescent(
        _configs(True), task="logistic", n_iterations=n_it,
        dtype=jnp.float64).run(game_ds)
    assert _coeff_diff(m_full, m_act) <= 1e-9
    solved = _solved(h_act)
    assert solved[0] == N_USERS  # first sweep is always a full solve
    assert min(solved) < N_USERS  # the frontier shrank at some sweep
    # the full-sweep run never skips anything
    assert all(s == N_USERS for s in _solved(h_full))


def test_frozen_entities_reactivated_after_fixed_effect_moves(game_ds):
    """Freezing is not a one-way door: with a loose drift tolerance the
    random effect freezes while the (iteration-capped, slowly-moving)
    fixed effect is still drifting; each refresh sweep re-solves the
    frozen entities against the moved offsets and actually changes their
    coefficients."""
    snaps = {}
    m, h = CoordinateDescent(
        _configs(True, fixed_kw={"max_iters": 2}, active_tol=3e-2,
                 refresh_every=3),
        task="logistic", n_iterations=10, dtype=jnp.float64,
    ).run(game_ds, checkpoint_callback=lambda it, model: snaps.update(
        {it: [np.array(b.coefficients) for b in
              model.coordinates["per-user"].buckets]}))
    re_recs = [r for r in h if r["coordinate"] == "per-user"]
    frozen_sweeps = [r["iteration"] for r in re_recs
                     if r["entities_solved"] == 0]
    assert frozen_sweeps, "loose active_tol should fully freeze some sweep"
    s = frozen_sweeps[0]
    refreshes = [r["iteration"] for r in re_recs
                 if r["iteration"] > s and r.get("refresh")]
    assert refreshes, "a refresh sweep must follow the frozen sweep"
    ref = refreshes[0]
    # frozen sweep: coefficients carried bit-identically
    for a, b in zip(snaps[s - 1], snaps[s]):
        np.testing.assert_array_equal(a, b)
    # the refresh re-solved against the fixed effect's moved offsets and
    # the frozen entities' coefficients actually moved (re-activation)
    assert max(np.max(np.abs(a - b)) for a, b in
               zip(snaps[ref - 1], snaps[ref])) > 0


def test_early_exit_deterministic_and_recorded(game_ds):
    """cd_tolerance early exit fires before the sweep budget, records the
    stop reason, and two identical runs are bit-identical."""
    def run():
        return CoordinateDescent(
            _configs(True), task="logistic", n_iterations=20,
            dtype=jnp.float64, cd_tolerance=1e-10).run(game_ds)

    m1, h1 = run()
    m2, h2 = run()
    assert h1[-1]["stop_reason"] == "cd_tolerance"
    assert h1[-1]["iteration"] + 1 < 20
    assert len(h1) == len(h2)
    assert [r["score_delta"] for r in h1] == [r["score_delta"] for r in h2]
    assert _coeff_diff(m1, m2) == 0.0
    # a disabled tolerance runs the full budget and says so
    _, h3 = CoordinateDescent(
        _configs(True), task="logistic", n_iterations=3,
        dtype=jnp.float64).run(game_ds)
    assert h3[-1]["stop_reason"] == "max_iterations"


def test_compile_counter_flat_across_shrinking_active_sets(game_ds):
    """Once the power-of-two sub-bucket ladder is warm, shrinking active
    sets must reuse it: 0 new RE-solver compiles at ANY sweep of a
    repeat run — the per-sweep anchors run through the shared
    CompileSanitizer instead of a hand-collected count list."""
    from photon_ml_tpu.analysis.sanitizers import CompileSanitizer

    def run(callback=None):
        return CoordinateDescent(
            _configs(True), task="logistic", n_iterations=14,
            dtype=jnp.float64).run(game_ds, checkpoint_callback=callback)

    run()  # warm the ladder
    with CompileSanitizer(re_solver_compile_count,
                          label="active-set repeat run") as san:
        _, h = run(callback=lambda it, m: san.check(f"sweep {it}"))
    assert min(_solved(h)) < N_USERS  # the active set did shrink


def test_running_total_parity(game_ds, monkeypatch):
    """Satellite: the O(1)-per-update running residual total must match
    the explicit per-coordinate re-sum it replaced (<= 1e-9 on the final
    f64 coefficients)."""
    m_run, _ = CoordinateDescent(
        _configs(True), task="logistic", n_iterations=6,
        dtype=jnp.float64).run(game_ds)

    def exact_excluding(self, name, scores):
        return self.base + sum(v for k, v in scores.items() if k != name)

    monkeypatch.setattr(descent_mod._ResidualTotal, "excluding",
                        exact_excluding)
    m_sum, _ = CoordinateDescent(
        _configs(True), task="logistic", n_iterations=6,
        dtype=jnp.float64).run(game_ds)
    assert _coeff_diff(m_run, m_sum) <= 1e-9


def test_residual_total_tracks_resum():
    rng = np.random.default_rng(3)
    base = jnp.asarray(rng.normal(size=200))
    scores = {k: jnp.asarray(rng.normal(size=200)) for k in "abc"}
    rt = descent_mod._ResidualTotal(base)
    rt.resync(scores)
    for _ in range(20):
        k = rng.choice(list("abc"))
        new = jnp.asarray(rng.normal(size=200))
        np.testing.assert_allclose(
            np.asarray(rt.excluding(k, scores)),
            np.asarray(base + sum(v for n, v in scores.items() if n != k)),
            atol=1e-12)
        rt.replace(scores[k], new)
        scores[k] = new
        np.testing.assert_allclose(
            np.asarray(rt.total),
            np.asarray(base + sum(scores.values())), atol=1e-12)


def test_incremental_scoring_matches_full(rng):
    """score_random_effect's incremental mode (changed-entity gather +
    scatter-overwrite) must reproduce the full recompute."""
    n, d, E = 300, 6, 24
    X = rng.normal(size=(n, d)) * (rng.random((n, d)) < 0.7)
    ids = rng.integers(0, E, n)
    y = rng.integers(0, 2, n).astype(float)
    data = build_random_effect_data(X, y, np.ones(n), ids, num_buckets=3)
    view = build_score_view(data, X, ids)
    W0 = [rng.normal(size=(b.num_entities, b.local_dim))
          for b in data.buckets]
    s0 = score_random_effect(view, W0, n, jnp.float64)
    # perturb a subset of entities in every bucket
    W1, changed = [], []
    for W in W0:
        mask = rng.random(W.shape[0]) < 0.3
        Wn = W.copy()
        Wn[mask] += rng.normal(size=(int(mask.sum()), W.shape[1]))
        W1.append(Wn)
        changed.append(mask)
    full = score_random_effect(view, W1, n, jnp.float64)
    incr = score_random_effect(view, W1, n, jnp.float64, prev=s0,
                               changed=changed)
    np.testing.assert_allclose(np.asarray(incr), np.asarray(full),
                               atol=1e-12)
    # empty changed masks are a no-op returning prev
    same = score_random_effect(view, W1, n, jnp.float64, prev=full,
                               changed=[np.zeros(len(m), bool)
                                        for m in changed])
    np.testing.assert_array_equal(np.asarray(same), np.asarray(full))


def test_train_random_effect_active_carries_frozen(rng):
    """Frozen entities' coefficients/variances ride through untouched and
    report converged=True / iterations=0; solved entities match a full
    solve restricted to them."""
    n, d, E = 240, 5, 16
    X = rng.normal(size=(n, d)) * (rng.random((n, d)) < 0.8)
    ids = rng.integers(0, E, n)
    y = rng.integers(0, 2, n).astype(float)
    data = build_random_effect_data(X, y, np.ones(n), ids, num_buckets=2)
    offs = jnp.zeros((n,), jnp.float64)
    kw = dict(task="logistic", l2=1.0, dtype=jnp.float64,
              optimizer="newton", compute_variance="diagonal",
              config=OptimizerConfig(max_iters=50, tolerance=1e-10))
    full = train_random_effect(data, offs, **kw)
    w0 = [np.array(c) for c in full.coefficients]
    active = [np.zeros(b.num_entities, bool) for b in data.buckets]
    active[0][: max(1, data.buckets[0].num_entities // 2)] = True
    refit = train_random_effect(data, offs, w0=w0,
                                prev_variances=full.variances,
                                active=active, **kw)
    for b in range(len(data.buckets)):
        frozen = ~active[b]
        np.testing.assert_array_equal(
            np.asarray(refit.coefficients[b])[frozen],
            np.asarray(w0[b])[frozen])
        np.testing.assert_array_equal(
            np.asarray(refit.variances[b])[frozen],
            np.asarray(full.variances[b])[frozen])
        assert refit.converged[b][frozen].all()
        assert (refit.iterations[b][frozen] == 0).all()
    assert refit.entities_solved == int(sum(a.sum() for a in active))
    # active without w0 is a contract violation
    with pytest.raises(ValueError, match="active-set training needs w0"):
        train_random_effect(data, offs, active=active, **kw)
    # shape-mismatched mask is rejected
    bad = [np.zeros(3, bool) for _ in data.buckets]
    with pytest.raises(ValueError, match="active mask"):
        train_random_effect(data, offs, w0=w0, active=bad, **kw)


def test_history_timing_split_and_logging(game_ds, caplog):
    """Satellite: per-coordinate records carry the solve vs eval timing
    split (PR-4 stall accounting composes with it), and the verbose path
    goes through logging, not print."""
    with caplog.at_level(logging.INFO, logger="photon_ml_tpu.game.descent"):
        _, h = CoordinateDescent(
            _configs(True), task="logistic", n_iterations=2,
            dtype=jnp.float64, evaluators=["auc"],
            verbose=True).run(game_ds, validation=game_ds)
    for r in h:
        assert {"solve_seconds", "eval_seconds", "seconds",
                "score_delta"} <= set(r)
        assert r["seconds"] >= r["solve_seconds"] >= 0
        assert r["eval_seconds"] >= 0
    assert any("cd.step" in rec.message for rec in caplog.records)


def test_tolerance_schedule():
    s = ToleranceSchedule(1e-2, 0.1)
    assert s.at(0, 1e-8) == 1e-2
    assert s.at(3, 1e-8) == pytest.approx(1e-5)
    assert s.at(10, 1e-8) == 1e-8  # clamped at the final tolerance
    assert s.at(5, 0.0) == 0.0  # explicit tol<=0 stays disabled
    assert parse_tolerance_schedule("off") is None
    assert parse_tolerance_schedule("1e-3:0.5") == ToleranceSchedule(1e-3, 0.5)
    for bad in ("1e-3", "1e-3:2", "nan:0.1", "a:b", "0:0.1"):
        with pytest.raises(ValueError):
            parse_tolerance_schedule(bad)


def test_solver_tol_schedule_in_history(game_ds):
    """The schedule's per-sweep effective tolerance is recorded and
    tightens geometrically to the coordinate tolerance."""
    _, h = CoordinateDescent(
        _configs(True), task="logistic", n_iterations=4,
        dtype=jnp.float64,
        solver_tol_schedule=ToleranceSchedule(1e-3, 0.1)).run(game_ds)
    tols = [r["solver_tolerance"] for r in h if r["coordinate"] == "fixed"]
    assert tols[0] == pytest.approx(1e-3)
    assert all(b <= a for a, b in zip(tols, tols[1:]))
    assert tols[-1] >= 1e-12  # never below the coordinate tolerance
