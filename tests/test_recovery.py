"""Fail-recover: elastic in-job recovery for entity-sharded GAME
training, plus the satellites that ride with it.

Covers, per the acceptance contract:

* the shared :class:`Backoff` schedule (jitter + deadline) and its
  adoption by ``retry_transient``;
* failure classification (``rollback`` / ``rank_loss`` / ``fatal``) and
  ``recovery_supported`` probing;
* in-job ROLLBACK and RANK-LOSS recovery of a sharded coordinate-descent
  run with **f64 bit parity** against an uninterrupted reference —
  including shrinking all the way to a single survivor — and the bounded
  escalation when the failure budget is exhausted;
* the crash-schedule chaos sweep: a drop-kill armed at EVERY registered
  fault-injection site, asserting clean coordinated abort or bit-parity
  recovery, never a hang;
* durable commits (``io/durable.py``): fsync-the-file-and-parent
  discipline and the ``durable.commit`` crash window leaving the
  destination untouched (registry ``LATEST`` included);
* respawn-with-backoff supervision (``run_supervised_processes``) and
  ``retry_collective``;
* the driver surface: ``--max-rank-failures`` / ``--recovery-snapshot-
  every`` wiring and a 4-rank ``photon-game-train --entity-shards 4``
  run that loses a rank mid-sweep and still produces the bit-identical
  model;
* the serving satellites: the registry watcher's consecutive-failure
  error backoff and the front door's real circuit breaker
  (open -> half-open probe -> readmit, ``photon_fd_backend_state``).
"""

import asyncio
import json
import os
import time

import numpy as np
import pytest

from photon_ml_tpu.parallel import fault_injection as fi
from photon_ml_tpu.parallel import resilience
from photon_ml_tpu.parallel.recovery import (
    FATAL,
    RANK_LOSS,
    ROLLBACK,
    RecoveryManager,
    classify_failure,
    recovery_supported,
    retry_collective,
)
from photon_ml_tpu.parallel.resilience import (
    CODE_DATA,
    CODE_DEVICE_LOSS,
    CODE_ERROR,
    Backoff,
    PeerFailure,
    WatchdogTimeout,
    retry_transient,
)
from photon_ml_tpu.testing import (
    Dropped,
    run_simulated_processes,
    run_supervised_processes,
)
from tests.test_entity_shard import _configs, _make_dataset


@pytest.fixture(autouse=True)
def _clear_faults():
    fi.clear()
    yield
    fi.clear()


@pytest.fixture(autouse=True)
def _short_barrier(monkeypatch):
    # a dead peer must fail its survivors' barriers quickly: no recovery
    # test is allowed to ride the 600 s production watchdog
    monkeypatch.setenv("PHOTON_ML_TPU_BARRIER_TIMEOUT_S", "30")


# -- Backoff: the one shared delay policy -----------------------------------
class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


class _MaxJitterRng:
    def uniform(self, lo, hi):
        return hi


def test_backoff_schedule_clamps_and_resets():
    clock = _Clock()
    b = Backoff(base_s=1.0, factor=2.0, max_s=5.0, jitter=0.0, clock=clock)
    assert [b.next_delay() for _ in range(5)] == [1.0, 2.0, 4.0, 5.0, 5.0]
    assert b.attempts == 5
    b.reset()
    assert b.attempts == 0 and b.next_delay() == 1.0


def test_backoff_jitter_is_a_fraction_and_deadline_expires():
    clock = _Clock()
    b = Backoff(base_s=2.0, factor=2.0, max_s=60.0, jitter=0.25,
                deadline_s=10.0, rng=_MaxJitterRng(), clock=clock)
    assert b.next_delay() == pytest.approx(2.0 * 1.25)
    assert not b.expired() and b.remaining() == pytest.approx(10.0)
    clock.t = 10.0
    assert b.expired() and b.remaining() == 0.0
    b.reset()  # the deadline window restarts at reset
    assert not b.expired() and b.remaining() == pytest.approx(10.0)


def test_retry_transient_jittered_delays():
    sleeps, calls = [], []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise RuntimeError("transient")
        return "ok"

    out = retry_transient(flaky, attempts=3, backoff_s=1.0,
                          backoff_factor=2.0, jitter=0.5,
                          rng=_MaxJitterRng(), sleep=sleeps.append)
    assert out == "ok" and len(calls) == 3
    assert sleeps == [pytest.approx(1.5), pytest.approx(3.0)]


def test_retry_transient_deadline_abandons_the_next_sleep():
    clock = _Clock()
    sleeps, calls = [], []

    def always():
        calls.append(1)
        raise RuntimeError("still down")

    with pytest.raises(RuntimeError, match="still down"):
        retry_transient(always, attempts=5, backoff_s=2.0,
                        jitter=0.0, deadline_s=1.0, clock=clock,
                        sleep=sleeps.append)
    # the first retry's 2 s sleep would overrun the 1 s deadline: the
    # last real error escalates instead of sleeping through it
    assert len(calls) == 1 and sleeps == []


# -- failure classification -------------------------------------------------
def test_classify_failure_taxonomy():
    assert classify_failure(
        WatchdogTimeout("gone", tag="t", failed={2: CODE_ERROR})) == RANK_LOSS
    assert classify_failure(
        PeerFailure("x", tag="t", failed={1: CODE_ERROR})) == ROLLBACK
    assert classify_failure(
        PeerFailure("x", tag="t", failed={1: CODE_DEVICE_LOSS})) == FATAL
    assert classify_failure(
        PeerFailure("x", tag="t", failed={1: CODE_DATA})) == FATAL
    assert classify_failure(ValueError("bad rows")) == FATAL


def test_recovery_supported_probes_the_transport():
    class NoRecover:
        def process_count(self):
            return 4

    class CanRecover(NoRecover):
        def recover(self, payload, timeout):  # pragma: no cover - probe
            raise NotImplementedError

    assert recovery_supported() is True  # single process: trivially yes
    assert recovery_supported(NoRecover()) is False
    assert recovery_supported(CanRecover()) is True


# -- in-job recovery: bit parity against the uninterrupted run --------------
N_SWEEPS = 4


@pytest.fixture(scope="module")
def reference_fit():
    """Uninterrupted single-host reference: the trajectory every
    recovered run must reproduce BIT-EXACTLY."""
    import jax.numpy as jnp

    from photon_ml_tpu.game.descent import CoordinateDescent
    from tests.test_entity_shard import _coeff_map

    ds, val = _make_dataset(with_val=True)
    model, history = CoordinateDescent(
        _configs(), task="logistic", n_iterations=N_SWEEPS,
        dtype=jnp.float64, evaluators=["auc"]).run(ds, validation=val)
    return ds, val, model, history, _coeff_map(model)


def _assert_bit_parity(model, history, reference_fit):
    from tests.test_entity_shard import _coeff_map

    _ds, _val, m_ref, h_ref, ref = reference_fit
    got = _coeff_map(model)
    assert max(float(np.max(np.abs(got[k] - ref[k]))) for k in ref) == 0.0
    fixed = np.asarray(model.coordinates["fixed"].model.coefficients.means)
    fixed_ref = np.asarray(
        m_ref.coordinates["fixed"].model.coefficients.means)
    assert float(np.max(np.abs(fixed - fixed_ref))) == 0.0
    if history is not None:
        aucs = [r["auc"] for r in history if "auc" in r]
        assert aucs == [r["auc"] for r in h_ref if "auc" in r]


def _sharded_fit(ds, val, rank, n, recovery):
    import jax.numpy as jnp

    from photon_ml_tpu.game.descent import CoordinateDescent
    from photon_ml_tpu.parallel.entity_shard import EntityShardSpec

    cd = CoordinateDescent(
        _configs(), task="logistic", n_iterations=N_SWEEPS,
        dtype=jnp.float64, evaluators=["auc"] if val is not None else (),
        entity_shard=EntityShardSpec(n, rank), recovery=recovery)
    model, history = cd.run(ds, validation=val)
    return model, history, recovery.as_dict()


def test_rank_loss_recovery_bit_parity_4_ranks(reference_fit, tmp_path):
    """The tentpole: rank 2 drop-killed mid-sweep; the three survivors
    reform onto a 3-shard owner map, redistribute its entities from the
    last committed snapshot, and finish with coefficients AND the AUC
    history bit-identical to the uninterrupted run."""
    ds, val, _m, _h, _ref = reference_fit

    def fn(rank):
        rec = RecoveryManager(str(tmp_path / "rec"), max_rank_failures=1,
                              backoff_s=0.01, jitter=0.0)
        return _sharded_fit(ds, val, rank, 4, rec)

    # cd.step fires once per (sweep, coordinate): occurrence 5 dies in
    # sweep 2's random-effect step, after sweep 2's snapshot committed
    fi.install(fi.crash_schedule((2, "cd.step", 5)))
    outs = run_simulated_processes(4, fn, join_timeout=600)
    assert isinstance(outs[2], (BaseException, Dropped))
    for r in (0, 1, 3):
        assert not isinstance(outs[r], (BaseException, Dropped)), (
            f"rank {r}: {outs[r]!r}")
        model, history, stats = outs[r]
        _assert_bit_parity(model, history, reference_fit)
        assert stats["recoveries"] == 1
        assert stats["rank_failures"] == 1 and stats["rollbacks"] == 0
        assert stats["members"] == [0, 1, 3]
        assert stats["recovery_seconds"] > 0.0


def test_rollback_recovery_bit_parity(reference_fit, tmp_path):
    """A transient raise (all ranks still alive) rolls back to the last
    committed sweep and retries on the SAME membership — bit parity."""
    ds, _val, _m, _h, _ref = reference_fit

    def fn(rank):
        rec = RecoveryManager(str(tmp_path / "rec"), max_rank_failures=0,
                              backoff_s=0.01, jitter=0.0)
        return _sharded_fit(ds, None, rank, 2, rec)

    fi.install(fi.crash_schedule((1, "entity_shard.exchange", 2),
                                 kind="raise"))
    outs = run_simulated_processes(2, fn, join_timeout=600)
    for r, o in enumerate(outs):
        assert not isinstance(o, (BaseException, Dropped)), f"rank {r}: {o!r}"
        model, _history, stats = o
        _assert_bit_parity(model, None, reference_fit)
        assert stats["rollbacks"] == 1 and stats["rank_failures"] == 0


def test_recovery_shrinks_to_single_survivor(reference_fit, tmp_path):
    """2 ranks, one killed: the lone survivor absorbs the whole entity
    table (the 1-shard owner map IS the single-process layout) and still
    lands on the reference coefficients."""
    ds, _val, _m, _h, _ref = reference_fit

    def fn(rank):
        rec = RecoveryManager(str(tmp_path / "rec"), max_rank_failures=1,
                              backoff_s=0.01, jitter=0.0)
        return _sharded_fit(ds, None, rank, 2, rec)

    fi.install(fi.crash_schedule((1, "cd.step", 3)))
    outs = run_simulated_processes(2, fn, join_timeout=600)
    assert isinstance(outs[1], (BaseException, Dropped))
    assert not isinstance(outs[0], (BaseException, Dropped)), repr(outs[0])
    model, _history, stats = outs[0]
    _assert_bit_parity(model, None, reference_fit)
    assert stats["members"] == [0] and stats["rank_failures"] == 1


def test_device_loss_stays_fatal_coordinated_abort(reference_fit, tmp_path):
    """Device loss is NOT recoverable in-job: every rank must take the
    coordinated-abort path (the drivers' exit-75/resume contract), and
    no recovery may be attempted."""
    ds, _val, _m, _h, _ref = reference_fit

    def fn(rank):
        rec = RecoveryManager(str(tmp_path / "rec"), max_rank_failures=1,
                              backoff_s=0.01, jitter=0.0)
        return _sharded_fit(ds, None, rank, 2, rec)

    fi.install([fi.Fault(site="cd.step", process=1, at=2,
                         kind="device_loss")])
    outs = run_simulated_processes(2, fn, join_timeout=600)
    assert all(isinstance(o, BaseException) for o in outs), outs
    assert isinstance(outs[0], PeerFailure) and outs[0].device_loss


def test_rank_failure_budget_bounds_escalation(reference_fit, tmp_path):
    """Losing MORE ranks than --max-rank-failures allows must escalate
    loudly on every survivor, not recover past the operator's budget."""
    ds, _val, _m, _h, _ref = reference_fit

    def fn(rank):
        import jax.numpy as jnp

        from photon_ml_tpu.game.descent import CoordinateDescent
        from photon_ml_tpu.parallel.entity_shard import EntityShardSpec

        rec = RecoveryManager(str(tmp_path / "rec"), max_rank_failures=1,
                              backoff_s=0.01, jitter=0.0)
        cd = CoordinateDescent(
            _configs(), task="logistic", n_iterations=6,
            dtype=jnp.float64, entity_shard=EntityShardSpec(4, rank),
            recovery=rec)
        return cd.run(ds)

    # rank 2 dies in sweep 1; after the rollback-and-reform, rank 3's
    # occurrence counter keeps advancing and kills it a few sweeps later
    # — the second loss exceeds max_rank_failures=1
    fi.install(fi.crash_schedule((2, "cd.step", 3), (3, "cd.step", 9)))
    outs = run_simulated_processes(4, fn, join_timeout=600)
    assert isinstance(outs[2], (BaseException, Dropped))
    assert isinstance(outs[3], (BaseException, Dropped))
    for r in (0, 1):
        assert isinstance(outs[r], PeerFailure), f"rank {r}: {outs[r]!r}"


# -- chaos harness: a kill armed at EVERY registered fault site -------------
# Every production fault-injection site, by literal name (the photon-check
# --fault-sites audit requires each to appear in a tier-1 test). Split by
# reachability from the in-memory 2-rank sharded fit: HOT sites fire on
# that path and each gets its own kill run; INERT sites (streaming, chunk
# cache, model/registry saves, the GLM grid, real rendezvous) cannot fire
# there, so all of them are armed together in one run per victim — one
# fit proves the whole armed plan is inert AND that arming it perturbs
# nothing (bit parity).
HOT_FAULT_SITES = [
    "cd.step",
    "entity_shard.exchange",
    "durable.commit",
    "transport.allgather",
    "recovery.commit",
]
INERT_FAULT_SITES = [
    "cd.score_gather",
    "multihost.init",
    "glm.lambda",
    "registry.publish_prepared",
    "registry.published",
    "chunk_cache.spill",
    "chunk_cache.commit",
    "model_io.save_coordinate",
    "model_io.save_metadata",
    "stream.chunk",
    "stream.block_payload",
]
ALL_FAULT_SITES = HOT_FAULT_SITES + INERT_FAULT_SITES


def _chaos_run(site_kills, victim, reference_fit, tmp_path, site_label):
    """One 2-rank sharded fit with a drop-kill plan armed. Contract: the
    run either completes CLEAN on every rank with bit parity (no armed
    site fires on this path, or recovery absorbed the loss), or the
    victim is dead and every other rank either recovered to parity or
    raised a coordinated abort — and nothing ever hangs (the 30 s
    watchdog plus the join timeout bound every wait)."""
    ds, _val, _m, _h, _ref = reference_fit

    def fn(rank):
        rec = RecoveryManager(str(tmp_path / "rec"), max_rank_failures=1,
                              backoff_s=0.01, jitter=0.0)
        return _sharded_fit(ds, None, rank, 2, rec)

    fi.install(fi.crash_schedule(*site_kills))
    outs = run_simulated_processes(2, fn, join_timeout=300)
    for r, o in enumerate(outs):
        if isinstance(o, Dropped):
            assert r == victim, (
                f"rank {r} dropped but the kill was armed on {victim} "
                f"at {site_label!r} — a survivor hung or died silently")
        elif isinstance(o, BaseException):
            # coordinated abort: a classified, raised failure — never a
            # hang; anything non-PeerFailure must be the victim's own
            assert isinstance(o, PeerFailure) or r == victim, (
                f"rank {r}: {o!r}")
        else:
            model, _history, _stats = o
            _assert_bit_parity(model, None, reference_fit)
    return outs


@pytest.mark.parametrize("victim", [0, 1])
@pytest.mark.parametrize("site", HOT_FAULT_SITES)
def test_chaos_crash_schedule_hot_sites(site, victim, reference_fit,
                                        tmp_path):
    """Drop-kill each rank at the first firing of every site on the
    sharded-fit path; these kills actually land, so each case must end
    in recovery-to-parity or a coordinated abort."""
    _chaos_run([(victim, site, 0)], victim, reference_fit, tmp_path, site)


@pytest.mark.parametrize("victim", [0, 1])
def test_chaos_crash_schedule_inert_sites_stay_clean(victim, reference_fit,
                                                     tmp_path):
    """Arm a kill for the victim at EVERY off-path site at once: none
    can fire during an in-memory fit, so every rank must complete clean
    with bit parity — a site that starts firing on this path shows up
    here as a kill and moves to HOT_FAULT_SITES."""
    kills = [(victim, site, 0) for site in INERT_FAULT_SITES]
    outs = _chaos_run(kills, victim, reference_fit, tmp_path,
                      "|".join(INERT_FAULT_SITES))
    assert not any(isinstance(o, (BaseException, Dropped)) for o in outs), (
        f"an 'inert' site fired during the fit: {outs!r}")


# -- durable commits --------------------------------------------------------
def test_durable_replace_fsyncs_file_and_parent(tmp_path, monkeypatch):
    from photon_ml_tpu.io import durable

    synced = []
    real_fsync = os.fsync
    monkeypatch.setattr(os, "fsync", lambda fd: (synced.append(fd),
                                                 real_fsync(fd))[1])
    tmp = tmp_path / "marker.tmp"
    dst = tmp_path / "marker.json"
    tmp.write_text("{}")
    durable.durable_replace(str(tmp), str(dst))
    assert dst.read_text() == "{}" and not tmp.exists()
    # one fsync for the temp file's content, one for the parent dir
    assert len(synced) >= 2


def test_durable_commit_crash_window_leaves_dst_untouched(tmp_path):
    from photon_ml_tpu.io.durable import durable_replace

    dst = tmp_path / "LATEST"
    dst.write_text("old")
    tmp = tmp_path / "LATEST.tmp"
    tmp.write_text("new")
    fi.install([fi.Fault(site="durable.commit")])
    with pytest.raises(fi.InjectedFault):
        durable_replace(str(tmp), str(dst))
    fi.clear()
    # the crash window is BEFORE the rename: the old commit survives and
    # the staged content is still there for inspection, never half-applied
    assert dst.read_text() == "old" and tmp.read_text() == "new"


def test_registry_set_latest_survives_commit_crash(saved_game_model,
                                                   tmp_path):
    from photon_ml_tpu.registry import ModelRegistry

    model_dir, _bundle = saved_game_model
    reg = ModelRegistry(str(tmp_path / "reg"))
    v1 = reg.publish(model_dir, set_latest=True)
    v2 = reg.publish(model_dir)
    fi.install([fi.Fault(site="durable.commit")])
    with pytest.raises(fi.InjectedFault):
        reg.set_latest(v2)
    fi.clear()
    assert reg.read_latest() == v1  # the promotion never half-landed
    reg.set_latest(v2)
    assert reg.read_latest() == v2


# -- supervision + collective retry ----------------------------------------
def test_run_supervised_processes_respawns_with_backoff():
    sleeps = []

    def fn(rank, attempt):
        if attempt == 0 and rank == 1:
            raise RuntimeError("first attempt dies")
        return attempt

    outs, attempts = run_supervised_processes(
        2, fn, max_restarts=2, backoff_s=0.01, jitter=0.0,
        sleep=sleeps.append)
    assert outs == [1, 1] and attempts == 2
    assert sleeps == [pytest.approx(0.01)]


def test_run_supervised_processes_gives_up_after_budget():
    def fn(rank):
        raise RuntimeError("always down")

    outs, attempts = run_supervised_processes(
        2, fn, max_restarts=1, backoff_s=0.0, jitter=0.0,
        sleep=lambda s: None)
    assert attempts == 2  # initial try + one restart, then surrender
    assert all(isinstance(o, RuntimeError) for o in outs)


def test_retry_collective_retries_rollback_class_once():
    calls = {}

    def fn(rank):
        def body():
            calls[rank] = calls.get(rank, 0) + 1
            if calls[rank] == 1:
                raise PeerFailure("transient exchange", tag="t",
                                  failed={rank: CODE_ERROR})
            return rank

        return retry_collective(body, max_retries=1, backoff_s=0.01,
                                jitter=0.0, tag="test.retry")

    outs = run_simulated_processes(2, fn, join_timeout=120)
    assert outs == [0, 1]
    assert calls == {0: 2, 1: 2}


def test_retry_collective_escalates_fatal_immediately():
    calls = []

    def body():
        calls.append(1)
        raise PeerFailure("device gone", tag="t",
                          failed={0: CODE_DEVICE_LOSS})

    with pytest.raises(PeerFailure):
        retry_collective(body, max_retries=3, backoff_s=0.0)
    assert len(calls) == 1  # fatal: no retry, no barrier


# -- driver surface ---------------------------------------------------------
def test_driver_recovery_flags_defaults_and_validation():
    from photon_ml_tpu.cli.game_training_driver import build_arg_parser
    from photon_ml_tpu.cli.glm_driver import build_arg_parser as glm_parser

    args = build_arg_parser().parse_args(
        ["--train-data", "x", "--output-dir", "y", "--coordinates", "z"])
    assert args.max_rank_failures == 0  # recovery is strictly opt-in
    assert args.recovery_snapshot_every == 1
    args = build_arg_parser().parse_args(
        ["--train-data", "x", "--output-dir", "y", "--coordinates", "z",
         "--max-rank-failures", "2", "--recovery-snapshot-every", "3"])
    assert args.max_rank_failures == 2
    assert args.recovery_snapshot_every == 3
    g = glm_parser().parse_args(
        ["--train-data", "x", "--output-dir", "y"])
    assert g.max_rank_failures == 0
    with pytest.raises(SystemExit):
        build_arg_parser().parse_args(
            ["--train-data", "x", "--output-dir", "y", "--coordinates",
             "z", "--recovery-snapshot-every", "0"])


@pytest.mark.slow
def test_game_driver_entity_sharded_recovery(tmp_path):
    # slow-marked for the tier-1 wall-clock budget: the same 4-rank
    # kill -> 3-survivor bit-parity contract is gated on every push by
    # the ci_lint exit-13 leg (scripts/chaos_smoke.py)
    """The acceptance run: ``photon-game-train --entity-shards 4
    --max-rank-failures 1`` on 4 simulated processes, one killed
    mid-sweep — the job finishes in-job and the saved model is
    bit-identical to an uninterrupted 4-shard run."""
    from photon_ml_tpu.cli.game_training_driver import main as train_main
    from photon_ml_tpu.io.model_io import load_game_model
    from photon_ml_tpu.testing import (
        synthetic_game_data,
        write_game_avro_fixture,
    )

    data = synthetic_game_data({"userId": 8}, seed=4)
    train = str(tmp_path / "train.avro")
    write_game_avro_fixture(train, data,
                            rows=np.arange(len(data.labels)))
    coords = json.dumps([
        {"name": "fixed", "coordinate_type": "fixed",
         "feature_shard": "global", "reg_type": "l2", "reg_weight": 0.5,
         "tolerance": 1e-10, "max_iters": 25},
        {"name": "per-user", "coordinate_type": "random",
         "feature_shard": "entity", "entity_column": "userId",
         "reg_type": "l2", "reg_weight": 1.0, "max_iters": 15,
         # lbfgs: bit-invariant to the survivor layout's bucket widths
         "optimizer": "lbfgs", "tolerance": 1e-9},
    ])
    shards = json.dumps({"global": ["g"], "entity": ["u"]})

    def argv(out):
        return [
            "--train-data", train, "--output-dir", str(out),
            "--task", "logistic_regression", "--coordinates", coords,
            "--feature-shards", shards, "--n-iterations", "3",
            "--dtype", "float64", "--entity-shards", "4",
            "--max-rank-failures", "1",
        ]

    def run(out):
        return run_simulated_processes(
            4, lambda rank: train_main(argv(out)), join_timeout=600)

    clean = run(tmp_path / "clean")
    assert all(rc == 0 for rc in clean), clean
    fi.install(fi.crash_schedule((2, "cd.step", 3)))
    crashed = run(tmp_path / "crashed")
    fi.clear()
    assert isinstance(crashed[2], (BaseException, Dropped))
    for r in (0, 1, 3):
        assert crashed[r] == 0, f"rank {r}: {crashed[r]!r}"

    ref = load_game_model(str(tmp_path / "clean" / "best"))
    got = load_game_model(str(tmp_path / "crashed" / "best"))
    np.testing.assert_array_equal(
        np.asarray(ref.coordinates["fixed"].model.coefficients.means),
        np.asarray(got.coordinates["fixed"].model.coefficients.means))
    # the survivor layout re-buckets entities (3-shard owner map), so
    # compare entity -> (feature index, coefficient) maps, not bucket order
    def coeff_map(model):
        out = {}
        for b in model.coordinates["per-user"].buckets:
            C = np.asarray(b.coefficients)
            proj = (np.asarray(b.projection)
                    if getattr(b, "projection", None) is not None else None)
            for r, eid in enumerate(b.entity_ids):
                if proj is not None:
                    valid = proj[r] >= 0
                    out[str(eid)] = sorted(zip(proj[r][valid].tolist(),
                                               C[r][valid].tolist()))
                else:
                    out[str(eid)] = list(enumerate(C[r].tolist()))
        return out

    ref_map, got_map = coeff_map(ref), coeff_map(got)
    assert sorted(ref_map) == sorted(got_map)
    for eid in ref_map:
        assert ref_map[eid] == got_map[eid], f"entity {eid} diverged"
    events = [json.loads(line)["event"] for line in
              (tmp_path / "crashed" / "photon.log.jsonl")
              .read_text().splitlines()]
    assert "in_job_recovery" in events


# -- serving satellites: watcher backoff + circuit breaker ------------------
class _FlakyRegistry:
    def __init__(self):
        self.fail = True

    def read_latest(self):
        if self.fail:
            raise RuntimeError("registry down")
        return None


class _StubSession:
    active_version = None


def test_watcher_error_backoff_escalates_and_resets():
    from photon_ml_tpu.serve.watcher import RegistryWatcher

    reg = _FlakyRegistry()
    w = RegistryWatcher(reg, _StubSession(), interval_s=10.0, jitter_s=0.0,
                        error_backoff_max_s=80.0)

    class _ZeroRng:
        def uniform(self, lo, hi):
            return 0.0

    rng = _ZeroRng()
    assert w._next_delay(rng) == 10.0  # healthy: the plain interval

    def tick():
        before = w.errors
        w.check_once()
        w._observe(before)
        return w._next_delay(rng)

    # consecutive failures: 2x, 4x, 8x the interval (within jitter),
    # capped at error_backoff_max_s
    d1, d2, d3 = tick(), tick(), tick()
    assert 20.0 <= d1 <= 22.0
    assert 40.0 <= d2 <= 44.0
    assert 80.0 <= d3 <= 88.0
    reg.fail = False  # first clean poll resets the schedule
    assert tick() == 10.0
    assert w.errors == 3


def test_backend_breaker_opens_after_consecutive_failures():
    from photon_ml_tpu.serve.aserver import _Backend

    b = _Backend("127.0.0.1", 9, cooldown_s=0.1)
    now = time.monotonic()
    b.record_failure(3, now)
    b.record_failure(3, now)
    assert b.state == "closed" and b.opened == 0  # 2 < threshold
    b.record_success()
    assert b.fails == 0  # any success resets the consecutive count
    for _ in range(3):
        b.record_failure(3, now)
    assert b.state == "open" and b.opened == 1
    assert b.next_probe_at > now
    # a failed half-open probe reopens with an escalated cool-down
    b.state = "half_open"
    b.record_failure(3, now)
    assert b.state == "open" and b.opened == 2
    b.record_success()
    assert b.state == "closed" and b.fails == 0


def test_front_door_half_open_probe_readmits_and_metrics_gauge():
    from photon_ml_tpu.serve.aserver import AsyncFrontDoor

    door = AsyncFrontDoor(["127.0.0.1:1"], retry_backend_s=0.01,
                          breaker_threshold=2)
    b = door._backends[0]
    healthy = {"v": False}

    async def fake_exchange(backend, raw):
        if not healthy["v"]:
            raise ConnectionError("still down")
        return b"HTTP/1.1 200 OK\r\nContent-Length: 0\r\n\r\n"

    door._backend_exchange = fake_exchange

    async def run():
        now = time.monotonic()
        b.record_failure(door.breaker_threshold, now)
        b.record_failure(door.breaker_threshold, now)
        assert b.state == "open"
        # first probe fails: back to open, escalated cool-down
        b.next_probe_at = 0.0
        door._maybe_probe(b, time.monotonic())
        assert b.state == "half_open" and b.probe_inflight
        await asyncio.sleep(0.01)
        assert b.state == "open" and door.readmitted == 0
        # replica recovers: the next probe readmits it
        healthy["v"] = True
        b.next_probe_at = 0.0
        door._maybe_probe(b, time.monotonic())
        await asyncio.sleep(0.01)
        assert b.state == "closed" and door.readmitted == 1
        # breaker state is exported for operators
        b.state = "open"
        b.next_probe_at = time.monotonic() + 999.0
        text = await door._fd_metrics()
        return text

    text = asyncio.run(run())
    assert "photon_fd_backend_state" in text
    assert 'photon_fd_backend_state{backend="127.0.0.1:1"} 2' in text
    assert "photon_fd_readmitted_total 1" in text
    stats = door.stats()
    assert stats["readmitted"] == 1
    assert stats["backends"][0]["state"] == "open"
    assert stats["backends"][0]["down"] is True


def test_front_door_sync_pick_never_flips_half_open_without_a_loop():
    """_maybe_probe from a no-loop context must leave the breaker open
    (probing requires the event loop) — the backend stays ejected rather
    than getting stuck half-open with no probe in flight."""
    from photon_ml_tpu.serve.aserver import AsyncFrontDoor

    door = AsyncFrontDoor(["127.0.0.1:1"], retry_backend_s=0.01,
                          breaker_threshold=1)
    b = door._backends[0]
    b.record_failure(1, time.monotonic())
    assert b.state == "open"
    b.next_probe_at = 0.0
    door._maybe_probe(b, time.monotonic())  # sync caller: no running loop
    assert b.state == "open" and not b.probe_inflight
