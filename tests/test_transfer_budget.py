"""Transfer-budget guard (utils.transfer_budget): the structural
protection against the bulk host->device uploads that wedged the axon
tunnel and crashed the TPU worker in rounds 2 and 3 (docs/PERF.md
"Measuring through the axon tunnel"). These run on the CPU mesh — the
budget is deliberately backend-independent byte accounting so the
mandated CPU dry-run of the hardware session exercises the same
enforcement the chip session relies on."""

import numpy as np
import jax.numpy as jnp
import pytest

from photon_ml_tpu.utils import transfer_budget as tb


@pytest.fixture(autouse=True)
def _clean_budget():
    tb.set_budget(None)
    yield
    tb.set_budget(None)


def test_no_budget_is_noop():
    tb.charge(10**12)  # would exceed any real budget


def test_single_transfer_cap():
    tb.set_budget(total_mb=1000.0, single_mb=1.0)
    tb.charge(900_000, "ok piece")
    with pytest.raises(tb.TransferBudgetExceeded, match="per-transfer cap"):
        tb.charge(2_000_000, "bulk")


def test_total_budget_accumulates():
    tb.set_budget(total_mb=1.0, single_mb=1.0)
    for _ in range(2):
        tb.charge(400_000)
    with pytest.raises(tb.TransferBudgetExceeded, match="over the"):
        tb.charge(400_000)
    # a failed charge must not have been added
    assert tb.get_budget().spent == 800_000


def test_waive_raises_total_but_not_single():
    tb.set_budget(total_mb=1.0, single_mb=1.0)
    tb.waive(10.0, reason="streaming bench moves bulk data by design")
    tb.charge(900_000)
    tb.charge(900_000)  # over the original total, under the waived one
    with pytest.raises(tb.TransferBudgetExceeded, match="per-transfer cap"):
        tb.charge(2_000_000)


def test_env_activation(monkeypatch):
    monkeypatch.setenv("PHOTON_TRANSFER_BUDGET_MB", "1")
    monkeypatch.setenv("PHOTON_TRANSFER_SINGLE_MB", "0.5")
    tb.set_budget(None)
    tb._initialized = False  # force re-read of the env
    with pytest.raises(tb.TransferBudgetExceeded):
        tb.charge(600_000)


def test_device_put_charges_numpy_only():
    tb.set_budget(total_mb=1.0, single_mb=1.0)
    tb.device_put(np.zeros(1000, np.float32))
    assert tb.get_budget().spent == 4000
    # already-on-device arrays are not host->device transfers
    tb.device_put(jnp.zeros(1000))
    assert tb.get_budget().spent == 4000


def test_streamed_fit_respects_budget():
    """fit_streaming's chunk uploads are budget-accounted: a budget too
    small for even one chunk aborts on the host before any transfer."""
    from photon_ml_tpu.ops.objective import make_objective
    from photon_ml_tpu.optimize import OptimizerConfig
    from photon_ml_tpu.parallel.streaming import HostChunk, fit_streaming

    rng = np.random.default_rng(0)
    n, k, dim = 256, 4, 64
    chunks = [HostChunk(rng.integers(0, dim, (n, k)).astype(np.int32),
                        None,
                        rng.integers(0, 2, n).astype(np.float32),
                        np.zeros(n, np.float32), np.ones(n, np.float32))]
    obj = make_objective("logistic")
    cfg = OptimizerConfig(max_iters=2, tolerance=0.0)

    tb.set_budget(total_mb=1e-6, single_mb=64.0)
    with pytest.raises(tb.TransferBudgetExceeded):
        fit_streaming(obj, chunks, dim, config=cfg)

    # a sane budget passes and records real bytes moved
    tb.set_budget(total_mb=64.0, single_mb=64.0)
    res = fit_streaming(obj, chunks, dim, config=cfg)
    assert int(res.iterations) == 2
    assert tb.get_budget().spent > 0
