"""make_mesh composition over the ``data`` x ``entity`` axes — the mesh
the entity-sharded GAME step runs on. The ``entity`` axis previously had
no direct tier-1 coverage: these pin axis-order invariance, the
clear-error contract for infeasible axis sizes, and the entity-sharded
``device_put`` layout round trip."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from photon_ml_tpu.parallel.mesh import make_mesh


def test_make_mesh_data_entity_composition():
    mesh = make_mesh({"data": 4, "entity": 2})
    assert mesh.shape == {"data": 4, "entity": 2}
    assert mesh.devices.size == 8
    assert len(set(d.id for d in mesh.devices.ravel())) == 8


def test_make_mesh_axis_order_invariance():
    """The same axis sizes in either order build meshes over the same
    device set with the same per-axis widths — a shard_map over
    P("entity") partitions identically either way."""
    m1 = make_mesh({"data": 4, "entity": 2})
    m2 = make_mesh({"entity": 2, "data": 4})
    assert dict(m1.shape) == {"data": 4, "entity": 2}
    assert dict(m2.shape) == {"entity": 2, "data": 4}
    assert (set(d.id for d in m1.devices.ravel())
            == set(d.id for d in m2.devices.ravel()))
    x = np.arange(16.0).reshape(8, 2)
    s1 = jax.device_put(jnp.asarray(x), NamedSharding(m1, P("entity")))
    s2 = jax.device_put(jnp.asarray(x), NamedSharding(m2, P("entity")))
    np.testing.assert_array_equal(np.asarray(s1), x)
    np.testing.assert_array_equal(np.asarray(s2), x)


def test_make_mesh_infeasible_axis_sizes_raise_clearly():
    """More mesh slots than devices must fail with the axis breakdown in
    the message, not a reshape traceback."""
    with pytest.raises(ValueError, match="devices"):
        make_mesh({"data": 64})
    with pytest.raises(ValueError, match="entity"):
        make_mesh({"data": 3, "entity": 3})  # 9 > 8 virtual devices
    with pytest.raises(ValueError, match="9 devices"):
        make_mesh({"data": 3, "entity": 3})


def test_make_mesh_rejects_nonpositive_axis():
    with pytest.raises(ValueError, match="entity"):
        make_mesh({"data": 4, "entity": 0})


def test_entity_sharded_device_put_layout_roundtrip():
    """An [E, ...] per-entity array laid out shard-by-entity on the mesh
    splits across exactly the entity axis and round-trips bit-exactly —
    the device boundary the sharded bucket solvers cross."""
    mesh = make_mesh({"data": 2, "entity": 4})
    E, D = 16, 3
    x = np.arange(E * D, dtype=np.float64).reshape(E, D)
    sharded = jax.device_put(jnp.asarray(x),
                             NamedSharding(mesh, P("entity")))
    np.testing.assert_array_equal(np.asarray(sharded), x)
    shards = sharded.addressable_shards
    assert len(shards) == 8
    # each entity-axis slice holds E/4 rows; the data axis replicates
    shapes = {s.data.shape for s in shards}
    assert shapes == {(E // 4, D)}
    rows_seen = sorted(int(s.index[0].start or 0) for s in shards)
    assert rows_seen == [0, 0, 4, 4, 8, 8, 12, 12]


def test_entity_axis_shard_map_sum_matches_host():
    """A no-collective shard_map over the entity axis (the bucket-solver
    pattern) computes the same per-entity results as the host."""
    from photon_ml_tpu.compat import shard_map

    mesh = make_mesh({"entity": 8})
    x = np.arange(32.0).reshape(8, 4)

    f = shard_map(lambda a: a * 2.0 + 1.0, mesh=mesh,
                  in_specs=(P("entity"),), out_specs=P("entity"),
                  check_vma=False)
    out = jax.jit(f)(jnp.asarray(x))
    np.testing.assert_array_equal(np.asarray(out), x * 2.0 + 1.0)
